// Native energy/area model library (the McPAT/DSENT-equivalent).
//
// Reference: Graphite links two separate C++ libraries — a patched McPAT
// (contrib/mcpat, core+cache area/leakage/dynamic energy) and DSENT
// (contrib/dsent, NoC router+link energy) — initialized at simulator boot
// (common/system/simulator.cc:93-104) and fed by model event counters
// (common/mcpat/mcpat_core_interface.cc, mcpat_cache_interface.cc).
//
// This library fills the same role natively: analytical area/leakage/
// per-event-energy models with McPAT-style technology and voltage scaling
// (dynamic energy ~ C_eff * V^2, leakage ~ area * I_off(V) with
// subthreshold DIBL scaling, SRAM structures scaled by capacity and port
// count).  The coefficients are calibrated to published 45/32/22nm
// ballparks; the point is the same breakdown structure and scaling
// behavior the reference exposes, computed from the engine's counters.
//
// C ABI only — the Python side binds with ctypes (no pybind11 in the
// image), and the driver can link it from C++ tools directly.

#include <cmath>
#include <cstdint>

extern "C" {

typedef struct {
  double area_mm2;
  double leakage_power_w;        // at nominal voltage
  double read_energy_j;          // per access
  double write_energy_j;
  double tag_energy_j;
} sram_energy_out;

typedef struct {
  double area_mm2;
  double leakage_power_w;
  double ifu_energy_j;           // per instruction fetched
  double decode_energy_j;        // per instruction decoded
  double rf_energy_j;            // per register operand
  double ialu_energy_j;          // per int ALU op
  double fpu_energy_j;           // per FP op
  double mul_energy_j;           // per mul/div op
  double lsu_energy_j;           // per load/store queue op
  double bypass_energy_j;        // per result broadcast
  double bpred_energy_j;         // per branch lookup
} core_energy_out;

typedef struct {
  double router_area_mm2;
  double router_leakage_w;
  double buffer_energy_j;        // per flit buffered
  double crossbar_energy_j;      // per flit traversal
  double arbiter_energy_j;       // per allocation
  double link_energy_j_per_mm;   // per flit per mm
  double link_leakage_w_per_mm;
} noc_energy_out;

// --- technology scaling ----------------------------------------------------
// Feature-size scaling from the 45nm anchor: area ~ s^2, capacitance ~ s,
// leakage current density rises as channels shrink (McPAT's device models
// show roughly flat-to-rising leakage per mm^2 across 45->22).

static double tech_scale(int node_nm) { return node_nm / 45.0; }

static double leak_density_w_per_mm2(int node_nm) {
  // ~0.1 W/mm^2 at 45nm HP, slightly rising at smaller nodes
  double s = tech_scale(node_nm);
  return 0.10 * (1.0 + 0.35 * (1.0 - s));
}

// Dynamic energy scales C*V^2: C ~ s relative to the 45nm anchor values,
// V^2 relative to 1.0V nominal.
static double dyn_scale(int node_nm, double voltage) {
  return tech_scale(node_nm) * voltage * voltage;
}

// Subthreshold leakage vs voltage: I_off ~ exp(k*(V - Vnom)) with DIBL
// factor ~2.5x per 100mV around nominal.
static double leak_vscale(double voltage) {
  return std::exp(2.3 * (voltage - 1.0));
}

// --- SRAM structures (caches, register files, directories) ----------------

void sram_energy(int node_nm, double voltage, long size_bytes,
                 int associativity, int line_bytes, int ports,
                 sram_energy_out* out) {
  double s = tech_scale(node_nm);
  double kb = size_bytes / 1024.0;
  double p = ports > 0 ? ports : 1;
  // 45nm anchor calibrated against the published CACTI-derived figures
  // collected in Horowitz, "Computing's Energy Problem" (ISSCC 2014):
  // ~10 pJ for an 8KB cache read, ~20 pJ for 32KB, ~100 pJ for 1MB at
  // 45nm.  With the sqrt capacity scaling below (bitline segmentation),
  // a 21e-12 coefficient at the 64KB/4-way anchor lands 8KB..1MB reads
  // within ~15% of those anchors (calibration table in PERF.md).
  out->area_mm2 = 0.0070 * kb * p * s * s;
  double cap_factor = std::sqrt(kb / 64.0);
  double assoc_factor = 1.0 + 0.08 * (associativity > 0 ? associativity : 1);
  out->read_energy_j =
      21e-12 * cap_factor * assoc_factor * dyn_scale(node_nm, voltage);
  out->write_energy_j = 1.15 * out->read_energy_j;
  out->tag_energy_j = 0.18 * out->read_energy_j;
  out->leakage_power_w = out->area_mm2 * leak_density_w_per_mm2(node_nm) *
                         leak_vscale(voltage);
  (void)line_bytes;
}

// --- core (IFU/EXU/LSU breakdown) -----------------------------------------

void core_energy(int node_nm, double voltage, int issue_width,
                 int load_queue_entries, int store_queue_entries,
                 core_energy_out* out) {
  double w = issue_width > 0 ? issue_width : 1;
  double ds = dyn_scale(node_nm, voltage);
  double s = tech_scale(node_nm);
  // 45nm anchors for a single-issue in-order core (~1.8 mm^2 sans caches)
  out->area_mm2 = (1.2 + 0.3 * w +
                   0.004 * (load_queue_entries + store_queue_entries)) *
                  s * s;
  out->ifu_energy_j = 9e-12 * ds;
  out->decode_energy_j = 4e-12 * ds;
  out->rf_energy_j = 2.5e-12 * ds;
  out->ialu_energy_j = 6e-12 * ds;
  out->fpu_energy_j = 22e-12 * ds;
  out->mul_energy_j = 16e-12 * ds;
  out->lsu_energy_j = 7e-12 * (1.0 + 0.01 * (load_queue_entries +
                                             store_queue_entries)) * ds;
  out->bypass_energy_j = 3e-12 * w * ds;
  out->bpred_energy_j = 1.5e-12 * ds;
  out->leakage_power_w = out->area_mm2 * leak_density_w_per_mm2(node_nm) *
                         leak_vscale(voltage);
}

// --- NoC router + link (the DSENT analog) ---------------------------------

void noc_energy(int node_nm, double voltage, int num_ports, int flit_bits,
                int buffers_per_port, noc_energy_out* out) {
  double ds = dyn_scale(node_nm, voltage);
  double s = tech_scale(node_nm);
  double p = num_ports > 0 ? num_ports : 5;
  double f = flit_bits > 0 ? flit_bits : 64;
  out->router_area_mm2 =
      0.015 * p * (f / 64.0) * (1.0 + 0.05 * buffers_per_port) * s * s;
  out->buffer_energy_j = 0.65e-12 * (f / 64.0) * ds;
  out->crossbar_energy_j = 1.6e-12 * (f / 64.0) * (p / 5.0) * ds;
  out->arbiter_energy_j = 0.25e-12 * ds;
  out->link_energy_j_per_mm = 0.9e-12 * (f / 64.0) * ds;
  out->link_leakage_w_per_mm = 0.0012 * (f / 64.0) * s * leak_vscale(voltage);
  out->router_leakage_w = out->router_area_mm2 *
                          leak_density_w_per_mm2(node_nm) *
                          leak_vscale(voltage);
}

// --- DRAM access energy ----------------------------------------------------

double dram_access_energy_j(int node_nm, int line_bytes) {
  // DRAM is off-die: roughly constant per-bit energy (~20 pJ/bit incl. IO)
  (void)node_nm;
  return 20e-12 * 8.0 * (line_bytes > 0 ? line_bytes : 64);
}

int energy_model_abi_version(void) { return 1; }

}  // extern "C"
