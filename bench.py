"""Benchmark: aggregate simulated instructions/second on one chip.

North star (BASELINE.json): ≥10M aggregate simulated instr/s on the
1024-tile e-mesh running SPLASH-2 FFT.  Default workload: the six-step FFT
trace program (`trace/benchmarks.py` — butterflies + three all-to-all
transposes + barriers, BENCH_POINTS points per tile) replayed through the
full vectorized core/network/sync stack on hop-counter NoC timing.  Set
BENCH_WORKLOAD=ring for the legacy compute+message ring.  Prints exactly
one JSON line.
"""

import json
import os
import sys
import time

N_TILES = int(os.environ.get("BENCH_TILES", "1024"))
WORKLOAD = os.environ.get("BENCH_WORKLOAD", "fft")
# fft: simulated FFT size = BENCH_TILES * BENCH_POINTS points
N_POINTS = int(os.environ.get("BENCH_POINTS", "2048"))
# ring workload knobs
N_ROUNDS = int(os.environ.get("BENCH_ROUNDS", "64"))
COMPUTE_PER_ROUND = int(os.environ.get("BENCH_COMPUTE", "62"))
# Basic-block-granularity replay (one BBLOCK record per straight-line run,
# cycle-identical timing — the engine's native trace granularity).  Set
# BENCH_COMPRESSED=0 to replay one record per instruction instead, which
# measures the raw per-record engine rate.
COMPRESSED = os.environ.get("BENCH_COMPRESSED", "1") != "0"
BASELINE_INSTR_PER_SEC = 10_000_000  # BASELINE.json north star


def main() -> None:
    import graphite_tpu  # noqa: F401  (x64)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from graphite_tpu.config import ConfigFile, SimConfig
    from graphite_tpu.engine.simulator import Simulator
    from graphite_tpu.trace import synthetic

    cfg_text = f"""
[general]
total_cores = {N_TILES}
mode = lite
max_frequency = 1.0
[network]
user = emesh_hop_counter
memory = emesh_hop_counter
[network/emesh_hop_counter]
flit_width = 64
[network/emesh_hop_counter/router]
delay = 1
[network/emesh_hop_counter/link]
delay = 1
[core/static_instruction_costs]
generic = 1
mov = 1
ialu = 1
imul = 3
falu = 3
fmul = 5
[branch_predictor]
type = one_bit
mispredict_penalty = 14
size = 1024
[clock_skew_management]
scheme = lax
"""
    sc = SimConfig(ConfigFile.from_string(cfg_text))
    if WORKLOAD == "fft":
        from graphite_tpu.trace.benchmarks import fft_trace

        batch = fft_trace(N_TILES, points_per_tile=N_POINTS)
        desc = f"SPLASH-2 FFT {N_TILES * N_POINTS}-point"
    elif WORKLOAD == "ring":
        batch = synthetic.message_ring_batch(
            N_TILES, n_rounds=N_ROUNDS, compute_per_round=COMPUTE_PER_ROUND,
            compressed=COMPRESSED,
        )
        desc = "compute+message workload"
    else:
        from graphite_tpu.trace.benchmarks import BENCHMARKS

        if WORKLOAD not in BENCHMARKS:
            names = ", ".join(["fft", "ring"]
                              + [n for n in BENCHMARKS if n != "fft"])
            raise SystemExit(
                f"unknown BENCH_WORKLOAD {WORKLOAD!r} (choose from: {names})"
            )
        batch = BENCHMARKS[WORKLOAD](N_TILES)
        desc = WORKLOAD
    # Barrier-phased workloads auto-size their [T,T,depth] rings from
    # the trace (Simulator auto_mailbox_depth -> 2 for FFT); the ring
    # workload's unphased send stream keeps an explicit small depth (its
    # recv interlock bounds true occupancy, which the trace-order bound
    # cannot see)
    depth = None if WORKLOAD != "ring" else 8
    # Big per-instruction traces stream host->HBM in windows instead of
    # living resident (trace/schema.py streaming mode): device trace
    # memory is bounded by one [T, W] window regardless of trace length.
    import dataclasses as _dc

    trace_bytes = sum(
        getattr(batch, f.name).nbytes for f in _dc.fields(batch))
    stream = trace_bytes > int(
        os.environ.get("BENCH_STREAM_THRESHOLD", str(1 << 30)))
    window = int(os.environ.get("BENCH_STREAM_WINDOW", "4096"))
    sim = Simulator(sc, batch, mailbox_depth=depth, inner_block=64,
                    stream=stream)

    if stream:
        # warm the XLA cache with a throwaway truncated-trace run (same
        # [T, W] window shapes -> same executables), so the timed run
        # excludes compilation like the resident path's warmup() does
        import numpy as _np

        warm_len = min(batch.length, 2 * window)
        import dataclasses as _dc2

        warm_batch = type(batch)(**{
            f.name: getattr(batch, f.name)[:, :warm_len]
            for f in _dc2.fields(batch)})
        from graphite_tpu.engine.simulator import DeadlockError

        try:
            Simulator(sc, warm_batch, mailbox_depth=depth, inner_block=64,
                      stream=True).run_streamed(window_records=window)
        except DeadlockError:
            # the truncation can cut a blocking record's resolving record
            # on another tile — the run only exists to warm the XLA
            # cache, which it has by the time the loop bails; any OTHER
            # failure must surface (a swallowed compile error would put
            # compilation inside the timed run and deflate the headline)
            pass
        t0 = time.perf_counter()
        results = sim.run_streamed(window_records=window)
        elapsed = time.perf_counter() - t0
    else:
        # Warm-up: compile (and run once) the full device-side loop.
        sim.warmup()
        t0 = time.perf_counter()
        results = sim.run()
        elapsed = time.perf_counter() - t0

    total_instr = results.total_instructions
    ips = total_instr / elapsed

    def _timed_rate(sim2):
        sim2.warmup()
        t0 = time.perf_counter()
        r = sim2.run()
        return r.total_instructions / (time.perf_counter() - t0), sim2

    # Companion rates so the round artifact tracks COHERENCE and NoC-
    # contention throughput, not just the memoryless headline (a
    # regression in either is then visible in BENCH_r*.json): the
    # graduated runner's config-2/3 shapes — 64-tile iocoom + full-MSI
    # FFT, and 256-tile hop-by-hop RADIX.  Skippable for quick local runs
    # with BENCH_COMPANIONS=0.
    companions = {}
    if os.environ.get("BENCH_COMPANIONS", "1") != "0":
        from graphite_tpu.trace.benchmarks import fft_trace, radix_trace
        from graphite_tpu.tools._template import config_text

        sc_msi = SimConfig(ConfigFile.from_string(config_text(
            64, core="iocoom", shared_mem=True, clock_scheme="lax")))
        msi_rate, msi_sim = _timed_rate(Simulator(
            sc_msi, fft_trace(64, points_per_tile=512, use_memory=True),
            inner_block=64))
        sc_hbh = SimConfig(ConfigFile.from_string(config_text(
            256, network="emesh_hop_by_hop", clock_scheme="lax")))
        hbh_rate, _ = _timed_rate(Simulator(
            sc_hbh, radix_trace(256, keys_per_tile=1024),
            inner_block=64))
        companions = {
            "coherence_msi_instr_per_s": round(msi_rate),
            "hop_by_hop_instr_per_s": round(hbh_rate),
            # gate observability (round 6): per-phase lax.cond skip
            # counts + the engine-iteration denominator, so BENCH_r{N}
            # tracks skip rates alongside throughput
            "coherence_msi_phase_skips": msi_sim.last_phase_skips,
            "coherence_msi_engine_iters": int(msi_sim.last_n_iterations),
        }

        # The north-star-shaped configuration, measured honestly (VERDICT
        # round 3 missing #2): 1024-tile FFT with the FULL memory engine.
        # Run in a subprocess (the biggest configs can kill the TPU
        # worker — 2.4 GB directory + XLA scatter-staging copies exhaust
        # HBM, and the remote-compile helper intermittently dies at this
        # program size), walking a fidelity ladder and recording the
        # first rung that completes, tagged with its config.  Skippable
        # via BENCH_COHERENCE_1024=0.
        if os.environ.get("BENCH_COHERENCE_1024", "1") != "0":
            import subprocess

            for net, dirsz, wl in (
                    ("hbh", "full", "fft"), ("hopctr", "full", "fft"),
                    ("hopctr", "full", "memstress"),
                    ("hopctr", "small", "fft")):
                try:
                    proc = subprocess.run(
                        [sys.executable, "-m",
                         "graphite_tpu.tools.coherence1024",
                         "--net", net, "--dir", dirsz, "--workload", wl],
                        capture_output=True, text=True, timeout=int(
                            os.environ.get("BENCH_C1024_TIMEOUT", "900")))
                except subprocess.TimeoutExpired:
                    continue
                if proc.returncode == 0 and proc.stdout.strip():
                    # scan backwards for the result line: runtime/absl
                    # warnings can land on stdout after it
                    rung = None
                    for line in reversed(proc.stdout.strip().splitlines()):
                        try:
                            cand = json.loads(line)
                        except ValueError:
                            continue
                        if isinstance(cand, dict) and "rate" in cand:
                            rung = cand
                            break
                    if rung is None:
                        continue
                    companions["coherence_1024_instr_per_s"] = rung["rate"]
                    companions["coherence_1024_config"] = rung["config"]
                    break

    # Batched-campaign throughput (round 7, sweep/ subsystem): a B-point
    # timing-knob grid through ONE compiled program with traced knobs.
    # The campaign comparison is COMPILE-INCLUSIVE on both sides,
    # because that is what a knob sweep actually pays: with knobs baked
    # static (the pre-round-7 tool), every grid point is a distinct XLA
    # program — B compiles; the sweep pays one compile for the whole
    # grid.  A representative single point's compile+run is measured as
    # the sequential per-point cost.  Warm per-iteration rates ride
    # along for transparency: on CPU the warm batched iteration does
    # NOT beat the warm gated sequential iteration (vmap turns the
    # activity-gating conds into both-branch selects — PERF.md round-7);
    # the on-chip op-tail amortization claim is a TPU re-measurement
    # item.  Skippable via BENCH_SWEEP=0; B via BENCH_SWEEP_B.
    if os.environ.get("BENCH_SWEEP", "1") != "0":
        from graphite_tpu.sweep import SweepRunner
        from graphite_tpu.tools._template import config_text

        B = int(os.environ.get("BENCH_SWEEP_B", "8"))
        sw_tiles = int(os.environ.get("BENCH_SWEEP_TILES", "16"))
        sc_sw = SimConfig(ConfigFile.from_string(config_text(
            sw_tiles, shared_mem=True, clock_scheme="lax")))
        sw_trace = synthetic.memory_stress_trace(
            sw_tiles, n_accesses=24, working_set_bytes=1 << 13,
            write_fraction=0.4, shared_fraction=0.5, seed=7)
        points = [{"dram_latency_ns": 40 + 20 * i} for i in range(B)]
        sweep = SweepRunner(sc_sw, [sw_trace], points)
        t0 = time.perf_counter()
        out = sweep.run()               # compile + run: the campaign cost
        sweep_total_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = sweep.run()               # warm steady-state rate
        sweep_warm_s = time.perf_counter() - t0
        total_iters = max(int(out.n_iterations.sum()), 1)

        # one representative off-default point of the sequential
        # campaign: fresh static params -> its own compile, plus the run
        import dataclasses as _dc3

        seq = Simulator(sc_sw, sw_trace, mailbox_depth=sweep.mailbox_depth)
        seq.params = _dc3.replace(
            seq.params,
            mem=_dc3.replace(seq.params.mem, dram_latency_ns=40))
        t0 = time.perf_counter()
        seq.run()
        seq_point_s = time.perf_counter() - t0
        seq_iters = max(int(seq.last_n_iterations), 1)
        seq2 = Simulator(sc_sw, sw_trace,
                         mailbox_depth=sweep.mailbox_depth)
        seq2.params = seq.params
        seq2.adopt_runner(seq)
        t0 = time.perf_counter()
        seq2.run()
        seq_warm_s = time.perf_counter() - t0

        ms_amort = 1000 * sweep_total_s / total_iters
        ms_seq = 1000 * seq_point_s / seq_iters
        companions.update({
            "sweep_batch": B,
            # steady-state campaign throughput (warm program)
            "sims_per_s": round(B / sweep_warm_s, 3),
            # compile-inclusive campaign economics (the headline):
            # per-useful-iteration cost of the whole grid vs ONE
            # sequential point's compile+run
            "ms_per_iter_amortized": round(ms_amort, 4),
            "ms_per_iter_sequential": round(ms_seq, 4),
            "sweep_vs_sequential": round(ms_amort / ms_seq, 4),
            # warm rates (no compiles anywhere) for transparency
            "ms_per_iter_amortized_warm": round(
                1000 * sweep_warm_s / total_iters, 4),
            "ms_per_iter_sequential_warm": round(
                1000 * seq_warm_s / seq_iters, 4),
        })

    # 2D batch x tile campaign layouts (round 18): warm ms/iter and
    # bytes-per-device for solo vs 1D-batch vs 2D at one fixed
    # geometry, plus the admission outcome for a sim that a 1-device
    # budget rejects (accepted-as-2D across devices).  Runs in-process
    # when >= 4 devices are visible; otherwise in a forced-4-device
    # CPU subprocess (the fields are then CPU numbers, flagged by
    # mesh2d_platform).  Skippable via BENCH_MESH2D=0.
    if os.environ.get("BENCH_MESH2D", "1") != "0":
        if len(jax.devices()) >= 4:
            from graphite_tpu.tools.mesh2d_bench import measure_mesh2d

            companions.update(measure_mesh2d())
        else:
            import subprocess as _sp

            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=4").strip()
            try:
                proc = _sp.run(
                    [sys.executable, "-m",
                     "graphite_tpu.tools.mesh2d_bench"],
                    capture_output=True, text=True, env=env,
                    timeout=int(os.environ.get("BENCH_MESH2D_TIMEOUT",
                                               "900")))
                row = None
                for line in reversed(
                        proc.stdout.strip().splitlines()):
                    try:
                        cand = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(cand, dict):
                        row = cand
                        break
                if row:
                    row["mesh2d_platform"] = "cpu-forced-4"
                    companions.update(row)
                else:
                    companions["mesh2d_error"] = (
                        f"rc={proc.returncode}: "
                        + proc.stderr.strip()[-200:])
            except _sp.TimeoutExpired:
                companions["mesh2d_error"] = "timeout"

    # Telemetry overhead (round 9, obs/ subsystem): warm per-iteration
    # cost of recording a DENSE device timeline (every available series,
    # S=256, sampled every barrier quantum — the worst case) vs
    # telemetry=None on the same 16-tile coherence program, plus the
    # timeline-derived summary fields CI tracks (peak USER-net injection
    # rate, mean per-tile clock spread).  Skippable via BENCH_TELEMETRY=0.
    if os.environ.get("BENCH_TELEMETRY", "1") != "0":
        from graphite_tpu.obs import TelemetrySpec
        from graphite_tpu.tools._template import config_text

        tl_tiles = int(os.environ.get("BENCH_TELEMETRY_TILES", "16"))
        sc_tl = SimConfig(ConfigFile.from_string(config_text(
            tl_tiles, shared_mem=True, clock_scheme="lax_barrier")))
        tl_trace = synthetic.memory_stress_trace(
            tl_tiles, n_accesses=24, working_set_bytes=1 << 13,
            write_fraction=0.4, shared_fraction=0.5, seed=7)
        base = Simulator(sc_tl, tl_trace)
        base.warmup()
        t0 = time.perf_counter()
        base.run()
        base_s = time.perf_counter() - t0
        base_iters = max(int(base.last_n_iterations), 1)
        tel = Simulator(sc_tl, tl_trace, telemetry=TelemetrySpec(
            sample_interval_ps=int(base.quantum_ps), n_samples=256))
        tel.warmup()
        t0 = time.perf_counter()
        tel_res = tel.run()
        tel_s = time.perf_counter() - t0
        tel_iters = max(int(tel.last_n_iterations), 1)
        ms_off = 1000 * base_s / base_iters
        ms_on = 1000 * tel_s / tel_iters
        tl_summary = tel_res.telemetry.summary()
        companions.update({
            "ms_per_iter_no_telemetry": round(ms_off, 4),
            "ms_per_iter_telemetry": round(ms_on, 4),
            "telemetry_overhead_pct": round(100 * (ms_on / ms_off - 1), 2),
            "telemetry_samples": tl_summary["samples"],
            "telemetry_peak_injection_per_ns": tl_summary.get(
                "peak_injection_per_ns"),
            "telemetry_mean_clock_spread_ps": tl_summary.get(
                "mean_clock_spread_ps"),
        })

    # Spatial-profiler overhead (round 16, obs/profile.py): warm
    # per-iteration cost of recording the DENSE per-tile [S, T, m] ring
    # (every available tile series, S=256, sampled every quantum — the
    # worst case) vs the scalar-telemetry-only ring vs recording
    # nothing, on the same 16-tile coherence program.  MEDIANS of
    # BENCH_PROFILE_REPS warm runs (per-run wall on CPU is noisy at
    # this size), plus the ring's residency bill and the straggler
    # summary CI tracks.  Skippable via BENCH_PROFILE=0.
    if os.environ.get("BENCH_PROFILE", "1") != "0":
        import statistics as _stats

        from graphite_tpu.obs import ProfileSpec, TelemetrySpec
        from graphite_tpu.tools._template import config_text

        pf_tiles = int(os.environ.get("BENCH_PROFILE_TILES", "16"))
        reps = max(1, int(os.environ.get("BENCH_PROFILE_REPS", "3")))
        sc_pf = SimConfig(ConfigFile.from_string(config_text(
            pf_tiles, shared_mem=True, clock_scheme="lax_barrier")))
        pf_trace = synthetic.memory_stress_trace(
            pf_tiles, n_accesses=24, working_set_bytes=1 << 13,
            write_fraction=0.4, shared_fraction=0.5, seed=7)

        def _median_ms_iter(mk):
            # run() consumes self.state (a finished sim re-runs as a
            # no-op), so each rep gets a FRESH instance adopting the
            # warmed donor's compiled runner — every sample times a
            # full run, none times a retrace
            donor = mk()
            donor.warmup()
            samples = []
            res2 = sim2 = None
            for _ in range(reps):
                sim2 = mk()
                sim2.adopt_runner(donor)
                t0 = time.perf_counter()
                res2 = sim2.run()
                wall = time.perf_counter() - t0
                assert int(sim2.last_n_iterations) > 0
                samples.append(
                    1000 * wall / int(sim2.last_n_iterations))
            return _stats.median(samples), res2, sim2

        probe = Simulator(sc_pf, pf_trace)
        qps_pf = int(probe.quantum_ps)
        tel_spec = TelemetrySpec(sample_interval_ps=qps_pf,
                                 n_samples=256)
        prof_spec = ProfileSpec(sample_interval_ps=qps_pf,
                                n_samples=256)
        ms_pf_off, _, _ = _median_ms_iter(
            lambda: Simulator(sc_pf, pf_trace))
        ms_pf_tel, _, _ = _median_ms_iter(
            lambda: Simulator(sc_pf, pf_trace, telemetry=tel_spec))
        ms_pf_on, pf_res, pf_sim = _median_ms_iter(
            lambda: Simulator(sc_pf, pf_trace, telemetry=tel_spec,
                              profile=prof_spec))
        pf_summary = pf_res.profile.summary()
        companions.update({
            "ms_per_iter_profile_off": round(ms_pf_off, 4),
            "ms_per_iter_telemetry_only": round(ms_pf_tel, 4),
            "ms_per_iter_profile": round(ms_pf_on, 4),
            "profile_overhead_pct": round(
                100 * (ms_pf_on / ms_pf_tel - 1), 2),
            "profile_ring_bytes": int(
                pf_sim.residency_breakdown()["profile"]),
            "profile_max_skew_ps": pf_summary.get("max_skew_ps"),
            "profile_straggler_tile": pf_summary.get("straggler_tile"),
            "profile_traffic_gini": pf_summary.get("traffic_gini"),
        })

    # Latency-histogram overhead (round 21, obs/hist.py): warm
    # per-iteration cost of the DENSE commit-site scatter-add recording
    # (every available source into the log2 bucket ladder — the worst
    # case) vs the scalar telemetry ring alone vs recording nothing,
    # on the same 16-tile coherence program, plus the deterministic
    # miss-service-latency quantiles CI tracks.  MEDIANS of
    # BENCH_HIST_REPS warm runs.  Skippable via BENCH_HIST=0.
    if os.environ.get("BENCH_HIST", "1") != "0":
        import statistics as _stats_h

        from graphite_tpu.obs import HistSpec, TelemetrySpec
        from graphite_tpu.tools._template import config_text

        hs_tiles = int(os.environ.get("BENCH_HIST_TILES", "16"))
        hs_reps = max(1, int(os.environ.get("BENCH_HIST_REPS", "3")))
        sc_hs = SimConfig(ConfigFile.from_string(config_text(
            hs_tiles, shared_mem=True, clock_scheme="lax_barrier")))
        hs_trace = synthetic.memory_stress_trace(
            hs_tiles, n_accesses=24, working_set_bytes=1 << 13,
            write_fraction=0.4, shared_fraction=0.5, seed=7)

        def _median_ms_iter_h(mk):
            # fresh instance per rep adopting the warmed donor's
            # runner — same shape as the profile block's sampler
            donor = mk()
            donor.warmup()
            samples = []
            res2 = sim2 = None
            for _ in range(hs_reps):
                sim2 = mk()
                sim2.adopt_runner(donor)
                t0 = time.perf_counter()
                res2 = sim2.run()
                wall = time.perf_counter() - t0
                assert int(sim2.last_n_iterations) > 0
                samples.append(
                    1000 * wall / int(sim2.last_n_iterations))
            return _stats_h.median(samples), res2, sim2

        probe_h = Simulator(sc_hs, hs_trace)
        tel_h = TelemetrySpec(
            sample_interval_ps=int(probe_h.quantum_ps), n_samples=256)
        ms_hs_off, _, _ = _median_ms_iter_h(
            lambda: Simulator(sc_hs, hs_trace))
        ms_hs_tel, _, _ = _median_ms_iter_h(
            lambda: Simulator(sc_hs, hs_trace, telemetry=tel_h))
        ms_hs_on, hs_res, hs_sim = _median_ms_iter_h(
            lambda: Simulator(sc_hs, hs_trace, hist=HistSpec()))
        hist = hs_res.hist
        companions.update({
            "ms_per_iter_hist_off": round(ms_hs_off, 4),
            "ms_per_iter_hist_scalar_ring": round(ms_hs_tel, 4),
            "ms_per_iter_hist": round(ms_hs_on, 4),
            "hist_overhead_pct": round(
                100 * (ms_hs_on / ms_hs_off - 1), 2),
            "hist_ring_bytes": int(
                hs_sim.residency_breakdown()["hist"]),
            "miss_lat_p50_ps": hist.quantile("miss_lat_ps", 0.5),
            "miss_lat_p95_ps": hist.quantile("miss_lat_ps", 0.95),
            "miss_lat_p99_ps": hist.quantile("miss_lat_ps", 0.99),
        })

    # Campaign-service throughput (round 13, serve/ subsystem): N
    # same-class jobs submitted through the admission-controlled
    # service, batched and served off the fingerprint-keyed compiled-
    # program cache — the service-level view of the round-7 batching
    # win (jobs/s is COMPILE-INCLUSIVE: one compile amortized over the
    # whole job stream is exactly the economics the service sells).
    # The sequential baseline runs the SAME jobs one-by-one through the
    # bit-exact oracle path (a fresh Simulator per job with the knobs
    # baked static — what a campaign without the service pays).
    # Skippable via BENCH_SERVE=0; sizes via BENCH_SERVE_JOBS/_BATCH.
    if os.environ.get("BENCH_SERVE", "1") != "0":
        import dataclasses as _dcs

        from graphite_tpu.serve import CampaignService, Job
        from graphite_tpu.tools._template import config_text

        sv_jobs = int(os.environ.get("BENCH_SERVE_JOBS", "8"))
        sv_batch = int(os.environ.get("BENCH_SERVE_BATCH", "4"))
        sv_tiles = int(os.environ.get("BENCH_SERVE_TILES", "16"))
        sc_sv = SimConfig(ConfigFile.from_string(config_text(
            sv_tiles, shared_mem=True, clock_scheme="lax")))

        def _sv_trace(seed):
            return synthetic.memory_stress_trace(
                sv_tiles, n_accesses=24, working_set_bytes=1 << 13,
                write_fraction=0.4, shared_fraction=0.5, seed=seed)

        jobs = [Job(f"bench-{i}", sc_sv, _sv_trace(i + 1),
                    knobs={"dram_latency_ns": 40 + 10 * i}, seed=i + 1)
                for i in range(sv_jobs)]
        service = CampaignService(batch_size=sv_batch)
        t0 = time.perf_counter()
        for job in jobs:
            service.submit(job)
        served = service.run_all()
        serve_wall = time.perf_counter() - t0
        assert len(served) == sv_jobs and all(r.ok for r in served)
        t0 = time.perf_counter()
        for job in jobs:
            seq_sim = Simulator(sc_sv, job.trace)
            seq_sim.params = _dcs.replace(
                seq_sim.params,
                mem=_dcs.replace(seq_sim.params.mem, **job.knobs))
            seq_sim.run()
        seq_wall = time.perf_counter() - t0
        sv_c = service.counters
        companions.update({
            "serve_jobs": sv_jobs,
            "serve_jobs_per_s": round(sv_jobs / serve_wall, 3),
            "serve_batch_occupancy": round(
                sv_c["mean_batch_occupancy"], 3),
            "serve_cache_hit_rate": round(sv_c["cache_hit_rate"], 3),
            "serve_compile_count": sv_c["compile_count"],
            "serve_vs_sequential": round(seq_wall / serve_wall, 3),
            "sequential_jobs_per_s": round(sv_jobs / seq_wall, 3),
        })

        # Observability overhead (round 14, obs/ host side): the SAME
        # job stream through a service with span tracing + the metrics
        # registry on — so the "observability is ~free" claim is
        # measured, not asserted.  Both runs are compile-inclusive
        # (each service pays its one compile), so the ratio compares
        # like with like.  Skippable via BENCH_OBS=0.
        if os.environ.get("BENCH_OBS", "1") != "0":
            service_t = CampaignService(batch_size=sv_batch,
                                        tracing=True)
            t0 = time.perf_counter()
            for job in jobs:
                service_t.submit(job)
            served_t = service_t.run_all()
            traced_wall = time.perf_counter() - t0
            assert len(served_t) == sv_jobs and all(r.ok for r in served_t)
            dwell = service_t.metrics["queue_dwell_seconds"]
            companions.update({
                "serve_jobs_per_s_traced": round(
                    sv_jobs / traced_wall, 3),
                "obs_overhead_pct": round(
                    100 * (traced_wall / serve_wall - 1), 2),
                "obs_spans": len(service_t.tracer.spans),
                "obs_queue_dwell_p90_s": dwell.quantile(0.9),
            })

        # Persistent AOT program store (round 17, store/ subsystem):
        # the SAME job stream through (a) a cold store-backed service —
        # pays the one compile AND the serialize/fill — then (b) a
        # warm-started second service over the same store, which
        # deserializes instead of compiling.  The warm jobs/s vs the
        # round-13 in-memory serve_jobs_per_s is the fleet cold-start
        # win the store sells; per-class compile vs deserialize wall
        # is the microscopic view.  Skippable via BENCH_STORE=0; rides
        # INSIDE the serve section (it reuses its job set and its
        # serve_wall baseline), so BENCH_SERVE=0 disables it too.
        if os.environ.get("BENCH_STORE", "1") != "0":
            import shutil as _sh
            import tempfile as _tf

            sdir = _tf.mkdtemp(prefix="graphite-bench-store-")
            try:
                service_c = CampaignService(batch_size=sv_batch,
                                            store=sdir)
                t0 = time.perf_counter()
                for job in jobs:
                    service_c.submit(job)
                served_c = service_c.run_all()
                cold_wall = time.perf_counter() - t0
                assert len(served_c) == sv_jobs \
                    and all(r.ok for r in served_c)

                service_w = CampaignService(batch_size=sv_batch,
                                            store=sdir)
                t0 = time.perf_counter()
                n_warm = service_w.warm_start()
                for job in jobs:
                    service_w.submit(job)
                served_w = service_w.run_all()
                warm_wall = time.perf_counter() - t0
                assert len(served_w) == sv_jobs \
                    and all(r.ok for r in served_w)
                c_cold = service_c.counters
                c_warm = service_w.counters
                des = service_w.metrics["store_deserialize_seconds"]
                comp = service_c.metrics["compile_seconds"]
                companions.update({
                    "store_cold_jobs_per_s": round(
                        sv_jobs / cold_wall, 3),
                    "store_warm_jobs_per_s": round(
                        sv_jobs / warm_wall, 3),
                    # warm fleet member vs the round-13 in-memory serve
                    # (both compile-inclusive from THEIR perspective:
                    # the warm one simply has no compiles left to pay)
                    "store_warm_vs_inmem_serve": round(
                        (sv_jobs / warm_wall)
                        / (sv_jobs / serve_wall), 3),
                    "store_compile_s_per_class": round(comp.mean, 3),
                    "store_deserialize_s_per_class": round(
                        des.mean, 3),
                    "store_warm_start_classes": n_warm,
                    "store_cold_compiles": c_cold["compile_count"],
                    "store_warm_compiles": c_warm["compile_count"],
                    "store_fills": c_cold["store_fills"],
                    "store_warm_hits": c_warm["store_hits"],
                })
            finally:
                _sh.rmtree(sdir, ignore_errors=True)

    # Runtime-DVFS overhead + race-to-idle campaign (round 19, dvfs/):
    # (a) warm per-iteration cost of CARRYING per-domain frequency
    # through the quantum loop (DvfsSpec attached at the config's own
    # frequencies, so both memory engines and the network/DRAM timing
    # read carried state instead of constant-folded MemParams) vs the
    # folded baseline on the 16-tile coherence program — MEDIANS of
    # BENCH_DVFS_REPS warm runs; (b) the headline race-to-idle
    # campaign: TWO domain layouts (chip-global, core/uncore split) x
    # a per-domain frequency grid served as ONE job stream with
    # V^2*f-scaled energy pricing, one (energy_pj, wall) trade point
    # per operating point — the rows tools/report.py --trade-curve
    # renders as the energy-vs-wall Pareto frontier.  Skippable via
    # BENCH_DVFS=0; rows also land in $BENCH_DVFS_OUT (JSON-lines)
    # when that is set.
    if os.environ.get("BENCH_DVFS", "1") != "0":
        import statistics as _stats

        from graphite_tpu.dvfs import DvfsSpec
        from graphite_tpu.obs import EnergyPrices, TelemetrySpec
        from graphite_tpu.serve import CampaignService, Job
        from graphite_tpu.tools._template import config_text

        dv_tiles = int(os.environ.get("BENCH_DVFS_TILES", "16"))
        dv_reps = max(1, int(os.environ.get("BENCH_DVFS_REPS", "3")))
        sc_dv = SimConfig(ConfigFile.from_string(config_text(
            dv_tiles, shared_mem=True, clock_scheme="lax_barrier")))
        dv_trace = synthetic.memory_stress_trace(
            dv_tiles, n_accesses=24, working_set_bytes=1 << 13,
            write_fraction=0.4, shared_fraction=0.5, seed=7)

        def _dv_median(mk):
            donor = mk()
            donor.warmup()
            samples = []
            for _ in range(dv_reps):
                sim2 = mk()
                sim2.adopt_runner(donor)
                t0 = time.perf_counter()
                sim2.run()
                wall = time.perf_counter() - t0
                samples.append(
                    1000 * wall / max(int(sim2.last_n_iterations), 1))
            return _stats.median(samples)

        ms_dv_off = _dv_median(lambda: Simulator(sc_dv, dv_trace))
        ms_dv_on = _dv_median(
            lambda: Simulator(sc_dv, dv_trace, dvfs=DvfsSpec()))
        companions.update({
            "ms_per_iter_dvfs_off": round(ms_dv_off, 4),
            "ms_per_iter_dvfs_carried": round(ms_dv_on, 4),
            "dvfs_carry_overhead_pct": round(
                100 * (ms_dv_on / ms_dv_off - 1), 2),
        })

        # race-to-idle: one served stream, two admission classes (the
        # domain layout is part of the config digest AND Job.dvfs
        # joins the class key), frequency grid co-batched per class
        # through the dvfs_domain_mhz knob
        dv_extra = """
[general]
technology_node = 22
[dvfs]
max_frequency = 1.0
synchronization_delay = 2
domains = "{domains}"
"""
        sc_one = SimConfig(ConfigFile.from_string(
            config_text(dv_tiles, shared_mem=True, clock_scheme="lax")
            + dv_extra.format(
                domains="<1.0, CORE, L1_ICACHE, L1_DCACHE, L2_CACHE, "
                "DIRECTORY, NETWORK_USER, NETWORK_MEMORY>")))
        sc_two = SimConfig(ConfigFile.from_string(
            config_text(dv_tiles, shared_mem=True, clock_scheme="lax")
            + dv_extra.format(
                domains="<1.0, CORE, L1_ICACHE, L1_DCACHE, L2_CACHE>, "
                "<1.0, DIRECTORY, NETWORK_USER, NETWORK_MEMORY>")))
        prices = EnergyPrices(
            instruction_pj=3, l1d_access_pj=2, l2_access_pj=9,
            l2_miss_pj=120, invalidation_pj=15, eviction_pj=20,
            dram_access_pj=500, packet_pj=7)
        tel_dv = TelemetrySpec(sample_interval_ps=1_000_000,
                               n_samples=256, energy_prices=prices)
        grid_one = ((1000,), (870,), (750,), (500,))
        grid_two = ((1000, 1000), (870, 1000), (750, 870), (500, 630))
        dv_jobs = [
            Job(f"r2i-one-{p[0]}", sc_one, dv_trace,
                knobs={"dvfs_domain_mhz": p}, dvfs=DvfsSpec(),
                telemetry=tel_dv)
            for p in grid_one
        ] + [
            Job(f"r2i-two-{p[0]}-{p[1]}", sc_two, dv_trace,
                knobs={"dvfs_domain_mhz": p}, dvfs=DvfsSpec(),
                telemetry=tel_dv)
            for p in grid_two
        ]
        svc_dv = CampaignService(batch_size=4, max_quanta=200_000)
        t0 = time.perf_counter()
        for job in dv_jobs:
            svc_dv.submit(job)
        served_dv = svc_dv.run_all()
        r2i_wall = time.perf_counter() - t0
        assert len(served_dv) == len(dv_jobs) \
            and all(r.ok for r in served_dv)
        trade = [r.to_json() for r in served_dv]
        assert all("energy_pj" in row for row in trade)
        out_path = os.environ.get("BENCH_DVFS_OUT")
        if out_path:
            with open(out_path, "w") as fh:
                for row in trade:
                    fh.write(json.dumps(row) + "\n")
        companions.update({
            "dvfs_campaign_jobs": len(dv_jobs),
            "dvfs_campaign_classes": svc_dv.counters["compile_count"],
            "dvfs_campaign_wall_s": round(r2i_wall, 3),
            "dvfs_trade_points": [
                {"job": row["job"],
                 "dvfs_domain_mhz": row["dvfs_domain_mhz"],
                 "wall_ns": row["completion_time_ns"],
                 "energy_pj": row["energy_pj"]}
                for row in trade],
        })

    # Static cost-model trajectory (round 12): the audited gated-MSI
    # program's per-iteration kernel/byte proxy and its per-phase/base
    # split (analysis/cost.py — the SAME numbers BUDGETS.json gates), so
    # BENCH_r*.json tracks the proxy on CPU where wall-clock is noisy.
    # Skippable via BENCH_COST=0.
    if os.environ.get("BENCH_COST", "1") != "0":
        from graphite_tpu.analysis.audit import default_programs
        from graphite_tpu.analysis.cost import cost_report

        spec = default_programs(8, names=("gated-msi",))[0]
        rep = cost_report(spec)
        companions.update({
            "cost_program": rep.program,
            "kernels_per_iter": int(rep.kernels_per_iter),
            "bytes_per_iter": int(rep.bytes_per_iter),
            "phase_kernels_per_iter": {
                p.name: int(p.eqns) for p in rep.phase_costs},
            "base_kernels_per_iter": int(rep.base_kernels_per_iter),
        })

    print(
        json.dumps(
            {
                # only the ring workload honors BENCH_COMPRESSED; the
                # benchmark programs always emit bblock-compressed compute
                "metric": f"simulated instr/s ({N_TILES}-tile emesh, "
                f"{desc}, "
                + ("bblock" if COMPRESSED or WORKLOAD != "ring"
                   else "per-instr")
                + " trace)",
                "value": round(ips),
                "unit": "instr/s",
                "vs_baseline": round(ips / BASELINE_INSTR_PER_SEC, 4),
                **companions,
            }
        )
    )


def _main_with_retry() -> None:
    """The tunnel can hand a fresh client UNAVAILABLE right after another
    TPU process exits; re-exec once so a transient never fails the bench."""
    try:
        main()
    except Exception as e:  # noqa: BLE001
        if ("UNAVAILABLE" in str(e)
                and not os.environ.get("GRAPHITE_BENCH_RETRIED")):
            os.environ["GRAPHITE_BENCH_RETRIED"] = "1"
            time.sleep(10)
            os.execv(sys.executable, [sys.executable] + sys.argv)
        raise


if __name__ == "__main__":
    sys.exit(_main_with_retry())
