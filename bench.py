"""Benchmark: aggregate simulated instructions/second on one chip.

North star (BASELINE.json): ≥10M aggregate simulated instr/s at 1024 tiles.
The kernel: a compute+message workload (BENCH_TILES, default 1024 tiles) (nearest-neighbor
pattern over the e-mesh, hop-counter NoC timing) replayed through the full
vectorized core/network/sync stack.  Prints exactly one JSON line.
"""

import json
import os
import sys
import time

N_TILES = int(os.environ.get("BENCH_TILES", "1024"))
N_ROUNDS = int(os.environ.get("BENCH_ROUNDS", "64"))
COMPUTE_PER_ROUND = int(os.environ.get("BENCH_COMPUTE", "62"))
# Basic-block-granularity replay (one BBLOCK record per straight-line run,
# cycle-identical timing — the engine's native trace granularity).  Set
# BENCH_COMPRESSED=0 to replay one record per instruction instead, which
# measures the raw per-record engine rate.
COMPRESSED = os.environ.get("BENCH_COMPRESSED", "1") != "0"
BASELINE_INSTR_PER_SEC = 10_000_000  # BASELINE.json north star


def main() -> None:
    import graphite_tpu  # noqa: F401  (x64)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from graphite_tpu.config import ConfigFile, SimConfig
    from graphite_tpu.engine.simulator import Simulator
    from graphite_tpu.trace import synthetic

    cfg_text = f"""
[general]
total_cores = {N_TILES}
mode = lite
max_frequency = 1.0
[network]
user = emesh_hop_counter
memory = emesh_hop_counter
[network/emesh_hop_counter]
flit_width = 64
[network/emesh_hop_counter/router]
delay = 1
[network/emesh_hop_counter/link]
delay = 1
[core/static_instruction_costs]
generic = 1
mov = 1
ialu = 1
imul = 3
falu = 3
fmul = 5
[branch_predictor]
type = one_bit
mispredict_penalty = 14
size = 1024
[clock_skew_management]
scheme = lax
"""
    sc = SimConfig(ConfigFile.from_string(cfg_text))
    batch = synthetic.message_ring_batch(
        N_TILES, n_rounds=N_ROUNDS, compute_per_round=COMPUTE_PER_ROUND,
        compressed=COMPRESSED,
    )
    sim = Simulator(sc, batch, mailbox_depth=8, inner_block=64)

    # Warm-up: compile (and run once) the full device-side simulation loop.
    sim.warmup()

    t0 = time.perf_counter()
    results = sim.run()
    elapsed = time.perf_counter() - t0

    total_instr = results.total_instructions
    ips = total_instr / elapsed
    print(
        json.dumps(
            {
                "metric": f"simulated instr/s ({N_TILES}-tile emesh, "
                f"compute+message workload, "
                f"{'bblock' if COMPRESSED else 'per-instr'} trace)",
                "value": round(ips),
                "unit": "instr/s",
                "vs_baseline": round(ips / BASELINE_INSTR_PER_SEC, 4),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
