"""Simulated-time types: picosecond-resolution Time and cycle Latency.

Reference semantics: `common/misc/time_types.h:7-119`.
 - Time is an integer picosecond count (`time_types.h:31-78`).
 - Latency is (cycles, frequency-in-GHz); conversion to picoseconds is
   ceil(1000 * cycles / frequency) (`time_types.h:81-86`).
 - Time.toCycles(frequency) = ceil(ps * frequency / 1000) (`time_types.h:104-109`).
 - Time.toNanosec = ceil(ps / 1000) (`time_types.h:111-114`).

Design differences for the TPU build:
 - Frequencies are carried as *integer megahertz* so every conversion is exact
   integer ceil-division — device code (int32/int64 tensors) and host code
   produce bit-identical results, which the determinism tests rely on.  The
   reference's `double`-based ceil matches integer ceil-div for every
   frequency expressible in MHz (all of `technology/dvfs_levels_*.cfg` is).
 - Both scalar-host and jnp-tensor forms are provided; the tensor forms are
   what the vectorized models use.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from graphite_tpu.intmath import nn_ceil_div

# Conversion factors.
PS_PER_NS = 1000
PS_PER_CYCLE_NUMERATOR = 1_000_000  # ps/cycle = 1e6 / freq_mhz


def ghz_to_mhz(freq_ghz: float) -> int:
    """Represent a GHz float frequency exactly as integer MHz."""
    mhz = round(freq_ghz * 1000.0)
    if mhz <= 0:
        raise ValueError(f"non-positive frequency: {freq_ghz} GHz")
    return int(mhz)


def _ceil_div(a, b):
    """Ceil division for non-negative ints; works on ints and jnp arrays.

    Every caller's operands are non-negative by contract (cycle counts,
    picosecond durations, MHz frequencies), so the device form routes
    through `intmath.nn_ceil_div` — a single `lax.div` instead of the
    ~9-equation sign-fixup chain jnp's `//` lowers to, bit-identical on
    non-negative operands (PERF.md round 12)."""
    return nn_ceil_div(a, b)


def cycles_to_ps(cycles, freq_mhz):
    """Latency::toPicosec (`time_types.h:81-86`): ceil(1e6*cycles/freq_mhz).

    Works elementwise on jnp int arrays (int64 recommended) and python ints.
    """
    return _ceil_div(cycles * PS_PER_CYCLE_NUMERATOR, freq_mhz)


def ps_to_cycles(ps, freq_mhz):
    """Time::toCycles (`time_types.h:104-109`): ceil(ps*freq_mhz/1e6)."""
    return _ceil_div(ps * freq_mhz, PS_PER_CYCLE_NUMERATOR)


def ps_to_ns(ps):
    """Time::toNanosec (`time_types.h:111-114`): ceil(ps/1000)."""
    return _ceil_div(ps, PS_PER_NS)


def ns_to_ps(ns):
    return ns * PS_PER_NS


@dataclasses.dataclass(frozen=True, order=True)
class Time:
    """Host-side scalar simulated time, integer picoseconds.

    Mirrors `common/misc/time_types.h:31-78`.  Device-side code uses raw
    int64 tensors of picoseconds; this wrapper is for host orchestration,
    config parsing, and summaries.
    """

    ps: int = 0

    def __add__(self, other: "Time | Latency") -> "Time":
        if isinstance(other, Latency):
            return Time(self.ps + other.to_ps())
        return Time(self.ps + other.ps)

    def __sub__(self, other: "Time") -> "Time":
        return Time(self.ps - other.ps)

    def to_cycles(self, freq_mhz: int) -> int:
        return ps_to_cycles(self.ps, freq_mhz)

    def to_ns(self) -> int:
        return ps_to_ns(self.ps)

    def to_sec(self) -> float:
        return self.ps / 1.0e12

    @staticmethod
    def from_ns(ns: int) -> "Time":
        return Time(ns * PS_PER_NS)

    @staticmethod
    def from_cycles(cycles: int, freq_mhz: int) -> "Time":
        return Time(cycles_to_ps(cycles, freq_mhz))


@dataclasses.dataclass(frozen=True)
class Latency:
    """Host-side (cycles, frequency) pair; `time_types.h:7-29`.

    Adding latencies requires matching frequencies, as in the reference
    (`time_types.h:88-102`).
    """

    cycles: int
    freq_mhz: int

    def __add__(self, other: "Latency") -> "Latency":
        if self.freq_mhz != other.freq_mhz:
            raise ValueError(
                "Attempting to add latencies from different frequencies"
            )
        return Latency(self.cycles + other.cycles, self.freq_mhz)

    def to_ps(self) -> int:
        return cycles_to_ps(self.cycles, self.freq_mhz)

    def to_time(self) -> Time:
        return Time(self.to_ps())


# --- Device-side helpers -------------------------------------------------

TIME_DTYPE = jnp.int64  # absolute simulated times
DELTA_DTYPE = jnp.int32  # per-quantum deltas (quantum ≤ ~2ms always fits)


def time_zeros(shape):
    return jnp.zeros(shape, dtype=TIME_DTYPE)
