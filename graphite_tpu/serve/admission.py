"""Admission control: budget bin-packing + FIFO queueing for the service.

The admission controller answers three questions per submitted job,
entirely from host-side arithmetic (no tracing, no compile):

 - *which program class* does it belong to?  Jobs co-batch only when
   they provably share one compiled program: same config digest, same
   tile count, same memory-ness, same telemetry spec, same per-tile
   profile spec, and the same
   bucketed mailbox depth / trace length (lengths and depths round up
   to powers of two so successive batches share one [B, T, L] shape —
   and therefore one program-cache entry);

 - *can it ever fit*?  The per-sim residency bill — state pytree +
   padded trace rows + telemetry ring, the exact consumers
   `analysis/cost.residency_breakdown` itemizes — is compared against
   `hbm_budget_bytes`.  A job whose B=1 bill exceeds the budget can
   never be admitted and is rejected IMMEDIATELY with the itemized
   breakdown (`ResidencyBudgetError`, the round-10 refusal type);

 - *how many co-batch*?  Every campaign consumer scales linearly in B,
   so the class's batch capacity is `budget // per_sim_total`, clamped
   to the service's `batch_size`.  No admitted batch's
   `residency_breakdown` total can exceed the budget by construction
   (and the SweepRunner's own pre-compile fail-fast re-proves it).

Jobs that fit but not *now* wait in per-class FIFO queues under a
global `max_pending` bound — when the queue is full, `admit` raises
`QueueFullError` (backpressure: the caller must drain results before
submitting more).  `next_batch` serves the class whose HEAD job is
globally oldest, so no class starves behind a busier one (FIFO
fairness across classes, strict FIFO within one).
"""

from __future__ import annotations

import collections
import dataclasses

from graphite_tpu.serve.job import Job, config_digest


class QueueFullError(RuntimeError):
    """Backpressure: the pending queue is at `max_pending`."""


def _pow2_bucket(n: int, lo: int) -> int:
    """Smallest power of two >= max(n, lo)."""
    n = max(int(n), int(lo))
    return 1 << (n - 1).bit_length()


@dataclasses.dataclass
class Pending:
    """One queued job plus its service bookkeeping."""

    job: Job
    seq: int           # global submission order (FIFO fairness key)
    attempts: int = 0  # failed executions so far (split/retry budget)
    # observability (round 14): service-clock timestamp of the LAST
    # enqueue (submit or requeue — the queue-dwell histogram's start;
    # None until the service stamps it), and the dwell the most recent
    # batch-form measured from it
    enqueue_ts: "float | None" = None
    dwell_s: float = 0.0


class JobClass:
    """One program class: jobs that provably share a compiled program.

    A probe Simulator is built once (never run) to read the engine
    params and the per-sim state bytes, then dropped; the class keeps
    the per-sim residency bill, the batch capacity the budget allows,
    and the class FIFO.
    """

    def __init__(self, key: tuple, job: Job, *, mailbox_depth: int,
                 pad_length: int, hbm_budget_bytes: int, batch_size: int):
        from graphite_tpu.analysis.cost import tree_bytes
        from graphite_tpu.engine.simulator import Simulator

        self.key = key
        self.config = job.resolved_config()
        self.mailbox_depth = int(mailbox_depth)
        self.pad_length = int(pad_length)
        self.fifo: "collections.deque[Pending]" = collections.deque()
        # The probe: ONE Simulator built exactly the way the batch
        # runner will build its per-sim program (same config, same
        # mailbox depth), so its state pytree IS the per-sim state bill.
        # Telemetry stays off the probe — the ring is priced separately
        # (obs.TelemetrySpec.ring_bytes, the one size model).
        from graphite_tpu.analysis.cost import trace_record_bytes

        probe = Simulator(self.config, job.trace,
                          mailbox_depth=self.mailbox_depth,
                          barrier_host=False)
        # keep only the params and the byte counts: the probe's state
        # pytree is real device memory, and retaining one per class
        # forever would be exactly the residency this controller
        # exists to police
        self.params = probe.params
        self.telemetry = None
        if job.telemetry is not None:
            self.telemetry = job.telemetry.resolve(self.params)
        # the per-tile profile ring joins the admission bill the same
        # way (obs.ProfileSpec.ring_bytes — the one size model); its T
        # factor is what makes a dense big-tile profile pay its way
        # through the budget instead of OOMing a compiled batch
        self.profile = None
        if job.profile is not None:
            self.profile = job.profile.resolve(self.params)
        per_sim = {
            "state": int(tree_bytes(probe.state)),
            "trace": (self.params.n_tiles * self.pad_length
                      * trace_record_bytes(job.trace)),
        }
        if self.telemetry is not None:
            per_sim["telemetry"] = int(self.telemetry.ring_bytes())
        if self.profile is not None:
            per_sim["profile"] = int(self.profile.ring_bytes())
        self.per_sim_bytes = per_sim
        self.per_sim_total = sum(per_sim.values())
        if hbm_budget_bytes:
            self.batch_cap = min(
                int(batch_size),
                int(hbm_budget_bytes) // max(self.per_sim_total, 1))
        else:
            self.batch_cap = int(batch_size)

    @property
    def n_tiles(self) -> int:
        return int(self.params.n_tiles)

    def breakdown(self, batch: int = 1) -> "dict[str, int]":
        """The itemized residency bill for a `batch`-wide campaign of
        this class — consumer-for-consumer the dict
        `SweepRunner.residency_breakdown` computes for the real batch
        (every consumer scales linearly in B)."""
        out = {k: v * int(batch) for k, v in self.per_sim_bytes.items()}
        out["total"] = sum(out.values())
        return out


class AdmissionController:
    """Classify, budget-check, and queue jobs; form FIFO-fair batches."""

    def __init__(self, *, hbm_budget_bytes: int = 0, batch_size: int = 4,
                 max_pending: int = 1024):
        if int(batch_size) < 1:
            raise ValueError("batch_size must be >= 1")
        self.hbm_budget_bytes = int(hbm_budget_bytes)
        self.batch_size = int(batch_size)
        self.max_pending = int(max_pending)
        self.classes: "dict[tuple, JobClass]" = {}
        # pre-formed batches (split/retry requeues) served before any
        # new batch forms — without this, a split's halves would simply
        # re-coalesce into the failing batch on the next pop
        self._ready: "collections.deque[tuple]" = collections.deque()
        self._seq = 0
        self._depth = 0

    @property
    def queue_depth(self) -> int:
        return self._depth

    def class_key(self, job: Job) -> tuple:
        """The program-class key: everything that changes the compiled
        artifact and is knowable without tracing.  Traced knobs are
        deliberately absent (they share the program — that is the whole
        round-7 point); the cache's fingerprint check is the proof the
        key was sufficient."""
        from graphite_tpu.engine.simulator import auto_mailbox_depth

        depth = _pow2_bucket(auto_mailbox_depth(job.trace), 2)
        length = _pow2_bucket(job.trace.length, 16)
        tel = job.telemetry
        # energy_prices is part of the key: the pJ prices fold into the
        # compiled step as literals, so two jobs differing only in
        # prices lower different programs and must never co-batch
        tel_key = None if tel is None else (
            int(tel.sample_interval_ps), int(tel.n_samples), tel.series,
            tel.energy_prices)
        prof = job.profile
        # the profile spec is part of the key for the same reason: the
        # [S, T, m] ring (and its series selection / prices) is baked
        # into the lowering, so differing specs never co-batch
        prof_key = None if prof is None else (
            int(prof.sample_interval_ps), int(prof.n_samples),
            prof.series, prof.energy_prices)
        return (config_digest(job.resolved_config()), job.n_tiles,
                job.has_mem_trace(), depth, length, tel_key, prof_key)

    def admit(self, job: Job) -> "tuple[JobClass, Pending]":
        """Queue `job` (validated by the caller) or refuse it.

        Raises `analysis.cost.ResidencyBudgetError` — with the itemized
        per-consumer breakdown attached as `.breakdown` — when the job
        can NEVER fit the per-device budget, and `QueueFullError` when
        the pending queue is at `max_pending` (backpressure)."""
        from graphite_tpu.analysis.cost import (
            ResidencyBudgetError, format_breakdown,
        )

        if self._depth >= self.max_pending:
            raise QueueFullError(
                f"pending queue is full ({self._depth} >= max_pending="
                f"{self.max_pending}) — drain results before submitting "
                "more")
        key = self.class_key(job)
        cls = self.classes.get(key)
        if cls is None:
            cls = JobClass(key, job,
                           mailbox_depth=key[3], pad_length=key[4],
                           hbm_budget_bytes=self.hbm_budget_bytes,
                           batch_size=self.batch_size)
            self.classes[key] = cls
        if self.hbm_budget_bytes and cls.batch_cap < 1:
            bd = cls.breakdown(1)
            err = ResidencyBudgetError(
                f"job {job.job_id!r} can never fit hbm_budget_bytes="
                f"{self.hbm_budget_bytes}: one sim alone costs "
                + format_breakdown(bd)
                + " — shrink the trace/telemetry ring or raise the "
                "budget")
            err.breakdown = bd
            raise err
        pending = Pending(job=job, seq=self._seq)
        self._seq += 1
        cls.fifo.append(pending)
        self._depth += 1
        return cls, pending

    def requeue_batch(self, cls: JobClass,
                      pendings: "list[Pending]") -> None:
        """Requeue a split half (or a lone retry) as a PRE-FORMED batch
        at the head of the ready line: it must re-run at its reduced
        size — returning the jobs to the class FIFO would let the next
        pop re-coalesce the exact batch that just failed.  The jobs
        were admitted once, so max_pending does not apply again
        (refusing here would drop accepted work)."""
        self._ready.appendleft((cls, list(pendings)))
        self._depth += len(pendings)

    def _oldest_waiting(self) -> "JobClass | None":
        """The class whose HEAD job is globally oldest (no class
        starves) — the ONE selector `peek_batch` reports and
        `next_batch` pops, so the two can never drift apart."""
        waiting = [c for c in self.classes.values() if c.fifo]
        if not waiting:
            return None
        return min(waiting, key=lambda c: c.fifo[0].seq)

    def peek_batch(self) -> "tuple[JobClass, int, Pending, bool] | None":
        """What `next_batch` WOULD pop, without popping: (class, batch
        size, head job, preformed) or None on an idle queue.  The
        service's latency-aware dwell policy reads this to decide
        whether an under-full batch should wait for more arrivals;
        `preformed` marks a requeued split/retry batch, which must
        never wait (its jobs are the globally oldest)."""
        if self._ready:
            cls, batch = self._ready[0]
            return cls, len(batch), batch[0], True
        cls = self._oldest_waiting()
        if cls is None:
            return None
        return (cls, min(len(cls.fifo), cls.batch_cap), cls.fifo[0],
                False)

    def full_class(self) -> "JobClass | None":
        """A class whose queue can ALREADY fill a batch (oldest head
        among them), or None.  The dwell policy runs a full class
        while the globally-oldest under-full head keeps aging — a full
        batch gains nothing by waiting."""
        full = [c for c in self.classes.values()
                if len(c.fifo) >= c.batch_cap]
        if not full:
            return None
        return min(full, key=lambda c: c.fifo[0].seq)

    def next_batch(self, from_cls: "JobClass | None" = None
                   ) -> "tuple[JobClass, list[Pending]] | None":
        """Pop the next batch: requeued (split/retry) batches first —
        they hold the globally oldest jobs — then the class whose HEAD
        job is globally oldest (no class starves), up to the class's
        budget-derived batch capacity, strict FIFO within the class.
        `from_cls` pops from a specific class instead (the dwell
        policy's run-the-full-class-now path); requeued batches still
        outrank it."""
        if self._ready:
            cls, batch = self._ready.popleft()
            self._depth -= len(batch)
            return cls, batch
        cls = from_cls if from_cls is not None else self._oldest_waiting()
        if cls is None:
            return None
        batch = []
        while cls.fifo and len(batch) < cls.batch_cap:
            batch.append(cls.fifo.popleft())
        if not batch:
            return None
        self._depth -= len(batch)
        return cls, batch
