"""Admission control: budget bin-packing + FIFO queueing for the service.

The admission controller answers three questions per submitted job,
entirely from host-side arithmetic (no tracing, no compile):

 - *which program class* does it belong to?  Jobs co-batch only when
   they provably share one compiled program: same config digest, same
   tile count, same memory-ness, same telemetry spec, same per-tile
   profile spec, the same runtime-DVFS spec (the carried-frequency
   reads are baked into the program — differing domain configurations
   never co-batch, while `dvfs_domain_mhz` knob points of ONE spec
   do), the same latency-histogram spec (round 21 — the int64 bucket
   ring is baked into the program too), the same
   bucketed mailbox depth / trace length (lengths and depths round up
   to powers of two so successive batches share one [B, T, L] shape —
   and therefore one program-cache entry), and — round 18 — the same
   DEVICE LAYOUT axis: a job served under the 2D batch x tile mesh
   lowers a different program than a solo job, so 1D and 2D jobs never
   co-batch (the layout tag is the key's last element);

 - *can it ever fit*?  The per-sim residency bill — state pytree +
   padded trace rows + telemetry ring, the exact consumers
   `analysis/cost.residency_breakdown` itemizes — is compared against
   `hbm_budget_bytes`.  A job whose B=1 bill exceeds ONE device's
   budget is no longer bounced (round 18): with `n_devices` > 1 the
   bill is split into per-device TILE BLOCKS
   (`analysis/cost.device_residency_breakdown` — the big per-tile
   arrays, trace rows and profile ring shard with the directory) and
   the job is admitted under the smallest tile split whose per-device
   block fits.  Only a job too big even when split over EVERY device
   is rejected — immediately, with the itemized per-device breakdown
   (`ResidencyBudgetError`, the round-10 refusal type);

 - *how many co-batch*?  Every campaign consumer scales linearly in B,
   so a solo class's batch capacity is `budget // per_sim_total`,
   clamped to the service's `batch_size`.  A 2D class accounts
   DEVICES x budget instead of one budget: with batch_shards
   devices on the batch axis, capacity is `batch_shards x (budget //
   per_device_block)` (then rounded to a batch_shards multiple so the
   mesh divides evenly).  No admitted batch's per-device
   residency can exceed the budget by construction (and the
   SweepRunner's own pre-compile fail-fast re-proves it).

Jobs that fit but not *now* wait in per-class FIFO queues under a
global `max_pending` bound — when the queue is full, `admit` raises
`QueueFullError` (backpressure: the caller must drain results before
submitting more).  `next_batch` serves the class whose HEAD job is
globally oldest, so no class starves behind a busier one (FIFO
fairness across classes, strict FIFO within one).
"""

from __future__ import annotations

import collections
import dataclasses

from graphite_tpu.serve.job import Job, config_digest


class QueueFullError(RuntimeError):
    """Backpressure: the pending queue is at `max_pending`."""


def _pow2_bucket(n: int, lo: int) -> int:
    """Smallest power of two >= max(n, lo)."""
    n = max(int(n), int(lo))
    return 1 << (n - 1).bit_length()


@dataclasses.dataclass
class Pending:
    """One queued job plus its service bookkeeping."""

    job: Job
    seq: int           # global submission order (FIFO fairness key)
    attempts: int = 0  # failed executions so far (split/retry budget)
    # observability (round 14): service-clock timestamp of the LAST
    # enqueue (submit or requeue — the queue-dwell histogram's start;
    # None until the service stamps it), and the dwell the most recent
    # batch-form measured from it
    enqueue_ts: "float | None" = None
    dwell_s: float = 0.0


@dataclasses.dataclass
class JobMeasure:
    """One class's probe measurements: the engine params, resolved
    ring specs, and the residency byte counts the layout planner and
    the class capacity arithmetic both consume.  The probe Simulator
    itself is dropped immediately (its state pytree is real device
    memory — retaining one per class forever would be exactly the
    residency the controller polices)."""

    params: object
    telemetry: object          # resolved TelemetrySpec | None
    profile: object            # resolved ProfileSpec | None
    hist: object               # resolved HistSpec | None
    pad_length: int
    per_sim_bytes: "dict[str, int]"    # whole-sim consumers (dt=1)
    state_replicated: int      # control state every tile shard holds
    state_tile_local: int      # big per-tile arrays (shard with dt)

    @property
    def per_sim_total(self) -> int:
        return sum(self.per_sim_bytes.values())

    def device_block(self, tile_shards: int = 1,
                     sims: int = 1) -> "dict[str, int]":
        """Itemized PER-DEVICE bill of `sims` sims' tile blocks under
        a `tile_shards`-way tile split — delegates to THE per-device
        arithmetic (`analysis/cost.device_residency_breakdown`) with
        the probe's retained byte counts, so the admission bill and
        the runner's fail-fast can never desynchronize."""
        from graphite_tpu.analysis.cost import device_residency_breakdown

        return device_residency_breakdown(
            state_split={"replicated": self.state_replicated,
                         "tile_local": self.state_tile_local},
            sims_per_shard=sims, tile_shards=tile_shards,
            per_sim_trace_bytes=self.per_sim_bytes["trace"],
            telemetry_spec=self.telemetry,
            profile_spec=self.profile,
            hist_spec=self.hist)


def measure_job(job: Job, *, mailbox_depth: int,
                pad_length: int) -> JobMeasure:
    """Build the probe Simulator exactly the way the batch runner will
    build its per-sim program (same config, same mailbox depth), read
    the byte counts, drop the probe."""
    from graphite_tpu.analysis.cost import trace_record_bytes, tree_bytes
    from graphite_tpu.engine.simulator import Simulator
    from graphite_tpu.parallel.mesh import shard_split_bytes

    probe = Simulator(job.resolved_config(), job.trace,
                      mailbox_depth=int(mailbox_depth),
                      barrier_host=False)
    params = probe.params
    telemetry = (job.telemetry.resolve(params)
                 if job.telemetry is not None else None)
    # the per-tile profile ring joins the admission bill the same way
    # (obs.ProfileSpec.ring_bytes — the one size model); its T factor
    # is what makes a dense big-tile profile pay its way through the
    # budget instead of OOMing a compiled batch
    profile = (job.profile.resolve(params)
               if job.profile is not None else None)
    # the int64 bucket ring joins the bill through the same size model
    # (obs.HistSpec.ring_bytes) — a dense per-tile recording pays its
    # way through the budget like the profile ring does
    hist = (job.hist.resolve(params)
            if job.hist is not None else None)
    per_sim = {
        "state": int(tree_bytes(probe.state)),
        "trace": (params.n_tiles * int(pad_length)
                  * trace_record_bytes(job.trace)),
    }
    if telemetry is not None:
        per_sim["telemetry"] = int(telemetry.ring_bytes())
    if profile is not None:
        per_sim["profile"] = int(profile.ring_bytes())
    if hist is not None:
        per_sim["hist"] = int(hist.ring_bytes())
    split = shard_split_bytes(probe.state)
    return JobMeasure(params=params, telemetry=telemetry,
                      profile=profile, hist=hist,
                      pad_length=int(pad_length),
                      per_sim_bytes=per_sim,
                      state_replicated=int(split["replicated"]),
                      state_tile_local=int(split["tile_local"]))


def plan_layout(measure: JobMeasure, *, hbm_budget_bytes: int,
                batch_size: int, n_devices: int) -> dict:
    """The class's device layout + batch capacity, from arithmetic the
    measure already holds.

    Solo (tag ('solo',)) when the budget is off or one sim fits one
    device: capacity = budget // per_sim (the round-13 rule).  When a
    sim alone exceeds the budget and devices exist, the smallest tile
    split whose per-device block fits wins (tag ('2d', db, dt)):
    batch_shards devices on the batch axis each run cap//db sims'
    blocks, so capacity accounts DEVICES x budget.  Tag ('never',)
    when even the maximal split exceeds the budget — the only
    remaining rejection."""
    budget = int(hbm_budget_bytes)
    batch_size = int(batch_size)
    n_dev = max(int(n_devices), 1)
    if not budget:
        return {"tag": ("solo",), "batch_shards": 1, "tile_shards": 1,
                "batch_cap": batch_size}
    if measure.per_sim_total <= budget:
        return {"tag": ("solo",), "batch_shards": 1, "tile_shards": 1,
                "batch_cap": min(batch_size,
                                 budget // max(measure.per_sim_total,
                                               1))}
    T = int(measure.params.n_tiles)
    best_bd = measure.device_block(1)
    # any tile divisor up to the device count is a candidate — dt need
    # not divide n_devices (the mesh simply uses db*dt of them; idle
    # devices beat a rejection), smallest split that fits wins
    for dt in range(2, n_dev + 1):
        if T % dt:
            continue
        bd = measure.device_block(dt)
        if bd["total"] < best_bd["total"]:
            best_bd = bd
        if bd["total"] > budget:
            continue
        cap_per_shard = budget // bd["total"]
        db = n_dev // dt
        cap = min(batch_size, db * cap_per_shard)
        if cap < 1:
            continue
        if cap < db:
            # fewer sims than batch shards: shrink the batch axis
            db = cap
        else:
            cap -= cap % db
        return {"tag": ("2d", db, dt), "batch_shards": db,
                "tile_shards": dt, "batch_cap": cap}
    return {"tag": ("never",), "batch_shards": 1, "tile_shards": 1,
            "batch_cap": 0, "best_breakdown": best_bd}


class JobClass:
    """One program class: jobs that provably share a compiled program.

    A probe Simulator is built once (never run) to read the engine
    params and the per-sim state bytes, then dropped; the class keeps
    the per-sim residency bill, the device layout + batch capacity the
    budget allows, and the class FIFO.
    """

    def __init__(self, key: tuple, job: Job, *, mailbox_depth: int,
                 pad_length: int, hbm_budget_bytes: int, batch_size: int,
                 n_devices: int = 1, measure: "JobMeasure | None" = None):
        self.key = key
        self.config = job.resolved_config()
        self.dvfs = job.dvfs
        self.mailbox_depth = int(mailbox_depth)
        self.pad_length = int(pad_length)
        self.fifo: "collections.deque[Pending]" = collections.deque()
        if measure is None:
            measure = measure_job(job, mailbox_depth=self.mailbox_depth,
                                  pad_length=self.pad_length)
        self.measure = measure
        self.params = measure.params
        self.telemetry = measure.telemetry
        self.profile = measure.profile
        self.hist = measure.hist
        self.per_sim_bytes = dict(measure.per_sim_bytes)
        self.per_sim_total = measure.per_sim_total
        plan = plan_layout(measure, hbm_budget_bytes=hbm_budget_bytes,
                           batch_size=batch_size, n_devices=n_devices)
        self.layout_tag = plan["tag"]
        self.batch_shards = int(plan["batch_shards"])
        self.tile_shards = int(plan["tile_shards"])
        self.batch_cap = int(plan["batch_cap"])
        self.best_breakdown = plan.get("best_breakdown")

    @property
    def n_tiles(self) -> int:
        return int(self.params.n_tiles)

    @property
    def sharded(self) -> bool:
        """True when this class runs under the 2D batch x tile mesh."""
        return self.tile_shards > 1

    def breakdown(self, batch: int = 1) -> "dict[str, int]":
        """The itemized residency bill for a `batch`-wide campaign of
        this class — consumer-for-consumer the dict
        `SweepRunner.residency_breakdown` computes for the real batch
        (every consumer scales linearly in B)."""
        out = {k: v * int(batch) for k, v in self.per_sim_bytes.items()}
        out["total"] = sum(out.values())
        return out

    def device_breakdown(self, batch: "int | None" = None
                         ) -> "dict[str, int]":
        """The itemized PER-DEVICE bill of a `batch`-wide campaign
        (default: the class capacity) under this class's layout — the
        bill the 2D admission proves <= hbm_budget_bytes."""
        batch = self.batch_cap if batch is None else int(batch)
        db = max(self.batch_shards, 1)
        sims = max((batch + db - 1) // db, 1) if batch else 0
        return self.measure.device_block(self.tile_shards, sims=sims)


class AdmissionController:
    """Classify, budget-check, and queue jobs; form FIFO-fair batches."""

    def __init__(self, *, hbm_budget_bytes: int = 0, batch_size: int = 4,
                 max_pending: int = 1024, n_devices: int = 1):
        if int(batch_size) < 1:
            raise ValueError("batch_size must be >= 1")
        self.hbm_budget_bytes = int(hbm_budget_bytes)
        self.batch_size = int(batch_size)
        self.max_pending = int(max_pending)
        # round 18: devices the service may spread a class over — a
        # per-sim bill above ONE device's budget bin-packs ACROSS them
        # (the 2D batch x tile layout) instead of bouncing.  Default 1
        # keeps the round-13 single-device admission bit-identically.
        self.n_devices = max(int(n_devices), 1)
        self.classes: "dict[tuple, JobClass]" = {}
        # probe measurements + layout plans memoized per BASE key (the
        # key minus its layout element): the layout axis is derived
        # from the measurement, and re-probing per submit would build a
        # device-state pytree per job
        self._measures: "dict[tuple, JobMeasure]" = {}
        # pre-formed batches (split/retry requeues) served before any
        # new batch forms — without this, a split's halves would simply
        # re-coalesce into the failing batch on the next pop
        self._ready: "collections.deque[tuple]" = collections.deque()
        self._seq = 0
        self._depth = 0

    @property
    def queue_depth(self) -> int:
        return self._depth

    def class_key(self, job: Job) -> tuple:
        """The program-class key: everything that changes the compiled
        artifact and is knowable without tracing.  Traced knobs are
        deliberately absent (they share the program — that is the whole
        round-7 point); the cache's fingerprint check is the proof the
        key was sufficient."""
        from graphite_tpu.engine.simulator import auto_mailbox_depth

        depth = _pow2_bucket(auto_mailbox_depth(job.trace), 2)
        length = _pow2_bucket(job.trace.length, 16)
        tel = job.telemetry
        # energy_prices is part of the key: the pJ prices fold into the
        # compiled step as literals, so two jobs differing only in
        # prices lower different programs and must never co-batch
        tel_key = None if tel is None else (
            int(tel.sample_interval_ps), int(tel.n_samples), tel.series,
            tel.energy_prices)
        prof = job.profile
        # the profile spec is part of the key for the same reason: the
        # [S, T, m] ring (and its series selection / prices) is baked
        # into the lowering, so differing specs never co-batch
        prof_key = None if prof is None else (
            int(prof.sample_interval_ps), int(prof.n_samples),
            prof.series, prof.energy_prices)
        # the runtime-DVFS spec splits classes the same way: a DvfsSpec
        # (frozen, hashable) bakes the carried-frequency reads and the
        # governor into the lowering; dvfs=None jobs keep the historical
        # program.  The per-point dvfs_domain_mhz knob is absent here on
        # purpose — points of one spec share the compiled program.
        hs = job.hist
        # the hist spec splits classes too: the int64 bucket ring (its
        # edges, source selection, per-tile switch and prices) is baked
        # into the lowering; hist=None jobs keep the historical program
        hist_key = None if hs is None else (
            hs.sources, hs.edges, int(hs.log2_buckets),
            bool(hs.per_tile), hs.energy_prices)
        base = (config_digest(job.resolved_config()), job.n_tiles,
                job.has_mem_trace(), depth, length, tel_key, prof_key,
                job.dvfs, hist_key)
        # round 18: the DEVICE LAYOUT axis.  A 2D batch x tile class
        # lowers a different program than a solo class (the shard_map
        # mesh, specs and exchange are part of the artifact), so the
        # layout tag joins the key and 1D/2D jobs never co-batch.  The
        # tag is derived from the probe measurement (memoized per base
        # key) + the controller's budget/device arithmetic.
        return base + (self._layout_tag(base, job, depth, length),)

    def _layout_tag(self, base: tuple, job: Job, mailbox_depth: int,
                    pad_length: int) -> tuple:
        measure = self._measures.get(base)
        if measure is None:
            measure = measure_job(job, mailbox_depth=mailbox_depth,
                                  pad_length=pad_length)
            self._measures[base] = measure
        return plan_layout(measure,
                           hbm_budget_bytes=self.hbm_budget_bytes,
                           batch_size=self.batch_size,
                           n_devices=self.n_devices)["tag"]

    def admit(self, job: Job) -> "tuple[JobClass, Pending]":
        """Queue `job` (validated by the caller) or refuse it.

        Raises `analysis.cost.ResidencyBudgetError` — with the itemized
        per-consumer breakdown attached as `.breakdown` — when the job
        can NEVER fit the per-device budget, and `QueueFullError` when
        the pending queue is at `max_pending` (backpressure)."""
        from graphite_tpu.analysis.cost import (
            ResidencyBudgetError, format_breakdown,
        )

        if self._depth >= self.max_pending:
            raise QueueFullError(
                f"pending queue is full ({self._depth} >= max_pending="
                f"{self.max_pending}) — drain results before submitting "
                "more")
        key = self.class_key(job)
        cls = self.classes.get(key)
        if cls is None:
            cls = JobClass(key, job,
                           mailbox_depth=key[3], pad_length=key[4],
                           hbm_budget_bytes=self.hbm_budget_bytes,
                           batch_size=self.batch_size,
                           n_devices=self.n_devices,
                           measure=self._measures.get(key[:-1]))
            self.classes[key] = cls
        if self.hbm_budget_bytes and cls.batch_cap < 1:
            bd = cls.breakdown(1)
            if self.n_devices > 1:
                best = cls.best_breakdown or bd
                extra = (
                    f" — at the best tile split the {self.n_devices} "
                    f"device(s) allow, one per-device block still costs "
                    + format_breakdown(best)
                    + "; shrink the trace/telemetry ring, raise the "
                    "budget, or add devices")
            else:
                extra = (
                    " — shrink the trace/telemetry ring, raise the "
                    "budget, or give the service devices to bin-pack "
                    "across (n_devices > 1 admits it under the 2D "
                    "batch x tile layout)")
            err = ResidencyBudgetError(
                f"job {job.job_id!r} can never fit hbm_budget_bytes="
                f"{self.hbm_budget_bytes}: one sim alone costs "
                + format_breakdown(bd) + extra)
            err.breakdown = bd
            raise err
        pending = Pending(job=job, seq=self._seq)
        self._seq += 1
        cls.fifo.append(pending)
        self._depth += 1
        return cls, pending

    def requeue_batch(self, cls: JobClass,
                      pendings: "list[Pending]") -> None:
        """Requeue a split half (or a lone retry) as a PRE-FORMED batch
        at the head of the ready line: it must re-run at its reduced
        size — returning the jobs to the class FIFO would let the next
        pop re-coalesce the exact batch that just failed.  The jobs
        were admitted once, so max_pending does not apply again
        (refusing here would drop accepted work)."""
        self._ready.appendleft((cls, list(pendings)))
        self._depth += len(pendings)

    def _oldest_waiting(self) -> "JobClass | None":
        """The class whose HEAD job is globally oldest (no class
        starves) — the ONE selector `peek_batch` reports and
        `next_batch` pops, so the two can never drift apart."""
        waiting = [c for c in self.classes.values() if c.fifo]
        if not waiting:
            return None
        return min(waiting, key=lambda c: c.fifo[0].seq)

    def peek_batch(self) -> "tuple[JobClass, int, Pending, bool] | None":
        """What `next_batch` WOULD pop, without popping: (class, batch
        size, head job, preformed) or None on an idle queue.  The
        service's latency-aware dwell policy reads this to decide
        whether an under-full batch should wait for more arrivals;
        `preformed` marks a requeued split/retry batch, which must
        never wait (its jobs are the globally oldest)."""
        if self._ready:
            cls, batch = self._ready[0]
            return cls, len(batch), batch[0], True
        cls = self._oldest_waiting()
        if cls is None:
            return None
        return (cls, min(len(cls.fifo), cls.batch_cap), cls.fifo[0],
                False)

    def full_class(self) -> "JobClass | None":
        """A class whose queue can ALREADY fill a batch (oldest head
        among them), or None.  The dwell policy runs a full class
        while the globally-oldest under-full head keeps aging — a full
        batch gains nothing by waiting."""
        full = [c for c in self.classes.values()
                if len(c.fifo) >= c.batch_cap]
        if not full:
            return None
        return min(full, key=lambda c: c.fifo[0].seq)

    def next_batch(self, from_cls: "JobClass | None" = None
                   ) -> "tuple[JobClass, list[Pending]] | None":
        """Pop the next batch: requeued (split/retry) batches first —
        they hold the globally oldest jobs — then the class whose HEAD
        job is globally oldest (no class starves), up to the class's
        budget-derived batch capacity, strict FIFO within the class.
        `from_cls` pops from a specific class instead (the dwell
        policy's run-the-full-class-now path); requeued batches still
        outrank it."""
        if self._ready:
            cls, batch = self._ready.popleft()
            self._depth -= len(batch)
            return cls, batch
        cls = from_cls if from_cls is not None else self._oldest_waiting()
        if cls is None:
            return None
        batch = []
        while cls.fifo and len(batch) < cls.batch_cap:
            batch.append(cls.fifo.popleft())
        if not batch:
            return None
        self._depth -= len(batch)
        return cls, batch
