"""The campaign service: admission-controlled job batching over a
fingerprint-keyed compiled-program cache.

This is the piece that *serves* every amortization primitive the repo
already has: jobs (`serve/job.py`) are validated up front, bin-packed
into same-program batches by the admission controller
(`serve/admission.py` — `residency_breakdown` arithmetic against a
per-device `hbm_budget_bytes`), executed as vmapped `SweepRunner`
campaigns through the LRU compiled-program cache (`serve/cache.py` —
keyed by program class, proven by `analysis/identity` fingerprints
resolved through an `analysis/registry`-style record set), and demuxed
back into per-job `SimResults` + telemetry envelopes as each batch
completes.

Graceful degradation is structural, not best-effort:

 - a job that can never fit the budget is rejected at submit with the
   itemized breakdown; a full queue raises backpressure;
 - batches are padded to the class's FIXED capacity (replicating the
   first job — semantically a re-run, so padding adds no new failure
   modes) so every batch of a class reuses ONE compiled shape; the
   padded tail is masked out of the result stream;
 - a failed batch (deadlock, mailbox overflow, max_quanta timeout)
   SPLITS in half and re-enqueues at the front of its class FIFO —
   halving isolates the offending job in log2(B) steps instead of
   poisoning the queue; a job that fails ALONE is retried up to
   `max_attempts` and then reported as a failed envelope.  Every
   failure increments each member's attempt counter, so the
   split/retry recursion provably terminates.

The bit-exact sequential path (`Simulator.run`) remains the equivalence
oracle: `tools/regress.py --smoke`'s serve rung replays a mixed-
geometry job set both ways and requires identical results + telemetry.

Observability (round 14) is built in, not bolted on: every rate the
service reports is ONE instrument in an `obs.MetricsRegistry` (queue
dwell, admission/batch-form/execute latency, compile time, split depth
and batch occupancy are fixed-bucket histograms; the accounting
identities are counters), `counters` is a compatibility view over that
registry, and — when constructed with `tracing=` — every job gets a
lifecycle span trace (submit → validate → admit/reject → queue dwell →
execute → emit/failed) and every batch an execution span carrying the
class, capacity, occupancy, cache hit, compile time and residency.
Both ride an injectable monotonic clock (`clock=`) so tests pin exact
latencies; neither ever touches a traced program, so serve results are
bit-equal with tracing on or off (regress rung 9).
"""

from __future__ import annotations

import contextlib
import dataclasses
import time

from graphite_tpu.obs.metrics import (
    DEFAULT_COUNT_BUCKETS, MetricsRegistry, RATIO_BUCKETS,
)
from graphite_tpu.obs.trace import Tracer
from graphite_tpu.serve.admission import AdmissionController, JobClass, \
    Pending, QueueFullError
from graphite_tpu.serve.cache import CacheEntry, ProgramCache, \
    ProgramCacheError
from graphite_tpu.serve.job import (
    Job, JobResult, STATUS_FAILED, STATUS_OK,
)


@dataclasses.dataclass
class BatchReport:
    """One executed (or failed) batch's bookkeeping row."""

    batch_id: int
    class_name: str
    n_tiles: int
    job_ids: "list[str]"
    n_jobs: int                # real jobs (pre-padding)
    batch_cap: int             # the padded B the program ran at
    occupancy: float           # n_jobs / batch_cap
    residency_total: int       # the admitted layout's residency bill
    cache_hit: bool
    ok: bool
    wall_s: float
    error: "str | None" = None
    # round 17: the in-memory miss was served by the persistent AOT
    # program store (deserialize, not compile)
    store_hit: bool = False
    # round 18: the device layout the batch ran under ("solo",
    # "1d-batch(d=N)", "2d(b=DB,t=DT)", ...)
    layout: str = "solo"


class CampaignService:
    """Persistent front end: submit jobs, drain result envelopes.

    `hbm_budget_bytes`: per-device admission budget (0 = off);
    `batch_size`: max sims per campaign batch (the class capacity is
    `min(batch_size, budget // per_sim_bytes)`); `n_devices` (round
    18): devices admission may bin-pack a too-big-for-one-device sim
    across — such jobs are served under the 2D batch x tile mesh
    layout (per-device tile blocks proven <= the budget) instead of
    rejected; "auto" reads the visible device count, the default 1
    keeps round-13 admission exactly; `cache_bytes`: program
    cache budget for byte-accounted LRU eviction (0 = unbounded);
    `max_pending`: queue depth before submit raises backpressure;
    `max_attempts`: per-job failure budget across splits/retries;
    `max_quanta`: the batch programs' quantum bound (part of the
    compiled program, hence of the cache key); `verify_hits`: re-lower
    every cache hit and re-prove fingerprint equality (a retrace, never
    a recompile — the belt-and-braces mode the regress rung runs);
    `validate`: run `trace/validate.py` on every submitted trace;
    `max_history`: newest result envelopes / batch reports retained on
    the service (`results` / `batch_log`) — streaming consumers use
    `drain()`; counters stay exact regardless.

    `store` (round 17): a `store.ProgramStore` (or a directory path)
    layered UNDER the in-memory cache as its miss/fill backend — an
    in-memory miss deserializes the fingerprint-keyed on-disk
    executable instead of compiling (store hit: retrace + deserialize,
    zero compiles), and a fresh compile is serialized back (store
    fill), so a fleet of processes sharing one store dir compiles each
    program class once per FLEET.  `warm_start()` pre-deserializes
    compatible entries at startup.  `max_dwell_s` (round 17): let an
    under-full batch wait up to this long for its class to fill before
    forming — the latency/occupancy dial the round-14
    `queue_dwell_seconds` histogram measures; 0 (default) keeps the
    wait-for-nothing scheduler bit-identically.

    Observability: `metrics` (an `obs.MetricsRegistry`) is always live
    — it IS the service bookkeeping, not a copy of it; `tracing=True`
    (or a caller-owned `obs.Tracer`) records job-lifecycle + batch
    spans, exported via `export_spans()` / `tools/serve.py
    --trace-out`; `clock` injects the monotonic time source both read
    (default `time.monotonic` — tests pass a fake clock and get exact
    dwell/latency histograms).
    """

    def __init__(self, *, hbm_budget_bytes: int = 0, batch_size: int = 4,
                 cache_bytes: int = 0, max_pending: int = 1024,
                 max_attempts: int = 3, max_quanta: int = 1_000_000,
                 verify_hits: bool = False, validate: bool = True,
                 shard_batch: "bool | None" = False,
                 n_devices: "int | str" = 1,
                 max_history: int = 4096,
                 tracing: "bool | Tracer" = False,
                 clock=None,
                 store: "object | str | None" = None,
                 max_dwell_s: float = 0.0):
        import collections

        # round 18: devices the admission controller may bin-pack a
        # too-big-for-one-device sim across (the 2D batch x tile
        # layout).  "auto" reads the visible device count; the default
        # 1 keeps round-13 single-device admission bit-identically.
        import jax

        if n_devices == "auto":
            n_devices = len(jax.devices())
        self.n_devices = max(int(n_devices), 1)
        if self.n_devices > len(jax.devices()):
            # fail at construction, not mid-drain: a 2D class planned
            # for more devices than exist would otherwise crash the
            # serve loop at execute (mesh construction), stranding
            # admitted jobs without terminal envelopes
            raise ValueError(
                f"n_devices={self.n_devices} exceeds the "
                f"{len(jax.devices())} visible device(s) — the service "
                "executes locally; force more host devices with "
                "XLA_FLAGS=--xla_force_host_platform_device_count=N "
                "on CPU, or pass 'auto'")
        self.admission = AdmissionController(
            hbm_budget_bytes=hbm_budget_bytes, batch_size=batch_size,
            max_pending=max_pending, n_devices=self.n_devices)
        self.cache = ProgramCache(cache_bytes)
        self.registry: "dict[str, object]" = {}   # name -> ProgramRecord
        self.hbm_budget_bytes = int(hbm_budget_bytes)
        self.max_attempts = int(max_attempts)
        self.max_quanta = int(max_quanta)
        self.verify_hits = bool(verify_hits)
        self.validate = bool(validate)
        self.shard_batch = shard_batch
        if isinstance(tracing, Tracer):
            # ONE timebase: reconstructed spans (queue dwell, execute)
            # are recorded with service-clock timestamps, so a caller-
            # owned tracer must share it.  An explicit `clock=` is
            # adopted by both; otherwise the service adopts the
            # tracer's clock.
            self.tracer: "Tracer | None" = tracing
            if clock is not None:
                self._clock = clock
                tracing.clock = clock
            else:
                self._clock = tracing.clock
        else:
            self._clock = clock if clock is not None else time.monotonic
            self.tracer = Tracer(clock=self._clock) if tracing else None
        # retention is BOUNDED (`max_history` newest entries): envelopes
        # stream out through drain(); keeping every SimResults +
        # BatchReport forever would grow a persistent service without
        # bound.  Counters stay exact over all time (the registry's
        # instruments are running sums, and the metrics timeline /
        # tracer spans are bounded deques of their own).
        self.batch_log: "collections.deque[BatchReport]" = \
            collections.deque(maxlen=int(max_history))
        self._completed: "collections.deque[JobResult]" = \
            collections.deque(maxlen=int(max_history))
        self._next_batch_id = 0
        self._last_residency = 0
        self._last_cache_hit = False
        self._last_compile_s = 0.0
        self._last_layout = "solo"
        # persistent AOT program store (round 17): the in-memory
        # cache's miss/fill backend — a fleet of service processes
        # sharing one store dir compiles each class once per FLEET
        if isinstance(store, str):
            from graphite_tpu.store import ProgramStore

            store = ProgramStore(store)
        self.store = store
        # fingerprint-keyed staging area `warm_start()` fills from
        # disk: (fingerprint, B) -> (executable, manifest, deserialize_s)
        self._warm: dict = {}
        self._last_store_hit = False
        self._last_deserialize_s = 0.0
        # latency-aware batching: an under-full batch may wait up to
        # `max_dwell_s` for the class to fill before forming (0 = the
        # round-13 wait-for-nothing scheduler, bit-identically);
        # `_dwell_wait_s` reports the remaining wait after a step that
        # chose to hold
        self.max_dwell_s = float(max_dwell_s)
        self._dwell_wait_s = 0.0
        self.metrics = MetricsRegistry(clock=self._clock,
                                       max_timeline=int(max_history))
        self._init_metrics()

    def _init_metrics(self) -> None:
        """Register every instrument up front (one definition of each
        rate; the exposition shows zeros instead of omitting series)."""
        m = self.metrics
        self._m = {
            "submitted": m.counter(
                "jobs_submitted_total", "jobs accepted into the queue"),
            "completed": m.counter(
                "jobs_completed_total", "ok envelopes emitted"),
            "failed": m.counter(
                "jobs_failed_total", "failed envelopes emitted"),
            "rejected": m.counter(
                "jobs_rejected_total", "jobs refused at submit"),
            "backpressure": m.counter(
                "backpressure_total", "submits refused by a full queue"),
            "batches": m.counter("batches_total", "batches executed"),
            "splits": m.counter(
                "splits_total", "failed batches split in half"),
            "retries": m.counter(
                "retries_total", "batch/job re-executions"),
            "cache_hits": m.counter(
                "cache_hits_total", "program-cache hits"),
            "compiles": m.counter(
                "compiles_total", "program-cache miss compiles"),
            "execute_wall": m.counter(
                "execute_wall_seconds", "wall seconds inside batch "
                "execution (jobs_per_s denominator)"),
            "store_hits": m.counter(
                "store_hits_total", "program-store hits (executable "
                "deserialized instead of compiled)"),
            "store_misses": m.counter(
                "store_misses_total", "program-store misses (store "
                "attached, fresh compile paid)"),
            "store_fills": m.counter(
                "store_fills_total", "executables serialized into the "
                "program store"),
            "store_fill_errors": m.counter(
                "store_fill_errors_total", "store writes that failed "
                "(disk/serialization; the batch still served)"),
            "store_integrity": m.counter(
                "store_integrity_total", "store entries quarantined at "
                "load (checksum/truncation/version/fingerprint/"
                "deserialize)"),
        }
        self._g = {
            "queue_depth": m.gauge("queue_depth", "pending jobs"),
            "cache_entries": m.gauge("cache_entries",
                                     "compiled programs cached"),
            "cache_bytes": m.gauge("cache_bytes",
                                   "program-cache residency bytes"),
        }
        self._h = {
            "admission": m.histogram(
                "admission_seconds",
                "submit latency (validate + classify + enqueue)"),
            "dwell": m.histogram(
                "queue_dwell_seconds",
                "enqueue to batch-form wait per job"),
            "batch_form": m.histogram(
                "batch_form_seconds", "queue pop + batch assembly"),
            "execute": m.histogram(
                "execute_seconds", "batch execution wall time"),
            "compile": m.histogram(
                "compile_seconds", "program lower+compile on cache miss"),
            "occupancy": m.histogram(
                "batch_occupancy", "real jobs / batch capacity",
                buckets=RATIO_BUCKETS),
            "split_depth": m.histogram(
                "split_depth", "attempts consumed per terminal job",
                buckets=DEFAULT_COUNT_BUCKETS),
            "store_deserialize": m.histogram(
                "store_deserialize_seconds",
                "store-hit payload load+deserialize time"),
            "store_fill": m.histogram(
                "store_fill_seconds",
                "store-miss serialize+write time"),
        }

    def _span(self, trace_id: str, name: str, **attrs):
        if self.tracer is None:
            return contextlib.nullcontext(None)
        return self.tracer.span(trace_id, name, **attrs)

    def export_spans(self, path_or_file) -> int:
        """Write the retained spans as JSON-lines (the `--trace-out`
        artifact); returns the span count, 0 when tracing is off."""
        if self.tracer is None:
            return 0
        return self.tracer.export_jsonl(path_or_file)

    # -- submission ------------------------------------------------------

    def submit(self, job: Job) -> int:
        """Validate and queue one job; returns its submission sequence
        number.  Raises `TraceValidationError`/`ValueError` on a
        malformed job, `analysis.cost.ResidencyBudgetError` (with
        `.breakdown`) on a job that can never fit, `QueueFullError`
        under backpressure."""
        t0 = self._clock()
        jid = job.job_id
        try:
            with self._span(jid, "submit"):
                with self._span(jid, "validate"):
                    job.validate(validate_trace=self.validate)
                with self._span(jid, "admit"):
                    cls, pending = self.admission.admit(job)
        except QueueFullError:
            # backpressure is NOT a rejection: the job is fine, the
            # queue is full — the caller drains and resubmits, and the
            # later successful submit must keep the accounting identity
            # submitted == completed + failed (+ rejected never counts
            # a job that eventually ran)
            self._m["backpressure"].inc()
            if self.tracer is not None:
                self.tracer.event(jid, "backpressure")
            raise
        except Exception as e:
            self._m["rejected"].inc()
            if self.tracer is not None:
                # terminal span: a rejected job's lifecycle ends here
                self.tracer.event(
                    jid, "reject", error=f"{type(e).__name__}: {e}")
            raise
        now = self._clock()
        self._h["admission"].observe(now - t0)
        pending.enqueue_ts = now
        self._m["submitted"].inc()
        self._g["queue_depth"].set(self.admission.queue_depth)
        return pending.seq

    @property
    def queue_depth(self) -> int:
        return self.admission.queue_depth

    # -- scheduling ------------------------------------------------------

    def step(self, *, force: bool = False) -> "list[JobResult]":
        """Form and run ONE batch (the oldest-head class); returns the
        envelopes it completed (empty when a failed batch split and
        re-enqueued, when the queue is idle, or when the dwell policy
        chose to wait).

        With `max_dwell_s > 0` an UNDER-FULL batch holds until its
        head job has dwelled `max_dwell_s` (trading latency for
        occupancy the way inference servers do — the trade the
        round-14 `queue_dwell_seconds` x `batch_occupancy` instruments
        measure); a full batch, or a requeued split/retry batch, never
        waits.  `force=True` overrides the hold (the drain-to-idle
        paths use it so a waiting scheduler cannot spin)."""
        t0 = self._clock()
        self._dwell_wait_s = 0.0
        from_cls = None
        if self.max_dwell_s > 0 and not force:
            peek = self.admission.peek_batch()
            if peek is not None:
                cls, n, head, preformed = peek
                if (not preformed and n < cls.batch_cap
                        and head.enqueue_ts is not None):
                    dwelled = t0 - head.enqueue_ts
                    if dwelled < self.max_dwell_s:
                        # the oldest head is held — but a FULL batch of
                        # another class never waits: run it now, the
                        # held head keeps aging for free
                        from_cls = self.admission.full_class()
                        if from_cls is None:
                            self._dwell_wait_s = \
                                self.max_dwell_s - dwelled
                            return []
        nxt = self.admission.next_batch(from_cls)
        if nxt is None:
            return []
        cls, pendings = nxt
        self._h["batch_form"].observe(self._clock() - t0)
        return self._run_batch(cls, pendings)

    def drain(self, *, force: bool = False):
        """Generator: run batches until the queue is idle, yielding
        result envelopes as each batch completes (the streaming read
        the CLI prints line-by-line).  Dwell-aware: a held under-full
        batch sleeps out its window on the real clock; under an
        injected clock that does not advance on its own, the batch is
        forced instead — drain always terminates.  `force=True` skips
        every dwell hold outright: when the caller KNOWS no new job
        can arrive (input exhausted, shutdown), waiting buys nothing
        but latency."""
        while self.admission.queue_depth:
            got = False
            for res in self.step(force=force):
                got = True
                yield res
            if got or not self._dwell_wait_s:
                continue
            # sleep a slice of the window (never a busy spin), then
            # check whether the clock moved: any real clock
            # (monotonic/time/perf_counter) or auto-advancing test
            # clock ages the held head on its own and the loop simply
            # re-steps; a FROZEN injected clock can never age it past
            # the dwell window, so the batch is forced instead of
            # spinning forever
            before = self._clock()
            time.sleep(min(self._dwell_wait_s, 0.02))
            if self._clock() == before:
                for res in self.step(force=True):
                    yield res

    def run_all(self) -> "list[JobResult]":
        # synchronous: nothing can arrive while we run, so a dwell
        # hold could only add latency — force past it
        return list(self.drain(force=True))

    @property
    def results(self) -> "list[JobResult]":
        """Every envelope completed so far (streaming callers use
        `drain()` instead)."""
        return list(self._completed)

    # -- batch execution -------------------------------------------------

    def _run_batch(self, cls: JobClass,
                   pendings: "list[Pending]") -> "list[JobResult]":
        from graphite_tpu.engine.simulator import (
            DeadlockError, MailboxOverflowError,
        )

        self._m["batches"].inc()
        batch_id = self._next_batch_id
        self._next_batch_id += 1
        btid = f"batch-{batch_id}"
        t0 = self._clock()
        # queue dwell ends when the batch forms: one histogram
        # observation per member, one reconstructed `queue` span per
        # job (requeued members' clocks restarted at requeue time, so
        # a split's second wait is a second observation, not a longer
        # first one)
        for p in pendings:
            if p.enqueue_ts is not None:
                p.dwell_s = t0 - p.enqueue_ts
                self._h["dwell"].observe(p.dwell_s)
                if self.tracer is not None:
                    self.tracer.record(p.job.job_id, "queue",
                                       p.enqueue_ts, t0, batch=batch_id)
        try:
            results = self._execute(cls, pendings, batch_id)
        except ProgramCacheError as e:
            # identity failures are NOT load: retrying cannot make a
            # mismatched artifact provable — surface them.  The popped
            # jobs still get failed envelopes first, so the accounting
            # (submitted == completed + failed + rejected) survives the
            # raise and no admitted work silently vanishes
            for p in pendings:
                p.attempts += 1
                self._completed.append(JobResult(
                    job_id=p.job.job_id, status=STATUS_FAILED,
                    error=f"ProgramCacheError: {e}", batch_id=batch_id,
                    attempts=p.attempts, seed=p.job.seed))
                self._m["failed"].inc()
                self._h["split_depth"].observe(p.attempts)
                if self.tracer is not None:
                    self.tracer.event(
                        p.job.job_id, "failed", batch=batch_id,
                        attempts=p.attempts,
                        error=f"ProgramCacheError: {e}")
            raise
        except (DeadlockError, MailboxOverflowError, RuntimeError) as e:
            wall = self._clock() - t0
            self._finish_batch_metrics(wall)
            return self._handle_failure(cls, pendings, batch_id, e,
                                        t0, wall)
        wall = self._clock() - t0
        self._finish_batch_metrics(wall)
        occupancy = len(pendings) / cls.batch_cap
        self._h["occupancy"].observe(occupancy)
        self.batch_log.append(BatchReport(
            batch_id=batch_id, class_name=self._class_name(cls),
            n_tiles=cls.n_tiles,
            job_ids=[p.job.job_id for p in pendings],
            n_jobs=len(pendings), batch_cap=cls.batch_cap,
            occupancy=occupancy,
            residency_total=self._last_residency,
            cache_hit=self._last_cache_hit,
            store_hit=self._last_store_hit, ok=True, wall_s=wall,
            layout=self._last_layout))
        if self.tracer is not None:
            self.tracer.record(
                btid, "batch", t0, t0 + wall,
                **self._batch_attrs(cls, pendings, ok=True))
            for p, res in zip(pendings, results):
                # terminal emit span; `telemetry_samples` references
                # the demuxed device timeline riding the envelope
                attrs = {"batch": batch_id, "attempts": res.attempts}
                if res.telemetry is not None:
                    attrs["telemetry_samples"] = len(res.telemetry)
                if res.profile is not None:
                    # the emit span links to the per-tile profile the
                    # way it links to the scalar timeline
                    attrs["profile_samples"] = len(res.profile)
                if res.hist is not None:
                    attrs["hist_events"] = int(sum(
                        res.hist.total(s) for s in res.hist.sources))
                self.tracer.event(p.job.job_id, "emit", **attrs)
        for p, res in zip(pendings, results):
            self._h["split_depth"].observe(res.attempts)
            if self.tracer is not None:
                res.timings = {"queue_dwell_s": round(p.dwell_s, 6),
                               "batch_execute_s": round(wall, 6)}
        self._completed.extend(results)
        self._m["completed"].inc(len(results))
        return results

    def _finish_batch_metrics(self, wall: float) -> None:
        self._m["execute_wall"].inc(wall)
        self._h["execute"].observe(wall)
        self._g["queue_depth"].set(self.admission.queue_depth)
        self._g["cache_entries"].set(len(self.cache))
        self._g["cache_bytes"].set(self.cache.total_bytes)
        # one periodic metrics-timeline row per executed batch — the
        # time series tools/report.py --metrics renders
        self.metrics.sample()

    def _batch_attrs(self, cls: JobClass, pendings, *, ok: bool,
                     error: "str | None" = None) -> dict:
        attrs = {
            "class": self._class_name(cls),
            "n_tiles": cls.n_tiles,
            "capacity": cls.batch_cap,
            "n_jobs": len(pendings),
            "occupancy": round(len(pendings) / cls.batch_cap, 6),
            "cache_hit": self._last_cache_hit,
            "store_hit": self._last_store_hit,
            "compile_s": round(self._last_compile_s, 6),
            "deserialize_s": round(self._last_deserialize_s, 6),
            "residency_bytes": self._last_residency,
            "layout": self._last_layout,
            "jobs": [p.job.job_id for p in pendings],
            "ok": ok,
        }
        if error is not None:
            attrs["error"] = error
        return attrs

    def _handle_failure(self, cls, pendings, batch_id, exc, t0, wall
                        ) -> "list[JobResult]":
        """Split-and-requeue (n > 1) or retry/fail (n == 1); every
        member's attempt counter moves, so the recursion terminates."""
        msg = f"{type(exc).__name__}: {exc}"
        self.batch_log.append(BatchReport(
            batch_id=batch_id, class_name=self._class_name(cls),
            n_tiles=cls.n_tiles,
            job_ids=[p.job.job_id for p in pendings],
            n_jobs=len(pendings), batch_cap=cls.batch_cap,
            occupancy=len(pendings) / cls.batch_cap,
            residency_total=self._last_residency,
            cache_hit=self._last_cache_hit,
            store_hit=self._last_store_hit,
            ok=False, wall_s=wall, error=msg,
            layout=self._last_layout))
        if self.tracer is not None:
            # the span covers the REAL execute window (t0, t0+wall) —
            # clock reads after it (metrics sampling) must not shift it
            self.tracer.record(
                f"batch-{batch_id}", "batch", t0, t0 + wall,
                **self._batch_attrs(cls, pendings, ok=False, error=msg))
        now = self._clock()
        for p in pendings:
            p.attempts += 1
            # a requeued member's dwell clock restarts: its second wait
            # is a second histogram observation, not a longer first one
            p.enqueue_ts = now
        if len(pendings) > 1:
            # halving isolates the offender in ~log2(B) steps; the
            # halves requeue as PRE-FORMED batches (head of the ready
            # line, first half first) so they re-run at their reduced
            # size — and still pad to the class capacity, so every
            # retry is a cache hit on the one compiled program
            mid = (len(pendings) + 1) // 2
            self.admission.requeue_batch(cls, pendings[mid:])
            self.admission.requeue_batch(cls, pendings[:mid])
            self._m["splits"].inc()
            self._m["retries"].inc()
            if self.tracer is not None:
                for p in pendings:
                    self.tracer.event(p.job.job_id, "split",
                                      batch=batch_id, error=msg)
            return []
        p = pendings[0]
        if p.attempts >= self.max_attempts:
            res = JobResult(job_id=p.job.job_id, status=STATUS_FAILED,
                            error=msg, batch_id=batch_id,
                            attempts=p.attempts, seed=p.job.seed)
            self._completed.append(res)
            self._m["failed"].inc()
            self._h["split_depth"].observe(p.attempts)
            if self.tracer is not None:
                self.tracer.event(p.job.job_id, "failed",
                                  batch=batch_id, attempts=p.attempts,
                                  error=msg)
            return [res]
        self.admission.requeue_batch(cls, [p])
        self._m["retries"].inc()
        if self.tracer is not None:
            self.tracer.event(p.job.job_id, "retry", batch=batch_id,
                              attempts=p.attempts, error=msg)
        return []

    def _class_name(self, cls: JobClass) -> str:
        import hashlib

        digest = cls.key[0][:8]
        tel = "-tel" if cls.telemetry is not None else ""
        tel += "-prof" if cls.profile is not None else ""
        tel += "-dvfs" if getattr(cls, "dvfs", None) is not None else ""
        tel += "-hist" if getattr(cls, "hist", None) is not None else ""
        # round 18: 2D classes carry their mesh in the name — the
        # layout tag is in the key (injective hash below), but a
        # readable "-2d2x2" names the program a human greps for
        mesh = (f"-2d{cls.batch_shards}x{cls.tile_shards}"
                if getattr(cls, "tile_shards", 1) > 1 else "")
        # the key hash keeps the name INJECTIVE over class keys: the
        # readable fields alone miss key components (mem-ness,
        # telemetry spec details), and two distinct classes colliding
        # on one registry name would read as an identity violation
        khash = hashlib.sha256(repr(cls.key).encode()).hexdigest()[:8]
        return (f"serve-{digest}-t{cls.n_tiles}-b{cls.batch_cap}"
                f"-l{cls.pad_length}-d{cls.mailbox_depth}{tel}{mesh}"
                f"-k{khash}")

    def _execute(self, cls: JobClass, pendings: "list[Pending]",
                 batch_id: int) -> "list[JobResult]":
        """Pack, cache-resolve, run, and demux one batch.  Raises the
        engine's own failure types on a bad batch — `_run_batch` owns
        the split/retry policy."""
        from graphite_tpu.sweep.pack import pack_traces
        from graphite_tpu.sweep.runner import SweepRunner

        jobs = [p.job for p in pendings]
        n, B = len(jobs), cls.batch_cap
        btid = f"batch-{batch_id}"
        # per-batch stats reset FIRST: a failure before they are
        # recomputed must not report the previous batch's numbers
        self._last_residency = 0
        self._last_cache_hit = False
        self._last_compile_s = 0.0
        self._last_store_hit = False
        self._last_deserialize_s = 0.0
        self._last_layout = "solo"
        # pad to the class's FIXED capacity with replicas of job 0 so
        # every batch of this class shares one [B, T, L] program shape;
        # the replicas' rows are dropped below (the tail mask)
        traces = [j.trace for j in jobs] + [jobs[0].trace] * (B - n)
        points = [dict(j.knobs) for j in jobs] \
            + [dict(jobs[0].knobs)] * (B - n)
        if getattr(cls, "dvfs", None) is not None:
            from graphite_tpu.sweep.knobs import DVFS_KNOB_FIELD

            if any(DVFS_KNOB_FIELD in p for p in points):
                # jobs of one DVFS class co-batch whether or not they
                # sweep the operating point; absent points run at the
                # config's default domain frequencies
                default = tuple(int(f)
                                for f in cls.params.dvfs.domain_freq_mhz)
                for p in points:
                    p.setdefault(DVFS_KNOB_FIELD, default)
        pack = pack_traces(traces, validate=False,
                           pad_length=cls.pad_length)
        # the budget is passed as an INT always: 0 explicitly disables
        # the runner's fail-fast (None would fall back to the config's
        # own `[general] hbm_budget_bytes`, refusing batches the
        # service-level admission never checked against)
        # round 18: a 2D class runs the Mesh(('batch','tile')) program
        # its admission plan sized — the layout is part of the class
        # key, so every batch of the class lowers the same artifact
        if getattr(cls, "tile_shards", 1) > 1:
            layout_kw = {"layout": (cls.batch_shards, cls.tile_shards)}
        else:
            layout_kw = {"shard_batch": self.shard_batch}
        runner = SweepRunner(
            cls.config, pack, points,
            mailbox_depth=cls.mailbox_depth,
            hbm_budget_bytes=self.hbm_budget_bytes,
            telemetry=cls.telemetry,
            profile=cls.profile, dvfs=cls.dvfs,
            hist=getattr(cls, "hist", None), **layout_kw)
        self._last_layout = runner.layout_name
        self._last_residency = int(
            runner.residency_breakdown()["total"])
        # the budget is PER DEVICE: a 2D batch's whole-campaign bill
        # legitimately exceeds it — its per-device tile blocks may not
        admitted = (int(runner.device_breakdown()["total"])
                    if getattr(cls, "tile_shards", 1) > 1
                    else self._last_residency)
        if self.hbm_budget_bytes \
                and admitted > self.hbm_budget_bytes:
            # unreachable by construction (admission sized batch_cap
            # from the same arithmetic and the runner's own fail-fast
            # already re-checked) — a trip here is a real bug, not load
            raise AssertionError(
                f"admitted batch per-device residency {admitted} "
                f"exceeds hbm_budget_bytes={self.hbm_budget_bytes}")
        with self._span(btid, "cache") as cspan:
            entry = self._resolve_program(cls, runner, B)
            if cspan is not None:
                cspan.attrs.update(hit=self._last_cache_hit,
                                   compile_s=round(
                                       self._last_compile_s, 6),
                                   store_hit=self._last_store_hit,
                                   deserialize_s=round(
                                       self._last_deserialize_s, 6))
        t_exec = self._clock()
        out = runner.run(max_quanta=self.max_quanta)
        t_done = self._clock()
        if self.tracer is not None:
            # one batch-trace execute span + one per member, so a job
            # trace alone carries its full host timeline
            self.tracer.record(btid, "execute", t_exec, t_done,
                               cache_hit=self._last_cache_hit)
            for p in pendings:
                self.tracer.record(p.job.job_id, "execute",
                                   t_exec, t_done, batch=batch_id)
        with self._span(btid, "demux"):
            results = []
            for b in range(n):  # the padded tail [n:B] never leaves here
                p = pendings[b]
                tl = None if out.timelines is None else out.timelines[b]
                pf = None if out.profiles is None else out.profiles[b]
                hf = (None if getattr(out, "hists", None) is None
                      else out.hists[b])
                results.append(JobResult(
                    job_id=p.job.job_id, status=STATUS_OK,
                    results=out.results[b], telemetry=tl, profile=pf,
                    hist=hf,
                    batch_id=batch_id, attempts=p.attempts + 1,
                    seed=p.job.seed, knob_point=dict(p.job.knobs),
                    n_quanta=int(out.n_quanta[b]),
                    n_iterations=int(out.n_iterations[b])))
        return results

    # -- program cache ---------------------------------------------------

    def _resolve_program(self, cls: JobClass, runner, B: int
                         ) -> CacheEntry:
        """Serve the batch through the compiled-program cache.

        MISS: lower the campaign, fingerprint it
        (`analysis/identity.fingerprint`), resolve the name through the
        service registry (a registry-mismatched fingerprint at insert
        time errors LOUDLY — `ProgramCacheError`), register + insert,
        and hand the runner its own fresh jit (the one compile).
        HIT: resolve the stored record through the registry, optionally
        re-lower and re-prove fingerprint equality (`verify_hits` — a
        retrace, never a recompile), and inject the cached jitted
        callable into the fresh runner, so the batch executes the
        PROVABLY-same compiled artifact with zero new compiles."""
        from graphite_tpu.analysis.identity import fingerprint
        from graphite_tpu.analysis.registry import ProgramRecord

        name = self._class_name(cls)
        key = cls.key + (B, self.max_quanta)
        shape_sig = (B, cls.n_tiles, cls.pad_length)
        entry = self.cache.get(key, shape_sig)
        if entry is not None:
            reg = self.registry.get(entry.name)
            if reg is None or reg.fingerprint != entry.record.fingerprint:
                raise ProgramCacheError(
                    f"cache entry {entry.name!r} no longer resolves "
                    "through the registry — refusing to serve an "
                    "unprovable artifact")
            if self.verify_hits:
                closed, _ = runner.lower(self.max_quanta)
                fp = fingerprint(closed)
                if fp != entry.record.fingerprint:
                    raise ProgramCacheError(
                        f"cache hit verification failed for "
                        f"{entry.name!r}: this batch lowers to "
                        f"{fp[:24]}... but the cached program is "
                        f"{entry.record.fingerprint[:24]}... — the "
                        "class key admitted a different program")
            runner._runner = entry.jitted
            runner._runner_max_quanta = entry.max_quanta
            self._m["cache_hits"].inc()
            self._last_cache_hit = True
            # a hit still knows what its program cost to build
            self._last_compile_s = entry.compile_s
            return entry
        self._last_cache_hit = False
        t_compile = self._clock()
        closed, _ = runner.lower(self.max_quanta)
        fp = fingerprint(closed)
        record = ProgramRecord(name=name, fingerprint=fp,
                               tiles=cls.n_tiles)
        reg = self.registry.get(name)
        if reg is not None and reg.fingerprint != fp:
            raise ProgramCacheError(
                f"program {name!r} lowered to fingerprint {fp[:24]}... "
                f"but is registered as {reg.fingerprint[:24]}... — "
                "refusing the insert: the same class key must not "
                "silently serve two different artifacts")
        self.registry[name] = record
        if self.store is not None:
            # STORE HIT: another fleet process (or a prior life of this
            # one) already compiled this exact program — deserialize
            # its executable and inject it, zero compiles.  The
            # fingerprint we just lowered IS the store key, so every
            # store hit is identity-proven by retrace (the same proof
            # `verify_hits` buys for in-memory hits).
            t_probe = self._clock()
            entry = self._store_resolve(runner, record, B, shape_sig)
            if entry is not None:
                self.cache.put(key, entry, expect_fingerprint=fp)
                return entry
            # the disk probe (possibly a multi-MB read + sha256 + a
            # quarantine rename) is not compile time: keep it out of
            # compile_seconds and the compile_s the manifest persists
            t_compile += self._clock() - t_probe
            # STORE MISS: compile AOT against the real device inputs
            # (the jit path compiles lazily inside run(), which cannot
            # be serialized), fill the store, serve the batch
            from graphite_tpu.store.aot import aot_compile_runner

            compiled = aot_compile_runner(runner, self.max_quanta)
            self._last_compile_s = self._clock() - t_compile
            self._m["store_misses"].inc()
            jitted = compiled
        else:
            jitted = runner._get_runner(self.max_quanta)
            self._last_compile_s = self._clock() - t_compile
        self._h["compile"].observe(self._last_compile_s)
        entry = CacheEntry(
            name=name, record=record, jitted=jitted,
            max_quanta=self.max_quanta,
            nbytes=self._last_residency, shape_sig=shape_sig,
            compile_s=self._last_compile_s)
        self.cache.put(key, entry, expect_fingerprint=fp)
        self._m["compiles"].inc()
        if self.store is not None:
            self._store_fill(entry, B, jitted)
        return entry

    def _store_resolve(self, runner, record, B: int, shape_sig
                       ) -> "CacheEntry | None":
        """Serve an in-memory miss from the persistent store when it
        can prove the artifact: `warm_start()`-staged executables
        first, then a disk load.  An integrity failure quarantines the
        entry, counts, and returns None (fall through to compile) —
        never a crash, never a silently wrong program."""
        from graphite_tpu.store import (
            StoreError, StoreIntegrityError, StoreKey,
        )
        from graphite_tpu.store.aot import runtime_env

        fp = record.fingerprint
        staged = self._warm.pop((fp, B), None)
        if staged is not None:
            fnc, man, des_s = staged
        else:
            skey = StoreKey(fp, B, self.max_quanta, runtime_env())
            t0 = self._clock()
            try:
                got = self.store.load_executable(
                    skey, expect_fingerprint=fp)
            except StoreIntegrityError:
                self._m["store_integrity"].inc()
                return None
            except (StoreError, OSError):
                # store unreachable (read-only mount, deleted locks/,
                # disk error): an availability loss, not a
                # correctness one — fall back to a local compile,
                # never a crash
                return None
            if got is None:
                return None
            fnc, man = got
            des_s = self._clock() - t0
        runner._runner = fnc
        runner._runner_max_quanta = self.max_quanta
        self._m["store_hits"].inc()
        self._h["store_deserialize"].observe(des_s)
        self._last_store_hit = True
        self._last_deserialize_s = des_s
        # what the ORIGINAL fleet miss paid to build this program —
        # the round-14 "a hit still knows its build cost" contract,
        # now surviving process death via the manifest
        try:
            self._last_compile_s = float(man.get("compile_s", 0.0))
        except (TypeError, ValueError):
            self._last_compile_s = 0.0
        return CacheEntry(
            name=record.name, record=record, jitted=fnc,
            max_quanta=self.max_quanta, nbytes=self._last_residency,
            shape_sig=shape_sig, compile_s=self._last_compile_s,
            source="store", deserialize_s=des_s)

    def _store_fill(self, entry: CacheEntry, B: int, compiled) -> None:
        """Serialize + publish the fresh executable (atomic, locked).
        A fill failure is an availability loss, not a correctness one:
        counted, never raised into the batch — the compiled program
        still serves this process."""
        from graphite_tpu.store import StoreKey
        from graphite_tpu.store.aot import runtime_env

        t0 = self._clock()
        try:
            skey = StoreKey(entry.record.fingerprint, B,
                            self.max_quanta, runtime_env())
            self.store.save_executable(skey, compiled, manifest={
                "name": entry.name,
                "shape_sig": list(entry.shape_sig),
                "nbytes": int(entry.nbytes),
                "compile_s": round(float(entry.compile_s), 6),
                "record": {"name": entry.record.name,
                           **entry.record.to_json()},
            })
        except Exception:    # noqa: BLE001 — the batch must serve:
            # serialize/pickle/disk failures of EVERY flavor are an
            # availability loss for the FLEET, never a correctness
            # loss for this batch (StoreError, PicklingError, OSError,
            # backend serialization RuntimeErrors, ...)
            self._m["store_fill_errors"].inc()
            return
        self._m["store_fills"].inc()
        self._h["store_fill"].observe(self._clock() - t0)

    def warm_start(self, limit: "int | None" = None) -> int:
        """Pre-populate from the persistent store: deserialize entries
        compatible with this process (same runtime environment, same
        `max_quanta`) into a fingerprint-keyed staging area, so the
        first job of each stored class pays its deserialize at STARTUP
        and zero compiles at serve time.  Returns the number of
        programs staged; 0 without a store.  Integrity failures
        quarantine + count and skip the entry, exactly like the lazy
        load path.

        Staged executables live on the host/devices until a job of
        their class pops them, so startup wall time and memory scale
        with what is staged — `limit` bounds that to the N
        most-recently-used entries (a fleet store can hold far more
        classes than one process will ever serve; an unstaged class
        still store-hits lazily on its first job).  None stages every
        compatible entry."""
        if self.store is None:
            return 0
        from graphite_tpu.store import (
            StoreError, StoreIntegrityError, StoreKey,
        )
        from graphite_tpu.store.aot import runtime_env

        env = runtime_env()
        n = 0
        try:
            rows = self.store.entries()
        except OSError:
            return 0    # store unreachable: cold start, not a crash
        # entries() sorts oldest-used first; stage MRU-first so a
        # `limit` keeps the entries most likely to serve soon
        for row in reversed(rows):
            if limit is not None and n >= limit:
                break
            man = row["manifest"]
            if man is None:
                continue
            try:
                fp = str(man["fingerprint"])
                batch = int(man["batch"])
                ok = (int(man["max_quanta"]) == self.max_quanta
                      and tuple(man["env"]) == env)
            except (KeyError, TypeError, ValueError):
                continue
            if not ok or (fp, batch) in self._warm:
                continue
            skey = StoreKey(fp, batch, self.max_quanta, env)
            t0 = self._clock()
            try:
                got = self.store.load_executable(
                    skey, expect_fingerprint=fp)
            except StoreIntegrityError:
                self._m["store_integrity"].inc()
                continue
            except (StoreError, OSError):
                continue    # unreachable entry: serve cold instead
            if got is None:
                continue
            fnc, man2 = got
            self._warm[(fp, batch)] = (fnc, man2, self._clock() - t0)
            n += 1
        return n

    # -- observability ---------------------------------------------------

    @property
    def counters(self) -> dict:
        """Service counters: queue depth, batch occupancy, cache hit
        rate, compile count, jobs/s — the inference-stack dashboard.

        This is a COMPATIBILITY VIEW over `self.metrics` (the one
        definition of each rate lives in the registry): the round-13
        dict keys are preserved for `tools/serve.py` summary output and
        existing tests, each derived from exactly one instrument."""
        m = self._m
        hits = int(m["cache_hits"].value)
        compiles = int(m["compiles"].value)
        # store hits are neither an in-memory hit nor a compile, but
        # they ARE resolved batches — the rate's denominator counts
        # every resolution so a warm-started fleet member reads an
        # honest in-memory hit fraction
        store_hits = int(m["store_hits"].value)
        occ = self._h["occupancy"]
        wall = m["execute_wall"].value
        completed = int(m["completed"].value)
        return {
            "submitted": int(m["submitted"].value),
            "completed": completed,
            "failed": int(m["failed"].value),
            "rejected": int(m["rejected"].value),
            "backpressure": int(m["backpressure"].value),
            "batches": int(m["batches"].value),
            "splits": int(m["splits"].value),
            "retries": int(m["retries"].value),
            "cache_hits": hits,
            "compile_count": compiles,
            "queue_depth": self.admission.queue_depth,
            "mean_batch_occupancy": occ.mean,
            "cache_hit_rate": (hits / (hits + compiles + store_hits)
                               if hits + compiles + store_hits
                               else 0.0),
            "cache_entries": len(self.cache),
            "cache_bytes": self.cache.total_bytes,
            "cache_evictions": self.cache.evictions,
            "store_hits": store_hits,
            "store_misses": int(m["store_misses"].value),
            "store_fills": int(m["store_fills"].value),
            "store_integrity": int(m["store_integrity"].value),
            "jobs_per_s": completed / wall if wall > 0 else 0.0,
        }
