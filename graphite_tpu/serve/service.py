"""The campaign service: admission-controlled job batching over a
fingerprint-keyed compiled-program cache.

This is the piece that *serves* every amortization primitive the repo
already has: jobs (`serve/job.py`) are validated up front, bin-packed
into same-program batches by the admission controller
(`serve/admission.py` — `residency_breakdown` arithmetic against a
per-device `hbm_budget_bytes`), executed as vmapped `SweepRunner`
campaigns through the LRU compiled-program cache (`serve/cache.py` —
keyed by program class, proven by `analysis/identity` fingerprints
resolved through an `analysis/registry`-style record set), and demuxed
back into per-job `SimResults` + telemetry envelopes as each batch
completes.

Graceful degradation is structural, not best-effort:

 - a job that can never fit the budget is rejected at submit with the
   itemized breakdown; a full queue raises backpressure;
 - batches are padded to the class's FIXED capacity (replicating the
   first job — semantically a re-run, so padding adds no new failure
   modes) so every batch of a class reuses ONE compiled shape; the
   padded tail is masked out of the result stream;
 - a failed batch (deadlock, mailbox overflow, max_quanta timeout)
   SPLITS in half and re-enqueues at the front of its class FIFO —
   halving isolates the offending job in log2(B) steps instead of
   poisoning the queue; a job that fails ALONE is retried up to
   `max_attempts` and then reported as a failed envelope.  Every
   failure increments each member's attempt counter, so the
   split/retry recursion provably terminates.

The bit-exact sequential path (`Simulator.run`) remains the equivalence
oracle: `tools/regress.py --smoke`'s serve rung replays a mixed-
geometry job set both ways and requires identical results + telemetry.
"""

from __future__ import annotations

import dataclasses
import time

from graphite_tpu.serve.admission import AdmissionController, JobClass, \
    Pending, QueueFullError
from graphite_tpu.serve.cache import CacheEntry, ProgramCache, \
    ProgramCacheError
from graphite_tpu.serve.job import (
    Job, JobResult, STATUS_FAILED, STATUS_OK,
)


@dataclasses.dataclass
class BatchReport:
    """One executed (or failed) batch's bookkeeping row."""

    batch_id: int
    class_name: str
    n_tiles: int
    job_ids: "list[str]"
    n_jobs: int                # real jobs (pre-padding)
    batch_cap: int             # the padded B the program ran at
    occupancy: float           # n_jobs / batch_cap
    residency_total: int       # the admitted layout's residency bill
    cache_hit: bool
    ok: bool
    wall_s: float
    error: "str | None" = None


class CampaignService:
    """Persistent front end: submit jobs, drain result envelopes.

    `hbm_budget_bytes`: per-device admission budget (0 = off);
    `batch_size`: max sims per campaign batch (the class capacity is
    `min(batch_size, budget // per_sim_bytes)`); `cache_bytes`: program
    cache budget for byte-accounted LRU eviction (0 = unbounded);
    `max_pending`: queue depth before submit raises backpressure;
    `max_attempts`: per-job failure budget across splits/retries;
    `max_quanta`: the batch programs' quantum bound (part of the
    compiled program, hence of the cache key); `verify_hits`: re-lower
    every cache hit and re-prove fingerprint equality (a retrace, never
    a recompile — the belt-and-braces mode the regress rung runs);
    `validate`: run `trace/validate.py` on every submitted trace;
    `max_history`: newest result envelopes / batch reports retained on
    the service (`results` / `batch_log`) — streaming consumers use
    `drain()`; counters stay exact regardless.
    """

    def __init__(self, *, hbm_budget_bytes: int = 0, batch_size: int = 4,
                 cache_bytes: int = 0, max_pending: int = 1024,
                 max_attempts: int = 3, max_quanta: int = 1_000_000,
                 verify_hits: bool = False, validate: bool = True,
                 shard_batch: "bool | None" = False,
                 max_history: int = 4096):
        import collections

        self.admission = AdmissionController(
            hbm_budget_bytes=hbm_budget_bytes, batch_size=batch_size,
            max_pending=max_pending)
        self.cache = ProgramCache(cache_bytes)
        self.registry: "dict[str, object]" = {}   # name -> ProgramRecord
        self.hbm_budget_bytes = int(hbm_budget_bytes)
        self.max_attempts = int(max_attempts)
        self.max_quanta = int(max_quanta)
        self.verify_hits = bool(verify_hits)
        self.validate = bool(validate)
        self.shard_batch = shard_batch
        # retention is BOUNDED (`max_history` newest entries): envelopes
        # stream out through drain(); keeping every SimResults +
        # BatchReport forever would grow a persistent service without
        # bound.  Counters stay exact over all time (running sums).
        self.batch_log: "collections.deque[BatchReport]" = \
            collections.deque(maxlen=int(max_history))
        self._completed: "collections.deque[JobResult]" = \
            collections.deque(maxlen=int(max_history))
        self._occ_sum = 0.0
        self._occ_batches = 0
        self._next_batch_id = 0
        self._last_residency = 0
        self._last_cache_hit = False
        self._counts = {
            "submitted": 0, "completed": 0, "failed": 0, "rejected": 0,
            "backpressure": 0, "batches": 0, "splits": 0, "retries": 0,
            "cache_hits": 0, "compile_count": 0,
        }
        self._execute_wall_s = 0.0

    # -- submission ------------------------------------------------------

    def submit(self, job: Job) -> int:
        """Validate and queue one job; returns its submission sequence
        number.  Raises `TraceValidationError`/`ValueError` on a
        malformed job, `analysis.cost.ResidencyBudgetError` (with
        `.breakdown`) on a job that can never fit, `QueueFullError`
        under backpressure."""
        try:
            job.validate(validate_trace=self.validate)
            cls, pending = self.admission.admit(job)
        except QueueFullError:
            # backpressure is NOT a rejection: the job is fine, the
            # queue is full — the caller drains and resubmits, and the
            # later successful submit must keep the accounting identity
            # submitted == completed + failed (+ rejected never counts
            # a job that eventually ran)
            self._counts["backpressure"] += 1
            raise
        except Exception:
            self._counts["rejected"] += 1
            raise
        self._counts["submitted"] += 1
        return pending.seq

    @property
    def queue_depth(self) -> int:
        return self.admission.queue_depth

    # -- scheduling ------------------------------------------------------

    def step(self) -> "list[JobResult]":
        """Form and run ONE batch (the oldest-head class); returns the
        envelopes it completed (empty when a failed batch split and
        re-enqueued, or when the queue is idle)."""
        nxt = self.admission.next_batch()
        if nxt is None:
            return []
        cls, pendings = nxt
        return self._run_batch(cls, pendings)

    def drain(self):
        """Generator: run batches until the queue is idle, yielding
        result envelopes as each batch completes (the streaming read
        the CLI prints line-by-line)."""
        while self.admission.queue_depth:
            for res in self.step():
                yield res

    def run_all(self) -> "list[JobResult]":
        return list(self.drain())

    @property
    def results(self) -> "list[JobResult]":
        """Every envelope completed so far (streaming callers use
        `drain()` instead)."""
        return list(self._completed)

    # -- batch execution -------------------------------------------------

    def _run_batch(self, cls: JobClass,
                   pendings: "list[Pending]") -> "list[JobResult]":
        from graphite_tpu.engine.simulator import (
            DeadlockError, MailboxOverflowError,
        )

        self._counts["batches"] += 1
        batch_id = self._next_batch_id
        self._next_batch_id += 1
        t0 = time.perf_counter()
        try:
            results = self._execute(cls, pendings, batch_id)
        except ProgramCacheError as e:
            # identity failures are NOT load: retrying cannot make a
            # mismatched artifact provable — surface them.  The popped
            # jobs still get failed envelopes first, so the accounting
            # (submitted == completed + failed + rejected) survives the
            # raise and no admitted work silently vanishes
            for p in pendings:
                p.attempts += 1
                self._completed.append(JobResult(
                    job_id=p.job.job_id, status=STATUS_FAILED,
                    error=f"ProgramCacheError: {e}", batch_id=batch_id,
                    attempts=p.attempts, seed=p.job.seed))
                self._counts["failed"] += 1
            raise
        except (DeadlockError, MailboxOverflowError, RuntimeError) as e:
            wall = time.perf_counter() - t0
            self._execute_wall_s += wall
            return self._handle_failure(cls, pendings, batch_id, e, wall)
        wall = time.perf_counter() - t0
        self._execute_wall_s += wall
        self.batch_log.append(BatchReport(
            batch_id=batch_id, class_name=self._class_name(cls),
            n_tiles=cls.n_tiles,
            job_ids=[p.job.job_id for p in pendings],
            n_jobs=len(pendings), batch_cap=cls.batch_cap,
            occupancy=len(pendings) / cls.batch_cap,
            residency_total=self._last_residency,
            cache_hit=self._last_cache_hit, ok=True, wall_s=wall))
        self._occ_sum += len(pendings) / cls.batch_cap
        self._occ_batches += 1
        self._completed.extend(results)
        self._counts["completed"] += len(results)
        return results

    def _handle_failure(self, cls, pendings, batch_id, exc, wall
                        ) -> "list[JobResult]":
        """Split-and-requeue (n > 1) or retry/fail (n == 1); every
        member's attempt counter moves, so the recursion terminates."""
        msg = f"{type(exc).__name__}: {exc}"
        self.batch_log.append(BatchReport(
            batch_id=batch_id, class_name=self._class_name(cls),
            n_tiles=cls.n_tiles,
            job_ids=[p.job.job_id for p in pendings],
            n_jobs=len(pendings), batch_cap=cls.batch_cap,
            occupancy=len(pendings) / cls.batch_cap,
            residency_total=self._last_residency,
            cache_hit=self._last_cache_hit,
            ok=False, wall_s=wall, error=msg))
        for p in pendings:
            p.attempts += 1
        if len(pendings) > 1:
            # halving isolates the offender in ~log2(B) steps; the
            # halves requeue as PRE-FORMED batches (head of the ready
            # line, first half first) so they re-run at their reduced
            # size — and still pad to the class capacity, so every
            # retry is a cache hit on the one compiled program
            mid = (len(pendings) + 1) // 2
            self.admission.requeue_batch(cls, pendings[mid:])
            self.admission.requeue_batch(cls, pendings[:mid])
            self._counts["splits"] += 1
            self._counts["retries"] += 1
            return []
        p = pendings[0]
        if p.attempts >= self.max_attempts:
            res = JobResult(job_id=p.job.job_id, status=STATUS_FAILED,
                            error=msg, batch_id=batch_id,
                            attempts=p.attempts, seed=p.job.seed)
            self._completed.append(res)
            self._counts["failed"] += 1
            return [res]
        self.admission.requeue_batch(cls, [p])
        self._counts["retries"] += 1
        return []

    def _class_name(self, cls: JobClass) -> str:
        import hashlib

        digest = cls.key[0][:8]
        tel = "-tel" if cls.telemetry is not None else ""
        # the key hash keeps the name INJECTIVE over class keys: the
        # readable fields alone miss key components (mem-ness,
        # telemetry spec details), and two distinct classes colliding
        # on one registry name would read as an identity violation
        khash = hashlib.sha256(repr(cls.key).encode()).hexdigest()[:8]
        return (f"serve-{digest}-t{cls.n_tiles}-b{cls.batch_cap}"
                f"-l{cls.pad_length}-d{cls.mailbox_depth}{tel}-k{khash}")

    def _execute(self, cls: JobClass, pendings: "list[Pending]",
                 batch_id: int) -> "list[JobResult]":
        """Pack, cache-resolve, run, and demux one batch.  Raises the
        engine's own failure types on a bad batch — `_run_batch` owns
        the split/retry policy."""
        from graphite_tpu.sweep.pack import pack_traces
        from graphite_tpu.sweep.runner import SweepRunner

        jobs = [p.job for p in pendings]
        n, B = len(jobs), cls.batch_cap
        # per-batch stats reset FIRST: a failure before they are
        # recomputed must not report the previous batch's numbers
        self._last_residency = 0
        self._last_cache_hit = False
        # pad to the class's FIXED capacity with replicas of job 0 so
        # every batch of this class shares one [B, T, L] program shape;
        # the replicas' rows are dropped below (the tail mask)
        traces = [j.trace for j in jobs] + [jobs[0].trace] * (B - n)
        points = [dict(j.knobs) for j in jobs] \
            + [dict(jobs[0].knobs)] * (B - n)
        pack = pack_traces(traces, validate=False,
                           pad_length=cls.pad_length)
        # the budget is passed as an INT always: 0 explicitly disables
        # the runner's fail-fast (None would fall back to the config's
        # own `[general] hbm_budget_bytes`, refusing batches the
        # service-level admission never checked against)
        runner = SweepRunner(
            cls.config, pack, points,
            mailbox_depth=cls.mailbox_depth,
            shard_batch=self.shard_batch,
            hbm_budget_bytes=self.hbm_budget_bytes,
            telemetry=cls.telemetry)
        self._last_residency = int(
            runner.residency_breakdown()["total"])
        if self.hbm_budget_bytes \
                and self._last_residency > self.hbm_budget_bytes:
            # unreachable by construction (admission sized batch_cap
            # from the same arithmetic and the runner's own fail-fast
            # already re-checked) — a trip here is a real bug, not load
            raise AssertionError(
                f"admitted batch residency {self._last_residency} "
                f"exceeds hbm_budget_bytes={self.hbm_budget_bytes}")
        entry = self._resolve_program(cls, runner, B)
        out = runner.run(max_quanta=self.max_quanta)
        results = []
        for b in range(n):   # the padded tail [n:B] never leaves here
            p = pendings[b]
            tl = None if out.timelines is None else out.timelines[b]
            results.append(JobResult(
                job_id=p.job.job_id, status=STATUS_OK,
                results=out.results[b], telemetry=tl,
                batch_id=batch_id, attempts=p.attempts + 1,
                seed=p.job.seed, knob_point=dict(p.job.knobs),
                n_quanta=int(out.n_quanta[b]),
                n_iterations=int(out.n_iterations[b])))
        return results

    # -- program cache ---------------------------------------------------

    def _resolve_program(self, cls: JobClass, runner, B: int
                         ) -> CacheEntry:
        """Serve the batch through the compiled-program cache.

        MISS: lower the campaign, fingerprint it
        (`analysis/identity.fingerprint`), resolve the name through the
        service registry (a registry-mismatched fingerprint at insert
        time errors LOUDLY — `ProgramCacheError`), register + insert,
        and hand the runner its own fresh jit (the one compile).
        HIT: resolve the stored record through the registry, optionally
        re-lower and re-prove fingerprint equality (`verify_hits` — a
        retrace, never a recompile), and inject the cached jitted
        callable into the fresh runner, so the batch executes the
        PROVABLY-same compiled artifact with zero new compiles."""
        from graphite_tpu.analysis.identity import fingerprint
        from graphite_tpu.analysis.registry import ProgramRecord

        name = self._class_name(cls)
        key = cls.key + (B, self.max_quanta)
        shape_sig = (B, cls.n_tiles, cls.pad_length)
        entry = self.cache.get(key, shape_sig)
        if entry is not None:
            reg = self.registry.get(entry.name)
            if reg is None or reg.fingerprint != entry.record.fingerprint:
                raise ProgramCacheError(
                    f"cache entry {entry.name!r} no longer resolves "
                    "through the registry — refusing to serve an "
                    "unprovable artifact")
            if self.verify_hits:
                closed, _ = runner.lower(self.max_quanta)
                fp = fingerprint(closed)
                if fp != entry.record.fingerprint:
                    raise ProgramCacheError(
                        f"cache hit verification failed for "
                        f"{entry.name!r}: this batch lowers to "
                        f"{fp[:24]}... but the cached program is "
                        f"{entry.record.fingerprint[:24]}... — the "
                        "class key admitted a different program")
            runner._runner = entry.jitted
            runner._runner_max_quanta = entry.max_quanta
            self._counts["cache_hits"] += 1
            self._last_cache_hit = True
            return entry
        self._last_cache_hit = False
        closed, _ = runner.lower(self.max_quanta)
        fp = fingerprint(closed)
        record = ProgramRecord(name=name, fingerprint=fp,
                               tiles=cls.n_tiles)
        reg = self.registry.get(name)
        if reg is not None and reg.fingerprint != fp:
            raise ProgramCacheError(
                f"program {name!r} lowered to fingerprint {fp[:24]}... "
                f"but is registered as {reg.fingerprint[:24]}... — "
                "refusing the insert: the same class key must not "
                "silently serve two different artifacts")
        self.registry[name] = record
        jitted = runner._get_runner(self.max_quanta)
        entry = CacheEntry(
            name=name, record=record, jitted=jitted,
            max_quanta=self.max_quanta,
            nbytes=self._last_residency, shape_sig=shape_sig)
        self.cache.put(key, entry, expect_fingerprint=fp)
        self._counts["compile_count"] += 1
        return entry

    # -- observability ---------------------------------------------------

    @property
    def counters(self) -> dict:
        """Service counters: queue depth, batch occupancy, cache hit
        rate, compile count, jobs/s — the inference-stack dashboard."""
        total_lookups = (self._counts["cache_hits"]
                         + self._counts["compile_count"])
        return {
            **self._counts,
            "queue_depth": self.admission.queue_depth,
            "mean_batch_occupancy": (
                self._occ_sum / self._occ_batches
                if self._occ_batches else 0.0),
            "cache_hit_rate": (
                self._counts["cache_hits"] / total_lookups
                if total_lookups else 0.0),
            "cache_entries": len(self.cache),
            "cache_bytes": self.cache.total_bytes,
            "cache_evictions": self.cache.evictions,
            "jobs_per_s": (
                self._counts["completed"] / self._execute_wall_s
                if self._execute_wall_s > 0 else 0.0),
        }
