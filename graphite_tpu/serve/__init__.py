"""Campaign service: admission-controlled job batching over a
fingerprint-keyed compiled-program cache (see serve/service.py)."""

from graphite_tpu.serve.admission import (      # noqa: F401
    AdmissionController, JobClass, QueueFullError,
)
from graphite_tpu.serve.cache import (          # noqa: F401
    CacheEntry, ProgramCache, ProgramCacheError,
)
from graphite_tpu.serve.job import (            # noqa: F401
    CLOCK_SCHEMES, Job, JobResult, STATUS_FAILED, STATUS_OK,
)
from graphite_tpu.serve.service import (        # noqa: F401
    BatchReport, CampaignService,
)
