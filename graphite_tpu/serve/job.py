"""Job specs and result envelopes for the campaign service.

A `Job` is one simulation request: a trace, a configuration, optional
timing-knob overrides (the round-7 traced `Knobs` fields — they never
change the compiled program), an optional `TelemetrySpec`, an optional
per-job clock-skew scheme, and a seed carried as metadata.  `validate()`
runs every static check a host can prove before the job touches the
queue: trace well-formedness (`trace/validate.py`), geometry agreement,
knob-name/scheme compatibility — so a malformed job is rejected at
submit time with a named error instead of poisoning a batch minutes
into a compiled run.

A `JobResult` is the streaming envelope the service emits as each batch
completes: the job's own demuxed `SimResults` + telemetry timeline (or
a failure record after the retry budget is exhausted), plus the batch
bookkeeping (batch id, attempts, the knob point that ran).
"""

from __future__ import annotations

import dataclasses
import hashlib

import numpy as np

from graphite_tpu.config.config_file import ConfigFile
from graphite_tpu.config.simconfig import SimConfig

# The selectable clock-skew management schemes (engine/simulator.py):
# lax_barrier runs quantum barriers (the strict scheme; quantum_ps is a
# sweepable knob there), lax runs one unbounded quantum, lax_p2p runs
# unbounded quanta with pairwise slack clamping.  Exposed per-job so one
# service instance can serve a skew-tolerance scenario axis — jobs with
# different schemes compile different programs and never co-batch.
CLOCK_SCHEMES = ("lax_barrier", "lax", "lax_p2p")

STATUS_OK = "ok"
STATUS_FAILED = "failed"


def _coerce_config(config) -> SimConfig:
    if isinstance(config, str):
        config = ConfigFile.from_string(config)
    if isinstance(config, ConfigFile):
        config = SimConfig(config)
    if not isinstance(config, SimConfig):
        raise TypeError("config must be a SimConfig, ConfigFile, or "
                        "config INI text")
    return config


def override_clock_scheme(config: SimConfig, scheme: str) -> SimConfig:
    """A SimConfig identical to `config` except for the clock-skew
    management scheme — the per-job `clock_scheme` field's resolution.
    Rebuilt from the flat key dict so every other knob passes through
    untouched."""
    cfg = ConfigFile()
    for k, v in config.cfg.as_dict().items():
        cfg.set(k, v)
    cfg.set("clock_skew_management/scheme", scheme)
    return SimConfig(cfg)


def config_digest(config: SimConfig) -> str:
    """Stable digest of the full flat key dict — the static half of the
    service's program-class key (two jobs whose configs differ in ANY
    key never co-batch; timing values that are traced knobs still live
    in the config, so equal-digest is sufficient, not necessary, for
    program equality — the cache's fingerprint check is the proof)."""
    h = hashlib.sha256()
    for k, v in sorted(config.cfg.as_dict().items()):
        h.update(f"{k}={v}\n".encode())
    return h.hexdigest()


@dataclasses.dataclass
class Job:
    """One simulation request.

    `knobs`: round-7 traced timing-knob overrides (sweep/knobs.py
    KNOB_FIELDS) — same compiled program, different point.
    `telemetry`: an `obs.TelemetrySpec` to record a device timeline for
    this job (jobs with different specs never co-batch — the ring is
    baked into the program).  `profile`: an `obs.ProfileSpec` to record
    the per-tile spatial profile ring (same never-co-batch rule — the
    [S, T, m] ring is baked in too).  `dvfs`: a `dvfs.DvfsSpec`
    attaching the runtime DVFS manager (per-domain carried frequencies;
    same never-co-batch rule — the carried-frequency reads are baked
    into the program, so jobs with differing specs split classes); a
    `dvfs_domain_mhz` knob then seeds this job's operating point and
    co-batches with other points of the same spec.  `hist`: an
    `obs.HistSpec` recording device-resident latency histograms (the
    round-21 int64 bucket ring is baked in — same never-co-batch rule).
    `clock_scheme`:
    override the config's clock-skew management scheme (CLOCK_SCHEMES);
    None keeps the config's own.  `seed`: metadata echoed into the
    result envelope.
    """

    job_id: str
    config: object               # SimConfig | ConfigFile | INI text
    trace: object                # TraceBatch
    knobs: dict = dataclasses.field(default_factory=dict)
    telemetry: object = None     # obs.TelemetrySpec | None
    profile: object = None       # obs.ProfileSpec | None
    dvfs: object = None          # dvfs.DvfsSpec | None
    hist: object = None          # obs.HistSpec | None
    seed: "int | None" = None
    clock_scheme: "str | None" = None

    def __post_init__(self):
        self.config = _coerce_config(self.config)
        self._resolved = None

    @property
    def n_tiles(self) -> int:
        return int(self.trace.n_tiles)

    def resolved_config(self) -> SimConfig:
        """The config this job actually runs under (clock_scheme
        override applied)."""
        if self._resolved is None:
            if self.clock_scheme is None:
                self._resolved = self.config
            else:
                self._resolved = override_clock_scheme(
                    self.config, self.clock_scheme)
        return self._resolved

    def effective_scheme(self) -> str:
        return self.resolved_config().cfg.get_string(
            "clock_skew_management/scheme", "lax_barrier")

    def validate(self, *, validate_trace: bool = True) -> None:
        """Every statically provable admission check; raises ValueError
        (or `trace.validate.TraceValidationError`) naming the problem."""
        from graphite_tpu.sweep.knobs import (
            ALL_KNOB_FIELDS, DVFS_KNOB_FIELD,
        )

        if self.clock_scheme is not None \
                and self.clock_scheme not in CLOCK_SCHEMES:
            raise ValueError(
                f"job {self.job_id!r}: unknown clock_scheme "
                f"{self.clock_scheme!r} (valid: {', '.join(CLOCK_SCHEMES)})")
        sc = self.resolved_config()
        if self.n_tiles != sc.application_tiles:
            raise ValueError(
                f"job {self.job_id!r}: trace has {self.n_tiles} tiles "
                f"but the config expects {sc.application_tiles}")
        unknown = set(self.knobs) - set(ALL_KNOB_FIELDS)
        if unknown:
            raise ValueError(
                f"job {self.job_id!r}: unknown knob(s) {sorted(unknown)} "
                f"(valid: {', '.join(ALL_KNOB_FIELDS)})")
        if "quantum_ps" in self.knobs:
            if self.effective_scheme() != "lax_barrier":
                raise ValueError(
                    f"job {self.job_id!r}: quantum_ps knob needs the "
                    f"lax_barrier clock scheme (the "
                    f"{self.effective_scheme()} scheme has no quantum)")
            if int(self.knobs["quantum_ps"]) <= 0:
                raise ValueError(
                    f"job {self.job_id!r}: quantum_ps must be positive")
        for k, v in self.knobs.items():
            if k == DVFS_KNOB_FIELD:
                vals = [int(x) for x in v]   # a per-domain int vector
                if not vals or any(x <= 0 for x in vals):
                    raise ValueError(
                        f"job {self.job_id!r}: {DVFS_KNOB_FIELD} must "
                        "be a non-empty vector of positive MHz values")
                continue
            int(v)  # raises if not int-coercible
        if DVFS_KNOB_FIELD in self.knobs and self.dvfs is None:
            raise ValueError(
                f"job {self.job_id!r}: the {DVFS_KNOB_FIELD} knob needs "
                "dvfs=DvfsSpec(...) on the job (the carried-frequency "
                "program is opt-in)")
        if self.telemetry is not None:
            from graphite_tpu.obs.telemetry import TelemetrySpec

            if not isinstance(self.telemetry, TelemetrySpec):
                raise ValueError(
                    f"job {self.job_id!r}: telemetry must be an "
                    f"obs.TelemetrySpec")
        if self.profile is not None:
            from graphite_tpu.obs.profile import ProfileSpec

            if not isinstance(self.profile, ProfileSpec):
                raise ValueError(
                    f"job {self.job_id!r}: profile must be an "
                    f"obs.ProfileSpec")
        if self.dvfs is not None:
            from graphite_tpu.dvfs.runtime import DvfsSpec

            if not isinstance(self.dvfs, DvfsSpec):
                raise ValueError(
                    f"job {self.job_id!r}: dvfs must be a dvfs.DvfsSpec")
        if self.hist is not None:
            from graphite_tpu.obs.hist import HistSpec

            if not isinstance(self.hist, HistSpec):
                raise ValueError(
                    f"job {self.job_id!r}: hist must be an obs.HistSpec")
        if validate_trace:
            from graphite_tpu.trace.validate import validate_batch

            validate_batch(self.trace)

    def has_mem_trace(self) -> bool:
        """Does this TRACE carry memory operands?  This is deliberately
        the flags-only predicate — exactly the per-sim agreement check
        `SweepRunner` enforces on a batch — so the class key can never
        co-batch jobs the runner would refuse.  Config-level memory
        switches (enable_shared_mem, enable_icache_modeling) are
        already in the config digest half of the key."""
        from graphite_tpu.trace.schema import FLAG_MEM0_VALID, \
            FLAG_MEM1_VALID

        return bool(np.any(
            self.trace.flags & (FLAG_MEM0_VALID | FLAG_MEM1_VALID)))


@dataclasses.dataclass
class JobResult:
    """The streaming result envelope for one job."""

    job_id: str
    status: str                    # STATUS_OK | STATUS_FAILED
    results: object = None         # SimResults (ok only)
    telemetry: object = None       # obs.Timeline | None
    profile: object = None         # obs.TileProfile | None
    hist: object = None            # obs.Hist | None
    error: "str | None" = None     # failure message (failed only)
    batch_id: "int | None" = None
    attempts: int = 1
    seed: "int | None" = None
    knob_point: "dict | None" = None
    n_quanta: "int | None" = None
    n_iterations: "int | None" = None
    # host latency breakdown (round 14) — populated when the service
    # runs with tracing on: {"queue_dwell_s": ..., "batch_execute_s": ...}
    timings: "dict | None" = None

    @property
    def ok(self) -> bool:
        return self.status == STATUS_OK

    def to_json(self) -> dict:
        """One JSON-able dict (the CLI's per-job output line)."""
        row = {"job": self.job_id, "status": self.status,
               "batch": self.batch_id, "attempts": self.attempts}
        if self.seed is not None:
            row["seed"] = int(self.seed)
        if self.knob_point:
            row.update({
                k: (tuple(int(x) for x in v) if isinstance(
                    v, (tuple, list)) else int(v))
                for k, v in self.knob_point.items()})
        if self.ok and self.results is not None:
            r = self.results
            row.update({
                "completion_time_ns": r.completion_time_ps // 1000,
                "total_instructions": r.total_instructions,
                "n_quanta": self.n_quanta,
                "n_iterations": self.n_iterations,
                "func_errors": r.func_errors,
            })
            if self.telemetry is not None:
                row["telemetry_samples"] = len(self.telemetry)
                if "energy_pj" in getattr(self.telemetry, "series", ()):
                    col = self.telemetry.col("energy_pj")
                    if len(col) and not self.telemetry.wrapped:
                        # a delta series: the unwrapped sum is the job's
                        # total energy at its operating point(s) — the
                        # trade-curve's y-axis (wrapped rings undercount,
                        # so the field is omitted rather than wrong)
                        row["energy_pj"] = int(col.sum())
            if self.profile is not None:
                row["profile_samples"] = len(self.profile)
            if self.hist is not None:
                # total event count across sources — a cheap liveness
                # signal; the full counts go to --hist-out npz files
                row["hist_events"] = int(sum(
                    self.hist.total(s) for s in self.hist.sources))
        if self.timings:
            row.update({k: float(v) for k, v in self.timings.items()})
        if self.error is not None:
            row["error"] = self.error
        return row
