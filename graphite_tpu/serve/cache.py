"""The fingerprint-keyed compiled-program cache.

A cache entry is one jitted batched-campaign callable (the
`SweepRunner` runner function) plus the `analysis/registry`
`ProgramRecord` that proves WHAT it is: the canonical jaxpr fingerprint
(`analysis/identity.fingerprint`) of the lowering it was compiled from.
The service resolves every insert and hit through its registry, so

 - at INSERT time, the freshly lowered program's fingerprint must match
   the registered identity for that key (first insert registers it) —
   a mismatch raises `ProgramCacheError` LOUDLY instead of silently
   caching a program that is not what the key claims (e.g. a re-lowered
   class that drifted after an eviction);
 - at HIT time, the stored record must still resolve to the registered
   fingerprint, and (with `verify_hits`) the service re-lowers the new
   batch and re-proves fingerprint equality — a retrace, never a
   recompile, so the round-7 compile-count probe still reads 1.

Since round 17 the in-memory cache can sit over a persistent
fingerprint-keyed store of serialized executables (`store/`): a miss
consults the store before compiling, a fresh compile fills it, and
`CacheEntry.source` records which path materialized the entry — the
service (`serve/service.py _resolve_program`) owns that layering, this
module stays pure host-side bookkeeping.

Eviction is byte-accounted LRU: each entry carries the residency bill
of the campaign layout it serves (the same
`analysis/cost.residency_breakdown` total the admission controller
budgets), and inserts evict least-recently-used entries until the cache
total fits `max_bytes` (0 = unbounded).  The newest entry is never
evicted — a cache that cannot hold one program would force a compile
per batch, which is strictly worse than admitting the overage.
"""

from __future__ import annotations

import collections
import dataclasses


class ProgramCacheError(RuntimeError):
    """A cache entry failed identity or shape verification."""


@dataclasses.dataclass
class CacheEntry:
    """One compiled campaign program + its provable identity."""

    name: str                 # registry key (human-readable class name)
    record: object            # analysis.registry.ProgramRecord
    jitted: object            # the jitted runner callable
    max_quanta: int
    nbytes: int               # residency bill of the layout it serves
    shape_sig: tuple          # (B, n_tiles, pad_length)
    hits: int = 0
    # host seconds the miss paid to lower + fingerprint + set up the
    # jit (round 14 observability — batch spans report it on hits too,
    # so "what did this program cost to build" survives the miss)
    compile_s: float = 0.0
    # round 17: how this entry materialized — "compile" (lowered and
    # compiled in this process) or "store" (deserialized from the
    # persistent AOT program store) — and the host seconds the store
    # hit paid to deserialize the payload (0.0 for in-process compiles;
    # for store entries compile_s reports what the ORIGINAL fleet miss
    # paid, read from the entry manifest)
    source: str = "compile"
    deserialize_s: float = 0.0


class ProgramCache:
    """Byte-accounted LRU over compiled campaign programs."""

    def __init__(self, max_bytes: int = 0):
        self.max_bytes = int(max_bytes)
        self._entries: "collections.OrderedDict[tuple, CacheEntry]" = \
            collections.OrderedDict()
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key) -> bool:
        return key in self._entries

    @property
    def total_bytes(self) -> int:
        return sum(e.nbytes for e in self._entries.values())

    def keys(self):
        return list(self._entries)

    def get(self, key, shape_sig: "tuple | None" = None
            ) -> "CacheEntry | None":
        """LRU-touching lookup.  `shape_sig` guards the one silent
        failure mode jit would otherwise hide: calling a cached
        callable with different input shapes would quietly COMPILE a
        second executable instead of erroring — a shape mismatch here
        means the class key failed to capture a shape-bearing input and
        must be fixed, not papered over."""
        entry = self._entries.get(key)
        if entry is None:
            return None
        if shape_sig is not None and tuple(shape_sig) != entry.shape_sig:
            raise ProgramCacheError(
                f"cache entry {entry.name!r} serves shape "
                f"{entry.shape_sig} but the batch asks for "
                f"{tuple(shape_sig)} — the class key missed a "
                "shape-bearing input (calling through would silently "
                "recompile)")
        self._entries.move_to_end(key)
        entry.hits += 1
        return entry

    def put(self, key, entry: CacheEntry, *,
            expect_fingerprint: str) -> CacheEntry:
        """Insert with identity verification: `expect_fingerprint` is
        the registry-resolved identity for this key, and the entry's
        record must match it — a registry-mismatched fingerprint at
        insert time errors loudly instead of silently serving a stale
        (or wrong) program under the key's name."""
        if entry.record.fingerprint != expect_fingerprint:
            raise ProgramCacheError(
                f"refusing to cache {entry.name!r}: lowered fingerprint "
                f"{entry.record.fingerprint[:24]}... does not match the "
                f"registered identity {expect_fingerprint[:24]}... — "
                "the program drifted from what this key previously "
                "compiled; a silent insert would serve a different "
                "artifact under the same name")
        self._entries[key] = entry
        self._entries.move_to_end(key)
        while (self.max_bytes and len(self._entries) > 1
               and self.total_bytes > self.max_bytes):
            self._entries.popitem(last=False)
            self.evictions += 1
        return entry

    def stats(self) -> dict:
        return {
            "entries": len(self._entries),
            "bytes": self.total_bytes,
            "evictions": self.evictions,
            "hits": sum(e.hits for e in self._entries.values()),
        }
