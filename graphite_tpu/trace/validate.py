"""Pre-run trace validation: fail fast on malformed campaign traces.

A malformed trace costs the most where it is cheapest to catch: a RECV
with no matching SEND deadlocks a 1024-tile compiled program minutes
into a run (and a SEND-carrying trace is exactly the shape that still
crashes the TPU worker under the hbh NoC — ROADMAP), a barrier whose
arrivals never reach its participant count hangs the last generation
forever, and an out-of-range opcode scatters into whatever the engine's
clipped gather happens to read.  This pass checks the STATIC properties
a host can prove from the record arrays alone — op-code range,
SEND/RECV pairing, barrier participant-count consistency — and raises
a named `TraceValidationError` before anything is packed, uploaded, or
compiled.  `sweep/pack.py` runs it on every sim of a campaign.

Provable-deadlock conditions are errors; order-dependent hazards (e.g.
mixed BARRIER_WAIT/ARRIVE remainders, which may or may not strand a
blocking waiter depending on interleaving) are warnings.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from graphite_tpu.trace.schema import Op

ANY_SENDER = -1  # engine/step.py wildcard NET_RECV partner

SEV_ERROR = "error"
SEV_WARNING = "warning"


class TraceValidationError(ValueError):
    """A trace failed static validation; `.findings` holds the details."""

    def __init__(self, message, findings=()):
        super().__init__(message)
        self.findings = list(findings)


@dataclasses.dataclass
class TraceFinding:
    severity: str
    kind: str       # "op-range" | "send-recv" | "barrier" | "dvfs"
    message: str
    data: dict = dataclasses.field(default_factory=dict)

    def __str__(self) -> str:
        return f"[{self.kind}/{self.severity}] {self.message}"


def _check_op_range(batch, out):
    valid = np.isin(batch.op, [int(o) for o in Op])
    if valid.all():
        return
    bad = np.argwhere(~valid)
    vals = sorted({int(batch.op[t, i]) for t, i in bad[:64]})
    out.append(TraceFinding(
        SEV_ERROR, "op-range",
        f"{len(bad)} record(s) carry opcodes outside the Op enum "
        f"(values {vals[:8]}; first at tile {int(bad[0][0])} record "
        f"{int(bad[0][1])})",
        data={"count": int(len(bad)), "values": vals[:8],
              "first": [int(bad[0][0]), int(bad[0][1])]}))


def _check_send_recv(batch, out):
    T = batch.n_tiles
    op, aux0 = batch.op, batch.aux0
    send = op == int(Op.SEND)
    recv = op == int(Op.NET_RECV)
    if not (send.any() or recv.any()):
        return
    tiles = np.broadcast_to(np.arange(T)[:, None], op.shape)

    s_src, s_dst = tiles[send], aux0[send]
    bad_dst = (s_dst < 0) | (s_dst >= T)
    if bad_dst.any():
        k = int(np.argmax(bad_dst))
        out.append(TraceFinding(
            SEV_ERROR, "send-recv",
            f"{int(bad_dst.sum())} SEND(s) target tiles outside "
            f"[0, {T}) (e.g. tile {int(s_src[k])} -> {int(s_dst[k])})",
            data={"count": int(bad_dst.sum())}))
    r_dst, r_src = tiles[recv], aux0[recv]
    bad_src = (r_src < ANY_SENDER) | (r_src >= T)
    if bad_src.any():
        k = int(np.argmax(bad_src))
        out.append(TraceFinding(
            SEV_ERROR, "send-recv",
            f"{int(bad_src.sum())} RECV(s) name senders outside "
            f"[0, {T}) or ANY_SENDER (e.g. tile {int(r_dst[k])} <- "
            f"{int(r_src[k])})",
            data={"count": int(bad_src.sum())}))
    if bad_dst.any() or bad_src.any():
        return  # matrix math below assumes in-range partners

    sends = np.zeros((T, T), np.int64)       # [src, dst]
    np.add.at(sends, (s_src, s_dst), 1)
    specific = r_src >= 0
    recvs = np.zeros((T, T), np.int64)       # [src, dst]
    np.add.at(recvs, (r_src[specific], r_dst[specific]), 1)
    any_recvs = np.zeros(T, np.int64)
    np.add.at(any_recvs, r_dst[~specific], 1)

    # a specific RECV r<-s can only ever match a SEND s->r: more recvs
    # than sends on a pair is a guaranteed deadlock
    over = recvs > sends
    if over.any():
        pairs = np.argwhere(over)[:8]
        out.append(TraceFinding(
            SEV_ERROR, "send-recv",
            f"{int(over.sum())} (sender, receiver) pair(s) RECV more "
            f"messages than are ever SENT — guaranteed deadlock "
            f"(e.g. tile {int(pairs[0][1])} receives "
            f"{int(recvs[pairs[0][0], pairs[0][1]])} from tile "
            f"{int(pairs[0][0])} which sends "
            f"{int(sends[pairs[0][0], pairs[0][1]])})",
            data={"pairs": [[int(s), int(d)] for s, d in pairs]}))
    # total receives at a tile (specific + wildcard) bounded by total
    # sends addressed to it
    tot_recv = recvs.sum(axis=0) + any_recvs
    tot_sent = sends.sum(axis=0)
    starved = tot_recv > tot_sent
    if starved.any():
        t = int(np.argmax(starved))
        out.append(TraceFinding(
            SEV_ERROR, "send-recv",
            f"tile(s) {np.flatnonzero(starved).tolist()[:8]} RECV more "
            f"messages than are addressed to them (e.g. tile {t}: "
            f"{int(tot_recv[t])} receives, {int(tot_sent[t])} sends in "
            f"flight) — guaranteed deadlock",
            data={"tiles": np.flatnonzero(starved).tolist()[:8]}))


def _check_barriers(batch, out, n_barriers=None):
    T = batch.n_tiles
    op, aux0, aux1 = batch.op, batch.aux0, batch.aux1
    init = op == int(Op.BARRIER_INIT)
    wait = op == int(Op.BARRIER_WAIT)
    arrive = op == int(Op.BARRIER_ARRIVE)
    sync = op == int(Op.BARRIER_SYNC)
    if not (init.any() or wait.any() or arrive.any() or sync.any()):
        return

    # the engine clips barrier ids to [0, n_barriers) (engine/step.py
    # jnp.clip), so an out-of-range id silently ALIASES another barrier
    # — corrupting counts the per-id analysis below models as distinct
    any_bar = init | wait | arrive | sync
    ids = aux0[any_bar]
    bad = ids < 0
    if n_barriers is not None:
        bad = bad | (ids >= n_barriers)
    if bad.any():
        vals = sorted({int(v) for v in ids[bad]})[:8]
        hi = f", {n_barriers})" if n_barriers is not None else ")"
        out.append(TraceFinding(
            SEV_ERROR, "barrier",
            f"{int(bad.sum())} barrier record(s) use id(s) {vals} "
            f"outside [0{hi} — the engine clips ids, silently aliasing "
            f"another barrier",
            data={"ids": vals}))
        return

    counts: dict = {}
    for bar, cnt in zip(aux0[init].tolist(), aux1[init].tolist()):
        counts.setdefault(int(bar), set()).add(int(cnt))

    used = {}
    for kind, mask in (("WAIT", wait), ("ARRIVE", arrive),
                       ("SYNC", sync)):
        for bar in aux0[mask].tolist():
            used.setdefault(int(bar), {"WAIT": 0, "ARRIVE": 0,
                                       "SYNC": 0})[kind] += 1
    # highest release generation any BARRIER_SYNC rendezvouses with
    # (engine/step.py: sync #g blocks until barrier_gen[bar] >= g, and
    # barrier_gen advances only when arrivals reach the count)
    max_sync_gen: dict = {}
    for bar, gen in zip(aux0[sync].tolist(), aux1[sync].tolist()):
        bar, gen = int(bar), int(gen)
        max_sync_gen[bar] = max(max_sync_gen.get(bar, 0), gen)

    uninit = sorted(set(used) - set(counts))
    if uninit:
        out.append(TraceFinding(
            SEV_ERROR, "barrier",
            f"barrier id(s) {uninit[:8]} are waited on but never "
            f"BARRIER_INIT'd",
            data={"ids": uninit[:8]}))
    for bar, cs in sorted(counts.items()):
        if len(cs) > 1:
            out.append(TraceFinding(
                SEV_ERROR, "barrier",
                f"barrier {bar} is INIT'd with inconsistent participant "
                f"counts {sorted(cs)}",
                data={"id": bar, "counts": sorted(cs)}))
            continue
        cnt = next(iter(cs))
        if not 1 <= cnt <= T:
            out.append(TraceFinding(
                SEV_ERROR, "barrier",
                f"barrier {bar} participant count {cnt} outside "
                f"[1, {T}]",
                data={"id": bar, "count": cnt}))
            continue
        u = used.get(bar, {"WAIT": 0, "ARRIVE": 0, "SYNC": 0})
        arrivals = u["WAIT"] + u["ARRIVE"]
        # a SYNC targeting a generation beyond what the arrivals can
        # ever release blocks forever (releases = arrivals // count)
        releases = arrivals // cnt
        want_gen = max_sync_gen.get(bar, 0)
        if want_gen > releases:
            out.append(TraceFinding(
                SEV_ERROR, "barrier",
                f"barrier {bar}: a BARRIER_SYNC waits for release "
                f"generation {want_gen} but {arrivals} arrival(s) at "
                f"participant count {cnt} release only {releases} "
                f"generation(s) — guaranteed deadlock",
                data={"id": bar, "generation": want_gen,
                      "releases": releases, "arrivals": arrivals,
                      "count": cnt}))
        if arrivals % cnt == 0:
            continue
        if u["ARRIVE"] == 0 and u["SYNC"] == 0:
            # pure blocking WAITs: the last generation can never reach
            # the participant count — every straggler hangs
            out.append(TraceFinding(
                SEV_ERROR, "barrier",
                f"barrier {bar}: {arrivals} BARRIER_WAITs with "
                f"participant count {cnt} ({arrivals % cnt} stranded "
                f"in the final generation) — guaranteed deadlock",
                data={"id": bar, "arrivals": arrivals, "count": cnt}))
        else:
            out.append(TraceFinding(
                SEV_WARNING, "barrier",
                f"barrier {bar}: {arrivals} arrivals "
                f"(WAIT+ARRIVE) are not a multiple of participant "
                f"count {cnt} — the final generation never releases; "
                f"deadlocks if any WAIT/SYNC lands in it",
                data={"id": bar, "arrivals": arrivals, "count": cnt}))


def _check_dvfs(batch, out, n_domains=None):
    """DVFS_SET/DVFS_GET static checks.  aux0 is the domain index (the
    engine clips it, so an out-of-range domain silently retunes another
    one — same aliasing hazard as barrier ids); DVFS_SET's aux1 encodes
    the frequency in MHz, negated for HOLD-voltage requests, so only
    aux1 == 0 (no frequency at all) is statically malformed — positive
    out-of-table frequencies are a RUNTIME rejection the engine counts
    in `dvfs.errors`."""
    op, aux0, aux1 = batch.op, batch.aux0, batch.aux1
    dset = op == int(Op.DVFS_SET)
    dget = op == int(Op.DVFS_GET)
    if not (dset.any() or dget.any()):
        return
    any_d = dset | dget
    doms = aux0[any_d]
    bad = doms < 0
    if n_domains is not None:
        bad = bad | (doms >= n_domains)
    if bad.any():
        vals = sorted({int(v) for v in doms[bad]})[:8]
        hi = f", {n_domains})" if n_domains is not None else ")"
        out.append(TraceFinding(
            SEV_ERROR, "dvfs",
            f"{int(bad.sum())} DVFS record(s) name domain(s) {vals} "
            f"outside [0{hi} — the engine clips domain indices, "
            f"silently retuning another domain",
            data={"domains": vals}))
    zero = aux1[dset] == 0
    if zero.any():
        out.append(TraceFinding(
            SEV_ERROR, "dvfs",
            f"{int(zero.sum())} DVFS_SET record(s) request frequency 0 "
            f"— a retune must name a positive MHz value (negated for "
            f"HOLD)",
            data={"count": int(zero.sum())}))


def validate_batch(batch, *, raise_on_error: bool = True,
                   n_barriers: "int | None" = None,
                   n_domains: "int | None" = None,
                   ) -> "list[TraceFinding]":
    """Static validation of one TraceBatch; returns all findings.

    With `raise_on_error` (the default), error-severity findings raise
    `TraceValidationError` naming the first problem; warnings never
    raise.  `n_barriers` (the Simulator's barrier-table size, default
    64) tightens the barrier-id range check; negative ids are rejected
    unconditionally (the engine clips ids, so out-of-range ones alias
    another barrier).  `n_domains` (the config's DVFS domain count)
    likewise tightens the DVFS domain-index check."""
    out: "list[TraceFinding]" = []
    _check_op_range(batch, out)
    _check_send_recv(batch, out)
    _check_barriers(batch, out, n_barriers)
    _check_dvfs(batch, out, n_domains)
    errors = [f for f in out if f.severity == SEV_ERROR]
    if errors and raise_on_error:
        more = f" (+{len(errors) - 1} more)" if len(errors) > 1 else ""
        raise TraceValidationError(
            f"trace validation failed: {errors[0].message}{more}",
            findings=out)
    return out
