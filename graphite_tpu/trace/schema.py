"""Trace record schema: the instruction/event stream consumed by the engine.

The op space unifies two reference concepts:
 - `InstructionType` (`common/tile/core/instruction.h:20-43`): the static
   instruction classes whose costs come from
   `[core/static_instruction_costs]` (`carbon_sim.cfg:189-200`), plus the
   dynamic classes (recv/sync/spawn/stall, `instruction.h:149-198`);
 - the user-API calls that Pin's routine replacement intercepts
   (`pin/routine_replace.cc:37-101`): CAPI send/recv (`capi.h:18-24`),
   mutex/cond/barrier (`sync_api.h:19-34`), thread spawn/join
   (`thread_support.h:66-71`), DVFS get/set (`dvfs.h:42-48`), model toggles
   (`performance_counter_support.h:8-9`).

Record layout (struct-of-arrays, leading axes [n_tiles, T]):

    op        uint8   opcode (Op enum below)
    flags     uint8   bit0-1: mem-op slot valid; bit2-3: slot is-write;
                      bit4: branch taken; bit5: atomic
    pc        uint32  instruction address (icache + branch predictor index)
    addr0/1   uint32  memory operand addresses (slot 0 / slot 1)
    size0/1   uint8   memory operand sizes in bytes
    aux0      int32   partner tile / sync-object id / dvfs domain
    aux1      int32   message size / barrier count / frequency (MHz)
    dyn_ps    int64   dynamic-instruction cost in ps (Op.SPAWN: absolute time)

32 bytes per record; a 1024-tile x 1M-instruction trace is 32 GB streamed in
windows, or generated on device.  Memory operands are pre-split at cache-line
boundaries by producers (the reference splits in
`core.cc:140-267 initiateMemoryAccess`).
"""

from __future__ import annotations

import dataclasses
import enum

import numpy as np


MAX_MEM_OPS = 2  # matches Pin operand scan (`pin/instruction_modeling.cc:33-124`)

# flags bits
FLAG_MEM0_VALID = 1 << 0
FLAG_MEM1_VALID = 1 << 1
FLAG_MEM0_WRITE = 1 << 2
FLAG_MEM1_WRITE = 1 << 3
FLAG_BRANCH_TAKEN = 1 << 4
FLAG_ATOMIC = 1 << 5
# Self-checking-test hook (the engine's analog of the reference unit tests'
# assert-based checking, e.g. `tests/unit/shared_mem_test1`): a load with
# FLAG_CHECK compares the loaded word against aux0 and bumps a global
# functional-error counter on mismatch.
FLAG_CHECK = 1 << 6
# MOV whose only memory operand is a single load (`Instruction::
# isSimpleMovMemoryLoad`): the iocoom model lets the next instruction issue
# at load-queue allocate time instead of load completion.
FLAG_SIMPLE_MOV_LOAD = 1 << 7


class Op(enum.IntEnum):
    """Unified opcode space.

    0-19 mirror `InstructionType` (`instruction.h:20-43`) in order, so the
    static-cost table indexes directly.  32+ are user-API events.
    """

    GENERIC = 0
    MOV = 1
    IALU = 2
    IMUL = 3
    IDIV = 4
    FALU = 5
    FMUL = 6
    FDIV = 7
    XMM_SS = 8
    XMM_SD = 9
    XMM_PS = 10
    BRANCH = 11
    LFENCE = 12
    SFENCE = 13
    MFENCE = 14
    DYNAMIC_MISC = 15
    RECV = 16
    SYNC = 17
    SPAWN = 18
    STALL = 19
    # --- user-API events (L7 surface) ---
    SEND = 32          # CAPI_message_send_w:   aux0=dest tile, aux1=bytes
    NET_RECV = 33      # CAPI_message_receive_w: aux0=sender tile, aux1=bytes
    MUTEX_INIT = 34    # aux0=mutex id
    MUTEX_LOCK = 35    # aux0=mutex id
    MUTEX_UNLOCK = 36  # aux0=mutex id
    COND_INIT = 37     # aux0=cond id
    COND_WAIT = 38     # aux0=cond id, aux1=mutex id
    COND_SIGNAL = 39   # aux0=cond id
    COND_BROADCAST = 40  # aux0=cond id
    BARRIER_INIT = 41  # aux0=barrier id, aux1=count
    BARRIER_WAIT = 42  # aux0=barrier id
    THREAD_SPAWN = 43  # aux0=target tile
    THREAD_JOIN = 44   # aux0=target tile
    THREAD_EXIT = 45   # end of this tile's stream
    ENABLE_MODELS = 46
    DISABLE_MODELS = 47
    DVFS_SET = 48      # aux0=domain, aux1=frequency in MHz
    DVFS_GET = 49      # aux0=domain
    # Compressed straight-line run: aux0 = instruction count, aux1 = total
    # cycles (sum of per-instruction static costs).  The TPU-native analog
    # of Pin's basic-block granularity (`pin/instruction_modeling.cc`
    # instruments per-INS but the cost algebra over a run of static-cost
    # instructions is associative, so one record carries the whole run —
    # cycle-identical at fixed frequency when icache modeling is off, and
    # DVFS changes only occur at DVFS_SET records, never inside a run).
    # With icache modeling ON, a BBLOCK pays ONE icache fetch for its first
    # line (record pc) rather than per-line fetches — a documented
    # block-granularity approximation.  No memory operands, branches, or
    # events inside a run.
    BBLOCK = 50
    # Syscall rerouted to the central SyscallServer on the MCP tile
    # (`syscall_model.cc:132-244` marshals to MCP; `syscall_server.cc`
    # executes): aux0 = syscall class (SYS_* below), aux1 = arg (bytes).
    # Functional execution happens host-side (system/syscall_server.py);
    # replay charges the SYSTEM-network round trip to the MCP.
    SYSCALL = 51
    # --- co-located-thread sync forms (the live frontend's split ops) ---
    # Threads sharing a tile serialize onto ONE engine lane; a blocking
    # record whose resolution lies LATER on the same lane would deadlock
    # the replay.  The live frontend therefore splits blocking sync into a
    # non-blocking contribution at call time and a rendezvous at functional
    # completion time (recorded after the thread is rescheduled, hence
    # after any co-located segments that ran meanwhile):
    BARRIER_ARRIVE = 52  # aux0=barrier id: count the arrival, don't block
    BARRIER_SYNC = 53    # aux0=id, aux1=generation: wait for release #gen
    COND_JOIN = 54       # aux0=cond id, aux1=signal seq: wait for it, take
    #                      its time (pairs with MUTEX_UNLOCK at wait start
    #                      + MUTEX_LOCK re-acquire after)
    NOP = 255          # padding past THREAD_EXIT


# Syscall classes marshalled to the MCP SyscallServer (the reference
# handles ~25 in `syscall_model.cc:132-244`; ids here are internal).
SYS_OPEN = 0
SYS_CLOSE = 1
SYS_READ = 2
SYS_WRITE = 3
SYS_LSEEK = 4
SYS_ACCESS = 5
SYS_UNLINK = 6
SYS_STAT = 7
SYS_BRK = 8
SYS_MMAP = 9
SYS_MUNMAP = 10
SYS_FUTEX = 11
SYS_GETPID = 12
SYS_OTHER = 13


N_STATIC_INSTRUCTION_TYPES = 20  # MAX_INSTRUCTION_COUNT (`instruction.h:42`)

STATIC_COST_KEYS = (
    # `INSTRUCTION_NAMES` (`instruction.h:45-46`); costs read from
    # core/static_instruction_costs/<name> with default 0
    # (`core_model.cc:65-76`).
    "generic", "mov", "ialu", "imul", "idiv", "falu", "fmul", "fdiv",
    "xmm_ss", "xmm_sd", "xmm_ps", "branch", "lfence", "sfence", "mfence",
    "dynamic_misc", "recv", "sync", "spawn", "stall",
)

NO_REG = 0xFFFF  # sentinel: operand slot unused

_FIELDS = (
    ("op", np.uint8),
    ("flags", np.uint8),
    ("pc", np.uint32),
    ("addr0", np.uint32),
    ("addr1", np.uint32),
    ("size0", np.uint8),
    ("size1", np.uint8),
    ("aux0", np.int32),
    ("aux1", np.int32),
    ("dyn_ps", np.int64),
    # register operands (iocoom scoreboard; `instruction.h` RegisterOperand
    # lists, bounded to 2 reads + 1 write per record).  NO_REG = unused.
    ("rreg0", np.uint16),
    ("rreg1", np.uint16),
    ("wreg", np.uint16),
)


@dataclasses.dataclass
class TraceBatch:
    """A padded batch of per-tile traces, shape [n_tiles, length] per field."""

    op: np.ndarray
    flags: np.ndarray
    pc: np.ndarray
    addr0: np.ndarray
    addr1: np.ndarray
    size0: np.ndarray
    size1: np.ndarray
    aux0: np.ndarray
    aux1: np.ndarray
    dyn_ps: np.ndarray
    rreg0: np.ndarray
    rreg1: np.ndarray
    wreg: np.ndarray

    @property
    def n_tiles(self) -> int:
        return self.op.shape[0]

    @property
    def length(self) -> int:
        return self.op.shape[1]

    def save(self, path: str) -> None:
        from graphite_tpu.trace.io import save_trace_npz

        save_trace_npz(path, self)

    @classmethod
    def load(cls, path: str) -> "TraceBatch":
        from graphite_tpu.trace.io import load_trace_npz

        return load_trace_npz(path)

    @classmethod
    def from_builders(cls, builders: "list[TraceBuilder]") -> "TraceBatch":
        """Pad per-tile streams to a common length with THREAD_EXIT + NOP."""
        for b in builders:
            if not b._op or b._op[-1] != Op.THREAD_EXIT:
                b.exit()
        length = max(len(b._op) for b in builders)
        n = len(builders)
        arrays = {
            name: np.zeros((n, length), dtype=dtype) for name, dtype in _FIELDS
        }
        arrays["op"][:] = int(Op.NOP)
        for reg_field in ("rreg0", "rreg1", "wreg"):
            arrays[reg_field][:] = NO_REG
        for t, b in enumerate(builders):
            for name, _ in _FIELDS:
                col = getattr(b, "_" + name)
                arrays[name][t, : len(col)] = col
        return cls(**arrays)


class TraceBuilder:
    """Append-records-for-one-tile helper used by generators and tests."""

    def __init__(self) -> None:
        for name, _ in _FIELDS:
            setattr(self, "_" + name, [])

    def _append(self, op, flags=0, pc=0, addr0=0, addr1=0, size0=0, size1=0,
                aux0=0, aux1=0, dyn_ps=0, rreg0=NO_REG, rreg1=NO_REG,
                wreg=NO_REG) -> "TraceBuilder":
        self._op.append(int(op))
        self._flags.append(flags)
        self._pc.append(pc)
        self._addr0.append(addr0)
        self._addr1.append(addr1)
        self._size0.append(size0)
        self._size1.append(size1)
        self._aux0.append(aux0)
        self._aux1.append(aux1)
        self._dyn_ps.append(dyn_ps)
        self._rreg0.append(rreg0)
        self._rreg1.append(rreg1)
        self._wreg.append(wreg)
        return self

    # --- instructions ----------------------------------------------------

    def instr(self, op: Op, pc: int = 0, rregs=(), wreg: int = NO_REG,
              ) -> "TraceBuilder":
        """A compute instruction with no memory operands."""
        rr = tuple(rregs) + (NO_REG, NO_REG)
        return self._append(op, pc=pc, rreg0=rr[0], rreg1=rr[1], wreg=wreg)

    def load(self, addr: int, size: int = 4, pc: int = 0,
             op: Op = Op.MOV, rregs=(), wreg: int = NO_REG,
             ) -> "TraceBuilder":
        flags = FLAG_MEM0_VALID
        if op == Op.MOV:
            flags |= FLAG_SIMPLE_MOV_LOAD
        rr = tuple(rregs) + (NO_REG, NO_REG)
        return self._append(op, flags=flags, pc=pc,
                            addr0=addr, size0=size,
                            rreg0=rr[0], rreg1=rr[1], wreg=wreg)

    def store(self, addr: int, size: int = 4, pc: int = 0,
              op: Op = Op.MOV, rregs=(), wreg: int = NO_REG,
              ) -> "TraceBuilder":
        rr = tuple(rregs) + (NO_REG, NO_REG)
        return self._append(op, flags=FLAG_MEM0_VALID | FLAG_MEM0_WRITE,
                            pc=pc, addr0=addr, size0=size,
                            rreg0=rr[0], rreg1=rr[1], wreg=wreg)

    def store_value(self, addr: int, value: int, size: int = 4, pc: int = 0,
                    op: Op = Op.MOV) -> "TraceBuilder":
        """Store with a functional value (engine writes `value` to the word)."""
        return self._append(op, flags=FLAG_MEM0_VALID | FLAG_MEM0_WRITE,
                            pc=pc, addr0=addr, size0=size, aux0=value)

    def load_check(self, addr: int, expect: int, size: int = 4,
                   pc: int = 0, op: Op = Op.MOV) -> "TraceBuilder":
        """Self-checking load: bumps the functional-error counter unless the
        loaded word equals `expect` (FLAG_CHECK)."""
        flags = FLAG_MEM0_VALID | FLAG_CHECK
        if op == Op.MOV:
            flags |= FLAG_SIMPLE_MOV_LOAD
        return self._append(op, flags=flags, pc=pc,
                            addr0=addr, size0=size, aux0=expect)

    def load_store(self, raddr: int, waddr: int, size: int = 4,
                   pc: int = 0, op: Op = Op.GENERIC) -> "TraceBuilder":
        flags = (FLAG_MEM0_VALID | FLAG_MEM1_VALID | FLAG_MEM1_WRITE)
        return self._append(op, flags=flags, pc=pc, addr0=raddr,
                            addr1=waddr, size0=size, size1=size)

    def bblock(self, n_instr: int, cycles: int, pc: int = 0) -> "TraceBuilder":
        """A compressed run of `n_instr` straight-line instructions costing
        `cycles` total (Op.BBLOCK)."""
        return self._append(Op.BBLOCK, pc=pc, aux0=n_instr, aux1=cycles)

    def branch(self, taken: bool, pc: int = 0) -> "TraceBuilder":
        flags = FLAG_BRANCH_TAKEN if taken else 0
        return self._append(Op.BRANCH, flags=flags, pc=pc)

    def dynamic(self, op: Op, cost_ps: int) -> "TraceBuilder":
        return self._append(op, dyn_ps=cost_ps)

    # --- user-API events -------------------------------------------------

    def send(self, dest: int, size: int = 8) -> "TraceBuilder":
        return self._append(Op.SEND, aux0=dest, aux1=size)

    def recv(self, sender: int, size: int = 8) -> "TraceBuilder":
        return self._append(Op.NET_RECV, aux0=sender, aux1=size)

    def mutex_init(self, mux: int) -> "TraceBuilder":
        return self._append(Op.MUTEX_INIT, aux0=mux)

    def mutex_lock(self, mux: int) -> "TraceBuilder":
        return self._append(Op.MUTEX_LOCK, aux0=mux)

    def mutex_unlock(self, mux: int) -> "TraceBuilder":
        return self._append(Op.MUTEX_UNLOCK, aux0=mux)

    def cond_init(self, cond: int) -> "TraceBuilder":
        return self._append(Op.COND_INIT, aux0=cond)

    def cond_wait(self, cond: int, mux: int) -> "TraceBuilder":
        return self._append(Op.COND_WAIT, aux0=cond, aux1=mux)

    def cond_signal(self, cond: int, publish: bool = False) -> "TraceBuilder":
        # publish=True: the live frontend's sequence-published form (bumps
        # the cond's signal counter for COND_JOIN waiters)
        return self._append(Op.COND_SIGNAL, aux0=cond,
                            aux1=1 if publish else 0)

    def cond_broadcast(self, cond: int,
                       publish: bool = False) -> "TraceBuilder":
        return self._append(Op.COND_BROADCAST, aux0=cond,
                            aux1=1 if publish else 0)

    def cond_join(self, cond: int, seq: int) -> "TraceBuilder":
        return self._append(Op.COND_JOIN, aux0=cond, aux1=seq)

    def barrier_arrive(self, bar: int) -> "TraceBuilder":
        return self._append(Op.BARRIER_ARRIVE, aux0=bar)

    def barrier_sync(self, bar: int, generation: int) -> "TraceBuilder":
        return self._append(Op.BARRIER_SYNC, aux0=bar, aux1=generation)

    def barrier_init(self, bar: int, count: int) -> "TraceBuilder":
        return self._append(Op.BARRIER_INIT, aux0=bar, aux1=count)

    def barrier_wait(self, bar: int) -> "TraceBuilder":
        return self._append(Op.BARRIER_WAIT, aux0=bar)

    def thread_spawn(self, target_tile: int) -> "TraceBuilder":
        return self._append(Op.THREAD_SPAWN, aux0=target_tile)

    def thread_join(self, target_tile: int) -> "TraceBuilder":
        return self._append(Op.THREAD_JOIN, aux0=target_tile)

    def exit(self) -> "TraceBuilder":
        return self._append(Op.THREAD_EXIT)

    def syscall(self, sc_class: int, arg: int = 0) -> "TraceBuilder":
        return self._append(Op.SYSCALL, aux0=sc_class, aux1=arg)

    def dvfs_set(self, domain: int, freq_mhz: int,
                 hold: bool = False) -> "TraceBuilder":
        """Retune a DVFS domain; hold=True keeps the current voltage
        (fails if the frequency exceeds its maximum — `dvfs.h` HOLD)."""
        return self._append(Op.DVFS_SET, aux0=domain,
                            aux1=-freq_mhz if hold else freq_mhz)
