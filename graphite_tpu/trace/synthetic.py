"""Synthetic trace generators — the NoC/memory stress frontends.

Reproduces the reference's synthetic benchmark generators as trace producers:
 - traffic patterns from `tests/benchmarks/synthetic_network/
   synthetic_network.cc:16-25,215-341`: uniform_random (LCG permutation
   matrix), bit_complement, shuffle, transpose, tornado, nearest_neighbor;
 - a synthetic memory-stress generator (`tests/benchmarks/synthetic_memory`):
   random/strided load/store streams over a configurable working set;
 - a ping-pong CAPI latency microbenchmark (`tests/apps/ping_pong`);
 - a generic compute-mix generator for core-model unit tests.
"""

from __future__ import annotations

import numpy as np

from graphite_tpu.models.network_emesh import is_tile_count_permissible, mesh_dims
from graphite_tpu.trace.schema import Op, TraceBatch, TraceBuilder

TRAFFIC_PATTERNS = (
    "uniform_random",
    "bit_complement",
    "shuffle",
    "transpose",
    "tornado",
    "nearest_neighbor",
)


def _mesh_dims(n_tiles: int) -> tuple[int, int]:
    # same factorization as the NoC models (`network_emesh.py`), asserted
    # like the reference generator (`synthetic_network.cc:344-349`)
    assert is_tile_count_permissible(n_tiles), \
        "synthetic mesh patterns need w*h tile counts"
    return mesh_dims(n_tiles)


def uniform_random_matrix(n_tiles: int) -> np.ndarray:
    """The reference's LCG permutation schedule, reproduced exactly.

    `synthetic_network.cc:235-286`: send_matrix[slot][sender] with
    send_matrix[0][0] = n/2, row-chained seed send_matrix[i][0] =
    send_matrix[i-1][1], recurrence s[i][j] = (13*s[i][j-1] + 5) % n.
    Every row and every column is a permutation of 0..n-1 (asserted, as in
    the reference).  Returns [n_slots=n_tiles, n_senders=n_tiles].
    """
    n = n_tiles
    send = np.zeros((n, n), dtype=np.int32)
    send[0][0] = n // 2
    for i in range(n):
        if i != 0:
            send[i][0] = send[i - 1][1]
        for j in range(1, n):
            send[i][j] = (13 * send[i][j - 1] + 5) % n
    for i in range(n):
        assert sorted(send[i]) == list(range(n)), "row not a permutation"
    for j in range(n):
        assert sorted(send[:, j]) == list(range(n)), "column not a permutation"
    return send


def destinations(pattern: str, n_tiles: int) -> np.ndarray:
    """Per-tile destination schedule, shape [n_slots, n_tiles].

    Deterministic patterns have one slot; uniform_random has n_tiles slots
    (`synthetic_network.cc:281-286`).
    """
    tile = np.arange(n_tiles, dtype=np.int32)
    if pattern == "uniform_random":
        return uniform_random_matrix(n_tiles)
    if pattern == "bit_complement":
        # `synthetic_network.cc:288-295`
        assert n_tiles & (n_tiles - 1) == 0, "bit_complement needs power of 2"
        return (~tile & (n_tiles - 1))[None, :]
    if pattern == "shuffle":
        # `synthetic_network.cc:297-305`
        assert n_tiles & (n_tiles - 1) == 0, "shuffle needs power of 2"
        nbits = n_tiles.bit_length() - 1
        return (((tile >> (nbits - 1)) & 1) | ((tile << 1) & (n_tiles - 1)))[None, :]
    w, h = _mesh_dims(n_tiles)
    sx, sy = tile % w, tile // w
    if pattern == "transpose":
        # `synthetic_network.cc:307-317`: (x,y) -> (y,x)
        return (sx * w + sy)[None, :]
    if pattern == "tornado":
        # `synthetic_network.cc:319-329`
        return (((sy + h // 2) % h) * w + ((sx + w // 2) % w))[None, :]
    if pattern == "nearest_neighbor":
        # `synthetic_network.cc:331-341`
        return (((sy + 1) % h) * w + ((sx + 1) % w))[None, :]
    raise ValueError(f"unknown traffic pattern: {pattern}")


def network_traffic_trace(
    n_tiles: int,
    pattern: str = "uniform_random",
    total_packets: int = 100,
    packet_size: int = 8,
    offered_load: float = 0.1,
    seed: int = 0,
) -> TraceBatch:
    """The synthetic_network benchmark as a trace program.

    Mirrors `sendNetworkTraffic` (`synthetic_network.cc:136-213`): each tile
    sends `total_packets` packets following the pattern schedule and receives
    the packets addressed to it; injection is Bernoulli(offered_load) per
    cycle, modeled as STALL records between sends (the reference advances
    `time` one cycle per loop iteration).  Receives are appended after sends
    (the reference drains receives with an outstanding window; ordering
    within a tile does not affect network timing because receives do not
    inject traffic).
    """
    dest = destinations(pattern, n_tiles)
    n_slots = dest.shape[0]
    rng = np.random.default_rng(seed)
    builders = [TraceBuilder() for _ in range(n_tiles)]

    # Precompute per-tile inter-send gaps (geometric with p=offered_load).
    for t in range(n_tiles):
        b = builders[t]
        for k in range(total_packets):
            if offered_load < 1.0:
                gap = int(rng.geometric(offered_load)) - 1
                if gap > 0:
                    # STALL cost accounted in ps at 1 GHz nominal; the engine
                    # rescales by tile frequency at replay.
                    b.dynamic(Op.STALL, cost_ps=gap * 1000)
            b.send(int(dest[k % n_slots][t]), packet_size)
        # Receive the packets addressed to this tile: one per slot from the
        # sender whose dest[slot] == t.
        recv_from = np.argwhere(dest == t)
        reps = total_packets // n_slots + (1 if total_packets % n_slots else 0)
        count = 0
        for rep in range(reps):
            for slot, sender in recv_from:
                if count >= total_packets:
                    break
                if (slot + rep * n_slots) < total_packets or n_slots == 1:
                    b.recv(int(sender), packet_size)
                    count += 1
        while count < total_packets:  # deterministic patterns: 1 sender
            b.recv(int(recv_from[0][1]), packet_size)
            count += 1
    return TraceBatch.from_builders(builders)


def memory_stress_trace(
    n_tiles: int,
    n_accesses: int = 1000,
    working_set_bytes: int = 1 << 20,
    write_fraction: float = 0.3,
    stride: int | None = None,
    shared_fraction: float = 0.0,
    cache_line_size: int = 64,
    seed: int = 0,
) -> TraceBatch:
    """Random/strided load-store streams (synthetic_memory analog).

    Each tile touches a private working set based at tile*working_set plus an
    optional shared region (for coherence stress).  Addresses are cache-line
    aligned +offset, never crossing a line.
    """
    rng = np.random.default_rng(seed)
    builders = []
    shared_base = (n_tiles + 1) * working_set_bytes
    for t in range(n_tiles):
        b = TraceBuilder()
        base = t * working_set_bytes
        for i in range(n_accesses):
            if stride is not None:
                offset = (i * stride) % working_set_bytes
            else:
                offset = int(rng.integers(0, working_set_bytes // 8)) * 8
            if shared_fraction > 0 and rng.random() < shared_fraction:
                addr = shared_base + offset % (working_set_bytes // 4)
            else:
                addr = base + offset
            addr -= addr % 8  # keep within one line
            if rng.random() < write_fraction:
                b.store(addr, 8, pc=0x1000 + (i % 256) * 4)
            else:
                b.load(addr, 8, pc=0x1000 + (i % 256) * 4)
        builders.append(b)
    return TraceBatch.from_builders(builders)


def ping_pong_trace(
    n_tiles: int = 2, n_rounds: int = 100, packet_size: int = 8
) -> TraceBatch:
    """tests/apps/ping_pong: tile 0 and 1 bounce a message back and forth."""
    assert n_tiles >= 2
    builders = [TraceBuilder() for _ in range(n_tiles)]
    for r in range(n_rounds):
        builders[0].send(1, packet_size)
        builders[0].recv(1, packet_size)
        builders[1].recv(0, packet_size)
        builders[1].send(0, packet_size)
    return TraceBatch.from_builders(builders)


def _batch_from_columns(op, *, flags=None, pc=None, aux0=None, aux1=None,
                        dyn_ps=None) -> TraceBatch:
    """Assemble a TraceBatch from [n_tiles, L] numpy columns (fast path)."""
    n, L = op.shape
    # append THREAD_EXIT column
    op = np.concatenate(
        [op, np.full((n, 1), int(Op.THREAD_EXIT), np.uint8)], axis=1
    )

    def pad(col, dtype, fill=0):
        if col is None:
            return np.full((n, L + 1), fill, dtype)
        return np.concatenate([col.astype(dtype),
                               np.full((n, 1), fill, dtype)], axis=1)

    from graphite_tpu.trace.schema import NO_REG

    return TraceBatch(
        op=op.astype(np.uint8),
        flags=pad(flags, np.uint8),
        pc=pad(pc, np.uint32),
        addr0=pad(None, np.uint32),
        addr1=pad(None, np.uint32),
        size0=pad(None, np.uint8),
        size1=pad(None, np.uint8),
        aux0=pad(aux0, np.int32),
        aux1=pad(aux1, np.int32),
        dyn_ps=pad(dyn_ps, np.int64),
        rreg0=pad(None, np.uint16, NO_REG),
        rreg1=pad(None, np.uint16, NO_REG),
        wreg=pad(None, np.uint16, NO_REG),
    )


def compute_mix_batch(
    n_tiles: int, n_instructions: int, seed: int = 0, branch_fraction: float = 0.1
) -> TraceBatch:
    """Vectorized large-scale compute mix (no per-record Python loop).

    The benchmark-scale analog of compute_mix_trace: ialu/mov/fmul/falu +
    branches with random outcomes.
    """
    rng = np.random.default_rng(seed)
    pool = np.array([int(Op.IALU), int(Op.MOV), int(Op.FMUL), int(Op.FALU)],
                    np.uint8)
    op = rng.choice(pool, size=(n_tiles, n_instructions))
    is_branch = rng.random((n_tiles, n_instructions)) < branch_fraction
    op = np.where(is_branch, np.uint8(int(Op.BRANCH)), op)
    taken = rng.random((n_tiles, n_instructions)) < 0.5
    from graphite_tpu.trace.schema import FLAG_BRANCH_TAKEN

    flags = np.where(is_branch & taken, np.uint8(FLAG_BRANCH_TAKEN), np.uint8(0))
    pc = (0x400000 + 4 * (np.arange(n_instructions, dtype=np.uint32) % 4096))[
        None, :
    ].repeat(n_tiles, axis=0)
    return _batch_from_columns(op, flags=flags, pc=pc)


def message_ring_batch(
    n_tiles: int,
    n_rounds: int,
    compute_per_round: int = 16,
    packet_size: int = 8,
    pattern: str = "nearest_neighbor",
    seed: int = 0,
    compressed: bool = False,
    cycles_per_instr: int = 1,
) -> TraceBatch:
    """Vectorized compute+communicate workload (the bench kernel).

    Each round: `compute_per_round` ialu instructions, one send following
    the traffic pattern, one receive (from whichever sender targets this
    tile) — a trace-program reduction of the synthetic_network send/recv
    loop (`synthetic_network.cc:136-213`).

    With `compressed=True` the per-round compute run is emitted as a single
    Op.BBLOCK record (aux0=count, aux1=count*cycles_per_instr) — identical
    simulated timing when the ialu static cost equals `cycles_per_instr`,
    at basic-block replay granularity.
    """
    dest = destinations(pattern, n_tiles)  # [n_slots, n_tiles]
    n_slots = dest.shape[0]
    # inverse: for slot s, sender[t] = who sends to t
    senders = np.empty_like(dest)
    for s in range(n_slots):
        senders[s, dest[s]] = np.arange(n_tiles, dtype=dest.dtype)

    n_compute_recs = 1 if compressed else compute_per_round
    L_round = n_compute_recs + 2
    L = n_rounds * L_round
    op = np.full((n_tiles, L), int(Op.IALU), np.uint8)
    aux0 = np.zeros((n_tiles, L), np.int32)
    aux1 = np.zeros((n_tiles, L), np.int32)
    send_cols = np.arange(n_rounds) * L_round + n_compute_recs
    recv_cols = send_cols + 1
    rounds = np.arange(n_rounds)
    if compressed:
        bblock_cols = np.arange(n_rounds) * L_round
        op[:, bblock_cols] = int(Op.BBLOCK)
        aux0[:, bblock_cols] = compute_per_round
        aux1[:, bblock_cols] = compute_per_round * cycles_per_instr
    op[:, send_cols] = int(Op.SEND)
    op[:, recv_cols] = int(Op.NET_RECV)
    aux0[:, send_cols] = dest[rounds % n_slots].T          # [n_tiles, n_rounds]
    aux0[:, recv_cols] = senders[rounds % n_slots].T
    aux1[:, send_cols] = packet_size
    aux1[:, recv_cols] = packet_size
    return _batch_from_columns(op, aux0=aux0, aux1=aux1)


def compute_mix_trace(
    n_tiles: int,
    n_instructions: int = 1000,
    mix: dict[Op, float] | None = None,
    seed: int = 0,
) -> TraceBatch:
    """A pure-compute instruction mix for core-model unit tests."""
    if mix is None:
        mix = {Op.IALU: 0.4, Op.MOV: 0.3, Op.FMUL: 0.1, Op.FALU: 0.1,
               Op.BRANCH: 0.1}
    ops = np.array([int(o) for o in mix], dtype=np.int32)
    probs = np.array(list(mix.values()))
    probs = probs / probs.sum()
    rng = np.random.default_rng(seed)
    builders = []
    for t in range(n_tiles):
        b = TraceBuilder()
        choices = rng.choice(ops, size=n_instructions, p=probs)
        takens = rng.random(n_instructions) < 0.5
        for i, op in enumerate(choices):
            pc = 0x400000 + 4 * i
            if op == int(Op.BRANCH):
                b.branch(bool(takens[i]), pc=pc)
            else:
                b.instr(Op(int(op)), pc=pc)
        builders.append(b)
    return TraceBatch.from_builders(builders)
