"""SPLASH-2-style benchmark trace programs.

The reference's benchmark tier runs the SPLASH-2 suite under Pin
(`tests/benchmarks/Makefile:4`; FFT/RADIX are the BASELINE.json graduated
configs) plus synthetic traffic generators.  On the TPU frontend the
benchmarks are *algorithmic trace programs*: each generator reproduces the
computation/communication/synchronization skeleton of the app — phase
structure, message pattern, per-phase instruction mix, memory footprint —
as per-tile trace streams replayed through the full timing stack.

Kernels:
 - fft:           radix-sqrt(N) six-step FFT — local butterflies + 3
                  all-to-all transposes + barriers (SPLASH-2 `kernels/fft`)
 - radix:         parallel radix sort — histogram, tree prefix-sum,
                  permutation all-to-all (SPLASH-2 `kernels/radix`)
 - blackscholes:  embarrassingly parallel option pricing, one barrier per
                  sweep (PARSEC `blackscholes`)
 - canneal:       random-access element swaps over a large footprint with
                  accept/reject branches (PARSEC `canneal`)

Per-instruction costs ride the `[core/static_instruction_costs]` table;
instruction *mixes* below (falu/fmul vs ialu ratios, loads per element)
follow the kernels' inner loops, not measured counts — documented
approximations, tunable per config.
"""

from __future__ import annotations

import numpy as np

from graphite_tpu.trace.schema import Op, TraceBatch, TraceBuilder

# All generators use barrier id 0 (one barrier per app run, reused).
_BAR = 0


def _all_to_all_phase(builders, n_tiles, bytes_per_msg):
    """Tile t sends one message to every other tile, then receives one from
    every other tile — the transpose/permutation skeleton.  Staggered start
    offsets avoid every tile hammering tile 0 first."""
    for t, b in enumerate(builders):
        for i in range(1, n_tiles):
            b.send((t + i) % n_tiles, bytes_per_msg)
        for i in range(1, n_tiles):
            b.recv((t - i) % n_tiles, bytes_per_msg)


def _barrier(builders):
    for b in builders:
        b.barrier_wait(_BAR)


def fft_trace(n_tiles: int, points_per_tile: int = 256,
              use_memory: bool = False,
              ops_per_point_per_stage: int = 6) -> TraceBatch:
    """Six-step FFT: transpose, column FFTs, twiddle, transpose, row FFTs,
    transpose (SPLASH-2 fft.C structure).

    Butterfly cost CALIBRATED against a real captured execution
    (`tools/capture_fft.py` — an actual parallel radix-2 FFT recorded
    instruction-by-instruction under the Carbon API): measured 10 fp ops
    per BUTTERFLY (4 FMUL + 6 FALU: complex twiddle mul + add/sub) plus
    ~2.3 integer index ops, i.e. ~5 fp + ~1.1 int = ~6 ops per POINT per
    log2 stage.  The pre-calibration guess of 10 per point per stage
    over-counted compute 1.7x (deltas recorded in PERF.md
    "Trace-capture calibration").

    The default (no-memory) form is built as vectorized [T, L] numpy
    columns — the per-record Python-append path is O(T^2) at 1024 tiles
    (6M+ appends) and would dominate bench startup."""
    stages = max(1, int(np.log2(max(2, points_per_tile))))
    fly_instr = points_per_tile * stages * ops_per_point_per_stage
    msg_bytes = max(8, (points_per_tile // max(1, n_tiles)) * 16)
    if use_memory:
        return _fft_trace_with_memory(n_tiles, points_per_tile, fly_instr,
                                      msg_bytes)

    from graphite_tpu.trace.synthetic import _batch_from_columns

    T = n_tiles
    t = np.arange(T, dtype=np.int64)[:, None]
    i = np.arange(1, T, dtype=np.int64)[None, :]

    def col(op, aux0, aux1):
        return (np.full((T, 1), int(op), np.uint8),
                np.broadcast_to(np.asarray(aux0, np.int64), (T, 1)),
                np.full((T, 1), aux1, np.int64))

    ops, a0s, a1s = [], [], []

    def emit(op_block, aux0_block, aux1_block):
        ops.append(op_block)
        a0s.append(aux0_block)
        a1s.append(aux1_block)

    # BARRIER_INIT on every tile: idempotent count set, zero cost
    emit(*col(Op.BARRIER_INIT, np.zeros((T, 1)), T))
    a2a_send = (np.full((T, T - 1), int(Op.SEND), np.uint8),
                (t + i) % T, np.full((T, T - 1), msg_bytes, np.int64))
    a2a_recv = (np.full((T, T - 1), int(Op.NET_RECV), np.uint8),
                (t - i) % T, np.full((T, T - 1), msg_bytes, np.int64))
    for phase in range(3):  # the three transposes bracket two FFT passes
        emit(*col(Op.BARRIER_WAIT, np.zeros((T, 1)), 0))
        emit(*a2a_send)
        emit(*a2a_recv)
        if phase < 2:
            emit(*col(Op.BBLOCK, np.full((T, 1), fly_instr), fly_instr))
    emit(*col(Op.BARRIER_WAIT, np.zeros((T, 1)), 0))
    return _batch_from_columns(
        np.concatenate(ops, axis=1),
        aux0=np.concatenate(a0s, axis=1),
        aux1=np.concatenate(a1s, axis=1),
    )


def _fft_trace_with_memory(n_tiles, points_per_tile, fly_instr, msg_bytes):
    builders = [TraceBuilder() for _ in range(n_tiles)]
    builders[0].barrier_init(_BAR, n_tiles)
    for phase in range(3):
        _barrier(builders)
        _all_to_all_phase(builders, n_tiles, msg_bytes)
        if phase < 2:
            for t, b in enumerate(builders):
                base = (t * points_per_tile) * 64
                for j in range(min(points_per_tile, 32)):
                    b.load(base + j * 64)
                b.bblock(fly_instr, fly_instr)  # 1-IPC fp pipeline
    _barrier(builders)
    return TraceBatch.from_builders(builders)


def radix_trace(n_tiles: int, keys_per_tile: int = 1024,
                radix: int = 16) -> TraceBatch:
    """Radix sort iteration: local histogram, log-tree prefix sum
    (point-to-point up/down sweeps), permutation all-to-all (SPLASH-2
    radix.C structure).

    Per-key costs CALIBRATED against a real captured execution
    (`tools/capture.py radix` — an actual parallel LSD radix sort
    recorded instruction-by-instruction under the Carbon API, validated
    against numpy's sort and replayed with FLAG_CHECK): measured 7.04
    records per key per digit pass — ~2.0 in the histogram phase (key
    load + digit extract), ~0.3 in the rank phase, ~4.1 in the
    permutation (key load, digit extract, address arithmetic, ranked
    store).  The pre-calibration guess of 4 histogram ops per key and
    ZERO permutation compute undercounted 1.7x (deltas in PERF.md
    "Trace-capture calibration")."""
    builders = [TraceBuilder() for _ in range(n_tiles)]
    builders[0].barrier_init(_BAR, n_tiles)
    digits = max(1, 32 // max(1, int(np.log2(radix))))
    for d in range(min(digits, 4)):
        # histogram: measured ~2 records per key + per-digit bookkeeping
        for b in builders:
            b.bblock(keys_per_tile * 2 + radix, keys_per_tile * 2 + radix)
        _barrier(builders)
        # tree prefix-sum: up-sweep + down-sweep over log2(T) rounds
        levels = max(1, int(np.log2(max(2, n_tiles))))
        for lvl in range(levels):
            stride = 1 << lvl
            for t, b in enumerate(builders):
                if (t % (stride * 2)) == 0 and t + stride < n_tiles:
                    b.recv(t + stride, radix * 4)
                elif (t % (stride * 2)) == stride:
                    b.send(t - stride, radix * 4)
            for b in builders:
                b.bblock(radix, radix)
        for lvl in reversed(range(levels)):
            stride = 1 << lvl
            for t, b in enumerate(builders):
                if (t % (stride * 2)) == 0 and t + stride < n_tiles:
                    b.send(t + stride, radix * 4)
                elif (t % (stride * 2)) == stride:
                    b.recv(t - stride, radix * 4)
        _barrier(builders)
        # permutation: measured ~4.1 records per key (load, digit
        # extract, address arithmetic, ranked store) alongside the
        # all-to-all key exchange
        for b in builders:
            b.bblock(keys_per_tile * 4, keys_per_tile * 4)
        _all_to_all_phase(builders, n_tiles,
                          max(8, keys_per_tile * 4 // max(1, n_tiles)))
        _barrier(builders)
    return TraceBatch.from_builders(builders)


def blackscholes_trace(n_tiles: int, options_per_tile: int = 512,
                       sweeps: int = 4) -> TraceBatch:
    """Embarrassingly parallel pricing: ~200 fp ops per option (CNDF +
    exp/log/sqrt approximations), one barrier per sweep (PARSEC
    blackscholes.c bs_thread loop)."""
    builders = [TraceBuilder() for _ in range(n_tiles)]
    builders[0].barrier_init(_BAR, n_tiles)
    per_sweep = options_per_tile * 200
    for s in range(sweeps):
        for b in builders:
            b.bblock(per_sweep, per_sweep)
        _barrier(builders)
    return TraceBatch.from_builders(builders)


def canneal_trace(n_tiles: int, footprint_lines: int = 4096,
                  swaps_per_tile: int = 64, seed: int = 1234,
                  use_memory: bool = True) -> TraceBatch:
    """Simulated-annealing element swaps: random-access loads over a large
    shared footprint (cache-hostile), ~60 int/fp ops to evaluate each swap,
    a taken/not-taken accept branch, and occasional stores (PARSEC canneal
    netlist swap loop)."""
    rng = np.random.default_rng(seed)
    builders = [TraceBuilder() for _ in range(n_tiles)]
    builders[0].barrier_init(_BAR, n_tiles)
    for t, b in enumerate(builders):
        for s in range(swaps_per_tile):
            if use_memory:
                a1 = int(rng.integers(footprint_lines)) * 64
                a2 = int(rng.integers(footprint_lines)) * 64
                b.load(a1)
                b.load(a2)
            b.bblock(60, 60)
            b.branch(bool(rng.integers(2)), pc=s & 0x3FF)
            if use_memory and rng.random() < 0.3:
                b.store(int(rng.integers(footprint_lines)) * 64)
    _barrier(builders)
    return TraceBatch.from_builders(builders)


BENCHMARKS = {
    "fft": fft_trace,
    "radix": radix_trace,
    "blackscholes": blackscholes_trace,
    "canneal": canneal_trace,
}


def lu_trace(n_tiles: int, blocks_per_side: int | None = None,
             block: int = 16, use_memory: bool = False) -> TraceBatch:
    """Blocked dense LU factorization (SPLASH-2 `kernels/lu/lu.C`):
    block-cyclic ownership; step k factorizes the diagonal block
    (~B^3/3 fp), updates the k-th row/column perimeter blocks (~B^3),
    then the interior trailing submatrix (~2B^3 per block), with a
    barrier between the three sub-phases (lu.C OneSolve loop).  With
    use_memory, perimeter/interior owners load the diagonal block's
    lines — the read-sharing the shared-memory original exhibits.

    fp structure VALIDATED against a real captured execution
    (`tools/capture.py lu` — an actual blocked fixed-point LU recorded
    under the Carbon API, L@U reconstruction error 7e-5): the capture
    measured 21,408 fp records where this model charges 21,160 for the
    same (n=32, B=8, 4-tile) run — within 1.2%, so the per-phase B^3
    coefficients stand (PERF.md "Trace-capture calibration")."""
    if blocks_per_side is None:
        blocks_per_side = max(2, int(np.sqrt(n_tiles)))
    N = blocks_per_side
    fp3 = block * block * block
    builders = [TraceBuilder() for _ in range(n_tiles)]
    builders[0].barrier_init(_BAR, n_tiles)

    def owner(i, j):
        return (i * N + j) % n_tiles

    for k in range(N):
        diag = owner(k, k)
        builders[diag].bblock(fp3 // 3, fp3 // 3)
        _barrier(builders)
        diag_base = (k * N + k) * block * block * 8
        for j in range(k + 1, N):
            for (bi, bj) in ((k, j), (j, k)):
                t = owner(bi, bj)
                if use_memory:
                    for ln in range(min(block, 8)):
                        builders[t].load(diag_base + ln * 64)
                builders[t].bblock(fp3, fp3)
        _barrier(builders)
        for i in range(k + 1, N):
            for j in range(k + 1, N):
                builders[owner(i, j)].bblock(2 * fp3, 2 * fp3)
        _barrier(builders)
    return TraceBatch.from_builders(builders)


def ocean_trace(n_tiles: int, rows_per_tile: int = 64, cols: int = 64,
                iterations: int = 4) -> TraceBatch:
    """Ocean current simulation (SPLASH-2 `apps/ocean`): red-black
    Gauss-Seidel relaxation over a partitioned grid — each iteration a
    ~7-fp-op 5-point stencil sweep over the tile's rows, boundary-row
    exchange with the up/down neighbors, and a barrier (ocean's
    relax/jacobcalc loops)."""
    builders = [TraceBuilder() for _ in range(n_tiles)]
    builders[0].barrier_init(_BAR, n_tiles)
    sweep = rows_per_tile * cols * 7
    row_bytes = cols * 8
    for it in range(iterations):
        for t, b in enumerate(builders):
            b.bblock(sweep, sweep)
        # boundary exchange: down then up (edge tiles skip the absent side)
        for t, b in enumerate(builders):
            if t + 1 < n_tiles:
                b.send(t + 1, row_bytes)
            if t > 0:
                b.send(t - 1, row_bytes)
        for t, b in enumerate(builders):
            if t > 0:
                b.recv(t - 1, row_bytes)
            if t + 1 < n_tiles:
                b.recv(t + 1, row_bytes)
        _barrier(builders)
    return TraceBatch.from_builders(builders)


def barnes_trace(n_tiles: int, bodies_per_tile: int = 64,
                 steps: int = 2, seed: int = 7,
                 use_memory: bool = False) -> TraceBatch:
    """Barnes-Hut N-body (SPLASH-2 `apps/barnes`): per timestep a
    tree-build phase (integer-heavy, irregular — maketree) behind a
    barrier, then force computation per body (~log N cell visits x ~20 fp
    ops — hackgrav) with irregular loads over the shared tree, then a
    position update sweep (grav.C/code.C stepsystem structure)."""
    rng = np.random.default_rng(seed)
    builders = [TraceBuilder() for _ in range(n_tiles)]
    builders[0].barrier_init(_BAR, n_tiles)
    logn = max(1, int(np.log2(max(2, n_tiles * bodies_per_tile))))
    for s in range(steps):
        for b in builders:
            b.bblock(bodies_per_tile * 8, bodies_per_tile * 8)  # maketree
        _barrier(builders)
        for t, b in enumerate(builders):
            for body in range(min(bodies_per_tile, 16)):
                if use_memory:
                    # ~logn tree-cell touches over a shared footprint
                    for v in range(min(logn, 4)):
                        b.load(int(rng.integers(1 << 14)) * 64)
                b.bblock(logn * 20, logn * 20)
            rem = bodies_per_tile - min(bodies_per_tile, 16)
            if rem > 0:
                b.bblock(rem * logn * 20, rem * logn * 20)
        _barrier(builders)
        for b in builders:
            b.bblock(bodies_per_tile * 6, bodies_per_tile * 6)  # advance
        _barrier(builders)
    return TraceBatch.from_builders(builders)


def water_nsquared_trace(n_tiles: int, molecules_per_tile: int = 32,
                         steps: int = 2) -> TraceBatch:
    """Water-NSquared molecular dynamics (SPLASH-2
    `apps/water-nsquared`): per timestep intra-molecule force updates,
    the O(n^2/2) inter-molecule pair sweep (~250 fp ops per pair —
    interf), and a mutex-protected global virial/energy accumulation
    (water.C mdmain loop; the global sum uses a lock in the original)."""
    builders = [TraceBuilder() for _ in range(n_tiles)]
    builders[0].barrier_init(_BAR, n_tiles)
    builders[0].mutex_init(0)
    _barrier(builders)
    n_total = molecules_per_tile * n_tiles
    pairs = molecules_per_tile * max(1, n_total // 2) // 64
    for s in range(steps):
        for b in builders:
            b.bblock(molecules_per_tile * 40, molecules_per_tile * 40)
        _barrier(builders)
        for b in builders:
            b.bblock(pairs * 250, pairs * 250)
        for b in builders:
            b.mutex_lock(0)
            b.bblock(20, 20)
            b.mutex_unlock(0)
        _barrier(builders)
    return TraceBatch.from_builders(builders)


def cholesky_trace(n_tiles: int, supernodes: int | None = None,
                   block: int = 16) -> TraceBatch:
    """Sparse Cholesky factorization (SPLASH-2 `kernels/cholesky`):
    supernode task queue — each supernode's owner factorizes it
    (~B^3/3 fp) and sends updates to the owners of affected later
    supernodes (task-queue puts), which fold them in (~B^2 fp per
    update).  The skeleton serializes dependency chains with
    point-to-point messages instead of the original's task-queue locks."""
    if supernodes is None:
        supernodes = max(4, n_tiles // 2)
    fp3 = block * block * block
    fp2 = block * block
    builders = [TraceBuilder() for _ in range(n_tiles)]
    builders[0].barrier_init(_BAR, n_tiles)
    for sn in range(supernodes):
        t = sn % n_tiles
        builders[t].bblock(fp3 // 3, fp3 // 3)
        # updates fan out to the next up-to-3 supernodes' owners
        targets = [(sn + d) % supernodes for d in (1, 2, 3)
                   if sn + d < supernodes]
        for d in targets:
            to = d % n_tiles
            if to != t:
                builders[t].send(to, fp2 * 8)
        for d in targets:
            to = d % n_tiles
            if to != t:
                builders[to].recv(t, fp2 * 8)
                builders[to].bblock(fp2 * 4, fp2 * 4)
    _barrier(builders)
    return TraceBatch.from_builders(builders)


BENCHMARKS.update({
    "lu": lu_trace,
    "ocean": ocean_trace,
    "barnes": barnes_trace,
    "water-nsquared": water_nsquared_trace,
    "cholesky": cholesky_trace,
})


def water_spatial_trace(n_tiles: int, molecules_per_tile: int = 32,
                        steps: int = 2) -> TraceBatch:
    """Water-Spatial molecular dynamics (SPLASH-2 `apps/water-spatial`):
    the O(n) spatial variant of water — molecules live in 3D cells, each
    tile owns a cell block; per timestep: intra-molecule updates, pair
    forces against molecules in NEIGHBORING cells only (~250 fp ops per
    pair, half the 26-neighborhood by Newton's 3rd law — here the mesh
    neighbor ring carries the boundary-molecule exchange), and the same
    mutex-protected global virial accumulation as water-nsquared
    (water-spatial's interf/bndry loops)."""
    builders = [TraceBuilder() for _ in range(n_tiles)]
    builders[0].barrier_init(_BAR, n_tiles)
    builders[0].mutex_init(0)
    _barrier(builders)
    # neighbor pairs only: O(molecules * local density), not O(n^2)
    pairs = molecules_per_tile * 8
    boundary_bytes = max(8, molecules_per_tile // 4 * 72)  # 9 doubles/mol
    for s in range(steps):
        for b in builders:
            b.bblock(molecules_per_tile * 40, molecules_per_tile * 40)
        # boundary-cell molecule exchange with the ±1 mesh neighbors
        for t, b in enumerate(builders):
            b.send((t + 1) % n_tiles, boundary_bytes)
            b.send((t - 1) % n_tiles, boundary_bytes)
        for t, b in enumerate(builders):
            b.recv((t - 1) % n_tiles, boundary_bytes)
            b.recv((t + 1) % n_tiles, boundary_bytes)
        for b in builders:
            b.bblock(pairs * 250, pairs * 250)
        for b in builders:
            b.mutex_lock(0)
            b.bblock(20, 20)
            b.mutex_unlock(0)
        _barrier(builders)
    return TraceBatch.from_builders(builders)


def volrend_trace(n_tiles: int, rays_per_tile: int = 128,
                  frames: int = 2, seed: int = 21,
                  use_memory: bool = False) -> TraceBatch:
    """Volume rendering (SPLASH-2 `apps/volrend`): per frame each tile
    ray-casts its image block — ~30 fp ops per sample, with early
    termination modeled by drawing an adaptive length (4–16 samples) for
    each of the first 16 rays; the remaining rays are lumped into one
    block at the 10-sample average (keeps trace records bounded), and
    irregular loads over the shared volume when use_memory; frames end
    at a barrier after a mutex-protected image merge (volrend's
    render/ray loops + the task-queue lock)."""
    rng = np.random.default_rng(seed)
    builders = [TraceBuilder() for _ in range(n_tiles)]
    builders[0].barrier_init(_BAR, n_tiles)
    builders[0].mutex_init(0)
    _barrier(builders)
    for f in range(frames):
        for t, b in enumerate(builders):
            lens = rng.integers(4, 17, size=min(rays_per_tile, 16))
            for ray, ln in enumerate(lens):
                if use_memory:
                    b.load(int(rng.integers(1 << 14)) * 64)
                b.bblock(int(ln) * 30, int(ln) * 30)
            rem = rays_per_tile - len(lens)
            if rem > 0:
                b.bblock(rem * 10 * 30, rem * 10 * 30)
        for b in builders:
            b.mutex_lock(0)
            b.bblock(16, 16)
            b.mutex_unlock(0)
        _barrier(builders)
    return TraceBatch.from_builders(builders)


def raytrace_trace(n_tiles: int, rays_per_tile: int = 128,
                   seed: int = 33, use_memory: bool = False) -> TraceBatch:
    """Ray tracing (SPLASH-2 `apps/raytrace`): a single frame of primary
    rays over image tiles — per ray a BSP-tree walk (~log-depth cell
    visits x ~40 fp intersection ops); tree depth (2–8) is drawn for
    each of the first 16 rays to model the irregular secondary-ray
    fan-out, the remaining rays lumped into one block at the depth-5
    average (keeps trace records bounded), with irregular
    shared-geometry loads; work stealing (raytrace's GetJobs/PutJobs)
    is modeled as a mutex-protected queue touch every 32 modeled rays —
    with the 16-ray cap that is one touch per tile, the lumped
    remainder carrying none."""
    rng = np.random.default_rng(seed)
    builders = [TraceBuilder() for _ in range(n_tiles)]
    builders[0].barrier_init(_BAR, n_tiles)
    builders[0].mutex_init(0)
    _barrier(builders)
    for t, b in enumerate(builders):
        depths = rng.integers(2, 9, size=min(rays_per_tile, 16))
        for ray, d in enumerate(depths):
            if ray % 32 == 0:
                b.mutex_lock(0)
                b.bblock(10, 10)
                b.mutex_unlock(0)
            if use_memory:
                b.load(int(rng.integers(1 << 14)) * 64)
            b.bblock(int(d) * 40, int(d) * 40)
        rem = rays_per_tile - len(depths)
        if rem > 0:
            b.bblock(rem * 5 * 40, rem * 5 * 40)
    _barrier(builders)
    return TraceBatch.from_builders(builders)


def radiosity_trace(n_tiles: int, patches_per_tile: int = 32,
                    iterations: int = 2, seed: int = 55) -> TraceBatch:
    """Hierarchical radiosity (SPLASH-2 `apps/radiosity`): per iteration
    each tile refines its patch interactions — ~60 fp ops per form-factor
    + visibility test, patch counts drawn per tile for the strong load
    imbalance the original exhibits — then distributes energy updates to
    other patch owners (task-queue puts, modeled as point-to-point sends
    to a random owner) behind a mutex; iterations end at a barrier
    (radiosity's process_tasks loop)."""
    rng = np.random.default_rng(seed)
    builders = [TraceBuilder() for _ in range(n_tiles)]
    builders[0].barrier_init(_BAR, n_tiles)
    builders[0].mutex_init(0)
    _barrier(builders)
    for it in range(iterations):
        counts = rng.integers(patches_per_tile // 2,
                              patches_per_tile * 2, size=n_tiles)
        tgt = [int(rng.integers(n_tiles)) for _ in range(n_tiles)]
        for t, b in enumerate(builders):
            b.bblock(int(counts[t]) * 60, int(counts[t]) * 60)
            b.mutex_lock(0)
            b.bblock(12, 12)
            b.mutex_unlock(0)
        # energy pushes: one update message to a random other owner,
        # mirrored receives keep the rendezvous deterministic
        for t, b in enumerate(builders):
            dst = tgt[t] if tgt[t] != t else (t + 1) % n_tiles
            b.send(dst, 64)
        recv_from = [[] for _ in range(n_tiles)]
        for t in range(n_tiles):
            dst = tgt[t] if tgt[t] != t else (t + 1) % n_tiles
            recv_from[dst].append(t)
        for t, b in enumerate(builders):
            for src in recv_from[t]:
                b.recv(src, 64)
            b.bblock(len(recv_from[t]) * 20 + 1, len(recv_from[t]) * 20 + 1)
        _barrier(builders)
    return TraceBatch.from_builders(builders)


def fmm_trace(n_tiles: int, bodies_per_tile: int = 64,
              multipole_terms: int = 4) -> TraceBatch:
    """Fast Multipole Method N-body (SPLASH-2 `apps/fmm`): per step —
    tree build (integer-heavy) | barrier | upward pass (multipole
    moments, ~p^2 fp per cell) | interaction lists: each cell's V-list
    multipole-to-local translations (~p^4 fp per interaction, exchanged
    with mesh-neighbor owners) | downward pass + near-field direct
    O(bodies x neighbors) | barrier (fmm's steps in interactions.C /
    construct_grid)."""
    p2 = multipole_terms * multipole_terms
    p4 = p2 * p2
    builders = [TraceBuilder() for _ in range(n_tiles)]
    builders[0].barrier_init(_BAR, n_tiles)
    cells = max(1, bodies_per_tile // 8)
    for b in builders:
        b.bblock(bodies_per_tile * 10, bodies_per_tile * 10)  # tree build
    _barrier(builders)
    for b in builders:
        b.bblock(cells * p2, cells * p2)                      # upward
    _barrier(builders)
    # V-list exchange: moments to/from the ±1, ±2 mesh neighbors
    mom_bytes = p2 * 16
    for off in (1, 2):
        for t, b in enumerate(builders):
            b.send((t + off) % n_tiles, mom_bytes)
        for t, b in enumerate(builders):
            b.recv((t - off) % n_tiles, mom_bytes)
    for b in builders:
        b.bblock(cells * 8 * p4, cells * 8 * p4)              # M2L
    _barrier(builders)
    near = bodies_per_tile * 9 * 20
    for b in builders:
        b.bblock(cells * p2 + near, cells * p2 + near)        # down + near
    _barrier(builders)
    return TraceBatch.from_builders(builders)


BENCHMARKS.update({
    "water-spatial": water_spatial_trace,
    "volrend": volrend_trace,
    "raytrace": raytrace_trace,
    "radiosity": radiosity_trace,
    "fmm": fmm_trace,
})
