"""Trace layer — the frontend of the TPU simulator.

Graphite's Pin frontend (`pin/`) executes x86 binaries and feeds decoded
instructions + memory references + thread/sync events into the timing models
(`pin/instruction_modeling.cc:13-21`, `pin/routine_replace.cc:37-101`).  On
TPU hosts Pin is out of scope; the frontend is a *trace producer*: programs
are recorded (or synthesized) as fixed-layout micro-op streams, streamed
host→HBM, and replayed through the full timing stack.  A trace record carries
exactly what the reference's Instruction + DynamicMemoryInfo +
DynamicBranchInfo + user-API calls carried.
"""

from graphite_tpu.trace.schema import (
    Op,
    TraceBatch,
    TraceBuilder,
    MAX_MEM_OPS,
)
from graphite_tpu.trace.validate import (
    TraceFinding,
    TraceValidationError,
    validate_batch,
)
from graphite_tpu.trace import synthetic

__all__ = ["Op", "TraceBatch", "TraceBuilder", "MAX_MEM_OPS", "synthetic",
           "TraceFinding", "TraceValidationError", "validate_batch"]
