"""External trace exchange: TraceBatch <-> .npz files.

The reference's frontend is Pin capturing a live binary
(`pin/instruction_modeling.cc`); on TPU hosts the frontend is a trace
producer, and this module is the ingestion point for traces captured by
ANY external tool (a Pin tool, QEMU plugin, DynamoRIO client, ...): dump
the record columns as numpy arrays in an .npz and replay them through
the full timing stack.

Format: one array per `TraceBatch` field (schema in `trace/schema.py`),
each shaped [n_tiles, length], plus a `schema_version` scalar.  Missing
optional fields default to zeros (e.g. a capture without register
dependencies still replays on the simple core model).  `op` is required.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from graphite_tpu.trace.schema import Op, TraceBatch

SCHEMA_VERSION = 1


def save_trace_npz(path: str, batch: TraceBatch) -> None:
    """Write a TraceBatch as a compressed .npz."""
    arrays = {
        f.name: getattr(batch, f.name) for f in dataclasses.fields(batch)
    }
    np.savez_compressed(path, schema_version=SCHEMA_VERSION, **arrays)


def load_trace_npz(path: str) -> TraceBatch:
    """Read an externally captured trace into a TraceBatch.

    Validates shape agreement and the op range; pads absent optional
    columns with zeros so minimal captures (op + flags + addresses)
    replay directly.
    """
    with np.load(path) as data:
        version = int(data["schema_version"]) if "schema_version" in data \
            else 1
        if version > SCHEMA_VERSION:
            raise ValueError(
                f"trace {path!r} has schema_version {version}; this build "
                f"reads <= {SCHEMA_VERSION}")
        if "op" not in data:
            raise ValueError(f"trace {path!r} has no 'op' array")
        op = np.asarray(data["op"], np.uint8)
        if op.ndim != 2:
            raise ValueError(f"'op' must be [n_tiles, length], got "
                             f"{op.shape}")
        known = {int(v) for v in Op}
        bad = set(np.unique(op).tolist()) - known
        if bad:
            raise ValueError(f"trace {path!r} contains unknown opcodes "
                             f"{sorted(bad)[:8]}")
        # schema dtypes (TraceBuilder's layout) — present fields are
        # coerced so mistyped external captures (float64 dyn_ps, int64
        # addresses...) cannot flow into the jitted engine
        dtypes = {
            "flags": np.uint8, "pc": np.uint32,
            "addr0": np.uint32, "addr1": np.uint32,
            "size0": np.uint8, "size1": np.uint8,
            "aux0": np.int32, "aux1": np.int32,
            "dyn_ps": np.int64,
            "rreg0": np.uint16, "rreg1": np.uint16,
            "wreg": np.uint16,
        }
        fields = {}
        for f in dataclasses.fields(TraceBatch):
            if f.name == "op":
                fields["op"] = op
                continue
            dtype = dtypes[f.name]
            if f.name in data:
                arr = np.asarray(data[f.name])
                if arr.shape != op.shape:
                    raise ValueError(
                        f"trace {path!r}: '{f.name}' shape {arr.shape} != "
                        f"op shape {op.shape}")
                if arr.dtype != dtype:
                    cast = arr.astype(dtype)
                    if not np.array_equal(
                            cast.astype(arr.dtype, copy=False), arr):
                        raise ValueError(
                            f"trace {path!r}: '{f.name}' values do not fit "
                            f"{np.dtype(dtype).name}")
                    arr = cast
            else:
                arr = np.zeros(op.shape, dtype)
            fields[f.name] = arr
        return TraceBatch(**fields)
