"""Power/area modeling (SURVEY §2.9): the McPAT/DSENT-equivalent layer.

Reference: two native C++ libraries (contrib/mcpat, contrib/dsent) wrapped
by `McPATCoreInterface` / `McPATCacheInterface`
(`common/mcpat/mcpat_core_interface.h:80-99`) and a DSENT interface
(`simulator.cc:93-104`), fed by per-model event counters and queried for
area + leakage + dynamic energy breakdowns.

Here the analytical models live in the native library
`native/energy/energy_model.cc` (built to libgraphite_energy.so, bound via
ctypes — pybind11 is not in the image), and this package provides the
interface classes that turn a SimResults' counters into the same
area/leakage/dynamic-energy breakdown structure, with per-voltage scaling
for DVFS (`mcpat_core_interface.h` per-voltage wrapper cache).
"""

from graphite_tpu.power.interface import (
    DSENTInterface,
    McPATCacheInterface,
    McPATCoreInterface,
    TileEnergyMonitor,
    load_native,
)

__all__ = [
    "DSENTInterface",
    "McPATCacheInterface",
    "McPATCoreInterface",
    "TileEnergyMonitor",
    "load_native",
]
