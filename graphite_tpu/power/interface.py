"""ctypes bindings + interface classes over the native energy library.

`McPATCoreInterface`/`McPATCacheInterface`/`DSENTInterface` mirror the
reference's wrappers (`common/mcpat/`, `simulator.cc:93-104`): constructed
per structure, queried per voltage (DVFS changes create new operating
points, like the reference's per-voltage wrapper cache), and fed event
counters to produce (area, leakage energy, dynamic energy) breakdowns.
`TileEnergyMonitor` aggregates them per tile over a run
(`common/tile/tile_energy_monitor.h:17-128`).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "lib", "libgraphite_energy.so")


class _SramOut(ctypes.Structure):
    _fields_ = [("area_mm2", ctypes.c_double),
                ("leakage_power_w", ctypes.c_double),
                ("read_energy_j", ctypes.c_double),
                ("write_energy_j", ctypes.c_double),
                ("tag_energy_j", ctypes.c_double)]


class _CoreOut(ctypes.Structure):
    _fields_ = [("area_mm2", ctypes.c_double),
                ("leakage_power_w", ctypes.c_double),
                ("ifu_energy_j", ctypes.c_double),
                ("decode_energy_j", ctypes.c_double),
                ("rf_energy_j", ctypes.c_double),
                ("ialu_energy_j", ctypes.c_double),
                ("fpu_energy_j", ctypes.c_double),
                ("mul_energy_j", ctypes.c_double),
                ("lsu_energy_j", ctypes.c_double),
                ("bypass_energy_j", ctypes.c_double),
                ("bpred_energy_j", ctypes.c_double)]


class _NocOut(ctypes.Structure):
    _fields_ = [("router_area_mm2", ctypes.c_double),
                ("router_leakage_w", ctypes.c_double),
                ("buffer_energy_j", ctypes.c_double),
                ("crossbar_energy_j", ctypes.c_double),
                ("arbiter_energy_j", ctypes.c_double),
                ("link_energy_j_per_mm", ctypes.c_double),
                ("link_leakage_w_per_mm", ctypes.c_double)]


_lib = None


def load_native() -> ctypes.CDLL:
    """Load (building if needed) the native energy library."""
    global _lib
    if _lib is not None:
        return _lib
    # always invoke make: the rule depends on the .cc, so an up-to-date
    # build is a no-op and source edits are never silently ignored
    proc = subprocess.run(["make", "-C", _NATIVE_DIR],
                          capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"native energy library build failed:\n{proc.stderr}")
    lib = ctypes.CDLL(_LIB_PATH)
    lib.sram_energy.argtypes = [
        ctypes.c_int, ctypes.c_double, ctypes.c_long, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.POINTER(_SramOut)]
    lib.core_energy.argtypes = [
        ctypes.c_int, ctypes.c_double, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.POINTER(_CoreOut)]
    lib.noc_energy.argtypes = [
        ctypes.c_int, ctypes.c_double, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.POINTER(_NocOut)]
    lib.dram_access_energy_j.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.dram_access_energy_j.restype = ctypes.c_double
    lib.energy_model_abi_version.restype = ctypes.c_int
    assert lib.energy_model_abi_version() == 1
    _lib = lib
    return lib


class McPATCacheInterface:
    """Per-cache-structure energy (`mcpat_cache_interface.h:22-72`)."""

    def __init__(self, node_nm: int, size_bytes: int, associativity: int,
                 line_bytes: int = 64, ports: int = 1, num_banks: int = 1):
        # num_banks mirrors the reference's only use of the knob — the
        # McPAT cache config (`mcpat_cache_interface.cc:226`): banked
        # arrays split the bitline/wordline energy per access.  Clamp the
        # bank count so each bank holds >= 1 KB (a physical SRAM macro
        # floor) instead of flooring the per-bank size: a small cache
        # configured with many banks would otherwise charge the 1 KB-array
        # energy num_banks times over and overestimate several-fold.
        num_banks = max(1, min(num_banks, size_bytes // 1024))
        self._args = (node_nm, max(size_bytes // num_banks, 1024),
                      associativity, line_bytes, ports)
        self._num_banks = num_banks
        self._cache: dict = {}   # per-voltage operating points

    def at_voltage(self, voltage: float) -> _SramOut:
        if voltage not in self._cache:
            node, size, assoc, line, ports = self._args
            out = _SramOut()
            load_native().sram_energy(node, voltage, size, assoc, line,
                                      ports, ctypes.byref(out))
            self._cache[voltage] = out
        return self._cache[voltage]

    def area_mm2(self, voltage: float = 1.0) -> float:
        return self.at_voltage(voltage).area_mm2 * self._num_banks

    def dynamic_energy_j(self, voltage: float, reads: int, writes: int,
                         tag_lookups: int = 0) -> float:
        o = self.at_voltage(voltage)
        return (reads * o.read_energy_j + writes * o.write_energy_j
                + tag_lookups * o.tag_energy_j)

    def leakage_energy_j(self, voltage: float, seconds: float) -> float:
        # all banks leak; dynamic energy is per-access in ONE bank
        return (self.at_voltage(voltage).leakage_power_w
                * self._num_banks * seconds)


class McPATCoreInterface:
    """Per-core energy with the IFU/LSU/EXU breakdown
    (`mcpat_core_interface.h:19-99`)."""

    def __init__(self, node_nm: int, issue_width: int = 1,
                 load_queue_entries: int = 8, store_queue_entries: int = 8):
        self._args = (node_nm, issue_width, load_queue_entries,
                      store_queue_entries)
        self._cache: dict = {}

    def at_voltage(self, voltage: float) -> _CoreOut:
        if voltage not in self._cache:
            node, w, lq, sq = self._args
            out = _CoreOut()
            load_native().core_energy(node, voltage, w, lq, sq,
                                      ctypes.byref(out))
            self._cache[voltage] = out
        return self._cache[voltage]

    def area_mm2(self, voltage: float = 1.0) -> float:
        return self.at_voltage(voltage).area_mm2

    def dynamic_energy_j(self, voltage: float, *, instructions: int,
                         int_ops: int = 0, fp_ops: int = 0,
                         mul_ops: int = 0, mem_ops: int = 0,
                         branches: int = 0, reg_reads: int = 0) -> float:
        """Event counters → energy (`updateEventCounters` + compute)."""
        o = self.at_voltage(voltage)
        return (
            instructions * (o.ifu_energy_j + o.decode_energy_j
                            + o.bypass_energy_j)
            + reg_reads * o.rf_energy_j
            + int_ops * o.ialu_energy_j
            + fp_ops * o.fpu_energy_j
            + mul_ops * o.mul_energy_j
            + mem_ops * o.lsu_energy_j
            + branches * o.bpred_energy_j
        )

    def leakage_energy_j(self, voltage: float, seconds: float) -> float:
        return self.at_voltage(voltage).leakage_power_w * seconds


class DSENTInterface:
    """NoC router+link energy (the contrib/dsent analog,
    `simulator.cc:93-99`)."""

    def __init__(self, node_nm: int, num_ports: int = 5,
                 flit_bits: int = 64, buffers_per_port: int = 4,
                 link_length_mm: float = 1.0):
        self._args = (node_nm, num_ports, flit_bits, buffers_per_port)
        self.link_length_mm = link_length_mm
        self._cache: dict = {}

    def at_voltage(self, voltage: float) -> _NocOut:
        if voltage not in self._cache:
            node, p, f, b = self._args
            out = _NocOut()
            load_native().noc_energy(node, voltage, p, f, b,
                                     ctypes.byref(out))
            self._cache[voltage] = out
        return self._cache[voltage]

    def router_dynamic_energy_j(self, voltage: float, flits: int) -> float:
        o = self.at_voltage(voltage)
        return flits * (o.buffer_energy_j + o.crossbar_energy_j
                        + o.arbiter_energy_j)

    def link_dynamic_energy_j(self, voltage: float, flit_hops: int) -> float:
        o = self.at_voltage(voltage)
        return flit_hops * o.link_energy_j_per_mm * self.link_length_mm

    def static_power_w(self, voltage: float) -> float:
        o = self.at_voltage(voltage)
        return (o.router_leakage_w
                + o.link_leakage_w_per_mm * self.link_length_mm)


class TileEnergyMonitor:
    """Aggregate per-tile energy over a run
    (`tile_energy_monitor.h:17-128`): core + caches + network dynamic
    energy from the run's counters, plus leakage over completion time."""

    def __init__(self, sim, results, node_nm: int | None = None):
        self.node_nm = node_nm or sim.config.technology_node
        self.sim = sim
        self.results = results
        mp = sim.params.mem
        line = mp.line_size if mp is not None else 64
        self.core_if = McPATCoreInterface(self.node_nm)
        self.l1i_if = self._cache_if(mp.l1i, line) if mp else None
        self.l1d_if = self._cache_if(mp.l1d, line) if mp else None
        self.l2_if = self._cache_if(mp.l2, line) if mp else None
        self.noc_if = DSENTInterface(self.node_nm)

    def _cache_if(self, lvl, line):
        return McPATCacheInterface(
            self.node_nm, lvl.num_sets * lvl.num_ways * line,
            lvl.num_ways, line, num_banks=lvl.num_banks)

    def tile_energy_j(self, tile: int, voltage: float = 1.0) -> dict:
        r = self.results
        seconds = r.clock_ps[tile] * 1e-12
        instr = int(r.instruction_count[tile])
        branches = int(r.bp_correct[tile] + r.bp_incorrect[tile])
        # split the instruction mix from the available counters: memory
        # ops from L1-D accesses, the remainder as integer ALU work
        mem_ops = 0
        if r.mem_counters is not None:
            mc = r.mem_counters
            mem_ops = int(mc["l1d_read_hits"][tile]
                          + mc["l1d_read_misses"][tile]
                          + mc["l1d_write_hits"][tile]
                          + mc["l1d_write_misses"][tile])
        int_ops = max(instr - mem_ops - branches, 0)
        core_dyn = self.core_if.dynamic_energy_j(
            voltage, instructions=instr, int_ops=int_ops,
            mem_ops=mem_ops, branches=branches)
        out = {
            "core_dynamic": core_dyn,
            "core_static": self.core_if.leakage_energy_j(voltage, seconds),
        }
        if r.mem_counters is not None and self.l1d_if is not None:
            mc = r.mem_counters
            out["l1i_dynamic"] = self.l1i_if.dynamic_energy_j(
                voltage, int(mc["l1i_hits"][tile]),
                0, int(mc["l1i_misses"][tile]))
            out["l1d_dynamic"] = self.l1d_if.dynamic_energy_j(
                voltage,
                int(mc["l1d_read_hits"][tile]),
                int(mc["l1d_write_hits"][tile]),
                int(mc["l1d_read_misses"][tile]
                    + mc["l1d_write_misses"][tile]))
            out["l2_dynamic"] = self.l2_if.dynamic_energy_j(
                voltage, int(mc["l2_hits"][tile]), 0,
                int(mc["l2_misses"][tile]))
            for lif, key in ((self.l1i_if, "l1i_static"),
                             (self.l1d_if, "l1d_static"),
                             (self.l2_if, "l2_static")):
                out[key] = lif.leakage_energy_j(voltage, seconds)
            dram_e = load_native().dram_access_energy_j(
                self.node_nm, self.sim.params.mem.line_size)
            out["dram_dynamic"] = dram_e * int(
                mc["dram_reads"][tile] + mc["dram_writes"][tile])
        # charged at the sender only (no double count across tiles);
        # single-flit per packet approximation — multi-hop/multi-flit
        # accounting needs the NoC model's per-hop counters
        flits = int(r.packets_sent[tile])
        out["network_dynamic"] = (
            self.noc_if.router_dynamic_energy_j(voltage, flits)
            + self.noc_if.link_dynamic_energy_j(voltage, flits))
        out["network_static"] = self.noc_if.static_power_w(voltage) * seconds
        out["total"] = sum(out.values())
        return out

    def output_summary(self) -> str:
        """Per-tile energy summary (`tile_energy_monitor` outputSummary)."""
        lines = ["Tile Energy Monitor Summary"]
        total = 0.0
        for t in range(self.results.n_tiles):
            e = self.tile_energy_j(t)
            total += e["total"]
            lines.append(f"  Tile {t}:")
            lines.append(f"    Total Energy (in J): {e['total']:.6e}")
            lines.append(
                "    Core Energy (in J): "
                f"{e['core_dynamic'] + e['core_static']:.6e}")
            if "l1d_dynamic" in e:
                cache_e = sum(v for k, v in e.items()
                              if k.startswith(("l1", "l2")))
                lines.append(f"    Cache Energy (in J): {cache_e:.6e}")
            lines.append(
                "    Network Energy (in J): "
                f"{e['network_dynamic'] + e['network_static']:.6e}")
        lines.append(f"  Total Energy (in J): {total:.6e}")
        return "\n".join(lines)
