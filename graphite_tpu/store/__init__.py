"""Persistent AOT program store: fingerprint-keyed on-disk executables
shared across a service fleet (see store/store.py)."""

from graphite_tpu.store.store import (       # noqa: F401
    ProgramStore, REASONS, StoreError, StoreIntegrityError, StoreKey,
)
