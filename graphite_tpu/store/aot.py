"""JAX AOT executable codec for the persistent program store.

The store's payloads are real compiled executables, not lowerings:
`jax.experimental.serialize_executable` pickles a `jax.stages.Compiled`
(the XLA executable plus its calling convention) and loads it back
WITHOUT retracing or recompiling — the whole point of the store is that
a warm-started fleet pays deserialize seconds, never compile seconds.

The input/output pytree definitions ride inside the payload (they
pickle alongside the executable), so a payload is self-contained: the
loader needs only the bytes plus an import of `graphite_tpu` (which
registers the custom pytree nodes the trees reference).

Two caveats this module owns:

 - **Executables are environment-bound.**  A serialized executable is
   only valid on the jax/jaxlib version, backend platform, and device
   topology it was compiled for — `runtime_env()` is that identity
   tuple, and it is part of the store key AND re-verified at load, so a
   drifted environment reads as a clean miss (or a quarantined entry),
   never a crash deep inside the runtime.
 - **Payloads are pickle.**  Deserializing executes pickle, so a store
   directory must be as trusted as the code itself (the same trust a
   shared XLA compilation cache already requires).  The integrity layer
   (sha256 checksums, store/store.py) protects against corruption, not
   against a malicious writer.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import pickle

# bumped whenever the payload tuple layout changes — an old payload
# under a new reader is an integrity error, not a crash
PAYLOAD_FORMAT = "graphite-aot-payload-v1"


def runtime_env() -> "tuple[str, str, str, str, int]":
    """The environment identity a serialized executable is bound to:
    (jax version, jaxlib version, backend platform, device KIND,
    device count).  The kind axis keys a heterogeneous fleet apart:
    two accelerator generations report the same backend string
    ("tpu", "gpu") but compile incompatible XLA targets — without it
    they would share one entry and quarantine each other's healthy
    executables in a recompile ping-pong."""
    import jax
    import jaxlib

    devs = jax.devices()
    kind = devs[0].device_kind if devs else "?"
    return (jax.__version__, jaxlib.__version__, jax.default_backend(),
            str(kind), jax.device_count())


@contextlib.contextmanager
def _fresh_codegen():
    """Bypass the JAX persistent compilation cache for one compile.

    A `.compile()` served from the persistent cache returns an
    executable DESERIALIZED from the cache payload — and re-serializing
    a deserialized XLA:CPU executable silently drops the object code
    its kernels live in, so the store would publish a payload that dies
    at load with "Symbols not found".  Only a cold compile (real
    codegen) captures every kernel symbol; `jax_compilation_cache_dir
    = None` is the authoritative off-switch (measured: with the cache
    dir unset the payload is byte-stable and loads every time; with it
    set, every warm compile produces a short unloadable payload).  The
    program store subsumes the role the XLA cache played for these
    programs anyway — one deliberate cold compile per FLEET beats a
    warm compile that cannot be shared."""
    import jax

    old = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", old)


# monotonically unique per-process AOT compile names (see
# aot_compile_runner: identical HLO must not dedup against resident
# executables, or the serialized artifact loses their object code)
_aot_counter = itertools.count()


def aot_compile_runner(runner, max_quanta: int):
    """AOT-compile a `SweepRunner`'s batched campaign function against
    its REAL device inputs (aval-exact, so the compiled executable
    accepts exactly the arrays `run()` passes) and inject it as the
    runner's executable.  Returns the `jax.stages.Compiled` — callable
    and serializable, bit-identical to the lazy `jax.jit` path (same
    lowering, same XLA optimization pipeline).

    Two measures keep the executable FULLY serializable (both measured
    necessary, see `_fresh_codegen` and the store README section):
    the persistent-cache bypass, and a process-unique function name.
    The name defeats in-memory dedup against identical already-resident
    executables — a deduped compile returns an executable whose
    serialization omits the object code the resident copy already
    carries, poisoning any process that later compiles a program it
    previously loaded (quarantine-refill, multi-class services).  The
    name only enters the HLO module label: the canonical jaxpr
    fingerprint (`analysis/identity`) and the numerics are invariant
    under it (test-pinned)."""
    import jax

    fn = runner._runner_fn(max_quanta)

    def campaign(states, dtr, knobs):
        return fn(states, dtr, knobs)

    campaign.__name__ = f"campaign_aot_{os.getpid()}_{next(_aot_counter)}"
    states0, dtr = runner._batched_inputs()
    with _fresh_codegen():
        compiled = jax.jit(campaign).lower(
            states0, dtr, runner.knobs).compile()
    runner._runner = compiled
    runner._runner_max_quanta = max_quanta
    return compiled


def serialize_compiled(compiled) -> bytes:
    """One self-contained payload blob for a `jax.stages.Compiled`:
    (format tag, executable bytes, in_tree, out_tree), pickled."""
    from jax.experimental import serialize_executable as se

    payload, in_tree, out_tree = se.serialize(compiled)
    return pickle.dumps((PAYLOAD_FORMAT, payload, in_tree, out_tree),
                        protocol=pickle.HIGHEST_PROTOCOL)


def deserialize_compiled(blob: bytes):
    """Load a payload blob back into a callable executable.  Raises
    `ValueError` on a foreign or malformed blob — the store maps any
    failure here to a quarantining `StoreIntegrityError`."""
    from jax.experimental import serialize_executable as se

    try:
        obj = pickle.loads(blob)
    except Exception as e:
        raise ValueError(f"payload does not unpickle: "
                         f"{type(e).__name__}: {e}") from e
    if (not isinstance(obj, tuple) or len(obj) != 4
            or obj[0] != PAYLOAD_FORMAT):
        raise ValueError("payload is not a "
                         f"{PAYLOAD_FORMAT!r} blob")
    _, payload, in_tree, out_tree = obj
    return se.deserialize_and_load(payload, in_tree, out_tree)
