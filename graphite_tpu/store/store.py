"""The persistent, fingerprint-keyed store of compiled executables.

This is the miss/fill backend under the round-13 in-memory
`ProgramCache`: a content-addressed on-disk layout keyed by

    (canonical program fingerprint, batch capacity B, max_quanta,
     runtime environment tuple)

where the fingerprint is the round-11 `analysis/identity` digest of the
lowered campaign program and the environment tuple
(`store/aot.runtime_env`) pins the jax/jaxlib versions, backend
platform, device kind and device count the executable was compiled
for.  A fleet of
service processes pointed at one store directory compiles each program
class ONCE per fleet: every later process (or restart) deserializes the
stored executable instead of recompiling it.

Layout (everything under one root):

    root/entries/<eid>/program.bin    the serialized executable payload
    root/entries/<eid>/manifest.json  identity + sha256 + metadata
    root/entries/<eid>/last_used      LRU timestamp (gc's sort key)
    root/entries/<eid>.corrupt-<n>/   quarantined entries (forensics)
    root/locks/<eid>.lock             advisory per-entry flock
    root/locks/store.lock             gc's store-wide flock

Durability and concurrency invariants:

 - **Atomic publication.**  Payload and manifest are written to
   temporaries and `os.replace`d into place, payload FIRST and manifest
   LAST — a visible manifest always names a fully written payload, so a
   crashed writer leaves a miss, never a half-entry.
 - **Advisory locking.**  Writers (fill, quarantine, evict, gc) hold an
   exclusive `flock` on the entry's lock file, so concurrent service
   processes never interleave partial writes; a filler that finds a
   valid entry under the lock skips its own write (the lost race is
   counted, not an error).  Readers stay lock-free: atomic publication
   plus checksums make a torn read detectable, and the one detectable
   race (manifest swapped between the reader's two reads) is retried
   and then arbitrated under the entry lock before it can quarantine
   a healthy entry.
 - **Integrity before identity before payload.**  A load verifies, in
   order: the manifest parses and carries every required field; the
   entry's format/environment/key fields match the requested key; the
   fingerprint matches both the key and the caller's expectation; the
   payload length matches; the sha256 matches.  Each failure raises a
   named `StoreIntegrityError` (`.reason` in REASONS) after the entry
   is QUARANTINED (renamed to `.corrupt-<n>`) — corruption is loud,
   forensically preserved, and never served.
 - **Byte-budgeted GC.**  `gc(max_bytes)` evicts least-recently-used
   entries (the `last_used` stamp, refreshed on every successful load)
   until the store fits; the most-recently-used entry always survives,
   mirroring the in-memory cache's newest-entry rule.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import os
import shutil
import time

try:
    import fcntl
except ImportError:          # pragma: no cover - non-POSIX fallback
    fcntl = None

FORMAT = "graphite-store-v1"

# every named way a stored entry can fail verification
REASONS = ("manifest", "version", "fingerprint", "truncated",
           "checksum", "deserialize")

_MANIFEST_REQUIRED = ("format", "fingerprint", "batch", "max_quanta",
                      "env", "payload_sha256", "payload_bytes")


class StoreError(RuntimeError):
    """Base type for program-store failures."""


class StoreIntegrityError(StoreError):
    """A stored entry failed verification; `.reason` names how (one of
    `REASONS`).  Raised AFTER the entry was quarantined — the caller's
    only correct recovery is a fresh compile."""

    def __init__(self, reason: str, message: str):
        assert reason in REASONS, reason
        super().__init__(message)
        self.reason = reason


@dataclasses.dataclass(frozen=True)
class StoreKey:
    """One executable's identity: program fingerprint x batch capacity
    x quantum bound x runtime environment."""

    fingerprint: str
    batch: int
    max_quanta: int
    env: tuple  # aot.runtime_env(): (jax, jaxlib, backend, kind, ndev)

    def canonical(self) -> str:
        return json.dumps(
            {"fingerprint": self.fingerprint, "batch": int(self.batch),
             "max_quanta": int(self.max_quanta), "env": list(self.env)},
            sort_keys=True)

    @property
    def entry_id(self) -> str:
        return hashlib.sha256(self.canonical().encode()).hexdigest()[:40]


def _sha256(blob: bytes) -> str:
    return hashlib.sha256(blob).hexdigest()


def _atomic_write(path: str, data: bytes) -> None:
    """Write-to-temporary + fsync + rename: `path` is either absent,
    the old content, or the complete new content — never a prefix."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class ProgramStore:
    """Fingerprint-keyed on-disk executables with integrity + LRU GC.

    `max_bytes` (0 = unbounded) arms auto-GC after every fill; `clock`
    injects the wall-clock source the LRU stamps and manifests read
    (tests pass a fake).  `counters` tracks store-local events (fills,
    lost write races, integrity quarantines, evictions) — the serving
    metrics (hits/misses) live in the service's round-14 registry,
    which owns rate accounting."""

    def __init__(self, root: str, *, max_bytes: int = 0, clock=time.time):
        self.root = os.path.abspath(root)
        self.max_bytes = int(max_bytes)
        self._clock = clock
        self.counters = {"fills": 0, "races": 0, "integrity": 0,
                         "evictions": 0}
        os.makedirs(os.path.join(self.root, "entries"), exist_ok=True)
        os.makedirs(os.path.join(self.root, "locks"), exist_ok=True)

    # -- paths -----------------------------------------------------------

    def _entries_root(self) -> str:
        return os.path.join(self.root, "entries")

    def _entry_dir(self, eid: str) -> str:
        return os.path.join(self._entries_root(), eid)

    @contextlib.contextmanager
    def _lock(self, name: str):
        """Blocking exclusive advisory flock on `locks/<name>.lock`.

        Stale-inode safe: gc's housekeeping may UNLINK a lock file for
        a long-gone entry, so after acquiring we confirm the path
        still names the inode we locked — a waiter that was blocked on
        the unlinked inode would otherwise "hold" a lock no later
        process can see, silently breaking mutual exclusion."""
        path = os.path.join(self.root, "locks", f"{name}.lock")
        while True:
            fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
            if fcntl is None:
                break
            fcntl.flock(fd, fcntl.LOCK_EX)
            try:
                if os.fstat(fd).st_ino == os.stat(path).st_ino:
                    break
            except OSError:
                pass            # unlinked while we waited: retry
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)
        try:
            yield
        finally:
            if fcntl is not None:
                fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    # -- read path -------------------------------------------------------

    def _read_manifest(self, eid: str) -> "dict | None":
        """The entry's manifest dict, or None when absent/unparsable —
        callers decide whether unparsable is a miss or an integrity
        failure."""
        try:
            with open(os.path.join(self._entry_dir(eid),
                                   "manifest.json")) as f:
                man = json.load(f)
        except (OSError, ValueError):
            return None
        return man if isinstance(man, dict) else None

    _READ = object()    # _check_entry sentinel: read the payload here

    def _check_entry(self, eid: str,
                     key: "StoreKey | None" = None,
                     expect_fingerprint: "str | None" = None,
                     blob=_READ, man=_READ) -> "tuple[str, str] | None":
        """Verify one entry without quarantining: None when it is
        sound, else (reason, message).  `blob` / `man` skip the
        re-read when the caller already holds the payload bytes or the
        parsed manifest (None = the caller found them missing or
        unparsable) — verifying the caller's copies also guarantees
        the verified manifest IS the one the caller returns."""
        edir = self._entry_dir(eid)
        if man is ProgramStore._READ:
            man = self._read_manifest(eid)
        if man is None:
            if os.path.exists(os.path.join(edir, "manifest.json")):
                return ("manifest", f"entry {eid}: manifest.json does "
                        "not parse as a JSON object")
            return ("manifest", f"entry {eid}: manifest.json missing "
                    "(payload without identity)")
        missing = [k for k in _MANIFEST_REQUIRED if k not in man]
        if missing:
            return ("manifest", f"entry {eid}: manifest missing "
                    f"field(s) {missing}")
        try:
            return self._check_fields(eid, man, key,
                                      expect_fingerprint, blob)
        except (TypeError, ValueError) as e:
            # a JSON-parsable manifest whose fields have the wrong
            # TYPES (int("12a"), tuple(None), slicing a number) is
            # corruption like any other: a named failure, not a crash
            return ("manifest", f"entry {eid}: manifest field has a "
                    f"wrong type: {type(e).__name__}: {e}")

    def _check_fields(self, eid, man, key, expect_fingerprint,
                      blob) -> "tuple[str, str] | None":
        """`_check_entry`'s field checks, free to assume the manifest
        values coerce (the caller maps TypeError/ValueError to a
        "manifest" integrity failure)."""
        edir = self._entry_dir(eid)
        if man["format"] != FORMAT:
            return ("version", f"entry {eid}: store format "
                    f"{man['format']!r} != {FORMAT!r}")
        if key is None:
            # keyless audits (`verify`) must still prove the entry
            # LIVES where its key fields hash — a dir restored under
            # the wrong id, or a manifest whose key fields were edited
            # consistently with its checksum, would audit clean here
            # yet quarantine at the first real request
            expect_eid = StoreKey(
                str(man["fingerprint"]), int(man["batch"]),
                int(man["max_quanta"]), tuple(man["env"])).entry_id
            if expect_eid != eid:
                return ("manifest", f"entry {eid}: manifest key "
                        f"fields hash to {expect_eid} — the entry "
                        "does not live where its identity says")
        if key is not None:
            if tuple(man["env"]) != tuple(key.env):
                return ("version", f"entry {eid}: compiled for env "
                        f"{tuple(man['env'])} but this process is "
                        f"{tuple(key.env)}")
            if (int(man["batch"]) != int(key.batch)
                    or int(man["max_quanta"]) != int(key.max_quanta)):
                return ("manifest", f"entry {eid}: manifest key fields "
                        f"(B={man['batch']}, max_quanta="
                        f"{man['max_quanta']}) do not match the "
                        f"requested key (B={key.batch}, max_quanta="
                        f"{key.max_quanta})")
            if man["fingerprint"] != key.fingerprint:
                return ("fingerprint", f"entry {eid}: stores "
                        f"{man['fingerprint'][:24]}... but the key "
                        f"names {key.fingerprint[:24]}...")
        if expect_fingerprint is not None \
                and man["fingerprint"] != expect_fingerprint:
            return ("fingerprint", f"entry {eid}: stores "
                    f"{man['fingerprint'][:24]}... but the caller "
                    f"expects {expect_fingerprint[:24]}... — a stale "
                    "artifact must recompile, never serve")
        if blob is ProgramStore._READ:
            try:
                with open(os.path.join(edir, "program.bin"), "rb") as f:
                    blob = f.read()
            except OSError:
                blob = None
        if blob is None:
            return ("truncated", f"entry {eid}: payload missing")
        if len(blob) != int(man["payload_bytes"]):
            return ("truncated", f"entry {eid}: payload is {len(blob)} "
                    f"bytes, manifest says {man['payload_bytes']}")
        if _sha256(blob) != man["payload_sha256"]:
            return ("checksum", f"entry {eid}: payload sha256 does not "
                    "match the manifest")
        return None

    def get_blob(self, key: StoreKey, *,
                 expect_fingerprint: "str | None" = None
                 ) -> "tuple[bytes, dict] | None":
        """Read + verify one entry: (payload bytes, manifest) on a
        sound hit, None on a clean miss.  An entry failing verification
        is quarantined and raises `StoreIntegrityError` — the caller
        falls back to a fresh compile.

        Lock-free read: atomic publication means a visible manifest
        names a complete payload.  A writer REPLACING the entry between
        our manifest and payload reads can make a sound entry look
        torn, so a checksum/truncation failure is re-read once; every
        failure is then arbitrated — and, if confirmed, quarantined in
        the same lock hold — under the entry lock, where no writer can
        be mid-publish."""
        eid = key.entry_id
        edir = self._entry_dir(eid)
        if not os.path.exists(os.path.join(edir, "manifest.json")):
            return None
        bad: "tuple[str, str] | None" = None
        for _attempt in range(2):
            try:
                with open(os.path.join(edir, "program.bin"), "rb") as f:
                    blob = f.read()
            except OSError:
                blob = None
            man = self._read_manifest(eid)
            bad = self._check_entry(
                eid, key=key, expect_fingerprint=expect_fingerprint,
                blob=blob, man=man)
            if bad is None:
                self._touch(eid)
                return blob, man
            if bad[0] not in ("truncated", "checksum"):
                break           # identity failures don't race-retry
        # final arbitration under the entry lock: writers publish
        # while HOLDING it, so this view cannot be torn — a
        # repair-in-place writer that straddled both lock-free
        # attempts resolves to a sound entry and serves, a vanished
        # entry (concurrent evict/GC) resolves to a clean miss, and
        # real corruption quarantines ATOMICALLY with this
        # verification (the lock is not released in between, so a
        # healthy entry is never quarantined)
        with self._lock(eid):
            try:
                with open(os.path.join(edir, "program.bin"),
                          "rb") as f:
                    blob = f.read()
            except OSError:
                blob = None
            man = self._read_manifest(eid)
            bad = self._check_entry(
                eid, key=key, expect_fingerprint=expect_fingerprint,
                blob=blob, man=man)
            if bad is None:
                self._touch(eid)
                return blob, man
            reason, msg = bad
            dst = self._quarantine_locked(eid, reason)
        if dst is None:
            return None     # evicted under us: a miss, not corruption
        self.counters["integrity"] += 1
        raise StoreIntegrityError(reason, msg)

    def load_executable(self, key: StoreKey, *,
                        expect_fingerprint: "str | None" = None
                        ) -> "tuple[object, dict] | None":
        """`get_blob` + payload deserialize: (callable executable,
        manifest) on a hit, None on a miss; a payload that passes its
        checksum but fails to deserialize is quarantined too (reason
        "deserialize")."""
        got = self.get_blob(key, expect_fingerprint=expect_fingerprint)
        if got is None:
            return None
        blob, man = got
        from graphite_tpu.store.aot import deserialize_compiled

        try:
            fnc = deserialize_compiled(blob)
        except Exception as e:
            eid = key.entry_id
            self.quarantine(eid, "deserialize")
            raise StoreIntegrityError(
                "deserialize", f"entry {eid}: payload verified but "
                f"did not load: {type(e).__name__}: {e}") from e
        return fnc, man

    def _touch(self, eid: str) -> None:
        """Refresh the LRU stamp (best-effort: a read-only store still
        serves, it just can't reorder its own GC)."""
        try:
            _atomic_write(os.path.join(self._entry_dir(eid), "last_used"),
                          repr(float(self._clock())).encode())
        except OSError:
            pass

    def _last_used(self, eid: str) -> float:
        try:
            with open(os.path.join(self._entry_dir(eid),
                                   "last_used")) as f:
                return float(f.read().strip())
        except (OSError, ValueError):
            man = self._read_manifest(eid) or {}
            try:
                return float(man.get("created_s", 0.0))
            except (TypeError, ValueError):
                return 0.0

    # -- write path ------------------------------------------------------

    def put_blob(self, key: StoreKey, blob: bytes, *,
                 manifest: "dict | None" = None) -> dict:
        """Atomically publish one entry under the per-entry lock.  A
        valid entry already present wins the race (ours is discarded
        and `races` counted); an invalid one is repaired in place.
        Returns the manifest that ended up published."""
        eid = key.entry_id
        with self._lock(eid):
            if os.path.exists(os.path.join(self._entry_dir(eid),
                                           "manifest.json")):
                if self._check_entry(eid, key=key) is None:
                    self.counters["races"] += 1
                    return self._read_manifest(eid)
            man = dict(manifest or {})
            man.update({
                "format": FORMAT,
                "fingerprint": key.fingerprint,
                "batch": int(key.batch),
                "max_quanta": int(key.max_quanta),
                "env": list(key.env),
                "payload_sha256": _sha256(blob),
                "payload_bytes": len(blob),
                "created_s": float(self._clock()),
            })
            edir = self._entry_dir(eid)
            os.makedirs(edir, exist_ok=True)
            # payload FIRST, manifest LAST: publication is the manifest
            _atomic_write(os.path.join(edir, "program.bin"), blob)
            _atomic_write(os.path.join(edir, "manifest.json"),
                          (json.dumps(man, indent=1, sort_keys=True)
                           + "\n").encode())
            self._touch(eid)
            self.counters["fills"] += 1
        if self.max_bytes:
            self.gc(self.max_bytes)
        return man

    def save_executable(self, key: StoreKey, compiled, *,
                        manifest: "dict | None" = None,
                        verify: bool = True) -> dict:
        """Serialize a `jax.stages.Compiled` and publish it.

        `verify` (default on) load-backs the payload BEFORE publishing:
        XLA backends can emit executables whose serialization is
        incomplete (e.g. a CPU executable served from a warm
        compilation cache loses its kernel object code), and a payload
        that cannot deserialize here cannot deserialize anywhere —
        raising `StoreError` now (the caller counts a fill error and
        moves on) beats poisoning the fleet's store."""
        from graphite_tpu.store.aot import (
            deserialize_compiled, serialize_compiled,
        )

        blob = serialize_compiled(compiled)
        if verify:
            try:
                deserialize_compiled(blob)
            except Exception as e:
                raise StoreError(
                    f"refusing to publish {key.entry_id}: the payload "
                    f"fails its own load-back ({type(e).__name__}: "
                    f"{str(e)[:160]}) — the executable's serialization "
                    "is incomplete") from e
        return self.put_blob(key, blob, manifest=manifest)

    def quarantine(self, eid: str, reason: str) -> "str | None":
        """Move a failed entry aside (rename to `.corrupt-<n>`) so it
        is never served again but stays on disk for forensics; returns
        the quarantine path (None when the entry vanished under us)."""
        with self._lock(eid):
            dst = self._quarantine_locked(eid, reason)
        if dst is None:
            return None
        self.counters["integrity"] += 1
        return dst

    def _quarantine_locked(self, eid: str, reason: str) -> "str | None":
        """`quarantine`'s body, for callers already holding the entry
        lock (does NOT count — the caller does, outside the lock)."""
        edir = self._entry_dir(eid)
        if not os.path.isdir(edir):
            return None
        n = 0
        while os.path.exists(f"{edir}.corrupt-{n}"):
            n += 1
        dst = f"{edir}.corrupt-{n}"
        try:
            os.rename(edir, dst)
        except OSError:
            return None
        with contextlib.suppress(OSError):
            _atomic_write(os.path.join(dst, "quarantine.json"),
                          (json.dumps({"reason": reason,
                                       "when_s": float(self._clock())})
                           + "\n").encode())
        return dst

    # -- enumeration / maintenance --------------------------------------

    def _entry_bytes(self, path: str) -> int:
        total = 0
        with contextlib.suppress(OSError):
            for name in os.listdir(path):
                with contextlib.suppress(OSError):
                    total += os.path.getsize(os.path.join(path, name))
        return total

    def entries(self, *, include_corrupt: bool = False) -> "list[dict]":
        """One row per on-disk entry: {entry_id, manifest (None when
        unparsable), bytes, last_used, corrupt}.  Sorted oldest-used
        first (GC order)."""
        rows = []
        root = self._entries_root()
        for name in sorted(os.listdir(root)):
            path = os.path.join(root, name)
            if not os.path.isdir(path):
                continue
            corrupt = ".corrupt-" in name
            if corrupt and not include_corrupt:
                continue
            eid = name.split(".corrupt-")[0] if corrupt else name
            rows.append({
                "entry_id": name,
                "manifest": None if corrupt else self._read_manifest(eid),
                "bytes": self._entry_bytes(path),
                "last_used": 0.0 if corrupt else self._last_used(eid),
                "corrupt": corrupt,
            })
        rows.sort(key=lambda r: (r["corrupt"], r["last_used"]))
        return rows

    @property
    def total_bytes(self) -> int:
        return sum(r["bytes"] for r in self.entries())

    def verify(self) -> "list[dict]":
        """Non-quarantining full-store audit: one row per entry with
        {entry_id, ok, reason, message}.  Corrupt-quarantined dirs are
        reported (ok=False, reason="quarantined") so a populated-then-
        corrupted store audits loudly."""
        out = []
        for row in self.entries(include_corrupt=True):
            name = row["entry_id"]
            if row["corrupt"]:
                out.append({"entry_id": name, "ok": False,
                            "reason": "quarantined",
                            "message": "previously quarantined entry"})
                continue
            bad = self._check_entry(name)
            if bad is None:
                out.append({"entry_id": name, "ok": True,
                            "reason": None, "message": ""})
            else:
                out.append({"entry_id": name, "ok": False,
                            "reason": bad[0], "message": bad[1]})
        return out

    def evict(self, eid: str) -> bool:
        """Delete one entry (or quarantined dir) by its listing name.

        The id is a LISTING name, never a path: anything that would
        resolve outside `entries/` (separators, dot-segments, empty —
        `entries/..` is the store root and `rmtree` would eat it) is
        refused as not-an-entry, not deleted."""
        if (not eid or eid != os.path.basename(eid)
                or eid in (".", "..")):
            return False
        path = os.path.join(self._entries_root(), eid)
        lock_name = eid.split(".corrupt-")[0]
        with self._lock(lock_name):
            if not os.path.isdir(path):
                return False
            shutil.rmtree(path, ignore_errors=True)
            if os.path.isdir(path):
                return False    # undeletable (permissions, in use):
                                # the bytes are still there, say so
        self.counters["evictions"] += 1
        return True

    def gc(self, max_bytes: "int | None" = None, *,
           include_corrupt: bool = False) -> "list[str]":
        """Evict least-recently-used entries until the store fits
        `max_bytes` (default: the constructor budget).  The most-
        recently-used entry always survives — a store that cannot hold
        one program would force a compile per process, which is
        strictly worse than admitting the overage.  `include_corrupt`
        also deletes quarantined dirs (forensics over; they never count
        against the byte budget)."""
        budget = self.max_bytes if max_bytes is None else int(max_bytes)
        evicted = []
        with self._lock("store"):
            if include_corrupt:
                for row in self.entries(include_corrupt=True):
                    if row["corrupt"] and self.evict(row["entry_id"]):
                        evicted.append(row["entry_id"])
            if budget:
                rows = self.entries()      # oldest-used first
                total = sum(r["bytes"] for r in rows)
                while len(rows) > 1 and total > budget:
                    row = rows.pop(0)
                    if self.evict(row["entry_id"]):
                        total -= row["bytes"]
                        evicted.append(row["entry_id"])
            self._gc_orphan_locks()
        return evicted

    def _gc_orphan_locks(self) -> None:
        """Unlink lock files whose entry (and quarantine dirs) are
        gone — GC churn would otherwise grow `locks/` without bound.
        Non-blocking probe first: a held lock is in use, skip it; the
        stale-inode retry in `_lock` keeps a waiter that raced the
        unlink from holding an invisible lock."""
        if fcntl is None:
            return
        lroot = os.path.join(self.root, "locks")
        try:
            names = os.listdir(lroot)
            live = {n.split(".corrupt-")[0]
                    for n in os.listdir(self._entries_root())}
        except OSError:
            return
        for fname in names:
            eid = fname[:-5] if fname.endswith(".lock") else fname
            if eid == "store":
                continue        # the store-wide lock we are holding
            if eid in live:     # an entry or its quarantine dirs
                continue
            path = os.path.join(lroot, fname)
            try:
                fd = os.open(path, os.O_RDWR)
            except OSError:
                continue
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                os.close(fd)
                continue        # held right now: it is not an orphan
            try:
                with contextlib.suppress(OSError):
                    os.unlink(path)
            finally:
                fcntl.flock(fd, fcntl.LOCK_UN)
                os.close(fd)

    def stats(self) -> dict:
        rows = self.entries(include_corrupt=True)
        valid = [r for r in rows if not r["corrupt"]]
        return {
            "entries": len(valid),
            "corrupt": sum(1 for r in rows if r["corrupt"]),
            "bytes": sum(r["bytes"] for r in valid),
            **self.counters,
        }
