"""Runtime DVFS manager: the chip-global per-domain operating point as
simulation carry.

The round-1 port parsed `[dvfs] domains` into static `DvfsParams` and
mirrored only the CORE domain into `CoreState.freq_mhz`; cache/network/
DRAM timing constant-folded their domain frequencies out of `MemParams`.
This module makes the operating point *state*: `DvfsRtState` rides the
simulation carry (`SimState.dvfs_rt` — int32 MHz + mV per domain per
sim), the memory engines read the carried frequency through
`apply_rt_mem`, in-trace `CarbonSetDVFS` requests elect a new domain
point (`elect_domains`), and an optional ondemand-style governor steps
the V/f ladder on utilization thresholds at quantum boundaries
(`governor_tick` — masked arithmetic only, zero host sync).

Off-identity contract (same as telemetry/profile): `dvfs=None` attaches
no carry leaves and every call site branches at PYTHON level, so the
historical program lowers byte-identically — enforced by the `dvfs-off`
audit rule.

Chip-global simplification (documented divergence): the reference keeps
per-tile domain clocks; here a domain's operating point is one value per
sim.  When several tiles issue DVFS_SET to the same domain in the same
engine iteration, the LOWEST successful request wins (a deterministic
min-election — no scatter ordering), and the CORE domain's elected
frequency broadcasts to every tile's `CoreState.freq_mhz`.  Voltage
always follows AUTO (lowest voltage supporting the frequency); the
per-tile HOLD path remains on the legacy `SimState.dvfs` table.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np
from flax import struct

from graphite_tpu.dvfs.levels import (
    I32,
    I64,
    freq_at_level,
    level_for_freq,
    validate_levels,
    voltage_for_freq,
)

# DvfsParams.module_domains index of each module the carried frequency
# feeds back into (order: models/dvfs.DVFS_MODULES)
MOD_CORE = 0
MOD_L1I = 1
MOD_L1D = 2
MOD_L2 = 3
MOD_DIRECTORY = 4
MOD_NETWORK_USER = 5
MOD_NETWORK_MEMORY = 6


@dataclasses.dataclass(frozen=True)
class GovernorSpec:
    """Ondemand-style reactive governor (`cpufreq` semantics): every
    `interval_ps` of simulated time, compare the chip's utilization over
    the elapsed window (busy = clock minus sync+recv stall) against the
    thresholds and step the governed domains one V/f level up (toward
    level 0 = max frequency) or down.  Evaluated at quantum boundaries
    with masked arithmetic only — no cond payload, no host callback."""

    interval_ps: int
    up_threshold_pct: int = 80
    down_threshold_pct: int = 30
    domains: tuple = ()        # governed domain indices; () = all


@dataclasses.dataclass(frozen=True)
class DvfsSpec:
    """Opt-in runtime DVFS manager config (the `dvfs=` attach axis).

    `scale_energy`: price each `energy_pj` event at its domain's current
    V²·f operating point (Q16 integer factors; level 0 = the static
    prices' reference point).  `governor`: optional reactive stage.
    Hashable + frozen — joins the serve admission class key so jobs with
    differing DVFS configs never co-batch."""

    scale_energy: bool = True
    governor: "GovernorSpec | None" = None

    def resolve(self, params) -> "DvfsSpec":
        """Validate against the simulator's static params; returns self.
        Raises ValueError on a config that cannot host the runtime
        manager (no [dvfs] tables, broken V/f monotonicity, bad governor
        thresholds)."""
        dvp = params.dvfs
        if dvp is None:
            raise ValueError(
                "runtime DVFS needs [dvfs] tables in the config "
                "(params.dvfs is None)")
        validate_levels(dvp.voltages_mv, dvp.max_freq_mhz)
        if len(dvp.module_domains) == 0:
            raise ValueError(
                "params.dvfs.module_domains is empty — DvfsParams "
                "predates the runtime manager; rebuild via from_config")
        g = self.governor
        if g is not None:
            if int(g.interval_ps) <= 0:
                raise ValueError("governor interval_ps must be positive")
            if not (0 <= g.down_threshold_pct < g.up_threshold_pct
                    <= 100):
                raise ValueError(
                    f"governor thresholds must satisfy 0 <= down < up "
                    f"<= 100 (got down={g.down_threshold_pct}, "
                    f"up={g.up_threshold_pct})")
            for d in g.domains:
                if not (0 <= int(d) < dvp.n_domains):
                    raise ValueError(
                        f"governor domain {d} out of range "
                        f"(n_domains={dvp.n_domains})")
        return self


@struct.dataclass
class DvfsRtState:
    """The carried operating point: chip-global, per domain, per sim."""

    domain_mhz: "object"       # int32[ND] — current frequency
    domain_mv: "object"        # int32[ND] — current voltage
    # governor cursors (carried even without a governor — 4 scalars)
    next_ps: "object"          # int64[] — next evaluation time
    prev_clock_ps: "object"    # int64[] — clock sum at last evaluation
    prev_busy_ps: "object"     # int64[] — busy sum at last evaluation


def init_dvfs_rt(dvp, spec: DvfsSpec, domain_mhz=None) -> DvfsRtState:
    """Fresh carry seeded from the config's initial domain frequencies,
    or from a per-sim override (`dvfs_domain_mhz` sweep knob — may be a
    traced int32[ND])."""
    if domain_mhz is None:
        mhz = jnp.asarray(np.asarray(dvp.domain_freq_mhz, np.int32))
    else:
        mhz = jnp.asarray(domain_mhz, I32)
    interval = (int(spec.governor.interval_ps)
                if spec.governor is not None else 0)
    return DvfsRtState(
        domain_mhz=mhz,
        domain_mv=voltage_for_freq(dvp, mhz),
        next_ps=jnp.asarray(interval, I64),
        prev_clock_ps=jnp.zeros((), I64),
        prev_busy_ps=jnp.zeros((), I64),
    )


def apply_rt_mem(dvp, mem_p, rt: DvfsRtState):
    """MemParams with the constant-folded domain frequencies replaced by
    the carried ones — the memory engines' cycles<->ps conversions and
    the memory-network/DRAM models then track DVFS transitions in-trace.
    Domain indices are static, so this is two traced-scalar field swaps
    (the same dataclasses.replace lift the round-7 knobs use)."""
    return dataclasses.replace(
        mem_p,
        net_freq_mhz=rt.domain_mhz[dvp.module_domains[MOD_NETWORK_MEMORY]],
        dir_freq_mhz=rt.domain_mhz[dvp.module_domains[MOD_DIRECTORY]],
    )


def elect_domains(dvp, rt: DvfsRtState, req_mhz, dmask) -> DvfsRtState:
    """Fold this iteration's successful DVFS_SET requests into the
    carry.  `req_mhz` int32[T] (requested frequency per tile), `dmask`
    bool[T, ND] (request succeeded AND targeted that domain).  Election:
    per-domain min over successful requests — deterministic regardless
    of lane order.  Voltage follows AUTO."""
    big = jnp.asarray(np.iinfo(np.int32).max, I32)
    reqs = jnp.where(dmask, req_mhz.astype(I32)[:, None], big)
    won = jnp.min(reqs, axis=0)                      # [ND]
    any_d = jnp.any(dmask, axis=0)                   # [ND]
    new_mhz = jnp.where(any_d, won, rt.domain_mhz)
    new_mv = jnp.where(any_d, voltage_for_freq(dvp, new_mhz),
                       rt.domain_mv)
    return rt.replace(domain_mhz=new_mhz, domain_mv=new_mv)


def core_freq_tiles(dvp, rt: DvfsRtState, freq_mhz):
    """The CORE domain's carried frequency broadcast over the per-tile
    `CoreState.freq_mhz` array (chip-global semantics)."""
    return jnp.broadcast_to(
        rt.domain_mhz[dvp.core_domain].astype(freq_mhz.dtype),
        freq_mhz.shape)


def governor_tick(gov: GovernorSpec, dvp, rt: DvfsRtState,
                  state) -> DvfsRtState:
    """One quantum-boundary governor evaluation (masked arithmetic only
    — the telemetry_tick pattern, so the host-sync lint stays clean).

    Utilization over the window since the last evaluation:
    busy = Δ(Σ clock) − Δ(Σ sync_stall + recv_stall), util% = busy/Δclock.
    util ≥ up_threshold → one level toward level 0 (faster);
    util ≤ down_threshold → one level toward the table bottom (slower);
    in between holds.  All governed domains step on the same chip-wide
    signal (chip-global simplification)."""
    core = state.core
    clock = jnp.sum(core.clock_ps)
    busy = clock - jnp.sum(core.sync_stall_ps + core.recv_stall_ps)
    sim_time = jnp.max(core.clock_ps)
    do = sim_time >= rt.next_ps

    d_clock = jnp.maximum(clock - rt.prev_clock_ps, 1)
    d_busy = jnp.clip(busy - rt.prev_busy_ps, 0, None)
    util = (d_busy * 100) // d_clock                  # int64 scalar

    lvl = level_for_freq(dvp, rt.domain_mhz)          # [ND]
    up = util >= gov.up_threshold_pct
    down = util <= gov.down_threshold_pct
    n_levels = len(dvp.max_freq_mhz)
    new_lvl = jnp.clip(
        jnp.where(up, lvl - 1, jnp.where(down, lvl + 1, lvl)),
        0, n_levels - 1)

    nd = int(rt.domain_mhz.shape[0])
    governed = np.zeros(nd, bool)
    if gov.domains:
        governed[list(gov.domains)] = True
    else:
        governed[:] = True
    apply = do & jnp.asarray(governed)

    new_mhz = jnp.where(apply, freq_at_level(dvp, new_lvl),
                        rt.domain_mhz)
    new_mv = jnp.where(apply, voltage_for_freq(dvp, new_mhz),
                       rt.domain_mv)
    interval = int(gov.interval_ps)
    return rt.replace(
        domain_mhz=new_mhz,
        domain_mv=new_mv,
        next_ps=jnp.where(do, (sim_time // interval + 1) * interval,
                          rt.next_ps),
        prev_clock_ps=jnp.where(do, clock, rt.prev_clock_ps),
        prev_busy_ps=jnp.where(do, busy, rt.prev_busy_ps),
    )
