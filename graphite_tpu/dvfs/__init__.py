"""Runtime DVFS manager: carried per-domain operating points, V/f level
tables, voltage-scaled energy pricing, and the reactive governor."""

from graphite_tpu.dvfs.levels import (
    energy_scale_q16,
    freq_at_level,
    level_for_freq,
    validate_levels,
    voltage_for_freq,
)
from graphite_tpu.dvfs.runtime import (
    DvfsRtState,
    DvfsSpec,
    GovernorSpec,
    apply_rt_mem,
    core_freq_tiles,
    elect_domains,
    governor_tick,
    init_dvfs_rt,
)

__all__ = [
    "DvfsRtState",
    "DvfsSpec",
    "GovernorSpec",
    "apply_rt_mem",
    "core_freq_tiles",
    "elect_domains",
    "energy_scale_q16",
    "freq_at_level",
    "governor_tick",
    "init_dvfs_rt",
    "level_for_freq",
    "validate_levels",
    "voltage_for_freq",
]
