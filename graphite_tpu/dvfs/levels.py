"""V/f level-table helpers for the runtime DVFS manager.

The static tables live in `models/dvfs.py` (`DvfsParams.voltages_mv` /
`max_freq_mhz`, descending voltage — `DVFSManager::initializeDVFSLevels`).
This module adds what the *runtime* manager needs on top:

- `validate_levels`: the monotone V-per-f contract every table must obey
  (a lower voltage can never support a higher frequency) — checked once
  at spec-resolve time so traced lookups can use the argmax trick without
  re-validating on device.
- `voltage_for_freq`: the traced AUTO-voltage lookup (lowest voltage
  whose max frequency supports the request) — the vectorized
  `getMinVoltage`.
- `level_for_freq` / level stepping: the governor's discrete ladder.
- `energy_scale_q16`: the V²·f operating-point factor per domain as a
  Q16 fixed-point int64 — integer math end to end so the energy series
  stays bit-deterministic (no float in the carry).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

I32 = jnp.int32
I64 = jnp.int64


def validate_levels(voltages_mv, max_freq_mhz) -> None:
    """Raise ValueError unless the (voltage, max-frequency) rows form a
    valid V/f table: equal length, positive entries, strictly descending
    voltage, and monotone non-increasing max frequency (V-per-f: a lower
    voltage never supports a higher frequency)."""
    v = tuple(int(x) for x in voltages_mv)
    f = tuple(int(x) for x in max_freq_mhz)
    if len(v) != len(f):
        raise ValueError(
            f"V/f table length mismatch: {len(v)} voltages vs "
            f"{len(f)} frequencies")
    if not v:
        raise ValueError("empty V/f table")
    if any(x <= 0 for x in v) or any(x <= 0 for x in f):
        raise ValueError("V/f table entries must be positive")
    for a, b in zip(v, v[1:]):
        if b >= a:
            raise ValueError(
                f"V/f table voltages must be strictly descending "
                f"(got {a} mV then {b} mV)")
    for a, b in zip(f, f[1:]):
        if b > a:
            raise ValueError(
                f"V/f table is not monotone V-per-f: max frequency rises "
                f"from {a} MHz to {b} MHz as voltage drops")


def level_arrays(dvp):
    """The table as device constants: (voltages_mv int32[L],
    max_freq_mhz int32[L]), descending."""
    return (jnp.asarray(np.asarray(dvp.voltages_mv, np.int32)),
            jnp.asarray(np.asarray(dvp.max_freq_mhz, np.int32)))


def level_for_freq(dvp, freq_mhz):
    """Traced: the DEEPEST (lowest-voltage) level whose max frequency
    still supports `freq_mhz` (int32[...]).  Levels are descending, so
    this is `(L-1) - argmax(ok[..., ::-1])` — exactly the in-trace
    DVFS_SET lookup.  Frequencies above level 0 clamp to level 0."""
    _, maxf = level_arrays(dvp)
    ok = freq_mhz[..., None] <= maxf[None, :]
    L = maxf.shape[0]
    return jnp.where(jnp.any(ok, axis=-1),
                     (L - 1) - jnp.argmax(ok[..., ::-1], axis=-1),
                     0).astype(I32)


def voltage_for_freq(dvp, freq_mhz):
    """Traced AUTO-voltage: lowest voltage supporting `freq_mhz`
    (vectorized `DvfsParams.min_voltage_mv`); requests above the table
    max get level 0's voltage (the in-trace path rejects them before
    this lookup)."""
    volts, _ = level_arrays(dvp)
    return volts[level_for_freq(dvp, freq_mhz)]


def freq_at_level(dvp, level):
    """Traced: the max frequency at `level` (clamped to the table)."""
    _, maxf = level_arrays(dvp)
    L = maxf.shape[0]
    return maxf[jnp.clip(level, 0, L - 1)]


def energy_scale_q16(dvp, domain_mhz, domain_mv):
    """Per-domain V²·f operating-point factor as Q16 int64[ND].

    The reference point is level 0 (max voltage, max frequency) — the
    operating point the static `EnergyPrices` were quoted at — so a
    domain running the table top prices at exactly 1.0 (1 << 16) and the
    `dvfs=None` series is reproduced bit-for-bit at full throttle.
    int64 headroom: mv² · mhz ≲ 1.5e6² · 4e3 ≈ 9e15, × 2^16 overflows —
    so the shift happens after dividing mv² by the reference mv² would
    lose precision; instead scale in two stages (voltage² Q8 then
    frequency Q8)."""
    ref_mv = jnp.asarray(int(dvp.voltages_mv[0]), I64)
    ref_f = jnp.asarray(int(dvp.max_freq_mhz[0]), I64)
    mv = domain_mv.astype(I64)
    f = domain_mhz.astype(I64)
    v2 = (mv * mv * 256) // (ref_mv * ref_mv)          # Q8
    fq = (f * 256) // ref_f                            # Q8
    return v2 * fq                                     # Q16
