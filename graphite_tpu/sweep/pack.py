"""Trace packing: B same-geometry traces into one [B, T, L] batch.

The sweep runner vmaps the quantum step over a leading sim axis, so the
B traces must share one static shape.  Packing pads every field to the
longest sim's record length the same way `TraceBatch.from_builders` pads
tiles within one sim: `op` with NOP (the engine's stream-end sentinel,
so shorter sims simply finish earlier — the per-sim "length mask" is the
NOP tail itself), register fields with NO_REG, everything else with
zeros.  Per-sim RNG seeds are carried as metadata so a campaign's JSON
lines can name the trace that produced each row.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from graphite_tpu.trace.schema import NO_REG, Op, TraceBatch


@dataclasses.dataclass
class PackedTraces:
    """B stacked TraceBatches, [B, T, L] per field (host-side)."""

    op: np.ndarray
    flags: np.ndarray
    pc: np.ndarray
    addr0: np.ndarray
    addr1: np.ndarray
    size0: np.ndarray
    size1: np.ndarray
    aux0: np.ndarray
    aux1: np.ndarray
    dyn_ps: np.ndarray
    rreg0: np.ndarray
    rreg1: np.ndarray
    wreg: np.ndarray
    lengths: np.ndarray          # int64[B] pre-padding record length
    seeds: "np.ndarray | None"   # int64[B] generator seeds (metadata)

    _TRACE_FIELDS = tuple(f.name for f in dataclasses.fields(TraceBatch))

    @property
    def n_sims(self) -> int:
        return self.op.shape[0]

    @property
    def n_tiles(self) -> int:
        return self.op.shape[1]

    @property
    def length(self) -> int:
        return self.op.shape[2]

    def sim(self, b: int) -> TraceBatch:
        """Sim b back as a standalone TraceBatch (padded length — the
        NOP tail is semantically inert, see module docstring)."""
        return TraceBatch(**{f: getattr(self, f)[b]
                             for f in self._TRACE_FIELDS})

    def device_traces(self):
        """A [B, T, L] DeviceTrace pytree — vmap over axis 0 yields each
        sim's ordinary [T, L] trace."""
        import jax.numpy as jnp

        from graphite_tpu.engine.state import DeviceTrace

        return DeviceTrace(**{f: jnp.asarray(getattr(self, f))
                              for f in self._TRACE_FIELDS})

    def replicate(self, b: int) -> "PackedTraces":
        """Sim 0 tiled to B rows — the one-trace x B-knob-points grid."""
        if self.n_sims != 1:
            raise ValueError("replicate() applies to a single-sim pack")
        rep = {f: np.repeat(getattr(self, f), b, axis=0)
               for f in self._TRACE_FIELDS}
        return PackedTraces(**rep, lengths=np.repeat(self.lengths, b),
                            seeds=(None if self.seeds is None
                                   else np.repeat(self.seeds, b)))


def pack_traces(batches: "list[TraceBatch]",
                seeds: "list[int] | None" = None, *,
                validate: bool = True,
                pad_length: "int | None" = None) -> PackedTraces:
    """Pad B same-geometry TraceBatches to a common [B, T, L] layout.

    Every sim is statically validated first (trace/validate.py:
    op-code range, SEND/RECV pairing, barrier participant-count
    consistency) so a malformed campaign trace fails fast with a named
    `TraceValidationError` instead of padding silently and deadlocking
    — or crashing the TPU worker — minutes into the compiled run.
    `validate=False` skips the pass (e.g. deliberately pathological
    test traces).

    `pad_length` pads every sim to a FIXED record length (>= the
    longest sim) instead of the batch maximum — the campaign service
    buckets lengths this way so successive batches share one compiled
    [B, T, L] shape (and therefore one cache entry) even when their
    longest traces differ.  The extra tail is the same inert NOP
    padding as ordinary length equalization."""
    if not batches:
        raise ValueError("pack_traces needs at least one trace")
    if validate:
        from graphite_tpu.trace.validate import (
            TraceValidationError, validate_batch,
        )

        seen: set = set()  # seed x grid campaigns repeat the same object
        for i, b in enumerate(batches):
            if id(b) in seen:
                continue
            seen.add(id(b))
            try:
                validate_batch(b)
            except TraceValidationError as e:
                raise TraceValidationError(
                    f"sim {i}: {e}", findings=e.findings) from None
    T = batches[0].n_tiles
    bad = [i for i, b in enumerate(batches) if b.n_tiles != T]
    if bad:
        raise ValueError(
            f"all traces must share one tile count ({T}); sims {bad} "
            "differ — a sweep shares ONE compiled geometry")
    if seeds is not None and len(seeds) != len(batches):
        raise ValueError("seeds length != number of traces")
    L = max(b.length for b in batches)
    if pad_length is not None:
        if int(pad_length) < L:
            raise ValueError(
                f"pad_length={pad_length} is shorter than the longest "
                f"trace ({L} records) — padding cannot truncate")
        L = int(pad_length)
    B = len(batches)
    out = {}
    for f in PackedTraces._TRACE_FIELDS:
        ref = getattr(batches[0], f)
        arr = np.zeros((B, T, L), dtype=ref.dtype)
        if f == "op":
            arr[:] = np.uint8(Op.NOP)
        elif f in ("rreg0", "rreg1", "wreg"):
            arr[:] = NO_REG
        for i, b in enumerate(batches):
            arr[i, :, : b.length] = getattr(b, f)
        out[f] = arr
    return PackedTraces(
        **out,
        lengths=np.asarray([b.length for b in batches], np.int64),
        seeds=None if seeds is None else np.asarray(seeds, np.int64))
