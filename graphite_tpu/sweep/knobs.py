"""Traced timing knobs: the dynamic half of the engine's parameter split.

The engine's compile-time parameters conflate two different things:
*geometry* (tile count, cache sets/ways, mesh width — array shapes, truly
static) and *timing scalars* (DRAM latency, directory access cycles, NoC
hop latency, DVFS synchronization delay, the lax_barrier quantum) that
only ever enter the program as arithmetic operands.  Baking the timing
scalars into the jit means a 20-point latency sweep pays 20 compiles and
20 full per-iteration op tails (ROADMAP: config 5's ~0.2 ms dense floor
is per-*program*).

`Knobs` lifts the timing scalars into a pytree of traced int64 leaves so
ONE compiled XLA program serves an entire grid of timing points: pass a
scalar `Knobs` to `run_simulation(..., knobs=...)` for recompile-free
point hopping, or a batched `[B]` `Knobs` under `vmap` (sweep/runner.py)
to run B timing points simultaneously.  When `knobs` is None everywhere,
the engines read the same values off the static params as plain Python
ints — the historical constant-folded program, bit-identical by
construction.

Knob semantics (all integers):
  dram_latency_ns     [dram] latency (`dram_perf_model.cc:80-115`)
  dram_processing_ns  line_size / bandwidth + 1 (same model)
  dir_access_cycles   [dram_directory] access_time staircase result
  hop_latency_cycles  MEMORY-net per-hop router+link delay
                      (`network_model_emesh_hop_counter.cc`)
  sync_delay_cycles   [dvfs] synchronization_delay (cross-domain module
                      handoffs, `cache.cc:559-567`)
  quantum_ps          lax_barrier quantum (`carbon_sim.cfg:92-97`);
                      ignored under the lax / lax_p2p schemes
  dvfs_domain_mhz     optional [n_domains] vector: per-point seed for the
                      runtime DVFS carry (dvfs/runtime.py) — requires a
                      DvfsSpec on the sweep's Simulator; never applied
                      onto MemParams
"""

from __future__ import annotations

import dataclasses
import itertools

import jax
import jax.numpy as jnp
from flax import struct

I64 = jnp.int64

# fields applied onto MemParams (quantum_ps rides the step loop instead)
MEM_KNOB_FIELDS = (
    "dram_latency_ns",
    "dram_processing_ns",
    "dir_access_cycles",
    "hop_latency_cycles",
    "sync_delay_cycles",
)
KNOB_FIELDS = MEM_KNOB_FIELDS + ("quantum_ps",)
# optional per-domain frequency vector (runtime DVFS manager): an extra
# [n_domains] / [B, n_domains] leaf that seeds SimState.dvfs_rt per sweep
# point (sweep/runner.py) instead of being applied onto MemParams — the
# engines then read the carried frequencies, so one compiled program
# serves a whole domain-frequency grid
DVFS_KNOB_FIELD = "dvfs_domain_mhz"
ALL_KNOB_FIELDS = KNOB_FIELDS + (DVFS_KNOB_FIELD,)


@struct.dataclass
class Knobs:
    """Timing scalars as a pytree of int64 leaves (scalar or [B])."""

    dram_latency_ns: jax.Array
    dram_processing_ns: jax.Array
    dir_access_cycles: jax.Array
    hop_latency_cycles: jax.Array
    sync_delay_cycles: jax.Array
    quantum_ps: jax.Array
    # [n_domains] ([B, n_domains] batched) per-domain MHz, or None (no
    # pytree leaf — sweeps without a DVFS axis lower bit-identically)
    dvfs_domain_mhz: "jax.Array | None" = None

    @classmethod
    def from_params(cls, params, quantum_ps: "int | None" = None) -> "Knobs":
        """Baseline knob point read off static params (EngineParams or
        MemParams).  Memoryless runs (EngineParams.mem None) get zeros
        for the memory knobs — the engines never read them."""
        mp = getattr(params, "mem", params)

        def get(name):
            return int(getattr(mp, name, 0) or 0) if mp is not None else 0

        return cls(**{f: jnp.asarray(get(f), I64) for f in MEM_KNOB_FIELDS},
                   quantum_ps=jnp.asarray(int(quantum_ps or 0), I64))

    def apply_mem(self, mp):
        """MemParams with the timing-scalar fields swapped for this
        Knobs' (possibly traced) leaves.  Geometry, protocol strings and
        every other static field pass through untouched; the replaced
        instance lives only inside a trace (it is no longer hashable as
        a jit-static argument)."""
        return dataclasses.replace(
            mp, **{f: getattr(self, f) for f in MEM_KNOB_FIELDS})

    @classmethod
    def stack(cls, base: "Knobs", points: "list[dict]") -> "Knobs":
        """A batched [B] Knobs from override dicts over a baseline point.

        Each dict maps knob-field name -> int; absent fields take the
        baseline's value.  Row b of every leaf is point b."""
        cols = {f: [] for f in KNOB_FIELDS}
        dv_rows = []
        for i, p in enumerate(points):
            unknown = set(p) - set(ALL_KNOB_FIELDS)
            if unknown:
                raise ValueError(
                    f"point {i}: unknown knob(s) {sorted(unknown)} "
                    f"(valid: {', '.join(ALL_KNOB_FIELDS)})")
            for f in KNOB_FIELDS:
                cols[f].append(int(p.get(f, getattr(base, f))))
            dv_rows.append(p.get(DVFS_KNOB_FIELD, base.dvfs_domain_mhz))
        dv = None
        if any(r is not None for r in dv_rows):
            rows = []
            for i, r in enumerate(dv_rows):
                if r is None:
                    raise ValueError(
                        f"point {i}: missing {DVFS_KNOB_FIELD} — once any "
                        "point sweeps the domain-frequency vector, every "
                        "point (or the baseline) must carry one")
                rows.append(tuple(int(x) for x in jnp.asarray(r).reshape(-1)))
            widths = {len(r) for r in rows}
            if len(widths) != 1:
                raise ValueError(
                    f"{DVFS_KNOB_FIELD} rows disagree on n_domains: "
                    f"{sorted(widths)}")
            dv = jnp.asarray(rows, I64)
        return cls(**{f: jnp.asarray(cols[f], I64) for f in KNOB_FIELDS},
                   dvfs_domain_mhz=dv)

    @property
    def batch(self) -> "int | None":
        """B for a batched Knobs, None for a scalar point."""
        shape = jnp.shape(self.dram_latency_ns)
        return None if shape == () else int(shape[0])

    def point(self, b: int) -> dict:
        """Host dict of point b's values (for reports / JSON lines)."""
        out = {f: int(jnp.asarray(getattr(self, f)).reshape(-1)[b])
               for f in KNOB_FIELDS}
        if self.dvfs_domain_mhz is not None:
            dv = jnp.asarray(self.dvfs_domain_mhz)
            row = dv[b] if dv.ndim == 2 else dv
            out[DVFS_KNOB_FIELD] = tuple(int(x) for x in row)
        return out


def grid_points(**axes) -> "list[dict]":
    """Cross product of knob axes into override dicts, row-major in the
    given keyword order: grid_points(dram_latency_ns=[50, 100],
    hop_latency_cycles=[1, 2]) -> 4 points."""
    unknown = set(axes) - set(ALL_KNOB_FIELDS)
    if unknown:
        raise ValueError(
            f"unknown knob axis(es) {sorted(unknown)} "
            f"(valid: {', '.join(ALL_KNOB_FIELDS)})")
    names = list(axes)
    return [dict(zip(names, vals))
            for vals in itertools.product(*(axes[n] for n in names))]
