"""Batched simulation campaigns: vmap B simulations through one compiled
step with traced timing knobs (zero recompiles across a knob grid).

  Knobs / grid_points  — traced timing-scalar pytree (knobs.py)
  pack_traces / PackedTraces — [B, T, L] trace packing (pack.py)
  SweepRunner / SweepOutcome — the vmapped campaign driver (runner.py)
"""

from graphite_tpu.sweep.knobs import KNOB_FIELDS, Knobs, grid_points
from graphite_tpu.sweep.pack import PackedTraces, pack_traces
from graphite_tpu.sweep.runner import SweepOutcome, SweepRunner

__all__ = [
    "KNOB_FIELDS",
    "Knobs",
    "grid_points",
    "PackedTraces",
    "pack_traces",
    "SweepOutcome",
    "SweepRunner",
]
