"""Batched simulation campaigns: B simulations through ONE compiled step.

Graphite's whole reason to exist is simulation *throughput* — the
reference parallelizes ONE simulation across host machines because
architects run campaigns: design-space sweeps over timing parameters,
traces, and seeds.  The TPU port has the inverse opportunity: the
per-iteration op tail (ROADMAP: config 5's ~0.2 ms dense floor) is a
per-*program* cost, so `vmap`ping B independent simulations through one
program amortizes it B-ways — the batching shape that makes inference
stacks fast.

Mechanics:
 - traces pack to a common [B, T, L] layout (sweep/pack.py); `vmap` maps
   the device-side simulation loop (`engine/step.run_simulation`) over
   the sim axis;
 - timing knobs ride as a traced `[B]` Knobs pytree (sweep/knobs.py), so
   a grid of timing points — DRAM latency, directory access, hop
   latency, sync delay, quantum — shares the single compiled program
   with ZERO recompiles;
 - per-sim done/overflow/deadlock masks drive each sim's own while_loop
   condition: under vmap's batching rule a finished sim's carry is
   select-frozen, so every sim's final state is BIT-IDENTICAL to its own
   sequential run (pinned in tests/test_sweep.py) and the batch
   early-exits once the last live sim finishes;
 - results demux back into B independent SimResults (plus per-sim
   phase-skip counters and iteration counts).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from graphite_tpu.sweep.knobs import Knobs
from graphite_tpu.sweep.pack import PackedTraces, pack_traces


@dataclasses.dataclass
class SweepOutcome:
    """One campaign's demuxed outputs."""

    results: list                 # B SimResults (engine/simulator.py)
    knobs: "Knobs"                # the [B] knob batch that ran
    n_iterations: np.ndarray      # int64[B] subquantum iterations per sim
    n_quanta: np.ndarray          # int32[B]
    phase_skips: "list[dict] | None"  # per-sim gate skip counts (or None)
    seeds: "np.ndarray | None" = None  # per-sim trace seeds (pack metadata)
    # per-sim device-recorded timelines (obs.Timeline) when the campaign
    # ran with a TelemetrySpec: the batched [B, S, n_series] ring demuxed
    # sim-by-sim (each also rides its SimResults.telemetry)
    timelines: "list | None" = None
    # per-sim per-tile profiles (obs.TileProfile) when the campaign ran
    # with a ProfileSpec: the [B, S, T, m] ring demuxed sim-by-sim
    # (each also rides its SimResults.profile)
    profiles: "list | None" = None
    # per-sim latency histograms (obs.Hist) when the campaign ran with
    # a HistSpec: the [B, H, B'] (or [B, T, H, B']) bucket-count ring
    # demuxed sim-by-sim (each also rides its SimResults.hist)
    hists: "list | None" = None
    # False for unbounded clock schemes (lax/lax_p2p): there is no
    # quantum in the program, so reporting the knob would claim a value
    # that never entered it
    quantum_valid: bool = True
    # the device layout the campaign actually ran under (round 18):
    # "solo", "1d-batch(d=N)", "1d-tile(t=N)", or "2d(b=DB,t=DT)" —
    # reported per row so a result line names the program that made it
    layout: str = "solo"

    def json_rows(self) -> "list[dict]":
        """One JSON-able dict per sim (the CLI's output lines)."""
        rows = []
        for b, r in enumerate(self.results):
            point = self.knobs.point(b)
            if not self.quantum_valid:
                point.pop("quantum_ps", None)
            rows.append({
                "sim": b,
                **({"seed": int(self.seeds[b])}
                   if self.seeds is not None else {}),
                **point,
                "layout": self.layout,
                "completion_time_ns": r.completion_time_ps // 1000,
                "total_instructions": r.total_instructions,
                "n_quanta": int(self.n_quanta[b]),
                "n_iterations": int(self.n_iterations[b]),
                "func_errors": r.func_errors,
            })
        return rows


def _divisors(n: int) -> "list[int]":
    return [d for d in range(1, int(n) + 1) if int(n) % d == 0]


class SweepRunner:
    """Run B same-geometry simulations as one batched compiled program.

    `traces`: a list of TraceBatch (or a PackedTraces).  `points`: knob
    override dicts (sweep/knobs.py KNOB_FIELDS); with one trace and K > 1
    points the trace is replicated across the grid.  Remaining kwargs
    reach the underlying Simulator construction (mailbox_depth,
    inner_block, phase_gate, telemetry, profile, ...); multi-chip tile
    sharding, streaming and host-barrier modes are out of scope for the
    batched program.  `telemetry=obs.TelemetrySpec(...)` records one
    device timeline PER SIM ([B, S, n_series] total), demuxed post-run
    into `SweepOutcome.timelines` / each result's `.telemetry`;
    `profile=obs.ProfileSpec(...)` likewise records one per-tile ring
    PER SIM ([B, S, T, m] total), demuxed into `SweepOutcome.profiles`
    / each result's `.profile` — under both vmap and batch shard_map;
    `dvfs=dvfs.DvfsSpec(...)` attaches the runtime DVFS manager to
    every sim, and a `dvfs_domain_mhz` knob axis then seeds each
    point's per-domain operating frequencies so ONE compiled program
    sweeps a whole domain-frequency grid (the race-to-idle study).

    Four batching programs, chosen by `layout` (or the legacy
    `shard_batch` kwarg):
     - "solo": `vmap` over the sim axis (the default on one device):
       one program, B-wide arrays.  vmap converts the engine's
       activity-gating lax.conds into both-branch selects, so this
       program runs UNGATED by default (gating is mechanism, not policy
       — results are bit-identical either way; pass phase_gate=True to
       override).
     - "batch" (legacy `shard_batch=True`): batch-axis `shard_map` when
       several devices are visible and B divides evenly: each device
       runs B/ndev sims; with one sim per device the per-device program
       is the plain UNBATCHED engine — real lax.cond gating stays alive
       and sims run in parallel across devices.
     - "tile" / "2d" / an explicit `(batch_shards, tile_shards)` tuple:
       the round-18 `Mesh(('batch', 'tile'))` program — each device
       holds a TILE BLOCK of a SUBSET of sims.  The big per-tile arrays
       (cache meta, the directory + its staging rows, trace rows, the
       per-tile profile ring) are block-local on the tile axis and the
       round-12 packed per-phase exchange (one working-set gather + one
       merged scatter per iteration, parallel/px.py) runs over the tile
       axis only; batch cells never communicate.  This is the layout
       for sims whose per-sim residency bill exceeds ONE device's
       `hbm_budget_bytes`: the bill splits into per-device tile blocks
       (`device_breakdown()`).  Results are bit-identical to solo runs
       (regress rung 12).

    `layout=None` picks automatically from `residency_breakdown` + the
    device count: a campaign whose PER-SIM bill exceeds the per-device
    budget shards the tile axis (smallest tile_shards that fits, batch
    shards filling the remaining devices); otherwise the legacy choice
    (batch-axis shard_map when B divides the device count, else solo).
    The chosen layout is reported in `json_rows` ("layout" column) and
    `SweepOutcome.layout`, and `lower()` lowers the REAL composition
    (via a device-less AbstractMesh) so the audit lints, cost model and
    identity lock cover the 2D program on any host.

    `hbm_budget_bytes` (else `[general] hbm_budget_bytes`, 0 = off)
    arms the pre-compile residency fail-fast: the campaign's estimated
    footprint (B x state + resident traces + telemetry rings) above the
    budget raises `analysis.cost.ResidencyBudgetError` — with the
    per-consumer breakdown — before any tracing starts.  Under a
    tile-sharded layout the check is PER DEVICE (`device_breakdown`),
    which is exactly what lets a too-big-for-one-device sim run.
    """

    def __init__(self, config, traces, points: "list[dict] | None" = None,
                 *, mailbox_depth: "int | None" = None,
                 shard_batch: "bool | None" = None,
                 layout=None,
                 hbm_budget_bytes: "int | None" = None, **sim_kwargs):
        from graphite_tpu.engine.simulator import Simulator, \
            auto_mailbox_depth

        for bad in ("mesh", "stream", "barrier_host", "donate"):
            # pop rather than test: an explicit falsy value (e.g.
            # barrier_host=False) matches our own construction and must
            # not collide with the kwargs passed below
            if sim_kwargs.pop(bad, None):
                raise ValueError(
                    f"SweepRunner does not support {bad}= (the batched "
                    "program is single-device and resident)")
        pack = traces if isinstance(traces, PackedTraces) \
            else pack_traces(list(traces))
        if points and pack.n_sims == 1 and len(points) > 1:
            pack = pack.replicate(len(points))
        if points is not None and len(points) != pack.n_sims:
            raise ValueError(
                f"{len(points)} knob points for {pack.n_sims} traces — "
                "counts must match (or pass one trace to replicate)")
        self.pack = pack
        B = pack.n_sims

        # every sim must build the SAME engine program: the memory
        # subsystem is built iff a trace touches memory, so mixed
        # memory/memoryless campaigns cannot share one lowering
        from graphite_tpu.trace.schema import FLAG_MEM0_VALID, \
            FLAG_MEM1_VALID
        mem_flags = FLAG_MEM0_VALID | FLAG_MEM1_VALID
        has_mem = [bool(np.any(pack.flags[b] & mem_flags))
                   for b in range(B)]
        if len(set(has_mem)) != 1:
            raise ValueError(
                "all sims in a sweep must agree on touching memory "
                f"(sims {[b for b in range(B) if has_mem[b] != has_mem[0]]}"
                " differ): the memory engine is part of the compiled "
                "program")

        if mailbox_depth is None:
            # one ring depth serves the whole batch (ring timing is
            # depth-invariant below overflow, so per-sim equality holds)
            mailbox_depth = max(auto_mailbox_depth(pack.sim(b))
                                for b in range(B))

        # device layout: solo vmap, batch-axis shard_map, or the 2D
        # batch x tile mesh (see class doc)
        n_dev = len(jax.devices())
        if layout is not None and shard_batch is not None:
            raise ValueError(
                "pass layout= OR the legacy shard_batch=, not both "
                "(shard_batch=True is layout='batch', False is 'solo')")
        if layout is None and shard_batch is not None:
            layout = "batch" if shard_batch else "solo"
        auto = layout is None
        self._n_dev = n_dev
        if auto:
            # legacy auto guess; a budget-driven promotion to the 2D
            # layout happens below, once the sim's state bytes exist
            layout = ("batch" if n_dev > 1 and B % n_dev == 0
                      else "solo")
        layout = self._normalize_layout(layout, B, n_dev)
        self._user_gating = {
            k: sim_kwargs[k] for k in ("phase_gate", "mem_gate_bytes")
            if k in sim_kwargs}
        self._sim_ctor = (config, pack.sim(0), mailbox_depth,
                          dict(sim_kwargs))
        self._has_mem = bool(has_mem[0])
        self.sim = self._build_sim(layout)
        self.mailbox_depth = mailbox_depth
        base = Knobs.from_params(self.sim.params,
                                 self.sim.quantum_ps)
        points = points if points is not None else [{}] * B
        if self.sim.quantum_ps is None:
            # unbounded schemes (lax / lax_p2p) have no quantum for the
            # knob to steer — reject rather than silently ignore it
            bad_q = [i for i, p in enumerate(points) if "quantum_ps" in p]
            if bad_q:
                raise ValueError(
                    f"point(s) {bad_q} sweep quantum_ps but the clock "
                    "scheme has no lax_barrier quantum (the knob would "
                    "be reported yet never enter the program)")
        self.knobs = Knobs.stack(base, points)
        if self.knobs.dvfs_domain_mhz is not None:
            # the domain-frequency axis seeds the runtime DVFS carry, so
            # a DvfsSpec must be attached (it bakes the carried-frequency
            # reads into the program); validate the grid host-side — the
            # traced seed path clamps instead of raising
            if self.sim.dvfs_spec is None:
                raise ValueError(
                    "dvfs_domain_mhz knob points need dvfs=DvfsSpec(...) "
                    "on the campaign (the carried-frequency program is "
                    "opt-in; without it the knob would never enter the "
                    "lowering)")
            dvp = self.sim.params.dvfs
            grid = np.asarray(jax.device_get(self.knobs.dvfs_domain_mhz))
            if grid.shape[-1] != dvp.n_domains:
                raise ValueError(
                    f"dvfs_domain_mhz rows have {grid.shape[-1]} "
                    f"entries but the config defines {dvp.n_domains} "
                    "domain(s)")
            top = int(dvp.max_freq_mhz[0])
            if (grid <= 0).any() or (grid > top).any():
                raise ValueError(
                    "dvfs_domain_mhz points must be in (0, "
                    f"{top}] MHz (the V/f table's top level); got "
                    f"{sorted(set(grid.reshape(-1).tolist()) - set(range(1, top + 1)))}")
        if self.sim.quantum_ps is not None:
            q = np.asarray(jax.device_get(self.knobs.quantum_ps))
            if (q <= 0).any():
                raise ValueError(
                    f"quantum_ps knob points must be positive "
                    f"(sims {np.flatnonzero(q <= 0).tolist()}): the "
                    "boundary math divides by the quantum")
        self.last_n_iterations = None
        self._runner = None
        self._runner_max_quanta = None
        self._dtr = None      # device-resident [B, T, L] traces (cached)
        self._states0 = None  # broadcast [B, ...] initial states (cached)
        # lower-once plumbing (round 11): one tracing per max_quanta
        # serves audit + cost + fingerprint; lower_count is the probe.
        # _sim_lower_gen mirrors sim.lower_gen — attach_telemetry on
        # the wrapped sim changes the program AND initial state, so
        # every sim-derived cache here must drop (_sync_with_sim)
        self._lowered = {}
        self.lower_count = 0
        self._sim_lower_gen = self.sim.lower_gen
        # Pre-compile residency fail-fast (round 10): the campaign's HBM
        # bill is B x per-sim state + the resident [B, T, L] traces +
        # B telemetry rings — all known BEFORE tracing, so a sweep of
        # big sims with timelines refuses as a NAMED error here instead
        # of a device OOM minutes into compile.  Budget: kwarg, else
        # `[general] hbm_budget_bytes`, else 0 (disabled).
        if hbm_budget_bytes is None:
            hbm_budget_bytes = self.sim.config.cfg.get_int(
                "general/hbm_budget_bytes", 0)
        self.hbm_budget_bytes = int(hbm_budget_bytes)
        # Budget-driven layout promotion (round 18): a per-sim bill too
        # big for ONE device's budget is not a refusal anymore — shard
        # the tile axis (the smallest tile_shards whose per-device
        # block fits), batch shards filling the remaining devices.
        if auto and self.hbm_budget_bytes and n_dev > 1 \
                and not isinstance(layout, tuple):
            per_sim = self._per_sim_bill()
            if per_sim > self.hbm_budget_bytes:
                promoted = self._auto_mesh_layout(
                    B, pack.n_tiles, n_dev,
                    budget=self.hbm_budget_bytes)
                if promoted is not None:
                    old_vmapped = self._sims_per_cell(layout) > 1
                    layout = promoted
                    if (self._sims_per_cell(layout) > 1) != old_vmapped \
                            and self._has_mem and not self._user_gating:
                        # the gating defaults follow the per-cell
                        # program shape (vmapped cells run ungated);
                        # rebuild the wrapped sim so the executed and
                        # certified program agree
                        self.sim = self._build_sim(layout)
                        self._sim_lower_gen = self.sim.lower_gen
        self.layout_spec = layout
        if self.sim.dvfs_spec is not None and isinstance(layout, tuple):
            raise ValueError(
                "the runtime DVFS manager does not support tile-sharded "
                "layouts: the governor and the chip-global election "
                "reduce over ALL tiles, which a tile shard cannot see "
                "(use layout='solo' or 'batch')")
        self.shard_batch = layout == "batch"
        self._sims_per_dev = self._sims_per_cell(layout)
        self.layout_name = self._layout_name(layout)
        if self.hbm_budget_bytes:
            from graphite_tpu.analysis.cost import (
                ResidencyBudgetError, format_breakdown,
            )

            if isinstance(layout, tuple):
                # tile-sharded layouts budget PER DEVICE: each device
                # holds (B/db) sims' tile blocks, which is exactly what
                # lets a too-big-for-one-device sim run at all
                bd = self.device_breakdown()
                if bd["total"] > self.hbm_budget_bytes:
                    raise ResidencyBudgetError(
                        f"per-device residency of the "
                        f"{self.layout_name} campaign layout exceeds "
                        f"hbm_budget_bytes={self.hbm_budget_bytes} (B="
                        f"{self.pack.n_sims}): "
                        + format_breakdown(bd)
                        + " per device — raise tile_shards, shrink the "
                        "batch, or raise `[general] hbm_budget_bytes`")
            else:
                breakdown = self.residency_breakdown()
                if breakdown["total"] > self.hbm_budget_bytes:
                    raise ResidencyBudgetError(
                        f"campaign residency exceeds hbm_budget_bytes="
                        f"{self.hbm_budget_bytes} before compile (B="
                        f"{self.pack.n_sims}): "
                        + format_breakdown(breakdown)
                        + " — shrink the batch, stream fewer consumers "
                        "(drop telemetry or shorten traces), raise "
                        "`[general] hbm_budget_bytes`, or shard the "
                        "mesh both ways (layout='2d' / layout=(batch_"
                        "shards, tile_shards): the 2D batch x tile "
                        "layout splits the bill into per-device tile "
                        "blocks)")

    # -- device layouts (round 18) ---------------------------------------

    def _normalize_layout(self, layout, B: int, n_dev: int):
        """Normalize a layout request to "solo" | "batch" | (db, dt)."""
        T = self.pack.n_tiles
        if isinstance(layout, str):
            name = layout.lower().replace("_", "-")
            if name == "solo":
                return "solo"
            if name in ("batch", "1d-batch"):
                if n_dev <= 1 or B % n_dev != 0:
                    raise ValueError(
                        f"layout 'batch' needs B ({B}) divisible by "
                        f"the device count ({n_dev})")
                return "batch"
            if name in ("tile", "1d-tile"):
                if n_dev <= 1:
                    raise ValueError(
                        "layout 'tile' needs more than one device "
                        "(force some with XLA_FLAGS=--xla_force_host_"
                        "platform_device_count=N on CPU)")
                return self._check_mesh_layout((1, n_dev), B, T)
            if name == "2d":
                got = self._auto_mesh_layout(B, T, n_dev, budget=None)
                if got is None:
                    raise ValueError(
                        f"no 2D layout fits: {n_dev} device(s), tile "
                        f"count {T}, B={B} — need a >1 tile divisor of "
                        "the device count (pass an explicit (batch_"
                        "shards, tile_shards) tuple to override)")
                return got
            raise ValueError(
                f"unknown layout {layout!r} (choose 'solo', 'batch', "
                "'tile', '2d', or an explicit (batch_shards, "
                "tile_shards) tuple)")
        if isinstance(layout, (tuple, list)) and len(layout) == 2:
            return self._check_mesh_layout(
                (int(layout[0]), int(layout[1])), B,
                self.pack.n_tiles)
        raise ValueError(
            f"unknown layout {layout!r} (choose 'solo', 'batch', "
            "'tile', '2d', or an explicit (batch_shards, tile_shards) "
            "tuple)")

    def _check_mesh_layout(self, layout, B: int, T: int):
        """Validate an explicit (db, dt) mesh layout.  Device
        availability is deliberately NOT checked here: lowering (audit,
        fingerprint, lock) uses a device-less AbstractMesh, so a 2D
        program is auditable on a 1-device host; `_get_runner` checks
        the real devices at execution time."""
        db, dt = layout
        if db < 1 or dt < 1:
            raise ValueError(
                f"layout shards must be positive (got {layout})")
        if B % db:
            raise ValueError(
                f"layout batch_shards={db} must divide B ({B})")
        if T % dt:
            raise ValueError(
                f"layout tile_shards={dt} must divide the tile count "
                f"({T})")
        return (db, dt)

    def _auto_mesh_layout(self, B: int, T: int, n_dev: int, *,
                          budget: "int | None"):
        """Pick a (db, dt) mesh layout.  With a `budget`, the smallest
        tile_shards whose per-device block fits, batch shards filling
        the remaining devices (largest divisor of B that fits); with
        budget=None (an explicit '2d' request), the smallest >1 tile
        split the geometry allows.  None when nothing fits."""
        # any tile divisor up to the device count is a candidate — dt
        # need not divide n_dev (the mesh uses db*dt of the devices;
        # idle devices beat a refusal), smallest split that fits wins
        for dt in range(2, n_dev + 1):
            if T % dt:
                continue
            db_max = n_dev // dt
            if budget is None:
                db = max(d for d in _divisors(B) if d <= db_max)
                return (db, dt)
            block = self._per_sim_bill(tile_shards=dt)
            cap = budget // max(block, 1)
            if cap < 1 or block > budget:
                continue
            db = max(d for d in _divisors(B) if d <= db_max)
            if B // db <= cap:
                return (db, dt)
        return None

    def _sims_per_cell(self, layout) -> int:
        B = self.pack.n_sims
        if layout == "batch":
            return B // self._n_dev_hint()
        if isinstance(layout, tuple):
            return B // layout[0]
        return B

    def _n_dev_hint(self) -> int:
        n = getattr(self, "_n_dev", None)
        return n if n else len(jax.devices())

    def _layout_name(self, layout) -> str:
        if layout == "solo":
            return "solo"
        if layout == "batch":
            return f"1d-batch(d={self._n_dev_hint()})"
        db, dt = layout
        if db == 1:
            return f"1d-tile(t={dt})"
        return f"2d(b={db},t={dt})"

    def _build_sim(self, layout):
        from graphite_tpu.engine.simulator import Simulator

        config, trace0, mbd, kwargs = self._sim_ctor
        kwargs = dict(kwargs)
        if self._sims_per_cell(layout) > 1 and self._has_mem:
            # the per-cell program is vmapped: its gating conds become
            # both-branch selects, so default them OFF (bit-identical
            # results, measured faster; explicit kwargs win)
            kwargs.setdefault("phase_gate", False)
            kwargs.setdefault("mem_gate_bytes", 0)
        return Simulator(config, trace0, mailbox_depth=mbd,
                         barrier_host=False, **kwargs)

    def _per_sim_bill(self, tile_shards: int = 1) -> int:
        """ONE sim's residency bill — whole (tile_shards=1) or its
        per-device tile block under a tile-sharded layout."""
        return self._device_bd(sims_per_shard=1,
                               tile_shards=tile_shards)["total"]

    def _device_bd(self, *, sims_per_shard: int,
                   tile_shards: int) -> "dict[str, int]":
        from graphite_tpu.analysis.cost import (
            device_residency_breakdown, trace_record_bytes,
        )

        state = self.sim.state
        if state.telemetry is not None:
            state = state.replace(telemetry=None)
        if state.profile is not None:
            state = state.replace(profile=None)
        if state.hist is not None:
            state = state.replace(hist=None)
        per_sim_trace = (self.pack.n_tiles * self.pack.length
                         * trace_record_bytes(self.pack.sim(0)))
        return device_residency_breakdown(
            state=state, sims_per_shard=sims_per_shard,
            tile_shards=tile_shards,
            per_sim_trace_bytes=per_sim_trace,
            telemetry_spec=self.sim.telemetry_spec,
            profile_spec=self.sim.profile_spec,
            hist_spec=self.sim.hist_spec)

    def device_breakdown(self) -> "dict[str, int]":
        """Per-DEVICE itemized residency of the chosen layout: each
        device holds (B / batch_shards) sims' tile blocks — the
        replicated control state in full, 1/tile_shards of the big
        per-tile arrays, trace rows and profile ring (the telemetry
        ring's scalar rows are replicated).  For solo this equals
        `residency_breakdown` modulo the packed-trace padding; for the
        batch layout it is the per-device share."""
        if isinstance(self.layout_spec, tuple):
            db, dt = self.layout_spec
        elif self.layout_spec == "batch":
            db, dt = self._n_dev_hint(), 1
        else:
            db, dt = 1, 1
        return self._device_bd(sims_per_shard=self.pack.n_sims // db,
                               tile_shards=dt)

    def residency_breakdown(self) -> "dict[str, int]":
        """Per-consumer HBM estimate of this campaign's resident layout
        (analysis/cost.residency_breakdown): B x per-sim state, the
        packed [B, T, L] traces, B telemetry rings.  The same itemized
        dict the pre-compile fail-fast prints."""
        from graphite_tpu.analysis.cost import residency_breakdown
        from graphite_tpu.sweep.pack import PackedTraces

        trace_arrays = {f: getattr(self.pack, f)
                        for f in PackedTraces._TRACE_FIELDS}
        # the rings are itemized as their own consumers — strip them
        # from the per-sim state so an attached spec is not counted twice
        state = self.sim.state
        if state.telemetry is not None:
            state = state.replace(telemetry=None)
        if state.profile is not None:
            state = state.replace(profile=None)
        if state.hist is not None:
            state = state.replace(hist=None)
        return residency_breakdown(
            state=state, trace=trace_arrays,
            batch=self.pack.n_sims,
            telemetry_spec=self.sim.telemetry_spec,
            profile_spec=self.sim.profile_spec,
            hist_spec=self.sim.hist_spec)

    @property
    def n_sims(self) -> int:
        return self.pack.n_sims

    def _runner_fn(self, max_quanta: int, abstract: bool = False):
        """The (unjitted) batched campaign function — `_get_runner`
        jits it; `lower()` hands it to `jax.make_jaxpr` for the
        program auditor.  `abstract=True` (lowering only) builds any
        mesh layout over a device-less AbstractMesh, so the 2D program
        is auditable/fingerprintable on hosts without the forced
        device platform."""
        from graphite_tpu.engine.step import run_simulation

        params = self.sim.params
        unbounded = self.sim.quantum_ps is None
        tel = self.sim.telemetry_spec
        prof = self.sim.profile_spec
        hs = self.sim.hist_spec
        dv = self.sim.dvfs_spec

        def one(state, trace, kn, px=None):
            q = None if unbounded else kn.quantum_ps
            kw = {} if px is None else {"px": px}
            if dv is not None and kn.dvfs_domain_mhz is not None:
                # per-point operating seed: rebuild the DVFS carry from
                # this row's [n_domains] frequencies (AUTO voltage) and
                # re-broadcast the CORE domain into the tile clocks, so
                # one compiled program serves the whole frequency grid
                from graphite_tpu.dvfs.runtime import (
                    core_freq_tiles, init_dvfs_rt,
                )

                rt = init_dvfs_rt(params.dvfs, dv,
                                  domain_mhz=kn.dvfs_domain_mhz)
                state = state.replace(
                    dvfs_rt=rt,
                    core=state.core.replace(freq_mhz=core_freq_tiles(
                        params.dvfs, rt, state.core.freq_mhz)),
                    dvfs=state.dvfs.replace(
                        freq_mhz=jnp.broadcast_to(
                            rt.domain_mhz[None],
                            state.dvfs.freq_mhz.shape),
                        voltage_mv=jnp.broadcast_to(
                            rt.domain_mv[None],
                            state.dvfs.voltage_mv.shape)))
            return run_simulation(params, trace, state, q, max_quanta,
                                  knobs=kn, telemetry=tel, profile=prof,
                                  dvfs=dv, hist=hs, **kw)

        if isinstance(self.layout_spec, tuple):
            # the 2D batch x tile mesh: each device holds a tile block
            # of a subset of sims; the packed per-phase exchange runs
            # over the tile axis only (parallel/mesh.py round 18)
            from jax.sharding import PartitionSpec as P

            from graphite_tpu.parallel.mesh import (
                TILE_AXIS_2D, _shard_map, campaign_state_specs,
                campaign_trace_specs, make_batch_tile_mesh,
            )
            from graphite_tpu.parallel.px import ParallelCtx

            db, dt = self.layout_spec
            px = ParallelCtx(axis=TILE_AXIS_2D, n_dev=dt)
            mesh = make_batch_tile_mesh(db, dt, abstract=abstract)
            state_specs = campaign_state_specs(self.sim.state)
            trace_specs = campaign_trace_specs(self.sim.device_trace)
            knob_specs = jax.tree.map(lambda _: P("batch"), self.knobs)
            Bl = self.pack.n_sims // db

            def per_cell(state, trace, kn):
                if Bl == 1:
                    # one sim's tile blocks per batch cell: strip the
                    # [1] batch dim and run the plain engine under the
                    # tile exchange — real lax.cond gating stays alive
                    sq = jax.tree_util.tree_map
                    out = one(*(sq(lambda x: x[0], t)
                                for t in (state, trace, kn)), px)
                    return sq(lambda x: x[None], out)
                return jax.vmap(lambda s, t, k: one(s, t, k, px))(
                    state, trace, kn)

            return _shard_map(
                per_cell, mesh=mesh,
                in_specs=(state_specs, trace_specs, knob_specs),
                out_specs=(state_specs, P("batch"), P("batch"),
                           P("batch")))

        if not self.shard_batch:
            return jax.vmap(one)

        from jax.sharding import Mesh, PartitionSpec as P

        from graphite_tpu.parallel.mesh import _shard_map

        K = self._sims_per_dev
        mesh = Mesh(np.array(jax.devices()), ("b",))

        def per_device(state, trace, kn):
            if K > 1:
                return jax.vmap(one)(state, trace, kn)
            # one sim per device: strip the [1] batch dim and run
            # the plain UNBATCHED program — real lax.cond gating,
            # bit-identical to a sequential Simulator run
            squeeze = jax.tree_util.tree_map
            out = one(*(squeeze(lambda x: x[0],
                                t) for t in (state, trace, kn)))
            return squeeze(lambda x: x[None], out)

        return _shard_map(per_device, mesh=mesh,
                          in_specs=(P("b"), P("b"), P("b")),
                          out_specs=P("b"))

    def _sync_with_sim(self):
        """Drop caches derived from the wrapped sim's program when its
        identity changed (attach_telemetry after this runner was built):
        the lowering, the jitted runner, and the broadcast initial
        states all bake the telemetry spec/ring in, and serving stale
        ones would certify or execute a different artifact than the
        sim describes."""
        if self._sim_lower_gen != self.sim.lower_gen:
            self._sim_lower_gen = self.sim.lower_gen
            self._lowered = {}
            self._runner = None
            self._runner_max_quanta = None
            self._states0 = None
            self._dtr = None

    def _get_runner(self, max_quanta: int):
        self._sync_with_sim()
        if self._runner is None or self._runner_max_quanta != max_quanta:
            self._runner = jax.jit(self._runner_fn(max_quanta))
            self._runner_max_quanta = max_quanta
        return self._runner

    def _batched_inputs(self):
        """The [B, ...] initial states and [B, T, L] device traces,
        built once and cached so repeat run() calls (timed benchmark
        loops) measure the program, not a host->device re-upload."""
        self._sync_with_sim()
        if self._states0 is None:
            B = self.pack.n_sims
            self._states0 = jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(x[None], (B,) + x.shape),
                self.sim.state)
            self._dtr = self.pack.device_traces()
        return self._states0, self._dtr

    def lower(self, max_quanta: int = 4096):
        """The batched campaign program as a ClosedJaxpr plus its flat
        invar paths (states first, then traces, then knob leaves) — the
        program auditor's input (analysis/audit.py; the knob-fold rule
        maps knob names to invars via the paths).

        Pure tracing over abstract inputs: make_jaxpr only needs avals,
        so audit-only callers never pay the [B, ...] state broadcast or
        the [B, T, L] trace upload run() caches for execution.
        Lower-once: cached per max_quanta, so audit + cost +
        fingerprint share one tracing (`lower_count` is the probe)."""
        from graphite_tpu.analysis.walk import invar_path_strings
        from graphite_tpu.engine.state import DeviceTrace
        from graphite_tpu.sweep.pack import PackedTraces

        self._sync_with_sim()
        hit = self._lowered.get(max_quanta)
        if hit is not None:
            return hit
        B = self.pack.n_sims
        states_abs = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct((B,) + jnp.shape(x),
                                           jnp.result_type(x)),
            self.sim.state)
        dtr_abs = DeviceTrace(**{
            f: jax.ShapeDtypeStruct(getattr(self.pack, f).shape,
                                    getattr(self.pack, f).dtype)
            for f in PackedTraces._TRACE_FIELDS})
        closed = jax.make_jaxpr(self._runner_fn(max_quanta,
                                                abstract=True))(
            states_abs, dtr_abs, self.knobs)
        self.lower_count += 1
        hit = (closed, invar_path_strings((states_abs, dtr_abs,
                                           self.knobs)))
        self._lowered[max_quanta] = hit
        return hit

    def run(self, max_quanta: int = 1_000_000) -> SweepOutcome:
        from graphite_tpu.engine.simulator import (
            DeadlockError, MailboxOverflowError, Simulator,
        )

        B = self.pack.n_sims
        # B identical initial states (same config/geometry -> same init)
        states0, dtr = self._batched_inputs()
        state, nq_d, deadlock_d, iters_d = self._get_runner(max_quanta)(
            states0, dtr, self.knobs)
        net_part, mem_part, ioc_part, tel_part, prof_part, hist_part = \
            Simulator._result_parts(state)
        (nq, deadlock, overflow, done, core_h, net_h, mem_h, ioc_h,
         tel_h, prof_h, hist_h, iters) = jax.device_get((
            nq_d, deadlock_d, state.net.overflow, state.done, state.core,
            net_part, mem_part, ioc_part, tel_part, prof_part, hist_part,
            iters_d))
        if overflow.any():
            raise MailboxOverflowError(
                f"mailbox ring overflow in sim(s) "
                f"{np.flatnonzero(overflow).tolist()}; re-run with a "
                "larger mailbox_depth")
        if deadlock.any():
            raise DeadlockError(
                f"no progress across a quantum in sim(s) "
                f"{np.flatnonzero(deadlock).tolist()}")
        undone = ~done.all(axis=1)
        if undone.any():
            raise RuntimeError(
                f"sim(s) {np.flatnonzero(undone).tolist()} exceeded "
                f"max_quanta={max_quanta}")
        # self.sim.state keeps the PRISTINE initial state: repeat run()
        # calls (timed benchmark loops) restart the campaign from zero
        self.last_n_iterations = np.asarray(iters)

        def row(tree, b):
            return jax.tree_util.tree_map(lambda x: x[b], tree)

        timelines = None
        if self.sim.telemetry_spec is not None and tel_h is not None:
            from graphite_tpu.obs.telemetry import Timeline

            # the whole [B, S, n_series] ring rode the ONE batched fetch
            # above; demux sim-by-sim host-side (shard_map campaigns
            # gather per-device buffers through the out_specs, so the
            # same demux serves both batching programs)
            buf_h, count_h = np.asarray(tel_h[0]), np.asarray(tel_h[1])
            timelines = [
                Timeline.from_host_state(self.sim.telemetry_spec,
                                         buf_h[b], int(count_h[b]))
                for b in range(B)
            ]
        profiles = None
        if self.sim.profile_spec is not None and prof_h is not None:
            from graphite_tpu.obs.profile import demux_profiles

            # the [B, S, T, m] ring rode the same ONE batched fetch;
            # the demux serves vmap and batch-shard_map campaigns alike
            profiles = demux_profiles(self.sim.profile_spec, prof_h)
        hists = None
        if self.sim.hist_spec is not None and hist_h is not None:
            from graphite_tpu.obs.hist import demux_hists

            # the [B, (T,) H, B'] count ring rode the same ONE batched
            # fetch; the demux serves vmap and shard_map campaigns alike
            hists = demux_hists(self.sim.hist_spec, hist_h)
        results = [
            self.sim._results_host(
                row(core_h, b), row(net_h, b),
                None if mem_h is None else row(mem_h, b),
                int(nq[b]),
                None if ioc_h is None else row(ioc_h, b),
                telemetry=None if timelines is None else timelines[b],
                profile=None if profiles is None else profiles[b],
                hist=None if hists is None else hists[b])
            for b in range(B)
        ]
        phase_skips = None
        if state.mem is not None:
            from graphite_tpu.engine.simulator import mem_phase_names

            skips = np.asarray(jax.device_get(state.mem.phase_skips))
            names = mem_phase_names(self.sim.params)
            phase_skips = [
                {n: int(v) for n, v in zip(names, skips[b].tolist())}
                for b in range(B)
            ]
        return SweepOutcome(results=results, knobs=self.knobs,
                            n_iterations=np.asarray(iters),
                            n_quanta=np.asarray(nq),
                            phase_skips=phase_skips,
                            seeds=self.pack.seeds,
                            quantum_valid=self.sim.quantum_ps is not None,
                            timelines=timelines,
                            profiles=profiles,
                            hists=hists,
                            layout=self.layout_name)
