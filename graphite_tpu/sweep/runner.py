"""Batched simulation campaigns: B simulations through ONE compiled step.

Graphite's whole reason to exist is simulation *throughput* — the
reference parallelizes ONE simulation across host machines because
architects run campaigns: design-space sweeps over timing parameters,
traces, and seeds.  The TPU port has the inverse opportunity: the
per-iteration op tail (ROADMAP: config 5's ~0.2 ms dense floor) is a
per-*program* cost, so `vmap`ping B independent simulations through one
program amortizes it B-ways — the batching shape that makes inference
stacks fast.

Mechanics:
 - traces pack to a common [B, T, L] layout (sweep/pack.py); `vmap` maps
   the device-side simulation loop (`engine/step.run_simulation`) over
   the sim axis;
 - timing knobs ride as a traced `[B]` Knobs pytree (sweep/knobs.py), so
   a grid of timing points — DRAM latency, directory access, hop
   latency, sync delay, quantum — shares the single compiled program
   with ZERO recompiles;
 - per-sim done/overflow/deadlock masks drive each sim's own while_loop
   condition: under vmap's batching rule a finished sim's carry is
   select-frozen, so every sim's final state is BIT-IDENTICAL to its own
   sequential run (pinned in tests/test_sweep.py) and the batch
   early-exits once the last live sim finishes;
 - results demux back into B independent SimResults (plus per-sim
   phase-skip counters and iteration counts).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from graphite_tpu.sweep.knobs import Knobs
from graphite_tpu.sweep.pack import PackedTraces, pack_traces


@dataclasses.dataclass
class SweepOutcome:
    """One campaign's demuxed outputs."""

    results: list                 # B SimResults (engine/simulator.py)
    knobs: "Knobs"                # the [B] knob batch that ran
    n_iterations: np.ndarray      # int64[B] subquantum iterations per sim
    n_quanta: np.ndarray          # int32[B]
    phase_skips: "list[dict] | None"  # per-sim gate skip counts (or None)
    seeds: "np.ndarray | None" = None  # per-sim trace seeds (pack metadata)
    # per-sim device-recorded timelines (obs.Timeline) when the campaign
    # ran with a TelemetrySpec: the batched [B, S, n_series] ring demuxed
    # sim-by-sim (each also rides its SimResults.telemetry)
    timelines: "list | None" = None
    # per-sim per-tile profiles (obs.TileProfile) when the campaign ran
    # with a ProfileSpec: the [B, S, T, m] ring demuxed sim-by-sim
    # (each also rides its SimResults.profile)
    profiles: "list | None" = None
    # False for unbounded clock schemes (lax/lax_p2p): there is no
    # quantum in the program, so reporting the knob would claim a value
    # that never entered it
    quantum_valid: bool = True

    def json_rows(self) -> "list[dict]":
        """One JSON-able dict per sim (the CLI's output lines)."""
        rows = []
        for b, r in enumerate(self.results):
            point = self.knobs.point(b)
            if not self.quantum_valid:
                point.pop("quantum_ps", None)
            rows.append({
                "sim": b,
                **({"seed": int(self.seeds[b])}
                   if self.seeds is not None else {}),
                **point,
                "completion_time_ns": r.completion_time_ps // 1000,
                "total_instructions": r.total_instructions,
                "n_quanta": int(self.n_quanta[b]),
                "n_iterations": int(self.n_iterations[b]),
                "func_errors": r.func_errors,
            })
        return rows


class SweepRunner:
    """Run B same-geometry simulations as one batched compiled program.

    `traces`: a list of TraceBatch (or a PackedTraces).  `points`: knob
    override dicts (sweep/knobs.py KNOB_FIELDS); with one trace and K > 1
    points the trace is replicated across the grid.  Remaining kwargs
    reach the underlying Simulator construction (mailbox_depth,
    inner_block, phase_gate, telemetry, profile, ...); multi-chip tile
    sharding, streaming and host-barrier modes are out of scope for the
    batched program.  `telemetry=obs.TelemetrySpec(...)` records one
    device timeline PER SIM ([B, S, n_series] total), demuxed post-run
    into `SweepOutcome.timelines` / each result's `.telemetry`;
    `profile=obs.ProfileSpec(...)` likewise records one per-tile ring
    PER SIM ([B, S, T, m] total), demuxed into `SweepOutcome.profiles`
    / each result's `.profile` — under both vmap and batch shard_map.

    Two batching programs, chosen by `shard_batch`:
     - `vmap` over the sim axis (the default on one device): one
       program, B-wide arrays.  vmap converts the engine's activity-
       gating lax.conds into both-branch selects, so this program runs
       UNGATED by default (gating is mechanism, not policy — results are
       bit-identical either way; pass phase_gate=True to override).
     - batch-axis `shard_map` when several devices are visible and B
       divides evenly: each device runs B/ndev sims; with one sim per
       device the per-device program is the plain UNBATCHED engine —
       real lax.cond gating stays alive and sims run in parallel across
       devices (host cores on the virtual CPU platform, chips on a TPU
       slice).  `shard_batch=False` forces plain vmap.

    `hbm_budget_bytes` (else `[general] hbm_budget_bytes`, 0 = off)
    arms the pre-compile residency fail-fast: the campaign's estimated
    footprint (B x state + resident traces + telemetry rings) above the
    budget raises `analysis.cost.ResidencyBudgetError` — with the
    per-consumer breakdown — before any tracing starts.
    """

    def __init__(self, config, traces, points: "list[dict] | None" = None,
                 *, mailbox_depth: "int | None" = None,
                 shard_batch: "bool | None" = None,
                 hbm_budget_bytes: "int | None" = None, **sim_kwargs):
        from graphite_tpu.engine.simulator import Simulator, \
            auto_mailbox_depth

        for bad in ("mesh", "stream", "barrier_host", "donate"):
            # pop rather than test: an explicit falsy value (e.g.
            # barrier_host=False) matches our own construction and must
            # not collide with the kwargs passed below
            if sim_kwargs.pop(bad, None):
                raise ValueError(
                    f"SweepRunner does not support {bad}= (the batched "
                    "program is single-device and resident)")
        pack = traces if isinstance(traces, PackedTraces) \
            else pack_traces(list(traces))
        if points and pack.n_sims == 1 and len(points) > 1:
            pack = pack.replicate(len(points))
        if points is not None and len(points) != pack.n_sims:
            raise ValueError(
                f"{len(points)} knob points for {pack.n_sims} traces — "
                "counts must match (or pass one trace to replicate)")
        self.pack = pack
        B = pack.n_sims

        # every sim must build the SAME engine program: the memory
        # subsystem is built iff a trace touches memory, so mixed
        # memory/memoryless campaigns cannot share one lowering
        from graphite_tpu.trace.schema import FLAG_MEM0_VALID, \
            FLAG_MEM1_VALID
        mem_flags = FLAG_MEM0_VALID | FLAG_MEM1_VALID
        has_mem = [bool(np.any(pack.flags[b] & mem_flags))
                   for b in range(B)]
        if len(set(has_mem)) != 1:
            raise ValueError(
                "all sims in a sweep must agree on touching memory "
                f"(sims {[b for b in range(B) if has_mem[b] != has_mem[0]]}"
                " differ): the memory engine is part of the compiled "
                "program")

        if mailbox_depth is None:
            # one ring depth serves the whole batch (ring timing is
            # depth-invariant below overflow, so per-sim equality holds)
            mailbox_depth = max(auto_mailbox_depth(pack.sim(b))
                                for b in range(B))

        # batch-axis sharding layout: K sims per device (see class doc)
        n_dev = len(jax.devices())
        if shard_batch is None:
            shard_batch = n_dev > 1 and B % n_dev == 0
        if shard_batch and (n_dev <= 1 or B % n_dev != 0):
            raise ValueError(
                f"shard_batch needs B ({B}) divisible by the device "
                f"count ({n_dev})")
        self.shard_batch = bool(shard_batch)
        self._sims_per_dev = B // n_dev if self.shard_batch else B
        if self._sims_per_dev > 1 and has_mem[0]:
            # the per-device program is vmapped: its gating conds become
            # both-branch selects, so default them OFF (bit-identical
            # results, measured faster; explicit kwargs win)
            sim_kwargs.setdefault("phase_gate", False)
            sim_kwargs.setdefault("mem_gate_bytes", 0)
        self.sim = Simulator(config, pack.sim(0),
                             mailbox_depth=mailbox_depth,
                             barrier_host=False, **sim_kwargs)
        self.mailbox_depth = mailbox_depth
        base = Knobs.from_params(self.sim.params,
                                 self.sim.quantum_ps)
        points = points if points is not None else [{}] * B
        if self.sim.quantum_ps is None:
            # unbounded schemes (lax / lax_p2p) have no quantum for the
            # knob to steer — reject rather than silently ignore it
            bad_q = [i for i, p in enumerate(points) if "quantum_ps" in p]
            if bad_q:
                raise ValueError(
                    f"point(s) {bad_q} sweep quantum_ps but the clock "
                    "scheme has no lax_barrier quantum (the knob would "
                    "be reported yet never enter the program)")
        self.knobs = Knobs.stack(base, points)
        if self.sim.quantum_ps is not None:
            q = np.asarray(jax.device_get(self.knobs.quantum_ps))
            if (q <= 0).any():
                raise ValueError(
                    f"quantum_ps knob points must be positive "
                    f"(sims {np.flatnonzero(q <= 0).tolist()}): the "
                    "boundary math divides by the quantum")
        self.last_n_iterations = None
        self._runner = None
        self._runner_max_quanta = None
        self._dtr = None      # device-resident [B, T, L] traces (cached)
        self._states0 = None  # broadcast [B, ...] initial states (cached)
        # lower-once plumbing (round 11): one tracing per max_quanta
        # serves audit + cost + fingerprint; lower_count is the probe.
        # _sim_lower_gen mirrors sim.lower_gen — attach_telemetry on
        # the wrapped sim changes the program AND initial state, so
        # every sim-derived cache here must drop (_sync_with_sim)
        self._lowered = {}
        self.lower_count = 0
        self._sim_lower_gen = self.sim.lower_gen
        # Pre-compile residency fail-fast (round 10): the campaign's HBM
        # bill is B x per-sim state + the resident [B, T, L] traces +
        # B telemetry rings — all known BEFORE tracing, so a sweep of
        # big sims with timelines refuses as a NAMED error here instead
        # of a device OOM minutes into compile.  Budget: kwarg, else
        # `[general] hbm_budget_bytes`, else 0 (disabled).
        if hbm_budget_bytes is None:
            hbm_budget_bytes = self.sim.config.cfg.get_int(
                "general/hbm_budget_bytes", 0)
        self.hbm_budget_bytes = int(hbm_budget_bytes)
        if self.hbm_budget_bytes:
            from graphite_tpu.analysis.cost import (
                ResidencyBudgetError, format_breakdown,
            )

            breakdown = self.residency_breakdown()
            if breakdown["total"] > self.hbm_budget_bytes:
                raise ResidencyBudgetError(
                    f"campaign residency exceeds hbm_budget_bytes="
                    f"{self.hbm_budget_bytes} before compile (B="
                    f"{self.pack.n_sims}): "
                    + format_breakdown(breakdown)
                    + " — shrink the batch, stream fewer consumers "
                    "(drop telemetry or shorten traces), or raise "
                    "`[general] hbm_budget_bytes`")

    def residency_breakdown(self) -> "dict[str, int]":
        """Per-consumer HBM estimate of this campaign's resident layout
        (analysis/cost.residency_breakdown): B x per-sim state, the
        packed [B, T, L] traces, B telemetry rings.  The same itemized
        dict the pre-compile fail-fast prints."""
        from graphite_tpu.analysis.cost import residency_breakdown
        from graphite_tpu.sweep.pack import PackedTraces

        trace_arrays = {f: getattr(self.pack, f)
                        for f in PackedTraces._TRACE_FIELDS}
        # the rings are itemized as their own consumers — strip them
        # from the per-sim state so an attached spec is not counted twice
        state = self.sim.state
        if state.telemetry is not None:
            state = state.replace(telemetry=None)
        if state.profile is not None:
            state = state.replace(profile=None)
        return residency_breakdown(
            state=state, trace=trace_arrays,
            batch=self.pack.n_sims,
            telemetry_spec=self.sim.telemetry_spec,
            profile_spec=self.sim.profile_spec)

    @property
    def n_sims(self) -> int:
        return self.pack.n_sims

    def _runner_fn(self, max_quanta: int):
        """The (unjitted) batched campaign function — `_get_runner`
        jits it; `lower()` hands it to `jax.make_jaxpr` for the
        program auditor."""
        from graphite_tpu.engine.step import run_simulation

        params = self.sim.params
        unbounded = self.sim.quantum_ps is None
        tel = self.sim.telemetry_spec
        prof = self.sim.profile_spec

        def one(state, trace, kn):
            q = None if unbounded else kn.quantum_ps
            return run_simulation(params, trace, state, q, max_quanta,
                                  knobs=kn, telemetry=tel, profile=prof)

        if not self.shard_batch:
            return jax.vmap(one)

        from jax.sharding import Mesh, PartitionSpec as P

        from graphite_tpu.parallel.mesh import _shard_map

        K = self._sims_per_dev
        mesh = Mesh(np.array(jax.devices()), ("b",))

        def per_device(state, trace, kn):
            if K > 1:
                return jax.vmap(one)(state, trace, kn)
            # one sim per device: strip the [1] batch dim and run
            # the plain UNBATCHED program — real lax.cond gating,
            # bit-identical to a sequential Simulator run
            squeeze = jax.tree_util.tree_map
            out = one(*(squeeze(lambda x: x[0],
                                t) for t in (state, trace, kn)))
            return squeeze(lambda x: x[None], out)

        return _shard_map(per_device, mesh=mesh,
                          in_specs=(P("b"), P("b"), P("b")),
                          out_specs=P("b"))

    def _sync_with_sim(self):
        """Drop caches derived from the wrapped sim's program when its
        identity changed (attach_telemetry after this runner was built):
        the lowering, the jitted runner, and the broadcast initial
        states all bake the telemetry spec/ring in, and serving stale
        ones would certify or execute a different artifact than the
        sim describes."""
        if self._sim_lower_gen != self.sim.lower_gen:
            self._sim_lower_gen = self.sim.lower_gen
            self._lowered = {}
            self._runner = None
            self._runner_max_quanta = None
            self._states0 = None
            self._dtr = None

    def _get_runner(self, max_quanta: int):
        self._sync_with_sim()
        if self._runner is None or self._runner_max_quanta != max_quanta:
            self._runner = jax.jit(self._runner_fn(max_quanta))
            self._runner_max_quanta = max_quanta
        return self._runner

    def _batched_inputs(self):
        """The [B, ...] initial states and [B, T, L] device traces,
        built once and cached so repeat run() calls (timed benchmark
        loops) measure the program, not a host->device re-upload."""
        self._sync_with_sim()
        if self._states0 is None:
            B = self.pack.n_sims
            self._states0 = jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(x[None], (B,) + x.shape),
                self.sim.state)
            self._dtr = self.pack.device_traces()
        return self._states0, self._dtr

    def lower(self, max_quanta: int = 4096):
        """The batched campaign program as a ClosedJaxpr plus its flat
        invar paths (states first, then traces, then knob leaves) — the
        program auditor's input (analysis/audit.py; the knob-fold rule
        maps knob names to invars via the paths).

        Pure tracing over abstract inputs: make_jaxpr only needs avals,
        so audit-only callers never pay the [B, ...] state broadcast or
        the [B, T, L] trace upload run() caches for execution.
        Lower-once: cached per max_quanta, so audit + cost +
        fingerprint share one tracing (`lower_count` is the probe)."""
        from graphite_tpu.analysis.walk import invar_path_strings
        from graphite_tpu.engine.state import DeviceTrace
        from graphite_tpu.sweep.pack import PackedTraces

        self._sync_with_sim()
        hit = self._lowered.get(max_quanta)
        if hit is not None:
            return hit
        B = self.pack.n_sims
        states_abs = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct((B,) + jnp.shape(x),
                                           jnp.result_type(x)),
            self.sim.state)
        dtr_abs = DeviceTrace(**{
            f: jax.ShapeDtypeStruct(getattr(self.pack, f).shape,
                                    getattr(self.pack, f).dtype)
            for f in PackedTraces._TRACE_FIELDS})
        closed = jax.make_jaxpr(self._runner_fn(max_quanta))(
            states_abs, dtr_abs, self.knobs)
        self.lower_count += 1
        hit = (closed, invar_path_strings((states_abs, dtr_abs,
                                           self.knobs)))
        self._lowered[max_quanta] = hit
        return hit

    def run(self, max_quanta: int = 1_000_000) -> SweepOutcome:
        from graphite_tpu.engine.simulator import (
            DeadlockError, MailboxOverflowError, Simulator,
        )

        B = self.pack.n_sims
        # B identical initial states (same config/geometry -> same init)
        states0, dtr = self._batched_inputs()
        state, nq_d, deadlock_d, iters_d = self._get_runner(max_quanta)(
            states0, dtr, self.knobs)
        net_part, mem_part, ioc_part, tel_part, prof_part = \
            Simulator._result_parts(state)
        (nq, deadlock, overflow, done, core_h, net_h, mem_h, ioc_h,
         tel_h, prof_h, iters) = jax.device_get((
            nq_d, deadlock_d, state.net.overflow, state.done, state.core,
            net_part, mem_part, ioc_part, tel_part, prof_part, iters_d))
        if overflow.any():
            raise MailboxOverflowError(
                f"mailbox ring overflow in sim(s) "
                f"{np.flatnonzero(overflow).tolist()}; re-run with a "
                "larger mailbox_depth")
        if deadlock.any():
            raise DeadlockError(
                f"no progress across a quantum in sim(s) "
                f"{np.flatnonzero(deadlock).tolist()}")
        undone = ~done.all(axis=1)
        if undone.any():
            raise RuntimeError(
                f"sim(s) {np.flatnonzero(undone).tolist()} exceeded "
                f"max_quanta={max_quanta}")
        # self.sim.state keeps the PRISTINE initial state: repeat run()
        # calls (timed benchmark loops) restart the campaign from zero
        self.last_n_iterations = np.asarray(iters)

        def row(tree, b):
            return jax.tree_util.tree_map(lambda x: x[b], tree)

        timelines = None
        if self.sim.telemetry_spec is not None and tel_h is not None:
            from graphite_tpu.obs.telemetry import Timeline

            # the whole [B, S, n_series] ring rode the ONE batched fetch
            # above; demux sim-by-sim host-side (shard_map campaigns
            # gather per-device buffers through the out_specs, so the
            # same demux serves both batching programs)
            buf_h, count_h = np.asarray(tel_h[0]), np.asarray(tel_h[1])
            timelines = [
                Timeline.from_host_state(self.sim.telemetry_spec,
                                         buf_h[b], int(count_h[b]))
                for b in range(B)
            ]
        profiles = None
        if self.sim.profile_spec is not None and prof_h is not None:
            from graphite_tpu.obs.profile import demux_profiles

            # the [B, S, T, m] ring rode the same ONE batched fetch;
            # the demux serves vmap and batch-shard_map campaigns alike
            profiles = demux_profiles(self.sim.profile_spec, prof_h)
        results = [
            self.sim._results_host(
                row(core_h, b), row(net_h, b),
                None if mem_h is None else row(mem_h, b),
                int(nq[b]),
                None if ioc_h is None else row(ioc_h, b),
                telemetry=None if timelines is None else timelines[b],
                profile=None if profiles is None else profiles[b])
            for b in range(B)
        ]
        phase_skips = None
        if state.mem is not None:
            from graphite_tpu.engine.simulator import mem_phase_names

            skips = np.asarray(jax.device_get(state.mem.phase_skips))
            names = mem_phase_names(self.sim.params)
            phase_skips = [
                {n: int(v) for n, v in zip(names, skips[b].tolist())}
                for b in range(B)
            ]
        return SweepOutcome(results=results, knobs=self.knobs,
                            n_iterations=np.asarray(iters),
                            n_quanta=np.asarray(nq),
                            phase_skips=phase_skips,
                            seeds=self.pack.seeds,
                            quantum_valid=self.sim.quantum_ps is not None,
                            timelines=timelines,
                            profiles=profiles)
