"""Static cost & residency model over lowered programs, with budgets.

PR 3's auditor checks *structural* invariants of the lowered jaxpr;
nothing measured what a program *costs* until it ran on hardware we
rarely have.  This module is the static counterpart to bench.py: walking
the same `jax.make_jaxpr` artifacts `Simulator.lower()` /
`SweepRunner.lower()` expose (via the analysis/walk.py traversal), it
computes

  per-eqn bytes      operand + result bytes of every equation, with
                     loop trip-count multipliers (scan lengths are
                     static; while bodies count once — the
                     per-iteration view the op-tail floor lives in);
  kernel proxy       per-protocol-iteration equation count, attributed
                     per phase via the round-6 phase-cond structure
                     (rules.phase_conds) — eqns >= fused kernels, but
                     the count moves monotonically with the op tail
                     the config-5 ~0.2 ms floor is made of;
  peak residency     a live-range scan over the program: vars become
                     live at definition, die after last use; cond/while
                     outputs are counted ON TOP of their live operands
                     (XLA double-buffers them — the round-6 pathology).
                     Ignores buffer donation/aliasing and fusion, so it
                     is an over-estimate; `backend_memory_comparison`
                     records the deviation from the backend's own
                     `compiled.memory_analysis()` where available.

On top sits the budget layer: `BUDGETS.json` holds a measured baseline
and slack-derived ceiling per audited program; `check_budget` fails when
any metric exceeds its ceiling, naming the largest-contributing equation
— so a layout mistake (round 4's 10.7 GB temp inflation) or an op-tail
regression is caught in tier-1 CI, statically, with no TPU.

Residency is budgeted once, in one place: `residency_breakdown` itemizes
the HBM consumers ROADMAP lists (per-sim state x B, resident campaign
traces, telemetry rings, streaming windows), `ResidencyBudgetError` is
the ONE exception type every residency refusal raises (SweepRunner's
pre-compile fail-fast, attach_telemetry's stream/mesh rejections), and
its message always carries the per-consumer breakdown.
"""

from __future__ import annotations

import dataclasses
import json
import os

import jax
import numpy as np

from graphite_tpu.analysis.walk import (
    as_jaxpr, aval_bytes, iter_eqns, iter_eqns_with_site, subjaxprs,
)


class ResidencyBudgetError(ValueError):
    """A residency budget refused a program layout.

    The one exception type for every HBM-residency refusal — the
    SweepRunner pre-compile fail-fast and attach_telemetry's
    stream/mesh rejections both raise it, and the message always
    includes the analyzer's per-consumer breakdown
    (`residency_breakdown` / `format_breakdown`).  Subclasses
    ValueError: callers that treated the old refusals as value errors
    keep working.
    """


# ---------------------------------------------------------------------------
# per-consumer residency model
# ---------------------------------------------------------------------------


def tree_bytes(tree) -> int:
    """Total bytes of a pytree's array leaves (concrete arrays, numpy
    arrays, or ShapeDtypeStructs — anything with .shape/.dtype)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        total += aval_bytes(leaf)
    return total


def trace_record_bytes(trace) -> int:
    """Bytes of ONE record across every field of a trace dataclass
    (TraceBatch or any per-record array bundle) — the per-record unit
    the streaming-window bound (Simulator.residency_breakdown) and the
    campaign service's admission bill both price from.  One definition,
    so adding or retyping a trace field moves every residency estimate
    together."""
    return int(sum(
        np.dtype(np.asarray(getattr(trace, f.name)).dtype).itemsize
        for f in dataclasses.fields(trace)))


def residency_breakdown(*, state=None, trace=None, batch: int = 1,
                        telemetry_spec=None, profile_spec=None,
                        hist_spec=None,
                        stream_window_bytes: "int | None" = None,
                        ) -> "dict[str, int]":
    """Itemized HBM residency estimate, bytes per consumer.

    `state`: one sim's state pytree (multiplied by `batch` — a campaign
    broadcasts B copies).  `trace`: the RESIDENT trace pytree — for a
    campaign pass the packed [B, T, L] arrays (already batch-shaped, so
    NOT multiplied).  `telemetry_spec`: a resolved obs.TelemetrySpec
    whose ring rides each sim's carry (x batch).  `profile_spec`: a
    resolved obs.ProfileSpec whose [S, T, m] per-tile ring rides each
    sim's carry (x batch).  `hist_spec`: a resolved obs.HistSpec whose
    [(T,) H, B] bucket-count ring rides each sim's carry (x batch).
    `stream_window_bytes`:
    the host->HBM window bound of a streaming run.  Returns consumer ->
    bytes plus a "total" key.  The while-carry double-buffer is NOT
    applied here (it is program-dependent); `CostReport.peak_bytes` is
    the program-level estimate that includes it.
    """
    out: "dict[str, int]" = {}
    if state is not None:
        out["state"] = int(tree_bytes(state)) * int(batch)
    if trace is not None:
        out["trace"] = int(tree_bytes(trace))
    if telemetry_spec is not None:
        out["telemetry"] = int(telemetry_ring_bytes(telemetry_spec)) \
            * int(batch)
    if profile_spec is not None:
        out["profile"] = int(profile_ring_bytes(profile_spec)) \
            * int(batch)
    if hist_spec is not None:
        out["hist"] = int(hist_ring_bytes(hist_spec)) * int(batch)
    if stream_window_bytes is not None:
        out["stream_window"] = int(stream_window_bytes)
    out["total"] = sum(out.values())
    return out


def device_residency_breakdown(*, state=None, state_split=None,
                               sims_per_shard: int = 1,
                               tile_shards: int = 1,
                               per_sim_trace_bytes: int = 0,
                               telemetry_spec=None,
                               profile_spec=None,
                               hist_spec=None) -> "dict[str, int]":
    """Itemized PER-DEVICE residency of one mesh cell under the round-18
    2D batch x tile campaign layout: each device holds
    `sims_per_shard` sims' tile blocks.

    The split follows the shard_map sharding policy
    (parallel/mesh._SHARD_MAP_LOCAL): the big per-tile arrays, the
    trace rows and the per-tile profile ring hold 1/tile_shards of
    their tile axis per device; the replicated control state and the
    telemetry ring (scalar series, identical on every tile shard) are
    held in full.  `tile_shards=1, sims_per_shard=B` reduces to the
    whole-campaign bill, so one arithmetic serves solo, 1D and 2D
    admission.  `state_split` (a precomputed
    `parallel/mesh.shard_split_bytes` dict) substitutes for `state`
    when the caller dropped the probe pytree and kept only the byte
    counts (the admission controller's JobMeasure).  Returns consumer
    -> bytes plus a "total" key — the same shape
    `residency_breakdown` produces, so `format_breakdown` and the
    refusal messages serve both."""
    sims = int(sims_per_shard)
    dt = max(int(tile_shards), 1)
    out: "dict[str, int]" = {}
    if state is not None and state_split is None:
        from graphite_tpu.parallel.mesh import shard_split_bytes

        state_split = shard_split_bytes(state)
    if state_split is not None:
        out["state"] = sims * (int(state_split["replicated"])
                               + int(state_split["tile_local"]) // dt)
    if per_sim_trace_bytes:
        out["trace"] = sims * (int(per_sim_trace_bytes) // dt)
    if telemetry_spec is not None:
        out["telemetry"] = sims * int(telemetry_ring_bytes(telemetry_spec))
    if profile_spec is not None:
        out["profile"] = sims * int(profile_spec.ring_bytes(
            tile_shards=dt))
    if hist_spec is not None:
        # the aggregate [H, B] ring is replicated (held in full per
        # shard); only a per-tile [T, H, B] ring splits its tile axis
        out["hist"] = sims * int(hist_spec.ring_bytes(
            tile_shards=dt if hist_spec.per_tile else 1))
    out["total"] = sum(out.values())
    return out


def telemetry_ring_bytes(spec) -> int:
    """Per-sim bytes of a telemetry spec's device-resident state (ring +
    prev snapshot + cursors) — delegates to the spec's own accounting
    (obs.TelemetrySpec.ring_bytes) so the ONE size model feeds both the
    residency budget and the refusal messages."""
    return int(spec.ring_bytes())


def profile_ring_bytes(spec) -> int:
    """Per-sim bytes of a per-tile profile spec's device-resident state
    (the [S, T, m] ring + prev snapshot + times + cursors) — delegates
    to obs.ProfileSpec.ring_bytes, the ONE size model the admission
    bill and the refusal messages share."""
    return int(spec.ring_bytes())


def hist_ring_bytes(spec) -> int:
    """Per-sim bytes of a latency-histogram spec's device-resident state
    (the int64 bucket-count ring + boundary counter + optional energy
    snapshot) — delegates to obs.HistSpec.ring_bytes, the ONE size
    model the admission bill and the refusal messages share."""
    return int(spec.ring_bytes())


def format_breakdown(breakdown: "dict[str, int]") -> str:
    """One-line human rendering: 'state 1.2 GB + trace 64.0 MB + ...'."""
    parts = [f"{k} {_human(v)}" for k, v in breakdown.items()
             if k != "total"]
    return " + ".join(parts) + f" = {_human(breakdown['total'])}"


def _human(n: int) -> str:
    n = int(n)
    for unit, div in (("GB", 1 << 30), ("MB", 1 << 20), ("KB", 1 << 10)):
        if n >= div:
            return f"{n / div:.2f} {unit}"
    return f"{n} B"


# ---------------------------------------------------------------------------
# per-equation cost walk
# ---------------------------------------------------------------------------

# Shape-only bookkeeping XLA folds into neighbors — excluded from the
# kernel-count proxy (they still contribute bytes when they materialize,
# but counting them as kernels would drown the dispatchable-op signal).
_FREE_PRIMITIVES = frozenset({
    "reshape", "squeeze", "expand_dims", "broadcast_in_dim",
    "convert_element_type", "stop_gradient", "copy",
})

# Call-like primitives whose sub-jaxpr cost IS the eqn's cost (counting
# the call itself would double-count the body).
_CALL_PRIMITIVES = frozenset({
    "cond", "while", "scan", "pjit", "closed_call", "core_call",
    "xla_call", "custom_jvp_call", "custom_vjp_call", "remat",
    "checkpoint", "remat2",
})


def _eqn_bytes(eqn) -> "tuple[int, int]":
    """(operand bytes, result bytes) of one equation."""
    in_b = sum(aval_bytes(v.aval) for v in eqn.invars
               if not isinstance(v, jax.core.Literal))
    out_b = sum(aval_bytes(v.aval) for v in eqn.outvars)
    return in_b, out_b


@dataclasses.dataclass
class DynCost:
    """Trip-weighted cost of executing a jaxpr once: `eqns` counts
    non-free equations (the kernel proxy), `bytes_moved` sums operand +
    result bytes, both with scan lengths multiplied in and cond branches
    resolved to their heaviest arm (the dense-iteration view: every
    phase live is exactly the config-5 floor regime)."""

    eqns: int = 0
    bytes_moved: int = 0

    def __iadd__(self, other: "DynCost"):
        self.eqns += other.eqns
        self.bytes_moved += other.bytes_moved
        return self

    def scaled(self, k: int) -> "DynCost":
        return DynCost(self.eqns * k, self.bytes_moved * k)


def dynamic_cost(jaxpr, *, while_trips: int = 1) -> DynCost:
    """Trip-weighted execution cost of `jaxpr` (see DynCost).

    scan multiplies its body by the static `length`; while bodies count
    `while_trips` times (default 1 — the per-iteration view); cond costs
    its heaviest branch (one branch executes; the heavy one is the dense
    floor).  The eqn count is a KERNEL PROXY: XLA fuses, so real kernel
    counts are lower, but fusion is local and stable — the proxy moves
    with the program.
    """
    total = DynCost()
    j = as_jaxpr(jaxpr)
    for eqn in j.eqns:
        name = eqn.primitive.name
        in_b, out_b = _eqn_bytes(eqn)
        if name == "cond":
            branch_costs = [
                dynamic_cost(b, while_trips=while_trips)
                for _, b in subjaxprs(eqn)
            ]
            if branch_costs:
                total += max(branch_costs, key=lambda c: c.bytes_moved)
            # the select/copy of the carried outputs is real traffic
            total += DynCost(0, out_b)
            continue
        if name in _CALL_PRIMITIVES or list(subjaxprs(eqn)):
            mult = 1
            if name == "scan":
                mult = int(eqn.params.get("length", 1))
            elif name == "while":
                mult = int(while_trips)
            inner = DynCost()
            for _, sub in subjaxprs(eqn):
                inner += dynamic_cost(sub, while_trips=while_trips)
            total += inner.scaled(mult)
            continue
        total += DynCost(0 if name in _FREE_PRIMITIVES else 1,
                         in_b + out_b)
    return total


# ---------------------------------------------------------------------------
# peak-live residency scan
# ---------------------------------------------------------------------------


def peak_live_bytes(jaxpr, _memo=None) -> int:
    """Static peak-live-bytes estimate of executing `jaxpr` once.

    Linear live-range scan: the program's consts + invars are live at
    entry; each eqn's outputs materialize ON TOP of everything still
    live (so a cond/while whose outputs mirror its carried operands
    models XLA's double-buffering of branch/loop outputs — the round-6
    contract's cost); a var dies after its last use.  Call-like eqns add
    their sub-jaxpr's own transient peak (minus the operand bytes
    already counted as live here).  No buffer donation, aliasing, or
    fusion — a deliberate over-estimate whose deviation from the
    backend's `memory_analysis()` is recorded, not hidden.
    """
    if _memo is None:
        _memo = {}
    j = as_jaxpr(jaxpr)
    if id(j) in _memo:
        return _memo[id(j)]

    outset = {v for v in j.outvars
              if not isinstance(v, jax.core.Literal)}
    last: dict = {}
    for i, eqn in enumerate(j.eqns):
        for v in eqn.invars:
            if not isinstance(v, jax.core.Literal):
                last[v] = i

    live: dict = {}
    for v in list(j.constvars) + list(j.invars):
        live[v] = aval_bytes(v.aval)
    live_b = sum(live.values())
    peak = live_b
    # inputs nothing consumes (and that aren't outputs) die at entry
    for v in list(live):
        if v not in last and v not in outset:
            live_b -= live.pop(v)

    for i, eqn in enumerate(j.eqns):
        out_b = sum(aval_bytes(v.aval) for v in eqn.outvars)
        inner_extra = 0
        for _, sub in subjaxprs(eqn):
            sj = as_jaxpr(sub)
            sub_in = sum(aval_bytes(v.aval)
                         for v in list(sj.constvars) + list(sj.invars))
            inner_extra = max(inner_extra,
                              peak_live_bytes(sj, _memo) - sub_in)
        peak = max(peak, live_b + out_b + inner_extra)
        for v in eqn.outvars:
            if v in live:
                continue
            b = aval_bytes(v.aval)
            live[v] = b
            live_b += b
        for v in list(live):
            if last.get(v, -1) <= i and v not in outset:
                live_b -= live.pop(v)

    _memo[id(j)] = peak
    return peak


# ---------------------------------------------------------------------------
# per-iteration / per-phase attribution
# ---------------------------------------------------------------------------


def main_loop_body(jaxpr):
    """The body jaxpr of the program's main loop — the `while` eqn with
    the most nested equations (the quantum loop in `run_simulation`, the
    bounded dispatch loop under barrier_host).  None when the program
    has no while loop (single-quantum regions)."""
    best, best_n = None, -1
    for _, eqn in iter_eqns_with_site(jaxpr):
        if eqn.primitive.name != "while":
            continue
        body = as_jaxpr(eqn.params["body_jaxpr"])
        n = sum(1 for _ in iter_eqns(body))
        if n > best_n:
            best, best_n = body, n
    return best


@dataclasses.dataclass
class PhaseCost:
    """One protocol phase's share of the per-iteration cost (the cost of
    its gating cond's heaviest branch)."""

    name: str
    eqns: int
    bytes_moved: int

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def per_phase_costs(body, n_tiles: int,
                    phase_names=()) -> "list[PhaseCost]":
    """Attribute the per-iteration kernel proxy to protocol phases via
    the round-6 phase-cond structure (rules.phase_conds finds the conds
    that output mailbox matrices).  Conds appear in program order ==
    phase order; unnamed extras (or an ungated program's zero conds)
    degrade gracefully."""
    from graphite_tpu.analysis.rules import phase_conds

    out = []
    for k, (site, eqn) in enumerate(phase_conds(body, n_tiles)):
        branch_costs = [dynamic_cost(b) for _, b in subjaxprs(eqn)]
        heavy = max(branch_costs, key=lambda c: c.bytes_moved) \
            if branch_costs else DynCost()
        name = (phase_names[k] if k < len(phase_names)
                else f"phase_{k}")
        out.append(PhaseCost(name, heavy.eqns, heavy.bytes_moved))
    return out


# ---------------------------------------------------------------------------
# the report
# ---------------------------------------------------------------------------

_TOP_EQNS = 5


@dataclasses.dataclass
class CostReport:
    """One program's static cost & residency measurements.

    `metrics()` is the budgeted subset; the rest is context the CLI
    emits for humans (per-phase table, top-contributing equations, the
    backend memory_analysis comparison when one was recorded)."""

    program: str
    tiles: int                 # geometry the program was lowered at
    n_eqns_total: int          # every eqn at every depth, once
    kernels_per_iter: int      # trip-weighted proxy inside the main loop
    bytes_per_iter: int        # trip-weighted operand+result bytes there
    arg_bytes: int             # program inputs (consts + invars)
    out_bytes: int             # program outputs
    peak_bytes: int            # live-range scan peak (over-estimate)
    phase_costs: "list[PhaseCost]" = dataclasses.field(
        default_factory=list)
    base_kernels_per_iter: int = 0  # per-iter eqns outside the phase conds
    top_eqns: "list[dict]" = dataclasses.field(default_factory=list)
    memory_cmp: "dict | None" = None  # backend_memory_comparison output
    # round 22: the static collective/ICI metrics (analysis/comms.py).
    # None on non-mesh programs — the keys exist only where collectives
    # can, so every pre-round-22 BUDGETS.json entry stays byte-identical
    collectives_per_iter: "int | None" = None
    ici_bytes_per_iter: "int | None" = None

    def metrics(self) -> "dict[str, int]":
        out = {m: int(getattr(self, m)) for m in BUDGET_METRICS}
        for m in COMMS_METRICS:
            v = getattr(self, m)
            if v is not None:
                out[m] = int(v)
        return out

    def to_json(self) -> dict:
        return {
            "cost": True,
            "program": self.program,
            "tiles": self.tiles,
            **self.metrics(),
            "base_kernels_per_iter": self.base_kernels_per_iter,
            "phases": [p.to_json() for p in self.phase_costs],
            "top_eqns": self.top_eqns,
            **({"memory_analysis": self.memory_cmp}
               if self.memory_cmp is not None else {}),
        }


def _top_eqns(jaxpr, k: int = _TOP_EQNS) -> "list[dict]":
    """The k largest equations by result bytes — the named suspects a
    budget-gate failure points at."""
    rows = []
    for site, eqn in iter_eqns_with_site(jaxpr):
        if eqn.primitive.name in _CALL_PRIMITIVES:
            continue  # a call's bytes are its body's; name leaves
        in_b, out_b = _eqn_bytes(eqn)
        if out_b == 0:
            continue
        shape = getattr(eqn.outvars[0].aval, "shape", ())
        dtype = str(getattr(eqn.outvars[0].aval, "dtype", "?"))
        rows.append({"site": site, "primitive": eqn.primitive.name,
                     "out_bytes": int(out_b), "in_bytes": int(in_b),
                     "shape": [int(d) for d in shape], "dtype": dtype})
    rows.sort(key=lambda r: r["out_bytes"], reverse=True)
    return rows[:k]


def cost_report(spec) -> CostReport:
    """Measure one audited program (an audit.ProgramSpec)."""
    closed = spec.closed
    j = as_jaxpr(closed)
    arg_b = sum(aval_bytes(v.aval)
                for v in list(j.constvars) + list(j.invars))
    out_b = sum(aval_bytes(v.aval) for v in j.outvars
                if not isinstance(v, jax.core.Literal))
    n_total = sum(1 for _ in iter_eqns(closed))
    body = main_loop_body(closed)
    if body is not None:
        it = dynamic_cost(body)
        phases = per_phase_costs(body, spec.n_tiles,
                                 getattr(spec, "phase_names", ()))
    else:
        it = dynamic_cost(closed)
        phases = per_phase_costs(closed, spec.n_tiles,
                                 getattr(spec, "phase_names", ()))
    # lazy: comms imports this module (main_loop_body) at its top
    from graphite_tpu.analysis import comms

    cm = comms.collective_metrics(spec)
    return CostReport(
        program=spec.name,
        tiles=int(spec.n_tiles),
        n_eqns_total=n_total,
        kernels_per_iter=it.eqns,
        bytes_per_iter=it.bytes_moved,
        arg_bytes=arg_b,
        out_bytes=out_b,
        peak_bytes=peak_live_bytes(closed),
        phase_costs=phases,
        base_kernels_per_iter=it.eqns - sum(p.eqns for p in phases),
        top_eqns=_top_eqns(closed),
        collectives_per_iter=(None if cm is None
                              else cm["collectives_per_iter"]),
        ici_bytes_per_iter=(None if cm is None
                            else cm["ici_bytes_per_iter"]),
    )


# ---------------------------------------------------------------------------
# backend cross-check: compiled.memory_analysis()
# ---------------------------------------------------------------------------

# Documented agreement tolerance of the static model vs the backend's
# own accounting, where the backend provides memory_analysis():
#  - arguments/outputs: within ARG_OUT_TOL (layout padding only);
#  - peak: within [1, PEAK_OVER_FACTOR] x the backend's argument +
#    output + temp total (the live-range scan ignores donation/aliasing
#    and in-place loop-carry updates, so it over-estimates; it must
#    never UNDER-estimate the backend's floor).
ARG_OUT_TOL = 0.10
PEAK_OVER_FACTOR = 8.0


def backend_memory_comparison(fn, args, report: "CostReport | None" = None,
                              ) -> "dict | None":
    """Compile `fn(*args)` on the current backend and compare its
    `memory_analysis()` against the static estimate.  Returns None when
    the backend provides no analysis.  This COMPILES (the one cost.py
    operation that does) — callers gate it behind tests/flags."""
    compiled = jax.jit(fn).lower(*args).compile()
    ma = compiled.memory_analysis()
    if ma is None:
        return None
    out = {
        "backend": jax.default_backend(),
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
    }
    if report is not None:
        total = (out["argument_bytes"] + out["output_bytes"]
                 + out["temp_bytes"])
        out["static_arg_bytes"] = report.arg_bytes
        out["static_out_bytes"] = report.out_bytes
        out["static_peak_bytes"] = report.peak_bytes
        if total:
            out["peak_over_backend"] = round(report.peak_bytes / total, 3)
        report.memory_cmp = out
    return out


# ---------------------------------------------------------------------------
# budget layer
# ---------------------------------------------------------------------------

BUDGET_METRICS = ("n_eqns_total", "kernels_per_iter", "bytes_per_iter",
                  "arg_bytes", "out_bytes", "peak_bytes")

# round 22: the collective/ICI pair, budgeted ONLY on mesh programs
# (CostReport carries None elsewhere and metrics() drops them — the
# keys never appear in a non-mesh BUDGETS.json entry).  The ratchet
# over ici_bytes_per_iter is the [T, k] mailbox compaction's
# acceptance metric (ROADMAP).
COMMS_METRICS = ("collectives_per_iter", "ici_bytes_per_iter")

# ceiling = measured * rel + abs: counts get 10% + a small absolute
# slack (jax point releases shuffle a few eqns), byte metrics 15% + 64 KB
# (padding/layout noise) — tight enough that a doubled carried buffer or
# a new per-iteration phase trips, loose enough that benign refactors
# don't cry wolf.
_SLACK = {
    "n_eqns_total": (1.10, 16),
    "kernels_per_iter": (1.10, 8),
    "bytes_per_iter": (1.15, 1 << 16),
    "arg_bytes": (1.05, 1 << 12),
    "out_bytes": (1.05, 1 << 12),
    "peak_bytes": (1.15, 1 << 16),
    # collective counts are exact program structure — a single stray
    # collective should blow the count budget, so the absolute slack is
    # small; ICI bytes get byte-metric treatment at a 4 KB floor (the
    # audited shapes move only a few KB per iteration)
    "collectives_per_iter": (1.10, 2),
    "ici_bytes_per_iter": (1.15, 1 << 12),
}


def default_budgets_path() -> str:
    """BUDGETS.json at the repo root (next to BASELINE.json)."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(os.path.dirname(os.path.dirname(here)),
                        "BUDGETS.json")


def ceilings(report: CostReport) -> "dict[str, int]":
    return {m: int(v * _SLACK[m][0]) + _SLACK[m][1]
            for m, v in report.metrics().items()}


class BudgetRatchetError(ValueError):
    """A ratcheted budget refresh tried to RAISE a ceiling.

    `save_budgets(..., ratchet=True)` only lowers ceilings: a perf PR's
    win is locked in, and a later refresh cannot silently absorb a
    regression by re-baselining above the old ceiling.  Raising a
    metric requires naming it explicitly (`allow_increase` /
    `--allow-increase <metric>`), which makes the increase a reviewed
    decision instead of a side effect.  The message lists every
    offending (program, metric, old ceiling, new ceiling) tuple."""


def save_budgets(reports: "list[CostReport]", path: "str | None" = None,
                 fingerprints: "dict[str, str] | None" = None,
                 registry: "dict | None" = None, *,
                 ratchet: bool = False,
                 allow_increase: "tuple[str, ...]" = ()) -> str:
    """Write measured baselines + slack ceilings for `reports` (the
    --budget-update refresh; merges over an existing file so a subset
    run never drops the other programs' entries).  `fingerprints` maps
    program name -> identity digest (analysis/identity.fingerprint):
    each entry records WHICH program its ceilings were measured at, so
    the gate can refuse stale ceilings after an identity change.
    `registry` (name -> registry.ProgramRecord) keys each entry under
    the program's registered `budget_key` — the SAME key check_budget
    reads, so a refresh after a rename replaces the entry the gate
    resolves instead of orphaning a new-name copy next to the stale
    old-key one.

    `ratchet=True` (round 12): the refresh may only LOWER ceilings.  A
    metric whose new ceiling would exceed the existing entry's raises
    `BudgetRatchetError` unless it is named in `allow_increase` — the
    post-perf-PR refresh mode that locks wins in."""
    path = path or default_budgets_path()
    data = {}
    if os.path.exists(path):
        with open(path) as f:
            data = json.load(f)
    offenders = []
    for rep in reports:
        entry = {
            "tiles": int(rep.tiles),
            "measured": rep.metrics(),
            "ceiling": ceilings(rep),
        }
        if fingerprints and rep.program in fingerprints:
            entry["fingerprint"] = fingerprints[rep.program]
        rec = registry.get(rep.program) if registry else None
        key = rec.budget_key if rec is not None else rep.program
        if ratchet and key in data:
            old_ceil = data[key].get("ceiling", {})
            for m, c in entry["ceiling"].items():
                old = old_ceil.get(m)
                if old is None or c <= int(old):
                    continue
                if m in allow_increase:
                    continue
                offenders.append((rep.program, m, int(old), int(c)))
        data[key] = entry
    if offenders:
        rows = "; ".join(
            f"{prog}.{m}: ceiling {old} -> {new}"
            for prog, m, old, new in offenders)
        raise BudgetRatchetError(
            f"ratcheted refresh would RAISE {len(offenders)} ceiling(s): "
            f"{rows} — pass --allow-increase <metric> for each metric "
            f"whose increase is an intentional, reviewed decision")
    with open(path, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def load_budgets(path: "str | None" = None) -> dict:
    path = path or default_budgets_path()
    with open(path) as f:
        return json.load(f)


def check_budget(report: CostReport, budgets: dict,
                 record=None) -> list:
    """Gate one report against the checked-in budgets.  Returns
    rules.Finding rows (rule "budget", error severity) — empty means
    within budget.  A missing program entry is itself an error: silence
    on a new program would let it grow unbudgeted.

    `record` (a registry.ProgramRecord) resolves the program THROUGH
    the registry: the budget entry is looked up under the record's
    `budget_key` (renames keep their ceilings reachable), and an entry
    whose recorded fingerprint no longer matches the REGISTERED
    program's is a loud error — a retraced program can no longer
    silently inherit ceilings measured on a different artifact."""
    from graphite_tpu.analysis.rules import Finding, SEV_ERROR

    key = record.budget_key if record is not None else report.program
    entry = budgets.get(key)
    if entry is None:
        return [Finding(
            "budget", SEV_ERROR, "BUDGETS.json",
            f"no budget entry for program {report.program!r} "
            + (f"(registry key {key!r}) " if key != report.program
               else "")
            + f"— run `python -m graphite_tpu.tools.audit "
            f"--budget-update` after reviewing its cost report",
            program=report.program,
            data={"metrics": report.metrics()})]
    if record is not None and entry.get("fingerprint") is None:
        # a fingerprint-less entry resolved through the registry cannot
        # be staleness-checked — silence here would reopen the exact
        # stale-ceilings gap the identity plumbing closes
        return [Finding(
            "budget", SEV_ERROR, "BUDGETS.json",
            f"budget entry {key!r} records no fingerprint (it predates "
            f"the program registry) so its ceilings cannot be checked "
            f"against the registered artifact — refresh with "
            f"--budget-update",
            program=report.program,
            data={"registered_fingerprint": record.fingerprint})]
    if record is not None \
            and entry["fingerprint"] != record.fingerprint:
        return [Finding(
            "budget", SEV_ERROR, "BUDGETS.json",
            f"budget entry {key!r} was measured at fingerprint "
            f"{entry['fingerprint'][:24]}... but the registered "
            f"program is {record.fingerprint[:24]}... — the ceilings "
            f"are STALE for this artifact; review the cost report and "
            f"refresh with --budget-update (after --lock-update)",
            program=report.program,
            data={"budget_fingerprint": entry["fingerprint"],
                  "registered_fingerprint": record.fingerprint})]
    base_tiles = entry.get("tiles")
    if base_tiles is not None and report.tiles \
            and int(base_tiles) != int(report.tiles):
        # eqn counts and footprints scale with geometry: gating a
        # 16-tile lowering against 8-tile ceilings fabricates
        # regressions, and a mismatched --budget-update would silently
        # defang the default-geometry CI gate
        return [Finding(
            "budget", SEV_ERROR, "BUDGETS.json",
            f"program {report.program!r} was lowered at tiles="
            f"{report.tiles} but its budget entry was measured at "
            f"tiles={base_tiles} — rerun at the budgeted geometry, or "
            f"refresh with --budget-update at the new one",
            program=report.program,
            data={"tiles": int(report.tiles),
                  "budget_tiles": int(base_tiles)})]
    out = []
    ceil = entry["ceiling"]
    for m, v in report.metrics().items():
        c = ceil.get(m)
        if c is None:
            # a metric with no ceiling would grow unbudgeted — same
            # failure mode as a missing program entry, same severity
            out.append(Finding(
                "budget", SEV_ERROR, "BUDGETS.json",
                f"no ceiling for metric {m!r} of program "
                f"{report.program!r} (stale BUDGETS.json?) — refresh "
                f"with --budget-update", program=report.program,
                data={"metric": m, "measured": int(v)}))
            continue
        if v <= c:
            continue
        suspect = report.top_eqns[0] if report.top_eqns else None
        extra = ""
        if suspect and m in ("bytes_per_iter", "peak_bytes", "arg_bytes",
                             "out_bytes"):
            extra = (f"; largest equation: {suspect['primitive']} "
                     f"{suspect['shape']} {suspect['dtype']} "
                     f"({_human(suspect['out_bytes'])}) at "
                     f"{suspect['site']}")
        out.append(Finding(
            "budget", SEV_ERROR, "BUDGETS.json",
            f"{m} = {v} exceeds the budget ceiling {c} "
            f"(baseline {entry['measured'].get(m)}){extra} — if the "
            f"change is intentional, refresh with --budget-update",
            program=report.program,
            data={"metric": m, "measured": int(v), "ceiling": int(c),
                  "baseline": entry["measured"].get(m),
                  **({"suspect": suspect} if suspect else {})}))
    return out


def check_budgets(reports: "list[CostReport]", budgets: dict,
                  registry: "dict | None" = None) -> list:
    """Gate every report; `registry` (name -> registry.ProgramRecord,
    from registry.load_lock) resolves budget keys and arms the
    stale-fingerprint check per report."""
    out = []
    for rep in reports:
        rec = registry.get(rep.program) if registry else None
        out.extend(check_budget(rep, budgets, record=rec))
    return out


# ---------------------------------------------------------------------------
# known-regression fixture
# ---------------------------------------------------------------------------


def budget_regression_fixture(tiles: int = 8, pad_mb: int = 96):
    """The gated-MSI program with an artificially inflated carried
    buffer — the known-regression fixture the budget gate must trip on
    (naming the offending equation).  Wraps the REAL audited program:
    an extra `pad_mb` int64 buffer rides a while carry alongside it,
    exactly the shape of regression the gate exists for (a layout
    mistake ballooning a loop-carried temp — round 4's 10.7 GB lesson).
    Returns an audit.ProgramSpec named "gated-msi" so the check runs
    against the real program's checked-in ceilings."""
    import jax.numpy as jnp

    from graphite_tpu.analysis.audit import default_programs, \
        spec_from_simulator  # noqa: F401  (spec type)

    spec = default_programs(tiles, names=("gated-msi",))[0]
    closed = spec.closed

    n_pad = (pad_mb << 20) // 8

    def inflated(pad, *args):
        out = jax.core.eval_jaxpr(closed.jaxpr, closed.consts, *args)

        def body(c):
            p, i = c
            return p + i, i + 1

        pad2, _ = jax.lax.while_loop(
            lambda c: c[1] < 4, body, (pad, jnp.asarray(0, jnp.int64)))
        return tuple(out) + (pad2,)

    pad_abs = jax.ShapeDtypeStruct((n_pad,), jnp.int64)
    in_abs = [jax.ShapeDtypeStruct(v.aval.shape, v.aval.dtype)
              for v in closed.jaxpr.invars]
    inflated_closed = jax.make_jaxpr(inflated)(pad_abs, *in_abs)
    return dataclasses.replace(
        spec, closed=inflated_closed,
        invar_paths=["pad"] + list(spec.invar_paths),
        # the pad invar shifts every original invar one slot right
        clock_invars=tuple(i + 1 for i in spec.clock_invars))
