"""Reusable jaxpr traversal: the program auditor's walker.

jax programs arrive as nested jaxprs: `cond` carries one branch jaxpr
per arm, `while` a cond and a body, `scan`/`pjit`/`remat`/custom-
derivative calls one inner jaxpr each — and `vmap` leaves no call at
all (batching rewrites eqns in place, which is exactly why a gated
cond can silently become a both-branch select under it).  Every
auditor rule (analysis/rules.py) and every structural test assertion
walks the SAME recursion below — the traversal the round-6
phase-gating test used to keep as a private `_walk_eqns` helper.

Three layers:
 - `iter_eqns` / `iter_eqns_with_site`: flat iteration over every eqn
   at every nesting depth (site strings name the path for findings);
 - `call_arg_maps`: the structural operand<->sub-jaxpr wiring of the
   call-like primitives, so dataflow analyses can cross call
   boundaries instead of stopping at them;
 - `used_invar_mask` / `taint_narrowing`: the two dataflow passes the
   rules are built on — "is this input ever consumed?" (knob-fold)
   and "does a value derived from this input get integer-narrowed?"
   (time-dtype).
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np


def as_jaxpr(j):
    """Normalize ClosedJaxpr | Jaxpr -> Jaxpr."""
    inner = getattr(j, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        return inner
    return j


def subjaxprs(eqn):
    """Yield (tag, Jaxpr) for every sub-jaxpr in eqn.params.

    Handles both ClosedJaxpr-valued params (cond branches, while
    cond/body, scan/pjit jaxprs) and raw-Jaxpr values, singly or in
    tuples/lists — the same duck-typing the primitives themselves use.
    """
    for name, val in eqn.params.items():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for i, v in enumerate(vals):
            tag = name if len(vals) == 1 else f"{name}[{i}]"
            inner = getattr(v, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                yield tag, inner
            elif hasattr(v, "eqns"):
                yield tag, v


def iter_eqns_with_site(jaxpr, _site=""):
    """Depth-first (eqn-order) walk yielding (site, eqn) at every
    nesting depth.  `site` is a readable path like
    "while/body.cond/branches[1].scatter-add"."""
    j = as_jaxpr(jaxpr)
    for eqn in j.eqns:
        here = (f"{_site}.{eqn.primitive.name}" if _site
                else eqn.primitive.name)
        yield here, eqn
        for tag, inner in subjaxprs(eqn):
            yield from iter_eqns_with_site(inner, f"{here}/{tag}")


def iter_eqns(jaxpr):
    """Every eqn of `jaxpr` and all its sub-jaxprs, depth-first."""
    for _, eqn in iter_eqns_with_site(jaxpr):
        yield eqn


def find_eqns(jaxpr, primitive_name: str):
    """All (site, eqn) whose primitive is named `primitive_name`."""
    return [(s, e) for s, e in iter_eqns_with_site(jaxpr)
            if e.primitive.name == primitive_name]


def aval_bytes(aval) -> int:
    """Byte size of an abstract value (0 for non-array avals)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * np.dtype(dtype).itemsize


def aval_sig(aval):
    """Normalized (shape, dtype-string) signature, or None."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return None
    return (tuple(int(d) for d in shape), str(np.dtype(dtype)))


def invar_path_strings(args) -> "list[str]":
    """keystr paths of `args`' pytree leaves, in flatten order — which
    is exactly the invar order `jax.make_jaxpr(fn)(*args)` produces, so
    path i names closed.jaxpr.invars[i] (None leaves drop from both)."""
    leaves, _ = jax.tree_util.tree_flatten_with_path(args)
    return [jax.tree_util.keystr(p) for p, _ in leaves]


# ---------------------------------------------------------------------------
# operand <-> sub-jaxpr wiring of the call-like primitives
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SubCall:
    """One sub-jaxpr of a call-like eqn plus its wiring.

    in_map[i]   = eqn operand index feeding inner invar i (None: none)
    out_map[o]  = eqn outvar index fed by inner outvar o (None: none)
    feedback[o] = inner invar index inner outvar o loops back into
                  (while/scan carries), None otherwise
    """

    jaxpr: object
    in_map: list
    out_map: list
    feedback: list


def _direct(jaxpr, eqn):
    j = as_jaxpr(jaxpr)
    n_in, n_out = len(j.invars), len(j.outvars)
    return SubCall(j, list(range(min(n_in, len(eqn.invars))))
                   + [None] * max(0, n_in - len(eqn.invars)),
                   [o if o < len(eqn.outvars) else None
                    for o in range(n_out)],
                   [None] * n_out)


def call_arg_maps(eqn) -> "list[SubCall] | None":
    """Structural wiring of a call-like eqn's sub-jaxprs.

    Returns None when the primitive has no sub-jaxprs; conservative
    1:1-mapped SubCalls for unknown call-likes whose arity lines up.
    """
    name = eqn.primitive.name
    p = eqn.params
    if name == "cond":
        out = []
        for br in p["branches"]:
            j = as_jaxpr(br)
            in_map = [k + 1 for k in range(len(j.invars))]  # skip pred
            out_map = list(range(len(j.outvars)))
            out.append(SubCall(j, in_map, out_map,
                               [None] * len(j.outvars)))
        return out
    if name == "while":
        cj, bj = as_jaxpr(p["cond_jaxpr"]), as_jaxpr(p["body_jaxpr"])
        cn, bn = p["cond_nconsts"], p["body_nconsts"]
        n_carry = len(bj.outvars)
        # eqn.invars = cond_consts + body_consts + init_carry
        cond_in = ([k for k in range(cn)]
                   + [cn + bn + k for k in range(n_carry)])
        body_in = ([cn + k for k in range(bn)]
                   + [cn + bn + k for k in range(n_carry)])
        return [
            SubCall(cj, cond_in, [None] * len(cj.outvars),
                    [None] * len(cj.outvars)),
            SubCall(bj, body_in, list(range(n_carry)),
                    [bn + k for k in range(n_carry)]),
        ]
    if name == "scan":
        j = as_jaxpr(p["jaxpr"])
        nc, ncar = p["num_consts"], p["num_carry"]
        n_out = len(j.outvars)
        return [SubCall(
            j, list(range(len(j.invars))),
            list(range(n_out)),
            [nc + k if k < ncar else None for k in range(n_out)])]
    if name in ("pjit", "closed_call", "core_call", "xla_call",
                "custom_jvp_call", "custom_vjp_call", "remat",
                "checkpoint", "custom_vjp_call_jaxpr", "remat2"):
        j = p.get("jaxpr") or p.get("call_jaxpr") or p.get("fun_jaxpr")
        if j is not None and hasattr(as_jaxpr(j), "eqns"):
            return [_direct(j, eqn)]
        return None
    # unknown primitive: if it carries sub-jaxprs whose invar count
    # matches the eqn's operand count, assume direct wiring
    subs = list(subjaxprs(eqn))
    if not subs:
        return None
    out = []
    for _, j in subs:
        jj = as_jaxpr(j)
        if len(jj.invars) == len(eqn.invars):
            out.append(_direct(jj, eqn))
        else:
            return []  # sub-jaxprs exist but wiring unknown: signal "opaque"
    return out


# ---------------------------------------------------------------------------
# dataflow pass 1: is an input ever consumed?  (knob-fold)
# ---------------------------------------------------------------------------


def used_invar_mask(jaxpr, *, count_outvars=False, _memo=None) -> "list[bool]":
    """Per-invar flag: does anything in the (recursively walked) program
    consume this input?

    An invar is "used" when it feeds any eqn — for call-like eqns, only
    when the corresponding inner invar is itself used (recursively), so
    a value merely threaded through a while carry untouched does not
    count at the top level unless `count_outvars` (inner jaxprs pass
    True: their outputs flow onward).  Over-approximates liveness (an
    eqn computing a dead value still counts as a use) — make_jaxpr
    output is not DCE'd, and tracing never records a value nothing
    consumed, so the approximation errs loud, not silent.
    """
    if _memo is None:
        _memo = {}
    j = as_jaxpr(jaxpr)
    key = (id(j), bool(count_outvars))
    if key in _memo:
        return _memo[key]
    used = set()
    if count_outvars:
        for v in j.outvars:
            if not isinstance(v, jax.core.Literal):
                used.add(v)
    for eqn in j.eqns:
        subs = call_arg_maps(eqn)
        if subs is None:
            for v in eqn.invars:
                if not isinstance(v, jax.core.Literal):
                    used.add(v)
        elif not subs:  # opaque call-like: conservatively all-used
            for v in eqn.invars:
                if not isinstance(v, jax.core.Literal):
                    used.add(v)
        else:
            for sc in subs:
                inner = used_invar_mask(sc.jaxpr, count_outvars=True,
                                        _memo=_memo)
                for i, u in enumerate(inner):
                    if u and i < len(sc.in_map) \
                            and sc.in_map[i] is not None:
                        v = eqn.invars[sc.in_map[i]]
                        if not isinstance(v, jax.core.Literal):
                            used.add(v)
    mask = [v in used for v in j.invars]
    _memo[key] = mask
    return mask


# ---------------------------------------------------------------------------
# dataflow pass 2: forward time-taint + integer-narrowing detection
# ---------------------------------------------------------------------------

# Primitives through which "absolute simulated time" does NOT propagate:
# differences (latencies/deltas — legitimately int32, time_types.
# DELTA_DTYPE), ratios/remainders (quantum phases, ring slots),
# predicates, bit twiddling, and index-producing reductions.
TAINT_STOP = frozenset({
    "sub", "div", "rem", "eq", "ne", "lt", "le", "gt", "ge",
    "and", "or", "xor", "not", "shift_left", "shift_right_logical",
    "shift_right_arithmetic", "argmin", "argmax", "reduce_and",
    "reduce_or", "iota", "sign", "population_count", "clz",
    "is_finite", "stop_gradient",
})

_INT_KINDS = ("i", "u")


def _is_narrowing(old_dtype, new_dtype) -> bool:
    o, n = np.dtype(old_dtype), np.dtype(new_dtype)
    return (o.kind in _INT_KINDS and n.kind in _INT_KINDS
            and n.itemsize < o.itemsize)


def taint_narrowing(jaxpr, in_taint, on_finding=None, _site="",
                    _depth=0) -> "list[bool]":
    """Forward taint from `in_taint`-marked invars; report every integer
    narrowing of a tainted value via `on_finding(site, eqn, old, new)`.

    Taint propagates through value-preserving/monotone arithmetic (add,
    mul, min/max, selects, data movement, scatters, reductions) and
    crosses call boundaries (cond/while/scan/pjit) via `call_arg_maps`,
    iterating loop carries to a fixpoint.  It STOPS at `TAINT_STOP` —
    a difference of two absolute clocks is a delta, which the engine
    legitimately keeps in int32 (time_types.DELTA_DTYPE).  Returns the
    outvar taint mask.
    """
    j = as_jaxpr(jaxpr)
    env = {}
    for v, t in zip(j.invars, in_taint):
        env[v] = bool(t)

    def get(v):
        return (not isinstance(v, jax.core.Literal)) and env.get(v, False)

    for eqn in j.eqns:
        site = (f"{_site}.{eqn.primitive.name}" if _site
                else eqn.primitive.name)
        tin = [get(v) for v in eqn.invars]
        name = eqn.primitive.name
        subs = call_arg_maps(eqn)
        if subs:
            out_taint = [False] * len(eqn.outvars)

            def inner_taint(sc, jj, marks):
                return [marks[sc.in_map[i]]
                        if i < len(sc.in_map)
                        and sc.in_map[i] is not None else False
                        for i in range(len(jj.invars))]

            # Stabilize loop-carry taint FIRST, at the eqn-operand
            # level: a carry that becomes tainted in a later iteration
            # taints that operand position for EVERY sub-jaxpr —
            # including the while-COND's copy of it, which has no
            # feedback edges of its own (a narrowing in the loop
            # condition must still be reported).
            tin_eff = list(tin)
            for sc in subs:
                if not any(f is not None for f in sc.feedback):
                    continue
                jj = as_jaxpr(sc.jaxpr)
                for _ in range(len(jj.outvars) + 2):
                    inner_out = taint_narrowing(
                        jj, inner_taint(sc, jj, tin_eff), None, site,
                        _depth + 1)
                    changed = False
                    for o, fb in enumerate(sc.feedback):
                        if fb is None or not inner_out[o] \
                                or fb >= len(sc.in_map):
                            continue
                        op_i = sc.in_map[fb]
                        if op_i is not None and not tin_eff[op_i]:
                            tin_eff[op_i] = True
                            changed = True
                    if not changed:
                        break
            # one reporting pass per sub-jaxpr with the stable marks
            for sc in subs:
                jj = as_jaxpr(sc.jaxpr)
                inner_out = taint_narrowing(
                    jj, inner_taint(sc, jj, tin_eff), on_finding, site,
                    _depth + 1)
                for o, t in enumerate(inner_out):
                    if t and o < len(sc.out_map) \
                            and sc.out_map[o] is not None:
                        out_taint[sc.out_map[o]] = True
            for v, t in zip(eqn.outvars, out_taint):
                env[v] = t
            continue
        if subs == []:  # opaque call-like: conservative taint-through
            t = any(tin)
            for v in eqn.outvars:
                env[v] = t
            continue
        if name == "convert_element_type":
            old = getattr(eqn.invars[0].aval, "dtype", None)
            new = eqn.params.get("new_dtype")
            if tin[0] and old is not None and new is not None \
                    and _is_narrowing(old, new):
                if on_finding is not None:
                    on_finding(site, eqn, old, new)
                env[eqn.outvars[0]] = False  # reported; don't cascade
            else:
                env[eqn.outvars[0]] = tin[0]
            continue
        if name.startswith("scatter"):
            # scatter(operand, indices, updates): tainted updates landing
            # in a narrower accumulator is an int32 time accumulation
            upd_i = 2 if len(eqn.invars) > 2 else len(eqn.invars) - 1
            tgt = getattr(eqn.invars[0].aval, "dtype", None)
            upd = getattr(eqn.invars[upd_i].aval, "dtype", None)
            if tin[upd_i] and tgt is not None and upd is not None \
                    and _is_narrowing(upd, tgt):
                if on_finding is not None:
                    on_finding(site, eqn, upd, tgt)
                env[eqn.outvars[0]] = False
            else:
                env[eqn.outvars[0]] = tin[0] or tin[upd_i]
            continue
        tainted = any(tin) and name not in TAINT_STOP
        for v in eqn.outvars:
            env[v] = tainted
    return [get(v) for v in j.outvars]
