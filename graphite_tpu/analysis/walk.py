"""Reusable jaxpr traversal: the program auditor's walker.

jax programs arrive as nested jaxprs: `cond` carries one branch jaxpr
per arm, `while` a cond and a body, `scan`/`pjit`/`remat`/custom-
derivative calls one inner jaxpr each — and `vmap` leaves no call at
all (batching rewrites eqns in place, which is exactly why a gated
cond can silently become a both-branch select under it).  Every
auditor rule (analysis/rules.py) and every structural test assertion
walks the SAME recursion below — the traversal the round-6
phase-gating test used to keep as a private `_walk_eqns` helper.

Four layers:
 - `iter_eqns` / `iter_eqns_with_site`: flat iteration over every eqn
   at every nesting depth (site strings name the path for findings);
 - `call_arg_maps`: the structural operand<->sub-jaxpr wiring of the
   call-like primitives, so dataflow analyses can cross call
   boundaries instead of stopping at them;
 - `used_invar_mask` / `taint_narrowing`: the two dataflow passes the
   rules are built on — "is this input ever consumed?" (knob-fold)
   and "does a value derived from this input get integer-narrowed?"
   (time-dtype);
 - `Scope` / `distinct_axes` / `masked_index_select`: backward value
   provenance for scatter INDEX operands — "is this index array
   provably collision-free (an iota column survives into every row)"
   and "is this the engines' masked scratch-redirect idiom" — the
   round-11 scatter-determinism rule's analysis.  Resolution follows
   def chains upward through cond/scan/pjit boundaries via
   `call_arg_maps` (loop-carried positions stay unresolved: their
   value changes across iterations).
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np


def as_jaxpr(j):
    """Normalize ClosedJaxpr | Jaxpr -> Jaxpr."""
    inner = getattr(j, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        return inner
    return j


def subjaxprs(eqn):
    """Yield (tag, Jaxpr) for every sub-jaxpr in eqn.params.

    Handles both ClosedJaxpr-valued params (cond branches, while
    cond/body, scan/pjit jaxprs) and raw-Jaxpr values, singly or in
    tuples/lists — the same duck-typing the primitives themselves use.
    """
    for name, val in eqn.params.items():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for i, v in enumerate(vals):
            tag = name if len(vals) == 1 else f"{name}[{i}]"
            inner = getattr(v, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                yield tag, inner
            elif hasattr(v, "eqns"):
                yield tag, v


def iter_eqns_with_site(jaxpr, _site=""):
    """Depth-first (eqn-order) walk yielding (site, eqn) at every
    nesting depth.  `site` is a readable path like
    "while/body.cond/branches[1].scatter-add"."""
    j = as_jaxpr(jaxpr)
    for eqn in j.eqns:
        here = (f"{_site}.{eqn.primitive.name}" if _site
                else eqn.primitive.name)
        yield here, eqn
        for tag, inner in subjaxprs(eqn):
            yield from iter_eqns_with_site(inner, f"{here}/{tag}")


def iter_eqns(jaxpr):
    """Every eqn of `jaxpr` and all its sub-jaxprs, depth-first."""
    for _, eqn in iter_eqns_with_site(jaxpr):
        yield eqn


def find_eqns(jaxpr, primitive_name: str):
    """All (site, eqn) whose primitive is named `primitive_name`."""
    return [(s, e) for s, e in iter_eqns_with_site(jaxpr)
            if e.primitive.name == primitive_name]


def aval_bytes(aval) -> int:
    """Byte size of an abstract value (0 for non-array avals)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * np.dtype(dtype).itemsize


def aval_sig(aval):
    """Normalized (shape, dtype-string) signature, or None."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return None
    return (tuple(int(d) for d in shape), str(np.dtype(dtype)))


def invar_path_strings(args) -> "list[str]":
    """keystr paths of `args`' pytree leaves, in flatten order — which
    is exactly the invar order `jax.make_jaxpr(fn)(*args)` produces, so
    path i names closed.jaxpr.invars[i] (None leaves drop from both)."""
    leaves, _ = jax.tree_util.tree_flatten_with_path(args)
    return [jax.tree_util.keystr(p) for p, _ in leaves]


# ---------------------------------------------------------------------------
# operand <-> sub-jaxpr wiring of the call-like primitives
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SubCall:
    """One sub-jaxpr of a call-like eqn plus its wiring.

    in_map[i]   = eqn operand index feeding inner invar i (None: none)
    out_map[o]  = eqn outvar index fed by inner outvar o (None: none)
    feedback[o] = inner invar index inner outvar o loops back into
                  (while/scan carries), None otherwise
    """

    jaxpr: object
    in_map: list
    out_map: list
    feedback: list


def _direct(jaxpr, eqn):
    j = as_jaxpr(jaxpr)
    n_in, n_out = len(j.invars), len(j.outvars)
    return SubCall(j, list(range(min(n_in, len(eqn.invars))))
                   + [None] * max(0, n_in - len(eqn.invars)),
                   [o if o < len(eqn.outvars) else None
                    for o in range(n_out)],
                   [None] * n_out)


def call_arg_maps(eqn) -> "list[SubCall] | None":
    """Structural wiring of a call-like eqn's sub-jaxprs.

    Returns None when the primitive has no sub-jaxprs; conservative
    1:1-mapped SubCalls for unknown call-likes whose arity lines up.
    """
    name = eqn.primitive.name
    p = eqn.params
    if name == "cond":
        out = []
        for br in p["branches"]:
            j = as_jaxpr(br)
            in_map = [k + 1 for k in range(len(j.invars))]  # skip pred
            out_map = list(range(len(j.outvars)))
            out.append(SubCall(j, in_map, out_map,
                               [None] * len(j.outvars)))
        return out
    if name == "while":
        cj, bj = as_jaxpr(p["cond_jaxpr"]), as_jaxpr(p["body_jaxpr"])
        cn, bn = p["cond_nconsts"], p["body_nconsts"]
        n_carry = len(bj.outvars)
        # eqn.invars = cond_consts + body_consts + init_carry
        cond_in = ([k for k in range(cn)]
                   + [cn + bn + k for k in range(n_carry)])
        body_in = ([cn + k for k in range(bn)]
                   + [cn + bn + k for k in range(n_carry)])
        return [
            SubCall(cj, cond_in, [None] * len(cj.outvars),
                    [None] * len(cj.outvars)),
            SubCall(bj, body_in, list(range(n_carry)),
                    [bn + k for k in range(n_carry)]),
        ]
    if name == "scan":
        j = as_jaxpr(p["jaxpr"])
        nc, ncar = p["num_consts"], p["num_carry"]
        n_out = len(j.outvars)
        return [SubCall(
            j, list(range(len(j.invars))),
            list(range(n_out)),
            [nc + k if k < ncar else None for k in range(n_out)])]
    if name in ("pjit", "closed_call", "core_call", "xla_call",
                "custom_jvp_call", "custom_vjp_call", "remat",
                "checkpoint", "custom_vjp_call_jaxpr", "remat2"):
        j = p.get("jaxpr") or p.get("call_jaxpr") or p.get("fun_jaxpr")
        if j is not None and hasattr(as_jaxpr(j), "eqns"):
            return [_direct(j, eqn)]
        return None
    # unknown primitive: if it carries sub-jaxprs whose invar count
    # matches the eqn's operand count, assume direct wiring
    subs = list(subjaxprs(eqn))
    if not subs:
        return None
    out = []
    for _, j in subs:
        jj = as_jaxpr(j)
        if len(jj.invars) == len(eqn.invars):
            out.append(_direct(jj, eqn))
        else:
            return []  # sub-jaxprs exist but wiring unknown: signal "opaque"
    return out


# ---------------------------------------------------------------------------
# dataflow pass 1: is an input ever consumed?  (knob-fold)
# ---------------------------------------------------------------------------


def used_invar_mask(jaxpr, *, count_outvars=False, _memo=None) -> "list[bool]":
    """Per-invar flag: does anything in the (recursively walked) program
    consume this input?

    An invar is "used" when it feeds any eqn — for call-like eqns, only
    when the corresponding inner invar is itself used (recursively), so
    a value merely threaded through a while carry untouched does not
    count at the top level unless `count_outvars` (inner jaxprs pass
    True: their outputs flow onward).  Over-approximates liveness (an
    eqn computing a dead value still counts as a use) — make_jaxpr
    output is not DCE'd, and tracing never records a value nothing
    consumed, so the approximation errs loud, not silent.
    """
    if _memo is None:
        _memo = {}
    j = as_jaxpr(jaxpr)
    key = (id(j), bool(count_outvars))
    if key in _memo:
        return _memo[key]
    used = set()
    if count_outvars:
        for v in j.outvars:
            if not isinstance(v, jax.core.Literal):
                used.add(v)
    for eqn in j.eqns:
        subs = call_arg_maps(eqn)
        if subs is None:
            for v in eqn.invars:
                if not isinstance(v, jax.core.Literal):
                    used.add(v)
        elif not subs:  # opaque call-like: conservatively all-used
            for v in eqn.invars:
                if not isinstance(v, jax.core.Literal):
                    used.add(v)
        else:
            for sc in subs:
                inner = used_invar_mask(sc.jaxpr, count_outvars=True,
                                        _memo=_memo)
                for i, u in enumerate(inner):
                    if u and i < len(sc.in_map) \
                            and sc.in_map[i] is not None:
                        v = eqn.invars[sc.in_map[i]]
                        if not isinstance(v, jax.core.Literal):
                            used.add(v)
    mask = [v in used for v in j.invars]
    _memo[key] = mask
    return mask


# ---------------------------------------------------------------------------
# dataflow pass 2: forward time-taint + integer-narrowing detection
# ---------------------------------------------------------------------------

# Primitives through which "absolute simulated time" does NOT propagate:
# differences (latencies/deltas — legitimately int32, time_types.
# DELTA_DTYPE), ratios/remainders (quantum phases, ring slots),
# predicates, bit twiddling, and index-producing reductions.
TAINT_STOP = frozenset({
    "sub", "div", "rem", "eq", "ne", "lt", "le", "gt", "ge",
    "and", "or", "xor", "not", "shift_left", "shift_right_logical",
    "shift_right_arithmetic", "argmin", "argmax", "reduce_and",
    "reduce_or", "iota", "sign", "population_count", "clz",
    "is_finite", "stop_gradient",
})

_INT_KINDS = ("i", "u")


def _is_narrowing(old_dtype, new_dtype) -> bool:
    o, n = np.dtype(old_dtype), np.dtype(new_dtype)
    return (o.kind in _INT_KINDS and n.kind in _INT_KINDS
            and n.itemsize < o.itemsize)


def taint_narrowing(jaxpr, in_taint, on_finding=None, _site="",
                    _depth=0) -> "list[bool]":
    """Forward taint from `in_taint`-marked invars; report every integer
    narrowing of a tainted value via `on_finding(site, eqn, old, new)`.

    Taint propagates through value-preserving/monotone arithmetic (add,
    mul, min/max, selects, data movement, scatters, reductions) and
    crosses call boundaries (cond/while/scan/pjit) via `call_arg_maps`,
    iterating loop carries to a fixpoint.  It STOPS at `TAINT_STOP` —
    a difference of two absolute clocks is a delta, which the engine
    legitimately keeps in int32 (time_types.DELTA_DTYPE).  Returns the
    outvar taint mask.
    """
    j = as_jaxpr(jaxpr)
    env = {}
    for v, t in zip(j.invars, in_taint):
        env[v] = bool(t)

    def get(v):
        return (not isinstance(v, jax.core.Literal)) and env.get(v, False)

    for eqn in j.eqns:
        site = (f"{_site}.{eqn.primitive.name}" if _site
                else eqn.primitive.name)
        tin = [get(v) for v in eqn.invars]
        name = eqn.primitive.name
        subs = call_arg_maps(eqn)
        if subs:
            out_taint = [False] * len(eqn.outvars)

            def inner_taint(sc, jj, marks):
                return [marks[sc.in_map[i]]
                        if i < len(sc.in_map)
                        and sc.in_map[i] is not None else False
                        for i in range(len(jj.invars))]

            # Stabilize loop-carry taint FIRST, at the eqn-operand
            # level: a carry that becomes tainted in a later iteration
            # taints that operand position for EVERY sub-jaxpr —
            # including the while-COND's copy of it, which has no
            # feedback edges of its own (a narrowing in the loop
            # condition must still be reported).
            tin_eff = list(tin)
            for sc in subs:
                if not any(f is not None for f in sc.feedback):
                    continue
                jj = as_jaxpr(sc.jaxpr)
                for _ in range(len(jj.outvars) + 2):
                    inner_out = taint_narrowing(
                        jj, inner_taint(sc, jj, tin_eff), None, site,
                        _depth + 1)
                    changed = False
                    for o, fb in enumerate(sc.feedback):
                        if fb is None or not inner_out[o] \
                                or fb >= len(sc.in_map):
                            continue
                        op_i = sc.in_map[fb]
                        if op_i is not None and not tin_eff[op_i]:
                            tin_eff[op_i] = True
                            changed = True
                    if not changed:
                        break
            # one reporting pass per sub-jaxpr with the stable marks
            for sc in subs:
                jj = as_jaxpr(sc.jaxpr)
                inner_out = taint_narrowing(
                    jj, inner_taint(sc, jj, tin_eff), on_finding, site,
                    _depth + 1)
                for o, t in enumerate(inner_out):
                    if t and o < len(sc.out_map) \
                            and sc.out_map[o] is not None:
                        out_taint[sc.out_map[o]] = True
            for v, t in zip(eqn.outvars, out_taint):
                env[v] = t
            continue
        if subs == []:  # opaque call-like: conservative taint-through
            t = any(tin)
            for v in eqn.outvars:
                env[v] = t
            continue
        if name == "convert_element_type":
            old = getattr(eqn.invars[0].aval, "dtype", None)
            new = eqn.params.get("new_dtype")
            if tin[0] and old is not None and new is not None \
                    and _is_narrowing(old, new):
                if on_finding is not None:
                    on_finding(site, eqn, old, new)
                env[eqn.outvars[0]] = False  # reported; don't cascade
            else:
                env[eqn.outvars[0]] = tin[0]
            continue
        if name.startswith("scatter"):
            # scatter(operand, indices, updates): tainted updates landing
            # in a narrower accumulator is an int32 time accumulation
            upd_i = 2 if len(eqn.invars) > 2 else len(eqn.invars) - 1
            tgt = getattr(eqn.invars[0].aval, "dtype", None)
            upd = getattr(eqn.invars[upd_i].aval, "dtype", None)
            if tin[upd_i] and tgt is not None and upd is not None \
                    and _is_narrowing(upd, tgt):
                if on_finding is not None:
                    on_finding(site, eqn, upd, tgt)
                env[eqn.outvars[0]] = False
            else:
                env[eqn.outvars[0]] = tin[0] or tin[upd_i]
            continue
        tainted = any(tin) and name not in TAINT_STOP
        for v in eqn.outvars:
            env[v] = tainted
    return [get(v) for v in j.outvars]


# ---------------------------------------------------------------------------
# dataflow pass 3: backward index provenance (scatter-determinism)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Scope:
    """One jaxpr nesting level of a provenance walk: def sites at this
    level plus the wiring back to the enclosing level, so a value that
    enters a cond branch (or a scan body's const slot) as an invar can
    be chased to its real definition outside."""

    jaxpr: object                  # the (raw) Jaxpr of this level
    defs: dict                     # var -> defining eqn at this level
    parent: "Scope | None" = None
    parent_eqn: object | None = None   # the call-like eqn that owns us
    sub: "SubCall | None" = None       # our wiring inside parent_eqn
    consts: dict = dataclasses.field(default_factory=dict)
    # var -> concrete array for the top ClosedJaxpr's constvars — lets
    # the analysis check hoisted np.arange tables for real uniqueness


def make_scope(jaxpr, parent=None, parent_eqn=None, sub=None,
               consts: "dict | None" = None) -> Scope:
    j = as_jaxpr(jaxpr)
    defs = {}
    for eqn in j.eqns:
        for v in eqn.outvars:
            defs[v] = eqn
    return Scope(j, defs, parent, parent_eqn, sub, consts or {})


def scope_from_closed(closed) -> Scope:
    """Top-level Scope of a ClosedJaxpr, with its consts resolvable."""
    j = as_jaxpr(closed)
    consts = {}
    for v, c in zip(j.constvars, getattr(closed, "consts", ()) or ()):
        consts[v] = np.asarray(c) if hasattr(c, "shape") else c
    return make_scope(j, consts=consts)


def resolve_var(var, scope: Scope):
    """Chase `var` one level up when it is an invar of `scope.jaxpr`:
    returns (outer_var, outer_scope, axis_shift) or (None, None, 0)
    when the definition cannot be followed (top-level input, loop
    carry, opaque wiring).  axis_shift is 1 when the outer value is a
    scan xs operand the body sees one leading axis short."""
    if scope.parent is None or scope.sub is None:
        return None, None, 0
    try:
        i = list(scope.jaxpr.invars).index(var)
    except ValueError:
        return None, None, 0
    sub = scope.sub
    # loop-carried slots change value across iterations: unresolvable
    if any(fb == i for fb in sub.feedback if fb is not None):
        return None, None, 0
    if i >= len(sub.in_map) or sub.in_map[i] is None:
        return None, None, 0
    if scope.parent_eqn.primitive.name == "while":
        # the while COND's SubCall carries no feedback edges of its
        # own, but its carry slots are just as iteration-variant as
        # the body's: everything past the two const blocks is carry
        cn = scope.parent_eqn.params["cond_nconsts"]
        bn = scope.parent_eqn.params["body_nconsts"]
        if sub.in_map[i] >= cn + bn:
            return None, None, 0
    outer = scope.parent_eqn.invars[sub.in_map[i]]
    if isinstance(outer, jax.core.Literal):
        return None, None, 0
    shift = 0
    if scope.parent_eqn.primitive.name == "scan":
        r_out = len(getattr(outer.aval, "shape", ()) or ())
        r_in = len(getattr(var.aval, "shape", ()) or ())
        if r_out == r_in + 1:
            shift = 1   # an xs operand: the body sees slice [l, ...]
    return outer, scope.parent, shift


# Per-axis provenance forms (the value of _axis_forms):
#   ("D",)       distinct: any two positions differing in this axis
#                hold different values (no congruence info — e.g. a
#                concrete const table checked exhaustively)
#   (m, c)       affine-congruent: value = pos + c exactly when m == 0,
#                else value ≡ pos + c (mod m).  c may be None for an
#                unknown-but-uniform shift (e.g. pos + traced_scalar).
#                Distinct along an axis of size n iff m == 0 or m >= n.
# The congruence form is what survives the engines' wraparound idiom
# (`jnp.where(h < T, h, h - T)` -> select_n of pos+c1 / pos+c2 arms:
# both ≡ pos mod |c1-c2|, still collision-free at the axis size).


def _const_axis_forms(arr) -> dict:
    """("D",) for every axis of a concrete array along which all pairs
    of positions differ (checked exhaustively — consts are host-side
    and small)."""
    arr = np.asarray(arr)
    out = {}
    for a in range(arr.ndim):
        m = np.moveaxis(arr, a, 0).reshape(arr.shape[a], -1)
        # need every pair of rows to differ in EVERY column
        if all(len(np.unique(m[:, c])) == m.shape[0]
               for c in range(m.shape[1])):
            out[a] = ("D",)
    return out


_DISTINCT_PASS_THROUGH = frozenset({
    "convert_element_type", "copy", "stop_gradient",
    # jnp.asarray(host_const) inserts a device_put between a hoisted
    # index table and its use — value-preserving movement, without
    # which Scope.consts/_const_axis_forms is unreachable
    "device_put",
})

_DIRECT_CALLS = frozenset({
    "pjit", "closed_call", "core_call", "xla_call", "custom_jvp_call",
    "custom_vjp_call", "remat", "checkpoint", "remat2",
})

_PROVENANCE_DEPTH = 24


def _descend_outvar(eqn, var, scope: Scope):
    """When `var` is an output of a direct-call eqn (pjit et al — the
    wrappers jnp.where/jnp.mod lowerings hide behind), step INTO the
    sub-jaxpr: returns (inner outvar, inner Scope) or None."""
    if eqn.primitive.name not in _DIRECT_CALLS:
        return None
    subs = call_arg_maps(eqn)
    if not subs:
        return None
    sub = subs[0]
    try:
        o = list(eqn.outvars).index(var)
    except ValueError:
        return None
    for io, oo in enumerate(sub.out_map):
        if oo == o:
            inner = as_jaxpr(sub.jaxpr).outvars[io]
            if isinstance(inner, jax.core.Literal):
                return None
            return inner, make_scope(sub.jaxpr, scope, eqn, sub)
    return None


def _scalar_literal(v, scope: Scope):
    """The Python value of a scalar literal (chasing trivial
    broadcasts/converts), or None."""
    for _ in range(6):
        if isinstance(v, jax.core.Literal):
            val = np.asarray(v.val)
            return val.item() if val.ndim == 0 else None
        if getattr(v.aval, "shape", None) == () and v in scope.consts:
            return np.asarray(scope.consts[v]).item()
        e = scope.defs.get(v)
        if e is None or e.primitive.name not in (
                "broadcast_in_dim", "convert_element_type", "reshape",
                "squeeze", "copy"):
            return None
        v = e.invars[0]
    return None


def _peel_uniform_shift(v, scope: Scope):
    """Resolve `v` to (base var, base scope, accumulated literal shift)
    by peeling add/sub of scalar literals and trivial wrappers — the
    shape of a wrap-fixup select arm (`t` and `t - T` share base `t`
    with shifts 0 and -T).  Returns None when `v` is a literal or the
    chain leaves the provable shape."""
    if isinstance(v, jax.core.Literal):
        return None
    shift = 0
    for _ in range(12):
        eqn = scope.defs.get(v)
        if eqn is None:
            v2, s2, sh = resolve_var(v, scope)
            if v2 is None or sh:
                break
            v, scope = v2, s2
            continue
        down = _descend_outvar(eqn, v, scope)
        if down is not None:
            v, scope = down
            continue
        name = eqn.primitive.name
        if name in ("add", "sub"):
            x, y = eqn.invars[0], eqn.invars[1]
            k = _scalar_literal(y, scope)
            if k is not None and not isinstance(x, jax.core.Literal):
                shift += -int(k) if name == "sub" else int(k)
                v = x
                continue
            if name == "add":
                k = _scalar_literal(x, scope)
                if k is not None \
                        and not isinstance(y, jax.core.Literal):
                    shift += int(k)
                    v = y
                    continue
            break
        if name in _DISTINCT_PASS_THROUGH:
            v = eqn.invars[0]
            continue
        break
    return v, scope, shift


def _const_cross_shift_distinct(arr, axis: int, shifts) -> bool:
    """For a concrete index table: can two positions along `axis` (same
    other coordinates) collide under ANY per-position choice of the
    literal `shifts`?  Exhaustive, like _const_axis_forms — this is
    what lets a no-repeat const table stay proven through the .at[]
    wrap-fixup select (`select(d < 0, d, d + N)`), whose arms shift
    the same base by different amounts."""
    arr = np.asarray(arr)
    m = np.moveaxis(arr, axis, 0).reshape(arr.shape[axis], -1)
    shifts = sorted({int(s) for s in shifts})
    for c in range(m.shape[1]):
        seen = {}
        for i, x in enumerate(m[:, c]):
            for s in shifts:
                key = int(x) + s
                if seen.setdefault(key, i) != i:
                    return False
    return True


def _is_uniform_scalar(v, scope: Scope, _depth: int = 0) -> bool:
    """Does `v` hold one value replicated everywhere (a broadcast of a
    scalar)?  Adding such an operand shifts every position equally, so
    per-axis distinctness survives even when the value is traced; a
    select arm like this is a single redirect slot."""
    if _depth > 12:
        return False
    while True:
        if isinstance(v, jax.core.Literal):
            val = np.asarray(v.val)
            return val.ndim == 0 or len(np.unique(val)) == 1
        if getattr(v.aval, "shape", None) == ():
            return True
        eqn = scope.defs.get(v)
        if eqn is not None:
            down = _descend_outvar(eqn, v, scope)
            if down is None:
                break
            v, scope = down
            continue
        if v in scope.consts:
            c = np.asarray(scope.consts[v])
            return c.size == 1 or len(np.unique(c)) == 1
        v2, s2, shift = resolve_var(v, scope)
        if v2 is None:
            return False
        v, scope = v2, s2
    # broadcasting/reshaping a uniform value stays uniform
    if eqn.primitive.name in (
            "broadcast_in_dim", "reshape", "squeeze", "copy",
            "convert_element_type", "stop_gradient", "expand_dims"):
        return _is_uniform_scalar(eqn.invars[0], scope, _depth + 1)
    return False


def _merge_arm_forms(forms: "list") -> "tuple | None":
    """Combine per-arm forms of an elementwise select: every position
    takes SOME arm's value, so the result is congruent mod the gcd of
    the arms' moduli and pairwise offset differences."""
    if any(f is None for f in forms):
        return None
    if all(f == ("D",) for f in forms) and len(forms) == 1:
        return ("D",)
    if any(f == ("D",) for f in forms):
        return None   # no congruence info to reconcile the arms with
    if any(f[1] is None for f in forms):
        # unknown shifts: offset differences unprovable across arms
        return forms[0] if len(forms) == 1 else None
    g = 0
    for f in forms:
        g = int(np.gcd(g, int(f[0])))
    c0 = forms[0][1]
    for f in forms[1:]:
        g = int(np.gcd(g, abs(int(f[1]) - int(c0))))
    return (g, c0 % g if g else c0)


def _axis_forms(var, scope: Scope, _depth: int = 0) -> dict:
    """axis -> provenance form (see above) for `var`.  Conservative:
    a missing axis means "not provable", never "aliasing"."""
    if _depth > _PROVENANCE_DEPTH or isinstance(var, jax.core.Literal):
        return {}
    while True:
        eqn = scope.defs.get(var)
        if eqn is not None:
            down = _descend_outvar(eqn, var, scope)
            if down is None:
                break
            var, scope = down
            continue
        if var in scope.consts:
            return _const_axis_forms(scope.consts[var])
        var2, scope2, shift = resolve_var(var, scope)
        if var2 is None:
            return {}
        if shift:
            outer = _axis_forms(var2, scope2, _depth + 1)
            return {a - 1: f for a, f in outer.items() if a >= 1}
        var, scope = var2, scope2
    name = eqn.primitive.name
    if name == "iota":
        return {int(eqn.params["dimension"]): (0, 0)}
    if name in _DISTINCT_PASS_THROUGH:
        return _axis_forms(eqn.invars[0], scope, _depth + 1)
    if name in ("add", "sub"):
        x, y = eqn.invars[0], eqn.invars[1]
        # value = structured + uniform shift: distinctness survives,
        # and a literal shift keeps the congruence offset exact
        candidates = [(x, y, -1 if name == "sub" else 1)]
        if name == "add":
            candidates.append((y, x, 1))
        for a, b, sign in candidates:
            if isinstance(a, jax.core.Literal) \
                    or not _is_uniform_scalar(b, scope):
                continue
            forms = _axis_forms(a, scope, _depth + 1)
            k = _scalar_literal(b, scope)
            out = {}
            for ax, f in forms.items():
                if f == ("D",):
                    out[ax] = f
                elif k is None or f[1] is None:
                    out[ax] = (f[0], None)
                else:
                    c = int(f[1]) + sign * int(k)
                    out[ax] = (f[0], c % f[0] if f[0] else c)
            return out
        return {}
    if name == "rem":
        r = _scalar_literal(eqn.invars[1], scope)
        if r is None or int(r) <= 0:
            return {}
        r = int(r)
        forms = _axis_forms(eqn.invars[0], scope, _depth + 1)
        out = {}
        for ax, f in forms.items():
            if f == ("D",):
                continue   # remainder of an arbitrary table can collide
            m, c = f
            if m == 0 or m % r == 0:
                out[ax] = (r, None if c is None else int(c) % r)
        return out
    if name == "select_n":
        # shared-base arms first (the .at[] wrap fixup: select(p, t,
        # t - T)): the arms' absolute offsets may be unknown, but
        # their RELATIVE literal shifts still pin congruence mod the
        # shift gcd — per position the value is base + shift_j, so
        # distinctness mod gcd(base modulus, shift differences) holds
        peeled = [_peel_uniform_shift(v, scope)
                  for v in eqn.invars[1:]]
        if len(peeled) > 1 and all(p is not None for p in peeled):
            b0, s0, k0 = peeled[0]
            if all(p[0] is b0 and p[1].jaxpr is s0.jaxpr
                   for p in peeled[1:]):
                g = 0
                for _, _, k in peeled[1:]:
                    g = int(np.gcd(g, abs(int(k) - int(k0))))
                cval = s0.consts.get(b0)
                shifts = [k0] + [p[2] for p in peeled[1:]]
                out = {}
                for ax, f in _axis_forms(b0, s0, _depth + 1).items():
                    if f == ("D",):
                        # identical shifts are a pure copy; differing
                        # shifts keep a CONST table distinct exactly
                        # when no cross-shift pair collides (checked
                        # exhaustively, consts are small)
                        if g == 0 or (cval is not None
                                      and _const_cross_shift_distinct(
                                          cval, ax, shifts)):
                            out[ax] = f
                        continue
                    if g == 0:
                        m, c = int(f[0]), f[1]
                        out[ax] = (m, None if c is None
                                   else (int(c) + k0) % m if m
                                   else int(c) + k0)
                        continue
                    m = int(np.gcd(int(f[0]), g))
                    if m:
                        out[ax] = (m, None if f[1] is None
                                   else (int(f[1]) + k0) % m)
                return out
        arms = [
            _axis_forms(v, scope, _depth + 1)
            if not isinstance(v, jax.core.Literal) else {}
            for v in eqn.invars[1:]
        ]
        out = {}
        for ax in set().union(*[set(a) for a in arms]) if arms else ():
            merged = _merge_arm_forms([a.get(ax) for a in arms])
            if merged is not None:
                out[ax] = merged
        return out
    if name == "broadcast_in_dim":
        inner = _axis_forms(eqn.invars[0], scope, _depth + 1)
        bd = eqn.params["broadcast_dimensions"]
        in_shape = getattr(eqn.invars[0].aval, "shape", ())
        return {
            int(bd[a]): f for a, f in inner.items()
            if a < len(bd) and int(in_shape[a]) ==
            int(eqn.outvars[0].aval.shape[bd[a]])
        }
    if name in ("reshape", "squeeze"):
        # only size-1 insertions/removals are tracked: the non-unit
        # dims must survive in order for the axis map to be sound
        in_shape = tuple(getattr(eqn.invars[0].aval, "shape", ()))
        out_shape = tuple(eqn.outvars[0].aval.shape)
        in_nz = [a for a, d in enumerate(in_shape) if d != 1]
        out_nz = [a for a, d in enumerate(out_shape) if d != 1]
        if [in_shape[a] for a in in_nz] != [out_shape[a] for a in out_nz]:
            return {}
        inner = _axis_forms(eqn.invars[0], scope, _depth + 1)
        remap = dict(zip(in_nz, out_nz))
        return {remap[a]: f for a, f in inner.items() if a in remap}
    if name == "concatenate":
        d = int(eqn.params["dimension"])
        out = {}
        for v in eqn.invars:
            for a, f in _axis_forms(v, scope, _depth + 1).items():
                if a != d and a not in out:
                    out[a] = f
        return out
    return {}


def distinct_axes(var, scope: Scope) -> frozenset:
    """Axes `a` of `var` with the pairwise-distinct property: any two
    positions differing in axis `a` hold different values, regardless
    of the other coordinates.  Proven by provenance (`_axis_forms`):
    an iota column, a concrete const table with no repeats, or an
    affine-congruent form whose modulus covers the axis size (the
    wraparound-select idiom).  Conservative: an empty set means "not
    provable", not "aliasing"."""
    shape = tuple(getattr(var.aval, "shape", ()) or ())
    out = set()
    for a, f in _axis_forms(var, scope).items():
        if a >= len(shape):
            continue
        if f == ("D",) or f[0] == 0 or f[0] >= int(shape[a]):
            out.add(a)
    return frozenset(out)


def masked_index_select(var, scope: Scope, _depth: int = 0) -> bool:
    """Is `var` an index array built by the engines' masked
    scratch-redirect idiom — a select between real indices and a
    uniform scratch slot (`jnp.where(mask, word, SCRATCH)`), the
    round-9 "masked store" shape?  Such a scatter is masked BY
    CONSTRUCTION: disabled lanes all land on the dedicated slot.  The
    detection sees through jnp's pjit-wrapped where/mod composites and
    the index-wrap fixup select the `.at[]` lowering adds on top."""
    if _depth > _PROVENANCE_DEPTH or isinstance(var, jax.core.Literal):
        return False
    while True:
        eqn = scope.defs.get(var)
        if eqn is not None:
            down = _descend_outvar(eqn, var, scope)
            if down is None:
                break
            var, scope = down
            continue
        var2, scope2, shift = resolve_var(var, scope)
        if var2 is None or shift:
            return False
        var, scope = var2, scope2
    name = eqn.primitive.name
    if name in _DISTINCT_PASS_THROUGH or name in (
            "broadcast_in_dim", "reshape", "squeeze", "concatenate",
            "add", "sub", "rem"):
        # index arithmetic (the .at[] wrap fixup adds/rems the axis
        # size) and movement preserve "one arm is a fixed slot" ONLY
        # when every operand is the masked select or uniform: a masked
        # redirect added to an OPAQUE base (base + where(mask, 0, S))
        # re-opens collisions between the base rows, and an opaque
        # part concatenated next to a masked one can alias it
        got_masked = False
        for v in eqn.invars:
            if isinstance(v, jax.core.Literal) \
                    or _is_uniform_scalar(v, scope):
                continue
            if not masked_index_select(v, scope, _depth + 1):
                return False
            got_masked = True
        return got_masked
    if name != "select_n":
        return False

    def is_uniform_arm(v):
        # a literal, a broadcast scalar, or anything else uniform:
        # every masked-off lane lands on ONE slot
        return isinstance(v, jax.core.Literal) \
            or _is_uniform_scalar(v, scope)

    # select_n(pred, arm0, arm1, ...): one arm a uniform scratch slot
    # (the masked-store idiom proper), else EVERY arm itself a masked
    # select (the wrap fixup selects between two shifted copies of the
    # redirect) — an opaque sibling arm re-opens collisions between
    # the lanes that select it
    if any(is_uniform_arm(v) for v in eqn.invars[1:]):
        return True
    got_masked = False
    for v in eqn.invars[1:]:
        if not masked_index_select(v, scope, _depth + 1):
            return False
        got_masked = True
    return got_masked


def scatter_row_axes(eqn) -> "tuple[int, ...]":
    """The index-row axes of a scatter's indices operand: everything
    except the trailing index-vector dim and any vmap batching dims
    (a batching dim addresses a DIFFERENT operand slice per position,
    so it cannot alias across itself)."""
    idx = eqn.invars[1]
    rank = len(getattr(idx.aval, "shape", ()) or ())
    dn = eqn.params.get("dimension_numbers")
    batch = tuple(getattr(dn, "scatter_indices_batching_dims", ()) or ())
    return tuple(a for a in range(rank - 1) if a not in batch)


def scatter_writer_proof(eqn, scope: Scope) -> "str | None":
    """Name of the proof that this scatter writes every target cell at
    most once (each cell has a SINGLE writer within the op), or None
    when no proof holds.  The proof ladder, in order:

      "unique-indices"  the op declares unique_indices=True — the
                        caller asserts non-aliasing and XLA is allowed
                        to exploit it, so a lie is already UB
      "constant-index"  the index operand is a literal — a fixed,
                        statically visible row set (treated as the
                        author's explicit layout, like the old
                        scatter-determinism literal skip)
      "single-row"      every non-batching row axis has size 1 (or
                        there are none): one row per addressed slice
                        cannot collide with itself
      "distinct-axes"   index provenance shows the one multi-row axis
                        is pairwise-distinct (an iota column survives
                        into every row — `distinct_axes`)
      "masked-select"   the masked scratch-redirect idiom: disabled
                        lanes all land on one spill slot
                        (`masked_index_select`)

    Sound for at most ONE multi-row axis, same as scatter-determinism:
    per-axis distinctness covers pairs differing in one axis only."""
    if eqn.params.get("unique_indices"):
        return "unique-indices"
    idx = eqn.invars[1]
    if isinstance(idx, jax.core.Literal):
        return "constant-index"
    idx_shape = tuple(getattr(idx.aval, "shape", ()) or ())
    rows = tuple(a for a in scatter_row_axes(eqn) if idx_shape[a] > 1)
    if not rows:
        return "single-row"
    if len(rows) == 1 and rows[0] in distinct_axes(idx, scope):
        return "distinct-axes"
    if masked_index_select(idx, scope):
        return "masked-select"
    return None
