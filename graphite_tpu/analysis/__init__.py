"""Static analysis of lowered programs: jaxpr walker + invariant lints.

Graphite's perf story rests on properties of the COMPILED program that
Python-level code cannot see break: no big store rides a `lax.cond`
output (round 6), every sweep knob stays a traced operand (round 7),
absolute picosecond clocks never narrow below int64, batched programs
don't pay for gating that vmap turned into selects, and no host
callback hides in the device loop.  This package checks them all on
`jax.make_jaxpr` output — `audit()` for the default config set,
`walk.iter_eqns` / `rules.*` for bespoke assertions in tests.

    from graphite_tpu.analysis import audit
    report = audit()          # the five default-config programs
    assert report.ok, report.findings

CLI: `python -m graphite_tpu.tools.audit` (JSON-lines report).
"""

from graphite_tpu.analysis.audit import (  # noqa: F401
    AuditReport, ProgramSpec, RuleResult, audit, audit_program,
    clock_invar_indices, default_programs, spec_from_simulator,
    spec_from_sweep,
)
from graphite_tpu.analysis.comms import (  # noqa: F401
    Collective, CommsReport, PhaseComms, collective_kind,
    collective_metrics, comms_report, extract_collectives,
    gspmd_insertion_fixture, has_mesh_region, mesh_axis_sizes,
    replication_drift_fixture, shard_map_uniformity,
)
from graphite_tpu.analysis.cost import (  # noqa: F401
    CostReport, ResidencyBudgetError, backend_memory_comparison,
    budget_regression_fixture, check_budget, check_budgets, cost_report,
    dynamic_cost, format_breakdown, load_budgets, peak_live_bytes,
    residency_breakdown, save_budgets,
)
from graphite_tpu.analysis.identity import (  # noqa: F401
    DiffEntry, canonical_lines, diff_or_none, fingerprint, same_program,
    structural_diff,
)
from graphite_tpu.analysis.registry import (  # noqa: F401
    ProgramRecord, check_lock, load_lock, lock_regression_fixture,
    record_from_spec, save_lock,
)
from graphite_tpu.analysis.rules import (  # noqa: F401
    Finding, LaneWrite, cond_payload, gspmd_insertion, host_sync,
    knob_fold, lane_summary, lane_writes, phase_conds,
    replication_drift, scatter_determinism, time_dtype, vmap_gate,
    write_race,
)
from graphite_tpu.analysis.walk import (  # noqa: F401
    aval_bytes, aval_sig, find_eqns, invar_path_strings, iter_eqns,
    iter_eqns_with_site, scatter_row_axes, scatter_writer_proof,
    subjaxprs, taint_narrowing, used_invar_mask,
)
