"""The program registry: name -> identity for every audited program.

ROADMAP's campaign-service direction asks for "an explicit program
registry so the service, the auditor, and the budget gate all key off
the same artifact identity".  This is it: every `ProgramSpec` in the
audit default set registers

    name -> {fingerprint, tile geometry, knob signature, budget key}

and the checked-in `PROGRAMS.lock` (repo root, next to BUDGETS.json)
pins those identities in CI:

  - `tools/audit.py --lock` recomputes each default program's
    fingerprint and fails loudly on any drift — naming the program,
    and (for the self-test fixture) the first divergent equation with
    its protocol phase via `identity.structural_diff`;
  - `--lock-update` re-registers after an INTENTIONAL program change
    (merging, like --budget-update);
  - the budget gate resolves `BUDGETS.json` entries THROUGH the
    registry: each budget entry records the fingerprint it was
    measured at, and a ceiling whose fingerprint no longer matches the
    registered program is an error — a renamed or retraced program can
    no longer silently inherit stale ceilings.

The lock is the artifact-identity substrate the campaign service's
compiled-program cache will key off: same registry key == same lowered
program == same executable, byte for byte.
"""

from __future__ import annotations

import dataclasses
import json
import os

from graphite_tpu.analysis.identity import fingerprint


def default_lock_path() -> str:
    """PROGRAMS.lock at the repo root (next to BUDGETS.json)."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(os.path.dirname(os.path.dirname(here)),
                        "PROGRAMS.lock")


@dataclasses.dataclass
class ProgramRecord:
    """One registered program's identity."""

    name: str
    fingerprint: str
    tiles: int
    # sorted knob names with live traced invars (sweep campaigns), or
    # None for un-swept programs — a knob appearing/disappearing is an
    # interface change even when the digest moves anyway
    knobs: "tuple[str, ...] | None" = None
    # the BUDGETS.json key this program's ceilings live under (defaults
    # to the program name; a rename keeps old ceilings reachable)
    budget_key: str = ""

    def __post_init__(self):
        if not self.budget_key:
            self.budget_key = self.name

    def to_json(self) -> dict:
        out = {"fingerprint": self.fingerprint, "tiles": int(self.tiles),
               "budget_key": self.budget_key}
        if self.knobs is not None:
            out["knobs"] = sorted(self.knobs)
        return out

    @classmethod
    def from_json(cls, name: str, d: dict) -> "ProgramRecord":
        """Inverse of `to_json`.  Raises a clean ValueError on a
        malformed dict — callers deserializing records from artifacts
        they do not control (the program store's entry manifests) need
        a named refusal, not a KeyError deep in a load path."""
        try:
            return cls(name=name, fingerprint=str(d["fingerprint"]),
                       tiles=int(d["tiles"]),
                       knobs=(tuple(d["knobs"]) if "knobs" in d
                              else None),
                       budget_key=str(d.get("budget_key", name)))
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(
                f"malformed ProgramRecord for {name!r}: "
                f"{type(e).__name__}: {e}") from e


def record_from_spec(spec) -> ProgramRecord:
    """Register one audited program (an audit.ProgramSpec)."""
    knobs = (tuple(sorted(spec.knob_invars))
             if spec.knob_invars is not None else None)
    return ProgramRecord(name=spec.name,
                         fingerprint=fingerprint(spec.closed),
                         tiles=int(spec.n_tiles), knobs=knobs)


def save_lock(records: "list[ProgramRecord]",
              path: "str | None" = None) -> str:
    """Write/merge registered identities (the --lock-update refresh;
    merges over an existing file so a --programs subset run never
    drops the other programs' entries)."""
    path = path or default_lock_path()
    data = {}
    if os.path.exists(path):
        with open(path) as f:
            data = json.load(f)
    for rec in records:
        row = rec.to_json()
        prev = data.get(rec.name)
        # a hand-set budget_key (rename workflow) survives refreshes:
        # record_from_spec only knows the name, so a default-keyed
        # record must not clobber the key the budget gate resolves by
        if prev and rec.budget_key == rec.name \
                and prev.get("budget_key", rec.name) != rec.name:
            row["budget_key"] = prev["budget_key"]
        data[rec.name] = row
    with open(path, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def load_lock(path: "str | None" = None) -> "dict[str, ProgramRecord]":
    path = path or default_lock_path()
    with open(path) as f:
        data = json.load(f)
    return {name: ProgramRecord.from_json(name, d)
            for name, d in data.items()}


LOCK_FIXTURE_PERTURBATION = """
[l2_cache/T1]
data_access_time = 19
"""


def lock_regression_fixture(tiles: int = 8, max_quanta: int = 4096):
    """The REAL gated-MSI program lowered with ONE perturbed literal —
    the L2 data-access latency (8 -> 19 cycles), a constant consumed
    inside the `requester` phase cond — under the registered name
    "gated-msi".  The lock gate MUST trip on it, and the structural
    diff against the reference lowering must name the first divergent
    equation WITH its protocol phase ("requester ... mul lit(8) ->
    lit(19)"), not just a failed hash: the CI self-test that the
    identity machinery attributes drift, exactly the way the inflated-
    carry fixture proves the budget gate trips (cost.
    budget_regression_fixture)."""
    from graphite_tpu.analysis.audit import (
        gated_msi_simulator, spec_from_simulator,
    )

    return spec_from_simulator(
        "gated-msi", gated_msi_simulator(tiles, LOCK_FIXTURE_PERTURBATION),
        max_quanta)


def check_lock(specs, lock: "dict[str, ProgramRecord]", *,
               expect_complete: bool = False) -> list:
    """Gate lowered programs against the checked-in registry.

    Returns rules.Finding rows (rule "lock", error severity) — empty
    means every program's recomputed fingerprint, geometry and knob
    signature match its registered identity.  A program missing from
    the lock is itself an error (silence would let it drift
    unregistered); with `expect_complete`, registered names absent
    from `specs` error too (a stale lock entry nothing verifies).
    """
    from graphite_tpu.analysis.rules import Finding, SEV_ERROR

    out = []
    seen = set()
    for spec in specs:
        seen.add(spec.name)
        rec = lock.get(spec.name)
        cur = record_from_spec(spec)
        if rec is None:
            out.append(Finding(
                "lock", SEV_ERROR, "PROGRAMS.lock",
                f"program {spec.name!r} is not registered — run "
                f"`python -m graphite_tpu.tools.audit --lock-update` "
                f"after reviewing its cost report",
                program=spec.name,
                data={"fingerprint": cur.fingerprint}))
            continue
        if int(rec.tiles) != int(cur.tiles):
            out.append(Finding(
                "lock", SEV_ERROR, "PROGRAMS.lock",
                f"program {spec.name!r} was lowered at tiles="
                f"{cur.tiles} but is registered at tiles={rec.tiles} — "
                f"rerun at the registered geometry, or re-register "
                f"with --lock-update",
                program=spec.name,
                data={"tiles": cur.tiles, "lock_tiles": rec.tiles}))
            continue
        if rec.knobs is not None or cur.knobs is not None:
            if tuple(rec.knobs or ()) != tuple(cur.knobs or ()):
                out.append(Finding(
                    "lock", SEV_ERROR, "PROGRAMS.lock",
                    f"program {spec.name!r} knob signature changed: "
                    f"registered {sorted(rec.knobs or ())} != lowered "
                    f"{sorted(cur.knobs or ())} — the sweep interface "
                    f"moved; re-register with --lock-update",
                    program=spec.name,
                    data={"knobs": sorted(cur.knobs or ()),
                          "lock_knobs": sorted(rec.knobs or ())}))
        if rec.fingerprint != cur.fingerprint:
            out.append(Finding(
                "lock", SEV_ERROR, "PROGRAMS.lock",
                f"program {spec.name!r} drifted from its registered "
                f"identity ({rec.fingerprint[:24]}... -> "
                f"{cur.fingerprint[:24]}...) — if intentional, review "
                f"the cost report and re-register with --lock-update "
                f"(then --budget-update: the ceilings were measured at "
                f"the old identity)",
                program=spec.name,
                data={"fingerprint": cur.fingerprint,
                      "lock_fingerprint": rec.fingerprint}))
    if expect_complete:
        for name in sorted(set(lock) - seen):
            out.append(Finding(
                "lock", SEV_ERROR, "PROGRAMS.lock",
                f"registered program {name!r} is not in the audited "
                f"set — nothing verifies its identity; remove the "
                f"stale entry or audit it",
                program=name,
                data={"lock_fingerprint": lock[name].fingerprint}))
    return out
