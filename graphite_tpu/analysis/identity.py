"""Program identity: canonical jaxpr fingerprints + structural diffs.

Graphite's credibility rests on knowing exactly which artifact was
measured — the paper's lax-sync comparisons only mean something because
the simulated program is held fixed while sync schemes vary.  The repo
now has three consumers of "the lowered program" (the round-8 auditor,
the round-10 cost/budget gate, `SweepRunner`'s zero-recompile
campaigns) and, until this module, three ad-hoc notions of whether two
programs are the same: `str(jaxpr)` comparisons in tests, hand-written
names keying `BUDGETS.json`, and nothing at all for the campaign cache.

Two tools, one definition of identity:

  fingerprint(closed)
      A canonical digest of a ClosedJaxpr.  The traversal assigns
      variables alpha-renaming-invariant numbers (first-appearance
      order per scope), recurses into every sub-jaxpr (cond branches,
      while cond/body, scan/pjit bodies), normalizes literals and
      params (arrays hash by shape/dtype/bytes; dicts sort; callables
      reduce to their names; memory addresses are scrubbed), and
      sha256-hashes the token stream.  Two traces of the same config
      produce the SAME fingerprint even though `str(jaxpr)` differs in
      var names and jax-version printing details; one changed literal,
      trip count or carried aval produces a different one.

  structural_diff(a, b)
      Given two programs whose fingerprints differ, walk them in
      LOCKSTEP and name the first divergent equation — with the same
      phase attribution `analysis/cost.py` uses (the round-6
      phase-cond structure), so a regression report says "mesi
      `home_commit` phase gained a 96 MB while-carry", not "hash
      changed".

`analysis/registry.py` builds the program registry + `PROGRAMS.lock`
on top; `tools/audit.py --lock` gates CI with it.
"""

from __future__ import annotations

import dataclasses
import hashlib
import re

import jax
import numpy as np

from graphite_tpu.analysis.walk import as_jaxpr, aval_bytes, aval_sig

_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")

FINGERPRINT_SCHEME = "gfp1"   # bump when the canonical form changes


# ---------------------------------------------------------------------------
# canonicalization
# ---------------------------------------------------------------------------


def _norm_array(a) -> str:
    a = np.asarray(a)
    if a.ndim == 0:
        # scalars print by value: cheap, and diffs stay readable
        return f"{a.dtype}:{a.item()!r}"
    digest = hashlib.sha256(np.ascontiguousarray(a).tobytes())
    return f"{a.dtype}{list(a.shape)}:{digest.hexdigest()[:16]}"


def _norm_param(v, emit_jaxpr) -> str:
    """One param value as a deterministic token.  `emit_jaxpr` renders
    nested (Closed)Jaxprs through the main canonicalizer so sub-program
    structure is part of the parent's identity."""
    if v is None or isinstance(v, (bool, int, str)):
        return repr(v)
    if isinstance(v, float):
        return repr(float(v))
    if isinstance(v, (tuple, list)):
        return "[" + ",".join(_norm_param(x, emit_jaxpr) for x in v) + "]"
    if isinstance(v, dict):
        return "{" + ",".join(
            f"{k!r}:{_norm_param(v[k], emit_jaxpr)}"
            for k in sorted(v, key=repr)) + "}"
    if hasattr(v, "eqns") or hasattr(getattr(v, "jaxpr", None), "eqns"):
        return emit_jaxpr(v)
    if isinstance(v, (np.ndarray, np.generic)) or hasattr(v, "__array__"):
        try:
            return _norm_array(v)
        except Exception:  # noqa: BLE001 — fall through to repr
            pass
    if isinstance(v, np.dtype) or (isinstance(v, type)
                                   and issubclass(v, np.generic)):
        return str(np.dtype(v))
    if callable(v):
        return f"<fn {getattr(v, '__name__', type(v).__name__)}>"
    # named tuples (GatherDimensionNumbers etc.), enums, shardings:
    # deterministic reprs modulo memory addresses, which we scrub
    return _ADDR_RE.sub("0x*", repr(v))


def _aval_token(aval) -> str:
    sig = aval_sig(aval)
    if sig is None:
        return str(type(aval).__name__)
    return f"{sig[1]}{list(sig[0])}"


class _Canon:
    """One canonicalization pass: emits the token stream."""

    def __init__(self):
        self.lines: "list[str]" = []

    def operand(self, v, env: dict) -> str:
        if isinstance(v, jax.core.Literal):
            val = v.val
            if hasattr(val, "shape") or isinstance(val, np.generic):
                return f"lit({_norm_array(val)})"
            return f"lit({val!r})"
        n = env.get(v)
        if n is None:
            # a free var from an enclosing scope (legacy-style jaxprs);
            # number it on first sight so references stay stable
            n = env[v] = ("^", len(env))
        return f"v{n[1]}:{_aval_token(v.aval)}" \
            if n[0] == "" else f"^{n[1]}:{_aval_token(v.aval)}"

    def jaxpr(self, j, consts=(), depth=0) -> str:
        jj = as_jaxpr(j)
        inner_consts = getattr(j, "consts", None)
        if inner_consts is None:
            inner_consts = consts
        env = {}
        for v in list(jj.constvars) + list(jj.invars):
            env[v] = ("", len(env))
        pre = "  " * depth
        self.lines.append(
            pre + "jaxpr{" + " in=["
            + ",".join(_aval_token(v.aval)
                       for v in list(jj.constvars) + list(jj.invars))
            + "]")
        for i, c in enumerate(inner_consts or ()):
            try:
                self.lines.append(pre + f" const{i}={_norm_array(c)}")
            except Exception:  # noqa: BLE001 — non-array const
                self.lines.append(pre + f" const{i}="
                                  + _ADDR_RE.sub("0x*", repr(c)))
        for eqn in jj.eqns:
            ins = ",".join(self.operand(v, env) for v in eqn.invars)
            sub_tokens = []

            def emit_sub(v):
                start = len(self.lines)
                self.jaxpr(v, depth=depth + 1)
                sub_tokens.append(len(self.lines) - start)
                return f"<sub@{len(sub_tokens) - 1}>"

            params = ",".join(
                f"{k}={_norm_param(eqn.params[k], emit_sub)}"
                for k in sorted(eqn.params))
            for v in eqn.outvars:
                if v not in env:
                    env[v] = ("", len(env))
            outs = ",".join(self.operand(v, env) for v in eqn.outvars)
            self.lines.append(
                pre + f" {eqn.primitive.name}({ins})"
                f"[{params}] -> {outs}")
        self.lines.append(
            pre + " ret=["
            + ",".join(self.operand(v, env) for v in jj.outvars) + "]}")
        return "<jaxpr>"


def canonical_lines(closed) -> "list[str]":
    """The canonical token stream of a (Closed)Jaxpr — the exact text
    the fingerprint hashes, alpha-renaming-invariant by construction.
    Exposed for debugging and golden tests."""
    c = _Canon()
    c.jaxpr(closed)
    return c.lines


def fingerprint(closed) -> str:
    """Stable identity digest of a lowered program:
    "gfp1:<sha256-hex>".  Equal iff the canonical forms are equal —
    same structure, same literals/consts, same avals — regardless of
    variable naming or printing order."""
    h = hashlib.sha256()
    for line in canonical_lines(closed):
        h.update(line.encode())
        h.update(b"\n")
    return f"{FINGERPRINT_SCHEME}:{h.hexdigest()}"


def same_program(a, b) -> bool:
    """Canonical structural equality of two lowered programs — the ONE
    definition of "same program" bit-identity claims and CI gates
    share (replaces ad-hoc `str(jaxpr)` comparisons)."""
    return fingerprint(a) == fingerprint(b)


# ---------------------------------------------------------------------------
# structural diff
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DiffEntry:
    """The first structural divergence between two lowered programs."""

    site: str              # primitive path, e.g. "while/body_jaxpr.cond"
    index: int             # eqn index at that nesting level
    kind: str              # primitive|operands|params|outputs|
    #                        eqn-count|signature|consts
    detail: str            # human sentence naming the divergence
    phase: "str | None" = None   # enclosing protocol phase, when known
    a: str = ""            # side-A rendering of the divergent element
    b: str = ""            # side-B rendering

    def to_json(self) -> dict:
        out = dataclasses.asdict(self)
        return {k: v for k, v in out.items() if v not in (None, "")}

    def __str__(self) -> str:
        where = f"{self.site or '<top>'}[{self.index}]"
        phase = f" (phase {self.phase})" if self.phase else ""
        return f"first divergence at {where}{phase}: {self.detail}"


def _human_bytes(n: int) -> str:
    n = int(n)
    for unit, div in (("GB", 1 << 30), ("MB", 1 << 20), ("KB", 1 << 10)):
        if n >= div:
            return f"{n / div:.2f} {unit}"
    return f"{n} B"


def _operand_token(v) -> str:
    if isinstance(v, jax.core.Literal):
        val = v.val
        if hasattr(val, "shape") and np.asarray(val).ndim:
            return f"lit({_norm_array(val)})"
        return f"lit({getattr(val, 'item', lambda: val)()!r})"
    return _aval_token(v.aval)


def _eqn_params_token(eqn) -> str:
    # sub-jaxprs excluded: they are diffed recursively, and inlining
    # them here would blame the whole call for a leaf-level change
    return ",".join(
        f"{k}={_norm_param(eqn.params[k], lambda v: '<sub>')}"
        for k in sorted(eqn.params)
        if not (hasattr(eqn.params[k], "eqns")
                or hasattr(getattr(eqn.params[k], "jaxpr", None), "eqns")
                or (isinstance(eqn.params[k], (tuple, list))
                    and any(hasattr(x, "eqns")
                            or hasattr(getattr(x, "jaxpr", None), "eqns")
                            for x in eqn.params[k]))))


def _is_phase_cond(eqn, n_tiles) -> bool:
    if n_tiles is None or eqn.primitive.name != "cond":
        return False
    from graphite_tpu.analysis.rules import _mailbox_outputs

    return bool(_mailbox_outputs(eqn, n_tiles))


class _DiffWalker:
    def __init__(self, n_tiles, phase_names):
        self.n_tiles = n_tiles
        self.phase_names = tuple(phase_names or ())
        self.phase_seen = 0

    def _phase_label(self, k: int) -> str:
        return (self.phase_names[k] if k < len(self.phase_names)
                else f"phase_{k}")

    def invars_diff(self, ja, jb, site, phase) -> "DiffEntry | None":
        va = list(ja.constvars) + list(ja.invars)
        vb = list(jb.constvars) + list(jb.invars)
        for i in range(min(len(va), len(vb))):
            ta, tb = _aval_token(va[i].aval), _aval_token(vb[i].aval)
            if ta != tb:
                return DiffEntry(
                    site, i, "signature",
                    f"input {i} of this region changed aval "
                    f"{ta} -> {tb} "
                    f"({_human_bytes(aval_bytes(va[i].aval))} -> "
                    f"{_human_bytes(aval_bytes(vb[i].aval))})",
                    phase, ta, tb)
        if len(va) != len(vb):
            longer, side = (va, "a") if len(va) > len(vb) else (vb, "b")
            extra = longer[min(len(va), len(vb))]
            return DiffEntry(
                site, min(len(va), len(vb)), "signature",
                f"region carries {abs(len(va) - len(vb))} extra "
                f"input(s) only in program "
                f"{'A' if side == 'a' else 'B'}; first extra: "
                f"{_aval_token(extra.aval)} "
                f"({_human_bytes(aval_bytes(extra.aval))})",
                phase,
                str(len(va)), str(len(vb)))
        return None

    def walk(self, a, b, site="", phase=None) -> "DiffEntry | None":
        ja, jb = as_jaxpr(a), as_jaxpr(b)
        d = self.invars_diff(ja, jb, site, phase)
        if d is not None:
            return d
        for i in range(min(len(ja.eqns), len(jb.eqns))):
            ea, eb = ja.eqns[i], jb.eqns[i]
            here = (f"{site}.{ea.primitive.name}" if site
                    else ea.primitive.name)
            if ea.primitive.name != eb.primitive.name:
                return DiffEntry(
                    site, i, "primitive",
                    f"equation {i} is {ea.primitive.name!r} in A but "
                    f"{eb.primitive.name!r} in B", phase,
                    ea.primitive.name, eb.primitive.name)
            ops_a = [_operand_token(v) for v in ea.invars]
            ops_b = [_operand_token(v) for v in eb.invars]
            if ops_a != ops_b:
                k = next(k for k, (x, y)
                         in enumerate(zip(ops_a, ops_b)) if x != y) \
                    if len(ops_a) == len(ops_b) else min(len(ops_a),
                                                         len(ops_b))
                return DiffEntry(
                    here, i, "operands",
                    f"{ea.primitive.name} operand {k} changed: "
                    f"{ops_a[k] if k < len(ops_a) else '<absent>'} -> "
                    f"{ops_b[k] if k < len(ops_b) else '<absent>'}",
                    phase,
                    "(" + ",".join(ops_a) + ")",
                    "(" + ",".join(ops_b) + ")")
            outs_a = [_aval_token(v.aval) for v in ea.outvars]
            outs_b = [_aval_token(v.aval) for v in eb.outvars]
            if outs_a != outs_b:
                return DiffEntry(
                    here, i, "outputs",
                    f"{ea.primitive.name} outputs changed "
                    f"({','.join(outs_a)}) -> ({','.join(outs_b)})",
                    phase, ",".join(outs_a), ",".join(outs_b))
            pa, pb = _eqn_params_token(ea), _eqn_params_token(eb)
            if pa != pb:
                return DiffEntry(
                    here, i, "params",
                    f"{ea.primitive.name} params changed: {pa} -> {pb}",
                    phase, pa, pb)
            # recurse into paired sub-jaxprs, tracking phase conds
            from graphite_tpu.analysis.walk import subjaxprs

            subs_a = list(subjaxprs(ea))
            subs_b = list(subjaxprs(eb))
            inner_phase = phase
            if _is_phase_cond(ea, self.n_tiles):
                inner_phase = self._phase_label(self.phase_seen)
                self.phase_seen += 1
            if len(subs_a) != len(subs_b):
                # a sub-program count divergence IS a divergence of
                # this region's program list — report it as eqn-count
                # and attribute it to the phase the region belongs to
                # (for a phase cond, its OWN label: `phase` here is the
                # ENCLOSING phase — None at top level — which loses the
                # attribution the recursion below would have carried)
                return DiffEntry(
                    here, i, "eqn-count",
                    f"{ea.primitive.name} has {len(subs_a)} sub-"
                    f"program(s) in A but {len(subs_b)} in B",
                    inner_phase, str(len(subs_a)), str(len(subs_b)))
            for (tag, sa), (_, sb) in zip(subs_a, subs_b):
                d = self.walk(sa, sb, f"{here}/{tag}", inner_phase)
                if d is not None:
                    return d
        if len(ja.eqns) != len(jb.eqns):
            n = min(len(ja.eqns), len(jb.eqns))
            longer, label = (ja, "A") if len(ja.eqns) > len(jb.eqns) \
                else (jb, "B")
            extra = longer.eqns[n]
            out_b = sum(aval_bytes(v.aval) for v in extra.outvars)
            return DiffEntry(
                site, n, "eqn-count",
                f"program {label} has {abs(len(ja.eqns) - len(jb.eqns))}"
                f" extra equation(s) here; first extra: "
                f"{extra.primitive.name} -> ("
                + ",".join(_aval_token(v.aval) for v in extra.outvars)
                + f") ({_human_bytes(out_b)})",
                phase, str(len(ja.eqns)), str(len(jb.eqns)))
        return None


def structural_diff(a, b, *, n_tiles: "int | None" = None,
                    phase_names=()) -> "DiffEntry | None":
    """First structural divergence between two lowered programs, or
    None when they are canonically identical.

    Lockstep DFS over equations and sub-jaxprs; the first mismatch in
    primitive / operand avals+literals / output avals / normalized
    params / region signature (while-carry and branch inputs — where a
    ballooned carry shows up) is reported with its site path and, when
    `n_tiles` is given, attributed to the protocol phase whose gating
    cond encloses it (`phase_names` in phase-cond program order, the
    same convention `cost.per_phase_costs` uses).
    """
    return _DiffWalker(n_tiles, phase_names).walk(a, b)


def diff_or_none(a, b, **kw) -> "DiffEntry | None":
    """`structural_diff` guarded by the cheap hash check first."""
    if fingerprint(a) == fingerprint(b):
        return None
    d = structural_diff(a, b, **kw)
    if d is None:
        # fingerprints differ but the lockstep walk found nothing —
        # the divergence is in a normalized corner (e.g. consts); say
        # so rather than claiming identity
        return DiffEntry(
            "", 0, "consts",
            "fingerprints differ but the equation walk found no "
            "divergence — check program consts / literal tables")
    return d
