"""Jaxpr invariant lints: the rules the program auditor runs.

Each rule checks one property Graphite's performance story depends on,
on the LOWERED program (a ClosedJaxpr from `jax.make_jaxpr`) — the
artifact the compiler actually sees, so a regression cannot hide behind
a Python-level abstraction:

  cond-payload  no lax.cond output may carry a big store (round 6: the
                directory entry/sharers must ride `_DirAcc`/`_RowAcc`
                delta plans, because XLA double-buffers cond outputs)
  knob-fold     every sweep timing knob must be CONSUMED as a traced
                operand (round 7: a knob the engine reads off static
                params instead constant-folds — one recompile per grid
                point and a silently wrong sweep report)
  time-dtype    no integer narrowing of values derived from absolute
                picosecond clocks (time_types.TIME_DTYPE discipline;
                deltas/latencies are legitimately int32)
  vmap-gate     a program built with phase_gate=True whose gating conds
                lowered to both-branch selects (vmap batching) is paying
                gating's bookkeeping and buying nothing (round-7 PERF
                finding — SweepRunner defaults gates off under vmap)
  host-sync     no callback/infeed/outfeed primitive inside the compiled
                step (a host round trip costs ~100 ms over a tunneled
                chip — the whole reason the quantum loop is
                device-driven)
  scatter-determinism
                inside a vmapped campaign (or any shard_mapped region)
                a replace-combiner scatter whose index rows can alias
                has an implementation-defined winner — the round-9
                telemetry contract says device stores are masked
                add-scatters; this enforces it program-wide.  A scatter
                passes by being commutative (add/mul/min/max), by
                declaring unique_indices, by an index-provenance proof
                (an iota column survives into every row — walk.
                distinct_axes), or by the masked scratch-redirect idiom
                (disabled lanes select a constant spill slot)
  telemetry-off a program lowered with telemetry=None must contain NO
                trace of the timeline machinery: no telemetry-state
                invar and no equation producing the ring's
                [S, n_series] aval (round 9's knobs=None-style
                contract — the default program stays bit-identical to
                the pre-telemetry one).  Telemetry-ON programs instead
                add the ring's aval to the cond-payload forbidden set:
                no phase cond may ever carry the buffer.
  profile-off   the same rule over the round-16 spatial profiler
                (telemetry_off with state_key="profile"): a
                profile=None program carries no profile-state invar and
                no [S, T, m] per-tile ring equation; profile-ON
                programs add that ring's aval to the cond-payload
                forbidden set instead.
  hist-off      the same rule over the round-21 latency histograms
                (telemetry_off with state_key="hist"): a hist=None
                program carries no hist-state invar and no int64
                [H, B] / [T, H, B] bucket-count ring equation; hist-ON
                programs add that ring's aval to the cond-payload
                forbidden set instead.
  write-race    the round-20 [T, k]-compaction gate: every scatter is
                classified single-writer / commutative-multi-writer /
                ordered-multi-writer through the shared writer-proof
                ladder (walk.scatter_writer_proof); an ORDERED write
                into a req lane (uint8/int64 [.., T]) or a mailbox
                matrix ([.., T, T]) is an error — a rewrite silently
                made a deterministic protocol lane racy.  The model
                checker (analysis/protocol.py) supplies the reachable
                per-matrix fan-in bounds the compaction needs;
                `lane_writes`/`lane_summary` expose the classification
                table (`tools/audit.py --lanes`).

Rules return `Finding` lists; `analysis/audit.py` assembles them into
per-program reports and the `tools/audit.py` CLI emits them as JSON
lines.
"""

from __future__ import annotations

import dataclasses
import re

import numpy as np

from graphite_tpu.analysis.walk import (
    aval_bytes, aval_sig, call_arg_maps, iter_eqns_with_site,
    make_scope, scatter_writer_proof, scope_from_closed, subjaxprs,
    taint_narrowing, used_invar_mask,
)

SEV_ERROR = "error"
SEV_WARNING = "warning"


@dataclasses.dataclass
class Finding:
    """One rule violation at one program site."""

    rule: str
    severity: str          # SEV_ERROR | SEV_WARNING
    site: str              # primitive path, e.g. "while/body.cond"
    message: str
    program: "str | None" = None   # filled in by audit()
    data: dict = dataclasses.field(default_factory=dict)

    def to_json(self) -> dict:
        out = {"rule": self.rule, "severity": self.severity,
               "site": self.site, "message": self.message}
        if self.program is not None:
            out["program"] = self.program
        if self.data:
            out["data"] = self.data
        return out

    def __str__(self) -> str:
        prog = f"{self.program}: " if self.program else ""
        return f"[{self.rule}/{self.severity}] {prog}{self.message} " \
               f"(at {self.site})"


def _sig_matches(sig, forbidden_sig) -> bool:
    """Aval signature match, ignoring leading batch axes: a vmapped
    program carries the same store as [B, *shape]."""
    if sig is None:
        return False
    shape, dtype = sig
    fshape, fdtype = forbidden_sig
    if dtype != fdtype or len(shape) < len(fshape):
        return False
    return tuple(shape[len(shape) - len(fshape):]) == tuple(fshape)


# ---------------------------------------------------------------------------
# rule 1: cond-payload
# ---------------------------------------------------------------------------


def cond_payload(jaxpr, *, max_bytes: "int | None" = None,
                 forbidden=()) -> "list[Finding]":
    """No lax.cond output may exceed `max_bytes` or match a `forbidden`
    (shape, dtype) signature (the directory stores).

    XLA double-buffers cond branch outputs, so a big array riding a cond
    costs a full extra copy in HBM every iteration — the round-2
    pathology that round 6's `_DirAcc`/`_RowAcc` delta plans exist to
    avoid.  Checked for EVERY cond at EVERY nesting depth, not just the
    one a test happens to sample.
    """
    forbidden = tuple((tuple(s), str(np.dtype(d))) for s, d in forbidden)
    out = []
    for site, eqn in iter_eqns_with_site(jaxpr):
        if eqn.primitive.name != "cond":
            continue
        for k, v in enumerate(eqn.outvars):
            sig = aval_sig(v.aval)
            for fsig in forbidden:
                if _sig_matches(sig, fsig):
                    out.append(Finding(
                        "cond-payload", SEV_ERROR, site,
                        f"lax.cond output {k} carries a forbidden store "
                        f"{sig[0]} {sig[1]} — it will be double-buffered "
                        f"(round-6 _DirAcc/_RowAcc contract)",
                        data={"output": k, "shape": list(sig[0]),
                              "dtype": sig[1],
                              "bytes": aval_bytes(v.aval)}))
                    break
            else:
                b = aval_bytes(v.aval)
                if max_bytes is not None and b > max_bytes:
                    sig = sig or ((), "?")
                    out.append(Finding(
                        "cond-payload", SEV_ERROR, site,
                        f"lax.cond output {k} is {b} bytes "
                        f"({sig[0]} {sig[1]}) > max_cond_bytes="
                        f"{max_bytes} — cond outputs are double-buffered",
                        data={"output": k, "bytes": b,
                              "shape": list(sig[0]), "dtype": sig[1]}))
    return out


# ---------------------------------------------------------------------------
# rule 2: knob-fold
# ---------------------------------------------------------------------------


def knob_fold(jaxpr, knob_invars: "dict[str, list[int]]",
              invar_paths=None) -> "list[Finding]":
    """Every sweep knob's invar must be transitively consumed by the
    lowered program.

    A knob leaf that reaches the jit as an argument but feeds no eqn
    means the engine read the STATIC param instead — the value is
    constant-folded, the sweep reports knob points that never entered
    the program, and every grid point recompiles (the round-7 zero-
    recompile contract).
    """
    mask = used_invar_mask(jaxpr)
    out = []
    for name, idxs in sorted(knob_invars.items()):
        if not idxs:
            out.append(Finding(
                "knob-fold", SEV_ERROR, "jaxpr.invars",
                f"knob {name!r} has no traced invar at all — it was "
                f"baked into the program as a literal",
                data={"knob": name}))
            continue
        if not any(mask[i] for i in idxs if i < len(mask)):
            paths = ([invar_paths[i] for i in idxs]
                     if invar_paths else idxs)
            out.append(Finding(
                "knob-fold", SEV_ERROR, "jaxpr.invars",
                f"knob {name!r} rides as a traced argument but nothing "
                f"consumes it — the engine constant-folded the static "
                f"param value instead (invars {paths})",
                data={"knob": name, "invars": list(idxs)}))
    return out


# ---------------------------------------------------------------------------
# rule 3: time-dtype
# ---------------------------------------------------------------------------


def time_dtype(jaxpr, clock_invars, invar_paths=None) -> "list[Finding]":
    """No integer narrowing of values derived from absolute picosecond
    clocks (the `clock_invars` taint sources — TIME_DTYPE leaves).

    A 1 GHz tile overflows int32 picoseconds after ~2 ms of simulated
    time, so absolute clocks are int64 everywhere (time_types.py).
    Taint stops at subtraction — a difference of clocks is a delta,
    which the engine legitimately keeps in int32 (DELTA_DTYPE).
    """
    j = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    n = len(j.invars)
    in_taint = [False] * n
    for i in clock_invars:
        if i < n:
            in_taint[i] = True
    out = []

    def on_finding(site, eqn, old, new):
        out.append(Finding(
            "time-dtype", SEV_ERROR, site,
            f"value derived from an absolute picosecond clock is "
            f"narrowed {np.dtype(old).name} -> {np.dtype(new).name} "
            f"(TIME_DTYPE discipline: absolute times stay int64; only "
            f"deltas may narrow)",
            data={"from": np.dtype(old).name, "to": np.dtype(new).name}))

    taint_narrowing(jaxpr, in_taint, on_finding)
    return out


# ---------------------------------------------------------------------------
# rule 4: vmap-gate
# ---------------------------------------------------------------------------


def phase_conds(jaxpr, n_tiles: int) -> list:
    """(site, eqn) of every cond that writes a mailbox — the memory
    engines' per-phase gating conds.  Each protocol phase writes either
    a uint8[.., T, T] type matrix (fwd/ack/evict) or, since the round-12
    request compaction, the per-REQUESTER lane signature: a uint8[.., T]
    type vector TOGETHER with an int64[.., T] time vector (the shared-L2
    requester phase's only mailbox write is the compacted request lane).
    Nothing else in the mem_gate-off programs emits either shape set as
    a cond output; see tests/test_phase_gating."""
    out = []
    for site, eqn in iter_eqns_with_site(jaxpr):
        if eqn.primitive.name == "cond" \
                and _mailbox_outputs(eqn, n_tiles):
            out.append((site, eqn))
    return out


def _mailbox_outputs(eqn, n_tiles: int) -> list:
    outs = []
    lane_u8 = []
    lane_i64 = False
    progress = False
    for v in eqn.outvars:
        sig = aval_sig(v.aval)
        if not sig:
            continue
        if len(sig[0]) >= 2 and sig[0][-2:] == (n_tiles, n_tiles) \
                and sig[1] == "uint8":
            outs.append(sig)
        if sig == ((), "int32"):
            # every phase cond returns its progress counter — the
            # discriminator that keeps lane-signature matching from
            # catching e.g. the record-fetch cond (uint8 ops + int64
            # dyn costs, but no scalar progress output)
            progress = True
        if sig[0][-1:] == (n_tiles,) and (len(sig[0]) < 2
                                          or sig[0][-2] != n_tiles):
            if sig[1] == "uint8":
                lane_u8.append(sig)
            elif sig[1] == "int64":
                lane_i64 = True
    if lane_u8 and lane_i64 and progress:
        outs.extend(lane_u8)
    return outs


def vmap_gate(jaxpr, n_tiles: int, expect_gated: bool,
              n_phases: int = 6) -> "list[Finding]":
    """A phase_gate=True program whose gating conds did not survive
    lowering is gating in name only.

    `vmap` batches a cond's predicate, which rewrites the cond into
    both-branch execution + `select_n` — every phase then runs every
    iteration AND pays the select (PERF.md round 7 measured gated-vmap
    ~2.8x slower than ungated-vmap; SweepRunner therefore defaults
    gates OFF in vmapped programs).  Warning severity: the program is
    correct, just paying for a mechanism that buys nothing.
    """
    if not expect_gated:
        return []
    conds = phase_conds(jaxpr, n_tiles)
    if len(conds) >= n_phases:
        return []
    n_sel = sum(1 for _, e in iter_eqns_with_site(jaxpr)
                if e.primitive.name == "select_n"
                and _mailbox_outputs(e, n_tiles))
    if not conds:
        return [Finding(
            "vmap-gate", SEV_WARNING, "jaxpr",
            f"program was built with phase_gate=True but NO per-phase "
            f"gating cond survived lowering ({n_sel} mailbox-shaped "
            f"select_n eqns present) — batching turned the gates into "
            f"both-branch selects; run the batched program ungated "
            f"(SweepRunner's default) or shard the batch axis",
            data={"phase_conds": 0, "mailbox_selects": n_sel})]
    return [Finding(
        "vmap-gate", SEV_WARNING, "jaxpr",
        f"only {len(conds)} of {n_phases} per-phase gating conds "
        f"survived lowering ({n_sel} mailbox-shaped select_n eqns "
        f"present) — part of the engine runs both branches every "
        f"iteration",
        data={"phase_conds": len(conds), "mailbox_selects": n_sel})]


# ---------------------------------------------------------------------------
# rule 5: host-sync
# ---------------------------------------------------------------------------

_HOST_SYNC_NAMES = ("infeed", "outfeed")
_HOST_SYNC_SUBSTR = ("callback",)


def host_sync(jaxpr) -> "list[Finding]":
    """No host round trip inside the compiled step.

    callback/infeed/outfeed primitives block the device on the host
    every iteration — ~100 ms per round trip over a tunneled chip,
    which is why the quantum loop is device-driven (engine/step.
    run_simulation) and why `barrier_host` batches its dispatches.
    A debug print left in an engine phase reintroduces exactly that.
    """
    out = []
    for site, eqn in iter_eqns_with_site(jaxpr):
        name = eqn.primitive.name
        if name in _HOST_SYNC_NAMES \
                or any(s in name for s in _HOST_SYNC_SUBSTR):
            out.append(Finding(
                "host-sync", SEV_ERROR, site,
                f"host-synchronizing primitive {name!r} inside the "
                f"compiled step — every iteration would pay a "
                f"host<->device round trip (~100 ms tunneled)",
                data={"primitive": name}))
    return out


# ---------------------------------------------------------------------------
# rule 6: scatter-determinism
# ---------------------------------------------------------------------------

# Commutative-combiner scatters produce the same result under any
# update order (integer add/mul/min/max are exactly associative), so
# aliasing index rows cannot make them nondeterministic.
_COMMUTATIVE_SCATTERS = frozenset({
    "scatter-add", "scatter-mul", "scatter-min", "scatter-max",
})


def scatter_determinism(jaxpr, *, batched: bool = False,
                        ) -> "list[Finding]":
    """No potentially-aliasing replace-scatter inside a batched region.

    XLA leaves the winner of colliding replace-scatter rows
    implementation-defined; today's serial CPU/TPU lowerings happen to
    pick last-in-index-order, but a parallelized batched lowering is
    free not to — and the repo's bit-identity claims (sweep-vs-
    sequential, telemetry on/off) assume determinism.  `batched=True`
    puts the WHOLE program in scope (it lowers under vmap —
    SweepRunner campaigns); otherwise only `shard_map`ped interiors
    are.  Warning severity, like vmap-gate: the program is correct on
    the backends we run today, but it leans on behavior the contract
    does not own.
    """
    scope0 = scope_from_closed(jaxpr)
    out = []

    def visit(scope, site, in_scope):
        for eqn in scope.jaxpr.eqns:
            name = eqn.primitive.name
            here = f"{site}.{name}" if site else name
            if name.startswith("scatter") and in_scope \
                    and name not in _COMMUTATIVE_SCATTERS:
                # the proof ladder (walk.scatter_writer_proof):
                # unique_indices / constant index rows / a single row
                # per addressed slice / one multi-row axis proven
                # pairwise-distinct by provenance / the masked
                # scratch-redirect idiom.  Sound for at most one
                # multi-row axis — per-axis distinctness covers pairs
                # differing in one axis, not rows differing in several
                # (a const table [[0,1],[1,0]] is distinct along both
                # axes yet rows (0,0) and (1,1) collide)
                if scatter_writer_proof(eqn, scope) is None:
                    idx = eqn.invars[1]
                    sig = aval_sig(eqn.outvars[0].aval) or ((), "?")
                    out.append(Finding(
                        "scatter-determinism", SEV_WARNING, here,
                        f"replace-combiner scatter into {sig[0]} "
                        f"{sig[1]} with potentially aliasing index "
                        f"rows inside a batched region — colliding "
                        f"rows have an implementation-defined "
                        f"winner; use a masked add-scatter (the "
                        f"round-9 ring-store contract), a scratch-"
                        f"slot redirect, or unique_indices=True",
                        data={"shape": list(sig[0]),
                              "dtype": sig[1],
                              "indices_shape": list(
                                  getattr(idx.aval, "shape", ()))}))
            subs = call_arg_maps(eqn)
            if subs:
                tags = [t for t, _ in subjaxprs(eqn)]
                for k, sc in enumerate(subs):
                    tag = tags[k] if k < len(tags) else str(k)
                    visit(make_scope(sc.jaxpr, scope, eqn, sc),
                          f"{here}/{tag}",
                          in_scope or "shard_map" in name)
    visit(scope0, "", batched)
    return out


# ---------------------------------------------------------------------------
# rule 7: telemetry-off
# ---------------------------------------------------------------------------


def telemetry_off(jaxpr, invar_paths=None, ring_sigs=(), *,
                  state_key: str = "telemetry",
                  rule: str = "telemetry-off") -> "list[Finding]":
    """A telemetry=None (or profile=None) program must record nothing.

    Two checks: (a) no invar path names a `state_key` recording-state
    leaf — the None spec must contribute ZERO pytree leaves to the
    carry (the SimState.telemetry=None / SimState.profile=None
    contract), and (b) no equation anywhere in the program produces a
    ring-buffer aval from `ring_sigs` (matched modulo leading batch
    axes, like cond-payload's forbidden set) — a ring materialized
    internally would mean the recording survived constant folding.
    Either finding breaks the round-7-style "None lowers the
    historical program bit-identically" guarantee every overhead claim
    rests on.  The round-16 spatial profiler runs the same rule with
    `state_key="profile"` / `rule="profile-off"` over the [S, T, m]
    ring signatures; the round-21 latency histograms with
    `state_key="hist"` / `rule="hist-off"` over the int64 bucket-count
    ring signatures.
    """
    out = []
    for i, p in enumerate(invar_paths or ()):
        # Match whole path segments, not substrings: state_key="hist"
        # must flag "[0].hist.buf" but NOT the pre-existing counter
        # "[0].mem.counters.line_util_hist".
        if state_key in re.split(r"[.\[\]']+", p):
            out.append(Finding(
                rule, SEV_ERROR, "jaxpr.invars",
                f"{rule} program carries a {state_key}-state "
                f"invar {p!r} (index {i}) — the None spec must add no "
                f"leaves to the carry",
                data={"invar": i, "path": p}))
    ring_sigs = tuple((tuple(s), str(np.dtype(d))) for s, d in ring_sigs)
    if ring_sigs:
        for site, eqn in iter_eqns_with_site(jaxpr):
            for k, v in enumerate(eqn.outvars):
                sig = aval_sig(v.aval)
                for fs in ring_sigs:
                    if _sig_matches(sig, fs):
                        out.append(Finding(
                            rule, SEV_ERROR, site,
                            f"{rule} program contains a "
                            f"ring-store equation "
                            f"({eqn.primitive.name} output {k}, "
                            f"{sig[0]} {sig[1]}) — the recording was "
                            f"not constant-folded away",
                            data={"primitive": eqn.primitive.name,
                                  "output": k, "shape": list(sig[0]),
                                  "dtype": sig[1]}))
                        break
    return out


# ---------------------------------------------------------------------------
# rule 10: write-race
# ---------------------------------------------------------------------------

# Lane kinds, by scatter-target signature (modulo leading batch axes):
#   req-lane  the round-12 compacted per-requester lanes — uint8[.., T]
#             type vectors / int64[.., T] time vectors (one lane per
#             requesting tile; the [T, k] compaction keeps this shape)
#   matrix    the [.., T, T] fwd/ack/evict mailboxes (row per sender or
#             receiver — the multi-writer surface the [T, k] compaction
#             wants to shrink)
#   state     everything else a phase writes: cache tag/state/data
#             arrays, DRAM words, the next-event heap
LANE_REQ = "req-lane"
LANE_MATRIX = "matrix"
LANE_STATE = "state"

CLASS_SINGLE = "single-writer"
CLASS_COMMUTATIVE = "commutative-multi-writer"
CLASS_ORDERED = "ordered-multi-writer"


@dataclasses.dataclass
class LaneWrite:
    """One scatter in the lowered program, classified for the
    write-race lane analysis."""

    site: str            # primitive path of the scatter eqn
    primitive: str       # "scatter", "scatter-add", ...
    kind: str            # LANE_REQ | LANE_MATRIX | LANE_STATE
    classification: str  # CLASS_SINGLE | CLASS_COMMUTATIVE | CLASS_ORDERED
    proof: str           # writer proof name, the combiner, or "-"
    shape: "tuple[int, ...]"
    dtype: str

    def to_json(self) -> dict:
        return {"site": self.site, "primitive": self.primitive,
                "kind": self.kind,
                "classification": self.classification,
                "proof": self.proof, "shape": list(self.shape),
                "dtype": self.dtype}


def _lane_kind(sig, n_tiles: int) -> str:
    shape, dtype = sig
    if len(shape) >= 2 and shape[-2:] == (n_tiles, n_tiles):
        return LANE_MATRIX
    if shape[-1:] == (n_tiles,) \
            and (len(shape) < 2 or shape[-2] != n_tiles) \
            and dtype in ("uint8", "int64"):
        return LANE_REQ
    return LANE_STATE


def lane_writes(jaxpr, n_tiles: int) -> "list[LaneWrite]":
    """Every scatter in the program, classified.

    The ladder: a scatter is SINGLE-WRITER when `walk.
    scatter_writer_proof` proves each target cell is written at most
    once (unique_indices, constant index rows, a single row per
    addressed slice, a provenance-distinct row axis, or the masked
    scratch-redirect); otherwise COMMUTATIVE-MULTI-WRITER when its
    combiner is order-independent (add/mul/min/max); otherwise
    ORDERED-MULTI-WRITER — the result depends on XLA's update order,
    which the contract does not own.  Note the ladder tries the
    single-writer proof even for commutative combiners: the round-12
    req lanes are masked ADD-scatters, and the analysis should say
    "single writer" about them, not merely "commutative"."""
    out = []

    def visit(scope, site):
        for eqn in scope.jaxpr.eqns:
            name = eqn.primitive.name
            here = f"{site}.{name}" if site else name
            if name.startswith("scatter"):
                sig = aval_sig(eqn.outvars[0].aval) or ((), "?")
                proof = scatter_writer_proof(eqn, scope)
                if proof is not None:
                    cls = CLASS_SINGLE
                elif name in _COMMUTATIVE_SCATTERS:
                    cls, proof = CLASS_COMMUTATIVE, name
                else:
                    cls, proof = CLASS_ORDERED, "-"
                out.append(LaneWrite(here, name,
                                     _lane_kind(sig, n_tiles), cls,
                                     proof, tuple(sig[0]), sig[1]))
            subs = call_arg_maps(eqn)
            if subs:
                tags = [t for t, _ in subjaxprs(eqn)]
                for k, sc in enumerate(subs):
                    tag = tags[k] if k < len(tags) else str(k)
                    visit(make_scope(sc.jaxpr, scope, eqn, sc),
                          f"{here}/{tag}")

    visit(scope_from_closed(jaxpr), "")
    return out


def lane_summary(writes: "list[LaneWrite]") -> dict:
    """{kind: {classification: count}} — the lane-classification table
    the README documents and `tools/audit.py --lanes` emits."""
    table = {}
    for w in writes:
        table.setdefault(w.kind, {}) \
             .setdefault(w.classification, 0)
    for w in writes:
        table[w.kind][w.classification] += 1
    return table


def write_race(jaxpr, n_tiles: int, *,
               fan_in: "dict | None" = None) -> "list[Finding]":
    """The standing gate for the [T, k] mailbox compaction.

    Classifies every scatter (`lane_writes`) and fails the audit when a
    rewrite has made a protocol write RACY — an ordered-multi-writer
    scatter into a req lane or a mailbox matrix.  The req lanes are
    single-writer by construction (each tile writes its own lane); the
    matrices are legitimately multi-writer but every current write is
    either provably cell-unique or commutative, and the bit-identity
    claims (sweep-vs-sequential, telemetry on/off, the differential
    model-checker replay) assume exactly that.  A rewrite that turns
    one of these into a replace-scatter with potentially aliasing rows
    silently hands the winner to XLA's update order — this rule is the
    error that stops it.  Ordered writes into other engine state get
    warning severity (scatter-determinism already polices them inside
    batched regions).

    `fan_in`, when given, is the per-matrix reachable fan-in bound from
    the model checker's exhaustive exploration
    (`analysis.protocol.explore(...).fan_in` — e.g. {"req": 1, "fwd":
    1, "ack": 1, "evict": 1}); it is attached to each finding so a
    failure report carries the bound the compaction design needs."""
    out = []
    for w in lane_writes(jaxpr, n_tiles):
        if w.classification != CLASS_ORDERED:
            continue
        gated = w.kind in (LANE_REQ, LANE_MATRIX)
        data = dict(w.to_json())
        if fan_in is not None:
            data["fan_in"] = dict(fan_in)
        if w.kind == LANE_REQ:
            msg = (f"req-lane scatter into {w.shape} {w.dtype} is "
                   f"ordered-multi-writer — the round-12 [T] request "
                   f"lanes are single-writer by construction (each "
                   f"tile owns its lane); this rewrite made the lane "
                   f"racy.  Restore a writer proof: iota/distinct row "
                   f"indices, the masked scratch-redirect, or "
                   f"unique_indices=True")
        elif w.kind == LANE_MATRIX:
            msg = (f"mailbox-matrix scatter into {w.shape} {w.dtype} "
                   f"is ordered-multi-writer — colliding rows hand "
                   f"the winner to XLA's update order and break the "
                   f"bit-identity contract.  Use a commutative "
                   f"combiner (masked add-scatter) or prove the rows "
                   f"distinct")
        else:
            msg = (f"engine-state scatter into {w.shape} {w.dtype} is "
                   f"ordered-multi-writer (no writer proof, "
                   f"non-commutative combiner)")
        out.append(Finding(
            "write-race", SEV_ERROR if gated else SEV_WARNING,
            w.site, msg, data=data))
    return out


# ---------------------------------------------------------------------------
# rule 12: gspmd-insertion (round 22)
# ---------------------------------------------------------------------------


def gspmd_insertion(jaxpr, n_tiles: int, *,
                    phase_names=()) -> "list[Finding]":
    """No collective outside the px packed-exchange whitelist.

    The regression gate for the mesh.py cliff: the packed exchange
    (`ParallelCtx.ag`) emits exactly ONE collective shape — a full-axis
    tiled int64 all_gather of the phase's packed descriptor — and the
    declared replication reductions are full-axis psum-likes.  Anything
    else in a mesh program is a STRAY: the tiny per-field/per-scatter
    collectives the GSPMD partitioner re-inserts when a rewrite loses
    the packing (~270 per iteration, measured 16x slower — see
    parallel/mesh.py's warning block), a partial-axis group reduction,
    or a permute the engine never emits.  Error severity; each finding
    names the collective's protocol phase so the report says WHERE the
    exchange discipline broke."""
    from graphite_tpu.analysis import comms

    out = []
    for c in comms.extract_collectives(
            jaxpr, n_tiles=n_tiles, phase_names=phase_names,
            axis_env=comms.mesh_axis_sizes(jaxpr)):
        if c.kind != comms.KIND_STRAY:
            continue
        out.append(Finding(
            "gspmd-insertion", SEV_ERROR, c.site,
            f"stray collective {c.primitive} over axis "
            f"({c.axis_name}) in phase '{c.phase}': "
            f"{c.dtype}{list(c.shape)} ({c.ici_bytes} ICI bytes) is "
            f"outside the px packed-exchange whitelist (one full-axis "
            f"tiled int64 all_gather per phase) and the declared "
            f"replication reductions — the GSPMD-insertion cliff "
            f"(parallel/mesh.py) reintroduces ~270 such collectives "
            f"per iteration.  Route the field through ParallelCtx.ag's "
            f"packed descriptor instead",
            data=c.to_json()))
    return out


# ---------------------------------------------------------------------------
# rule 13: replication-drift (round 22)
# ---------------------------------------------------------------------------


def replication_drift(jaxpr) -> "list[Finding]":
    """Every shard_map output DECLARED replicated across the tile axis
    must be PROVABLY uniform.

    The multi-chip engine recomputes its [T] control vectors, mailbox
    matrices and sync tables identically on every device
    (parallel/px.py's replication contract; `campaign_state_specs`
    declares them unsharded) — the contract holds only if nothing
    shard-dependent ever reaches a replicated carry slot.  The comms
    analyzer's tile-variance dataflow checks exactly that: variance
    enters at tile-sharded inputs, `axis_index`, and partial-axis
    (grouped) collectives, and is killed only by a full-axis exchange
    or reduction.  A declared-replicated output the dataflow cannot
    prove uniform — e.g. a partial-axis psum leaking a group-local
    value into a replicated carry — is silent cross-device divergence:
    the replicas disagree and every downstream bit-identity claim is
    void.  Error severity; findings name the leaking collective sites."""
    from graphite_tpu.analysis import comms

    out = []
    for row in comms.shard_map_uniformity(jaxpr):
        if not row["non_uniform"]:
            continue
        leak_s = ", ".join(
            f"{lk['primitive']} at {lk['site']}"
            for lk in row["leaks"]) or "no collective leak recorded " \
            "(variance flows from a sharded input or axis_index)"
        out.append(Finding(
            "replication-drift", SEV_ERROR, row["site"],
            f"shard_map output(s) {row['non_uniform']} are declared "
            f"replicated across the tile axis (no tile entry in "
            f"out_names) but are not provably uniform — a "
            f"shard-dependent value leaks into a replicated carry "
            f"slot and the device replicas can silently diverge.  "
            f"Variance sources: {leak_s}",
            data={"site": row["site"],
                  "non_uniform": list(row["non_uniform"]),
                  "declared_replicated":
                      list(row["declared_replicated"]),
                  "leaks": list(row["leaks"])}))
    return out
