"""Static collective/ICI traffic analyzer over lowered mesh programs.

Graphite's scalability argument is that CROSS-TILE traffic — not
per-tile work — is what a distributed simulator must keep bounded; our
TPU port's analog of its socket traffic is the ICI collectives the
`parallel/px.py` packed exchange emits per protocol iteration.  Rounds
10-12 budgeted the per-iteration kernel proxy and bytes moved; this
module budgets the collective dimension the two blocked ROADMAP items
(the [T, k] mailbox compaction and the 2D campaign's real-ICI leg)
actually turn on.

The analyzer is pure static analysis over the SAME `jax.make_jaxpr`
artifacts audit/cost/identity consume (one tracing, runs on 1-device
CPU CI — the mesh programs lower over a device-less AbstractMesh).
Three layers:

  extraction   `extract_collectives` walks every shard_map/pjit region
               and yields one `Collective` per collective equation
               (all_gather, ppermute, psum/pmin/pmax, all_to_all,
               reduce_scatter), each attributed to a protocol phase via
               the round-6 phase-cond structure — the SAME conds
               `cost.per_phase_costs` resolves, matched by equation
               IDENTITY (site strings are not unique: sibling eqns of
               one primitive share theirs).  A collective inside phase
               cond k belongs to phase k; one between conds belongs to
               the phase whose cond comes NEXT (it gathers that phase's
               working set); after the last cond (or in a cond-free
               vmapped program) it is "base".

  ICI pricing  per-collective payload bytes from operand avals and the
               sharded axis size, hop counts from the mesh topology:
               all_gather moves (n-1) x its shard per device over n-1
               ring hops ((n-1)/n of the full buffer per link);
               psum-likes pay the bidirectional ring all-reduce
               2(n-1)/n x the buffer; ppermute pays its payload times
               the max ring distance of its permutation; all_to_all
               and reduce_scatter (n-1)/n x the buffer.

  classification  every collective is kind "px-exchange" (the ONE
               packed descriptor `ParallelCtx.ag` emits: a full-axis
               tiled int64 all_gather — the signature the whitelist
               pins), "replication-reduction" (a full-axis psum/pmin/
               pmax, the declared way to uniformize a value), or
               "stray" — anything else, which is exactly what the
               GSPMD partitioner re-inserts when the packed exchange
               is lost (the mesh.py cliff: ~270 tiny per-scatter
               collectives per iteration, measured 16x slower).  The
               `gspmd-insertion` audit rule (rules.py) errors on every
               stray, naming its phase.

On top sit the two per-program budget metrics `collectives_per_iter`
and `ici_bytes_per_iter` (`collective_metrics` — consumed by
`cost.CostReport` and ratcheted through BUDGETS.json), the per-phase
table `tools/audit.py --comms` emits, and the tile-axis uniformity
dataflow (`shard_map_uniformity`) behind the replication-drift rule:
every shard_map output whose out_names declare it replicated across
the tile axis must be PROVABLY uniform — no partial-axis psum leaking
a shard-dependent value into a replicated carry slot.
"""

from __future__ import annotations

import dataclasses

import jax

from graphite_tpu.analysis.walk import (
    as_jaxpr, aval_bytes, aval_sig, call_arg_maps, iter_eqns_with_site,
    subjaxprs,
)

# Collective primitives as they appear in jaxprs.  `psum`/`pmin`/`pmax`
# carry `axes` + `axis_index_groups`; `all_gather` carries its
# `axis_size` and `tiled` flag; `ppermute` its `perm`;
# `all_to_all`/`reduce_scatter` move shards between devices.  (jax has
# no separate "all_reduce"/"collective_permute" eqn names — lax.psum IS
# the all-reduce and lax.ppermute IS the collective permute — but both
# aliases are kept in the set so a jax rename cannot silently blind the
# analyzer.)
COLLECTIVE_PRIMS = frozenset({
    "all_gather", "ppermute", "psum", "pmin", "pmax", "all_to_all",
    "reduce_scatter", "psum_scatter", "all_reduce",
    "collective_permute",
})

_PSUM_LIKE = frozenset({"psum", "pmin", "pmax", "all_reduce"})
_PERMUTE_LIKE = frozenset({"ppermute", "collective_permute"})
_SCATTERING = frozenset({"all_to_all", "reduce_scatter", "psum_scatter"})

# collective kinds (Collective.kind)
KIND_PX = "px-exchange"
KIND_REDUCTION = "replication-reduction"
KIND_STRAY = "stray"

# phase label for collectives outside every phase cond once all conds
# have passed — and for cond-free (vmapped) programs, where every
# collective is base
BASE_PHASE = "base"


def has_mesh_region(jaxpr) -> bool:
    """Does the program contain any shard_map region?  The gate for
    everything in this module: non-mesh programs have no collectives
    and get NO comms metrics (their budget entries stay byte-identical
    to the pre-round-22 ones)."""
    for _, eqn in iter_eqns_with_site(jaxpr):
        if eqn.primitive.name == "shard_map":
            return True
    return False


def mesh_axis_sizes(jaxpr) -> "dict[str, int]":
    """axis name -> size, merged over every shard_map eqn's mesh (the
    AbstractMesh the lowering traced over).  Feeds the psum-like
    pricing, whose eqns carry only axis NAMES."""
    out: "dict[str, int]" = {}
    for _, eqn in iter_eqns_with_site(jaxpr):
        if eqn.primitive.name != "shard_map":
            continue
        mesh = eqn.params.get("mesh")
        shape = getattr(mesh, "shape", None)
        if shape:
            for a, s in dict(shape).items():
                out[str(a)] = int(s)
    return out


def _collective_axes(eqn) -> "tuple[str, ...]":
    """The mesh axis names a collective eqn operates over (psum-likes
    use `axes`; the rest `axis_name`, which may be a bare string)."""
    p = eqn.params
    axes = p.get("axes") if "axes" in p else p.get("axis_name")
    if axes is None:
        return ()
    if isinstance(axes, (tuple, list)):
        return tuple(str(a) for a in axes)
    return (str(axes),)


def _group_size(eqn) -> "int | None":
    groups = eqn.params.get("axis_index_groups")
    if not groups:
        return None
    return int(len(groups[0]))


def _ring_distance(perm, n: int) -> int:
    """Max ring distance of a ppermute's (src, dst) pairs on an n-ring
    (ICI links are bidirectional: distance d or n-d, whichever is
    shorter)."""
    best = 0
    for s, d in perm or ():
        hop = abs(int(d) - int(s)) % n
        best = max(best, min(hop, n - hop))
    return best


@dataclasses.dataclass
class Collective:
    """One collective equation in a lowered mesh program, attributed
    and priced."""

    primitive: str
    site: str
    phase: str               # protocol phase name, or BASE_PHASE
    axis_name: str           # mesh axes joined with ","
    axis_size: int           # devices participating (group size if
    #                          axis_index_groups restricts the axis)
    shape: "tuple[int, ...]"  # operand (per-device shard) shape
    dtype: str
    shard_bytes: int         # per-device operand bytes
    payload_bytes: int       # the logical full buffer (result bytes)
    ici_bytes: int           # bytes crossing ICI links, per device
    hops: int                # worst-case link hops on the ring
    kind: str                # KIND_PX | KIND_REDUCTION | KIND_STRAY

    def to_json(self) -> dict:
        return {
            "primitive": self.primitive, "site": self.site,
            "phase": self.phase, "axis": self.axis_name,
            "axis_size": self.axis_size, "shape": list(self.shape),
            "dtype": self.dtype, "shard_bytes": self.shard_bytes,
            "payload_bytes": self.payload_bytes,
            "ici_bytes": self.ici_bytes, "hops": self.hops,
            "kind": self.kind,
        }


def collective_kind(eqn) -> str:
    """Classify one collective eqn against the px-exchange whitelist.

    The packed exchange (`ParallelCtx.ag`) emits EXACTLY one shape of
    collective: a full-axis (no axis_index_groups) TILED all_gather of
    an int64 descriptor — every field widened to int64 and concatenated
    so one collective moves the whole phase's working set.  A full-axis
    psum/pmin/pmax is the declared replication reduction (the sanctioned
    way to uniformize a value across shards).  Everything else is a
    STRAY: the per-scatter collectives GSPMD inserts when the packed
    exchange is lost (mesh.py's ~270/iteration cliff), a partial-axis
    group reduction, or a permute the engine never emits."""
    name = eqn.primitive.name
    if _group_size(eqn) is not None:
        return KIND_STRAY
    if name == "all_gather":
        dtype = str(getattr(eqn.invars[0].aval, "dtype", ""))
        if eqn.params.get("tiled") and dtype == "int64":
            return KIND_PX
        return KIND_STRAY
    if name in _PSUM_LIKE:
        return KIND_REDUCTION
    return KIND_STRAY


def _price(name: str, shard_bytes: int, n: int, perm=None,
           ) -> "tuple[int, int]":
    """(ici_bytes, hops) of one collective on an n-device ring."""
    if n <= 1:
        return 0, 0
    if name == "all_gather":
        # each device contributes its shard and receives n-1 others:
        # (n-1)/n of the full n*shard buffer crosses each link
        return (n - 1) * shard_bytes, n - 1
    if name in _PSUM_LIKE:
        # bidirectional ring all-reduce: reduce-scatter + all-gather,
        # each (n-1)/n of the buffer
        return (2 * (n - 1) * shard_bytes) // n, n - 1
    if name in _PERMUTE_LIKE:
        hops = _ring_distance(perm, n)
        return shard_bytes * hops, hops
    if name in _SCATTERING:
        return ((n - 1) * shard_bytes) // n, n - 1
    return shard_bytes, n - 1


def _make_collective(eqn, site: str, phase: str,
                     axis_env: "dict[str, int]") -> Collective:
    name = eqn.primitive.name
    axes = _collective_axes(eqn)
    group = _group_size(eqn)
    if group is not None:
        n = group
    elif name == "all_gather" and "axis_size" in eqn.params:
        n = int(eqn.params["axis_size"])
    else:
        n = 1
        for a in axes:
            n *= int(axis_env.get(a, 1))
    shard_b = aval_bytes(eqn.invars[0].aval) if eqn.invars else 0
    payload_b = aval_bytes(eqn.outvars[0].aval) if eqn.outvars else 0
    sig = (aval_sig(eqn.invars[0].aval) if eqn.invars else None) \
        or ((), "?")
    ici_b, hops = _price(name, shard_b, n,
                         perm=eqn.params.get("perm"))
    return Collective(
        primitive=name, site=site, phase=phase,
        axis_name=",".join(axes), axis_size=int(n),
        shape=tuple(sig[0]), dtype=sig[1],
        shard_bytes=int(shard_b), payload_bytes=int(payload_b),
        ici_bytes=int(ici_b), hops=int(hops),
        kind=collective_kind(eqn))


def extract_collectives(jaxpr, *, n_tiles: int, phase_names=(),
                        axis_env: "dict[str, int] | None" = None,
                        ) -> "list[Collective]":
    """Every collective eqn of `jaxpr` (at any depth), phase-attributed
    and priced.

    Phase attribution matches `cost.per_phase_costs`' structure but by
    equation IDENTITY: `rules.phase_conds` enumerates the gating conds
    in DFS program order; a collective inside cond k's subtree belongs
    to phase k, a collective outside every phase cond belongs to the
    phase whose cond the walk has NOT yet passed (the px gather that
    feeds phase k runs immediately before its cond), and once all conds
    have passed — or in a cond-free vmapped program — to BASE_PHASE.

    `axis_env` supplies mesh axis sizes for collectives whose eqns
    carry only axis names (psum-likes); pass `mesh_axis_sizes(closed)`
    when walking a SUB-jaxpr of the program (e.g. the main loop body,
    which sits inside the shard_map region that binds the axes)."""
    from graphite_tpu.analysis.rules import phase_conds

    j = as_jaxpr(jaxpr)
    pcs = {id(e): k for k, (_, e) in enumerate(phase_conds(j, n_tiles))}

    def pname(k: int) -> str:
        return phase_names[k] if k < len(phase_names) else f"phase_{k}"

    out: "list[Collective]" = []
    passed = {"n": 0}

    def walk(jx, site, env, phase):
        for eqn in as_jaxpr(jx).eqns:
            name = eqn.primitive.name
            here = f"{site}.{name}" if site else name
            if name in COLLECTIVE_PRIMS:
                if phase is not None:
                    ph = pname(phase)
                elif passed["n"] < len(pcs):
                    ph = pname(passed["n"])
                else:
                    ph = BASE_PHASE
                out.append(_make_collective(eqn, here, ph, env))
                continue
            k = pcs.get(id(eqn))
            if k is not None:
                for tag, sub in subjaxprs(eqn):
                    walk(sub, f"{here}/{tag}", env, k)
                passed["n"] += 1
                continue
            env2 = env
            if name == "shard_map":
                mesh = eqn.params.get("mesh")
                shape = getattr(mesh, "shape", None)
                if shape:
                    env2 = dict(env)
                    env2.update({str(a): int(s)
                                 for a, s in dict(shape).items()})
            for tag, sub in subjaxprs(eqn):
                walk(sub, f"{here}/{tag}", env2, phase)

    walk(j, "", dict(axis_env or {}), None)
    return out


# ---------------------------------------------------------------------------
# the report + budget metrics
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class PhaseComms:
    """One protocol phase's collective traffic (the --comms table row)."""

    phase: str
    collectives: int
    ici_bytes: int
    payload_bytes: int

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class CommsReport:
    """One mesh program's static collective/ICI measurements.

    The per-ITERATION view: collectives are extracted from the main
    quantum loop's body (`cost.main_loop_body`), the same per-iter
    scope the kernel/bytes budgets use, so `collectives_per_iter` and
    `ici_bytes_per_iter` move with what one protocol iteration costs
    the fabric."""

    program: str
    tiles: int
    axis_sizes: "dict[str, int]"
    collectives: "list[Collective]"

    @property
    def collectives_per_iter(self) -> int:
        return len(self.collectives)

    @property
    def ici_bytes_per_iter(self) -> int:
        return sum(c.ici_bytes for c in self.collectives)

    def strays(self) -> "list[Collective]":
        return [c for c in self.collectives if c.kind == KIND_STRAY]

    def phase_rows(self) -> "list[PhaseComms]":
        order: "list[str]" = []
        agg: "dict[str, PhaseComms]" = {}
        for c in self.collectives:
            row = agg.get(c.phase)
            if row is None:
                row = agg[c.phase] = PhaseComms(c.phase, 0, 0, 0)
                order.append(c.phase)
            row.collectives += 1
            row.ici_bytes += c.ici_bytes
            row.payload_bytes += c.payload_bytes
        return [agg[p] for p in order]

    def to_json(self) -> dict:
        return {
            "comms": True,
            "program": self.program,
            "tiles": self.tiles,
            "axis_sizes": dict(self.axis_sizes),
            "collectives_per_iter": self.collectives_per_iter,
            "ici_bytes_per_iter": self.ici_bytes_per_iter,
            "table": [r.to_json() for r in self.phase_rows()],
            "collectives": [c.to_json() for c in self.collectives],
        }


def comms_report(spec) -> CommsReport:
    """Measure one audited mesh program (an audit.ProgramSpec): the
    per-iteration collective set of its main quantum loop, phase-
    attributed.  Programs without a main while loop fall back to the
    whole program (single-quantum regions)."""
    from graphite_tpu.analysis.cost import main_loop_body

    closed = spec.closed
    env = mesh_axis_sizes(closed)
    body = main_loop_body(closed)
    scope = body if body is not None else closed
    cs = extract_collectives(
        scope, n_tiles=spec.n_tiles,
        phase_names=getattr(spec, "phase_names", ()), axis_env=env)
    return CommsReport(program=spec.name, tiles=int(spec.n_tiles),
                       axis_sizes=env, collectives=cs)


def collective_metrics(spec) -> "dict[str, int] | None":
    """The two budget metrics for `spec`, or None for a non-mesh
    program (whose BUDGETS.json entry must stay byte-identical to its
    pre-round-22 form — the metrics exist only where collectives can)."""
    if not has_mesh_region(spec.closed):
        return None
    rep = comms_report(spec)
    return {"collectives_per_iter": int(rep.collectives_per_iter),
            "ici_bytes_per_iter": int(rep.ici_bytes_per_iter)}


# ---------------------------------------------------------------------------
# tile-axis uniformity dataflow (replication-drift)
# ---------------------------------------------------------------------------

# Collectives that make their output IDENTICAL on every shard of the
# axis when run full-axis (no axis_index_groups): every device ends up
# holding the same reduction / the same gathered buffer.
_UNIFORMIZING = _PSUM_LIKE | {"all_gather"}


def _default_tile_axes() -> "tuple[str, ...]":
    from graphite_tpu.parallel.mesh import TILE_AXIS, TILE_AXIS_2D

    return (TILE_AXIS, TILE_AXIS_2D)


def _varying_outputs(jaxpr, in_varying, tile_axes, leaks, memo,
                     site=""):
    """Forward tile-variance dataflow over one jaxpr: given which
    invars hold shard-DEPENDENT values (True = varies across the tile
    axis), return the outvar variance mask.

    Sources of variance: tile-sharded inputs, `axis_index` over a tile
    axis, partial-axis (grouped) collectives, and the shard-scattering
    collectives (all_to_all / reduce_scatter).  Variance is KILLED by a
    full-axis uniformizing collective (psum-likes, all_gather) — the
    `ParallelCtx.ag` exchange is exactly such a kill, which is how the
    engine's replicated control state proves uniform.  Conds with a
    varying predicate poison every output (different shards take
    different branches); a while whose trip count can vary poisons the
    whole carry.  `leaks` collects the (site, primitive) pairs where
    variance was INTRODUCED by a collective — the named suspects a
    drift finding points at."""
    j = as_jaxpr(jaxpr)
    key = (id(j), tuple(bool(t) for t in in_varying))
    if key in memo:
        return memo[key]

    env: dict = {}
    for v, t in zip(j.invars, in_varying):
        env[v] = bool(t)

    def get(v):
        return (not isinstance(v, jax.core.Literal)) \
            and env.get(v, False)

    for eqn in j.eqns:
        name = eqn.primitive.name
        here = f"{site}.{name}" if site else name
        tin = [get(v) for v in eqn.invars]
        if name == "axis_index":
            varies = str(eqn.params.get("axis_name")) in tile_axes
            for v in eqn.outvars:
                env[v] = varies
            continue
        if name in COLLECTIVE_PRIMS:
            axes = _collective_axes(eqn)
            on_tile = any(a in tile_axes for a in axes)
            grouped = _group_size(eqn) is not None
            if on_tile and grouped:
                # the leak this rule exists for: a partial-axis
                # reduction gives each GROUP its own value
                for v in eqn.outvars:
                    env[v] = True
                leaks.append((here, name))
            elif on_tile and name in _UNIFORMIZING:
                for v in eqn.outvars:
                    env[v] = False
            elif on_tile and name in _SCATTERING:
                # each shard receives a DIFFERENT piece by design
                for v in eqn.outvars:
                    env[v] = True
                leaks.append((here, name))
            else:
                # permutes (and collectives over non-tile axes) move
                # values between shards: uniform in, uniform out
                t = any(tin)
                for v in eqn.outvars:
                    env[v] = t
            continue
        subs = call_arg_maps(eqn)
        if subs:
            if name == "cond":
                pred_varies = tin[0] if tin else False
                outs = [False] * len(eqn.outvars)
                if pred_varies:
                    # different shards take different branches — every
                    # output is shard-dependent
                    outs = [True] * len(eqn.outvars)
                else:
                    for sc in subs:
                        jj = as_jaxpr(sc.jaxpr)
                        inner_in = [
                            tin[sc.in_map[i]]
                            if i < len(sc.in_map)
                            and sc.in_map[i] is not None else False
                            for i in range(len(jj.invars))]
                        inner_out = _varying_outputs(
                            jj, inner_in, tile_axes, leaks, memo, here)
                        for o, t in enumerate(inner_out):
                            if t and o < len(sc.out_map) \
                                    and sc.out_map[o] is not None:
                                outs[sc.out_map[o]] = True
                for v, t in zip(eqn.outvars, outs):
                    env[v] = t
                continue

            def inner_mask(sc, jj, marks):
                return [marks[sc.in_map[i]]
                        if i < len(sc.in_map)
                        and sc.in_map[i] is not None else False
                        for i in range(len(jj.invars))]

            # while/scan: stabilize loop-carry variance at the
            # eqn-operand level (same fixpoint shape as
            # walk.taint_narrowing), then map the stable masks through
            tin_eff = list(tin)
            for sc in subs:
                if not any(f is not None for f in sc.feedback):
                    continue
                jj = as_jaxpr(sc.jaxpr)
                for _ in range(len(jj.outvars) + 2):
                    inner_out = _varying_outputs(
                        jj, inner_mask(sc, jj, tin_eff), tile_axes,
                        leaks, memo, here)
                    changed = False
                    for o, fb in enumerate(sc.feedback):
                        if fb is None or not inner_out[o] \
                                or fb >= len(sc.in_map):
                            continue
                        op_i = sc.in_map[fb]
                        if op_i is not None and not tin_eff[op_i]:
                            tin_eff[op_i] = True
                            changed = True
                    if not changed:
                        break
            out_t = [False] * len(eqn.outvars)
            diverged = False
            for sc in subs:
                jj = as_jaxpr(sc.jaxpr)
                inner_out = _varying_outputs(
                    jj, inner_mask(sc, jj, tin_eff), tile_axes, leaks,
                    memo, here)
                if name == "while" and sc is subs[0] \
                        and any(inner_out):
                    # a varying while PREDICATE means shards run
                    # different trip counts — the whole carry diverges
                    diverged = True
                for o, t in enumerate(inner_out):
                    if t and o < len(sc.out_map) \
                            and sc.out_map[o] is not None:
                        out_t[sc.out_map[o]] = True
            if diverged:
                leaks.append((here, "while-pred"))
                out_t = [True] * len(eqn.outvars)
            for v, t in zip(eqn.outvars, out_t):
                env[v] = t
            continue
        if subs == []:  # opaque call-like: conservative pass-through
            t = any(tin)
            for v in eqn.outvars:
                env[v] = t
            continue
        # plain eqn: deterministic math on uniform operands is uniform
        t = any(tin)
        for v in eqn.outvars:
            env[v] = t

    mask = [get(v) for v in j.outvars]
    memo[key] = mask
    return mask


def _names_have_tile(names, tile_axes) -> bool:
    """Does one shard_map in_names/out_names entry (dim -> axis tuple)
    mention a tile axis?"""
    for ax_tuple in (names or {}).values():
        axs = ax_tuple if isinstance(ax_tuple, (tuple, list)) \
            else (ax_tuple,)
        if any(str(a) in tile_axes for a in axs):
            return True
    return False


def shard_map_uniformity(jaxpr, tile_axes=None) -> "list[dict]":
    """Per-shard_map uniformity audit: which outputs are DECLARED
    replicated across the tile axis (out_names carries no tile entry)
    but not PROVABLY uniform by the variance dataflow.  Returns one row
    per shard_map region: {"site", "n_outputs", "declared_replicated",
    "non_uniform", "leaks"} — `non_uniform` non-empty means the
    replication-drift rule fires."""
    if tile_axes is None:
        tile_axes = _default_tile_axes()
    tile_axes = tuple(str(a) for a in tile_axes)
    rows = []
    for site, eqn in iter_eqns_with_site(as_jaxpr(jaxpr)):
        if eqn.primitive.name != "shard_map":
            continue
        in_names = eqn.params.get("in_names") or ()
        out_names = eqn.params.get("out_names") or ()
        body = eqn.params.get("jaxpr")
        if body is None:
            continue
        in_varying = [_names_have_tile(n, tile_axes) for n in in_names]
        bj = as_jaxpr(body)
        # align with the body's invars (shard_map wires 1:1)
        if len(in_varying) < len(bj.invars):
            in_varying += [False] * (len(bj.invars) - len(in_varying))
        leaks: "list[tuple[str, str]]" = []
        out_varying = _varying_outputs(
            body, in_varying[:len(bj.invars)], tile_axes, leaks, {},
            site)
        declared = [o for o, n in enumerate(out_names)
                    if not _names_have_tile(n, tile_axes)]
        bad = [o for o in declared
               if o < len(out_varying) and out_varying[o]]
        seen = set()
        uniq_leaks = []
        for lk in leaks:
            if lk not in seen:
                seen.add(lk)
                uniq_leaks.append({"site": lk[0], "primitive": lk[1]})
        rows.append({"site": site, "n_outputs": len(out_names),
                     "declared_replicated": declared,
                     "non_uniform": bad, "leaks": uniq_leaks})
    return rows


# ---------------------------------------------------------------------------
# known-bad fixtures (CI self-tests)
# ---------------------------------------------------------------------------


def gspmd_insertion_fixture(tiles: int = 8, tile_shards: int = 4):
    """The known-bad program the gspmd-insertion lint must trip on: a
    shard_map region lowering the LEGACY unpacked-scatter exchange — one
    small per-field collective (a uint8 gather, an untiled int64 gather)
    inside a real phase cond, instead of the ONE packed int64 descriptor
    `ParallelCtx.ag` emits.  This is exactly the mesh.py cliff shape:
    lose the packed exchange and the partitioner re-inserts tiny
    collectives per field/scatter.  Returns an audit.ProgramSpec named
    "gspmd-fixture" whose only failing rule must be gspmd-insertion,
    with the strays attributed to the 'requester' phase (the lint's
    exit-nonzero message names it)."""
    import jax.numpy as jnp
    from jax.sharding import AbstractMesh, PartitionSpec as P

    from graphite_tpu.analysis.audit import ProgramSpec
    from graphite_tpu.parallel.mesh import TILE_AXIS_2D, _shard_map

    T, dt = int(tiles), int(tile_shards)
    mesh = AbstractMesh(((TILE_AXIS_2D, dt),))

    def body(mail, types, times, progress):
        # mail: replicated uint8[T, T] mailbox; types/times: the
        # block-local per-lane fields the legacy layout exchanged one
        # collective EACH instead of packing
        def requester(m):
            t_full = jax.lax.all_gather(
                types, TILE_AXIS_2D, tiled=True)          # uint8: stray
            w_full = jax.lax.all_gather(
                times, TILE_AXIS_2D, tiled=False)         # untiled: stray
            row = jnp.zeros((T, T), jnp.uint8).at[0, :].set(t_full)
            bump = (w_full.sum() % 2).astype(jnp.uint8)
            return (m | row) + bump, progress + jnp.int32(1)

        def skip(m):
            return m, progress

        m2, prog = jax.lax.cond(progress < jnp.int32(4), requester,
                                skip, mail)
        return m2, prog

    fn = _shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(TILE_AXIS_2D), P(TILE_AXIS_2D), P()),
        out_specs=(P(), P()))
    closed = jax.make_jaxpr(fn)(
        jax.ShapeDtypeStruct((T, T), jnp.uint8),
        jax.ShapeDtypeStruct((T,), jnp.uint8),
        jax.ShapeDtypeStruct((T,), jnp.int64),
        jax.ShapeDtypeStruct((), jnp.int32))
    return ProgramSpec(
        name="gspmd-fixture", closed=closed,
        invar_paths=["mail", "types", "times", "progress"],
        n_tiles=T, phase_names=("requester",))


def replication_drift_fixture(tiles: int = 8, tile_shards: int = 4,
                              *, leak: bool = True):
    """The replication-drift pair: a shard_map whose scalar control
    output is DECLARED replicated but computed from a psum.  With
    `leak=True` the psum is partial-axis (axis_index_groups splits the
    tile axis) — each group gets its own value, the declared
    replication is a lie, and the rule must fire naming the grouped
    psum.  With `leak=False` the psum is full-axis and the proof goes
    through.  Returns an audit.ProgramSpec."""
    import jax.numpy as jnp
    from jax.sharding import AbstractMesh, PartitionSpec as P

    from graphite_tpu.analysis.audit import ProgramSpec
    from graphite_tpu.parallel.mesh import TILE_AXIS_2D, _shard_map

    T, dt = int(tiles), int(tile_shards)
    mesh = AbstractMesh(((TILE_AXIS_2D, dt),))
    half = list(range(dt // 2)), list(range(dt // 2, dt))
    groups = [list(g) for g in half] if leak else None

    def body(ctrl, vals):
        if groups is not None:
            part = jax.lax.psum(vals, TILE_AXIS_2D,
                                axis_index_groups=groups)
        else:
            part = jax.lax.psum(vals, TILE_AXIS_2D)
        return ctrl + part.sum()

    fn = _shard_map(body, mesh=mesh,
                    in_specs=(P(), P(TILE_AXIS_2D)), out_specs=P())
    closed = jax.make_jaxpr(fn)(
        jax.ShapeDtypeStruct((), jnp.int64),
        jax.ShapeDtypeStruct((T,), jnp.int64))
    name = "drift-fixture" if leak else "drift-fixture-ok"
    return ProgramSpec(name=name, closed=closed,
                       invar_paths=["ctrl", "vals"], n_tiles=T)
