"""Bounded model checking of the coherence protocols.

The golden interpreters (`golden/memory_model.py`,
`golden/memory_model_shl2.py`) are the readable, sequential statement of
the MSI/MOSI/shl2-MESI semantics.  This module drives them as a
*transition relation*: a configuration is a quiescent protocol state
(no transaction in flight), and each (tile, line, read|write) access is
one atomic transition.  BFS over the induced abstract state graph
exhaustively enumerates every reachable

    (directory entry, per-tile L1/L2 line state, data-freshness)

configuration for small geometries (2-4 tiles, 1-2 lines), checking the
classic coherence invariants at every state and along every transition:

  - ``single-writer-multiple-reader``: at most one tile holds a writable
    (M/E) copy, and a writable copy excludes every other copy (MOSI's O
    is read-only and may coexist with S).
  - ``data-value``: a read returns the value of the last write.  Checked
    with a version map evolved from the golden models' event stream
    (every write bumps a per-line global version; fills take the version
    of their actual data source — DRAM, the home's cdata buffer, a
    cache-to-cache supplier, or the shared-L2 slice).
  - ``directory-cache-agreement``: the directory entry's (dstate, owner,
    sharers) matches the actual cached copies, and L1 contents stay
    within L2 (private hierarchy).  The golden models' own internal
    asserts (a FWD to a non-holder) report under this invariant too.
  - ``bounded-in-flight``: the number of simultaneously outstanding
    protocol messages within a transition never exceeds the fan-out
    bound (T forwards + T acks + request + reply).
  - ``progress``: every transition completes within the event bound (no
    deadlock/livelock inside the exploration bound), and the BFS itself
    closes within ``max_states``.

Violations carry a named counterexample: the action path from reset plus
the violating transition's event sequence, rendered through the engines'
round-6 phase names (`engine.PHASE_NAMES` / `engine_shl2.SHL2_PHASE_NAMES`).

On top of the same exploration, the checker measures the per-matrix
fan-in actually reachable — the max simultaneous occupancy of the
fwd/ack/evict ``[T, T]`` mailbox matrices per home — which is the input
the planned ``[T, k]`` bounded-fanin compaction needs (ROADMAP).

Differential mode (`differential`) closes the loop on the *shipped*
kernels: every explored transition is replayed through the vectorized
engines (`memory/engine.py`, `memory/engine_shl2.py`) as a
barrier-serialized trace (the BFS path prefix plus the transition's
access), asserting bit-equality of clocks and all memory counters
against `golden.run_golden`, and agreement of the engines' final packed
state (via `engine.line_census` / `engine_shl2.shl2_line_census`) with
the model checker's successor configuration.  All replay traces are
padded to one uniform record count so a single jitted step function
serves every transition.
"""

from __future__ import annotations

import copy
import dataclasses
from collections import deque

import numpy as np

PROTOCOLS = {
    "msi": "pr_l1_pr_l2_dram_directory_msi",
    "mosi": "pr_l1_pr_l2_dram_directory_mosi",
    "shl2_mesi": "pr_l1_sh_l2_mesi",
}

INVARIANTS = (
    "single-writer-multiple-reader",
    "data-value",
    "directory-cache-agreement",
    "bounded-in-flight",
    "progress",
)

# line numbers used by the checker: stride 192 keeps every tracked line
# in the SAME L1 set (16 sets), L2/slice set (64 sets), directory set
# (8 sets) and home tile for 2-4 tiles, so multi-line exploration
# exercises victim eviction and directory NULLIFY on a 1-way geometry
BASE_LINE = 256
LINE_STRIDE = 192
LINE_BYTES = 64

# cache_array state names (INVALID/SHARED/MODIFIED/EXCLUSIVE/OWNED) +
# the shl2 slice's transient DATA_INVALID
_ST = {0: "I", 1: "S", 2: "M", 3: "E", 4: "O", 5: "DV"}
_DIRN = {0: "U", 1: "Sh", 2: "M", 3: "O", 4: "E"}

# event kind -> phase-name index (round-6 names; validated against the
# engines' PHASE_NAMES tuples in tests/test_protocol_mc.py)
_PRIV_PHASE = {"hit": 0, "evict": 1, "req": 2, "fwd": 2, "serve": 3,
               "reply": 4, "fill": 5}
_SHL2_PHASE = {"hit": 0, "serve": 1, "evict": 2, "slice_kill": 2,
               "reply": 3, "req": 4, "fwd": 4, "slice_fill": 4, "fill": 5}


@dataclasses.dataclass(frozen=True)
class Action:
    """One transition label: tile `tile` reads or writes `line`."""
    tile: int
    line: int
    write: bool

    def __str__(self):
        return (f"t{self.tile} {'W' if self.write else 'R'} "
                f"line {self.line:#x}")


@dataclasses.dataclass
class Violation:
    invariant: str
    message: str
    path: tuple        # Actions from reset up to AND INCLUDING the bad one
    events: tuple      # rendered event strings of the violating transition

    def render(self) -> str:
        lines = [f"invariant violated: {self.invariant}",
                 f"  {self.message}",
                 "  path from reset:"]
        lines += [f"    {i}. {a}" for i, a in enumerate(self.path)]
        lines.append("  events of the violating transition:")
        lines += [f"    {e}" for e in self.events]
        return "\n".join(lines)


@dataclasses.dataclass
class MCResult:
    protocol: str
    n_tiles: int
    lines: tuple
    states_explored: int
    transitions: int
    histogram: dict          # feature -> #states containing it
    fan_in: dict             # matrix -> max reachable simultaneous fan-in
    max_in_flight: int
    violations: list
    # every explored transition as (action sequence ending in it,
    # successor protocol-state key) — the differential replay's worklist
    transition_seqs: list = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations


@dataclasses.dataclass
class DiffResult:
    protocol: str
    n_transitions: int
    n_ok: int
    mismatches: list

    @property
    def ok(self) -> bool:
        return self.n_ok == self.n_transitions and not self.mismatches


# ---------------------------------------------------------------------------
# geometry / model construction
# ---------------------------------------------------------------------------


def mc_lines(n_lines: int) -> tuple:
    return tuple(BASE_LINE + i * LINE_STRIDE for i in range(n_lines))


def mc_sim_config(protocol: str, n_tiles: int):
    """Tiny-geometry SimConfig: 1-way 16-set L1s, 1-way 64-set L2, 2-way
    8-set directory — small enough that 2 tracked lines collide
    everywhere (evictions + NULLIFY reachable)."""
    from graphite_tpu.config import ConfigFile, SimConfig

    text = f"""
[general]
total_cores = {n_tiles}
mode = lite
max_frequency = 1.0
enable_shared_mem = true
[network]
user = magic
memory = magic
[caching_protocol]
type = {PROTOCOLS[protocol]}
[core/static_instruction_costs]
mov = 1
ialu = 1
[l1_icache/T1]
cache_size = 1
associativity = 1
[l1_dcache/T1]
cache_size = 1
associativity = 1
[l2_cache/T1]
cache_size = 4
associativity = 1
[dram_directory]
total_entries = 16
associativity = 2
"""
    return SimConfig(ConfigFile.from_string(text))


def make_model(sc, mutant: str | None = None):
    """A fresh golden model for `sc` (optionally a seeded mutant)."""
    from graphite_tpu.memory.params import MemParams
    from graphite_tpu.models.dvfs import module_freq_mhz

    mp = MemParams.from_config(sc)
    freq = int(module_freq_mhz(sc.cfg, "CORE"))
    if mp.protocol.startswith("pr_l1_sh_l2"):
        if mutant is not None:
            raise ValueError(f"mutant {mutant!r} targets the private"
                             " protocols")
        from graphite_tpu.golden.memory_model_shl2 import GoldenShL2

        return GoldenShL2(mp, freq)
    from graphite_tpu.golden.memory_model import GoldenMemory

    if mutant is None:
        return GoldenMemory(mp, freq)
    if mutant not in _MUTANTS:
        raise ValueError(f"unknown mutant {mutant!r} "
                         f"(choose from {', '.join(MUTANT_NAMES)})")
    return _MUTANTS[mutant]()(mp, freq)


def _mutant_mosi_owner_skips_wb():
    """MOSI O/M owner acks a WB fetch without supplying the line: the
    home fetches stale data from DRAM — a data-value bug the mutant
    self-test must catch."""
    from graphite_tpu.golden.memory_model import GoldenMemory

    class MosiOwnerSkipsWb(GoldenMemory):
        def _serve_fwd(self, s, kind, line, ftime, home, enabled):
            ack, supplies = super()._serve_fwd(s, kind, line, ftime,
                                               home, enabled)
            if kind == "wb":
                supplies = False
            return ack, supplies

    return MosiOwnerSkipsWb


_MUTANTS = {"mosi-owner-skips-wb": _mutant_mosi_owner_skips_wb}
MUTANT_NAMES = tuple(_MUTANTS)


# ---------------------------------------------------------------------------
# version map (data-value invariant)
# ---------------------------------------------------------------------------


class _Versions:
    """Per-line write-version bookkeeping.  `global_v` bumps on every
    committed write; every physical copy (per-tile hierarchy, DRAM, the
    private home's cdata buffer, the shl2 slice) carries the version of
    the data it holds.  A read that observes a version != global_v read
    stale data."""

    def __init__(self, lines):
        self.global_v = {ln: 0 for ln in lines}
        self.dram_v = {ln: 0 for ln in lines}
        self.cdata_v = {ln: 0 for ln in lines}
        self.slice_v = {ln: 0 for ln in lines}
        self.copy_v = {ln: {} for ln in lines}     # tile -> version


# ---------------------------------------------------------------------------
# the per-transition observer
# ---------------------------------------------------------------------------


class _TxnObserver:
    """Attached as `model.event_cb` for exactly one transition: records
    the event sequence, evolves the version map, counts in-flight
    messages and per-matrix fan-in, and flags in-transition violations
    (data-value, bounded-in-flight, progress)."""

    def __init__(self, versions: _Versions, lines, n_tiles, is_shl2,
                 is_mosi, event_bound):
        self.v = versions
        self.lines = set(lines)
        self.n_tiles = n_tiles
        self.is_shl2 = is_shl2
        self.is_mosi = is_mosi
        self.event_bound = event_bound
        self.events = []           # (kind, kw)
        self.violations = []       # (invariant, message)
        self.supply_v = {}         # line -> version travelling with acks
        self.fill_v = {}           # line -> version of the pending reply
        self.cur_mtype = None      # mtype of the innermost "req"
        self.outstanding_fwd = 0
        self.fan = {"req": 0, "fwd": 0, "ack": 0, "evict": 0}
        self.max_in_flight = 0
        self._txn_fwd = 0
        self._txn_ack = 0
        self._evicts = {}          # home -> count

    # -- helpers -----------------------------------------------------------

    def _flag(self, invariant, message):
        self.violations.append((invariant, message))

    def _track(self, line):
        return line in self.lines

    # -- the callback ------------------------------------------------------

    def __call__(self, kind, kw):
        self.events.append((kind, kw))
        if len(self.events) > self.event_bound:
            if len(self.events) == self.event_bound + 1:
                self._flag("progress",
                           f"transition exceeded {self.event_bound} "
                           "protocol events (livelock within bound)")
            return
        line = kw.get("line")
        v = self.v
        if kind == "req":
            self.fan["req"] = max(self.fan["req"], 1)
            self.cur_mtype = kw["mtype"]
            self._txn_fwd = 0
            self._txn_ack = 0
        elif kind == "fwd":
            self.outstanding_fwd += 1
            self._txn_fwd = (self.n_tiles if kw.get("broadcast")
                             else self._txn_fwd + 1)
            self.fan["fwd"] = max(self.fan["fwd"], self._txn_fwd)
            # request + outstanding forwards (+ the eventual reply)
            self.max_in_flight = max(self.max_in_flight,
                                     1 + self.outstanding_fwd)
            if self.outstanding_fwd > self.n_tiles:
                self._flag("bounded-in-flight",
                           f"{self.outstanding_fwd} forwards in flight "
                           f"for {self.n_tiles} tiles")
        elif kind == "serve":
            self.outstanding_fwd = max(0, self.outstanding_fwd - 1)
            self._txn_ack += 1
            self.fan["ack"] = max(self.fan["ack"], self._txn_ack)
            if self._track(line):
                t = kw["tile"]
                held = v.copy_v[line].get(t, v.dram_v[line])
                if kw["supplies"]:
                    self.supply_v[line] = held
                    if self.is_shl2:
                        # dirty ack data lands in the home slice
                        v.slice_v[line] = held
                    elif kw["kind"] == "flush" \
                            and self.cur_mtype == "nullify":
                        # NULLIFY flush: the dying entry's dirty data
                        # goes back to DRAM (`processNullifyReq`)
                        v.dram_v[line] = held
                if kw["kind"] in ("inv", "flush"):
                    v.copy_v[line].pop(t, None)
                elif kw["kind"] == "wb" and not self.is_shl2 \
                        and not self.is_mosi:
                    v.dram_v[line] = held           # MSI WB write-through
        elif kind == "evict":
            home = kw["home"]
            self._evicts[home] = self._evicts.get(home, 0) + 1
            self.fan["evict"] = max(self.fan["evict"], self._evicts[home])
            if self._track(line):
                src = kw["src"]
                held = v.copy_v[line].pop(src, v.dram_v[line])
                if kw["dirty"]:
                    if self.is_shl2:
                        v.slice_v[line] = held      # L1 flush -> slice
                    else:
                        v.cdata_v[line] = held      # parked in cdata
                        v.dram_v[line] = held       # and written through
        elif kind == "slice_fill":
            if self._track(line):
                v.slice_v[line] = v.dram_v[line]
        elif kind == "slice_kill":
            if self._track(line) and kw["dirty"]:
                v.dram_v[line] = v.slice_v[line]
        elif kind == "reply":
            if self._track(line):
                src = kw["source"]
                if src == "c2c":
                    self.fill_v[line] = self.supply_v.get(
                        line, v.dram_v[line])
                elif src == "cdata":
                    self.fill_v[line] = v.cdata_v[line]
                elif src == "slice":
                    self.fill_v[line] = v.slice_v[line]
                else:
                    self.fill_v[line] = v.dram_v[line]
        elif kind == "hit":
            if self._track(line):
                t = kw["tile"]
                held = v.copy_v[line].get(t, -1)
                if held != v.global_v[line]:
                    self._flag(
                        "data-value",
                        f"t{t} {'write' if kw['write'] else 'read'} hit "
                        f"observes version {held} of line {line:#x}, "
                        f"last write is {v.global_v[line]}")
                if kw["write"]:
                    v.global_v[line] += 1
                    v.copy_v[line][t] = v.global_v[line]
        elif kind == "fill":
            if self._track(line):
                t = kw["tile"]
                got = self.fill_v.get(line, v.dram_v[line])
                if got != v.global_v[line]:
                    self._flag(
                        "data-value",
                        f"t{t} {'write' if kw['write'] else 'read'} fill "
                        f"receives version {got} of line {line:#x}, "
                        f"last write is {v.global_v[line]}")
                v.copy_v[line][t] = got
                if kw["write"]:
                    v.global_v[line] += 1
                    v.copy_v[line][t] = v.global_v[line]


def render_event(protocol: str, kind: str, kw: dict) -> str:
    """One event line of a counterexample, named by its engine phase."""
    if protocol == "shl2_mesi":
        from graphite_tpu.memory.engine_shl2 import SHL2_PHASE_NAMES
        phase = SHL2_PHASE_NAMES[_SHL2_PHASE[kind]]
    else:
        from graphite_tpu.memory.engine import PHASE_NAMES
        phase = PHASE_NAMES[_PRIV_PHASE[kind]]
    line = kw.get("line", -1)
    if kind == "req":
        desc = (f"{kw['mtype'].upper()} req t{kw['requester']} -> "
                f"home t{kw['home']}, line {line:#x}")
    elif kind == "fwd":
        desc = (f"home t{kw['home']} -> t{kw['target']}: "
                f"{kw['kind'].upper()} line {line:#x}"
                + (" (broadcast)" if kw.get("broadcast") else ""))
    elif kind == "serve":
        desc = (f"t{kw['tile']} acks {kw['kind'].upper()} line {line:#x}"
                + (", supplies data" if kw["supplies"] else ""))
    elif kind == "evict":
        desc = (f"t{kw['src']} evicts line {line:#x} -> home t{kw['home']}"
                + (" (dirty)" if kw["dirty"] else ""))
    elif kind == "slice_fill":
        desc = (f"slice t{kw['home']} fills line {line:#x} "
                f"from {kw['source']}")
    elif kind == "slice_kill":
        desc = (f"slice t{kw['home']} drops line {line:#x}"
                + (", dirty -> DRAM" if kw["dirty"] else ""))
    elif kind == "reply":
        desc = (f"home t{kw['home']} replies to t{kw['requester']} "
                f"({kw['source']} data), line {line:#x}")
    elif kind == "hit":
        desc = (f"t{kw['tile']} {'write' if kw['write'] else 'read'} "
                f"{kw['level']} hit, line {line:#x}"
                + (" (E->M)" if kw.get("promoted") else ""))
    elif kind == "fill":
        desc = (f"t{kw['tile']} fills line {line:#x} -> "
                f"{_ST.get(kw['state'], '?')}")
    else:
        desc = repr(kw)
    return f"{phase}: {desc}"


# ---------------------------------------------------------------------------
# abstraction + quiescent-state invariants
# ---------------------------------------------------------------------------


def _cstate(cache, line) -> int:
    hit, _, st = cache.lookup(line)
    return int(st) if hit else 0


def _abstract_private(model, lines, v: _Versions, n_tiles):
    ks = []
    for line in lines:
        home = model._home_of(line)
        hm = model.homes[home]
        e = model._dir_find(hm, line)
        dent = (None if e is None
                else (e.dstate, e.owner, frozenset(e.sharers)))
        g = v.global_v[line]
        fresh = (v.dram_v[line] == g,
                 bool(hm.cdata_valid and hm.cdata_line == line
                      and v.cdata_v[line] == g),
                 tuple(v.copy_v[line].get(t, -1) == g
                       for t in range(n_tiles)))
        ks.append((
            tuple(_cstate(model.l1d[t], line) for t in range(n_tiles)),
            tuple(_cstate(model.l2[t], line) for t in range(n_tiles)),
            dent,
            bool(hm.cdata_valid and hm.cdata_line == line),
            fresh,
        ))
    return tuple(ks)


def _abstract_shl2(model, lines, v: _Versions, n_tiles):
    ks = []
    for line in lines:
        home = model._home_of(line)
        hit, way, slice_st = model.l2[home].lookup(line)
        dent = None
        if hit:
            e = model.dir[home].get((line % model.l2[home].sets, way))
            if e is not None:
                dent = (e.dstate, e.owner, frozenset(e.sharers))
        g = v.global_v[line]
        fresh = (v.dram_v[line] == g,
                 bool(hit and v.slice_v[line] == g),
                 tuple(v.copy_v[line].get(t, -1) == g
                       for t in range(n_tiles)))
        ks.append((
            tuple(_cstate(model.l1d[t], line) for t in range(n_tiles)),
            int(slice_st) if hit else 0,
            dent,
            fresh,
        ))
    return tuple(ks)


def _check_private(model, lines, v: _Versions, n_tiles):
    """Quiescent-state invariants for the private-L2 protocols."""
    from graphite_tpu.memory.cache_array import (
        EXCLUSIVE, MODIFIED, OWNED, SHARED)
    from graphite_tpu.memory.state import (
        DIR_MODIFIED, DIR_OWNED, DIR_SHARED, DIR_UNCACHED)

    out = []
    for line in lines:
        l2 = [_cstate(model.l2[t], line) for t in range(n_tiles)]
        l1 = [_cstate(model.l1d[t], line) for t in range(n_tiles)]
        holders = {t for t in range(n_tiles) if l2[t]}
        writers = {t for t in range(n_tiles)
                   if l2[t] in (MODIFIED, EXCLUSIVE)}
        desc = (f"line {line:#x}: l1d="
                + "".join(_ST[s] for s in l1)
                + " l2=" + "".join(_ST[s] for s in l2))
        if len(writers) > 1:
            out.append(("single-writer-multiple-reader",
                        f"{desc}: {len(writers)} writable copies"))
        if writers and len(holders) > 1:
            out.append(("single-writer-multiple-reader",
                        f"{desc}: writable copy coexists with other "
                        "copies"))
        for t in range(n_tiles):
            if l1[t] and not l2[t]:
                out.append(("directory-cache-agreement",
                            f"{desc}: t{t} L1 copy outside L2"))
        home = model._home_of(line)
        e = model._dir_find(model.homes[home], line)
        dstate = e.dstate if e is not None else DIR_UNCACHED
        dsh = set(e.sharers) if e is not None else set()
        downer = e.owner if e is not None else -1
        dname = _DIRN.get(dstate, "?")
        if dsh != holders:
            out.append(("directory-cache-agreement",
                        f"{desc}: dir {dname} sharers {sorted(dsh)} != "
                        f"holders {sorted(holders)}"))
        if dstate == DIR_UNCACHED and holders:
            out.append(("directory-cache-agreement",
                        f"{desc}: dir UNCACHED but line cached"))
        if dstate == DIR_SHARED and any(
                l2[t] not in (0, SHARED) for t in range(n_tiles)):
            out.append(("directory-cache-agreement",
                        f"{desc}: dir Sh with a non-S copy"))
        if dstate == DIR_MODIFIED and (
                downer not in writers or holders != {downer}):
            out.append(("directory-cache-agreement",
                        f"{desc}: dir M owner t{downer} mismatch"))
        if dstate == DIR_OWNED and (
                downer < 0 or l2[downer] != OWNED or any(
                    l2[t] not in (0, SHARED) for t in range(n_tiles)
                    if t != downer)):
            out.append(("directory-cache-agreement",
                        f"{desc}: dir O owner t{downer} mismatch"))
    return out


def _check_shl2(model, lines, v: _Versions, n_tiles):
    from graphite_tpu.memory.cache_array import (
        EXCLUSIVE, MODIFIED, SHARED)
    from graphite_tpu.memory.engine_shl2 import DATA_INVALID, DIR_EXCLUSIVE
    from graphite_tpu.memory.state import (
        DIR_MODIFIED, DIR_SHARED, DIR_UNCACHED)

    out = []
    for line in lines:
        l1 = [_cstate(model.l1d[t], line) for t in range(n_tiles)]
        holders = {t for t in range(n_tiles) if l1[t]}
        writers = {t for t in range(n_tiles)
                   if l1[t] in (MODIFIED, EXCLUSIVE)}
        desc = f"line {line:#x}: l1d=" + "".join(_ST[s] for s in l1)
        if len(writers) > 1:
            out.append(("single-writer-multiple-reader",
                        f"{desc}: {len(writers)} writable copies"))
        if writers and len(holders) > 1:
            out.append(("single-writer-multiple-reader",
                        f"{desc}: writable copy coexists with other "
                        "copies"))
        home = model._home_of(line)
        hit, way, slice_st = model.l2[home].lookup(line)
        if hit and slice_st == DATA_INVALID:
            out.append(("progress",
                        f"{desc}: slice stuck DATA_INVALID at rest"))
        e = (model.dir[home].get((line % model.l2[home].sets, way))
             if hit else None)
        dstate = e.dstate if e is not None else DIR_UNCACHED
        dsh = set(e.sharers) if e is not None else set()
        downer = e.owner if e is not None else -1
        dname = _DIRN.get(dstate, "?")
        if not hit and holders:
            out.append(("directory-cache-agreement",
                        f"{desc}: L1 copies without a slice line"))
        if dsh != holders:
            out.append(("directory-cache-agreement",
                        f"{desc}: dir {dname} sharers {sorted(dsh)} != "
                        f"holders {sorted(holders)}"))
        if dstate == DIR_SHARED and any(
                l1[t] not in (0, SHARED) for t in range(n_tiles)):
            out.append(("directory-cache-agreement",
                        f"{desc}: dir Sh with a non-S copy"))
        if dstate == DIR_MODIFIED and (
                downer < 0 or l1[downer] != MODIFIED
                or holders != {downer}):
            out.append(("directory-cache-agreement",
                        f"{desc}: dir M owner t{downer} mismatch"))
        if dstate == DIR_EXCLUSIVE and (
                downer < 0 or l1[downer] not in (EXCLUSIVE, MODIFIED)
                or holders != {downer}):
            # silent E->M promotion keeps dstate EXCLUSIVE (documented)
            out.append(("directory-cache-agreement",
                        f"{desc}: dir E owner t{downer} mismatch"))
    return out


def _histogram_add(hist, key, is_shl2):
    feats = set()
    for part in key:
        if is_shl2:
            l1, slice_st, dent, _fresh = part
            if slice_st:
                feats.add(f"slice:{_ST[slice_st]}")
        else:
            l1, l2, dent, cdata, _fresh = part
            for s in l2:
                if s:
                    feats.add(f"l2:{_ST[s]}")
            if cdata:
                feats.add("cdata")
        for s in l1:
            if s:
                feats.add(f"l1d:{_ST[s]}")
        if dent is not None:
            feats.add(f"dir:{_DIRN.get(dent[0], '?')}")
    for f in feats:
        hist[f] = hist.get(f, 0) + 1


# ---------------------------------------------------------------------------
# exploration
# ---------------------------------------------------------------------------


def explore(protocol: str, n_tiles: int = 2, n_lines: int = 1, *,
            mutant: str | None = None, max_states: int = 50000,
            event_bound: int = 128, max_violations: int = 8) -> MCResult:
    """BFS over the quiescent-configuration graph.  Exhaustive within
    the abstraction (protocol state x data freshness) — terminates when
    no new configuration is reachable or a bound trips (the latter is a
    ``progress`` violation, not silent truncation)."""
    if protocol not in PROTOCOLS:
        raise ValueError(f"unknown protocol {protocol!r}; "
                         f"one of {sorted(PROTOCOLS)}")
    sc = mc_sim_config(protocol, n_tiles)
    lines = mc_lines(n_lines)
    is_shl2 = protocol == "shl2_mesi"
    is_mosi = protocol == "mosi"
    abstract = _abstract_shl2 if is_shl2 else _abstract_private
    check = _check_shl2 if is_shl2 else _check_private

    model0 = make_model(sc, mutant)
    v0 = _Versions(lines)
    key0 = abstract(model0, lines, v0, n_tiles)

    reps = {key0: (model0, v0)}
    paths = {key0: ()}
    frontier = deque([key0])
    hist: dict = {}
    _histogram_add(hist, key0, is_shl2)
    fan = {"req": 0, "fwd": 0, "ack": 0, "evict": 0}
    max_in_flight = 0
    violations: list = []
    transition_seqs: list = []
    transitions = 0

    actions = [Action(t, ln, w) for t in range(n_tiles) for ln in lines
               for w in (False, True)]

    while frontier:
        key = frontier.popleft()
        model, vers = reps[key]
        path = paths[key]
        for a in actions:
            if len(violations) >= max_violations:
                frontier.clear()
                break
            m2 = copy.deepcopy(model)
            v2 = copy.deepcopy(vers)
            obs = _TxnObserver(v2, lines, n_tiles, is_shl2, is_mosi,
                               event_bound)
            m2.event_cb = obs
            try:
                m2._slot(a.tile, False, a.line * LINE_BYTES, a.write,
                         clock_ps=0, enabled=True)
            except AssertionError as exc:
                obs._flag("directory-cache-agreement", str(exc))
            except RecursionError:
                obs._flag("progress",
                          "unbounded protocol recursion (deadlock)")
            m2.event_cb = None
            transitions += 1
            seq = path + (a,)

            for mat in fan:
                fan[mat] = max(fan[mat], obs.fan[mat])
            max_in_flight = max(max_in_flight, obs.max_in_flight)

            found = list(obs.violations)
            if not found:
                found = check(m2, lines, v2, n_tiles)
            if found:
                rendered = tuple(render_event(protocol, k, kw)
                                 for k, kw in obs.events)
                for inv, msg in found:
                    violations.append(Violation(inv, msg, seq, rendered))
                continue   # do not explore past a broken configuration

            succ = abstract(m2, lines, v2, n_tiles)
            transition_seqs.append((seq, succ))
            if succ not in reps:
                if len(reps) >= max_states:
                    violations.append(Violation(
                        "progress",
                        f"state space exceeds max_states={max_states}",
                        seq, ()))
                    frontier.clear()
                    break
                reps[succ] = (m2, v2)
                paths[succ] = seq
                frontier.append(succ)
                _histogram_add(hist, succ, is_shl2)

    return MCResult(
        protocol=protocol, n_tiles=n_tiles, lines=lines,
        states_explored=len(reps), transitions=transitions,
        histogram=dict(sorted(hist.items())), fan_in=fan,
        max_in_flight=max_in_flight, violations=violations,
        transition_seqs=transition_seqs)


# ---------------------------------------------------------------------------
# differential replay through the vectorized engines
# ---------------------------------------------------------------------------


def _replay_builders(seq, n_tiles):
    from graphite_tpu.trace.schema import TraceBuilder

    bs = [TraceBuilder() for _ in range(n_tiles)]
    bs[0].barrier_init(9, n_tiles)
    for a in seq:
        for b in bs:
            b.barrier_wait(9)
        if a.write:
            bs[a.tile].store(a.line * LINE_BYTES, 8)
        else:
            bs[a.tile].load(a.line * LINE_BYTES, 8)
    return bs


def differential(result: MCResult, *, max_quanta: int = 4096,
                 max_transitions: int | None = None,
                 progress_cb=None) -> DiffResult:
    """Replay every explored transition through the shipped vectorized
    engine and assert bit-equality with the golden oracle.

    Each transition's action sequence (BFS path prefix + the step)
    becomes a barrier-serialized trace: all tiles rendezvous before each
    access, so the engine resolves the accesses in exactly the explored
    order and the established serialized bit-exactness contract applies
    (tests/test_memory_golden.py).  All traces are padded with IALU
    filler to one uniform record count, so ONE jitted step function
    serves every transition.  Checks, per transition:

      - engine clock_ps and every memory counter == `run_golden`,
      - engine completes (no deadlock flag) within `max_quanta`,
      - the engine's final packed per-line state (census) matches the
        model checker's successor configuration.
    """
    import jax

    from graphite_tpu.engine.simulator import Simulator
    from graphite_tpu.engine.state import DeviceTrace
    from graphite_tpu.golden import run_golden
    from graphite_tpu.memory.params import MemParams
    from graphite_tpu.trace.schema import Op, TraceBatch

    protocol = result.protocol
    n_tiles = result.n_tiles
    lines = result.lines
    is_shl2 = protocol == "shl2_mesi"
    sc = mc_sim_config(protocol, n_tiles)
    mp = MemParams.from_config(sc)

    seqs = result.transition_seqs
    if max_transitions is not None:
        seqs = seqs[:max_transitions]
    if not seqs:
        return DiffResult(protocol, 0, 0, [])

    all_builders = [_replay_builders(seq, n_tiles) for seq, _ in seqs]
    rmax = max(len(b._op) for bs in all_builders for b in bs)
    batches = []
    for bs in all_builders:
        for b in bs:
            while len(b._op) < rmax:
                b.instr(Op.IALU)
        batches.append(TraceBatch.from_builders(bs))

    sim = Simulator(sc, batches[0])
    fn, args = sim._auditable_fn(max_quanta)
    st0 = args[0]
    jfn = jax.jit(fn)

    mismatches = []
    n_ok = 0
    for i, ((seq, succ), batch) in enumerate(zip(seqs, batches)):
        out = jfn(st0, DeviceTrace.from_batch(batch))
        state = out[0]
        deadlock = bool(np.asarray(out[2]))
        label = " ; ".join(str(a) for a in seq)
        if deadlock or not bool(np.asarray(state.done).all()):
            mismatches.append(f"[{label}] engine "
                              + ("deadlock" if deadlock else
                                 f"did not finish in {max_quanta} quanta"))
            continue
        gold = run_golden(sc, batch)
        bad = False
        eng_clock = np.asarray(state.core.clock_ps)
        if not np.array_equal(eng_clock, gold.clock_ps):
            mismatches.append(
                f"[{label}] clock_ps {eng_clock.tolist()} != "
                f"{np.asarray(gold.clock_ps).tolist()}")
            bad = True
        for name in gold.mem_counters:
            e = np.asarray(getattr(state.mem.counters, name))
            g = np.asarray(gold.mem_counters[name])
            if not np.array_equal(e, g):
                mismatches.append(
                    f"[{label}] counter {f.name} {e.tolist()} != "
                    f"{g.tolist()}")
                bad = True
        cen = _engine_census(state.mem, mp, lines, is_shl2)
        want = _succ_census(succ, lines, n_tiles, is_shl2)
        if cen != want:
            mismatches.append(
                f"[{label}] final state census {cen} != explored "
                f"successor {want}")
            bad = True
        if not bad:
            n_ok += 1
        if progress_cb is not None:
            progress_cb(i + 1, len(seqs))

    return DiffResult(protocol, len(seqs), n_ok, mismatches)


def _engine_census(mem_state, mp, lines, is_shl2):
    """Normalized (hashable) engine-side view for comparison."""
    from graphite_tpu.memory.state import DIR_UNCACHED

    if is_shl2:
        from graphite_tpu.memory.engine_shl2 import shl2_line_census

        cen = shl2_line_census(mem_state, mp, lines)
        out = []
        for line in lines:
            c = cen[line]
            d = c["dir"]
            if d is not None and d[0] == DIR_UNCACHED and not d[2]:
                d = None
            out.append((c["l1d"], c["slice"], d))
        return tuple(out)
    from graphite_tpu.memory.engine import line_census

    cen = line_census(mem_state, mp, lines)
    out = []
    for line in lines:
        c = cen[line]
        d = c["dir"]
        if d is not None and d[0] == DIR_UNCACHED and not d[2]:
            d = None
        out.append((c["l1d"], c["l2"], d, c["cdata"]))
    return tuple(out)


def _succ_census(succ_key, lines, n_tiles, is_shl2):
    """The comparable protocol part of an explored successor key."""
    from graphite_tpu.memory.state import DIR_UNCACHED

    out = []
    for part in succ_key:
        if is_shl2:
            l1, slice_st, dent, _fresh = part
            if dent is not None and dent[0] == DIR_UNCACHED \
                    and not dent[2]:
                dent = None
            out.append((l1, slice_st, dent))
        else:
            l1, l2, dent, cdata, _fresh = part
            if dent is not None and dent[0] == DIR_UNCACHED \
                    and not dent[2]:
                dent = None
            out.append((l1, l2, dent, cdata))
    return tuple(out)
