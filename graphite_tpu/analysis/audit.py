"""The program auditor: lower a program, run every applicable lint.

`audit()` is the API the tests and `tools/regress.py --smoke` call;
`python -m graphite_tpu.tools.audit` is the CLI wrapper that emits the
report as JSON lines.  A ProgramSpec bundles one lowered program (a
ClosedJaxpr straight from `jax.make_jaxpr` — no compile needed, so the
auditor runs anywhere, including CPU-only CI) with the context the
rules need: which invars are absolute clocks (time-dtype taint
sources), which are sweep knobs (knob-fold), which aval signatures are
the big directory stores (cond-payload), and whether the program
believes it is phase-gated (vmap-gate).

The default program set mirrors the shapes every perf round is
measured on: the per-phase-GATED private-L2 engine, the UNGATED one,
the shared-L2 engine, the B=4 vmapped sweep campaign, the
telemetry-recording gated engine (round 9 — the timeline ring must
never ride a cond, and telemetry-off programs must carry no trace of
the recording machinery), and the combined sweep-B=4 + telemetry
campaign (round 10 — the composition of the two).
"""

from __future__ import annotations

import dataclasses
import re

from graphite_tpu.analysis import rules
from graphite_tpu.analysis.walk import invar_path_strings  # noqa: F401

# Invar leaves holding ABSOLUTE simulated times (taint sources for the
# time-dtype rule).  Everything matching carries int64 picosecond
# timestamps: running clocks, mailbox/protocol message arrival times,
# sync-object release/arrival/wake times, in-flight DRAM ready times.
# Deliberately NOT matched: *_stall_ps / acc_ps / *lat_ps (durations),
# dyn_ps (per-record costs), quantum/slack scalars.
CLOCK_LEAF_RE = re.compile(
    r"(clock_ps|time_ps|_time$|release_ps|arrival_ps|wake_ps|done_ps"
    r"|ready_ps|seq_ps)")

# Generic cond-payload ceiling: comfortably above every legitimate
# per-phase payload at audited shapes (mailbox matrices, net rings) and
# far below the multi-GB directory stores the rule exists to keep out
# of conds.  The CLI's --max-cond-bytes overrides it.
DEFAULT_MAX_COND_BYTES = 64 << 20


def clock_invar_indices(paths) -> "tuple[int, ...]":
    return tuple(i for i, p in enumerate(paths)
                 if CLOCK_LEAF_RE.search(p))


@dataclasses.dataclass
class ProgramSpec:
    """One lowered program plus the context its lints need."""

    name: str
    closed: object                    # ClosedJaxpr
    invar_paths: "list[str]"
    n_tiles: int
    expect_gated: bool = False
    n_phases: int = 6
    knob_invars: "dict | None" = None   # knob name -> invar indices
    forbidden_cond_avals: tuple = ()    # ((shape, dtype), ...)
    clock_invars: tuple = ()
    # round 9: telemetry-ON programs add the ring's [S, n_series] aval
    # to the cond-payload forbidden set; telemetry-OFF programs run the
    # telemetry-off rule (no telemetry invar, no ring-aval equation —
    # scanned against the canonical dense spec's ring sig)
    expect_telemetry: bool = False
    telemetry_sig: "tuple | None" = None   # ((S, n_series), dtype)
    # additional forbidden ring avals for telemetry-OFF programs
    # (round 14: the dense-plus-energy ring, one series wider — the
    # telemetry-off scan covers the energy series too)
    telemetry_extra_sigs: "tuple" = ()
    # round 16: the spatial profiler's [S, T, m] per-tile ring, policed
    # by the same machinery — profile-ON programs forbid the ring as a
    # cond payload; profile-OFF programs run the profile-off rule over
    # the canonical dense (and dense-plus-energy) per-tile ring sigs
    expect_profile: bool = False
    profile_sig: "tuple | None" = None     # ((S, T, m), dtype)
    profile_extra_sigs: "tuple" = ()
    # round 21: the latency-histogram bucket-count ring ([H, B]
    # aggregate or [T, H, B] per-tile int64) — hist-ON programs forbid
    # the ring as a cond payload; hist-OFF programs run the hist-off
    # rule over the canonical dense (and per-tile / dense-plus-energy)
    # ring sigs
    expect_hist: bool = False
    hist_sig: "tuple | None" = None        # ((H, B) | (T, H, B), dtype)
    hist_extra_sigs: "tuple" = ()
    # round 19: the runtime DVFS manager.  dvfs-ON programs carry the
    # per-domain operating point in the carry (SimState.dvfs_rt);
    # dvfs-OFF programs run the dvfs-off rule — no dvfs_rt invar may
    # survive in the lowering (the same None-adds-no-leaves contract as
    # telemetry/profile; the always-carried legacy `.dvfs.` table does
    # NOT match the `dvfs_rt` key)
    expect_dvfs: bool = False
    # round 10: the engine's protocol-phase names in phase-cond program
    # order, so the cost model (analysis/cost.py) can attribute the
    # per-iteration kernel proxy phase-by-phase
    phase_names: "tuple[str, ...]" = ()
    # round 11: vmapped campaign programs put the WHOLE program in the
    # scatter-determinism rule's scope (solo programs only police
    # shard_map interiors)
    batched: bool = False


def _mem_forbidden_avals(sim):
    """The big directory-store signatures of `sim`'s memory engine —
    the stores the round-6 delta plans keep out of every cond.

    Empty when the whole-engine mem_gate is ON: below its size ceiling
    the gate's lax.cond deliberately carries the ENTIRE memory state —
    directory included — and pays the double-buffer (that ceiling is
    the design; see EngineParams.mem_gate).  The contract "no cond
    output carries a directory store" is the BIG-state regime's
    (mem_gate off, per-phase conds the only gating).

    Signatures shared with a NON-directory state leaf are dropped: an
    aval match cannot tell the store apart from, say, a cache meta
    array of coincidentally equal geometry that legitimately rides the
    phase conds (the shl2 embedded-dir word shares the L2 meta's
    int64[T, S2, W2] aval BY CONSTRUCTION — its sharers rows are the
    observable proxy, detached and re-applied together with it by
    `_cond_dir`).  The phase-gating test picks collision-free geometry
    for the same reason."""
    import jax

    if sim.params.mem is None or sim.params.mem_gate:
        return ()
    if sim.params.mem.protocol.startswith("pr_l1_sh_l2"):
        from graphite_tpu.memory.engine_shl2 import dir_store_avals
    else:
        from graphite_tpu.memory.engine import dir_store_avals
    sigs = dir_store_avals(sim.state.mem)
    leaves, _ = jax.tree_util.tree_flatten_with_path(sim.state)
    non_dir = set()
    for p, leaf in leaves:
        path = jax.tree_util.keystr(p)
        if ".directory." not in path and ".dir." not in path \
                and hasattr(leaf, "shape"):
            non_dir.add((tuple(leaf.shape), str(leaf.dtype)))
    return tuple(s for s in sigs if s not in non_dir)


def _telemetry_fields(sim):
    """The telemetry policing shared by both spec builders:
    (extra forbidden cond avals, expect_telemetry, telemetry_sig).

    Telemetry-ON programs forbid the attached spec's actual ring as a
    cond payload (the [S, n] store would be double-buffered per
    iteration — the round-6 pathology the masked scatter-append
    avoids).  Telemetry-OFF programs get the canonical DENSE spec's
    ring sig (default S, every available series) — the shape an
    accidentally-hard-coded internal recorder would materialize, so
    the telemetry-off aval scan stays a live check instead of only
    policing carry invars — plus (round 14) the dense-plus-energy
    ring, one series wider, so the scan covers the opt-in `energy_pj`
    series too."""
    tel = sim.telemetry_spec
    if tel is not None:
        return (tel.buffer_sig(),), True, tel.buffer_sig(), ()
    from graphite_tpu.obs.telemetry import EnergyPrices, TelemetrySpec

    dense_sig = TelemetrySpec(sample_interval_ps=1).resolve(
        sim.params).buffer_sig()
    energy_sig = TelemetrySpec(
        sample_interval_ps=1,
        energy_prices=EnergyPrices()).resolve(sim.params).buffer_sig()
    return (), False, dense_sig, (energy_sig,)


def _profile_fields(sim):
    """The spatial-profiler policing shared by both spec builders:
    (extra forbidden cond avals, expect_profile, profile_sig,
    profile_extra_sigs) — the round-16 twin of `_telemetry_fields`.
    Profile-ON programs forbid the attached spec's actual [S, T, m]
    ring as a cond payload; profile-OFF programs get the canonical
    dense per-tile ring sig (default S, every available tile series)
    plus the dense-plus-energy variant, so the profile-off aval scan
    stays a live check."""
    prof = getattr(sim, "profile_spec", None)
    if prof is not None:
        return (prof.buffer_sig(),), True, prof.buffer_sig(), ()
    from graphite_tpu.obs.profile import ProfileSpec
    from graphite_tpu.obs.telemetry import EnergyPrices

    dense_sig = ProfileSpec(sample_interval_ps=1).resolve(
        sim.params).buffer_sig()
    energy_sig = ProfileSpec(
        sample_interval_ps=1,
        energy_prices=EnergyPrices()).resolve(sim.params).buffer_sig()
    return (), False, dense_sig, (energy_sig,)


def _hist_fields(sim):
    """The latency-histogram policing shared by both spec builders:
    (extra forbidden cond avals, expect_hist, hist_sig,
    hist_extra_sigs) — the round-21 twin of `_profile_fields`.
    Hist-ON programs forbid the attached spec's actual bucket-count
    ring as a cond payload; hist-OFF programs get the canonical dense
    aggregate [H, B] ring sig plus the per-tile [T, H, B] and
    dense-plus-energy variants, so the hist-off aval scan stays a live
    check for every recording layout."""
    hs = getattr(sim, "hist_spec", None)
    if hs is not None:
        return (hs.buffer_sig(),), True, hs.buffer_sig(), ()
    from graphite_tpu.obs.hist import HistSpec
    from graphite_tpu.obs.telemetry import EnergyPrices

    dense_sig = HistSpec().resolve(sim.params).buffer_sig()
    tile_sig = HistSpec(per_tile=True).resolve(sim.params).buffer_sig()
    energy_sig = HistSpec(
        energy_prices=EnergyPrices()).resolve(sim.params).buffer_sig()
    return (), False, dense_sig, (tile_sig, energy_sig)


def spec_from_simulator(name: str, sim,
                        max_quanta: int = 4096) -> ProgramSpec:
    """Lower a Simulator's single-device resident program into a spec."""
    from graphite_tpu.engine.simulator import mem_phase_names

    closed, paths = sim.lower(max_quanta)
    expect_gated = (sim.params.mem is not None
                    and bool(sim.params.mem.phase_gate))
    phase_names = (tuple(mem_phase_names(sim.params))
                   if sim.params.mem is not None else ())
    n_phases = len(phase_names) if phase_names else 6
    tel_forbidden, expect_tel, tel_sig, tel_extra = \
        _telemetry_fields(sim)
    prof_forbidden, expect_prof, prof_sig, prof_extra = \
        _profile_fields(sim)
    hist_forbidden, expect_hist, hist_sig, hist_extra = \
        _hist_fields(sim)
    return ProgramSpec(
        name=name, closed=closed, invar_paths=paths,
        n_tiles=sim.params.n_tiles, expect_gated=expect_gated,
        n_phases=n_phases,
        forbidden_cond_avals=(_mem_forbidden_avals(sim) + tel_forbidden
                              + prof_forbidden + hist_forbidden),
        clock_invars=clock_invar_indices(paths),
        expect_telemetry=expect_tel,
        telemetry_sig=tel_sig,
        telemetry_extra_sigs=tel_extra,
        expect_profile=expect_prof,
        profile_sig=prof_sig,
        profile_extra_sigs=prof_extra,
        expect_hist=expect_hist,
        hist_sig=hist_sig,
        hist_extra_sigs=hist_extra,
        expect_dvfs=getattr(sim, "dvfs_spec", None) is not None,
        phase_names=phase_names)


def spec_from_sweep(name: str, runner,
                    max_quanta: int = 4096) -> ProgramSpec:
    """Lower a SweepRunner's batched campaign program into a spec,
    mapping each sweep knob to its traced invar indices (knob-fold)."""
    from graphite_tpu.engine.simulator import mem_phase_names
    from graphite_tpu.sweep.knobs import KNOB_FIELDS

    closed, paths = runner.lower(max_quanta)
    knob_invars = {
        f: [i for i, p in enumerate(paths) if p.endswith("." + f)]
        for f in KNOB_FIELDS
    }
    if runner.knobs.dvfs_domain_mhz is not None:
        # the domain-frequency axis is a traced knob too: its invars
        # must stay live through the carried-frequency reads (knob-fold
        # proves a config that silently ignores the grid)
        from graphite_tpu.sweep.knobs import DVFS_KNOB_FIELD

        knob_invars[DVFS_KNOB_FIELD] = [
            i for i, p in enumerate(paths)
            if p.endswith("." + DVFS_KNOB_FIELD)]
    if runner.sim.quantum_ps is None:
        # unbounded clock schemes have no quantum for the knob to steer
        knob_invars.pop("quantum_ps", None)
    sim = runner.sim
    mp = sim.params.mem
    if mp is None:
        # memoryless campaigns never read the memory knobs by design
        # (Knobs.from_params zeroes them) — requiring them would fail
        # every healthy memoryless sweep
        from graphite_tpu.sweep.knobs import MEM_KNOB_FIELDS

        for f in MEM_KNOB_FIELDS:
            knob_invars.pop(f, None)
    elif len(set(mp.module_domains)) == 1:
        # single-DVFS-domain configs short-circuit every cross-domain
        # handoff to a Python 0 (MemParams.sync_cycles), so the sync
        # knob is structurally inert — not a folding bug.  Multi-domain
        # configs keep it in the required set.
        knob_invars.pop("sync_delay_cycles", None)
    expect_gated = (sim.params.mem is not None
                    and bool(sim.params.mem.phase_gate))
    phase_names = (tuple(mem_phase_names(sim.params))
                   if sim.params.mem is not None else ())
    n_phases = len(phase_names) if phase_names else 6
    tel_forbidden, expect_tel, tel_sig, tel_extra = \
        _telemetry_fields(sim)
    prof_forbidden, expect_prof, prof_sig, prof_extra = \
        _profile_fields(sim)
    hist_forbidden, expect_hist, hist_sig, hist_extra = \
        _hist_fields(sim)
    return ProgramSpec(
        name=name, closed=closed, invar_paths=paths,
        n_tiles=sim.params.n_tiles, expect_gated=expect_gated,
        n_phases=n_phases, knob_invars=knob_invars,
        forbidden_cond_avals=(_mem_forbidden_avals(sim) + tel_forbidden
                              + prof_forbidden + hist_forbidden),
        clock_invars=clock_invar_indices(paths),
        expect_telemetry=expect_tel,
        telemetry_sig=tel_sig,
        telemetry_extra_sigs=tel_extra,
        expect_profile=expect_prof,
        profile_sig=prof_sig,
        profile_extra_sigs=prof_extra,
        expect_hist=expect_hist,
        hist_sig=hist_sig,
        hist_extra_sigs=hist_extra,
        expect_dvfs=getattr(sim, "dvfs_spec", None) is not None,
        phase_names=phase_names,
        batched=not runner.shard_batch or runner._sims_per_dev > 1)


# ---------------------------------------------------------------------------
# default program set
# ---------------------------------------------------------------------------


DEFAULT_PROGRAM_NAMES = ("gated-msi", "ungated-msi", "shl2-mesi",
                         "sweep-b4", "gated-msi-tel", "sweep-b4-tel",
                         "sweep-b4-2d", "sweep-b4-dvfs",
                         "gated-msi-hist", "gated-msi-2d")

# cache/directory geometry chosen so the directory entry/sharers avals
# are UNIQUE in the program (same trick as the phase-gating test) — a
# cache meta array of coincidentally equal shape would make the
# cond-payload signature check blind to the store
AUDIT_GEOMETRY = """
[l1_icache/T1]
cache_size = 4
associativity = 2
[l1_dcache/T1]
cache_size = 8
associativity = 4
[l2_cache/T1]
cache_size = 32
associativity = 8
[dram_directory]
total_entries = 64
associativity = 4
"""


def _audit_trace(tiles: int):
    from graphite_tpu.trace import synthetic

    return synthetic.memory_stress_trace(
        tiles, n_accesses=16, working_set_bytes=1 << 12,
        write_fraction=0.4, shared_fraction=0.5, seed=7)


def gated_msi_simulator(tiles: int = 8, extra_cfg: str = ""):
    """The audited gated-MSI Simulator, optionally with `extra_cfg` INI
    appended — the hook registry.lock_regression_fixture uses to lower
    the SAME program shape with one intentionally perturbed literal."""
    from graphite_tpu.config import ConfigFile, SimConfig
    from graphite_tpu.engine.simulator import Simulator
    from graphite_tpu.tools._template import config_text

    sc = SimConfig(ConfigFile.from_string(config_text(
        tiles, shared_mem=True, clock_scheme="lax_barrier")
        + AUDIT_GEOMETRY + extra_cfg))
    return Simulator(sc, _audit_trace(tiles), phase_gate=True,
                     mem_gate_bytes=0)


def default_programs(tiles: int = 8, max_quanta: int = 4096,
                     names=None) -> "list[ProgramSpec]":
    """The ten audited shapes: gated, ungated, shl2, sweep B=4, the
    telemetry-recording gated engine (round 9: the ring's aval joins
    the cond-payload forbidden set; telemetry-OFF programs additionally
    run the telemetry-off lint), the COMBINED sweep-B=4 + telemetry
    campaign (round 10: campaign timelines were previously only audited
    solo, so the [B, S, n_series] ring under vmap never met the
    cond-payload or knob-fold lints — the composition is audited now),
    and the 2D batch x tile sweep campaign (round 18: the same B=4
    sweep on a 2x2 Mesh(('batch','tile')) with the packed tile-axis
    exchange, lowered over a device-less AbstractMesh), and the
    runtime-DVFS sweep campaign (round 19: a genuinely two-domain
    config sweeping a dvfs_domain_mhz grid — the carried-frequency
    program where both the sync-delay knob and the frequency grid must
    prove live), plus the latency-histogram gated engine (round 21: the
    dense bucket-count ring joins the cond-payload forbidden set and
    the commit-site scatters meet every structural lint), and the
    per-phase-GATED 2D campaign (round 22: one sim per batch cell so
    the real phase conds survive next to the packed tile-axis exchange
    — the shape the comms analyzer attributes phase-by-phase).

    Small geometry on purpose — the lints are structural, so the
    8-tile lowering carries the same program shape the 1024-tile
    config-5 run compiles (the phase-gating test separately pins the
    1024-tile shape).  `names` restricts to a subset of
    DEFAULT_PROGRAM_NAMES (each lowering costs a few seconds of
    tracing)."""
    from graphite_tpu.config import ConfigFile, SimConfig
    from graphite_tpu.engine.simulator import Simulator
    from graphite_tpu.sweep import SweepRunner
    from graphite_tpu.tools._template import config_text
    from graphite_tpu.trace import synthetic

    if names is None:
        names = DEFAULT_PROGRAM_NAMES
    unknown = set(names) - set(DEFAULT_PROGRAM_NAMES)
    if unknown:
        raise ValueError(
            f"unknown program(s) {sorted(unknown)} "
            f"(available: {', '.join(DEFAULT_PROGRAM_NAMES)})")

    batch = _audit_trace(tiles)
    geometry = AUDIT_GEOMETRY
    sc = SimConfig(ConfigFile.from_string(config_text(
        tiles, shared_mem=True, clock_scheme="lax_barrier") + geometry))
    sc_shl2 = SimConfig(ConfigFile.from_string(config_text(
        tiles, shared_mem=True, protocol="pr_l1_sh_l2_mesi",
        clock_scheme="lax_barrier")))
    # mem_gate_bytes=0: phase conds are the ONLY gating — the config-5
    # big-state regime the round-6 contract exists for
    specs = []
    if "gated-msi" in names:
        specs.append(spec_from_simulator(
            "gated-msi", gated_msi_simulator(tiles), max_quanta))
    if "ungated-msi" in names:
        specs.append(spec_from_simulator("ungated-msi", Simulator(
            sc, batch, phase_gate=False, mem_gate_bytes=0), max_quanta))
    if "shl2-mesi" in names:
        specs.append(spec_from_simulator("shl2-mesi", Simulator(
            sc_shl2, batch, phase_gate=True, mem_gate_bytes=0),
            max_quanta))
    if "sweep-b4" in names or "sweep-b4-tel" in names \
            or "sweep-b4-2d" in names or "sweep-b4-dvfs" in names \
            or "gated-msi-2d" in names:
        # the sweep config splits the modules over TWO DVFS domains so
        # the sync_delay knob actually crosses a boundary — in a
        # single-domain config it is structurally inert (MemParams.
        # sync_cycles returns a Python 0) and spec_from_sweep would
        # drop it from the required set
        sc_sweep = SimConfig(ConfigFile.from_string(
            config_text(tiles, shared_mem=True,
                        clock_scheme="lax_barrier")
            + geometry + """
[dvfs]
technology_node = 22
max_frequency = 1.0
synchronization_delay = 2
[dvfs/domains]
domains = "<1.0, CORE, L1_ICACHE, L1_DCACHE, L2_CACHE>, \
<1.0, DIRECTORY, NETWORK_USER, NETWORK_MEMORY>"
"""))
        sweep_traces = [
            synthetic.memory_stress_trace(
                tiles, n_accesses=16, working_set_bytes=1 << 12,
                write_fraction=0.4, shared_fraction=0.5, seed=s)
            for s in (1, 2, 3, 4)
        ]
    if "sweep-b4" in names:
        runner = SweepRunner(sc_sweep, sweep_traces, shard_batch=False)
        specs.append(spec_from_sweep("sweep-b4", runner, max_quanta))
    if "gated-msi-tel" in names:
        from graphite_tpu.obs import TelemetrySpec

        specs.append(spec_from_simulator("gated-msi-tel", Simulator(
            sc, batch, phase_gate=True, mem_gate_bytes=0,
            telemetry=TelemetrySpec(sample_interval_ps=1_000_000,
                                    n_samples=32)), max_quanta))
    if "sweep-b4-tel" in names:
        from graphite_tpu.obs import TelemetrySpec

        # the combined campaign-timelines program: the [B, S, n_series]
        # ring must stay off every cond AND every knob must stay live
        # with the recording machinery in the loop body
        runner_tel = SweepRunner(
            sc_sweep, sweep_traces, shard_batch=False,
            telemetry=TelemetrySpec(sample_interval_ps=1_000_000,
                                    n_samples=32))
        specs.append(spec_from_sweep("sweep-b4-tel", runner_tel,
                                     max_quanta))
    if "sweep-b4-2d" in names:
        # the round-18 2D batch x tile campaign: the SAME B=4 sweep on
        # a 2x2 Mesh(('batch','tile')) — each device one tile block of
        # two sims, the packed per-phase exchange over the tile axis.
        # Lowered via a device-less AbstractMesh (SweepRunner.lower),
        # so the lints/cost/lock cover the composition on 1-device CI.
        runner_2d = SweepRunner(sc_sweep, sweep_traces, layout=(2, 2))
        specs.append(spec_from_sweep("sweep-b4-2d", runner_2d,
                                     max_quanta))
    if "gated-msi-2d" in names:
        # round 22: the per-phase-GATED 2D campaign — layout (4, 2)
        # puts ONE sim per batch cell, so the real lax.cond phase gates
        # survive (the vmapped layouts above trade them for masked
        # always-run phases) alongside the packed tile-axis exchange.
        # This is the registered shape the comms analyzer attributes
        # collective-by-collective to protocol phases: each phase's
        # px gather sits immediately before (or inside) its cond.
        runner_g2d = SweepRunner(sc_sweep, sweep_traces, layout=(4, 2),
                                 phase_gate=True, mem_gate_bytes=0)
        specs.append(spec_from_sweep("gated-msi-2d", runner_g2d,
                                     max_quanta))
    if "gated-msi-hist" in names:
        # the round-21 latency-histogram program: the dense bucket-count
        # ring in the carry — its [H, B] aval joins the cond-payload
        # forbidden set, and the commit-site scatters must stay
        # deterministic / host-sync-free like every other ring
        from graphite_tpu.obs import HistSpec

        specs.append(spec_from_simulator("gated-msi-hist", Simulator(
            sc, batch, phase_gate=True, mem_gate_bytes=0,
            hist=HistSpec()), max_quanta))
    if "sweep-b4-dvfs" in names:
        # the round-19 runtime-DVFS campaign: the SAME B=4 sweep with a
        # GENUINELY multi-domain [dvfs] table (note `domains =` under
        # [dvfs] itself — the sc_sweep block above nests it under
        # [dvfs/domains], where the parser files it as the unread key
        # `dvfs/domains/domains` and the config silently stays
        # single-domain, which is why sync_delay_cycles was popped from
        # its required knob set for ten rounds).  Here the two-domain
        # split is real, so knob-fold proves sync_delay_cycles AND the
        # dvfs_domain_mhz grid live through the carried-frequency reads.
        from graphite_tpu.dvfs import DvfsSpec

        sc_dvfs = SimConfig(ConfigFile.from_string(
            config_text(tiles, shared_mem=True,
                        clock_scheme="lax_barrier")
            + geometry + """
[general]
technology_node = 22
[dvfs]
max_frequency = 1.0
synchronization_delay = 2
domains = "<1.0, CORE, L1_ICACHE, L1_DCACHE, L2_CACHE>, \
<1.0, DIRECTORY, NETWORK_USER, NETWORK_MEMORY>"
"""))
        dvfs_points = [{"dvfs_domain_mhz": p} for p in
                       ((1000, 1000), (870, 1000), (750, 870),
                        (500, 630))]
        runner_dvfs = SweepRunner(sc_dvfs, sweep_traces, dvfs_points,
                                  shard_batch=False, dvfs=DvfsSpec())
        specs.append(spec_from_sweep("sweep-b4-dvfs", runner_dvfs,
                                     max_quanta))
    return specs


# ---------------------------------------------------------------------------
# audit driver
# ---------------------------------------------------------------------------

RULE_NAMES = ("cond-payload", "knob-fold", "time-dtype", "vmap-gate",
              "host-sync", "scatter-determinism", "write-race",
              "telemetry-off", "profile-off", "hist-off", "dvfs-off",
              "gspmd-insertion", "replication-drift")


@dataclasses.dataclass
class RuleResult:
    program: str
    rule: str
    findings: "list[rules.Finding]"

    @property
    def ok(self) -> bool:
        return not any(f.severity == rules.SEV_ERROR
                       for f in self.findings)

    def to_json(self) -> dict:
        return {"program": self.program, "rule": self.rule,
                "status": "pass" if not self.findings
                else ("fail" if not self.ok else "warn"),
                "findings": [f.to_json() for f in self.findings]}


@dataclasses.dataclass
class AuditReport:
    results: "list[RuleResult]"

    @property
    def findings(self) -> "list[rules.Finding]":
        return [f for r in self.results for f in r.findings]

    @property
    def errors(self) -> "list[rules.Finding]":
        return [f for f in self.findings
                if f.severity == rules.SEV_ERROR]

    @property
    def ok(self) -> bool:
        return not self.errors

    def programs(self) -> "list[str]":
        seen = []
        for r in self.results:
            if r.program not in seen:
                seen.append(r.program)
        return seen

    def summary_rows(self) -> "list[dict]":
        rows = []
        for prog in self.programs():
            rs = [r for r in self.results if r.program == prog]
            n_err = sum(1 for r in rs for f in r.findings
                        if f.severity == rules.SEV_ERROR)
            n_warn = sum(1 for r in rs for f in r.findings
                         if f.severity == rules.SEV_WARNING)
            rows.append({"program": prog, "summary": True,
                         "rules_run": len(rs), "errors": n_err,
                         "warnings": n_warn, "ok": n_err == 0})
        return rows


def audit_program(spec: ProgramSpec, *,
                  max_cond_bytes: "int | None" = DEFAULT_MAX_COND_BYTES,
                  ) -> "list[RuleResult]":
    """Run every applicable rule on one lowered program."""
    results = []

    def add(rule, findings):
        for f in findings:
            f.program = spec.name
        results.append(RuleResult(spec.name, rule, findings))

    add("cond-payload", rules.cond_payload(
        spec.closed, max_bytes=max_cond_bytes,
        forbidden=spec.forbidden_cond_avals))
    if spec.knob_invars is not None:
        add("knob-fold", rules.knob_fold(
            spec.closed, spec.knob_invars, spec.invar_paths))
    add("time-dtype", rules.time_dtype(
        spec.closed, spec.clock_invars, spec.invar_paths))
    add("vmap-gate", rules.vmap_gate(
        spec.closed, spec.n_tiles, spec.expect_gated,
        n_phases=spec.n_phases))
    add("host-sync", rules.host_sync(spec.closed))
    add("scatter-determinism", rules.scatter_determinism(
        spec.closed, batched=spec.batched))
    # the standing gate for the [T, k] mailbox compaction: no rewrite
    # may turn a req-lane or mailbox-matrix scatter into an
    # ordered-multi-writer one (analysis/protocol.py's model checker
    # supplies the reachable fan-in bounds; the gate itself is static)
    add("write-race", rules.write_race(spec.closed, spec.n_tiles))
    from graphite_tpu.analysis import comms
    if comms.has_mesh_region(spec.closed):
        # round 22: mesh programs additionally run the collective
        # lints — every collective must match the px packed-exchange
        # whitelist (the mesh.py GSPMD-cliff regression gate), and
        # every output declared replicated across the tile axis must
        # be provably uniform
        add("gspmd-insertion", rules.gspmd_insertion(
            spec.closed, spec.n_tiles, phase_names=spec.phase_names))
        add("replication-drift", rules.replication_drift(spec.closed))
    if not spec.expect_telemetry:
        # telemetry-OFF programs must carry no trace of the timeline
        # machinery (ON programs instead police the ring via the
        # cond-payload forbidden set, added by spec_from_*)
        add("telemetry-off", rules.telemetry_off(
            spec.closed, spec.invar_paths,
            ring_sigs=(((spec.telemetry_sig,)
                        if spec.telemetry_sig is not None else ())
                       + tuple(spec.telemetry_extra_sigs))))
    if not spec.expect_profile:
        # profile-OFF programs must carry no trace of the spatial
        # profiler — same rule, profile state key + [S, T, m] ring sigs
        add("profile-off", rules.telemetry_off(
            spec.closed, spec.invar_paths,
            ring_sigs=(((spec.profile_sig,)
                        if spec.profile_sig is not None else ())
                       + tuple(spec.profile_extra_sigs)),
            state_key="profile", rule="profile-off"))
    if not spec.expect_hist:
        # hist-OFF programs must carry no trace of the latency
        # histograms — same rule, hist state key + bucket-ring sigs
        add("hist-off", rules.telemetry_off(
            spec.closed, spec.invar_paths,
            ring_sigs=(((spec.hist_sig,)
                        if spec.hist_sig is not None else ())
                       + tuple(spec.hist_extra_sigs)),
            state_key="hist", rule="hist-off"))
    if not spec.expect_dvfs:
        # dvfs=None programs must carry no runtime-DVFS manager state:
        # no `dvfs_rt` invar may survive (the carried operating point
        # would change the lowering).  No ring sigs — the manager has
        # no ring; its state is a handful of [n_domains] vectors whose
        # avals are too generic to scan for.
        add("dvfs-off", rules.telemetry_off(
            spec.closed, spec.invar_paths, ring_sigs=(),
            state_key="dvfs_rt", rule="dvfs-off"))
    return results


def audit(specs: "list[ProgramSpec] | None" = None, *,
          tiles: int = 8,
          max_cond_bytes: "int | None" = DEFAULT_MAX_COND_BYTES,
          max_quanta: int = 4096) -> AuditReport:
    """Audit `specs` (default: the five default-config programs).

    Pure static analysis over `jax.make_jaxpr` output — no compile, no
    execution, runs on CPU.  `report.ok` is False iff any error-severity
    finding fired (warnings — e.g. vmap-gate — do not fail the audit)."""
    if specs is None:
        specs = default_programs(tiles, max_quanta)
    results = []
    for spec in specs:
        results.extend(audit_program(spec, max_cond_bytes=max_cond_bytes))
    return AuditReport(results)
