"""Non-negative integer division/modulo for device code.

`jnp`'s `//` and `%` implement Python floor semantics on signed ints,
which XLA receives as a ~9-equation sign-fixup chain (div/rem + two
signs + compares + select) per call site.  The memory engines compute
set indices, home mappings, bit positions, and ceil-division time
conversions hundreds of times per subquantum iteration, always on
values that are non-negative by construction (line numbers, tile ids,
cycle counts, picosecond durations) — where truncating and flooring
division agree exactly.  These helpers emit the single `lax.div` /
`lax.rem` equation instead; results are bit-identical to the floor
forms for non-negative operands (the golden interpreters and the
regress base-consolidation rung pin this on randomized traces).

CONTRACT: both operands must be provably >= 0 (divisor > 0).  Sites
where a value can be negative — e.g. victim lines read off an invalid
cache way (tag -1) — must keep the floor operators; see the round-12
notes in PERF.md.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _traced(*xs) -> bool:
    return any(isinstance(x, jax.Array) for x in xs)


def _pair(a, b):
    a = jnp.asarray(a)
    b = jnp.asarray(b, a.dtype) if not hasattr(b, "dtype") \
        else b.astype(a.dtype) if b.dtype != a.dtype else b
    shape = jnp.broadcast_shapes(jnp.shape(a), jnp.shape(b))
    return jnp.broadcast_to(a, shape), jnp.broadcast_to(b, shape)


def nn_mod(a, b):
    """`a % b` for non-negative `a`, positive `b` — one lax.rem.

    Python ints and numpy arrays stay host-side (truncating and floor
    modulo agree on non-negative operands), so constant operands fold to
    constants instead of equations."""
    if not _traced(a, b):
        return a % b
    a, b = _pair(a, b)
    return lax.rem(a, b)


def nn_div(a, b):
    """`a // b` for non-negative `a`, positive `b` — one lax.div."""
    if not _traced(a, b):
        return a // b
    a, b = _pair(a, b)
    return lax.div(a, b)


def nn_divmod(a, b):
    """(a // b, a % b) for non-negative operands."""
    return nn_div(a, b), nn_mod(a, b)


def nn_ceil_div(a, b):
    """ceil(a / b) for non-negative `a`, positive `b`."""
    x = a + b - 1
    if isinstance(x, jax.Array):
        return nn_div(x, b)
    return x // b
