"""Device-resident telemetry timelines: in-sim sampled metrics, no host sync.

Graphite's statistics thread wakes at every barrier quantum that crosses
the sampling interval and appends time-series records to trace files
(`statistics_thread.h:8-28`, knobs `carbon_sim.cfg:394-411`).  The port's
chunked equivalent (`system/statistics.py`) chops the one-compiled-region
simulation into host-driven chunks — one host<->device round trip (~100 ms
tunneled) PER SAMPLE, the dispatch tail rounds 6 and 7 fought to remove.

This module records the timeline ON DEVICE instead: a preallocated ring
buffer `int64[S, n_series]` rides the simulation carry
(`engine/state.SimState.telemetry`), and the outer quantum loop
(`engine/step.run_simulation` and the `barrier_host_batch` dispatch path)
appends one row whenever simulated time crosses the next
`sample_interval_ps` boundary — the same barrier-quantum sampling points
the reference uses.  No callbacks, no infeed: the program still passes the
host-sync audit lint, and the host reads the whole timeline back in the
one post-run fetch it already pays.

Series are drawn from state already in the carry (cheap scalar
reductions): per-phase gate-skip deltas, memory-counter deltas (misses,
invalidations, evictions), USER-net packet injection, per-tile clock
spread (min/max/mean), zero-progress stall quanta, and iteration/quantum
counts.  `telemetry=None` (the default everywhere) constant-folds the
recording away to a bit-identical program — the same contract as the
round-7 `knobs=None`, jaxpr-asserted in tests and enforced by the
`telemetry-off` audit lint.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

I64 = jnp.int64
_BIG = 2**62

# Series that record the sampled LEVEL; everything else records the
# since-last-sample DELTA of a monotone cumulative counter (the delta is
# computed on device against the `prev` snapshot in TelemetryState, so
# ring wraparound never corrupts differencing).
LEVEL_SERIES = ("time_ps", "clock_min_ps", "clock_max_ps", "clock_mean_ps")

# Always-available series (state the core carry already holds).
CORE_SERIES = (
    "time_ps",        # laggard non-done clock (max clock once all done)
    "quanta",         # outer-loop quanta since last sample
    "iterations",     # subquantum engine iterations since last sample
    "stall_quanta",   # zero-progress quanta (boundary jumps / barrier stalls)
    "instructions",   # committed instructions (all tiles)
    "packets_sent",   # USER-net packet injection (all tiles)
    "sync_stall_ps",  # barrier/mutex/cond stall time (all tiles)
    "clock_min_ps",
    "clock_max_ps",
    "clock_mean_ps",
)

# Memory-engine counter series (require EngineParams.mem); the per-phase
# gate-skip series ride alongside, named skip_<phase> off the engines'
# own `mem_phase_names` (one source of truth — no parallel name list).
MEM_SERIES = ("l2_misses", "invalidations", "evictions")

# Energy series (round 14): cumulative picojoules priced from the event
# counters already in the carry.  Opt-in via TelemetrySpec.energy_prices
# — never part of the default dense selection, so every pre-round-14
# program (and its locked fingerprint/budget) is untouched.
ENERGY_SERIES = ("energy_pj",)

SKIP_PREFIX = "skip_"


@dataclasses.dataclass(frozen=True)
class EnergyPrices:
    """Per-event energy prices in integer picojoules — the static
    constants the `energy_pj` series folds into the compiled step.

    Each field prices one counter class the simulation carry already
    holds (MemCounters + instruction/packet counts), so the cumulative
    energy is a handful of multiply-adds over scalar reductions — a
    masked add-a-delta ring row like every other series, never a cond
    payload.  Integer pJ keeps the series int64-exact (hand-steppable
    oracle, bit-stable across platforms); sub-pJ events round at price
    construction, not per sample.

    `from_power_model` derives the prices from the McPAT/DSENT native
    energy library (`power/interface.py`) at a given technology node —
    the same per-event model `TileEnergyMonitor` charges post-run, now
    feeding a live device timeline.  Explicit field values keep tests
    (and air-gapped runs) independent of the native build.
    """

    instruction_pj: int = 0   # core front-end+bypass per committed instr
    l1i_access_pj: int = 0    # per L1-I lookup (hits + misses)
    l1d_access_pj: int = 0    # per L1-D access (read/write, hit/miss)
    l2_access_pj: int = 0     # per L2 lookup (hits + misses)
    l2_miss_pj: int = 0       # additional per L2 miss (tag + refill)
    invalidation_pj: int = 0  # per INV_REQ served with a valid line
    eviction_pj: int = 0      # per L2 eviction writeback
    dram_access_pj: int = 0   # per DRAM line read/write
    packet_pj: int = 0        # per USER-net packet injected (router+link)

    # fields that price MemCounters events — a memoryless program cannot
    # record them, so resolve() rejects nonzero mem prices there
    MEM_FIELDS = ("l1i_access_pj", "l1d_access_pj", "l2_access_pj",
                  "l2_miss_pj", "invalidation_pj", "eviction_pj",
                  "dram_access_pj")

    def __post_init__(self):
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if int(v) != v or int(v) < 0:
                raise ValueError(
                    f"EnergyPrices.{f.name} must be a non-negative "
                    f"integer picojoule price, got {v!r}")
            object.__setattr__(self, f.name, int(v))

    def needs_mem(self) -> bool:
        return any(getattr(self, f) for f in self.MEM_FIELDS)

    @classmethod
    def from_power_model(cls, node_nm: int = 45, *, voltage: float = 1.0,
                         line_bytes: int = 64,
                         l1_bytes: int = 32 * 1024, l1_assoc: int = 4,
                         l2_bytes: int = 512 * 1024, l2_assoc: int = 8
                         ) -> "EnergyPrices":
        """Price the events through the native McPAT/DSENT model
        (builds `native/libgraphite_energy.so` on first use)."""
        from graphite_tpu.power.interface import (
            DSENTInterface, McPATCacheInterface, McPATCoreInterface,
            load_native,
        )

        def pj(joules: float) -> int:
            return int(round(joules * 1e12))

        core = McPATCoreInterface(node_nm)
        l1 = McPATCacheInterface(node_nm, l1_bytes, l1_assoc, line_bytes)
        l2 = McPATCacheInterface(node_nm, l2_bytes, l2_assoc, line_bytes)
        noc = DSENTInterface(node_nm)
        l1o = l1.at_voltage(voltage)
        l2o = l2.at_voltage(voltage)
        return cls(
            instruction_pj=pj(core.dynamic_energy_j(
                voltage, instructions=1)),
            l1i_access_pj=pj(l1o.read_energy_j),
            l1d_access_pj=pj((l1o.read_energy_j + l1o.write_energy_j) / 2),
            l2_access_pj=pj(l2o.read_energy_j),
            l2_miss_pj=pj(l2o.tag_energy_j + l2o.write_energy_j),
            invalidation_pj=pj(l2o.tag_energy_j),
            eviction_pj=pj(l2o.write_energy_j),
            dram_access_pj=pj(load_native().dram_access_energy_j(
                node_nm, line_bytes)),
            packet_pj=pj(noc.router_dynamic_energy_j(voltage, 1)
                         + noc.link_dynamic_energy_j(voltage, 1)),
        )


def tile_energy_pj(ep: EnergyPrices, state, dvfs=None) -> jax.Array:
    """Cumulative per-tile event energy int64[T] — THE definition of
    the energy ladder, shared by the scalar `energy_pj` series (which
    reduces it with jnp.sum) and the round-16 per-tile profile series
    (which records it as-is), so the per-tile column sums over T to
    the scalar column exactly and a new price term cannot land in one
    ring but not the other.  Integer pJ prices fold as literals into a
    few multiply-adds; zero-priced terms add no ops at all.

    With `dvfs` (a `models.dvfs.DvfsParams`) and a runtime DVFS carry
    attached (`SimState.dvfs_rt`), each term is scaled by its module's
    domain V²·f factor (Q16 integer, level 0 = the prices' reference
    point): events-to-date priced at the domain's CURRENT operating
    point — exact whenever the domain's frequency is constant over the
    measurement window (the campaign case), an at-current-point
    approximation across in-window transitions.  `dvfs=None` (the
    default) traces the identical jaxpr as before round 19."""
    core = state.core
    T = core.clock_ps.shape[0]
    if dvfs is not None and getattr(state, "dvfs_rt", None) is not None:
        from graphite_tpu.dvfs.levels import energy_scale_q16

        rt = state.dvfs_rt
        sc = energy_scale_q16(dvfs, rt.domain_mhz, rt.domain_mv)
        dom = dvfs.module_domains

        def _at_point(val, module):
            return (val * sc[dom[module]]) >> 16
    else:
        def _at_point(val, module):
            return val
    # term -> models.dvfs.DVFS_MODULES index (CORE, L1_ICACHE, L1_DCACHE,
    # L2_CACHE, DIRECTORY, NETWORK_USER, NETWORK_MEMORY)
    e = jnp.zeros((T,), I64)
    if ep.instruction_pj:
        e = e + _at_point(core.instruction_count * ep.instruction_pj, 0)
    if ep.packet_pj:
        e = e + _at_point(state.net.packets_sent * ep.packet_pj, 5)
    if state.mem is not None:
        mc = state.mem.counters
        terms = (
            (ep.l1i_access_pj, 1, (mc.l1i_hits, mc.l1i_misses)),
            (ep.l1d_access_pj, 2, (mc.l1d_read_hits, mc.l1d_read_misses,
                                   mc.l1d_write_hits, mc.l1d_write_misses)),
            (ep.l2_access_pj, 3, (mc.l2_hits, mc.l2_misses)),
            (ep.l2_miss_pj, 3, (mc.l2_misses,)),
            (ep.invalidation_pj, 4, (mc.invalidations,)),
            (ep.eviction_pj, 3, (mc.evictions,)),
            (ep.dram_access_pj, 6, (mc.dram_reads, mc.dram_writes)),
        )
        for price, module, arrs in terms:
            if price:
                n = arrs[0]
                for a in arrs[1:]:
                    n = n + a
                e = e + _at_point(n * price, module)
    elif ep.needs_mem():
        raise ValueError(
            "energy_prices price memory events but this program has no "
            "memory subsystem")
    return e


def available_series(params) -> "tuple[str, ...]":
    """Every series the given EngineParams can record."""
    out = CORE_SERIES
    if params.mem is not None:
        from graphite_tpu.engine.simulator import mem_phase_names

        out = out + MEM_SERIES + tuple(
            SKIP_PREFIX + n for n in mem_phase_names(params))
    return out


@dataclasses.dataclass(frozen=True)
class TelemetrySpec:
    """What to record: sampling interval, ring depth S, series selection.

    `series=None` selects every series the engine parameters support
    (the dense spec).  `resolve(params)` validates the selection against
    the program and returns a spec with a concrete ordered tuple —
    `time_ps` always first (the demux key) — which is what the engine
    and the demux consume.

    `energy_prices` (an `EnergyPrices`) makes the `energy_pj` series
    available: cumulative event energy priced from the carry's own
    counters.  It is opt-in — with `energy_prices=None` (the default)
    `energy_pj` is neither offered nor selected, so the dense spec (and
    every locked pre-round-14 program) is unchanged.
    """

    sample_interval_ps: int
    n_samples: int = 256
    series: "tuple[str, ...] | None" = None
    # filled by resolve(): the engine's protocol phase names in skip-
    # vector order (`mem_phase_names` — the one source of truth), so a
    # SUBSET of skip_* series still indexes the right phase_skips slot
    phase_names: "tuple[str, ...]" = ()
    # per-event pJ prices enabling the energy_pj series (round 14)
    energy_prices: "EnergyPrices | None" = None

    def __post_init__(self):
        if int(self.sample_interval_ps) <= 0:
            raise ValueError("sample_interval_ps must be positive")
        if int(self.n_samples) <= 0:
            raise ValueError("n_samples must be positive")
        if self.series is not None:
            object.__setattr__(self, "series", tuple(self.series))

    @property
    def resolved(self) -> bool:
        return self.series is not None

    def resolve(self, params) -> "TelemetrySpec":
        avail = available_series(params)
        if self.energy_prices is not None:
            if params.mem is None and self.energy_prices.needs_mem():
                raise ValueError(
                    "energy_prices set nonzero memory-event prices but "
                    "this program has no memory subsystem (only "
                    "instruction_pj/packet_pj apply to memoryless "
                    "traces)")
            avail = avail + ENERGY_SERIES
        elif self.series is not None \
                and any(s in ENERGY_SERIES for s in self.series):
            raise ValueError(
                "the energy_pj series needs TelemetrySpec.energy_prices "
                "(an obs.EnergyPrices — explicit pJ fields or "
                "EnergyPrices.from_power_model)")
        if self.series is None:
            sel = avail
        else:
            unknown = [s for s in self.series if s not in avail]
            if unknown:
                raise ValueError(
                    f"unknown/unavailable telemetry series {unknown} "
                    f"(this program offers: {', '.join(avail)})")
            # time_ps leads (demux/report key); preserve the caller's
            # order otherwise, deduplicated
            seen = []
            for s in ("time_ps",) + tuple(self.series):
                if s not in seen:
                    seen.append(s)
            sel = tuple(seen)
        phase_names = ()
        if params.mem is not None:
            from graphite_tpu.engine.simulator import mem_phase_names

            phase_names = tuple(mem_phase_names(params))
        return dataclasses.replace(self, series=sel,
                                   phase_names=phase_names)

    @property
    def n_series(self) -> int:
        if self.series is None:
            raise ValueError("spec is unresolved (call resolve(params))")
        return len(self.series)

    def buffer_sig(self) -> "tuple[tuple, str]":
        """The ring buffer's aval signature ((S, n_series), dtype) — what
        the audit lints match (cond-payload forbidden set when telemetry
        is ON; the telemetry-off rule when it must be absent)."""
        return ((int(self.n_samples), self.n_series), "int64")

    def ring_bytes(self) -> int:
        """Per-sim device residency of this spec's TelemetryState: the
        [S, n_series] ring + the prev snapshot + the five scalar
        cursors, all int64.  The ONE size model the residency budget
        consumes (analysis/cost.residency_breakdown) — a campaign pays
        B x this, which is why `attach_telemetry` refuses layouts that
        cannot afford the ring."""
        (S, n), dtype = self.buffer_sig()
        item = np.dtype(dtype).itemsize
        return S * n * item + n * item + 5 * item

    def delta_mask(self) -> np.ndarray:
        """bool[n_series]: True where the series records a delta."""
        return np.array([s not in LEVEL_SERIES for s in self.series],
                        dtype=bool)


@struct.dataclass
class TelemetryState:
    """The device-resident recording state (rides SimState.telemetry).

    `buf` is the [S, n_series] ring; `count` the total samples taken
    (including overwritten ones — `count % S` is the next write slot);
    `prev` the cumulative snapshot at the last sample (delta baseline);
    `next_ps` the next simulated-time sample boundary.  `quanta`,
    `iters`, `stall_quanta` are cumulative loop counters the outer loop
    feeds the tick (they are series sources, not engine state)."""

    buf: jax.Array          # int64[S, n_series]
    prev: jax.Array         # int64[n_series]
    count: jax.Array        # int32[]
    next_ps: jax.Array      # int64[]
    quanta: jax.Array       # int64[]
    iters: jax.Array        # int64[]
    stall_quanta: jax.Array  # int64[]


def init_telemetry(spec: TelemetrySpec) -> TelemetryState:
    if not spec.resolved:
        raise ValueError("init_telemetry needs a resolved TelemetrySpec")
    n = spec.n_series
    return TelemetryState(
        buf=jnp.zeros((int(spec.n_samples), n), I64),
        prev=jnp.zeros((n,), I64),
        count=jnp.zeros((), jnp.int32),
        next_ps=jnp.asarray(int(spec.sample_interval_ps), I64),
        quanta=jnp.zeros((), I64),
        iters=jnp.zeros((), I64),
        stall_quanta=jnp.zeros((), I64),
    )


def _series_values(spec: TelemetrySpec, state, ts: TelemetryState,
                   sim_time: jax.Array, dvfs=None) -> jax.Array:
    """The CUMULATIVE value of every selected series, int64[n_series].
    Delta series are differenced against `ts.prev` by the tick."""
    core = state.core
    clocks = core.clock_ps
    T = clocks.shape[0]
    vals = {}
    sel = set(spec.series)
    if "time_ps" in sel:
        vals["time_ps"] = sim_time
    if "quanta" in sel:
        vals["quanta"] = ts.quanta
    if "iterations" in sel:
        vals["iterations"] = ts.iters
    if "stall_quanta" in sel:
        vals["stall_quanta"] = ts.stall_quanta
    if "instructions" in sel:
        vals["instructions"] = jnp.sum(core.instruction_count)
    if "packets_sent" in sel:
        vals["packets_sent"] = jnp.sum(state.net.packets_sent)
    if "sync_stall_ps" in sel:
        vals["sync_stall_ps"] = jnp.sum(core.sync_stall_ps)
    if "clock_min_ps" in sel:
        vals["clock_min_ps"] = jnp.min(clocks)
    if "clock_max_ps" in sel:
        vals["clock_max_ps"] = jnp.max(clocks)
    if "clock_mean_ps" in sel:
        vals["clock_mean_ps"] = jnp.sum(clocks) // T
    if state.mem is not None:
        mc = state.mem.counters
        if "l2_misses" in sel:
            vals["l2_misses"] = jnp.sum(mc.l2_misses)
        if "invalidations" in sel:
            vals["invalidations"] = jnp.sum(mc.invalidations)
        if "evictions" in sel:
            vals["evictions"] = jnp.sum(mc.evictions)
    if "energy_pj" in sel:
        ep = spec.energy_prices
        if ep is None:
            raise ValueError("energy_pj selected without energy_prices")
        vals["energy_pj"] = jnp.sum(tile_energy_pj(ep, state, dvfs))
    skip_names = [s for s in spec.series if s.startswith(SKIP_PREFIX)]
    if skip_names:
        if state.mem is None:
            raise ValueError("skip_* series need the memory subsystem")
        # spec.phase_names carries the engine's `mem_phase_names` order,
        # so even a SUBSET of skip_* series indexes its true slot
        for s in skip_names:
            idx = spec.phase_names.index(s[len(SKIP_PREFIX):])
            vals[s] = state.mem.phase_skips[idx]
    missing = [s for s in spec.series if s not in vals]
    if missing:
        raise ValueError(f"series {missing} unavailable in this program")
    return jnp.stack([vals[s].astype(I64) for s in spec.series])


def telemetry_tick(spec: TelemetrySpec, state, *,
                   progress: jax.Array, blk_iters: jax.Array,
                   dvfs=None) -> TelemetryState:
    """One outer-loop quantum's telemetry update (device-side, traced).

    Advances the cumulative loop counters, then — when simulated time
    (the laggard non-done clock; max clock once all tiles are done)
    crossed `next_ps`, or on the completing quantum — appends one row to
    the ring.  The row store is a MASKED add-a-delta scatter, never a
    lax.cond: the `[S, n_series]` buffer must not ride any cond output
    (the cond-payload audit rule forbids its aval), and the row itself
    is ~a dozen scalar reductions — noise next to a quantum.
    """
    ts = state.telemetry
    if ts is None:
        raise ValueError(
            "telemetry spec given but SimState.telemetry is None "
            "(init the state with obs.init_telemetry)")
    done = state.done
    clocks = state.core.clock_ps
    all_done = jnp.all(done)
    pending_min = jnp.min(jnp.where(~done, clocks, jnp.asarray(_BIG, I64)))
    sim_time = jnp.where(all_done, jnp.max(clocks), pending_min)

    zero = (progress == 0) & jnp.any(~done)
    ts = ts.replace(
        quanta=ts.quanta + 1,
        iters=ts.iters + blk_iters.astype(I64),
        stall_quanta=ts.stall_quanta + zero.astype(I64),
    )

    cur = _series_values(spec, state, ts, sim_time, dvfs)
    # the completing quantum records a final row (the chunked sampler's
    # sample-at-done), making the last cumulative state always visible
    do = (sim_time >= ts.next_ps) | all_done
    row = jnp.where(jnp.asarray(spec.delta_mask()), cur - ts.prev, cur)
    S = int(spec.n_samples)
    slot = (ts.count % S).astype(jnp.int32)
    # add-a-delta under mask: the scatter is the ring's only use, so XLA
    # updates the loop-carried buffer in place (no per-quantum copy)
    buf = ts.buf.at[slot].add(jnp.where(do, row - ts.buf[slot], 0))
    interval = jnp.asarray(int(spec.sample_interval_ps), I64)
    return ts.replace(
        buf=buf,
        prev=jnp.where(do, cur, ts.prev),
        count=ts.count + do.astype(jnp.int32),
        next_ps=jnp.where(do, (sim_time // interval + 1) * interval,
                          ts.next_ps),
    )


# ---------------------------------------------------------------------------
# host-side timeline (post-run demux)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Timeline:
    """One sim's recorded timeline, demuxed to chronological host rows.

    `data[i, j]` is sample i of series `series[j]`; delta series hold
    since-previous-sample deltas, level series sampled values.  When the
    run took more than S samples the ring wrapped: `data` holds the LAST
    S samples and `n_total` the true count (`wrapped` flags the loss)."""

    series: "tuple[str, ...]"
    data: np.ndarray          # int64[n_recorded, n_series]
    n_total: int
    sample_interval_ps: int
    wrapped: bool = False

    @classmethod
    def from_host_state(cls, spec: TelemetrySpec, buf: np.ndarray,
                        count: int) -> "Timeline":
        S = int(spec.n_samples)
        count = int(count)
        buf = np.asarray(buf)
        if count <= S:
            data = buf[:count].copy()
            wrapped = False
        else:
            slot = count % S
            data = np.concatenate([buf[slot:], buf[:slot]], axis=0)
            wrapped = True
        return cls(series=tuple(spec.series), data=data, n_total=count,
                   sample_interval_ps=int(spec.sample_interval_ps),
                   wrapped=wrapped)

    def __len__(self) -> int:
        return self.data.shape[0]

    def col(self, name: str) -> np.ndarray:
        return self.data[:, self.series.index(name)]

    @property
    def time_ns(self) -> np.ndarray:
        return self.col("time_ps") // 1000

    def summary(self) -> dict:
        """Timeline-derived scalars for bench/CI JSON: peak USER-net
        injection rate (packets per ns per tile-count-free total) and
        the mean per-tile clock spread, plus bookkeeping."""
        out = {
            "samples": int(len(self)),
            "samples_total": int(self.n_total),
            "wrapped": bool(self.wrapped),
        }
        if len(self) == 0:
            return out
        t_ns = self.time_ns.astype(np.int64)
        dt_ns = np.maximum(np.diff(np.concatenate([[0], t_ns])), 1)
        # wrapped ring: the first retained sample's baseline timestamp
        # was overwritten, so its interval (and any rate computed from
        # it) is unknowable — exclude it from the rate statistics
        rate_sl = slice(1, None) if self.wrapped else slice(None)
        if "packets_sent" in self.series:
            rate = (self.col("packets_sent") / dt_ns)[rate_sl]
            if rate.size:
                out["peak_injection_per_ns"] = float(rate.max())
                out["mean_injection_per_ns"] = float(rate.mean())
        if ("clock_max_ps" in self.series
                and "clock_min_ps" in self.series):
            spread = self.col("clock_max_ps") - self.col("clock_min_ps")
            out["mean_clock_spread_ps"] = float(spread.mean())
            out["max_clock_spread_ps"] = int(spread.max())
        if "stall_quanta" in self.series:
            out["stall_quanta_total"] = int(self.col("stall_quanta").sum())
        out["peaks"] = self.peaks()
        return out

    def peaks(self) -> dict:
        """Per-series maximum with its SAMPLE INDEX and time — so a
        spike is nameable ("l2_misses peaked at sample 17, t=42us")
        instead of only sized.  Clock levels are reported as their
        spread's peak (the raw max of a monotone clock is always the
        last sample, which names nothing)."""
        out = {}
        if len(self) == 0:
            return out
        t_ns = self.time_ns
        base = self.n_total - len(self)

        def peak(name, values):
            i = int(np.argmax(values))
            out[name] = {"max": int(values[i]),
                         "sample": int(base + i),
                         "time_ns": int(t_ns[i])}

        for s in self.series:
            if s == "time_ps" or s in LEVEL_SERIES:
                continue
            peak(s, self.col(s))
        if ("clock_max_ps" in self.series
                and "clock_min_ps" in self.series):
            peak("clock_spread_ps",
                 self.col("clock_max_ps") - self.col("clock_min_ps"))
        return out

    def json_rows(self) -> "list[dict]":
        """One JSON-able dict per sample (tools/report.py output)."""
        rows = []
        for i in range(len(self)):
            row = {"sample": int(self.n_total - len(self) + i),
                   "time_ns": int(self.time_ns[i])}
            for j, s in enumerate(self.series):
                if s == "time_ps":
                    continue
                row[s] = int(self.data[i, j])
            rows.append(row)
        return rows

    def save(self, path: str) -> None:
        np.savez(path, data=self.data,
                 series=np.array(self.series),
                 n_total=self.n_total,
                 sample_interval_ps=self.sample_interval_ps,
                 wrapped=self.wrapped)

    @classmethod
    def load(cls, path: str) -> "Timeline":
        z = np.load(path, allow_pickle=False)
        return cls(series=tuple(str(s) for s in z["series"]),
                   data=np.asarray(z["data"]),
                   n_total=int(z["n_total"]),
                   sample_interval_ps=int(z["sample_interval_ps"]),
                   wrapped=bool(z["wrapped"]))


def timeline_from_state(spec: TelemetrySpec, tstate) -> Timeline:
    """Fetch + demux one sim's TelemetryState (device or host pytree)."""
    buf, count = jax.device_get((tstate.buf, tstate.count))
    return Timeline.from_host_state(spec, np.asarray(buf), int(count))


def demux_timelines(spec: TelemetrySpec, tstate) -> "list[Timeline]":
    """Demux a batched [B, ...] TelemetryState (vmapped campaign or the
    batch-axis shard_map gather) into B per-sim Timelines."""
    buf, count = jax.device_get((tstate.buf, tstate.count))
    buf = np.asarray(buf)
    count = np.asarray(count)
    return [Timeline.from_host_state(spec, buf[b], int(count[b]))
            for b in range(buf.shape[0])]
