"""Host-side metrics registry: counters, gauges, fixed-bucket histograms.

The round-9 telemetry rings (`obs/telemetry.py`) instrument the *device*
program; this module instruments the *host* serving path.  The campaign
service (`serve/service.py`) holds one `MetricsRegistry` and replaces the
round-13 ad-hoc counter arithmetic with named instruments: queue dwell,
admission latency, batch-form latency, execute latency, compile time and
split depth become fixed-bucket histograms with deterministic
p50/p90/p99 summaries; the accounting identities (submitted ==
completed + failed, cache hits vs compiles) stay plain counters.

Design points:

 - **Injectable clock.**  The registry (and `obs/trace.py`'s tracer)
   takes a `clock` callable returning monotonic seconds; production uses
   `time.monotonic`, tests a fake clock — so dwell/latency histograms
   are *exact* under test, not approximately-timed.
 - **Deterministic quantiles.**  `Histogram.quantile(q)` returns the
   upper bound of the first bucket whose cumulative count reaches
   `ceil(q * count)` (the Prometheus convention without interpolation),
   and the true max for observations past the last finite bucket.  No
   estimation ambiguity: a hand-built observation set has one right
   answer, which the tests pin.
 - **Two exporters.**  `exposition()` renders the Prometheus text
   format (`# TYPE` comments, `_bucket{le=...}`/`_sum`/`_count` rows);
   `snapshot()` returns the JSON-able dict the CLI summary line embeds.
   `parse_exposition()` round-trips the text back into snapshot form —
   exporter output is CI-checkable, not write-only.
 - **Periodic timeline.**  `sample()` appends a timestamped snapshot
   row to a bounded deque — the service samples after every batch, so
   `tools/report.py --metrics` can render the service's counters as a
   time series, not just a final total.

Everything here is plain host Python: nothing touches a traced program,
so the registry can never perturb device results (the tracing-on/off
bit-equality contract rides on that).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import math
import time

INF = float("inf")

# Default latency buckets (seconds): 1 us .. ~100 s, 4 per decade.
DEFAULT_LATENCY_BUCKETS = tuple(
    round(10.0 ** (e / 4.0), 9) for e in range(-24, 9))
# Default count buckets (splits, attempts, depths): small exact ints.
DEFAULT_COUNT_BUCKETS = (0, 1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64)
# Occupancy / ratio buckets: exact eighths of [0, 1].
RATIO_BUCKETS = tuple(i / 8 for i in range(9))

SUMMARY_QUANTILES = (0.5, 0.9, 0.99)


class MetricsError(ValueError):
    """Registry misuse: name collision across types, unknown metric."""


def bucket_quantile(counts, bounds, q, *, overflow):
    """THE deterministic bucket-quantile definition, shared by host
    histograms (`Histogram.quantile`) and device histograms
    (`obs/hist.Hist.quantile`): the upper bound of the first bucket
    whose cumulative count reaches `ceil(q * total)`; observations in
    the trailing overflow bucket (counts has one more entry than
    bounds) resolve to `overflow`.  Empty -> 0.0."""
    if not 0.0 < q <= 1.0:
        raise MetricsError(f"quantile {q} outside (0, 1]")
    total = sum(counts)
    if total == 0:
        return 0.0
    rank = math.ceil(q * total)
    cum = 0
    for i, b in enumerate(bounds):
        cum += counts[i]
        if cum >= rank:
            return b
    return overflow


@dataclasses.dataclass
class Counter:
    """Monotone cumulative counter (float-valued so wall-clock sums can
    ride the same instrument)."""

    name: str
    help: str = ""
    value: float = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise MetricsError(f"counter {self.name!r} cannot decrease")
        self.value += n

    def to_snapshot(self):
        v = self.value
        return int(v) if float(v).is_integer() else v


@dataclasses.dataclass
class Gauge:
    """Point-in-time value (queue depth, cache bytes)."""

    name: str
    help: str = ""
    value: float = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def to_snapshot(self):
        v = self.value
        return int(v) if float(v).is_integer() else v


class Histogram:
    """Fixed-bucket histogram with deterministic quantile summaries.

    `buckets` are finite upper bounds (ascending); an implicit +Inf
    bucket catches the tail.  `observe(v)` increments the first bucket
    with `v <= bound`.  `quantile(q)` (q in (0, 1]) returns the upper
    bound of the first bucket whose cumulative count reaches
    `ceil(q * count)`; observations that landed in the +Inf bucket
    resolve to the true maximum seen (tracked exactly).  An empty
    histogram's quantile is 0.0.
    """

    def __init__(self, name: str, help: str = "",
                 buckets=DEFAULT_LATENCY_BUCKETS):
        bounds = tuple(float(b) for b in buckets)
        if not bounds or any(b2 <= b1 for b1, b2
                             in zip(bounds, bounds[1:])):
            raise MetricsError(
                f"histogram {name!r} needs ascending finite buckets")
        if math.isinf(bounds[-1]):
            raise MetricsError(
                f"histogram {name!r}: +Inf bucket is implicit")
        self.name = name
        self.help = help
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)   # last = +Inf bucket
        self.sum = 0.0
        self.count = 0
        self._min = INF
        self._max = -INF

    def observe(self, v: float) -> None:
        v = float(v)
        for i, b in enumerate(self.bounds):
            if v <= b:
                self.counts[i] += 1
                break
        else:
            self.counts[-1] += 1
        self.sum += v
        self.count += 1
        self._min = min(self._min, v)
        self._max = max(self._max, v)

    @property
    def min(self) -> float:
        return 0.0 if self.count == 0 else self._min

    @property
    def max(self) -> float:
        return 0.0 if self.count == 0 else self._max

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def quantile(self, q: float) -> float:
        return bucket_quantile(self.counts, self.bounds, q,
                               overflow=self.max)

    def to_snapshot(self) -> dict:
        out = {"count": self.count, "sum": self.sum,
               "min": self.min, "max": self.max, "mean": self.mean}
        for q in SUMMARY_QUANTILES:
            out[f"p{int(q * 100)}"] = self.quantile(q)
        return out


class MetricsRegistry:
    """Named instruments + exporters + a bounded snapshot timeline.

    `counter/gauge/histogram` are get-or-create (idempotent by name);
    re-registering a name as a different type is an error — one
    definition of each rate, by construction.
    """

    def __init__(self, *, clock=time.monotonic, max_timeline: int = 4096):
        self.clock = clock
        self._metrics: "collections.OrderedDict[str, object]" = \
            collections.OrderedDict()
        self.timeline: "collections.deque[dict]" = collections.deque(
            maxlen=int(max_timeline))

    def _get(self, name: str, typ, factory):
        m = self._metrics.get(name)
        if m is None:
            m = factory()
            self._metrics[name] = m
        elif not isinstance(m, typ):
            raise MetricsError(
                f"metric {name!r} already registered as "
                f"{type(m).__name__}, not {typ.__name__}")
        return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, Counter, lambda: Counter(name, help))

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, Gauge, lambda: Gauge(name, help))

    def histogram(self, name: str, help: str = "",
                  buckets=DEFAULT_LATENCY_BUCKETS) -> Histogram:
        h = self._get(name, Histogram,
                      lambda: Histogram(name, help, buckets))
        if h.bounds != tuple(float(b) for b in buckets):
            # same failure mode as a cross-type collision: two sites
            # disagreeing on the layout must fail fast, not silently
            # observe into the wrong buckets
            raise MetricsError(
                f"histogram {name!r} already registered with buckets "
                f"{h.bounds}, not {tuple(buckets)}")
        return h

    def __contains__(self, name: str) -> bool:
        return name in self._metrics

    def __getitem__(self, name: str):
        try:
            return self._metrics[name]
        except KeyError:
            raise MetricsError(f"unknown metric {name!r}") from None

    def names(self) -> "list[str]":
        return list(self._metrics)

    # -- exporters -------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-able view of every instrument (histograms summarized)."""
        return {name: m.to_snapshot()
                for name, m in self._metrics.items()}

    def sample(self) -> dict:
        """Append one timestamped snapshot row to the timeline."""
        row = {"t_s": float(self.clock()), **self.snapshot()}
        self.timeline.append(row)
        return row

    def timeline_jsonl(self) -> str:
        return "\n".join(json.dumps(row) for row in self.timeline)

    def exposition(self) -> str:
        """Prometheus text exposition of the current state."""
        lines = []
        for name, m in self._metrics.items():
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            if isinstance(m, Counter):
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {_fmt(m.value)}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {_fmt(m.value)}")
            else:
                lines.append(f"# TYPE {name} histogram")
                cum = 0
                for b, c in zip(m.bounds, m.counts):
                    cum += c
                    lines.append(
                        f'{name}_bucket{{le="{_fmt(b)}"}} {cum}')
                lines.append(
                    f'{name}_bucket{{le="+Inf"}} {m.count}')
                lines.append(f"{name}_sum {_fmt(m.sum)}")
                lines.append(f"{name}_count {m.count}")
        return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    v = float(v)
    return str(int(v)) if v.is_integer() else repr(v)


def parse_exposition(text: str) -> dict:
    """Parse a `MetricsRegistry.exposition()` dump back into
    `{name: {"type": ..., "value"/...}}` — the round-trip check the
    tests (and regress rung 9) run on exporter output.  Histograms come
    back with their per-bucket cumulative counts, sum and count, so a
    registry rebuilt from the text proves the dump lossless (up to the
    +Inf tail's true max, which the text format cannot carry)."""
    out: dict = {}
    types: dict = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, name, typ = line.split(None, 3)
            types[name] = typ
            out[name] = {"type": typ}
            if typ == "histogram":
                out[name].update({"buckets": {}, "sum": 0.0, "count": 0})
            continue
        if line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        name = name.strip()
        value = float(value)
        base, label = name, None
        if "{" in name:
            base, _, rest = name.partition("{")
            label = rest.rstrip("}")
        if base.endswith("_bucket") and label and label.startswith("le="):
            hname = base[: -len("_bucket")]
            le = label[len('le="'):].rstrip('"')
            out[hname]["buckets"][le] = int(value)
        elif base.endswith("_sum") and base[: -len("_sum")] in types:
            out[base[: -len("_sum")]]["sum"] = value
        elif base.endswith("_count") and base[: -len("_count")] in types:
            out[base[: -len("_count")]]["count"] = int(value)
        elif base in types:
            out[base]["value"] = value
        else:
            raise MetricsError(
                f"exposition line names unknown metric: {raw!r}")
    return out
