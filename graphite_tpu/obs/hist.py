"""Device-resident latency histograms: the distribution instrument.

Graphite's value as a simulator is the timing DISTRIBUTIONS it reports —
per-access miss latency, network delay, sync stall breakdowns
(`tile.cc:105-123` outputSummary) — and the TR-09 four-scheme clock study
compares distributions of skew, not just means.  The repo's first two
rings record cumulative counters (round 9, `obs/telemetry.py`) and
time-sampled per-tile series (round 16, `obs/profile.py`); every
per-event latency the engines already compute in-carry (`acc_ps`,
`slot_lat_ps` in `memory/engine.py`, the recv/barrier/mutex wait times in
`engine/step.py`) was folded into a sum and thrown away — no p50/p99, no
tail, no per-scheme distribution diff was observable.

This module records the distribution dimension: a third device-resident
ring of int64 bucket counts rides the simulation carry
(`engine/state.SimState.hist`), accumulated by masked scatter-add at
EVENT COMPLETION (the commit site in `engine/step.py`, not on sampling
boundaries) with zero host sync — the program still passes the
host-sync audit lint.  Sources are values the carry already holds:

 - per-slot memory latency at record commit (`slot_lat_ps[T, 3]` —
   icache slot -> `l1i_lat_ps`, mem slots -> `l1d_lat_ps`);
 - per-miss service time (`miss_lat_ps`): the requester's phase-6
   reply fill (`memory/engine.MemStepOut.fill_now` / `fill_lat_ps` —
   a per-call event, because a whole miss can start AND fill within
   one engine call);
 - USER-net packet latency at receive (`net_lat_ps`);
 - blocking-recv and sync stall durations (`recv_stall_ps`,
   `sync_stall_ps`) exactly where the scalar counters charge them;
 - per-boundary `clock_skew_ps` (every tile, every quantum — the
   four-scheme study's accuracy instrument) and opt-in per-boundary
   `energy_pj` deltas priced through the shared `EnergyPrices` ladder.

Every histogram total is CONSERVED against the matching cumulative
counter (`conservation_totals`): the recording masks are bit-identical
to the counter increments in `engine/step.py`, so on a completed run
with constant `models_enabled` the total count equals the counter —
the distribution analogue of round-16's cross-ring sum invariant,
asserted by tests/test_hist.py and regress rung 15.

`hist=None` (the default everywhere) constant-folds the recording away
to a bit-identical program — the same contract as `telemetry=None`
(round 9) and `profile=None` (round 16), jaxpr-asserted in
tests/test_hist.py and enforced by the `hist-off` audit lint.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from graphite_tpu.obs.metrics import bucket_quantile
from graphite_tpu.obs.telemetry import EnergyPrices, tile_energy_pj

I64 = jnp.int64

# Commit-site sources every program offers: recorded at the engine/step
# commit site under EXACTLY the masks the cumulative counters use
# (net_lat_ps <-> packets_received, recv_stall_ps <-> recv_instructions,
# sync_stall_ps <-> sync_instructions).
HIST_CORE_SOURCES = (
    "net_lat_ps",      # USER-net packet latency, at receive
    "recv_stall_ps",   # blocking-recv wait, charged receives only
    "sync_stall_ps",   # barrier/mutex/bsync/cjoin wait, charged syncs
)

# Memory-engine sources (require EngineParams.mem).  The slot latencies
# sample at record commit (one sample per present slot); the miss
# service time samples at the requester's reply-fill transition.
HIST_MEM_SOURCES = (
    "l1i_lat_ps",      # icache slot latency per committed record
    "l1d_lat_ps",      # L1-D slot latency per access (mem0 + mem1)
    "miss_lat_ps",     # full miss service time (phase-6 reply fill)
)

# Boundary sources: sampled for EVERY tile at EVERY executed quantum
# (unlike the interval-gated rings — skew is the four-scheme study's
# instrument, so each quantum is one observation of the whole fleet).
HIST_BOUNDARY_SOURCES = (
    "clock_skew_ps",   # tile clock minus the fleet-minimum clock
)

# Opt-in per-boundary per-tile energy delta (needs
# HistSpec.energy_prices — never part of the dense default, so locked
# programs are untouched).
HIST_ENERGY_SOURCES = ("energy_pj",)


def available_hist_sources(params) -> "tuple[str, ...]":
    """Every histogram source the given EngineParams can record
    (energy_pj joins only through HistSpec.energy_prices)."""
    out = HIST_CORE_SOURCES
    if params.mem is not None:
        out = out + HIST_MEM_SOURCES
    return out + HIST_BOUNDARY_SOURCES


@dataclasses.dataclass(frozen=True)
class HistSpec:
    """What to bucket: source selection, bucket edges, per-tile switch.

    `sources=None` selects every source the engine parameters support
    (the dense spec).  Buckets come from `edges` — an explicit strictly
    ascending tuple of non-negative ints — or, when None, the log2
    ladder `1, 2, 4, ..., 2**(log2_buckets - 2)` (so `log2_buckets`
    buckets total including the underflow-at-0 and overflow buckets).
    A value lands in the first bucket whose upper edge exceeds it;
    values at or past the last edge land in the overflow bucket.

    `per_tile=True` keeps one [H, B] plane per tile (int64[T, H, B],
    tile axis sharded with the directory under the 2D campaign mesh);
    the default aggregates the fleet into one int64[H, B] ring.

    `resolve(params)` validates the selection and fills `n_tiles` —
    `ring_bytes()` and `buffer_sig()` need the resolved spec.
    """

    sources: "tuple[str, ...] | None" = None
    edges: "tuple[int, ...] | None" = None
    log2_buckets: int = 32
    per_tile: bool = False
    # per-event pJ prices enabling the per-boundary energy_pj source
    energy_prices: "EnergyPrices | None" = None
    # filled by resolve(): the program's tile count
    n_tiles: int = 0

    def __post_init__(self):
        if self.sources is not None:
            object.__setattr__(self, "sources", tuple(self.sources))
        if self.edges is not None:
            e = tuple(int(v) for v in self.edges)
            if len(e) == 0:
                raise ValueError("edges must be non-empty when given")
            if any(v < 0 for v in e):
                raise ValueError("edges must be non-negative")
            if any(b <= a for a, b in zip(e, e[1:])):
                raise ValueError("edges must be strictly ascending")
            object.__setattr__(self, "edges", e)
        elif int(self.log2_buckets) < 2:
            raise ValueError("log2_buckets must be >= 2")

    @property
    def resolved(self) -> bool:
        return self.sources is not None and self.n_tiles > 0

    def resolve(self, params) -> "HistSpec":
        avail = available_hist_sources(params)
        if self.energy_prices is not None:
            if params.mem is None and self.energy_prices.needs_mem():
                raise ValueError(
                    "energy_prices set nonzero memory-event prices but "
                    "this program has no memory subsystem (only "
                    "instruction_pj/packet_pj apply to memoryless "
                    "traces)")
            avail = avail + HIST_ENERGY_SOURCES
        elif self.sources is not None \
                and any(s in HIST_ENERGY_SOURCES for s in self.sources):
            raise ValueError(
                "the energy_pj histogram needs HistSpec.energy_prices "
                "(an obs.EnergyPrices)")
        if self.sources is None:
            sel = avail
        else:
            unknown = [s for s in self.sources if s not in avail]
            if unknown:
                raise ValueError(
                    f"unknown/unavailable hist sources {unknown} "
                    f"(this program offers: {', '.join(avail)})")
            seen = []
            for s in self.sources:
                if s not in seen:
                    seen.append(s)
            sel = tuple(seen)
        return dataclasses.replace(self, sources=sel,
                                   n_tiles=int(params.n_tiles))

    @property
    def n_sources(self) -> int:
        if self.sources is None:
            raise ValueError("spec is unresolved (call resolve(params))")
        return len(self.sources)

    def bucket_edges(self) -> np.ndarray:
        """int64[E]: the bucket upper edges (explicit, or the log2
        ladder).  B = E + 1 buckets: index searchsorted(edges, v,
        'right') — below edges[0] is bucket 0, at/past edges[-1] the
        overflow bucket E."""
        if self.edges is not None:
            return np.asarray(self.edges, np.int64)
        return np.asarray([2 ** k for k in
                           range(int(self.log2_buckets) - 1)], np.int64)

    @property
    def n_buckets(self) -> int:
        return int(self.bucket_edges().shape[0]) + 1

    def buffer_sig(self) -> "tuple[tuple, str]":
        """The hist ring's aval signature ((T, H, B) per-tile or (H, B)
        aggregate, int64) — what the audit lints match (cond-payload
        forbidden set when the hist is ON; the hist-off rule when it
        must be absent)."""
        if not self.resolved:
            raise ValueError("buffer_sig needs a resolved HistSpec")
        shape = (self.n_sources, self.n_buckets)
        if self.per_tile:
            shape = (int(self.n_tiles),) + shape
        return (shape, "int64")

    def ring_bytes(self, tile_shards: int = 1) -> int:
        """Per-sim device residency of this spec's HistState: the
        bucket-count buffer + the boundaries scalar + (opt-in) the [T]
        prev-energy snapshot, all int64.  The ONE size model the
        residency budget and the admission bill consume
        (analysis/cost.residency_breakdown).

        `tile_shards` (round 18): per-DEVICE bytes under a tile-sharded
        2D campaign layout — a per-tile ring shards its tile axis with
        the directory; the aggregate ring, the boundaries cursor, and
        the prev-energy snapshot stay replicated."""
        shape, dtype = self.buffer_sig()
        item = np.dtype(dtype).itemsize
        ts = max(int(tile_shards), 1)
        if self.per_tile:
            T, H, B = shape
            if T % ts:
                raise ValueError(
                    f"tile count {T} not divisible by tile_shards={ts}")
            elems = (T // ts) * H * B
        else:
            elems = int(np.prod(shape))
        extra = (int(self.n_tiles)
                 if self.sources is not None
                 and any(s in HIST_ENERGY_SOURCES for s in self.sources)
                 else 0)
        return (elems + 1 + extra) * item


@struct.dataclass
class HistState:
    """The device-resident bucket-count state (rides SimState.hist).

    `buf` is the int64[H, B] (aggregate) or int64[T, H, B] (per-tile)
    bucket-count ring; `boundaries` counts executed quanta (one
    fleet-wide skew/energy observation each — the conservation
    denominator for the boundary sources); `prev_energy` is the [T]
    cumulative-pJ snapshot at the last boundary (present only when the
    energy_pj source is selected — the off spec carries no leaf)."""

    buf: jax.Array           # int64[H, B] | int64[T, H, B]
    boundaries: jax.Array    # int64[]
    prev_energy: "jax.Array | None" = None   # int64[T] | None


def init_hist(spec: HistSpec) -> HistState:
    if not spec.resolved:
        raise ValueError("init_hist needs a resolved HistSpec")
    shape, _ = spec.buffer_sig()
    prev = None
    if any(s in HIST_ENERGY_SOURCES for s in spec.sources):
        prev = jnp.zeros((int(spec.n_tiles),), I64)
    return HistState(buf=jnp.zeros(shape, I64),
                     boundaries=jnp.zeros((), I64),
                     prev_energy=prev)


def _bucketize(spec: HistSpec, values: jax.Array) -> jax.Array:
    """int32[T] bucket index per lane: first bucket whose upper edge
    exceeds the value (overflow bucket at/past the last edge)."""
    edges = jnp.asarray(spec.bucket_edges())
    return jnp.searchsorted(edges, values.astype(I64),
                            side="right").astype(jnp.int32)


def _scatter(spec: HistSpec, buf: jax.Array, h: int, mask: jax.Array,
             values: jax.Array, px=None) -> jax.Array:
    """Masked scatter-add of one event batch into source row `h`.

    Masked-off lanes still index a bucket but add 0 — the add-a-delta
    discipline, so the scatter is the buffer's only use and XLA updates
    the loop-carried ring in place.  Under a tile-sharded px the
    per-tile ring holds only this device's [Tl, H, B] block: the
    replicated [T] masks/values are lo()'d to the local lanes."""
    bucket = _bucketize(spec, values)
    if spec.per_tile:
        if px is not None and px.sharded:
            mask, bucket = px.lo((mask, bucket))
        rows = jnp.arange(bucket.shape[0], dtype=jnp.int32)
        return buf.at[rows, h, bucket].add(mask.astype(I64))
    return buf.at[h, bucket].add(mask.astype(I64))


def hist_commit_update(spec: HistSpec, hs: HistState, *,
                       advance, enabled,
                       recv_now, recv_lat_ps, recv_charged, recv_wait_ps,
                       sync_charged, sync_wait_ps,
                       present=None, slot_lat_ps=None,
                       miss_now=None, miss_lat_ps=None,
                       px=None) -> HistState:
    """One subquantum iteration's commit-site histogram update.

    Called from the `engine/step.py` commit site (after the charged
    masks are final) under a Python-level `hist is not None` gate, so
    the off program lowers byte-identically.  The masks are the SAME
    expressions the cumulative counters add (`conservation_totals`
    documents each pairing); the memory arguments are None exactly when
    the program has no memory subsystem (resolve() already refused
    memory sources then)."""
    if hs is None:
        raise ValueError(
            "hist spec given but SimState.hist is None "
            "(init the state with obs.init_hist)")
    buf = hs.buf
    sel = spec.sources
    if "net_lat_ps" in sel:
        # every receive, enabled or not — packets_received counts them all
        buf = _scatter(spec, buf, sel.index("net_lat_ps"),
                       recv_now, recv_lat_ps.astype(I64), px=px)
    if "recv_stall_ps" in sel:
        buf = _scatter(spec, buf, sel.index("recv_stall_ps"),
                       recv_charged, recv_wait_ps, px=px)
    if "sync_stall_ps" in sel:
        buf = _scatter(spec, buf, sel.index("sync_stall_ps"),
                       sync_charged, sync_wait_ps, px=px)
    if "l1i_lat_ps" in sel:
        # icache slot presence is already enabled-gated (slots_present)
        buf = _scatter(spec, buf, sel.index("l1i_lat_ps"),
                       advance & present[:, 0] & enabled,
                       slot_lat_ps[:, 0], px=px)
    if "l1d_lat_ps" in sel:
        h = sel.index("l1d_lat_ps")
        for s in (1, 2):
            buf = _scatter(spec, buf, h,
                           advance & present[:, s] & enabled,
                           slot_lat_ps[:, s], px=px)
    if "miss_lat_ps" in sel:
        buf = _scatter(spec, buf, sel.index("miss_lat_ps"),
                       miss_now & enabled, miss_lat_ps, px=px)
    return hs.replace(buf=buf)


def hist_boundary_tick(spec: HistSpec, state, px=None, dvfs=None
                       ) -> HistState:
    """One outer-loop quantum's boundary-source update (device-side,
    traced).  Unlike the interval-gated telemetry/profile ticks this
    samples EVERY executed quantum: each quantum is one observation of
    the whole fleet's skew (and energy delta), and `boundaries` is the
    conservation denominator (`total == boundaries * T`)."""
    hs = state.hist
    if hs is None:
        raise ValueError(
            "hist spec given but SimState.hist is None "
            "(init the state with obs.init_hist)")
    buf = hs.buf
    sel = spec.sources
    T = int(spec.n_tiles)
    ones = jnp.ones((T,), jnp.bool_)
    if "clock_skew_ps" in sel:
        clocks = state.core.clock_ps
        skew = clocks - jnp.min(clocks)
        buf = _scatter(spec, buf, sel.index("clock_skew_ps"),
                       ones, skew, px=px)
    prev = hs.prev_energy
    if "energy_pj" in sel:
        # delta on the full replicated [T] vector; the scatter lo()s it
        cur = tile_energy_pj(spec.energy_prices, state, dvfs)
        buf = _scatter(spec, buf, sel.index("energy_pj"),
                       ones, cur - hs.prev_energy, px=px)
        prev = cur
    return hs.replace(buf=buf, boundaries=hs.boundaries + 1,
                      prev_energy=prev)


# ---------------------------------------------------------------------------
# host-side histogram (post-run fetch)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Hist:
    """One sim's recorded histograms on the host.

    `counts[h, b]` (aggregate) or `counts[t, h, b]` (per-tile) is the
    event count of source `sources[h]` in bucket b; `edges[b]` is
    bucket b's upper edge (the overflow bucket has none).  Quantiles
    use the ONE shared definition (`obs.metrics.bucket_quantile`):
    first bucket edge whose cumulative count reaches ceil(q * n),
    saturating at the last edge for the overflow bucket."""

    sources: "tuple[str, ...]"
    edges: np.ndarray         # int64[B - 1]
    counts: np.ndarray        # int64[H, B] | int64[T, H, B]
    boundaries: int

    @property
    def per_tile(self) -> bool:
        return self.counts.ndim == 3

    @property
    def n_tiles(self) -> int:
        return self.counts.shape[0] if self.per_tile else 1

    def counts_for(self, source: str, tile: "int | None" = None
                   ) -> np.ndarray:
        """int64[B] — one source's buckets (fleet-summed, or one
        tile's plane when `tile` is given on a per-tile recording)."""
        h = self.sources.index(source)
        if not self.per_tile:
            if tile is not None:
                raise ValueError("tile= needs a per_tile recording")
            return self.counts[h]
        if tile is not None:
            return self.counts[int(tile), h]
        return self.counts[:, h].sum(axis=0)

    def total(self, source: str) -> int:
        return int(self.counts_for(source).sum())

    def totals(self) -> "dict[str, int]":
        return {s: self.total(s) for s in self.sources}

    def quantile(self, source: str, q: float,
                 tile: "int | None" = None) -> int:
        counts = self.counts_for(source, tile)
        bounds = [int(e) for e in self.edges]
        return int(bucket_quantile([int(c) for c in counts], bounds, q,
                                   overflow=bounds[-1]))

    def summary(self) -> dict:
        """Per-source count + p50/p95/p99 scalars for bench/CI JSON."""
        out = {"boundaries": int(self.boundaries),
               "per_tile": bool(self.per_tile)}
        for s in self.sources:
            out[f"{s}_count"] = self.total(s)
            for q in (0.5, 0.95, 0.99):
                out[f"{s}_p{int(q * 100)}"] = self.quantile(s, q)
        return out

    def save(self, path: str) -> None:
        np.savez(path, counts=self.counts, edges=self.edges,
                 sources=np.array(self.sources),
                 boundaries=self.boundaries)

    @classmethod
    def load(cls, path: str) -> "Hist":
        z = np.load(path, allow_pickle=False)
        return cls(sources=tuple(str(s) for s in z["sources"]),
                   edges=np.asarray(z["edges"]),
                   counts=np.asarray(z["counts"]),
                   boundaries=int(z["boundaries"]))


def hist_from_state(spec: HistSpec, hstate) -> Hist:
    """Fetch one sim's HistState (device or host pytree) into a Hist."""
    buf, boundaries = jax.device_get((hstate.buf, hstate.boundaries))
    return Hist(sources=tuple(spec.sources),
                edges=spec.bucket_edges(),
                counts=np.asarray(buf), boundaries=int(boundaries))


def demux_hists(spec: HistSpec, hstate) -> "list[Hist]":
    """Demux a batched [B, ...] HistState (vmapped campaign or the
    batch-axis shard_map gather) into B per-sim Hists.

    `hstate` may also be the already-fetched (buf, boundaries) host
    pair — SweepRunner passes the arrays from its ONE batched
    device->host fetch, so this is the single demux implementation
    every campaign path shares."""
    parts = (tuple(hstate) if isinstance(hstate, (tuple, list))
             else (hstate.buf, hstate.boundaries))
    buf, boundaries = (np.asarray(x) for x in jax.device_get(parts))
    return [Hist(sources=tuple(spec.sources), edges=spec.bucket_edges(),
                 counts=buf[b], boundaries=int(boundaries[b]))
            for b in range(buf.shape[0])]


def conservation_totals(hist: Hist, results, *,
                        protocol: "str | None" = None
                        ) -> "dict[str, tuple[int, int]]":
    """source -> (histogram total, the cumulative total it must
    bit-equal) — the conservation cross-check.

    Exact on COMPLETED runs with constant `models_enabled`, because the
    recording masks are the counter-increment masks:

      net_lat_ps     <-> packets_received      (every receive)
      recv_stall_ps  <-> recv_instructions     (charged receives)
      sync_stall_ps  <-> sync_instructions     (charged syncs)
      l1i_lat_ps     <-> l1i_hits + l1i_misses (one lookup per record)
      l1d_lat_ps     <-> all four l1d counters (one lookup per slot)
      miss_lat_ps    <-> l2_misses (private-L2 MSI) or the three L1
                         miss counters (pr_l1_sh_l2 — every L1 miss
                         goes remote)
      clock_skew_ps  <-> boundaries * T        (fleet sample/quantum)
      energy_pj      <-> boundaries * T
    """
    out = {}
    mc = results.mem_counters
    for s in hist.sources:
        if s == "net_lat_ps":
            want = int(np.sum(results.packets_received))
        elif s == "recv_stall_ps":
            want = int(np.sum(results.recv_instructions))
        elif s == "sync_stall_ps":
            want = int(np.sum(results.sync_instructions))
        elif s == "l1i_lat_ps":
            want = int(np.sum(mc["l1i_hits"]) + np.sum(mc["l1i_misses"]))
        elif s == "l1d_lat_ps":
            want = int(np.sum(mc["l1d_read_hits"])
                       + np.sum(mc["l1d_read_misses"])
                       + np.sum(mc["l1d_write_hits"])
                       + np.sum(mc["l1d_write_misses"]))
        elif s == "miss_lat_ps":
            if protocol is not None and protocol.startswith("pr_l1_sh_l2"):
                want = int(np.sum(mc["l1i_misses"])
                           + np.sum(mc["l1d_read_misses"])
                           + np.sum(mc["l1d_write_misses"]))
            else:
                want = int(np.sum(mc["l2_misses"]))
        elif s in ("clock_skew_ps", "energy_pj"):
            want = int(hist.boundaries) * int(results.n_tiles)
        else:
            continue
        out[s] = (hist.total(s), want)
    return out
