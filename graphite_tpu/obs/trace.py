"""Job-lifecycle span tracing for the campaign service.

One `Span` is a named host-side interval with attributes; one *trace*
is the set of spans sharing a `trace_id` — a job id for job lifecycles
(submit → validate → admit/reject → queue dwell → execute → emit), or
`batch-<n>` for batch execution spans (class key, capacity, occupancy,
cache hit/miss, compile time).  Together they answer "where did this
job's wall time go" with one artifact: host phases from the spans,
device time from the telemetry timeline the emit span references.

Contracts:

 - **Injectable clock** (same as `obs/metrics.py`): the tracer reads
   monotonic seconds from a caller-supplied callable, so tests drive a
   fake clock and assert exact span durations.
 - **Terminal completeness.**  Every job trace must end in exactly one
   terminal span (`emit`, `reject`, or `failed`).  `missing_terminal()`
   names the jobs that don't — the regress rung's span-set-complete
   check.
 - **JSON-lines export.**  `export_jsonl()` writes one span per line
   (`tools/serve.py --trace-out`); `load_jsonl()` reads it back for
   `tools/report.py --spans`.  Timestamps export as integer
   microseconds relative to the tracer's epoch (the first clock read),
   so files are stable and diffable under a fake clock.
 - **Bounded retention**: the span deque keeps the newest `max_spans`
   (a persistent service must not grow without bound); the export
   carries whatever is retained.

Tracing is strictly host-side observability: no traced program ever
sees the tracer, so serve results are bit-equal with tracing on or off
(regress-pinned).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import json
import time

# Span names in job-lifecycle order (report tables render this order).
JOB_SPANS = ("submit", "validate", "admit", "queue", "execute", "emit")
# Terminal span names: every submitted job's trace ends in exactly one.
TERMINAL_SPANS = ("emit", "reject", "failed")

BATCH_TRACE_PREFIX = "batch-"


@dataclasses.dataclass
class Span:
    """One named host-side interval within a trace."""

    trace_id: str
    name: str
    t_start: float               # tracer-clock seconds
    t_end: "float | None" = None
    attrs: dict = dataclasses.field(default_factory=dict)

    @property
    def dur_s(self) -> float:
        return 0.0 if self.t_end is None else self.t_end - self.t_start

    @property
    def open(self) -> bool:
        return self.t_end is None


class Tracer:
    """Collects spans against an injectable monotonic clock."""

    def __init__(self, *, clock=time.monotonic, max_spans: int = 65536):
        self.clock = clock
        self.spans: "collections.deque[Span]" = collections.deque(
            maxlen=int(max_spans))
        self._epoch: "float | None" = None

    def _now(self) -> float:
        t = float(self.clock())
        if self._epoch is None:
            self._epoch = t
        return t

    # -- recording -------------------------------------------------------

    def begin(self, trace_id: str, name: str, **attrs) -> Span:
        """Open a span (not yet retained — `end()` appends it)."""
        return Span(trace_id=str(trace_id), name=str(name),
                    t_start=self._now(), attrs=dict(attrs))

    def end(self, span: Span, **attrs) -> Span:
        span.t_end = self._now()
        span.attrs.update(attrs)
        self.spans.append(span)
        return span

    @contextlib.contextmanager
    def span(self, trace_id: str, name: str, **attrs):
        s = self.begin(trace_id, name, **attrs)
        try:
            yield s
        finally:
            self.end(s)

    def event(self, trace_id: str, name: str, **attrs) -> Span:
        """Zero-duration span (backpressure, retry, ...)."""
        return self.end(self.begin(trace_id, name, **attrs))

    def record(self, trace_id: str, name: str, t_start: float,
               t_end: float, **attrs) -> Span:
        """Append a span whose interval was measured elsewhere (e.g.
        queue dwell, reconstructed from the enqueue timestamp when the
        batch forms)."""
        self._now()   # pin the epoch even if this is the first record
        s = Span(trace_id=str(trace_id), name=str(name),
                 t_start=float(t_start), t_end=float(t_end),
                 attrs=dict(attrs))
        self.spans.append(s)
        return s

    # -- queries ---------------------------------------------------------

    def trace(self, trace_id: str) -> "list[Span]":
        return [s for s in self.spans if s.trace_id == str(trace_id)]

    def trace_ids(self) -> "list[str]":
        seen: "dict[str, None]" = {}
        for s in self.spans:
            seen.setdefault(s.trace_id, None)
        return list(seen)

    def missing_terminal(self, trace_ids) -> "list[str]":
        """The given traces that lack a terminal span — must be empty
        for every submitted job id once the service drained (the
        regress rung-9 completeness check)."""
        done = {s.trace_id for s in self.spans
                if s.name in TERMINAL_SPANS}
        return [str(t) for t in trace_ids if str(t) not in done]

    # -- export ----------------------------------------------------------

    def to_rows(self) -> "list[dict]":
        epoch = self._epoch or 0.0
        rows = []
        for s in self.spans:
            rows.append({
                "trace": s.trace_id,
                "span": s.name,
                "start_us": int(round((s.t_start - epoch) * 1e6)),
                "dur_us": int(round(s.dur_s * 1e6)),
                **s.attrs,
            })
        return rows

    def export_jsonl(self, path_or_file) -> int:
        """Write one JSON line per retained span; returns the count."""
        rows = self.to_rows()
        if hasattr(path_or_file, "write"):
            for row in rows:
                path_or_file.write(json.dumps(row) + "\n")
        else:
            with open(path_or_file, "w") as fh:
                for row in rows:
                    fh.write(json.dumps(row) + "\n")
        return len(rows)


def load_jsonl(path_or_file) -> "list[dict]":
    """Read spans back from a `export_jsonl` file (report input)."""
    if hasattr(path_or_file, "read"):
        lines = path_or_file.read().splitlines()
    else:
        with open(path_or_file) as fh:
            lines = fh.read().splitlines()
    rows = []
    for ln in lines:
        ln = ln.strip()
        if ln:
            rows.append(json.loads(ln))
    return rows


def job_breakdown(rows: "list[dict]") -> "list[dict]":
    """Fold exported span rows into one latency-breakdown row per job
    trace: `{job, <span>_us..., total_us, status, **terminal attrs}`.
    Batch traces (`batch-*`) are excluded — `tools/report.py --spans`
    renders them separately."""
    by_job: "dict[str, dict]" = {}
    for r in rows:
        tid = r["trace"]
        if tid.startswith(BATCH_TRACE_PREFIX):
            continue
        row = by_job.setdefault(tid, {"job": tid, "status": None})
        name = r["span"]
        # repeated spans (retries) accumulate duration
        row[name + "_us"] = row.get(name + "_us", 0) + r["dur_us"]
        if name in TERMINAL_SPANS:
            row["status"] = name
            for k, v in r.items():
                if k not in ("trace", "span", "start_us", "dur_us"):
                    row.setdefault(k, v)
    for row in by_job.values():
        row["total_us"] = sum(v for k, v in row.items()
                              if isinstance(v, int) and k.endswith("_us"))
    return list(by_job.values())
