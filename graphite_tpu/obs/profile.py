"""Device-resident per-tile profile rings: the spatial profiler.

Graphite's statistics subsystem dumps PER-TILE counters (core, cache,
network, power) at simulation end — `tile.cc:105-123` outputSummary per
tile — and that spatial view is how the HPCA'10 evaluation localizes
hotspots and how the TR-09 clock-skew study characterizes per-tile skew
under the lax schemes.  The round-9 telemetry ring (`obs/telemetry.py`)
records only fleet aggregates (summed counters, clock min/max/mean), so
it can say *that* traffic spiked but not *where*, and *that* clocks
spread but not *which tile is the straggler*.

This module records the spatial dimension: a second device-resident
ring `int64[S, T, m]` rides the simulation carry
(`engine/state.SimState.profile`) next to the scalar ring, sampled on
the SAME simulated-time boundaries (one boundary test per quantum, one
masked add-a-delta row scatter per ring, zero host sync — the program
still passes the host-sync audit lint).  Series are per-tile `[T]`
lanes the carry already holds: clock skew vs the laggard, committed
instructions and trace records, sync/recv stall time, per-tile cache
access/miss and directory-op deltas, USER-net packets in/out, and the
opt-in per-tile `energy_pj` priced through the same `EnergyPrices`
table the scalar series uses.

Cross-ring consistency is free by construction and regress-asserted
(`tools/regress.py --smoke` rung 10): a delta series shared with the
scalar ring sums over T to exactly the scalar column, and
`max(clock_skew_ps) + clock_min_ps == clock_max_ps` sample for sample.

`profile=None` (the default everywhere) constant-folds the recording
away to a bit-identical program — the same contract as `telemetry=None`
(round 9) and `knobs=None` (round 7), jaxpr-asserted in
tests/test_profile.py and enforced by the `profile-off` audit lint.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from graphite_tpu.obs.telemetry import EnergyPrices, tile_energy_pj

I64 = jnp.int64
_BIG = 2**62

# Series that record the sampled LEVEL; everything else records the
# since-last-sample DELTA of a monotone cumulative per-tile counter
# (differenced on device against the `prev` snapshot in ProfileState,
# so ring wraparound never corrupts — exactly the round-9 discipline).
PROFILE_LEVEL_SERIES = ("clock_skew_ps", "freq_mhz")

# Always-available per-tile series (state the core carry already holds
# as [T] lanes).  Names shared with the scalar telemetry ring
# (instructions, sync_stall_ps, packets_sent, ...) sum over T to the
# scalar series — the cross-ring invariant the regress rung asserts.
PROFILE_CORE_SERIES = (
    "clock_skew_ps",     # tile clock minus the fleet-minimum clock
    "instructions",      # committed instructions, this tile
    "records",           # committed trace records (per-tile progress)
    "sync_stall_ps",     # barrier/mutex/cond stall time, this tile
    "recv_stall_ps",     # blocking-recv stall time, this tile
    "packets_sent",      # USER-net injections, this tile
    "packets_received",  # USER-net receives, this tile
)

# Memory-engine per-tile counter series (require EngineParams.mem).
PROFILE_MEM_SERIES = (
    "l1d_accesses",      # L1-D lookups (read+write, hit+miss)
    "l1d_misses",
    "l2_accesses",       # L2 lookups (hits + misses)
    "l2_misses",
    "dir_accesses",      # directory operations homed at this tile
    "invalidations",
    "evictions",
)

# Per-tile energy (opt-in via ProfileSpec.energy_prices, like round 14's
# scalar series — never part of the dense default, so locked programs
# are untouched).
PROFILE_ENERGY_SERIES = ("energy_pj",)

# Per-tile operating frequency (round 19, opt-in via ProfileSpec.dvfs —
# same never-in-the-dense-default rule, so locked programs with
# series=None resolve unchanged).  A LEVEL series: the sampled MHz, not
# a delta.
PROFILE_DVFS_SERIES = ("freq_mhz",)


def available_tile_series(params) -> "tuple[str, ...]":
    """Every per-tile series the given EngineParams can record."""
    out = PROFILE_CORE_SERIES
    if params.mem is not None:
        out = out + PROFILE_MEM_SERIES
    return out


@dataclasses.dataclass(frozen=True)
class ProfileSpec:
    """What to record per tile: sampling interval, ring depth S, series.

    Mirrors `TelemetrySpec` deliberately — same interval/S fields, same
    resolve-against-the-program flow, same opt-in `energy_prices` — so
    a job carrying both specs samples both rings on one shared cursor
    schedule (the boundary test is identical arithmetic; give both
    specs the same `sample_interval_ps` and the rows align one-to-one,
    which is what makes the cross-ring sum invariant assertable).

    `series=None` selects every per-tile series the engine parameters
    support (the dense spec).  `resolve(params)` validates the
    selection and fills `n_tiles` — `ring_bytes()` and `buffer_sig()`
    need the resolved spec.
    """

    sample_interval_ps: int
    n_samples: int = 256
    series: "tuple[str, ...] | None" = None
    # per-event pJ prices enabling the per-tile energy_pj series
    energy_prices: "EnergyPrices | None" = None
    # True makes the per-tile freq_mhz series available (round 19 —
    # pair with a Simulator dvfs= spec to watch transitions; the core
    # carry always holds the [T] frequency, so the flag only gates the
    # series offering, keeping series=None resolutions unchanged)
    dvfs: bool = False
    # filled by resolve(): the program's tile count (the ring's T axis)
    n_tiles: int = 0

    def __post_init__(self):
        if int(self.sample_interval_ps) <= 0:
            raise ValueError("sample_interval_ps must be positive")
        if int(self.n_samples) <= 0:
            raise ValueError("n_samples must be positive")
        if self.series is not None:
            object.__setattr__(self, "series", tuple(self.series))

    @property
    def resolved(self) -> bool:
        return self.series is not None and self.n_tiles > 0

    def resolve(self, params) -> "ProfileSpec":
        avail = available_tile_series(params)
        if self.energy_prices is not None:
            if params.mem is None and self.energy_prices.needs_mem():
                raise ValueError(
                    "energy_prices set nonzero memory-event prices but "
                    "this program has no memory subsystem (only "
                    "instruction_pj/packet_pj apply to memoryless "
                    "traces)")
            avail = avail + PROFILE_ENERGY_SERIES
        elif self.series is not None \
                and any(s in PROFILE_ENERGY_SERIES for s in self.series):
            raise ValueError(
                "the per-tile energy_pj series needs "
                "ProfileSpec.energy_prices (an obs.EnergyPrices)")
        if self.dvfs:
            avail = avail + PROFILE_DVFS_SERIES
        elif self.series is not None \
                and any(s in PROFILE_DVFS_SERIES for s in self.series):
            raise ValueError(
                "the per-tile freq_mhz series needs ProfileSpec.dvfs=True")
        if self.series is None:
            sel = avail
        else:
            unknown = [s for s in self.series if s not in avail]
            if unknown:
                raise ValueError(
                    f"unknown/unavailable profile series {unknown} "
                    f"(this program offers: {', '.join(avail)})")
            seen = []
            for s in self.series:
                if s not in seen:
                    seen.append(s)
            sel = tuple(seen)
        return dataclasses.replace(self, series=sel,
                                   n_tiles=int(params.n_tiles))

    @property
    def n_series(self) -> int:
        if self.series is None:
            raise ValueError("spec is unresolved (call resolve(params))")
        return len(self.series)

    def buffer_sig(self) -> "tuple[tuple, str]":
        """The profile ring's aval signature ((S, T, m), dtype) — what
        the audit lints match (cond-payload forbidden set when the
        profile is ON; the profile-off rule when it must be absent).
        The [S] times ring is deliberately NOT a lint signature: a
        length-S int64 vector is far too generic an aval to forbid."""
        if not self.resolved:
            raise ValueError("buffer_sig needs a resolved ProfileSpec")
        return ((int(self.n_samples), int(self.n_tiles), self.n_series),
                "int64")

    def ring_bytes(self, tile_shards: int = 1) -> int:
        """Per-sim device residency of this spec's ProfileState: the
        [S, T, m] ring + the [T, m] prev snapshot + the [S] times ring
        + the two scalar cursors, all int64.  The ONE size model the
        residency budget and the admission bill consume
        (analysis/cost.residency_breakdown) — a campaign pays B x this,
        and the T factor is why a 1024-tile dense profile is priced,
        not assumed.

        `tile_shards` (round 18): per-DEVICE bytes under a tile-sharded
        2D campaign layout — the [S, T, m] ring and the [T, m] prev
        snapshot shard their tile axis with the directory (each device
        holds T/tile_shards rows), while the [S] times ring and the
        cursors stay replicated."""
        (S, T, m), dtype = self.buffer_sig()
        item = np.dtype(dtype).itemsize
        ts = max(int(tile_shards), 1)
        if T % ts:
            raise ValueError(
                f"tile count {T} not divisible by tile_shards={ts}")
        Tl = T // ts
        return (S * Tl * m + Tl * m + S + 2) * item

    def delta_mask(self) -> np.ndarray:
        """bool[n_series]: True where the series records a delta."""
        return np.array([s not in PROFILE_LEVEL_SERIES
                         for s in self.series], dtype=bool)


@struct.dataclass
class ProfileState:
    """The device-resident per-tile recording state (rides
    SimState.profile).

    `buf` is the [S, T, m] ring; `times` the [S] sample-time ring
    (simulated picoseconds — the host demux key, since per-tile rows
    have no scalar time column of their own); `prev` the cumulative
    [T, m] snapshot at the last sample; `count` the total samples taken
    (`count % S` is the next write slot); `next_ps` the next
    simulated-time sample boundary."""

    buf: jax.Array       # int64[S, T, m]
    times: jax.Array     # int64[S]
    prev: jax.Array      # int64[T, m]
    count: jax.Array     # int32[]
    next_ps: jax.Array   # int64[]


def init_profile(spec: ProfileSpec) -> ProfileState:
    if not spec.resolved:
        raise ValueError("init_profile needs a resolved ProfileSpec")
    S, T, m = spec.buffer_sig()[0]
    return ProfileState(
        buf=jnp.zeros((S, T, m), I64),
        times=jnp.zeros((S,), I64),
        prev=jnp.zeros((T, m), I64),
        count=jnp.zeros((), jnp.int32),
        next_ps=jnp.asarray(int(spec.sample_interval_ps), I64),
    )


def _tile_series_values(spec: ProfileSpec, state, dvfs=None) -> jax.Array:
    """The CUMULATIVE value of every selected series, int64[T, m].
    Delta series are differenced against `ProfileState.prev` by the
    tick."""
    core = state.core
    clocks = core.clock_ps
    vals = {}
    sel = set(spec.series)
    if "freq_mhz" in sel:
        vals["freq_mhz"] = core.freq_mhz.astype(I64)
    if "clock_skew_ps" in sel:
        # skew vs the laggard: the same jnp.min baseline the scalar
        # ring's clock_min_ps level records, so max-over-tiles of this
        # column plus clock_min_ps reconstructs clock_max_ps exactly
        vals["clock_skew_ps"] = clocks - jnp.min(clocks)
    if "instructions" in sel:
        vals["instructions"] = core.instruction_count
    if "records" in sel:
        vals["records"] = core.idx.astype(I64)
    if "sync_stall_ps" in sel:
        vals["sync_stall_ps"] = core.sync_stall_ps
    if "recv_stall_ps" in sel:
        vals["recv_stall_ps"] = core.recv_stall_ps
    if "packets_sent" in sel:
        vals["packets_sent"] = state.net.packets_sent
    if "packets_received" in sel:
        vals["packets_received"] = state.net.packets_received
    if sel & set(PROFILE_MEM_SERIES):
        if state.mem is None:
            raise ValueError("memory profile series need the memory "
                             "subsystem")
        mc = state.mem.counters
        if "l1d_accesses" in sel:
            vals["l1d_accesses"] = (mc.l1d_read_hits + mc.l1d_read_misses
                                    + mc.l1d_write_hits
                                    + mc.l1d_write_misses)
        if "l1d_misses" in sel:
            vals["l1d_misses"] = mc.l1d_read_misses + mc.l1d_write_misses
        if "l2_accesses" in sel:
            vals["l2_accesses"] = mc.l2_hits + mc.l2_misses
        if "l2_misses" in sel:
            vals["l2_misses"] = mc.l2_misses
        if "dir_accesses" in sel:
            vals["dir_accesses"] = mc.dir_accesses
        if "invalidations" in sel:
            vals["invalidations"] = mc.invalidations
        if "evictions" in sel:
            vals["evictions"] = mc.evictions
    if "energy_pj" in sel:
        ep = spec.energy_prices
        if ep is None:
            raise ValueError("energy_pj selected without energy_prices")
        # the ONE energy ladder (obs/telemetry.tile_energy_pj): the
        # scalar series is jnp.sum of exactly this vector
        vals["energy_pj"] = tile_energy_pj(ep, state, dvfs)
    missing = [s for s in spec.series if s not in vals]
    if missing:
        raise ValueError(f"series {missing} unavailable in this program")
    return jnp.stack([vals[s].astype(I64) for s in spec.series], axis=1)


def profile_tick(spec: ProfileSpec, state, px=None, dvfs=None
                 ) -> ProfileState:
    """One outer-loop quantum's profile update (device-side, traced).

    The boundary test is the SAME arithmetic as `telemetry_tick` —
    simulated time (the laggard non-done clock; max clock once all done)
    crossed `next_ps`, or the completing quantum — so when both rings
    ride one carry with equal intervals, XLA CSEs the shared scalar
    reductions and the two row appends cost one boundary test.  The row
    store is a MASKED add-a-delta scatter, never a lax.cond: the
    [S, T, m] buffer must not ride any cond output (it joins the
    cond-payload forbidden set), and the row itself is a handful of
    [T]-lane reads — noise next to a quantum.

    Under a tile-sharded `px` (the round-18 2D batch x tile campaign)
    the ring's tile axis shards with the directory: `ps.buf` is this
    device's [S, Tl, m] block and `ps.prev` its [Tl, m] snapshot, so
    the full [T, m] row — computed from replicated carry state — is
    sliced to the local lanes before the append (the cursors and the
    [S] times ring stay replicated).  The reassembled-on-fetch ring is
    bit-identical to the solo recording by construction.
    """
    ps = state.profile
    if ps is None:
        raise ValueError(
            "profile spec given but SimState.profile is None "
            "(init the state with obs.init_profile)")
    done = state.done
    clocks = state.core.clock_ps
    all_done = jnp.all(done)
    pending_min = jnp.min(jnp.where(~done, clocks,
                                    jnp.asarray(_BIG, I64)))
    sim_time = jnp.where(all_done, jnp.max(clocks), pending_min)

    cur = _tile_series_values(spec, state, dvfs)           # [T, m]
    if px is not None and px.sharded:
        cur = px.lo(cur)                                   # [Tl, m]
    do = (sim_time >= ps.next_ps) | all_done
    mask = jnp.asarray(spec.delta_mask())                  # [m]
    row = jnp.where(mask[None, :], cur - ps.prev, cur)
    S = int(spec.n_samples)
    slot = (ps.count % S).astype(jnp.int32)
    # add-a-delta under mask: the scatter is the ring's only use, so
    # XLA updates the loop-carried buffer in place (no per-quantum copy)
    buf = ps.buf.at[slot].add(jnp.where(do, row - ps.buf[slot], 0))
    times = ps.times.at[slot].add(
        jnp.where(do, sim_time - ps.times[slot], 0))
    interval = jnp.asarray(int(spec.sample_interval_ps), I64)
    return ps.replace(
        buf=buf,
        times=times,
        prev=jnp.where(do, cur, ps.prev),
        count=ps.count + do.astype(jnp.int32),
        next_ps=jnp.where(do, (sim_time // interval + 1) * interval,
                          ps.next_ps),
    )


# ---------------------------------------------------------------------------
# host-side per-tile profile (post-run demux)
# ---------------------------------------------------------------------------


def grid_shape(n_tiles: int) -> "tuple[int, int]":
    """(rows, cols) of the near-square tile grid heatmaps render —
    matches the emesh topology convention (width = ceil(sqrt(T)))."""
    cols = int(np.ceil(np.sqrt(max(int(n_tiles), 1))))
    rows = int(np.ceil(int(n_tiles) / cols))
    return rows, cols


def gini(values) -> float:
    """Gini coefficient of a non-negative per-tile distribution — the
    traffic-imbalance scalar the straggler summary reports (0 = fully
    balanced, -> 1 = one tile carries everything)."""
    x = np.sort(np.asarray(values, dtype=np.float64))
    n = x.size
    total = x.sum()
    if n == 0 or total == 0:
        return 0.0
    # mean absolute difference via the sorted-rank identity
    ranks = np.arange(1, n + 1)
    return float((2 * (ranks * x).sum() / (n * total)) - (n + 1) / n)


@dataclasses.dataclass
class TileProfile:
    """One sim's recorded per-tile profile, demuxed to chronological
    host rows.

    `data[i, t, j]` is sample i, tile t of series `series[j]`; delta
    series hold since-previous-sample deltas, level series sampled
    values.  `times_ps[i]` is sample i's simulated time.  When the run
    took more than S samples the ring wrapped: `data` holds the LAST S
    samples and `n_total` the true count (`wrapped` flags the loss)."""

    series: "tuple[str, ...]"
    data: np.ndarray          # int64[n_recorded, T, n_series]
    times_ps: np.ndarray      # int64[n_recorded]
    n_total: int
    sample_interval_ps: int
    wrapped: bool = False

    @classmethod
    def from_host_state(cls, spec: ProfileSpec, buf: np.ndarray,
                        times: np.ndarray, count: int) -> "TileProfile":
        S = int(spec.n_samples)
        count = int(count)
        buf = np.asarray(buf)
        times = np.asarray(times)
        if count <= S:
            data = buf[:count].copy()
            tp = times[:count].copy()
            wrapped = False
        else:
            slot = count % S
            data = np.concatenate([buf[slot:], buf[:slot]], axis=0)
            tp = np.concatenate([times[slot:], times[:slot]], axis=0)
            wrapped = True
        return cls(series=tuple(spec.series), data=data, times_ps=tp,
                   n_total=count,
                   sample_interval_ps=int(spec.sample_interval_ps),
                   wrapped=wrapped)

    def __len__(self) -> int:
        return self.data.shape[0]

    @property
    def n_tiles(self) -> int:
        return self.data.shape[1]

    def col(self, name: str) -> np.ndarray:
        """int64[n_recorded, T] — one series across all samples."""
        return self.data[:, :, self.series.index(name)]

    @property
    def time_ns(self) -> np.ndarray:
        return self.times_ps // 1000

    def tile_slice(self, name: str, sample: "int | str" = "total"
                   ) -> np.ndarray:
        """One [T] vector of series `name`: sample index (negative from
        the end), "last", or "total" (delta series sum over samples;
        level series take the last sample — a level has no meaningful
        sum)."""
        col = self.col(name)
        if isinstance(sample, str):
            if sample == "last":
                return col[-1]
            if sample == "total":
                if name in PROFILE_LEVEL_SERIES:
                    return col[-1]
                return col.sum(axis=0)
            raise ValueError(
                f"sample must be an index, 'last', or 'total' "
                f"(got {sample!r})")
        return col[int(sample)]

    def summary(self) -> dict:
        """Straggler/imbalance scalars for bench/CI JSON: per-tile skew
        distribution (max/mean over the whole run, leader + straggler
        tile ids) and traffic concentration (Gini + hottest tile)."""
        out = {
            "samples": int(len(self)),
            "samples_total": int(self.n_total),
            "wrapped": bool(self.wrapped),
            "n_tiles": int(self.n_tiles),
        }
        if len(self) == 0:
            return out
        if "clock_skew_ps" in self.series:
            skew = self.col("clock_skew_ps")
            mean_by_tile = skew.mean(axis=0)
            out["max_skew_ps"] = int(skew.max())
            out["mean_skew_ps"] = float(skew.mean())
            # the laggard everyone waits for has skew ~0; the leader
            # runs furthest ahead of it
            out["straggler_tile"] = int(mean_by_tile.argmin())
            out["leader_tile"] = int(mean_by_tile.argmax())
        for name, key in (("packets_sent", "traffic"),
                          ("l2_misses", "miss")):
            if name in self.series:
                totals = self.tile_slice(name, "total")
                out[f"{key}_gini"] = round(gini(totals), 6)
                out[f"hot_{key}_tile"] = int(totals.argmax())
                out[f"hot_{key}_total"] = int(totals.max())
        return out

    def json_rows(self, series=None, sample: "int | str | None" = None
                  ) -> "list[dict]":
        """One JSON-able dict per (sample, series) with the full [T]
        tile vector — the heatmap CLI's machine rows.  `sample`
        restricts to one time slice ("total"/"last"/index); None emits
        every recorded sample."""
        names = tuple(series) if series else self.series
        rows = []
        if sample is not None:
            for s in names:
                rows.append({"sample": sample
                             if isinstance(sample, str) else int(sample),
                             "series": s,
                             "tiles": [int(v) for v in
                                       self.tile_slice(s, sample)]})
            return rows
        for i in range(len(self)):
            base = int(self.n_total - len(self) + i)
            for s in names:
                j = self.series.index(s)
                rows.append({"sample": base,
                             "time_ns": int(self.time_ns[i]),
                             "series": s,
                             "tiles": [int(v)
                                       for v in self.data[i, :, j]]})
        return rows

    def save(self, path: str) -> None:
        np.savez(path, data=self.data, times_ps=self.times_ps,
                 series=np.array(self.series),
                 n_total=self.n_total,
                 sample_interval_ps=self.sample_interval_ps,
                 wrapped=self.wrapped)

    @classmethod
    def load(cls, path: str) -> "TileProfile":
        z = np.load(path, allow_pickle=False)
        return cls(series=tuple(str(s) for s in z["series"]),
                   data=np.asarray(z["data"]),
                   times_ps=np.asarray(z["times_ps"]),
                   n_total=int(z["n_total"]),
                   sample_interval_ps=int(z["sample_interval_ps"]),
                   wrapped=bool(z["wrapped"]))


def profile_from_state(spec: ProfileSpec, pstate) -> TileProfile:
    """Fetch + demux one sim's ProfileState (device or host pytree)."""
    buf, times, count = jax.device_get(
        (pstate.buf, pstate.times, pstate.count))
    return TileProfile.from_host_state(spec, np.asarray(buf),
                                       np.asarray(times), int(count))


def demux_profiles(spec: ProfileSpec, pstate) -> "list[TileProfile]":
    """Demux a batched [B, ...] ProfileState (vmapped campaign or the
    batch-axis shard_map gather) into B per-sim TileProfiles.

    `pstate` may also be the already-fetched (buf, times, count) host
    triple — SweepRunner passes the arrays from its ONE batched
    device→host fetch, so this is the single demux implementation
    every campaign path shares."""
    parts = (tuple(pstate) if isinstance(pstate, (tuple, list))
             else (pstate.buf, pstate.times, pstate.count))
    buf, times, count = (np.asarray(x)
                         for x in jax.device_get(parts))
    return [TileProfile.from_host_state(spec, buf[b], times[b],
                                        int(count[b]))
            for b in range(buf.shape[0])]
