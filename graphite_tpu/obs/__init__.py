"""Observability: device-resident telemetry timelines.

  TelemetrySpec      — what to record (interval, ring depth S, series)
  TelemetryState     — the [S, n_series] ring riding SimState.telemetry
  telemetry_tick     — the outer quantum loop's per-quantum update
  Timeline           — one sim's demuxed chronological host rows
  demux_timelines    — [B, ...] campaign state -> B Timelines

    spec = TelemetrySpec(sample_interval_ps=10_000_000)   # 10 us
    sim = Simulator(cfg, batch, telemetry=spec)
    res = sim.run()
    res.telemetry.summary()   # peak injection, clock spread, ...

`telemetry=None` (the default) lowers to a bit-identical program —
jaxpr-asserted in tests/test_telemetry.py and enforced by the
`telemetry-off` audit lint (`python -m graphite_tpu.tools.audit`).
"""

from graphite_tpu.obs.telemetry import (  # noqa: F401
    CORE_SERIES, LEVEL_SERIES, MEM_SERIES, SKIP_PREFIX, Timeline,
    TelemetrySpec, TelemetryState, available_series, demux_timelines,
    init_telemetry, telemetry_tick, timeline_from_state,
)

__all__ = [
    "CORE_SERIES",
    "LEVEL_SERIES",
    "MEM_SERIES",
    "SKIP_PREFIX",
    "Timeline",
    "TelemetrySpec",
    "TelemetryState",
    "available_series",
    "demux_timelines",
    "init_telemetry",
    "telemetry_tick",
    "timeline_from_state",
]
