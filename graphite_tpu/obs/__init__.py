"""Observability: device telemetry timelines + host spans and metrics.

Device side (round 9 + 14):

  TelemetrySpec      — what to record (interval, ring depth S, series)
  EnergyPrices       — per-event pJ prices enabling the energy_pj series
  TelemetryState     — the [S, n_series] ring riding SimState.telemetry
  telemetry_tick     — the outer quantum loop's per-quantum update
  Timeline           — one sim's demuxed chronological host rows
  demux_timelines    — [B, ...] campaign state -> B Timelines

    spec = TelemetrySpec(sample_interval_ps=10_000_000)   # 10 us
    sim = Simulator(cfg, batch, telemetry=spec)
    res = sim.run()
    res.telemetry.summary()   # peak injection, clock spread, ...

`telemetry=None` (the default) lowers to a bit-identical program —
jaxpr-asserted in tests/test_telemetry.py and enforced by the
`telemetry-off` audit lint (`python -m graphite_tpu.tools.audit`).
`energy_prices` is opt-in, so the dense default selection (and every
locked program fingerprint) is unchanged by the energy series.

Spatial profiler (round 16):

  ProfileSpec        — what to record PER TILE (interval, S, series)
  ProfileState       — the [S, T, m] ring riding SimState.profile
  profile_tick       — the outer quantum loop's per-tile row append
  TileProfile        — one sim's demuxed per-tile host rows (heatmap
                       input; `tools/report.py --heatmap`)
  demux_profiles     — [B, ...] campaign state -> B TileProfiles

    prof = ProfileSpec(sample_interval_ps=10_000_000)
    sim = Simulator(cfg, batch, profile=prof)
    res = sim.run()
    res.profile.summary()   # max/mean skew, straggler tile, Gini

`profile=None` (the default) lowers the same bit-identical program —
enforced by the `profile-off` audit lint.

Latency histograms (round 21):

  HistSpec           — what to bucket (sources, edges, per_tile)
  HistState          — the int64 [H, B] / [T, H, B] bucket-count ring
                       riding SimState.hist
  hist_commit_update — the commit site's masked scatter-add
  hist_boundary_tick — the outer loop's per-quantum skew/energy sample
  Hist               — one sim's fetched counts (+ deterministic
                       p50/p95/p99 via the shared bucket_quantile)
  demux_hists        — [B, ...] campaign state -> B Hists
  conservation_totals — histogram total vs matching cumulative counter

    hist = HistSpec()                 # dense: every available source
    sim = Simulator(cfg, batch, hist=hist)
    res = sim.run()
    res.hist.quantile("miss_lat_ps", 0.99)

`hist=None` (the default) lowers the same bit-identical program —
enforced by the `hist-off` audit lint.

Host side (round 14, consumed by serve/service.py):

  MetricsRegistry    — counters / gauges / fixed-bucket histograms with
                       deterministic p50/p90/p99, Prometheus text +
                       JSON snapshot exporters, a sampled timeline
  Tracer / Span      — job-lifecycle span tracing (submit → ... → emit)
                       with JSON-lines export and terminal-completeness
                       checking

Both take an injectable monotonic clock, so tests pin exact latencies
on a fake clock; neither ever touches a traced program (tracing on/off
serve results are bit-equal, regress-pinned).
"""

from graphite_tpu.obs.hist import (  # noqa: F401
    HIST_BOUNDARY_SOURCES, HIST_CORE_SOURCES, HIST_ENERGY_SOURCES,
    HIST_MEM_SOURCES, Hist, HistSpec, HistState, available_hist_sources,
    conservation_totals, demux_hists, hist_boundary_tick,
    hist_commit_update, hist_from_state, init_hist,
)
from graphite_tpu.obs.metrics import (  # noqa: F401
    Counter, DEFAULT_COUNT_BUCKETS, DEFAULT_LATENCY_BUCKETS, Gauge,
    Histogram, MetricsError, MetricsRegistry, RATIO_BUCKETS,
    bucket_quantile, parse_exposition,
)
from graphite_tpu.obs.telemetry import (  # noqa: F401
    CORE_SERIES, ENERGY_SERIES, EnergyPrices, LEVEL_SERIES, MEM_SERIES,
    SKIP_PREFIX, Timeline, TelemetrySpec, TelemetryState,
    available_series, demux_timelines, init_telemetry, telemetry_tick,
    timeline_from_state,
)
from graphite_tpu.obs.profile import (  # noqa: F401
    PROFILE_CORE_SERIES, PROFILE_ENERGY_SERIES, PROFILE_LEVEL_SERIES,
    PROFILE_MEM_SERIES, ProfileSpec, ProfileState, TileProfile,
    available_tile_series, demux_profiles, gini, grid_shape,
    init_profile, profile_from_state, profile_tick,
)
from graphite_tpu.obs.trace import (  # noqa: F401
    JOB_SPANS, Span, TERMINAL_SPANS, Tracer, job_breakdown, load_jsonl,
)

__all__ = [
    "CORE_SERIES",
    "Counter",
    "DEFAULT_COUNT_BUCKETS",
    "DEFAULT_LATENCY_BUCKETS",
    "ENERGY_SERIES",
    "EnergyPrices",
    "Gauge",
    "HIST_BOUNDARY_SOURCES",
    "HIST_CORE_SOURCES",
    "HIST_ENERGY_SOURCES",
    "HIST_MEM_SOURCES",
    "Hist",
    "HistSpec",
    "HistState",
    "Histogram",
    "JOB_SPANS",
    "LEVEL_SERIES",
    "MEM_SERIES",
    "MetricsError",
    "MetricsRegistry",
    "PROFILE_CORE_SERIES",
    "PROFILE_ENERGY_SERIES",
    "PROFILE_LEVEL_SERIES",
    "PROFILE_MEM_SERIES",
    "ProfileSpec",
    "ProfileState",
    "RATIO_BUCKETS",
    "SKIP_PREFIX",
    "Span",
    "TERMINAL_SPANS",
    "Timeline",
    "TelemetrySpec",
    "TelemetryState",
    "TileProfile",
    "Tracer",
    "available_hist_sources",
    "available_series",
    "available_tile_series",
    "bucket_quantile",
    "conservation_totals",
    "demux_hists",
    "demux_profiles",
    "demux_timelines",
    "gini",
    "grid_shape",
    "hist_boundary_tick",
    "hist_commit_update",
    "hist_from_state",
    "init_hist",
    "init_profile",
    "init_telemetry",
    "job_breakdown",
    "load_jsonl",
    "parse_exposition",
    "profile_from_state",
    "profile_tick",
    "telemetry_tick",
    "timeline_from_state",
]
