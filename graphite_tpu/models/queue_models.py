"""Vectorized contention queue models.

Reference: `common/shared_models/queue_models/` (SURVEY §2.8) — used by the
DRAM controller (`dram_perf_model.cc:95-100`) and the per-port NoC router
contention models (`components/router/router_model.h`).

Four reference models:
 - **basic** (`queue_model_basic.cc`): delay = max(0, queue_time - ref);
   queue_time = max(queue_time, ref) + processing; ref optionally a moving
   average of recent packet times (`[queue_model/basic]`).
 - **m_g_1** (`queue_model_m_g_1.cc`): analytical M/G/1 waiting time from
   running service-time moments.
 - **history_list / history_tree** (`queue_model_history_list.cc`,
   `queue_model_history_tree.cc:44-128`): free-interval bookkeeping with an
   M/G/1 fallback for packets older than the tracked window.  The interval
   list/tree is inherently sequential (SURVEY §7 hard part 3); the
   TPU-native form here is a **windowed tail** model: in-window packets get
   exact tail-append delays (equal to the list model when packets arrive in
   nondecreasing order, which the quantum engine's earliest-first message
   draining approximates), and packets that fall entirely before the
   tracked window use the same M/G/1 fallback.  Divergence is validated on
   synthetic traffic sweeps (tests/test_queue_models.py).

All state is struct-of-arrays over a leading queue axis; one call services
one packet per queue lane (masked), which is how the engines drive it (one
DRAM access per controller per subquantum iteration, one packet per router
port per iteration).

Masked-no-op invariant (load-bearing for the memory engines' per-phase
activity gating): a call whose mask is all-False leaves the queue state
BIT-IDENTICAL — masked lanes route to the scratch queue / contribute
zero deltas and max-with-zero against nonnegative times, never a real
mutation.  The gated engine phases (memory/engine.py, MemParams.
phase_gate) skip whole calls whose masks are provably all-False; that
skip is only bit-exact because of this invariant, so any new queue-state
write added here must preserve it.

Times are integer ns (the reference computes queue delays in ns/cycles at
1 GHz — `dram_perf_model.cc:80-91`).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import struct

I64 = jnp.int64
F64 = jnp.float64


@dataclasses.dataclass(frozen=True)
class QueueParams:
    kind: str = "history_tree"   # basic | m_g_1 | history_list | history_tree
    # [queue_model/basic]
    moving_avg_enabled: bool = True
    moving_avg_window: int = 64
    # [queue_model/history_list] / [queue_model/history_tree]
    max_list_size: int = 100
    analytical_enabled: bool = True
    # minimum processing time: sizes the tracked-history span
    min_processing_time: int = 1

    @classmethod
    def from_config(cls, cfg, kind: str, min_processing_time: int = 1):
        if kind in ("history_list", "history_tree"):
            sec = f"queue_model/{kind}"
            return cls(
                kind=kind,
                max_list_size=cfg.get_int(f"{sec}/max_list_size", 100),
                analytical_enabled=cfg.get_bool(
                    f"{sec}/analytical_model_enabled", True),
                min_processing_time=min_processing_time,
            )
        if kind == "basic":
            return cls(
                kind="basic",
                moving_avg_enabled=cfg.get_bool(
                    "queue_model/basic/moving_avg_enabled", False),
                moving_avg_window=cfg.get_int(
                    "queue_model/basic/moving_avg_window_size", 1),
                min_processing_time=min_processing_time,
            )
        if kind == "m_g_1":
            return cls(kind="m_g_1", min_processing_time=min_processing_time)
        raise ValueError(f"unknown queue model {kind!r}")

    @property
    def history_span(self) -> int:
        """Approximate span of the reference's interval list: at least
        max_list_size busy intervals of >= min_processing_time each."""
        return self.max_list_size * max(self.min_processing_time, 1)


# column layout of QueueArrays.data — one packed [N, 10] tensor so the
# scatter path (NoC router hops) costs 2 gathers + 4 scatters instead of
# ~19 per-field kernels (the engine is launch-count-bound; see PERF.md)
COL_QT = 0        # queue_time: end of the busy tail
COL_WS = 1        # window_start: oldest tracked time (history_*)
COL_NEWEST = 2    # newest_arrival (M/G/1 moments)
COL_SUM_ST = 3
COL_SUM_ST2 = 4
COL_N_ARR = 5
COL_REQS = 6      # total_requests (`updateQueueUtilizationCounters`)
COL_UTIL = 7      # total_utilized
COL_DELAY = 8     # total_delay
COL_ANA = 9       # analytical_used
N_COLS = 10


@struct.dataclass
class QueueArrays:
    """State for N independent queues (packed; see column layout above)."""

    data: jax.Array             # int64[N, 10]
    # moving average of packet times (basic, arithmetic mean over W)
    mavg_buf: jax.Array         # int64[N, W]
    mavg_pos: jax.Array         # int32[N]
    mavg_cnt: jax.Array         # int32[N]

    # read-only views (summaries, tests)
    @property
    def queue_time(self) -> jax.Array:
        return self.data[:, COL_QT]

    @property
    def window_start(self) -> jax.Array:
        return self.data[:, COL_WS]

    @property
    def newest_arrival(self) -> jax.Array:
        return self.data[:, COL_NEWEST]

    @property
    def sum_st(self) -> jax.Array:
        return self.data[:, COL_SUM_ST]

    @property
    def sum_st2(self) -> jax.Array:
        return self.data[:, COL_SUM_ST2]

    @property
    def n_arrivals(self) -> jax.Array:
        return self.data[:, COL_N_ARR]

    @property
    def total_requests(self) -> jax.Array:
        return self.data[:, COL_REQS]

    @property
    def total_utilized(self) -> jax.Array:
        return self.data[:, COL_UTIL]

    @property
    def total_delay(self) -> jax.Array:
        return self.data[:, COL_DELAY]

    @property
    def analytical_used(self) -> jax.Array:
        return self.data[:, COL_ANA]


def make_queues(n: int, params: QueueParams) -> QueueArrays:
    W = params.moving_avg_window if (
        params.kind == "basic" and params.moving_avg_enabled) else 1
    return QueueArrays(
        data=jnp.zeros((n, N_COLS), I64),
        mavg_buf=jnp.zeros((n, W), I64),
        mavg_pos=jnp.zeros(n, jnp.int32),
        mavg_cnt=jnp.zeros(n, jnp.int32),
    )


def _mg1_wait(n_arrivals, sum_st, sum_st2, newest_arrival) -> jax.Array:
    """`queue_model_m_g_1.cc:18-47` waiting-time formula, elementwise over
    running moments (shared by the lane-per-queue and scatter paths)."""
    n = n_arrivals.astype(F64)
    have = n_arrivals > 0
    n_safe = jnp.where(have, n, 1.0)
    mean_st = sum_st.astype(F64) / n_safe
    var_st = sum_st2.astype(F64) / n_safe - mean_st * mean_st
    service_rate = 1.0 / jnp.maximum(mean_st, 1e-12)
    arrival_rate = n / jnp.maximum(newest_arrival.astype(F64), 1e-12)
    arrival_rate = jnp.minimum(arrival_rate, 0.999 * service_rate)
    wait = 0.5 * service_rate * arrival_rate * (
        1.0 / (service_rate * service_rate) + var_st
    ) / (service_rate - arrival_rate)
    return jnp.where(have, jnp.ceil(wait), 0.0).astype(I64)


def _mg1_delay(q: QueueArrays) -> jax.Array:
    return _mg1_wait(q.n_arrivals, q.sum_st, q.sum_st2, q.newest_arrival)


def compute_queue_delay(
    params: QueueParams,
    q: QueueArrays,
    pkt_time: jax.Array,      # int64[N]
    processing_time: jax.Array,  # int64[N]
    mask: jax.Array,          # bool[N] lanes with a packet this call
):
    """Vectorized `QueueModel::computeQueueDelay` (`queue_model.h:20`).

    Returns (new_state, delay int64[N]).  Each lane services its own queue
    (pure elementwise column math on the packed state — one fused kernel).
    """
    pkt_time = jnp.asarray(pkt_time, I64)
    proc = jnp.maximum(jnp.asarray(processing_time, I64), 1)
    qt = q.queue_time
    ws = q.window_start
    newest = q.newest_arrival

    if params.kind == "basic":
        if params.moving_avg_enabled:
            W = params.moving_avg_window
            n = q.mavg_buf.shape[0]
            lanes = jnp.arange(n)
            buf = q.mavg_buf.at[lanes, q.mavg_pos].set(
                jnp.where(mask, pkt_time, q.mavg_buf[lanes, q.mavg_pos]))
            cnt = jnp.minimum(q.mavg_cnt + mask.astype(jnp.int32), W)
            ref = jnp.where(
                cnt > 0, buf.sum(axis=1) // jnp.maximum(cnt, 1), pkt_time
            ).astype(I64)
            q = q.replace(
                mavg_buf=buf,
                mavg_pos=jnp.where(mask, (q.mavg_pos + 1) % W, q.mavg_pos),
                mavg_cnt=cnt,
            )
        else:
            ref = pkt_time
        delay = jnp.maximum(qt - ref, 0)
        new_qt = jnp.where(mask, jnp.maximum(qt, ref) + proc, qt)
        new_ws = ws
        mg1_mask = jnp.zeros_like(mask)
        analytical = jnp.zeros_like(mask)

    elif params.kind == "m_g_1":
        delay = _mg1_delay(q)
        new_qt = qt
        new_ws = ws
        mg1_mask = mask
        analytical = mask

    else:  # history_list / history_tree (windowed tail + M/G/1 fallback)
        too_old = params.analytical_enabled & (
            (pkt_time + proc) < ws)
        mg1 = _mg1_delay(q)
        tail = jnp.maximum(qt - pkt_time, 0)
        delay = jnp.where(too_old, mg1, tail)
        in_window = mask & ~too_old
        cand_qt = jnp.maximum(qt, pkt_time) + proc
        new_qt = jnp.where(in_window, cand_qt, qt)
        new_ws = jnp.where(
            in_window,
            jnp.maximum(ws, cand_qt - params.history_span), ws)
        mg1_mask = mask
        analytical = mask & too_old

    end = pkt_time + delay + proc
    new_data = jnp.stack([
        new_qt,
        new_ws,
        jnp.where(mg1_mask, jnp.maximum(newest, end), newest),
        q.sum_st + jnp.where(mg1_mask, proc, 0),
        q.sum_st2 + jnp.where(mg1_mask, proc * proc, 0),
        q.n_arrivals + mg1_mask.astype(I64),
        q.total_requests + mask.astype(I64),
        q.total_utilized + jnp.where(mask, proc, 0),
        q.total_delay + jnp.where(mask, delay, 0),
        q.analytical_used + analytical.astype(I64),
    ], axis=1)
    return q.replace(data=new_data), jnp.where(mask, delay, 0)


def scatter_queue_delay(
    params: QueueParams,
    q: QueueArrays,
    qid: jax.Array,           # int32[L] queue index per lane (may repeat)
    pkt_time: jax.Array,      # int64[L]
    processing_time: jax.Array,  # int64[L]
    mask: jax.Array,          # bool[L]
):
    """Queue delay where lanes address arbitrary (possibly shared) queues.

    Used by the NoC router ports: several packets can traverse the same
    output port in one vectorized hop step.  Same-call conflicts read the
    same pre-state (each gets the tail delay as of the call) while
    occupancy accumulates exactly (scatter-max of arrival then scatter-add
    of every processing time), so the busy tail — and therefore every
    *later* packet's delay — stays exact; only simultaneous arrivals at
    one port underestimate each other's mutual wait.  Bounded, documented
    divergence vs the reference's strictly serial
    `computeQueueDelay` (`queue_model.h:20`).

    Lanes must route masked-off traffic to a scratch queue (last index).
    """
    pkt_time = jnp.asarray(pkt_time, I64)
    proc = jnp.maximum(jnp.asarray(processing_time, I64), 1)
    N = q.data.shape[0]
    qid = jnp.where(mask, qid, N - 1).astype(jnp.int32)

    row = q.data[qid]                               # [L, 10] — ONE gather
    qt = row[:, COL_QT]
    if params.kind in ("history_list", "history_tree"):
        too_old = params.analytical_enabled & (
            (pkt_time + proc) < row[:, COL_WS])
        # M/G/1 fallback from the queue's running moments (gathered view)
        mg1 = _mg1_wait(row[:, COL_N_ARR], row[:, COL_SUM_ST],
                        row[:, COL_SUM_ST2], row[:, COL_NEWEST])
        tail = jnp.maximum(qt - pkt_time, 0)
        delay = jnp.where(too_old, mg1, tail)
        in_window = mask & ~too_old
    else:  # basic semantics (no moving average in scatter form)
        delay = jnp.maximum(qt - pkt_time, 0)
        in_window = mask
        too_old = jnp.zeros_like(mask)

    # occupancy: scatter-max the arrival then scatter-add every processing
    data = q.data.at[qid, COL_QT].max(jnp.where(in_window, pkt_time, 0))
    data = data.at[qid, COL_QT].add(jnp.where(in_window, proc, 0))
    qt_new = data[qid, COL_QT]
    end = pkt_time + delay + proc
    # one combined max-scatter for (window_start, newest_arrival) ...
    max_vals = jnp.stack([
        jnp.where(in_window, qt_new - params.history_span, -(2**62)),
        jnp.where(mask, end, 0),
    ], axis=1)
    data = data.at[qid[:, None],
                   jnp.asarray([COL_WS, COL_NEWEST])[None, :]].max(max_vals)
    # ... and one combined add-scatter for the moments + counters
    add_vals = jnp.stack([
        jnp.where(mask, proc, 0),
        jnp.where(mask, proc * proc, 0),
        mask.astype(I64),
        mask.astype(I64),
        jnp.where(mask, proc, 0),
        jnp.where(mask, delay, 0),
        (mask & too_old).astype(I64),
    ], axis=1)
    data = data.at[
        qid[:, None],
        jnp.asarray([COL_SUM_ST, COL_SUM_ST2, COL_N_ARR, COL_REQS,
                     COL_UTIL, COL_DELAY, COL_ANA])[None, :]].add(add_vals)
    return q.replace(data=data), jnp.where(mask, delay, 0)
