"""Vectorized contention queue models.

Reference: `common/shared_models/queue_models/` (SURVEY §2.8) — used by the
DRAM controller (`dram_perf_model.cc:95-100`) and the per-port NoC router
contention models (`components/router/router_model.h`).

Four reference models:
 - **basic** (`queue_model_basic.cc`): delay = max(0, queue_time - ref);
   queue_time = max(queue_time, ref) + processing; ref optionally a moving
   average of recent packet times (`[queue_model/basic]`).
 - **m_g_1** (`queue_model_m_g_1.cc`): analytical M/G/1 waiting time from
   running service-time moments.
 - **history_list / history_tree** (`queue_model_history_list.cc`,
   `queue_model_history_tree.cc:44-128`): free-interval bookkeeping with an
   M/G/1 fallback for packets older than the tracked window.  The interval
   list/tree is inherently sequential (SURVEY §7 hard part 3); the
   TPU-native form here is a **windowed tail** model: in-window packets get
   exact tail-append delays (equal to the list model when packets arrive in
   nondecreasing order, which the quantum engine's earliest-first message
   draining approximates), and packets that fall entirely before the
   tracked window use the same M/G/1 fallback.  Divergence is validated on
   synthetic traffic sweeps (tests/test_queue_models.py).

All state is struct-of-arrays over a leading queue axis; one call services
one packet per queue lane (masked), which is how the engines drive it (one
DRAM access per controller per subquantum iteration, one packet per router
port per iteration).

Times are integer ns (the reference computes queue delays in ns/cycles at
1 GHz — `dram_perf_model.cc:80-91`).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import struct

I64 = jnp.int64
F64 = jnp.float64


@dataclasses.dataclass(frozen=True)
class QueueParams:
    kind: str = "history_tree"   # basic | m_g_1 | history_list | history_tree
    # [queue_model/basic]
    moving_avg_enabled: bool = True
    moving_avg_window: int = 64
    # [queue_model/history_list] / [queue_model/history_tree]
    max_list_size: int = 100
    analytical_enabled: bool = True
    # minimum processing time: sizes the tracked-history span
    min_processing_time: int = 1

    @classmethod
    def from_config(cls, cfg, kind: str, min_processing_time: int = 1):
        if kind in ("history_list", "history_tree"):
            sec = f"queue_model/{kind}"
            return cls(
                kind=kind,
                max_list_size=cfg.get_int(f"{sec}/max_list_size", 100),
                analytical_enabled=cfg.get_bool(
                    f"{sec}/analytical_model_enabled", True),
                min_processing_time=min_processing_time,
            )
        if kind == "basic":
            return cls(
                kind="basic",
                moving_avg_enabled=cfg.get_bool(
                    "queue_model/basic/moving_avg_enabled", False),
                moving_avg_window=cfg.get_int(
                    "queue_model/basic/moving_avg_window_size", 1),
                min_processing_time=min_processing_time,
            )
        if kind == "m_g_1":
            return cls(kind="m_g_1", min_processing_time=min_processing_time)
        raise ValueError(f"unknown queue model {kind!r}")

    @property
    def history_span(self) -> int:
        """Approximate span of the reference's interval list: at least
        max_list_size busy intervals of >= min_processing_time each."""
        return self.max_list_size * max(self.min_processing_time, 1)


@struct.dataclass
class QueueArrays:
    """State for N independent queues."""

    queue_time: jax.Array       # int64[N] end of the busy tail
    window_start: jax.Array     # int64[N] oldest tracked time (history_*)
    # moving average of packet times (basic, arithmetic mean over W)
    mavg_buf: jax.Array         # int64[N, W]
    mavg_pos: jax.Array         # int32[N]
    mavg_cnt: jax.Array         # int32[N]
    # M/G/1 running moments (`queue_model_m_g_1.cc`)
    sum_st: jax.Array           # int64[N]
    sum_st2: jax.Array          # int64[N]
    n_arrivals: jax.Array       # int64[N]
    newest_arrival: jax.Array   # int64[N]
    # counters (`QueueModel::updateQueueUtilizationCounters`)
    total_requests: jax.Array   # int64[N]
    total_utilized: jax.Array   # int64[N]
    total_delay: jax.Array      # int64[N]
    analytical_used: jax.Array  # int64[N]


def make_queues(n: int, params: QueueParams) -> QueueArrays:
    W = params.moving_avg_window if (
        params.kind == "basic" and params.moving_avg_enabled) else 1
    return QueueArrays(
        queue_time=jnp.zeros(n, I64),
        window_start=jnp.zeros(n, I64),
        mavg_buf=jnp.zeros((n, W), I64),
        mavg_pos=jnp.zeros(n, jnp.int32),
        mavg_cnt=jnp.zeros(n, jnp.int32),
        sum_st=jnp.zeros(n, I64),
        sum_st2=jnp.zeros(n, I64),
        n_arrivals=jnp.zeros(n, I64),
        newest_arrival=jnp.zeros(n, I64),
        total_requests=jnp.zeros(n, I64),
        total_utilized=jnp.zeros(n, I64),
        total_delay=jnp.zeros(n, I64),
        analytical_used=jnp.zeros(n, I64),
    )


def _mg1_wait(n_arrivals, sum_st, sum_st2, newest_arrival) -> jax.Array:
    """`queue_model_m_g_1.cc:18-47` waiting-time formula, elementwise over
    running moments (shared by the lane-per-queue and scatter paths)."""
    n = n_arrivals.astype(F64)
    have = n_arrivals > 0
    n_safe = jnp.where(have, n, 1.0)
    mean_st = sum_st.astype(F64) / n_safe
    var_st = sum_st2.astype(F64) / n_safe - mean_st * mean_st
    service_rate = 1.0 / jnp.maximum(mean_st, 1e-12)
    arrival_rate = n / jnp.maximum(newest_arrival.astype(F64), 1e-12)
    arrival_rate = jnp.minimum(arrival_rate, 0.999 * service_rate)
    wait = 0.5 * service_rate * arrival_rate * (
        1.0 / (service_rate * service_rate) + var_st
    ) / (service_rate - arrival_rate)
    return jnp.where(have, jnp.ceil(wait), 0.0).astype(I64)


def _mg1_delay(q: QueueArrays) -> jax.Array:
    return _mg1_wait(q.n_arrivals, q.sum_st, q.sum_st2, q.newest_arrival)


def _mg1_update(q: QueueArrays, pkt_time, service_time, wait, mask):
    end = pkt_time + wait + service_time
    return q.replace(
        sum_st=q.sum_st + jnp.where(mask, service_time, 0),
        sum_st2=q.sum_st2 + jnp.where(mask, service_time * service_time, 0),
        n_arrivals=q.n_arrivals + mask.astype(I64),
        newest_arrival=jnp.where(
            mask, jnp.maximum(q.newest_arrival, end), q.newest_arrival),
    )


def compute_queue_delay(
    params: QueueParams,
    q: QueueArrays,
    pkt_time: jax.Array,      # int64[N]
    processing_time: jax.Array,  # int64[N]
    mask: jax.Array,          # bool[N] lanes with a packet this call
):
    """Vectorized `QueueModel::computeQueueDelay` (`queue_model.h:20`).

    Returns (new_state, delay int64[N]).  Each lane services its own queue.
    """
    pkt_time = jnp.asarray(pkt_time, I64)
    proc = jnp.maximum(jnp.asarray(processing_time, I64), 1)

    if params.kind == "basic":
        if params.moving_avg_enabled:
            W = params.moving_avg_window
            n = q.mavg_buf.shape[0]
            lanes = jnp.arange(n)
            buf = q.mavg_buf.at[lanes, q.mavg_pos].set(
                jnp.where(mask, pkt_time, q.mavg_buf[lanes, q.mavg_pos]))
            cnt = jnp.minimum(q.mavg_cnt + mask.astype(jnp.int32), W)
            ref = jnp.where(
                cnt > 0, buf.sum(axis=1) // jnp.maximum(cnt, 1), pkt_time
            ).astype(I64)
            q = q.replace(
                mavg_buf=buf,
                mavg_pos=jnp.where(mask, (q.mavg_pos + 1) % W, q.mavg_pos),
                mavg_cnt=cnt,
            )
        else:
            ref = pkt_time
        delay = jnp.maximum(q.queue_time - ref, 0)
        new_qt = jnp.maximum(q.queue_time, ref) + proc
        q = q.replace(
            queue_time=jnp.where(mask, new_qt, q.queue_time))
        analytical = jnp.zeros_like(mask)

    elif params.kind == "m_g_1":
        delay = _mg1_delay(q)
        q = _mg1_update(q, pkt_time, proc, delay, mask)
        analytical = mask

    else:  # history_list / history_tree (windowed tail + M/G/1 fallback)
        too_old = params.analytical_enabled & (
            (pkt_time + proc) < q.window_start)
        mg1 = _mg1_delay(q)
        tail = jnp.maximum(q.queue_time - pkt_time, 0)
        delay = jnp.where(too_old, mg1, tail)
        in_window = mask & ~too_old
        new_qt = jnp.maximum(q.queue_time, pkt_time) + proc
        q = q.replace(
            queue_time=jnp.where(in_window, new_qt, q.queue_time),
            window_start=jnp.where(
                in_window,
                jnp.maximum(q.window_start, new_qt - params.history_span),
                q.window_start),
        )
        q = _mg1_update(q, pkt_time, proc, delay, mask)
        analytical = mask & too_old

    q = q.replace(
        total_requests=q.total_requests + mask.astype(I64),
        total_utilized=q.total_utilized + jnp.where(mask, proc, 0),
        total_delay=q.total_delay + jnp.where(mask, delay, 0),
        analytical_used=q.analytical_used + analytical.astype(I64),
    )
    return q, jnp.where(mask, delay, 0)


def scatter_queue_delay(
    params: QueueParams,
    q: QueueArrays,
    qid: jax.Array,           # int32[L] queue index per lane (may repeat)
    pkt_time: jax.Array,      # int64[L]
    processing_time: jax.Array,  # int64[L]
    mask: jax.Array,          # bool[L]
):
    """Queue delay where lanes address arbitrary (possibly shared) queues.

    Used by the NoC router ports: several packets can traverse the same
    output port in one vectorized hop step.  Same-call conflicts read the
    same pre-state (each gets the tail delay as of the call) while
    occupancy accumulates exactly (scatter-max of arrival then scatter-add
    of every processing time), so the busy tail — and therefore every
    *later* packet's delay — stays exact; only simultaneous arrivals at
    one port underestimate each other's mutual wait.  Bounded, documented
    divergence vs the reference's strictly serial
    `computeQueueDelay` (`queue_model.h:20`).

    Lanes must route masked-off traffic to a scratch queue (last index).
    """
    pkt_time = jnp.asarray(pkt_time, I64)
    proc = jnp.maximum(jnp.asarray(processing_time, I64), 1)
    N = q.queue_time.shape[0]
    qid = jnp.where(mask, qid, N - 1).astype(jnp.int32)

    qt = q.queue_time[qid]
    if params.kind in ("history_list", "history_tree"):
        too_old = params.analytical_enabled & (
            (pkt_time + proc) < q.window_start[qid])
        # M/G/1 fallback from the queue's running moments (gathered view)
        mg1 = _mg1_wait(q.n_arrivals[qid], q.sum_st[qid], q.sum_st2[qid],
                        q.newest_arrival[qid])
        tail = jnp.maximum(qt - pkt_time, 0)
        delay = jnp.where(too_old, mg1, tail)
        in_window = mask & ~too_old
    else:  # basic semantics (no moving average in scatter form)
        delay = jnp.maximum(qt - pkt_time, 0)
        in_window = mask
        too_old = jnp.zeros_like(mask)

    # occupancy: scatter-max the arrival then scatter-add every processing
    end_contrib = jnp.where(in_window, pkt_time, 0)
    queue_time = q.queue_time.at[qid].max(end_contrib)
    queue_time = queue_time.at[qid].add(jnp.where(in_window, proc, 0))
    window_start = q.window_start.at[qid].max(
        jnp.where(in_window, queue_time[qid] - params.history_span, -(2**62)))
    end = pkt_time + delay + proc
    q = q.replace(
        queue_time=queue_time,
        window_start=window_start,
        sum_st=q.sum_st.at[qid].add(jnp.where(mask, proc, 0)),
        sum_st2=q.sum_st2.at[qid].add(jnp.where(mask, proc * proc, 0)),
        n_arrivals=q.n_arrivals.at[qid].add(mask.astype(I64)),
        newest_arrival=q.newest_arrival.at[qid].max(
            jnp.where(mask, end, 0)),
        total_requests=q.total_requests.at[qid].add(mask.astype(I64)),
        total_utilized=q.total_utilized.at[qid].add(jnp.where(mask, proc, 0)),
        total_delay=q.total_delay.at[qid].add(jnp.where(mask, delay, 0)),
        analytical_used=q.analytical_used.at[qid].add(
            (mask & too_old).astype(I64)),
    )
    return q, jnp.where(mask, delay, 0)
