"""DVFS domains: frequency/voltage per module class.

Reference: `common/system/dvfs_manager.{h,cc}` (`dvfs_manager.h:19-88`),
config `[dvfs] domains` (`carbon_sim.cfg:147-155`), per-technology V/f
tables `technology/dvfs_levels_*.cfg`.

Round-1 scope: domain parsing + initial frequencies (consumed by the core
and network models) and the synchronization delay at asynchronous boundary
crossings.  Runtime set_frequency (the DVFS network + voltage scaling +
level tables) is layered on in the DVFSManager engine module.
"""

from __future__ import annotations

import re

from graphite_tpu.config.config_file import ConfigFile
from graphite_tpu.time_types import ghz_to_mhz

# Module classes (`dvfs.h` / `dvfs_manager.cc` domain map)
DVFS_MODULES = (
    "CORE",
    "L1_ICACHE",
    "L1_DCACHE",
    "L2_CACHE",
    "DIRECTORY",
    "NETWORK_USER",
    "NETWORK_MEMORY",
)


def parse_dvfs_domains(cfg: ConfigFile) -> list[tuple[int, list[str]]]:
    """Parse `[dvfs] domains` tuples `<freq_ghz, MODULE, ...>`.

    Returns [(freq_mhz, [modules]), ...] (`carbon_sim.cfg:148-151`).
    """
    text = cfg.get_string(
        "dvfs/domains",
        "<1.0, CORE, L1_ICACHE, L1_DCACHE, L2_CACHE, DIRECTORY, "
        "NETWORK_USER, NETWORK_MEMORY>",
    )
    domains: list[tuple[int, list[str]]] = []
    for tup in re.finditer(r"<([^<>]*)>", text):
        fields = [f.strip() for f in tup.group(1).split(",") if f.strip()]
        if not fields:
            continue
        freq_mhz = ghz_to_mhz(float(fields[0]))
        modules = [m.upper() for m in fields[1:]]
        for m in modules:
            if m not in DVFS_MODULES:
                raise ValueError(f"unknown DVFS module {m!r} in domains")
        domains.append((freq_mhz, modules))
    if not domains:
        raise ValueError("no DVFS domains parsed")
    # every module must belong to exactly one domain
    seen: set[str] = set()
    for _, modules in domains:
        for m in modules:
            if m in seen:
                raise ValueError(f"DVFS module {m} in two domains")
            seen.add(m)
    return domains


def module_freq_mhz(cfg: ConfigFile, module: str) -> int:
    """Initial frequency of the domain containing `module`, default 1 GHz."""
    for freq_mhz, modules in parse_dvfs_domains(cfg):
        if module.upper() in modules:
            return freq_mhz
    return 1000


def module_domain_index(cfg: ConfigFile, module: str) -> int:
    """Index of the domain containing `module` (-1 if unlisted).

    Used for `hasSameDVFSDomain` checks (`dvfs_manager.cc` domain map):
    synchronization delay applies only across different domains.
    """
    for i, (_, modules) in enumerate(parse_dvfs_domains(cfg)):
        if module.upper() in modules:
            return i
    return -1


def synchronization_delay_cycles(cfg: ConfigFile) -> int:
    """Delay crossing asynchronous domain boundaries (`carbon_sim.cfg:153-155`)."""
    return cfg.get_int("dvfs/synchronization_delay", 2)
