"""DVFS domains: frequency/voltage per module class.

Reference: `common/system/dvfs_manager.{h,cc}` (`dvfs_manager.h:19-88`),
config `[dvfs] domains` (`carbon_sim.cfg:147-155`), per-technology V/f
tables `technology/dvfs_levels_*.cfg`.

Round-1 scope: domain parsing + initial frequencies (consumed by the core
and network models) and the synchronization delay at asynchronous boundary
crossings.  Runtime set_frequency (the DVFS network + voltage scaling +
level tables) is layered on in the DVFSManager engine module.
"""

from __future__ import annotations

import re

from graphite_tpu.config.config_file import ConfigFile
from graphite_tpu.time_types import ghz_to_mhz

# Module classes (`dvfs.h` / `dvfs_manager.cc` domain map)
DVFS_MODULES = (
    "CORE",
    "L1_ICACHE",
    "L1_DCACHE",
    "L2_CACHE",
    "DIRECTORY",
    "NETWORK_USER",
    "NETWORK_MEMORY",
)


def parse_dvfs_domains(cfg: ConfigFile) -> list[tuple[int, list[str]]]:
    """Parse `[dvfs] domains` tuples `<freq_ghz, MODULE, ...>`.

    Returns [(freq_mhz, [modules]), ...] (`carbon_sim.cfg:148-151`).
    """
    text = cfg.get_string(
        "dvfs/domains",
        "<1.0, CORE, L1_ICACHE, L1_DCACHE, L2_CACHE, DIRECTORY, "
        "NETWORK_USER, NETWORK_MEMORY>",
    )
    domains: list[tuple[int, list[str]]] = []
    for tup in re.finditer(r"<([^<>]*)>", text):
        fields = [f.strip() for f in tup.group(1).split(",") if f.strip()]
        if not fields:
            continue
        freq_mhz = ghz_to_mhz(float(fields[0]))
        modules = [m.upper() for m in fields[1:]]
        for m in modules:
            if m not in DVFS_MODULES:
                raise ValueError(f"unknown DVFS module {m!r} in domains")
        domains.append((freq_mhz, modules))
    if not domains:
        raise ValueError("no DVFS domains parsed")
    # every module must belong to exactly one domain
    seen: set[str] = set()
    for _, modules in domains:
        for m in modules:
            if m in seen:
                raise ValueError(f"DVFS module {m} in two domains")
            seen.add(m)
    return domains


def module_freq_mhz(cfg: ConfigFile, module: str) -> int:
    """Initial frequency of the domain containing `module`, default 1 GHz."""
    for freq_mhz, modules in parse_dvfs_domains(cfg):
        if module.upper() in modules:
            return freq_mhz
    return 1000


def module_domain_index(cfg: ConfigFile, module: str) -> int:
    """Index of the domain containing `module` (-1 if unlisted).

    Used for `hasSameDVFSDomain` checks (`dvfs_manager.cc` domain map):
    synchronization delay applies only across different domains.
    """
    for i, (_, modules) in enumerate(parse_dvfs_domains(cfg)):
        if module.upper() in modules:
            return i
    return -1


def synchronization_delay_cycles(cfg: ConfigFile) -> int:
    """Delay crossing asynchronous domain boundaries (`carbon_sim.cfg:153-155`)."""
    return cfg.get_int("dvfs/synchronization_delay", 2)


# --------------------------------------------------------------------------
# voltage/frequency levels (`technology/dvfs_levels_*.cfg`,
# `DVFSManager::initializeDVFSLevels`)

# Built-in per-node tables: rows of (voltage V, max-frequency-factor); the
# max frequency at a voltage = factor * [general] max_frequency.  Matches
# the `technology/` table format; a `dvfs_levels_path` config key loads a
# file in that format instead.
_BUILTIN_LEVELS = {
    22: ((1.0, 1.0), (0.96, 0.87), (0.92, 0.75), (0.88, 0.63),
         (0.84, 0.5), (0.8, 0.37)),
    32: ((1.0, 1.0), (0.96, 0.88), (0.92, 0.77), (0.88, 0.65),
         (0.84, 0.54), (0.8, 0.42)),
    45: ((1.0, 1.0), (0.96, 0.89), (0.92, 0.78), (0.88, 0.68),
         (0.84, 0.57), (0.8, 0.46)),
}

# DVFS API return codes (`common/user/dvfs.h:10-17`)
RC_OK = 0
RC_INVALID_TILE = -1
RC_INVALID_DOMAIN = -2
RC_INVALID_VOLTAGE_OPTION = -3
RC_INVALID_FREQUENCY = -4
RC_ABOVE_MAX_FOR_VOLTAGE = -5

AUTO = 0
HOLD = 1


def load_levels(cfg: ConfigFile) -> tuple[tuple[float, float], ...]:
    """(voltage, max-frequency-factor) rows, descending voltage."""
    path = cfg.get_string("general/dvfs_levels_path", "")
    if path:
        rows = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                v, factor = line.split()[:2]
                rows.append((float(v), float(factor)))
        if not rows:
            raise ValueError(f"no DVFS levels in {path!r}")
        return tuple(sorted(rows, key=lambda r: -r[0]))
    node = cfg.get_int("general/technology_node", 22)
    if node not in _BUILTIN_LEVELS:
        raise ValueError(f"no DVFS levels for technology node {node}nm")
    rows = _BUILTIN_LEVELS[node]
    # every consumer assumes descending (voltage, frequency) order
    return tuple(sorted(rows, key=lambda r: -r[0]))


import dataclasses as _dc


@_dc.dataclass(frozen=True)
class DvfsParams:
    """Static DVFS tables for the engine + host API."""

    voltages_mv: tuple          # descending
    max_freq_mhz: tuple         # max frequency at each voltage, descending
    n_domains: int
    core_domain: int            # index of the domain containing CORE
    sync_delay_cycles: int
    domain_freq_mhz: tuple      # initial frequency per domain
    # domain index per DVFS_MODULES entry (unlisted modules fold into
    # domain 0) — lets the runtime DVFS manager map a counter/price term
    # to its operating point without re-parsing the config
    module_domains: tuple = ()

    @classmethod
    def from_config(cls, cfg: ConfigFile) -> "DvfsParams":
        levels = load_levels(cfg)
        max_f = ghz_to_mhz(cfg.get_float("general/max_frequency", 1.0))
        domains = parse_dvfs_domains(cfg)
        core_dom = 0
        for i, (f, modules) in enumerate(domains):
            if "CORE" in modules:
                core_dom = i
            if f > max_f:
                raise ValueError(
                    f"DVFS domain {i} initial frequency {f} MHz exceeds "
                    f"[general] max_frequency ({max_f} MHz)")
        return cls(
            voltages_mv=tuple(int(round(v * 1000)) for v, _ in levels),
            max_freq_mhz=tuple(int(round(f * max_f)) for _, f in levels),
            n_domains=len(domains),
            core_domain=core_dom,
            sync_delay_cycles=synchronization_delay_cycles(cfg),
            domain_freq_mhz=tuple(f for f, _ in domains),
            module_domains=tuple(
                max(module_domain_index(cfg, m), 0) for m in DVFS_MODULES),
        )

    def min_voltage_mv(self, freq_mhz: int) -> int:
        """Lowest voltage supporting `freq_mhz` (`getMinVoltage`), or -1."""
        best = -1
        for v, f in zip(self.voltages_mv, self.max_freq_mhz):
            if freq_mhz <= f:
                best = v
        return best

    def max_freq_at_mv(self, voltage_mv: int) -> int:
        for v, f in zip(self.voltages_mv, self.max_freq_mhz):
            if v == voltage_mv:
                return f
        return 0


class DVFSManager:
    """Host-side DVFS API facade (`dvfs.h` semantics with rc codes).

    Operates on a Simulator's state between/after runs; the in-trace
    DVFS_SET events apply the same table logic on device.
    """

    def __init__(self, sim):
        self._sim = sim
        # the same tables the in-trace DVFS_SET path validates against
        self.params = (sim.params.dvfs if sim.params.dvfs is not None
                       else DvfsParams.from_config(sim.config.cfg))

    def get_domain(self, module: str) -> int:
        idx = module_domain_index(self._sim.config.cfg, module)
        return idx

    def get_dvfs(self, tile_id: int, domain: int):
        """(rc, frequency_ghz, voltage_v)."""
        import numpy as np

        n = self._sim.params.n_tiles
        if tile_id < 0 or tile_id >= n:
            return RC_INVALID_TILE, 0.0, 0.0
        if domain < 0 or domain >= self.params.n_domains:
            return RC_INVALID_DOMAIN, 0.0, 0.0
        dv = self._sim.state.dvfs
        f = int(np.asarray(dv.freq_mhz)[tile_id, domain])
        v = int(np.asarray(dv.voltage_mv)[tile_id, domain])
        return RC_OK, f / 1000.0, v / 1000.0

    def set_dvfs(self, tile_id: int, domain: int, frequency_ghz: float,
                 voltage_flag: int = AUTO) -> int:
        """Immediate (inter-quantum) DVFS set with reference rc codes."""
        import jax.numpy as jnp
        import numpy as np

        n = self._sim.params.n_tiles
        if tile_id < 0 or tile_id >= n:
            return RC_INVALID_TILE
        if domain < 0 or domain >= self.params.n_domains:
            return RC_INVALID_DOMAIN
        if voltage_flag not in (AUTO, HOLD):
            return RC_INVALID_VOLTAGE_OPTION
        freq_mhz = int(round(frequency_ghz * 1000))
        if freq_mhz <= 0 or freq_mhz > self.params.max_freq_mhz[0]:
            return RC_INVALID_FREQUENCY
        dv = self._sim.state.dvfs
        if voltage_flag == HOLD:
            cur_v = int(np.asarray(dv.voltage_mv)[tile_id, domain])
            if freq_mhz > self.params.max_freq_at_mv(cur_v):
                return RC_ABOVE_MAX_FOR_VOLTAGE
            new_v = cur_v
        else:
            new_v = self.params.min_voltage_mv(freq_mhz)
        new_dv = dv.replace(
            freq_mhz=dv.freq_mhz.at[tile_id, domain].set(freq_mhz),
            voltage_mv=dv.voltage_mv.at[tile_id, domain].set(new_v),
        )
        state = self._sim.state.replace(dvfs=new_dv)
        if domain == self.params.core_domain:
            state = state.replace(core=state.core.replace(
                freq_mhz=state.core.freq_mhz.at[tile_id].set(
                    jnp.asarray(freq_mhz, state.core.freq_mhz.dtype))))
        self._sim.state = state
        return RC_OK
