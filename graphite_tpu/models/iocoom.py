"""Vectorized IOCOOM core model (in-order commit, out-of-order memory).

Reference: `common/tile/core/models/iocoom_core_model.{h,cc}` — a register
scoreboard over 512 registers, a load queue with optional speculative loads,
and a store queue with optional multiple outstanding RFOs and load-bypass
(`carbon_sim.cfg:180-185`).  The timing algebra per instruction
(`iocoom_core_model.cc:79-276`) is pure max/add over small fixed vectors, so
it vectorizes over the tile axis directly; the queues become [T, N] ring
scoreboards updated with one-hot dense writes (no scatters).

Semantics reproduced exactly:
 - instruction fetch: instruction_ready = curr_time + max(icache_lat - 1cy, 0)
   (`iocoom_core_model.cc:96-101`);
 - read-register operands wait on the scoreboard, split by producing unit
   (LOAD_UNIT vs EXECUTION_UNIT) for the stall breakdown (`:115-146`);
 - loads issue after all register reads; store-queue bypass returns in one
   cycle; otherwise the load queue allocates at max(head, sched) with
   speculative issue=allocate or FIFO issue=last (`:330-355`);
 - execution completes at read_operands_ready + cost; write registers are
   stamped with that time, tagged LOAD_UNIT only for simple MOV loads
   (`:185-198`);
 - stores allocate in the store queue after execution, ordered against the
   last load deallocate (TSO; `:406-436`);
 - the clock advances only to load_queue_ready (simple MOV load),
   read_operands_ready, or store_queue_ready — later work overlaps with
   younger instructions (`:240-267`);
 - seven detailed stall counters (`outputSummary`, `:64-77`).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import struct

from graphite_tpu.time_types import cycles_to_ps
from graphite_tpu.trace.schema import (
    FLAG_MEM0_VALID, FLAG_MEM0_WRITE, FLAG_MEM1_VALID, FLAG_MEM1_WRITE,
    FLAG_SIMPLE_MOV_LOAD, NO_REG,
)

I64 = jnp.int64

NUM_REGISTERS = 512  # `iocoom_core_model.h:77` _NUM_REGISTERS

# register_dependency_list units (`iocoom_core_model.h:13-19`)
UNIT_INVALID = 0
UNIT_LOAD = 1
UNIT_EXEC = 3


@dataclasses.dataclass(frozen=True)
class IocoomParams:
    """[core/iocoom] knobs (`carbon_sim.cfg:180-185`)."""

    num_load_queue_entries: int = 8
    num_store_queue_entries: int = 8
    speculative_loads_enabled: bool = True
    multiple_outstanding_rfos_enabled: bool = True

    @classmethod
    def from_config(cls, cfg) -> "IocoomParams":
        return cls(
            num_load_queue_entries=cfg.get_int(
                "core/iocoom/num_load_queue_entries", 8),
            num_store_queue_entries=cfg.get_int(
                "core/iocoom/num_store_queue_entries", 8),
            speculative_loads_enabled=cfg.get_bool(
                "core/iocoom/speculative_loads_enabled", True),
            multiple_outstanding_rfos_enabled=cfg.get_bool(
                "core/iocoom/multiple_outstanding_RFOs_enabled", True),
        )


@struct.dataclass
class IocoomState:
    reg_ready_ps: jax.Array   # int64[T, R] register scoreboard
    reg_unit: jax.Array       # uint8[T, R] producing unit per register
    lq_dealloc_ps: jax.Array  # int64[T, LQ] load-queue ring scoreboard
    lq_idx: jax.Array         # int32[T] next allocate index
    sq_dealloc_ps: jax.Array  # int64[T, SQ]
    sq_addr: jax.Array        # int32[T, SQ] line-granular store addresses
    sq_idx: jax.Array         # int32[T]
    # detailed pipeline stall counters (`iocoom_core_model.cc:51-61`)
    load_queue_stall_ps: jax.Array        # int64[T]
    store_queue_stall_ps: jax.Array       # int64[T]
    l1icache_stall_ps: jax.Array          # int64[T]
    intra_ins_l1dcache_stall_ps: jax.Array  # int64[T]
    inter_ins_l1dcache_stall_ps: jax.Array  # int64[T]
    intra_ins_execution_unit_stall_ps: jax.Array  # int64[T]
    inter_ins_execution_unit_stall_ps: jax.Array  # int64[T]


def init_iocoom_state(n_tiles: int, p: IocoomParams) -> IocoomState:
    T = n_tiles
    z = lambda: jnp.zeros(T, I64)  # noqa: E731
    return IocoomState(
        reg_ready_ps=jnp.zeros((T, NUM_REGISTERS), I64),
        reg_unit=jnp.zeros((T, NUM_REGISTERS), jnp.uint8),
        lq_dealloc_ps=jnp.zeros((T, p.num_load_queue_entries), I64),
        lq_idx=jnp.zeros(T, jnp.int32),
        sq_dealloc_ps=jnp.zeros((T, p.num_store_queue_entries), I64),
        sq_addr=jnp.full((T, p.num_store_queue_entries), -1, jnp.int32),
        sq_idx=jnp.zeros(T, jnp.int32),
        load_queue_stall_ps=z(), store_queue_stall_ps=z(),
        l1icache_stall_ps=z(),
        intra_ins_l1dcache_stall_ps=z(), inter_ins_l1dcache_stall_ps=z(),
        intra_ins_execution_unit_stall_ps=z(),
        inter_ins_execution_unit_stall_ps=z(),
    )


def _ring_row(arr, idx):
    """arr[t, idx[t]] via one-hot (N is small; avoids a TPU scatter)."""
    N = arr.shape[1]
    m = idx[:, None] == jnp.arange(N, dtype=jnp.int32)[None, :]
    return jnp.where(m, arr, 0).sum(axis=1)


def _ring_set(arr, idx, val, mask):
    N = arr.shape[1]
    m = (idx[:, None] == jnp.arange(N, dtype=jnp.int32)[None, :]) & (
        mask[:, None])
    return jnp.where(m, val[:, None], arr)


def iocoom_commit(
    p: IocoomParams,
    ioc: IocoomState,
    *,
    commit,            # bool[T] — instruction-like lanes committing now
    clock_ps,          # int64[T] current core clock
    freq_mhz,          # int64[T]
    cost_ps,           # int64[T] execution cost of the record
    flags,             # int32[T]
    rreg0, rreg1, wreg,  # uint16-ish int[T]
    addr0, addr1,      # uint32[T]
    slot_lat_ps,       # int64[T, 3] [icache, mem0, mem1]
    enabled,           # bool[] models enabled
):
    """One committing record per lane through the IOCOOM pipeline algebra.

    Returns (new_state, new_clock_ps, memory_stall_ps, execution_stall_ps)
    for the committing lanes (others pass through unchanged).
    """
    T = clock_ps.shape[0]
    tiles = jnp.arange(T, dtype=jnp.int32)
    one_cycle = cycles_to_ps(jnp.ones(T, I64), freq_mhz)
    commit = commit & enabled  # models disabled → whole model is a no-op

    # --- instruction fetch ------------------------------------------------
    icache_lat = slot_lat_ps[:, 0]
    icache_lat = jnp.where(icache_lat >= one_cycle,
                           icache_lat - one_cycle, icache_lat)
    instruction_ready = clock_ps + icache_lat

    # --- read-register operands ------------------------------------------
    def reg_read(r):
        valid = r != NO_REG
        rr = jnp.clip(r, 0, NUM_REGISTERS - 1).astype(jnp.int32)
        ready = jnp.take_along_axis(ioc.reg_ready_ps, rr[:, None], axis=1)[:, 0]
        unit = jnp.take_along_axis(ioc.reg_unit, rr[:, None], axis=1)[:, 0]
        lt = jnp.where(valid & (unit == UNIT_LOAD), ready, 0)
        et = jnp.where(valid & (unit == UNIT_EXEC), ready, 0)
        return lt, et

    l0, e0 = reg_read(rreg0)
    l1, e1 = reg_read(rreg1)
    ready_load_unit = jnp.maximum(instruction_ready, jnp.maximum(l0, l1))
    ready_exec_unit = jnp.maximum(instruction_ready, jnp.maximum(e0, e1))
    register_operands_ready = jnp.maximum(ready_load_unit, ready_exec_unit)

    # --- memory operand decomposition ------------------------------------
    m0_valid = (flags & FLAG_MEM0_VALID) != 0
    m1_valid = (flags & FLAG_MEM1_VALID) != 0
    m0_write = (flags & FLAG_MEM0_WRITE) != 0
    m1_write = (flags & FLAG_MEM1_WRITE) != 0
    simple_mov_load = (flags & FLAG_SIMPLE_MOV_LOAD) != 0
    line0 = (addr0 >> 6).astype(jnp.int32)
    line1 = (addr1 >> 6).astype(jnp.int32)

    # --- loads (`executeLoad` + LoadQueue::execute) -----------------------
    lq = ioc.lq_dealloc_ps
    lq_idx = ioc.lq_idx
    LQ = lq.shape[1]
    load_queue_ready = register_operands_ready
    read_mem_ready = register_operands_ready

    def do_load(lq, lq_idx, lqr, rmr, line, lat, is_load):
        sched = register_operands_ready
        # store-queue bypass (`isAddressAvailable`): any SQ entry with the
        # address whose deallocate >= sched
        byp = jnp.any(
            (ioc.sq_addr == line[:, None])
            & (ioc.sq_dealloc_ps >= sched[:, None]), axis=1)
        use_lq = is_load & ~byp
        load_lat = lat + one_cycle  # store-queue check cycle
        head = _ring_row(lq, lq_idx % LQ)
        last = _ring_row(lq, (lq_idx + LQ - 1) % LQ)
        alloc = jnp.maximum(head, sched)
        if p.speculative_loads_enabled:
            completion = alloc + load_lat
            dealloc = jnp.maximum(completion, last + one_cycle)
        else:
            issue = jnp.maximum(last, sched)
            completion = issue + load_lat
            dealloc = completion
        lq = _ring_set(lq, lq_idx % LQ, dealloc, use_lq)
        lq_idx = lq_idx + use_lq.astype(jnp.int32)
        alloc = jnp.where(byp, sched, alloc)
        completion = jnp.where(byp, sched + one_cycle, completion)
        lqr = jnp.where(is_load, jnp.maximum(lqr, alloc), lqr)
        rmr = jnp.where(is_load, jnp.maximum(rmr, completion), rmr)
        return lq, lq_idx, lqr, rmr

    is_load0 = commit & m0_valid & ~m0_write
    is_load1 = commit & m1_valid & ~m1_write
    lq, lq_idx, load_queue_ready, read_mem_ready = do_load(
        lq, lq_idx, load_queue_ready, read_mem_ready,
        line0, slot_lat_ps[:, 1], is_load0)
    lq, lq_idx, load_queue_ready, read_mem_ready = do_load(
        lq, lq_idx, load_queue_ready, read_mem_ready,
        line1, slot_lat_ps[:, 2], is_load1)

    # --- execution --------------------------------------------------------
    read_operands_ready = read_mem_ready
    write_operands_ready = read_operands_ready + cost_ps

    # --- write-register operands -----------------------------------------
    w_valid = commit & (wreg != NO_REG)
    wr = jnp.clip(wreg, 0, NUM_REGISTERS - 1).astype(jnp.int32)
    w_unit = jnp.where(simple_mov_load, UNIT_LOAD, UNIT_EXEC).astype(jnp.uint8)
    # (tiles, wr) pairs are unique per lane → delta-add scatters alias
    old_ready = jnp.take_along_axis(ioc.reg_ready_ps, wr[:, None], axis=1)[:, 0]
    old_unit = jnp.take_along_axis(ioc.reg_unit, wr[:, None], axis=1)[:, 0]
    reg_ready = ioc.reg_ready_ps.at[tiles, wr].add(
        jnp.where(w_valid, write_operands_ready - old_ready, 0))
    reg_unit = ioc.reg_unit.at[tiles, wr].add(
        jnp.where(w_valid, w_unit - old_unit, 0).astype(jnp.uint8))

    # --- stores (`executeStore` + StoreQueue::execute) --------------------
    sq = ioc.sq_dealloc_ps
    sq_addr = ioc.sq_addr
    sq_idx = ioc.sq_idx
    SQ = sq.shape[1]
    last_load_dealloc = _ring_row(lq, (lq_idx + LQ - 1) % LQ)
    store_queue_ready = write_operands_ready

    def do_store(sq, sq_addr, sq_idx, sqr, line, lat, is_store):
        sched = write_operands_ready
        store_lat = lat + one_cycle  # load-queue check cycle
        head = _ring_row(sq, sq_idx % SQ)
        last = _ring_row(sq, (sq_idx + SQ - 1) % SQ)
        alloc = jnp.maximum(head, sched)
        if p.multiple_outstanding_rfos_enabled:
            completion = alloc + store_lat
            dealloc = jnp.maximum(
                jnp.maximum(completion, last + one_cycle), last_load_dealloc)
        else:
            issue = jnp.maximum(jnp.maximum(sched, last), last_load_dealloc)
            completion = issue + store_lat
            dealloc = completion
        sq = _ring_set(sq, sq_idx % SQ, dealloc, is_store)
        sq_addr = _ring_set(
            sq_addr, sq_idx % SQ, line, is_store).astype(jnp.int32)
        sq_idx = sq_idx + is_store.astype(jnp.int32)
        sqr = jnp.where(is_store, jnp.maximum(sqr, alloc), sqr)
        return sq, sq_addr, sq_idx, sqr

    is_store0 = commit & m0_valid & m0_write
    is_store1 = commit & m1_valid & m1_write
    sq, sq_addr, sq_idx, store_queue_ready = do_store(
        sq, sq_addr, sq_idx, store_queue_ready,
        line0, slot_lat_ps[:, 1], is_store0)
    sq, sq_addr, sq_idx, store_queue_ready = do_store(
        sq, sq_addr, sq_idx, store_queue_ready,
        line1, slot_lat_ps[:, 2], is_store1)

    # --- clock advance + stall breakdown (`iocoom_core_model.cc:222-267`) -
    has_write_mem = m0_write & m0_valid | (m1_write & m1_valid)
    new_clock = load_queue_ready
    new_clock = jnp.where(~simple_mov_load, read_operands_ready, new_clock)
    new_clock = jnp.where(~simple_mov_load & has_write_mem,
                          store_queue_ready, new_clock)

    l1i_stall = instruction_ready - clock_ps
    inter_exec = ready_exec_unit - instruction_ready
    inter_l1d = register_operands_ready - ready_exec_unit
    lq_stall = load_queue_ready - register_operands_ready
    intra_l1d = jnp.where(~simple_mov_load,
                          read_mem_ready - load_queue_ready, 0)
    intra_exec = jnp.where(
        ~simple_mov_load & has_write_mem,
        write_operands_ready - read_operands_ready, 0)
    sq_stall = jnp.where(
        ~simple_mov_load & has_write_mem,
        store_queue_ready - write_operands_ready, 0)

    memory_stall = l1i_stall + inter_l1d + lq_stall + intra_l1d + sq_stall
    execution_stall = inter_exec + intra_exec

    def acc(counter, delta):
        return counter + jnp.where(commit, delta, 0)

    new_ioc = ioc.replace(
        reg_ready_ps=reg_ready,
        reg_unit=reg_unit,
        lq_dealloc_ps=lq,
        lq_idx=lq_idx,
        sq_dealloc_ps=sq,
        sq_addr=sq_addr,
        sq_idx=sq_idx,
        load_queue_stall_ps=acc(ioc.load_queue_stall_ps, lq_stall),
        store_queue_stall_ps=acc(ioc.store_queue_stall_ps, sq_stall),
        l1icache_stall_ps=acc(ioc.l1icache_stall_ps, l1i_stall),
        intra_ins_l1dcache_stall_ps=acc(
            ioc.intra_ins_l1dcache_stall_ps, intra_l1d),
        inter_ins_l1dcache_stall_ps=acc(
            ioc.inter_ins_l1dcache_stall_ps, inter_l1d),
        intra_ins_execution_unit_stall_ps=acc(
            ioc.intra_ins_execution_unit_stall_ps, intra_exec),
        inter_ins_execution_unit_stall_ps=acc(
            ioc.inter_ins_execution_unit_stall_ps, inter_exec),
    )
    new_clock = jnp.where(commit, new_clock, clock_ps)
    memory_stall = jnp.where(commit, memory_stall, 0)
    execution_stall = jnp.where(commit, execution_stall, 0)
    return new_ioc, new_clock, memory_stall, execution_stall
