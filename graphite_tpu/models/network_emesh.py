"""Electrical-mesh topology math shared by the e-mesh NoC models.

Host-side (static topology) pieces of the reference's
`common/network/models/network_model_emesh_hop_by_hop.cc`:
 - mesh dims: width = floor(sqrt(N)), height = ceil(N/width); the tile
   count must factor exactly (`:308-320`);
 - XY coordinates and Manhattan distance (`:282-296`);
 - greedy memory-controller placement on a sub-mesh grid (`:322-364`);
 - process→tile mapping as contiguous rectangular blocks (`:366-433`) — in
   the TPU build this is the sharding layout that keeps X/Y neighbor
   `ppermute` exchanges on adjacent ICI devices.

Device-side routing (per-hop timing, contention, broadcast tree) lives in
`network_emesh_hop_counter.py` / `network_emesh_hop_by_hop.py`.
"""

from __future__ import annotations

import math


def mesh_dims(tile_count: int) -> tuple[int, int]:
    """(width, height) of the 2D mesh (`network_model_emesh_hop_by_hop.cc:286-287`)."""
    width = int(math.floor(math.sqrt(tile_count)))
    height = int(math.ceil(tile_count / width))
    return width, height


def is_tile_count_permissible(tile_count: int) -> bool:
    """Mesh requires an exact w*h factorization (`:308-320`)."""
    w, h = mesh_dims(tile_count)
    return tile_count == w * h


def tile_xy(tile_id: int, mesh_width: int) -> tuple[int, int]:
    return tile_id % mesh_width, tile_id // mesh_width


def manhattan_distance(sender: int, receiver: int, mesh_width: int) -> int:
    sx, sy = tile_xy(sender, mesh_width)
    dx, dy = tile_xy(receiver, mesh_width)
    return abs(sx - dx) + abs(sy - dy)


def memory_controller_positions(num_controllers: int, tile_count: int) -> list[int]:
    """Greedy center-of-block placement (`:322-364`)."""
    mesh_width, mesh_height = mesh_dims(tile_count)
    mc_w = int(math.floor(math.sqrt(num_controllers)))
    mc_h = int(math.ceil(num_controllers / mc_w))

    positions: list[int] = []
    for j in range(mc_h):
        for i in range(mc_w):
            if len(positions) >= num_controllers:
                break
            size_x = mesh_width // mc_w
            size_y = mesh_height // mc_h
            base_x = i * size_x
            base_y = j * size_y
            if i == mc_w - 1:
                size_x = mesh_width - (mc_w - 1) * size_x
            if j == mc_h - 1:
                size_y = mesh_height - (mc_h - 1) * size_y
            pos_x = base_x + size_x // 2
            pos_y = base_y + size_y // 2
            positions.append(pos_x + pos_y * mesh_width)
    return positions


def emesh_process_to_tile_mapping(
    tile_count: int, process_count: int
) -> list[list[int]]:
    """Contiguous rectangular block decomposition (`:366-433`).

    Processes form a floor(sqrt(P)) × floor(P/pw) grid over the lower
    portion of the mesh; leftover processes split the remaining rows in
    vertical strips — reproduced exactly so sharded runs agree with the
    reference's distributed layout.
    """
    mesh_width, mesh_height = mesh_dims(tile_count)
    mapping: list[list[int]] = [[] for _ in range(process_count)]

    pw = int(math.floor(math.sqrt(process_count)))
    ph = int(math.floor(process_count / pw))
    mesh_height_l = int((mesh_height * pw * ph) / process_count)

    for i in range(pw):
        for j in range(ph):
            size_x = mesh_width // pw
            size_y = mesh_height_l // ph
            base_x = i * size_x
            base_y = j * size_y
            if i == pw - 1:
                size_x = mesh_width - (pw - 1) * size_x
            if j == ph - 1:
                size_y = mesh_height_l - (ph - 1) * size_y
            for ii in range(size_x):
                for jj in range(size_y):
                    tile_id = (base_x + ii) + (base_y + jj) * mesh_width
                    mapping[i + j * pw].append(tile_id)

    procs_left = process_count - pw * ph
    for p in range(pw * ph, process_count):
        size_x = mesh_width // procs_left
        size_y = mesh_height - mesh_height_l
        base_x = (p - pw * ph) * size_x
        base_y = mesh_height_l
        if p == process_count - 1:
            size_x = mesh_width - (procs_left - 1) * size_x
        for ii in range(size_x):
            for jj in range(size_y):
                tile_id = (base_x + ii) + (base_y + jj) * mesh_width
                mapping[p].append(tile_id)

    return mapping
