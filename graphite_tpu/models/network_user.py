"""USER-network latency models: magic and emesh_hop_counter (vectorized).

Reference semantics:
 - magic (`network_model_magic.cc:15-22`): every packet takes exactly 1
   network-clock cycle, regardless of model enable; flit_width = -1 so no
   serialization is ever added (`network_model.cc:203-211`).
 - emesh_hop_counter (`network_model_emesh_hop_counter.cc:142-157`):
   zero-load latency = manhattan_hops * (router_delay + link_delay) cycles
   when the model is enabled, else 0; no contention.  At the receive side
   ceil(packet_bits / flit_width) cycles of serialization are added when the
   model is enabled and sender != receiver
   (`network_model.cc:119-149 __processReceivedPacket`).
 - user-packet modeled length = (sizeof(NetPacket) + payload) * 8 bits
   (`network_model.cc:186-199`, `network.cc:705-708`); sizeof(NetPacket) is
   64 bytes on x86-64 (`network.h:27-53`).

Latencies are returned in picoseconds at the network's DVFS frequency
(`network_model.cc:472-487`; domain NETWORK_USER, `carbon_sim.cfg:147-151`).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from graphite_tpu.config.simconfig import SimConfig
from graphite_tpu.models.network_emesh import mesh_dims
from graphite_tpu.time_types import cycles_to_ps

NET_PACKET_HEADER_BYTES = 64  # sizeof(NetPacket), `network.h:27-53`


@dataclasses.dataclass(frozen=True)
class UserNetworkParams:
    kind: str                 # "magic" | "emesh_hop_counter"
    freq_mhz: int             # NETWORK_USER domain frequency
    mesh_width: int = 0
    hop_latency_cycles: int = 2   # router.delay + link.delay
    flit_width_bits: int = -1     # -1 => no serialization (magic)

    @classmethod
    def from_config(cls, cfg: SimConfig, network: str = "user") -> "UserNetworkParams":
        kind = cfg.network_types[0 if network == "user" else 1]
        freq_mhz = _network_domain_freq_mhz(cfg)
        if kind == "magic":
            return cls(kind="magic", freq_mhz=freq_mhz)
        if kind == "atac":
            # routing/timing handled by AtacParams (models/network_atac);
            # this placeholder only carries the domain frequency
            return cls(kind="atac", freq_mhz=freq_mhz)
        if kind in ("emesh_hop_counter", "emesh_hop_by_hop"):
            # These params carry only the ZERO-LOAD basis (hop-counter
            # math).  When the configured model is emesh_hop_by_hop, the
            # per-hop contention engine is built separately and carries
            # the authoritative timing: HopByHopParams in
            # EngineParams.user_hbh for the USER net, MemParams.net_hbh
            # for the MEMORY net (every coherence message then routes
            # through it — memory/engine.py mem_net_send).
            section = f"network/{kind}"
            router = cfg.cfg.get_int(f"{section}/router/delay", 1)
            link = cfg.cfg.get_int(f"{section}/link/delay", 1)
            flit = cfg.cfg.get_int(f"{section}/flit_width", 64)
            w, _ = mesh_dims(cfg.application_tiles)
            return cls(
                kind="emesh_hop_counter",
                freq_mhz=freq_mhz,
                mesh_width=w,
                hop_latency_cycles=router + link,
                flit_width_bits=flit,
            )
        raise ValueError(f"unsupported user network model: {kind}")


def _network_domain_freq_mhz(cfg: SimConfig, module: str = "NETWORK_USER") -> int:
    """DVFS domain frequency of a network module (`carbon_sim.cfg:147-151`)."""
    from graphite_tpu.models.dvfs import parse_dvfs_domains

    for freq_mhz, modules in parse_dvfs_domains(cfg.cfg):
        if module in modules:
            return freq_mhz
    return 1000


def num_flits(length_bits, flit_width_bits: int):
    """`network_model.cc:203-211`: ceil, or 0 when flit_width == -1."""
    if flit_width_bits <= 0:
        return jnp.zeros_like(jnp.asarray(length_bits))
    return (jnp.asarray(length_bits) + flit_width_bits - 1) // flit_width_bits


def user_packet_bits(payload_bytes):
    return (NET_PACKET_HEADER_BYTES + payload_bytes) * 8


def route_latency_ps(params: UserNetworkParams, src, dst, payload_bytes, enabled):
    """Zero-load arrival delay (route + receive serialization), elementwise.

    src/dst/payload_bytes are int arrays of the same shape; enabled is a
    bool scalar (models enabled).  Returns int64 ps.
    """
    src = jnp.asarray(src)
    dst = jnp.asarray(dst)
    if params.kind == "magic":
        cycles = jnp.ones_like(src, dtype=jnp.int64)  # unconditional 1 cycle
        return cycles_to_ps(cycles, params.freq_mhz)
    # emesh_hop_counter
    w = params.mesh_width
    hops = jnp.abs(src % w - dst % w) + jnp.abs(src // w - dst // w)
    route_cycles = hops.astype(jnp.int64) * params.hop_latency_cycles
    ser_cycles = num_flits(
        user_packet_bits(jnp.asarray(payload_bytes)), params.flit_width_bits
    ).astype(jnp.int64)
    ser_cycles = jnp.where(src == dst, 0, ser_cycles)  # self-sends skip recv-side
    cycles = jnp.where(enabled, route_cycles + ser_cycles, 0)
    return cycles_to_ps(cycles, params.freq_mhz)
