"""Timing models: cores, caches/coherence, NoC, DRAM, branch prediction.

Each module re-implements the *semantics* of one reference model family
(`common/tile/core/models/`, `common/tile/memory_subsystem/`,
`common/network/models/`) as vectorized JAX functions over the tile axis.
"""
