"""ATAC optical NoC model (`common/network/models/network_model_atac.cc`).

The ATAC network clusters the tile mesh: intra-cluster traffic rides an
electrical mesh (ENet); inter-cluster traffic goes through the sender
cluster's optical hub onto a WDM waveguide (ONet) to the receiver
cluster's hub, then down an electrical receive network (star/htree) to the
destination (`network_model_atac.h:18-60`, routing `:337-500`).  Routing
strategy `cluster_based` sends every inter-cluster unicast optically;
`distance_based` uses ONet only above `unicast_distance_threshold`
(`carbon_sim.cfg:315-352`, `computeGlobalRoute` `:798-830`).

Timing:
 - ENet hop: router + link cycles per XY hop (`routePacketOnENet`);
 - ONet: ENet to the cluster's optical access point, send-hub router (+
   contention queue), the optical link — waveguide delay per mm x length +
   E-O + O-E conversion cycles (`optical_link_model.cc:52-55`) — then the
   receive-hub router (+ contention) and one receive-net router hop
   (star; htree adds log2(cluster) levels);
 - receive-side serialization flits, as in every NetworkModel
   (`network_model.cc:143-149`).

Hub contention uses the shared queue models, one queue per send hub and
per receive hub (the reference attaches QueueModels to both hub routers);
WDM gives each sender cluster its own wavelength, so the waveguide itself
is contention-free.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
from flax import struct

from graphite_tpu.models.queue_models import (
    QueueArrays, QueueParams, make_queues, scatter_queue_delay,
)
from graphite_tpu.time_types import cycles_to_ps, ps_to_cycles

I64 = jnp.int64


@dataclasses.dataclass(frozen=True)
class AtacParams:
    n_tiles: int
    mesh_width: int
    mesh_height: int
    cluster_size: int          # tiles per cluster (square sub-mesh)
    cluster_width: int         # sub-mesh dims (cluster_width x cluster_height)
    cluster_height: int
    n_clusters: int
    flit_width_bits: int
    freq_mhz: int
    enet_hop_cycles: int       # enet router + link
    send_hub_cycles: int
    receive_hub_cycles: int
    receive_net_cycles: int    # per receive-net router
    receive_net_levels: int    # 1 for star, log2(cluster_size) for htree
    optical_link_ps: int       # waveguide + E-O + O-E, precomputed
    global_routing_strategy: str   # cluster_based | distance_based
    unicast_distance_threshold: int
    queue: QueueParams
    contention_enabled: bool = True

    @classmethod
    def from_config(cls, sc, network: str = "user") -> "AtacParams":
        from graphite_tpu.models.network_emesh import mesh_dims
        from graphite_tpu.models.network_user import _network_domain_freq_mhz

        cfg = sc.cfg
        sec = "network/atac"
        w, h = mesh_dims(sc.application_tiles)
        cluster_size = cfg.get_int(f"{sec}/cluster_size", 4)
        if sc.application_tiles % cluster_size != 0:
            raise ValueError(
                f"atac cluster_size {cluster_size} does not divide "
                f"{sc.application_tiles} tiles")
        n_clusters = sc.application_tiles // cluster_size
        # clusters are 2-D sub-meshes (`getClusterID`,
        # `network_model_atac.cc:659-674`): cw x ch tiles, as square as
        # cluster_size allows
        cw = int(math.isqrt(cluster_size))
        while cluster_size % cw != 0:
            cw -= 1
        ch = cluster_size // cw
        if w % cw != 0 or h % ch != 0:
            raise ValueError(
                f"atac cluster {cw}x{ch} does not tile the {w}x{h} mesh")
        freq_mhz = _network_domain_freq_mhz(
            sc, "NETWORK_USER" if network == "user" else "NETWORK_MEMORY")
        recv_type = cfg.get_string(f"{sec}/receive_network_type", "star")
        levels = (1 if recv_type == "star"
                  else max(1, int(math.log2(cluster_size))))
        # waveguide length: the serpentine visits every cluster hub — scale
        # with the chip's span (`computeOpticalLinkLength`): tile_width x
        # (mesh perimeter/2) mm
        tile_width_mm = cfg.get_float("general/tile_width", 1.0)
        length_mm = tile_width_mm * (w + h)
        wg_ns_per_mm = cfg.get_float(
            "link_model/optical/waveguide_delay_per_mm", 10e-3)
        eo = cfg.get_int("link_model/optical/E-O_conversion_delay", 1)
        oe = cfg.get_int("link_model/optical/O-E_conversion_delay", 1)
        from graphite_tpu.time_types import cycles_to_ps

        optical_link_ps = int(
            math.ceil(wg_ns_per_mm * length_mm * 1000)
            + cycles_to_ps(eo + oe, freq_mhz))
        qtype = cfg.get_string(f"{sec}/queue_model/type", "history_tree")
        return cls(
            n_tiles=sc.application_tiles,
            mesh_width=w,
            mesh_height=h,
            cluster_size=cluster_size,
            cluster_width=cw,
            cluster_height=ch,
            n_clusters=n_clusters,
            flit_width_bits=cfg.get_int(f"{sec}/flit_width", 64),
            freq_mhz=freq_mhz,
            enet_hop_cycles=(cfg.get_int(f"{sec}/enet/router/delay", 1)
                             + cfg.get_int(f"{sec}/enet/link/delay", 1)),
            send_hub_cycles=cfg.get_int(
                f"{sec}/onet/send_hub/router/delay", 1),
            receive_hub_cycles=cfg.get_int(
                f"{sec}/onet/receive_hub/router/delay", 1),
            receive_net_cycles=cfg.get_int(
                f"{sec}/star_net/router/delay", 1),
            receive_net_levels=levels,
            optical_link_ps=optical_link_ps,
            global_routing_strategy=cfg.get_string(
                f"{sec}/global_routing_strategy", "cluster_based"),
            unicast_distance_threshold=cfg.get_int(
                f"{sec}/unicast_distance_threshold", 4),
            queue=QueueParams.from_config(cfg, qtype, 1),
            contention_enabled=cfg.get_bool(
                f"{sec}/queue_model/enabled", True),
        )


@struct.dataclass
class AtacState:
    # [send hubs | receive hubs | scratch]: one queue per cluster hub
    hub_queues: QueueArrays


def init_atac_state(p: AtacParams) -> AtacState:
    return AtacState(hub_queues=make_queues(2 * p.n_clusters + 1, p.queue))


def _cluster_of(p: AtacParams, tile):
    """2-D sub-mesh cluster id (`getClusterID`)."""
    x = tile % p.mesh_width
    y = tile // p.mesh_width
    cx = x // p.cluster_width
    cy = y // p.cluster_height
    clusters_per_row = p.mesh_width // p.cluster_width
    return (cy * clusters_per_row + cx).astype(jnp.int32)


def _hub_tile(p: AtacParams, cluster):
    """The tile hosting the cluster's optical hub (the sub-mesh's top-left
    corner — `getTileIDWithOpticalHub`)."""
    clusters_per_row = p.mesh_width // p.cluster_width
    cx = cluster % clusters_per_row
    cy = cluster // clusters_per_row
    return (cy * p.cluster_height * p.mesh_width
            + cx * p.cluster_width).astype(jnp.int32)


def _enet_hops(p: AtacParams, a, b):
    w = p.mesh_width
    return (jnp.abs(a % w - b % w) + jnp.abs(a // w - b // w)).astype(I64)


def route_atac(p: AtacParams, state: AtacState, src, dst, bits, clock_ps,
               mask, enabled):
    """Route one packet per lane; returns (state, arrival_ps, used_onet).

    Mirrors `routePacket` (`network_model_atac.cc:337-368`): intra-cluster
    (or short-distance) unicasts ride the ENet; everything else goes
    hub → waveguide → hub → receive net.
    """
    src = jnp.asarray(src)
    dst = jnp.asarray(dst)
    csrc = _cluster_of(p, src)
    cdst = _cluster_of(p, dst)
    same_cluster = csrc == cdst
    hops_direct = _enet_hops(p, src, dst)
    if p.global_routing_strategy == "distance_based":
        use_enet = same_cluster | (hops_direct <= p.unicast_distance_threshold)
    else:
        use_enet = same_cluster
    use_onet = mask & ~use_enet
    # queue-state updates only when models are enabled (disabled-phase
    # traffic must not pollute contention history — `state.models_enabled`)
    onet_live = use_onet & jnp.asarray(enabled)

    def cyc(n):
        return cycles_to_ps(jnp.asarray(n, I64), p.freq_mhz)

    flits = ((jnp.asarray(bits) + p.flit_width_bits - 1)
             // p.flit_width_bits).astype(I64)
    ser_ps = jnp.where(src == dst, 0, cyc(flits))

    # --- ENet path -------------------------------------------------------
    enet_ps = cyc(hops_direct * p.enet_hop_cycles)

    # --- ONet path -------------------------------------------------------
    to_hub = _enet_hops(p, src, _hub_tile(p, csrc))
    from_hub = cyc(p.receive_net_levels * p.receive_net_cycles)
    sendhub_arrive = clock_ps + cyc(to_hub * p.enet_hop_cycles)
    # send-hub contention + router
    if p.contention_enabled:
        qid = jnp.where(onet_live, csrc, 2 * p.n_clusters).astype(jnp.int32)
        service = jnp.maximum(flits, 1)  # serialization cycles per packet
        queues, delay_cyc = scatter_queue_delay(
            p.queue, state.hub_queues, qid,
            ps_to_cycles(sendhub_arrive, p.freq_mhz),
            service, onet_live)
        sendhub_done = sendhub_arrive + cyc(delay_cyc + p.send_hub_cycles)
    else:
        queues = state.hub_queues
        sendhub_done = sendhub_arrive + cyc(p.send_hub_cycles)
    # optical traversal
    recvhub_arrive = sendhub_done + jnp.where(enabled, p.optical_link_ps, 0)
    # receive-hub contention + router
    if p.contention_enabled:
        qid2 = jnp.where(onet_live, p.n_clusters + cdst,
                         2 * p.n_clusters).astype(jnp.int32)
        queues, delay2 = scatter_queue_delay(
            p.queue, queues, qid2,
            ps_to_cycles(recvhub_arrive, p.freq_mhz),
            jnp.maximum(flits, 1), onet_live)
        recvhub_done = recvhub_arrive + cyc(delay2 + p.receive_hub_cycles)
    else:
        recvhub_done = recvhub_arrive + cyc(p.receive_hub_cycles)
    onet_ps = (recvhub_done - clock_ps) + from_hub

    route_ps = jnp.where(use_onet, onet_ps, enet_ps)
    total_ps = jnp.where(enabled, route_ps + ser_ps, 0)
    arrival = clock_ps + jnp.where(mask, total_ps, 0)
    return AtacState(hub_queues=queues), arrival, use_onet


def atac_use_onet(p: AtacParams, src, dst):
    """Which (src, dst) pairs ride the ONet (broadcastable bool)."""
    src = jnp.asarray(src)
    dst = jnp.asarray(dst)
    same_cluster = _cluster_of(p, src) == _cluster_of(p, dst)
    if p.global_routing_strategy == "distance_based":
        return ~(same_cluster
                 | (_enet_hops(p, src, dst) <= p.unicast_distance_threshold))
    return ~same_cluster


def atac_zeroload_ps(p: AtacParams, src, dst, bits, enabled):
    """Contention-free ATAC latency (broadcastable [.., ..] math): the
    route_atac path costs with zero hub-queue delay — what a packet pays
    on idle hubs (`test_atac.py` pins route_atac == this on fresh state).
    Used for the MEMORY net's zero-load call sites (shl2 DRAM round trip,
    fan-out per-target legs)."""
    src = jnp.asarray(src)
    dst = jnp.asarray(dst)

    def cyc(n):
        return cycles_to_ps(jnp.asarray(n, I64), p.freq_mhz)

    flits = ((jnp.asarray(bits) + p.flit_width_bits - 1)
             // p.flit_width_bits).astype(I64)
    ser_ps = jnp.where(src == dst, 0, cyc(flits))
    use_onet = atac_use_onet(p, src, dst)
    enet_ps = cyc(_enet_hops(p, src, dst) * p.enet_hop_cycles)
    to_hub = cyc(_enet_hops(p, src, _hub_tile(p, _cluster_of(p, src)))
                 * p.enet_hop_cycles)
    onet_ps = (to_hub + cyc(p.send_hub_cycles)
               + jnp.where(jnp.asarray(enabled, bool), p.optical_link_ps, 0)
               + cyc(p.receive_hub_cycles)
               + cyc(p.receive_net_levels * p.receive_net_cycles))
    total = jnp.where(use_onet, onet_ps, enet_ps) + ser_ps
    return jnp.where(jnp.asarray(enabled, bool), total, 0)
