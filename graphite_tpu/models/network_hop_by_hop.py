"""emesh_hop_by_hop: full per-hop 2D-mesh NoC with per-port contention.

Reference: `common/network/models/network_model_emesh_hop_by_hop.{h,cc}`
(SURVEY §2.6) + `components/router/router_model.cc:52-108`.

Per-packet semantics mirrored exactly (`routePacket`,
`network_model_emesh_hop_by_hop.cc:146-265`):
 - injection router at the sender (1 output port): router delay +
   injection-port contention;
 - XY routing (x first, then y); at every intermediate tile the mesh
   router adds router delay + output-port contention (queue model with
   processing = num_flits) and the output link adds link delay;
 - delivery goes through the destination's SELF port + SELF link;
 - the receiver adds num_flits serialization cycles
   (`network_model.cc:119-149`).

The reference's broadcast tree (`network_model_emesh_hop_by_hop.cc:163-222`,
knob `carbon_sim.cfg:304`) has no analog here BY CONSTRUCTION: nothing in
this engine injects NetPacket broadcasts into the modeled USER NoC — the
reference's broadcast senders are the MCP control plane (host-side here)
and coherence INV sweeps (whose MEMORY-net timing uses per-target
zero-load latencies in `memory/engine.py`).  The knob is therefore not
parsed rather than parsed-and-dead.

TPU-native form: instead of per-tile router objects called hop-by-hop on
the receiving process's sim thread, every packet's whole path is resolved
at once as dense [packets, h, w] grid math (`_dense_contention`): an
exact max-plus scan of the serial hop recurrence gives per-cell read
times, and the flat QueueArrays [n_tiles*6 + scratch] occupancies commit
with dense reductions — no gather/scatter kernels anywhere.  The serial
semantics are pinned by `tests/test_hop_by_hop.py`, including
differentials against the golden interpreter's independent per-hop loop.

Ports: 0=RIGHT 1=LEFT 2=UP 3=DOWN 4=SELF 5=INJECT.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from graphite_tpu.models.queue_models import (
    QueueArrays, QueueParams, make_queues,
)
from graphite_tpu.time_types import cycles_to_ps, ps_to_cycles

I64 = jnp.int64
NUM_PORTS = 6
PORT_RIGHT, PORT_LEFT, PORT_UP, PORT_DOWN, PORT_SELF, PORT_INJECT = range(6)


@dataclasses.dataclass(frozen=True)
class HopByHopParams:
    n_tiles: int
    mesh_width: int
    mesh_height: int
    router_delay: int          # cycles
    link_delay: int            # cycles
    flit_width_bits: int
    freq_mhz: int
    queue: QueueParams
    contention_enabled: bool = True

    @classmethod
    def from_config(cls, sc, network: str) -> "HopByHopParams":
        from graphite_tpu.models.network_emesh import mesh_dims
        from graphite_tpu.models.network_user import _network_domain_freq_mhz

        cfg = sc.cfg
        sec = "network/emesh_hop_by_hop"
        w, h = mesh_dims(sc.application_tiles)
        qenabled = cfg.get_bool(f"{sec}/queue_model/enabled", True)
        qtype = cfg.get_string(f"{sec}/queue_model/type", "history_tree")
        return cls(
            n_tiles=sc.application_tiles,
            mesh_width=w,
            mesh_height=h,
            router_delay=cfg.get_int(f"{sec}/router/delay", 1),
            link_delay=cfg.get_int(f"{sec}/link/delay", 1),
            flit_width_bits=cfg.get_int(f"{sec}/flit_width", 64),
            freq_mhz=_network_domain_freq_mhz(
                sc, "NETWORK_USER" if network == "user" else "NETWORK_MEMORY"),
            queue=QueueParams.from_config(cfg, qtype, 1),
            contention_enabled=qenabled,
        )

    @property
    def max_hops(self) -> int:
        return self.mesh_width + self.mesh_height  # (w-1)+(h-1)+SELF+slack


@struct.dataclass
class NocState:
    queues: QueueArrays   # [n_tiles*6 + 1] port queues (+ scratch)


def init_noc_state(p: HopByHopParams) -> NocState:
    return NocState(queues=make_queues(p.n_tiles * NUM_PORTS + 1, p.queue))


def _xy_next(p: HopByHopParams, cur: jax.Array, dst: jax.Array):
    """XY route step: (next_tile, port).  x first, then y, else SELF."""
    w = p.mesh_width
    cx, cy = cur % w, cur // w
    dx, dy = dst % w, dst // w
    port = jnp.where(
        cx > dx, PORT_LEFT,
        jnp.where(cx < dx, PORT_RIGHT,
                  jnp.where(cy > dy, PORT_DOWN,
                            jnp.where(cy < dy, PORT_UP, PORT_SELF))))
    nxt = jnp.where(
        port == PORT_LEFT, cur - 1,
        jnp.where(port == PORT_RIGHT, cur + 1,
                  jnp.where(port == PORT_DOWN, cur - w,
                            jnp.where(port == PORT_UP, cur + w, cur))))
    return nxt.astype(jnp.int32), port.astype(jnp.int32)


def route_hop_by_hop(
    p: HopByHopParams,
    nst: NocState,
    src: jax.Array,        # int32[L]
    dst: jax.Array,        # int32[L]
    bits,                  # int | int64[L] modeled packet length
    t_send_ps: jax.Array,  # int64[L]
    mask: jax.Array,       # bool[L]
    enabled,               # bool[] models enabled
):
    """Route one packet per lane; returns (nst, arrival_ps, zero_load_ps,
    contention_ps).

    Dense formulation: each packet's XY path lives on [L, h, w] grids
    (horizontal run, vertical run, inject + SELF cells); per-cell read
    times come from an EXACT max-plus scan of the serial hop recurrence
    (see _dense_contention), all against the PRE-call port state, and
    occupancies commit with dense reductions — no gather/scatter
    kernels.

    This extends `scatter_queue_delay`'s same-call-conflict contract from
    single cells to whole paths: packets routed in the SAME subquantum
    iteration see each other's occupancy only through the next
    iteration's pre-state.  Cross-iteration behavior — the regime the
    reference's serial `routePacket` models — is unchanged.  The win is
    structural: a handful of gather/scatter kernels per call instead of
    ~6 per hop x w+h hops (each such kernel costs ~0.1-0.2 ms on TPU; the
    per-hop loop made hop-by-hop configs ~8x slower than hop-counter).
    """
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    live = mask & jnp.asarray(enabled, bool)
    flits = jnp.maximum(
        (jnp.asarray(bits, I64) + p.flit_width_bits - 1)
        // p.flit_width_bits, 1)
    t0 = ps_to_cycles(t_send_ps, p.freq_mhz)  # network-clock cycles
    w, h = p.mesh_width, p.mesh_height
    sx, sy = src % w, src // w
    dx, dy = dst % w, dst // w
    dist = (jnp.abs(sx - dx) + jnp.abs(sy - dy)).astype(I64)
    step_cyc = p.router_delay + p.link_delay
    zero_load = p.router_delay + (dist + 1) * step_cyc

    if p.contention_enabled:
        queues, contention = _dense_contention(
            p, nst.queues, live, flits, t0, sx, sy, dx, dy, dist)
        t = t0 + zero_load + contention
    else:
        queues = nst.queues
        contention = jnp.zeros_like(t0)
        t = t0 + zero_load

    # receiver serialization (`__processReceivedPacket`), skipped for
    # self-sends like the zero-load models
    ser = jnp.where(src == dst, 0, flits)
    t = t + ser
    zero_load = jnp.where(live, zero_load + ser, 0)

    arrival_ps = jnp.where(
        live, cycles_to_ps(t, p.freq_mhz), t_send_ps)
    zero_load_ps = cycles_to_ps(zero_load, p.freq_mhz)
    contention_ps = jnp.where(live, cycles_to_ps(contention, p.freq_mhz), 0)
    return nst.replace(queues=queues), arrival_ps, zero_load_ps, contention_ps


def _dense_contention(p, q, live, flits, t0, sx, sy, dx, dy, dist):
    """Per-port contention for all packets at once as DENSE grid math.

    XY routing makes every path a horizontal run (row sy, ports
    RIGHT/LEFT), a vertical run (column dx, ports UP/DOWN), one INJECT
    cell and one SELF cell — so cell membership, zero-load arrival
    offsets, in-path prefix sums of delays, and the per-port occupancy
    commits are all expressible as [L, h, w] elementwise masks, cumsums
    and reductions over the packet axis.  NO gather/scatter kernels:
    conflicting-index scatters cost ~0.1-1 ms EACH on TPU (serialized),
    which made both the per-hop loop and the flattened-path scatter
    formulations orders of magnitude slower than this.

    Same-call semantics follow the documented `scatter_queue_delay`
    contract lifted to paths: every cell's delay is read against the
    PRE-call port state (packets in one subquantum iteration see each
    other only through the next iteration's state), a packet's own
    upstream compounding is EXACT (max-plus closed form of the serial
    hop recurrence), and occupancy commits exactly (max of arrivals,
    then the sum of every processing time).
    """
    L = live.shape[0]
    w, h = p.mesh_width, p.mesh_height
    step_cyc = jnp.asarray(p.router_delay + p.link_delay, I64)
    X = jnp.arange(w, dtype=jnp.int32)[None, None, :]     # [1, 1, w]
    Y = jnp.arange(h, dtype=jnp.int32)[None, :, None]     # [1, h, 1]
    sx_, sy_ = sx[:, None, None], sy[:, None, None]
    dx_, dy_ = dx[:, None, None], dy[:, None, None]
    live_ = live[:, None, None]
    t0_ = t0[:, None, None]
    proc = flits[:, None, None]

    # port state as dense [h, w, 10] grids per direction
    from graphite_tpu.models import queue_models as qm

    grid = q.data[: w * h * NUM_PORTS].reshape(h, w, NUM_PORTS, qm.N_COLS)

    def port_state(d):
        return grid[None, :, :, d, :]       # [1, h, w, 10] broadcast over L

    def delay_at(d, arr, member):
        """Queue delay for member cells of port-plane d at arrival arr."""
        st = port_state(d)
        qt = st[..., qm.COL_QT]
        if p.queue.kind in ("history_list", "history_tree"):
            too_old = p.queue.analytical_enabled & (
                (arr + proc) < st[..., qm.COL_WS])
            mg1 = qm._mg1_wait(
                st[..., qm.COL_N_ARR], st[..., qm.COL_SUM_ST],
                st[..., qm.COL_SUM_ST2], st[..., qm.COL_NEWEST])
            dly = jnp.where(too_old, mg1, jnp.maximum(qt - arr, 0))
        else:
            too_old = jnp.zeros(arr.shape, bool)
            dly = jnp.maximum(qt - arr, 0)
        return jnp.where(member, dly, 0), too_old

    # ---- cell membership + hop index (steps from src) per plane ---------
    on_row = Y == sy_
    on_col = X == dx_
    m_right = live_ & on_row & (X >= sx_) & (X < dx_)
    m_left = live_ & on_row & (X <= sx_) & (X > dx_)
    m_up = live_ & on_col & (Y >= sy_) & (Y < dy_)
    m_down = live_ & on_col & (Y <= sy_) & (Y > dy_)
    m_self = live_ & (X == dx_) & (Y == dy_)
    m_inject = live_ & (X == sx_) & (Y == sy_)
    steps_h = jnp.abs(X - sx_).astype(I64)                 # horizontal run
    steps_v = (jnp.abs(dx_ - sx_) + jnp.abs(Y - sy_)).astype(I64)
    steps_self = dist[:, None, None]

    planes = (
        (PORT_RIGHT, m_right, steps_h, "x+"),
        (PORT_LEFT, m_left, steps_h, "x-"),
        (PORT_UP, m_up, steps_v, "y+"),
        (PORT_DOWN, m_down, steps_v, "y-"),
        (PORT_SELF, m_self, steps_self, None),
        (PORT_INJECT, m_inject, None, None),
    )

    # ---- EXACT per-packet arrivals via a max-plus scan ------------------
    # The serial hop recurrence t_{j+1} = step + max(t_j, qt_j) has the
    # closed form t_j = s_j*step + max(base, max_{i<j}(qt_i - s_i*step)),
    # so each cell's read time is a directional EXCLUSIVE cummax of
    # (qt - steps*step) along the path — bit-identical to the serial loop
    # for in-window traffic.  The M/G/1 too-old fallback substitutes its
    # analytical wait at the scanned read time; its (rare, deep-backlog)
    # downstream compounding is approximate — documented with the
    # windowed-tail queue model itself.
    NEG = -(2**61)

    def qt_of(d):
        return port_state(d)[..., qm.COL_QT]

    # injection: read at t0 (one cell per packet)
    d_inj_cells, too_inj = delay_at(
        PORT_INJECT, jnp.broadcast_to(t0_, m_inject.shape), m_inject)
    base = t0_ + p.router_delay + d_inj_cells.sum((1, 2))[:, None, None]

    going_right = (dx > sx)[:, None, None]
    going_up = (dy > sy)[:, None, None]

    def excl_cummax(v, axis, forward):
        c = lax.cummax(v, axis=axis, reverse=not forward)
        # shift one along the direction to make it exclusive
        pad = [(0, 0)] * v.ndim
        pad[axis] = (1, 0) if forward else (0, 1)
        sl = [slice(None)] * v.ndim
        sl[axis] = slice(0, -1) if forward else slice(1, None)
        return jnp.pad(c[tuple(sl)], pad, constant_values=NEG)

    # horizontal field (each packet uses RIGHT xor LEFT)
    qt_h = jnp.where(m_right, qt_of(PORT_RIGHT),
                     jnp.where(m_left, qt_of(PORT_LEFT), NEG))
    v_h = jnp.where(m_right | m_left, qt_h - steps_h * step_cyc, NEG)
    excl_h = jnp.where(going_right, excl_cummax(v_h, 2, True),
                       excl_cummax(v_h, 2, False))
    t_read_h = steps_h * step_cyc + jnp.maximum(base, excl_h)
    h_all = jnp.max(v_h, axis=(1, 2), keepdims=True)

    # vertical field (UP xor DOWN), carrying the whole horizontal segment
    qt_v = jnp.where(m_up, qt_of(PORT_UP),
                     jnp.where(m_down, qt_of(PORT_DOWN), NEG))
    v_v = jnp.where(m_up | m_down, qt_v - steps_v * step_cyc, NEG)
    carry_v = jnp.maximum(base, h_all)
    excl_v = jnp.where(going_up, excl_cummax(v_v, 1, True),
                       excl_cummax(v_v, 1, False))
    t_read_v = steps_v * step_cyc + jnp.maximum(carry_v, excl_v)
    v_all = jnp.max(v_v, axis=(1, 2), keepdims=True)

    # SELF delivery cell: everything upstream
    t_read_s = steps_self * step_cyc + jnp.maximum(carry_v, v_all)

    d1 = {}
    arrs = {}
    for d, member, steps, order in planes:
        if d == PORT_INJECT:
            arr = jnp.broadcast_to(t0_, member.shape)
            dly, too_old = d_inj_cells, too_inj
        else:
            arr = (t_read_h if order in ("x+", "x-")
                   else t_read_v if order in ("y+", "y-") else t_read_s)
            dly, too_old = delay_at(d, arr, member)
        d1[d] = dly
        arrs[d] = (arr, too_old, member)

    # ---- commit occupancy per port plane (dense reductions over L) ------
    new_grid = grid
    span = p.queue.history_span
    for d, member, steps, order in planes:
        arr, too_old, _ = arrs[d]
        in_win = member & ~too_old
        st = grid[:, :, d, :]                          # [h, w, 10]
        qt = st[..., qm.COL_QT]
        any_win = in_win.any(axis=0)
        arr_max = jnp.max(jnp.where(in_win, arr, -(2**62)), axis=0)
        proc_sum = jnp.sum(jnp.where(in_win, proc, 0), axis=0)
        qt_new = jnp.where(
            any_win, jnp.maximum(qt, arr_max) + proc_sum, qt)
        end = arr + d1[d] + proc
        newest = jnp.maximum(
            st[..., qm.COL_NEWEST],
            jnp.max(jnp.where(member, end, 0), axis=0))
        ws_new = jnp.where(
            any_win,
            jnp.maximum(st[..., qm.COL_WS], qt_new - span),
            st[..., qm.COL_WS])

        def msum(v):
            return jnp.sum(jnp.where(member, v, 0), axis=0)

        cols = jnp.stack([
            qt_new,
            ws_new,
            newest,
            st[..., qm.COL_SUM_ST] + msum(jnp.broadcast_to(
                proc, member.shape)),
            st[..., qm.COL_SUM_ST2] + msum(jnp.broadcast_to(
                proc * proc, member.shape)),
            st[..., qm.COL_N_ARR] + member.sum(axis=0, dtype=I64),
            st[..., qm.COL_REQS] + member.sum(axis=0, dtype=I64),
            st[..., qm.COL_UTIL] + msum(jnp.broadcast_to(
                proc, member.shape)),
            st[..., qm.COL_DELAY] + msum(d1[d]),
            st[..., qm.COL_ANA] + (member & too_old).sum(axis=0, dtype=I64),
        ], axis=-1)
        new_grid = new_grid.at[:, :, d, :].set(cols)

    data = q.data.at[: w * h * NUM_PORTS].set(
        new_grid.reshape(w * h * NUM_PORTS, qm.N_COLS))
    contention = sum(d1[d].sum((1, 2)) for d in range(NUM_PORTS))
    return q.replace(data=data), contention
