"""emesh_hop_by_hop: full per-hop 2D-mesh NoC with per-port contention.

Reference: `common/network/models/network_model_emesh_hop_by_hop.{h,cc}`
(SURVEY §2.6) + `components/router/router_model.cc:52-108`.

Per-packet semantics mirrored exactly (`routePacket`,
`network_model_emesh_hop_by_hop.cc:146-265`):
 - injection router at the sender (1 output port): router delay +
   injection-port contention;
 - XY routing (x first, then y); at every intermediate tile the mesh
   router adds router delay + output-port contention (queue model with
   processing = num_flits) and the output link adds link delay;
 - delivery goes through the destination's SELF port + SELF link;
 - the receiver adds num_flits serialization cycles
   (`network_model.cc:119-149`).

TPU-native form: instead of per-tile router objects called hop-by-hop on
the receiving process's sim thread, ALL in-flight packets advance one hop
per `lax.fori_loop` step; port occupancies live in one flat QueueArrays
[n_tiles*6 + scratch] updated with scatter-max/add (see
`scatter_queue_delay` for the conflict-approximation contract).

Ports: 0=RIGHT 1=LEFT 2=UP 3=DOWN 4=SELF 5=INJECT.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from graphite_tpu.models.queue_models import (
    QueueArrays, QueueParams, make_queues, scatter_queue_delay,
)
from graphite_tpu.time_types import cycles_to_ps, ps_to_cycles

I64 = jnp.int64
NUM_PORTS = 6
PORT_RIGHT, PORT_LEFT, PORT_UP, PORT_DOWN, PORT_SELF, PORT_INJECT = range(6)


@dataclasses.dataclass(frozen=True)
class HopByHopParams:
    n_tiles: int
    mesh_width: int
    mesh_height: int
    router_delay: int          # cycles
    link_delay: int            # cycles
    flit_width_bits: int
    freq_mhz: int
    queue: QueueParams
    contention_enabled: bool = True
    broadcast_tree: bool = True

    @classmethod
    def from_config(cls, sc, network: str) -> "HopByHopParams":
        from graphite_tpu.models.network_emesh import mesh_dims
        from graphite_tpu.models.network_user import _network_domain_freq_mhz

        cfg = sc.cfg
        sec = "network/emesh_hop_by_hop"
        w, h = mesh_dims(sc.application_tiles)
        qenabled = cfg.get_bool(f"{sec}/queue_model/enabled", True)
        qtype = cfg.get_string(f"{sec}/queue_model/type", "history_tree")
        return cls(
            n_tiles=sc.application_tiles,
            mesh_width=w,
            mesh_height=h,
            router_delay=cfg.get_int(f"{sec}/router/delay", 1),
            link_delay=cfg.get_int(f"{sec}/link/delay", 1),
            flit_width_bits=cfg.get_int(f"{sec}/flit_width", 64),
            freq_mhz=_network_domain_freq_mhz(
                sc, "NETWORK_USER" if network == "user" else "NETWORK_MEMORY"),
            queue=QueueParams.from_config(cfg, qtype, 1),
            contention_enabled=qenabled,
            broadcast_tree=cfg.get_bool(f"{sec}/broadcast_tree_enabled", True),
        )

    @property
    def max_hops(self) -> int:
        return self.mesh_width + self.mesh_height  # (w-1)+(h-1)+SELF+slack


@struct.dataclass
class NocState:
    queues: QueueArrays   # [n_tiles*6 + 1] port queues (+ scratch)


def init_noc_state(p: HopByHopParams) -> NocState:
    return NocState(queues=make_queues(p.n_tiles * NUM_PORTS + 1, p.queue))


def _xy_next(p: HopByHopParams, cur: jax.Array, dst: jax.Array):
    """XY route step: (next_tile, port).  x first, then y, else SELF."""
    w = p.mesh_width
    cx, cy = cur % w, cur // w
    dx, dy = dst % w, dst // w
    port = jnp.where(
        cx > dx, PORT_LEFT,
        jnp.where(cx < dx, PORT_RIGHT,
                  jnp.where(cy > dy, PORT_DOWN,
                            jnp.where(cy < dy, PORT_UP, PORT_SELF))))
    nxt = jnp.where(
        port == PORT_LEFT, cur - 1,
        jnp.where(port == PORT_RIGHT, cur + 1,
                  jnp.where(port == PORT_DOWN, cur - w,
                            jnp.where(port == PORT_UP, cur + w, cur))))
    return nxt.astype(jnp.int32), port.astype(jnp.int32)


def route_hop_by_hop(
    p: HopByHopParams,
    nst: NocState,
    src: jax.Array,        # int32[L]
    dst: jax.Array,        # int32[L]
    bits,                  # int | int64[L] modeled packet length
    t_send_ps: jax.Array,  # int64[L]
    mask: jax.Array,       # bool[L]
    enabled,               # bool[] models enabled
):
    """Route one packet per lane; returns (nst, arrival_ps, zero_load_ps,
    contention_ps)."""
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    live = mask & jnp.asarray(enabled, bool)
    flits = jnp.maximum(
        (jnp.asarray(bits, I64) + p.flit_width_bits - 1)
        // p.flit_width_bits, 1)
    t0 = ps_to_cycles(t_send_ps, p.freq_mhz)  # network-clock cycles

    # injection router (`routePacket` SEND_TILE branch)
    inj_qid = src * NUM_PORTS + PORT_INJECT
    if p.contention_enabled:
        queues, inj_delay = scatter_queue_delay(
            p.queue, nst.queues, inj_qid, t0, flits, live)
    else:
        queues, inj_delay = nst.queues, jnp.zeros_like(t0)
    t = t0 + p.router_delay + inj_delay
    zero_load = jnp.full_like(t0, p.router_delay)
    contention = inj_delay

    def hop(_, carry):
        queues, t, cur, delivered, zero_load, contention = carry
        nxt, port = _xy_next(p, cur, dst)
        go = live & ~delivered
        qid = cur * NUM_PORTS + port
        if p.contention_enabled:
            queues, cdelay = scatter_queue_delay(
                p.queue, queues, qid, t, flits, go)
        else:
            cdelay = jnp.zeros_like(t)
        step_zero = p.router_delay + p.link_delay
        t = jnp.where(go, t + step_zero + cdelay, t)
        zero_load = jnp.where(go, zero_load + step_zero, zero_load)
        contention = jnp.where(go, contention + cdelay, contention)
        delivered = delivered | (go & (port == PORT_SELF))
        cur = jnp.where(go, nxt, cur)
        return queues, t, cur, delivered, zero_load, contention

    delivered = ~live  # masked lanes are "done" from the start
    queues, t, cur, delivered, zero_load, contention = lax.fori_loop(
        0, p.max_hops, hop,
        (queues, t, src, delivered, zero_load, contention))

    # receiver serialization (`__processReceivedPacket`), skipped for
    # self-sends like the zero-load models
    ser = jnp.where(src == dst, 0, flits)
    t = t + ser
    zero_load = zero_load + ser

    arrival_ps = jnp.where(
        live, cycles_to_ps(t, p.freq_mhz), t_send_ps)
    zero_load_ps = jnp.where(live, cycles_to_ps(zero_load, p.freq_mhz), 0)
    contention_ps = jnp.where(live, cycles_to_ps(contention, p.freq_mhz), 0)
    return nst.replace(queues=queues), arrival_ps, zero_load_ps, contention_ps
