"""emesh_hop_by_hop: full per-hop 2D-mesh NoC with per-port contention.

Reference: `common/network/models/network_model_emesh_hop_by_hop.{h,cc}`
(SURVEY §2.6) + `components/router/router_model.cc:52-108`.

Per-packet semantics mirrored exactly (`routePacket`,
`network_model_emesh_hop_by_hop.cc:146-265`):
 - injection router at the sender (1 output port): router delay +
   injection-port contention;
 - XY routing (x first, then y); at every intermediate tile the mesh
   router adds router delay + output-port contention (queue model with
   processing = num_flits) and the output link adds link delay;
 - delivery goes through the destination's SELF port + SELF link;
 - the receiver adds num_flits serialization cycles
   (`network_model.cc:119-149`).

The reference's broadcast tree (`network_model_emesh_hop_by_hop.cc:163-222`,
knob `carbon_sim.cfg:304`) has no analog here BY CONSTRUCTION: nothing in
this engine injects NetPacket broadcasts into the modeled USER NoC — the
reference's broadcast senders are the MCP control plane (host-side here)
and coherence INV sweeps (whose MEMORY-net timing uses per-target
zero-load latencies in `memory/engine.py`).  The knob is therefore not
parsed rather than parsed-and-dead.

TPU-native form: instead of per-tile router objects called hop-by-hop on
the receiving process's sim thread, ALL in-flight packets advance one hop
per `lax.fori_loop` step; port occupancies live in one flat QueueArrays
[n_tiles*6 + scratch] updated with scatter-max/add (see
`scatter_queue_delay` for the conflict-approximation contract).

Ports: 0=RIGHT 1=LEFT 2=UP 3=DOWN 4=SELF 5=INJECT.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from graphite_tpu.models.queue_models import (
    QueueArrays, QueueParams, make_queues,
)
from graphite_tpu.time_types import cycles_to_ps, ps_to_cycles

I64 = jnp.int64
NUM_PORTS = 6
PORT_RIGHT, PORT_LEFT, PORT_UP, PORT_DOWN, PORT_SELF, PORT_INJECT = range(6)


@dataclasses.dataclass(frozen=True)
class HopByHopParams:
    n_tiles: int
    mesh_width: int
    mesh_height: int
    router_delay: int          # cycles
    link_delay: int            # cycles
    flit_width_bits: int
    freq_mhz: int
    queue: QueueParams
    contention_enabled: bool = True

    @classmethod
    def from_config(cls, sc, network: str) -> "HopByHopParams":
        from graphite_tpu.models.network_emesh import mesh_dims
        from graphite_tpu.models.network_user import _network_domain_freq_mhz

        cfg = sc.cfg
        sec = "network/emesh_hop_by_hop"
        w, h = mesh_dims(sc.application_tiles)
        qenabled = cfg.get_bool(f"{sec}/queue_model/enabled", True)
        qtype = cfg.get_string(f"{sec}/queue_model/type", "history_tree")
        return cls(
            n_tiles=sc.application_tiles,
            mesh_width=w,
            mesh_height=h,
            router_delay=cfg.get_int(f"{sec}/router/delay", 1),
            link_delay=cfg.get_int(f"{sec}/link/delay", 1),
            flit_width_bits=cfg.get_int(f"{sec}/flit_width", 64),
            freq_mhz=_network_domain_freq_mhz(
                sc, "NETWORK_USER" if network == "user" else "NETWORK_MEMORY"),
            queue=QueueParams.from_config(cfg, qtype, 1),
            contention_enabled=qenabled,
        )

    @property
    def max_hops(self) -> int:
        return self.mesh_width + self.mesh_height  # (w-1)+(h-1)+SELF+slack


@struct.dataclass
class NocState:
    queues: QueueArrays   # [n_tiles*6 + 1] port queues (+ scratch)


def init_noc_state(p: HopByHopParams) -> NocState:
    return NocState(queues=make_queues(p.n_tiles * NUM_PORTS + 1, p.queue))


def _xy_next(p: HopByHopParams, cur: jax.Array, dst: jax.Array):
    """XY route step: (next_tile, port).  x first, then y, else SELF."""
    w = p.mesh_width
    cx, cy = cur % w, cur // w
    dx, dy = dst % w, dst // w
    port = jnp.where(
        cx > dx, PORT_LEFT,
        jnp.where(cx < dx, PORT_RIGHT,
                  jnp.where(cy > dy, PORT_DOWN,
                            jnp.where(cy < dy, PORT_UP, PORT_SELF))))
    nxt = jnp.where(
        port == PORT_LEFT, cur - 1,
        jnp.where(port == PORT_RIGHT, cur + 1,
                  jnp.where(port == PORT_DOWN, cur - w,
                            jnp.where(port == PORT_UP, cur + w, cur))))
    return nxt.astype(jnp.int32), port.astype(jnp.int32)


def route_hop_by_hop(
    p: HopByHopParams,
    nst: NocState,
    src: jax.Array,        # int32[L]
    dst: jax.Array,        # int32[L]
    bits,                  # int | int64[L] modeled packet length
    t_send_ps: jax.Array,  # int64[L]
    mask: jax.Array,       # bool[L]
    enabled,               # bool[] models enabled
):
    """Route one packet per lane; returns (nst, arrival_ps, zero_load_ps,
    contention_ps).

    Dense formulation: each packet's XY path (a static unrolled
    elementwise computation — no per-hop loop) becomes a [L, H+1] matrix
    of (port queue, step) cells — column 0 the injection port, columns
    1..dist+1 the mesh hops including the SELF delivery step.  Contention
    is resolved against the PRE-call port state for every cell at once
    (one gather), with per-packet compounding of upstream delays applied
    by a two-pass fixed point (delays only shrink as arrivals grow, so
    two passes bracket the serial value), and the port occupancies are
    committed with one scatter-max/add round per call.

    This extends `scatter_queue_delay`'s same-call-conflict contract from
    single cells to whole paths: packets routed in the SAME subquantum
    iteration see each other's occupancy only through the next
    iteration's pre-state.  Cross-iteration behavior — the regime the
    reference's serial `routePacket` models — is unchanged.  The win is
    structural: a handful of gather/scatter kernels per call instead of
    ~6 per hop x w+h hops (each such kernel costs ~0.1-0.2 ms on TPU; the
    per-hop loop made hop-by-hop configs ~8x slower than hop-counter).
    """
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    live = mask & jnp.asarray(enabled, bool)
    flits = jnp.maximum(
        (jnp.asarray(bits, I64) + p.flit_width_bits - 1)
        // p.flit_width_bits, 1)
    t0 = ps_to_cycles(t_send_ps, p.freq_mhz)  # network-clock cycles
    w, h = p.mesh_width, p.mesh_height
    sx, sy = src % w, src // w
    dx, dy = dst % w, dst // w
    dist = (jnp.abs(sx - dx) + jnp.abs(sy - dy)).astype(I64)
    step_cyc = p.router_delay + p.link_delay
    zero_load = p.router_delay + (dist + 1) * step_cyc

    if p.contention_enabled:
        queues, contention = _dense_contention(
            p, nst.queues, live, flits, t0, sx, sy, dx, dy, dist)
        t = t0 + zero_load + contention
    else:
        queues = nst.queues
        contention = jnp.zeros_like(t0)
        t = t0 + zero_load

    # receiver serialization (`__processReceivedPacket`), skipped for
    # self-sends like the zero-load models
    ser = jnp.where(src == dst, 0, flits)
    t = t + ser
    zero_load = jnp.where(live, zero_load + ser, 0)

    arrival_ps = jnp.where(
        live, cycles_to_ps(t, p.freq_mhz), t_send_ps)
    zero_load_ps = cycles_to_ps(zero_load, p.freq_mhz)
    contention_ps = jnp.where(live, cycles_to_ps(contention, p.freq_mhz), 0)
    return nst.replace(queues=queues), arrival_ps, zero_load_ps, contention_ps


def _dense_contention(p, q, live, flits, t0, sx, sy, dx, dy, dist):
    """Per-port contention for all packets at once as DENSE grid math.

    XY routing makes every path a horizontal run (row sy, ports
    RIGHT/LEFT), a vertical run (column dx, ports UP/DOWN), one INJECT
    cell and one SELF cell — so cell membership, zero-load arrival
    offsets, in-path prefix sums of delays, and the per-port occupancy
    commits are all expressible as [L, h, w] elementwise masks, cumsums
    and reductions over the packet axis.  NO gather/scatter kernels:
    conflicting-index scatters cost ~0.1-1 ms EACH on TPU (serialized),
    which made both the per-hop loop and the flattened-path scatter
    formulations orders of magnitude slower than this.

    Same-call semantics follow the documented `scatter_queue_delay`
    contract lifted to paths: every cell's delay is read against the
    PRE-call port state (packets in one subquantum iteration see each
    other only through the next iteration's state), a packet's own
    upstream delays compound via a two-pass fixed point, and occupancy
    commits exactly (max of arrivals, then the sum of every processing
    time).
    """
    L = live.shape[0]
    w, h = p.mesh_width, p.mesh_height
    step_cyc = jnp.asarray(p.router_delay + p.link_delay, I64)
    X = jnp.arange(w, dtype=jnp.int32)[None, None, :]     # [1, 1, w]
    Y = jnp.arange(h, dtype=jnp.int32)[None, :, None]     # [1, h, 1]
    sx_, sy_ = sx[:, None, None], sy[:, None, None]
    dx_, dy_ = dx[:, None, None], dy[:, None, None]
    live_ = live[:, None, None]
    t0_ = t0[:, None, None]
    proc = flits[:, None, None]

    # port state as dense [h, w, 10] grids per direction
    from graphite_tpu.models import queue_models as qm

    grid = q.data[: w * h * NUM_PORTS].reshape(h, w, NUM_PORTS, qm.N_COLS)

    def port_state(d):
        return grid[None, :, :, d, :]       # [1, h, w, 10] broadcast over L

    def delay_at(d, arr, member):
        """Queue delay for member cells of port-plane d at arrival arr."""
        st = port_state(d)
        qt = st[..., qm.COL_QT]
        if p.queue.kind in ("history_list", "history_tree"):
            too_old = p.queue.analytical_enabled & (
                (arr + proc) < st[..., qm.COL_WS])
            mg1 = qm._mg1_wait(
                st[..., qm.COL_N_ARR], st[..., qm.COL_SUM_ST],
                st[..., qm.COL_SUM_ST2], st[..., qm.COL_NEWEST])
            dly = jnp.where(too_old, mg1, jnp.maximum(qt - arr, 0))
        else:
            too_old = jnp.zeros(arr.shape, bool)
            dly = jnp.maximum(qt - arr, 0)
        return jnp.where(member, dly, 0), too_old

    # ---- cell membership + hop index (steps from src) per plane ---------
    on_row = Y == sy_
    on_col = X == dx_
    m_right = live_ & on_row & (X >= sx_) & (X < dx_)
    m_left = live_ & on_row & (X <= sx_) & (X > dx_)
    m_up = live_ & on_col & (Y >= sy_) & (Y < dy_)
    m_down = live_ & on_col & (Y <= sy_) & (Y > dy_)
    m_self = live_ & (X == dx_) & (Y == dy_)
    m_inject = live_ & (X == sx_) & (Y == sy_)
    steps_h = jnp.abs(X - sx_).astype(I64)                 # horizontal run
    steps_v = (jnp.abs(dx_ - sx_) + jnp.abs(Y - sy_)).astype(I64)
    steps_self = dist[:, None, None]

    planes = (
        (PORT_RIGHT, m_right, steps_h, "x+"),
        (PORT_LEFT, m_left, steps_h, "x-"),
        (PORT_UP, m_up, steps_v, "y+"),
        (PORT_DOWN, m_down, steps_v, "y-"),
        (PORT_SELF, m_self, steps_self, None),
        (PORT_INJECT, m_inject, None, None),
    )

    def arr0_of(steps):
        # arrival BEFORE paying the cell's own router (serial-loop order)
        return t0_ + p.router_delay + steps * step_cyc

    def prefix(dly, order):
        """Exclusive prefix of a packet's own delays along path order."""
        if order == "x+":
            return jnp.cumsum(dly, axis=2) - dly
        if order == "x-":
            r = jnp.flip(jnp.cumsum(jnp.flip(dly, 2), axis=2), 2)
            return r - dly
        if order == "y+":
            return jnp.cumsum(dly, axis=1) - dly
        if order == "y-":
            r = jnp.flip(jnp.cumsum(jnp.flip(dly, 1), axis=1), 1)
            return r - dly
        return jnp.zeros_like(dly)

    def resolve(pass_delays):
        """One fixed-point pass: per-plane delays given upstream delays
        from the previous pass (None = zero-load arrivals)."""
        if pass_delays is None:
            inj_prev = jnp.zeros((L, 1, 1), I64)
            h_prev = v_prev = None
        else:
            inj_prev = pass_delays[PORT_INJECT].sum((1, 2))[:, None, None]
            h_prev = pass_delays[PORT_RIGHT] + pass_delays[PORT_LEFT]
            v_prev = pass_delays[PORT_UP] + pass_delays[PORT_DOWN]
        h_tot = (0 if h_prev is None
                 else h_prev.sum((1, 2))[:, None, None])
        v_tot = (0 if v_prev is None
                 else v_prev.sum((1, 2))[:, None, None])
        out = {}
        arrs = {}
        for d, member, steps, order in planes:
            if d == PORT_INJECT:
                arr = jnp.broadcast_to(t0_, member.shape)
            else:
                arr = arr0_of(steps) + inj_prev
                if order in ("x+", "x-") and h_prev is not None:
                    arr = arr + prefix(h_prev, order)
                elif order in ("y+", "y-"):
                    arr = arr + h_tot
                    if v_prev is not None:
                        arr = arr + prefix(v_prev, order)
                elif order is None and d == PORT_SELF:
                    arr = arr + h_tot + v_tot
            dly, too_old = delay_at(d, arr, member)
            out[d] = dly
            arrs[d] = (arr, too_old, member)
        return out, arrs

    d0, _ = resolve(None)
    d1, arrs = resolve(d0)

    # ---- commit occupancy per port plane (dense reductions over L) ------
    new_grid = grid
    span = p.queue.history_span
    for d, member, steps, order in planes:
        arr, too_old, _ = arrs[d]
        in_win = member & ~too_old
        st = grid[:, :, d, :]                          # [h, w, 10]
        qt = st[..., qm.COL_QT]
        any_win = in_win.any(axis=0)
        arr_max = jnp.max(jnp.where(in_win, arr, -(2**62)), axis=0)
        proc_sum = jnp.sum(jnp.where(in_win, proc, 0), axis=0)
        qt_new = jnp.where(
            any_win, jnp.maximum(qt, arr_max) + proc_sum, qt)
        end = arr + d1[d] + proc
        newest = jnp.maximum(
            st[..., qm.COL_NEWEST],
            jnp.max(jnp.where(member, end, 0), axis=0))
        ws_new = jnp.where(
            any_win,
            jnp.maximum(st[..., qm.COL_WS], qt_new - span),
            st[..., qm.COL_WS])

        def msum(v):
            return jnp.sum(jnp.where(member, v, 0), axis=0)

        cols = jnp.stack([
            qt_new,
            ws_new,
            newest,
            st[..., qm.COL_SUM_ST] + msum(jnp.broadcast_to(
                proc, member.shape)),
            st[..., qm.COL_SUM_ST2] + msum(jnp.broadcast_to(
                proc * proc, member.shape)),
            st[..., qm.COL_N_ARR] + member.sum(axis=0, dtype=I64),
            st[..., qm.COL_REQS] + member.sum(axis=0, dtype=I64),
            st[..., qm.COL_UTIL] + msum(jnp.broadcast_to(
                proc, member.shape)),
            st[..., qm.COL_DELAY] + msum(d1[d]),
            st[..., qm.COL_ANA] + (member & too_old).sum(axis=0, dtype=I64),
        ], axis=-1)
        new_grid = new_grid.at[:, :, d, :].set(cols)

    data = q.data.at[: w * h * NUM_PORTS].set(
        new_grid.reshape(w * h * NUM_PORTS, qm.N_COLS))
    contention = sum(d1[d].sum((1, 2)) for d in range(NUM_PORTS))
    return q.replace(data=data), contention
