"""graphite_tpu — a TPU-native tile-array multicore simulator.

A brand-new JAX/XLA/Pallas framework with the capabilities of MIT's Graphite
distributed multicore simulator (reference: nmtrmail/Graphite).  Instead of
Graphite's two-host-threads-per-tile + TCP-socket transport design
(`common/system/sim_thread.cc`, `common/transport/socktransport.cc`), all tile
state lives in a struct-of-arrays tensor sharded over the TPU's ICI mesh and
every tile advances one lax-barrier quantum per compiled XLA step.

Layer map (mirrors SURVEY.md §1, reference layers L0–L7):

    frontend/   the user-API surface (carbon_api live recording — the
                routine-replacement analog); trace/ holds the producers
    trace/      record schema, synthetic generators, benchmark skeletons
    config/     carbon_sim.cfg-compatible config + target-topology math
    models/     core timing (simple/iocoom), NoC models, DVFS, queue models
    memory/     cache arrays + coherence protocol engines (MSI/MOSI/shL2)
    engine/     the quantum-step state machine + Simulator orchestration
    golden/     sequential differential oracles (core + memory hierarchy)
    parallel/   device-mesh sharding: shard_map packed exchange (default
                multi-chip program) + legacy GSPMD specs, over ICI
    power/      McPAT/DSENT-equivalent energy models fed by event counters
    system/     host-side MCP analogs: threads, syscalls, stats, checkpoint
    tools/      drivers (graduated runner, regress sweep, output parsing)

Simulated time is exact integer picoseconds throughout
(reference: `common/misc/time_types.h:31-78`), so the package enables
jax_enable_x64 at import.  Hot per-quantum deltas still use int32 internally.
"""

import os

import jax

# Picosecond-resolution simulated time needs 64-bit integers (a 1 GHz tile
# overflows int32 picoseconds after ~2ms of simulated time).  TPUs emulate
# int64 in pairs of int32 ops; the hot kernels keep deltas in int32.
jax.config.update("jax_enable_x64", True)

# The compiled quantum loop is a large program (core + protocol + NoC +
# sync FSMs fused into one while_loop); cold compiles run 1-3 minutes at
# large tile counts.  Cache compilations persistently so repeat runs of
# the same topology start in seconds.  GRAPHITE_TPU_NO_CACHE=1 opts out.
if (not os.environ.get("GRAPHITE_TPU_NO_CACHE")
        and jax.config.jax_compilation_cache_dir is None):
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.expanduser("~"), ".cache", "graphite_tpu_xla"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)

__version__ = "0.1.0"

from graphite_tpu.time_types import Time, Latency  # noqa: E402,F401
from graphite_tpu.config import ConfigFile, SimConfig  # noqa: E402,F401
