"""Application frontends: the Carbon user API (live threaded apps recorded
to traces) and trace capture helpers.

The reference's frontend is Intel Pin (`pin/pin_sim.cc`) instrumenting x86
binaries; on TPU hosts the frontend is a *trace producer* (SURVEY §7).  This
package provides the lite-mode analog: apps written against the Carbon user
API (`common/user/carbon_user.h`, `capi.h`, `sync_api.h`,
`thread_support.h`) execute functionally as real host threads while every
API call records trace events; the recorded per-tile streams then replay
through the vectorized timing engine.
"""

from graphite_tpu.frontend.carbon_api import (  # noqa: F401
    CAPI_message_receive_w,
    CAPI_message_send_w,
    CarbonApp,
    CarbonBarrier,
    CarbonCond,
    CarbonMutex,
    carbon_access,
    carbon_barrier_init,
    carbon_barrier_wait,
    carbon_branch,
    carbon_brk,
    carbon_close,
    carbon_disable_models,
    carbon_enable_models,
    carbon_get_affinity,
    carbon_get_tile_id,
    carbon_instr,
    carbon_join_thread,
    carbon_load,
    carbon_lseek,
    carbon_migrate_self,
    carbon_mmap,
    carbon_munmap,
    carbon_open,
    carbon_read,
    carbon_set_affinity,
    carbon_set_tile_frequency,
    carbon_spawn_thread,
    carbon_stat_size,
    carbon_store,
    carbon_unlink,
    carbon_work,
    carbon_write,
    carbon_yield,
)
