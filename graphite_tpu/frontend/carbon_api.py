"""The Carbon user API as a live, trace-recording frontend.

Mirrors `common/user/` (reference): `CarbonStartSim/StopSim`
(`carbon_user.h:18-20`), CAPI messaging (`capi.h:18-24`),
`CarbonSpawnThread/JoinThread` (`thread_support.h:66-71`),
`CarbonMutex/Cond/Barrier*` (`sync_api.h:19-34`), DVFS get/set
(`dvfs.h:42-48`), and `CarbonEnableModels/DisableModels`
(`performance_counter_support.h:8-9`).

Execution model (the lite-mode analog, `pin/lite/routine_replace.cc`):
the app runs *functionally* as real host threads — messages move through
host queues, sync uses host primitives, memory reads return live values —
while every API call records a trace event on the calling tile's stream.
The recorded per-tile streams then replay through the vectorized timing
engine, which re-executes the synchronization/coherence state machines in
simulated time.  Live load values are recorded as check oracles
(FLAG_CHECK), so the replay cross-validates the functional execution.

Compute between API calls is annotated with `carbon_work(...)` — the
trace-driven equivalent of Pin's instruction instrumentation
(`pin/instruction_modeling.cc`): a frontend that cannot observe every
machine instruction asks the app to declare its basic blocks.

Oversubscription (threads > tiles): the scheduler queues threads per tile
and every blocking call is a scheduling point that releases the core
(`ThreadManager::stallThread`).  Threads sharing a tile serialize onto
ONE engine lane; so that a blocked thread's record never sits in front of
the co-located record that would resolve it, every blocking call records
its rendezvous at COMPLETION time (after rescheduling — hence after any
co-located segments that ran meanwhile), and barriers/condvars use the
split ops (BARRIER_ARRIVE/BARRIER_SYNC, MUTEX_UNLOCK+COND_JOIN+MUTEX_LOCK
— see trace/schema.py).  Co-located threads may therefore synchronize
freely with each other and across tiles: barriers, condvars, mutexes,
CAPI pairs, joins.
"""

from __future__ import annotations

import threading

from graphite_tpu.trace.schema import Op, TraceBatch, TraceBuilder

_TLS = threading.local()
_APP_LOCK = threading.Lock()
_APP: "CarbonApp | None" = None


def _app() -> "CarbonApp":
    if _APP is None:
        raise RuntimeError("no CarbonApp running (use CarbonApp.start)")
    return _APP


def _tile() -> int:
    t = getattr(_TLS, "tile", None)
    if t is None:
        raise RuntimeError("not inside a Carbon app thread")
    return t


class CarbonApp:
    """One simulated application: functional threads + recorded traces.

    `CarbonStartSim` boots the simulator in-process and returns to `main`
    (`carbon_user.cc:22-75`); here `start()` runs `main_fn` on tile 0 and
    blocks until every spawned thread exits (`CarbonStopSim`), yielding the
    recorded `TraceBatch`.  `run()` replays it through the timing engine.
    """

    def __init__(self, sim_config, max_threads: int | None = None):
        from graphite_tpu.system.thread_scheduler import (
            RoundRobinThreadScheduler,
        )

        self.sim_config = sim_config
        self.n_tiles = sim_config.application_tiles
        self.max_threads = max_threads or 4 * self.n_tiles
        self.builders = [TraceBuilder() for _ in range(self.n_tiles)]
        self._threads: dict[int, threading.Thread] = {}  # tid -> host thread
        self._next_tid = 1
        self._alloc_lock = threading.Lock()
        # scheduling: per-tile FIFO run queues; queued threads block until
        # the occupant exits or yields (the reference's cooperative scheme)
        self.scheduler = RoundRobinThreadScheduler(self.n_tiles)
        self._sched_cv = threading.Condition()
        # functional state
        self._channels: dict[tuple[int, int], list] = {}
        self._chan_cv = threading.Condition()
        self._memory: dict[int, int] = {}
        self._mem_lock = threading.Lock()
        self._mutexes: dict[int, threading.Lock] = {}
        self._conds: dict[int, threading.Condition] = {}
        self._barriers: dict[int, threading.Barrier] = {}
        # published-signal sequence per cond (the COND_JOIN rendezvous key)
        self._cond_signal_seq: dict[int, int] = {}
        self._cond_meta_lock = threading.Lock()
        self._next_sync_id = [0]
        self._errors: list = []
        # centralized OS view (MCP-side servers)
        from graphite_tpu.system.syscall_server import SyscallServer, VMManager

        self.syscalls = SyscallServer()
        self.vm = VMManager()

    # ---- lifecycle ------------------------------------------------------

    def start(self, main_fn, *args) -> TraceBatch:
        global _APP
        with _APP_LOCK:
            if _APP is not None:
                raise RuntimeError("another CarbonApp is already running")
            _APP = self
        try:
            with self._sched_cv:
                self.scheduler.schedule(0, requested_tile=0)
            t = self._spawn_thread(0, main_fn, args)
            t.join()
            # join every straggler (threads the app spawned but never joined)
            while True:
                with self._alloc_lock:
                    live = [th for th in self._threads.values()
                            if th.is_alive()]
                if not live:
                    break
                for th in live:
                    th.join()
        finally:
            with _APP_LOCK:
                _APP = None
        if self._errors:
            raise self._errors[0]
        # one stream-end marker per tile (co-located thread segments were
        # serialized in scheduling order; joins synchronize on tile streams)
        for b in self.builders:
            b.exit()
        return TraceBatch.from_builders(self.builders)

    def run(self, **sim_kwargs):
        """Record (if not yet recorded via start) and replay through the
        timing engine, returning `SimResults`."""
        from graphite_tpu.engine.simulator import Simulator

        batch = TraceBatch.from_builders(self.builders)
        sim = Simulator(self.sim_config, batch, **sim_kwargs)
        return sim.run()

    # ---- internals ------------------------------------------------------

    def _spawn_thread(self, tid: int, fn, args) -> threading.Thread:
        def runner():
            _TLS.tid = tid
            self._wait_for_core(tid)
            _TLS.tile = self.scheduler.threads[tid].tile
            try:
                fn(*args)
            except BaseException as e:  # surface app errors to start()
                self._errors.append(e)
            finally:
                with self._sched_cv:
                    self.scheduler.thread_exit(tid)
                    self._sched_cv.notify_all()

        th = threading.Thread(target=runner, name=f"carbon-thread-{tid}",
                              daemon=True)
        with self._alloc_lock:
            self._threads[tid] = th
        th.start()
        return th

    def _wait_for_core(self, tid: int) -> None:
        """Block until this thread is the head of its tile's run queue."""
        with self._sched_cv:
            while True:
                tile = self.scheduler.threads[tid].tile
                if self.scheduler.running_on(tile) == tid:
                    return
                self._sched_cv.wait()

    def _alloc_tid(self) -> int:
        with self._alloc_lock:
            if self._next_tid >= self.max_threads:
                raise RuntimeError(
                    f"out of threads ({self.max_threads}) for "
                    "CarbonSpawnThread"
                )
            t = self._next_tid
            self._next_tid += 1
            return t

    def _alloc_sync_id(self) -> int:
        with self._alloc_lock:
            i = self._next_sync_id[0]
            self._next_sync_id[0] += 1
            return i


# ---- thread API (`thread_support.h:66-71`) ------------------------------


def carbon_get_tile_id() -> int:
    """`CarbonGetTileId` — the calling thread's tile."""
    return _tile()


def _blocking_wait(app: "CarbonApp", wait_fn):
    """Run a host-blocking wait as a scheduling point
    (`ThreadManager::stallThread`): release the tile's core so co-located
    queued threads can run, wait, then reacquire the core."""
    tid = _TLS.tid
    with app._sched_cv:
        app.scheduler.block_thread(tid)
        app._sched_cv.notify_all()
    try:
        return wait_fn()
    finally:
        with app._sched_cv:
            app.scheduler.unblock_thread(tid)
            app._sched_cv.notify_all()
        app._wait_for_core(tid)


def carbon_spawn_thread(fn, *args, affinity=None) -> int:
    """`CarbonSpawnThread`: the scheduler places the thread round-robin
    over (affinity-allowed) tiles (`masterScheduleThread`); when every tile
    is occupied the thread queues until its tile frees (cooperative
    scheduling — the shipped reference scheme).  Returns the thread id for
    `carbon_join_thread`."""
    app = _app()
    tid = app._alloc_tid()
    with app._sched_cv:
        target_tile = app.scheduler.schedule(tid, affinity=affinity)
    app.builders[_tile()].thread_spawn(target_tile)
    app._spawn_thread(tid, fn, args)
    return tid


def carbon_join_thread(tid: int) -> None:
    """`CarbonJoinThread` — blocks until the target exits; replay pins the
    joiner's clock at the target tile's stream end (`masterJoinThread`;
    with co-located threads this is the tile's *last* exit — a documented
    serialization approximation).

    A join is a scheduling point (`ThreadManager::stallThread`): the joiner
    releases its core while blocked so queued threads — including a target
    queued on the joiner's own tile — can run.  A same-tile join records no
    THREAD_JOIN (the serialized stream order already carries the timing)."""
    app = _app()
    target_tile = app.scheduler.threads[tid].tile
    if target_tile != _tile():
        app.builders[_tile()].thread_join(target_tile)
    th = app._threads.get(tid)
    if th is not None and th.is_alive():
        _blocking_wait(app, th.join)


def carbon_yield() -> None:
    """`CarbonYieldThread` (`thread_scheduler.h:48` yieldThread): requeue
    behind any waiting co-located thread; blocks until rescheduled."""
    app = _app()
    tid = _TLS.tid
    with app._sched_cv:
        app.scheduler.yield_thread(tid)
        app._sched_cv.notify_all()
    app._wait_for_core(tid)


def carbon_migrate_self(dst_tile: int) -> None:
    """`CarbonMigrateThread` (self-migration): subsequent records land on
    the destination tile's stream; blocks until the destination grants."""
    app = _app()
    tid = _TLS.tid
    with app._sched_cv:
        app.scheduler.migrate(tid, dst_tile)
        app._sched_cv.notify_all()
    app._wait_for_core(tid)
    _TLS.tile = dst_tile


def carbon_set_affinity(tiles) -> None:
    """`CarbonSchedSetAffinity` on the calling thread; migrates it when the
    current tile leaves the mask (`masterSchedSetAffinity`)."""
    app = _app()
    tid = _TLS.tid
    with app._sched_cv:
        app.scheduler.set_affinity(tid, tiles)
        app._sched_cv.notify_all()
    app._wait_for_core(tid)
    _TLS.tile = app.scheduler.threads[tid].tile


def carbon_get_affinity():
    """`CarbonSchedGetAffinity` on the calling thread."""
    return _app().scheduler.get_affinity(_TLS.tid)


# ---- CAPI messaging (`capi.h:18-24` → `core.cc:67-123`) -----------------


def CAPI_message_send_w(sender: int, receiver: int, payload) -> None:
    app = _app()
    assert sender == _tile(), "CAPI send must come from the sending tile"
    size = len(payload) if hasattr(payload, "__len__") else 8
    app.builders[sender].send(receiver, size)
    with app._chan_cv:
        app._channels.setdefault((sender, receiver), []).append(payload)
        app._chan_cv.notify_all()


def CAPI_message_receive_w(sender: int, receiver: int, size: int = 8):
    app = _app()
    assert receiver == _tile(), "CAPI recv must run on the receiving tile"

    def _wait():
        with app._chan_cv:
            while not app._channels.get((sender, receiver)):
                app._chan_cv.wait()
            return app._channels[(sender, receiver)].pop(0)

    payload = _blocking_wait(app, _wait)
    # record at COMPLETION: a co-located sender's SEND record (emitted
    # while this thread was blocked) must precede this NET_RECV on the
    # shared lane, or the replay would deadlock; the engine's
    # clock = max(clock, arrival) charges the same simulated wait
    app.builders[receiver].recv(sender, size)
    return payload


# ---- sync API (`sync_api.h:19-34` → MCP SyncServer) ---------------------


class CarbonMutex:
    def __init__(self):
        app = _app()
        self.id = app._alloc_sync_id()
        app._mutexes[self.id] = threading.Lock()
        app.builders[_tile()].mutex_init(self.id)

    def lock(self):
        app = _app()
        # record at COMPLETION (after the functional acquire): a
        # co-located holder's MUTEX_UNLOCK then precedes this record on
        # the shared lane; the engine's grant still charges
        # max(handoff - clock, 0) of simulated wait
        _blocking_wait(app, app._mutexes[self.id].acquire)
        app.builders[_tile()].mutex_lock(self.id)

    def unlock(self):
        app = _app()
        app.builders[_tile()].mutex_unlock(self.id)
        app._mutexes[self.id].release()

    def __enter__(self):
        self.lock()
        return self

    def __exit__(self, *exc):
        self.unlock()


class CarbonCond:
    def __init__(self, mutex: CarbonMutex):
        app = _app()
        self.id = app._alloc_sync_id()
        self.mutex = mutex
        app._conds[self.id] = threading.Condition(app._mutexes[mutex.id])
        app.builders[_tile()].cond_init(self.id)

    def wait(self):
        # split form (schema COND_JOIN): release the mutex at wait start,
        # rendezvous with the waking signal's published sequence at
        # completion, then re-acquire — so a co-located signaler's record
        # can land between the two halves on the shared lane
        app = _app()
        app.builders[_tile()].mutex_unlock(self.mutex.id)
        _blocking_wait(app, app._conds[self.id].wait)
        with app._cond_meta_lock:
            seq = app._cond_signal_seq.get(self.id, 0)
        app.builders[_tile()].cond_join(self.id, seq)
        app.builders[_tile()].mutex_lock(self.mutex.id)

    def _publish(self) -> None:
        """Bump the cond's published-signal sequence (the COND_JOIN
        rendezvous key) before the record + functional notify."""
        app = _app()
        with app._cond_meta_lock:
            app._cond_signal_seq[self.id] = (
                app._cond_signal_seq.get(self.id, 0) + 1)

    def _notify(self, notify_all: bool) -> None:
        # POSIX allows signaling without holding the mutex; Python's
        # Condition does not — take the lock when the caller doesn't hold it
        app = _app()
        cond = app._conds[self.id]
        fn = cond.notify_all if notify_all else cond.notify

        def _locked():
            with app._mutexes[self.mutex.id]:
                fn()

        try:
            fn()
        except RuntimeError:
            _blocking_wait(app, _locked)

    def signal(self):
        self._publish()
        _app().builders[_tile()].cond_signal(self.id, publish=True)
        self._notify(False)

    def broadcast(self):
        self._publish()
        _app().builders[_tile()].cond_broadcast(self.id, publish=True)
        self._notify(True)


class CarbonBarrier:
    def __init__(self, count: int):
        app = _app()
        self.id = app._alloc_sync_id()
        # the action hook runs exactly once per release, BEFORE any waiter
        # resumes — a race-free GLOBAL release-generation counter (a
        # thread-local arrival count would drift when participants skip
        # rounds)
        self._gen = 0
        self._gen_lock = threading.Lock()

        def _on_release():
            with self._gen_lock:
                self._gen += 1

        app._barriers[self.id] = threading.Barrier(count, action=_on_release)
        app.builders[_tile()].barrier_init(self.id, count)

    def wait(self):
        # split form (schema BARRIER_ARRIVE/BARRIER_SYNC): contribute the
        # arrival BEFORE blocking (a co-located peer's arrival would
        # otherwise sit unreachable behind this lane's blocked record),
        # then rendezvous with the release generation that freed us.
        # Bounded-overcharge contract: the generation is read AFTER this
        # thread resumes, so if another full release completes in the gap
        # the recorded generation is one (or more) later and replay
        # charges that later release's time — a small overcharge bounded
        # by the live run's own scheduling skew, same class as the
        # split-op contract documented at the schema.  (Capturing the
        # generation inside the Barrier action hook cannot attribute it
        # per-waiter: the hook runs once per release on one thread.)
        app = _app()
        app.builders[_tile()].barrier_arrive(self.id)
        _blocking_wait(app, app._barriers[self.id].wait)
        with self._gen_lock:
            gen = self._gen
        app.builders[_tile()].barrier_sync(self.id, gen)


def carbon_barrier_init(count: int) -> CarbonBarrier:
    return CarbonBarrier(count)


def carbon_barrier_wait(bar: CarbonBarrier) -> None:
    bar.wait()


# ---- memory (redirected ops → the coherence engine on replay) -----------


def _wrap_i32(value: int) -> int:
    """Wrap to signed 32-bit: trace aux fields are int32; the engine
    compares them as uint32 bit patterns."""
    return ((value & 0xFFFFFFFF) ^ 0x80000000) - 0x80000000


def carbon_store(addr: int, value: int, size: int = 4) -> None:
    """Store through the simulated memory hierarchy (replay runs the full
    L1/L2/directory path; functionally a host-memory write)."""
    app = _app()
    app.builders[_tile()].store_value(addr, _wrap_i32(value), size)
    with app._mem_lock:
        app._memory[addr] = value & 0xFFFFFFFF


def carbon_load(addr: int, size: int = 4, check: bool = False) -> int:
    """Load (live host value returned; replay runs the full coherence path).

    With check=True the live value becomes the replay's check oracle
    (FLAG_CHECK) and a disagreement reports func_errors.  Only valid for
    *order-deterministic* reads — e.g. barrier-separated single-writer
    data.  Values ordered by mutexes/condvars are NOT replay-checkable:
    the engine grants locks in simulated-time order, which legitimately
    differs from the host interleaving the recording observed."""
    app = _app()
    with app._mem_lock:
        value = app._memory.get(addr, 0)
    b = app.builders[_tile()]
    if check:
        b.load_check(addr, _wrap_i32(value), size)
    else:
        b.load(addr, size)
    return value


# ---- compute annotation (`pin/instruction_modeling.cc` analog) ----------


def carbon_work(n_instr: int, cycles: int | None = None) -> None:
    """Declare a straight-line run of `n_instr` instructions costing
    `cycles` (default 1 IPC) — recorded at basic-block granularity
    (Op.BBLOCK), the engine's native compressed form."""
    if n_instr <= 0:
        return
    _app().builders[_tile()].bblock(n_instr, cycles if cycles is not None
                                    else n_instr)


def carbon_instr(op: Op = Op.IALU, pc: int = 0) -> None:
    """Record one instruction (fine-grained form of carbon_work)."""
    _app().builders[_tile()].instr(op, pc=pc)


def carbon_branch(taken: bool, pc: int = 0) -> None:
    _app().builders[_tile()].branch(taken, pc=pc)


# ---- model toggles + DVFS (`performance_counter_support.h`, `dvfs.h`) ---


def carbon_enable_models() -> None:
    b = _app().builders[_tile()]
    b._append(Op.ENABLE_MODELS)


def carbon_disable_models() -> None:
    b = _app().builders[_tile()]
    b._append(Op.DISABLE_MODELS)


def carbon_set_tile_frequency(domain: int, freq_mhz: int) -> None:
    """`CarbonSetDVFS` (`dvfs.h:42-48`) — takes effect on replay."""
    _app().builders[_tile()].dvfs_set(domain, freq_mhz)


def carbon_get_tile_frequency(domain: int) -> None:
    """`CarbonGetDVFS` — the replay charges the DVFS-network round trip to
    the queried manager (1 magic-network cycle each way, like a syscall's
    SYSTEM-net trip); the frequency itself is a replay-side quantity (the
    live frontend has no simulated clock), so the call returns None."""
    b = _app().builders[_tile()]
    b._append(Op.DVFS_GET, aux0=domain)


# ---- syscalls (SyscallMdl client → MCP SyscallServer) -------------------
# Each call executes against the app's central simulated-OS view and
# records one SYSCALL trace event; replay charges the SYSTEM-network round
# trip to the MCP (`syscall_model.cc` marshalling, `syscall_server.cc`).

from graphite_tpu.trace.schema import (  # noqa: E402
    SYS_ACCESS, SYS_BRK, SYS_CLOSE, SYS_LSEEK, SYS_MMAP, SYS_MUNMAP,
    SYS_OPEN, SYS_READ, SYS_STAT, SYS_UNLINK, SYS_WRITE,
)


def _sysrec(sc_class: int, arg: int = 0) -> None:
    _app().builders[_tile()].syscall(sc_class, arg)


def carbon_open(path: str, flags: int = 0) -> int:
    _sysrec(SYS_OPEN)
    return _app().syscalls.open(path, flags)


def carbon_close(fd: int) -> int:
    _sysrec(SYS_CLOSE)
    return _app().syscalls.close(fd)


def carbon_read(fd: int, nbytes: int):
    _sysrec(SYS_READ, nbytes)
    return _app().syscalls.read(fd, nbytes)


def carbon_write(fd: int, data: bytes) -> int:
    _sysrec(SYS_WRITE, len(data))
    return _app().syscalls.write(fd, data)


def carbon_lseek(fd: int, offset: int, whence: int = 0) -> int:
    _sysrec(SYS_LSEEK)
    return _app().syscalls.lseek(fd, offset, whence)


def carbon_access(path: str) -> int:
    _sysrec(SYS_ACCESS)
    return _app().syscalls.access(path)


def carbon_unlink(path: str) -> int:
    _sysrec(SYS_UNLINK)
    return _app().syscalls.unlink(path)


def carbon_stat_size(path: str) -> int:
    _sysrec(SYS_STAT)
    return _app().syscalls.stat_size(path)


def carbon_brk(addr: int = 0) -> int:
    _sysrec(SYS_BRK)
    return _app().vm.brk(addr)


def carbon_mmap(length: int) -> int:
    _sysrec(SYS_MMAP, length)
    return _app().vm.mmap(length)


def carbon_munmap(base: int) -> int:
    _sysrec(SYS_MUNMAP)
    return _app().vm.munmap(base)
