"""Golden sequential oracle for differential testing.

The reference framework's role of "second implementation to diff against"
(the cycle-parity harness of SURVEY §4) is played here by an independent
event-driven Python interpreter of the same trace semantics: it shares no
code with the vectorized engine and orders every decision by simulated
time, so engine-vs-oracle equality on random traces checks that the
masked-iteration engine implements exactly the time-ordered semantics it
claims.
"""

from graphite_tpu.golden.interpreter import GoldenResult, run_golden  # noqa: F401
