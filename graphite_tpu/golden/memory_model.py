"""Sequential golden model of the private-L1/L2 dram-directory protocols.

Independent second implementation of the memory-hierarchy semantics for
differential testing of `memory/engine.py` (the vectorized MSI/MOSI
engine).  Written as a classic one-access-at-a-time interpreter over
plain Python data structures — per-tile caches as lists, the directory as
dicts of sets — deliberately sharing **no code** with the engine beyond
`MemParams` (the config-derived geometry/timing constants, which are
inputs, not the logic under test).

Semantics modeled (reference citations, same as the engine's):
 - requester path `l1_cache_cntlr.cc:90-180` / `l2_cache_cntlr.cc:181-292`:
   instruction-buffer fast path, L1 lookup, L2 fill, upgrade-as-refetch,
   miss request to the home tile;
 - directory FSM `dram_directory_cntlr.cc:44-559`: immediate grants from
   UNCACHED/SHARED, INV multicast on EX, FLUSH/WB to the owner on
   MODIFIED, NULLIFY on directory-set conflict with the original request
   saved and resumed, per-home same-address completion floor;
 - sharer service `l2_cache_cntlr.cc:295-503`: INV/FLUSH invalidate
   L1+L2, WB downgrades (MSI M->S; MOSI M->O keeps the dirty line);
 - MOSI extras (`pr_l1_pr_l2_dram_directory_mosi/`): O state,
   cache-to-cache SH fetches, INV_FLUSH_COMBINED data supplier;
 - all five directory schemes (`directory_schemes/directory_entry_*.cc`):
   full_map, limited_no_broadcast displacement, ackwise /
   limited_broadcast sweeps, limitless software-trap penalty;
 - timing: cache/tag cycles at per-tile frequency, DVFS-domain
   synchronization delays, directory access cycles, DRAM latency +
   processing, MEMORY-net zero-load hop + serialization latency.

Ordering discipline: accesses are processed **synchronously** in the
order the caller (the golden core interpreter) presents them — smallest
core clock first.  The vectorized engine instead interleaves protocol
phases across subquantum iterations; the two orderings agree exactly
whenever concurrent transactions touch disjoint lines (message-carried
timestamps make disjoint transactions commutative) and may diverge by a
bounded race window when two tiles race for the same line, an eviction
races a re-request, or directory-set victims race.  The differential
tests therefore assert bit-exactness on serialized/disjoint workloads
and a quantified envelope on racy ones.
"""

from __future__ import annotations

from graphite_tpu.memory.params import MemParams
from graphite_tpu.memory.state import (
    MOD_CORE, MOD_DIR, MOD_L1D, MOD_L1I, MOD_L2, MOD_NET_MEM,
)
from graphite_tpu.trace.schema import (
    FLAG_MEM0_VALID, FLAG_MEM0_WRITE, FLAG_MEM1_VALID, FLAG_MEM1_WRITE, Op,
)

# cache states (`cache_state.h`) — redeclared, not imported: the oracle
# must not share logic tables with the engine
INVALID, SHARED, MODIFIED, EXCLUSIVE, OWNED = 0, 1, 2, 3, 4

DIR_UNCACHED, DIR_SHARED, DIR_MODIFIED, DIR_OWNED = 0, 1, 2, 3


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def _cycles_to_ps(cycles: int, freq_mhz: int) -> int:
    return _ceil_div(cycles * 10**6, freq_mhz)


def _readable(st: int) -> bool:
    return st in (SHARED, MODIFIED, EXCLUSIVE, OWNED)


def _writable(st: int) -> bool:
    return st in (MODIFIED, EXCLUSIVE)


class _Cache:
    """Per-tile set-associative cache mirroring `Cache` semantics
    (`cache.h:26-135`): modulo set hash, LRU with invalid-way-first
    victims, invalidate keeps the tag (state-only)."""

    __slots__ = ("sets", "ways", "tags", "state", "lru", "policy")

    def __init__(self, num_sets: int, num_ways: int, policy: str = "lru"):
        self.sets = num_sets
        self.ways = num_ways
        self.policy = policy
        self.tags = [[-1] * num_ways for _ in range(num_sets)]
        self.state = [[INVALID] * num_ways for _ in range(num_sets)]
        self.lru = [list(range(num_ways)) for _ in range(num_sets)]

    def _set(self, line: int) -> int:
        return line % self.sets

    def lookup(self, line: int):
        """(hit, way, state) — first matching valid way."""
        s = self._set(line)
        for w in range(self.ways):
            if self.tags[s][w] == line and self.state[s][w] != INVALID:
                return True, w, self.state[s][w]
        return False, 0, INVALID

    def touch(self, line: int, way: int) -> None:
        """Hit recency update — a no-op under round_robin
        (`RoundRobinReplacementPolicy::update`)."""
        if self.policy == "round_robin":
            return
        self._rotate(line, way)

    def _rotate(self, line: int, way: int) -> None:
        s = self._set(line)
        rank = self.lru[s][way]
        for w in range(self.ways):
            if self.lru[s][w] < rank:
                self.lru[s][w] += 1
        self.lru[s][way] = 0

    def pick_victim(self, line: int):
        """(way, victim_valid, victim_line, victim_state).  lru: first
        invalid way, else max rank.  round_robin: the rotating index
        regardless of validity (`round_robin_replacement_policy.cc`)."""
        s = self._set(line)
        if self.policy != "round_robin":
            for w in range(self.ways):
                if self.state[s][w] == INVALID:
                    return w, False, self.tags[s][w], INVALID
        w = max(range(self.ways), key=lambda x: self.lru[s][x])
        valid = self.state[s][w] != INVALID
        return w, valid, self.tags[s][w], self.state[s][w]

    def insert_at(self, line: int, way: int, st: int) -> None:
        s = self._set(line)
        self.tags[s][way] = line
        self.state[s][way] = st
        # insertion always rotates: under round_robin the rank rotation IS
        # the decrementing replacement index; under lru it makes way MRU
        self._rotate(line, way)

    def set_state(self, line: int, way: int, st: int) -> None:
        self.state[self._set(line)][way] = st

    def invalidate(self, line: int) -> None:
        hit, way, _ = self.lookup(line)
        if hit:
            self.set_state(line, way, INVALID)


class _DirEntry:
    __slots__ = ("tag", "dstate", "owner", "sharers")

    def __init__(self):
        self.tag = -1
        self.dstate = DIR_UNCACHED
        self.owner = -1
        self.sharers: set[int] = set()


class _Home:
    """One home tile's directory slice + serialization bookkeeping."""

    __slots__ = ("entries", "last_line", "last_done_ps",
                 "cdata_line", "cdata_valid")

    def __init__(self, dir_sets: int, dir_ways: int):
        self.entries = [[_DirEntry() for _ in range(dir_ways)]
                        for _ in range(dir_sets)]
        self.last_line = -1
        self.last_done_ps = 0
        self.cdata_line = -1
        self.cdata_valid = False


class GoldenMemory:
    """Callable memory hierarchy for the golden interpreter.

    `access_record(tile, op, flags, pc, addr0, addr1, clock_ps, enabled)`
    processes every memory slot of one trace record (icache fetch, mem
    operand 0, mem operand 1 — `fillNumMemoryOperands`,
    `pin/instruction_modeling.cc:33-124`) and returns the record's total
    memory latency in ps, mutating global cache/directory state.
    """

    def __init__(self, mp: MemParams, freq_mhz):
        self.mp = mp
        T = mp.n_tiles
        self.freq = [int(f) for f in freq_mhz] if hasattr(
            freq_mhz, "__len__") else [int(freq_mhz)] * T
        def geom(lp, t):
            s = lp.tile_sets[t] if lp.tile_sets is not None else lp.num_sets
            w = lp.tile_ways[t] if lp.tile_ways is not None else lp.num_ways
            return s, w

        self.l1i = [_Cache(*geom(mp.l1i, t), mp.l1i.replacement)
                    for t in range(T)]
        self.l1d = [_Cache(*geom(mp.l1d, t), mp.l1d.replacement)
                    for t in range(T)]
        self.l2 = [_Cache(*geom(mp.l2, t), mp.l2.replacement)
                   for t in range(T)]
        # which L1 caches each L2 entry ((set, way) -> MOD_L1I/MOD_L1D/0)
        self.l2_cloc = [dict() for _ in range(T)]
        self.homes = {h: _Home(mp.dir_sets, mp.dir_ways)
                      for h in mp.mc_tiles}
        # serial per-hop MEMORY net when `[network] memory =
        # emesh_hop_by_hop` — the independent counterpart of the engine's
        # mem_net_send routing (fan-outs share the engine's approximation;
        # see _HbhNet.fanout)
        if mp.net_hbh is not None:
            from graphite_tpu.golden.interpreter import _HbhNet

            self.net = _HbhNet(mp.net_hbh)
        elif mp.net_atac is not None:
            # coherence messages over the ATAC optical NoC (`[network]
            # memory = atac`) — the serial hub-queue oracle
            from graphite_tpu.golden.interpreter import _AtacNet

            self.net = _AtacNet(mp.net_atac)
        else:
            self.net = None
        self.instr_buf = [-1] * T
        # L2 miss-type tracking (`cache.cc getMissType`): three per-tile
        # bucket sets (the model hashes lines to 2^16 buckets — the
        # engine's bitmap spec, shared so the differential stays exact)
        self.mt_fetched = [set() for _ in range(T)]
        self.mt_evicted = [set() for _ in range(T)]
        self.mt_invalidated = [set() for _ in range(T)]
        self.counters = {
            k: [0] * T
            for k in ("l1i_hits", "l1i_misses", "l1d_read_hits",
                      "l1d_read_misses", "l1d_write_hits",
                      "l1d_write_misses", "l2_hits", "l2_misses",
                      "evictions", "invalidations", "dir_accesses",
                      "dir_broadcasts", "dram_reads", "dram_writes",
                      "dram_total_lat_ps", "l2_cold_misses",
                      "l2_capacity_misses", "l2_sharing_misses",
                      "line_util_reads", "line_util_writes")
        }
        # L2 cache-line utilization (`cache_line_utilization.h`): per-line
        # [reads, writes] while resident, keyed (set, way) like the
        # engine's packed counter cell; histogram of totals on departure
        self.counters["line_util_hist"] = [[0] * 8 for _ in range(T)]
        self.l2_util = [dict() for _ in range(T)]
        # optional protocol-event observer (analysis/protocol.py model
        # checker); None in normal runs — zero semantic effect
        self.event_cb = None

    def _emit(self, etype: str, **kw) -> None:
        if self.event_cb is not None:
            self.event_cb(etype, kw)

    # -- L2 cache-line utilization (engine's _util_* counterparts) --------

    def _util_touch(self, t, line, way, write, enabled):
        if not (self.mp.l2.track_line_utilization and enabled):
            return
        u = self.l2_util[t].setdefault((line % self.l2[t].sets, way),
                                       [0, 0])
        if u[write] < 0xFFFF:
            u[write] += 1

    def _util_depart(self, t, line, way, enabled):
        """Classify + drop the counter of a line leaving (set, way)."""
        if not (self.mp.l2.track_line_utilization and enabled):
            return
        key = (line % self.l2[t].sets, way)
        rd, wr = self.l2_util[t].pop(key, (0, 0))
        total = rd + wr
        self.counters["line_util_hist"][t][min(7, total.bit_length())] += 1
        self.counters["line_util_reads"][t] += rd
        self.counters["line_util_writes"][t] += wr

    def _util_init(self, t, line, way, write, enabled):
        """A filled line's counter restarts with the miss access itself."""
        if not (self.mp.l2.track_line_utilization and enabled):
            return
        self.l2_util[t][(line % self.l2[t].sets, way)] = (
            [0, 1] if write else [1, 0])

    # -- L2 miss-type tracking (`cache.h:45-49`, hashed-bucket model) ------

    @staticmethod
    def _mt_bucket(line):
        return line & 0xFFFF

    def _mt_classify(self, t, line, enabled):
        if not self.mp.l2.track_miss_types or not enabled:
            return
        b = self._mt_bucket(line)
        c = self.counters
        if b in self.mt_evicted[t]:
            c["l2_capacity_misses"][t] += 1
        elif b in self.mt_invalidated[t] or b in self.mt_fetched[t]:
            c["l2_sharing_misses"][t] += 1
        else:
            c["l2_cold_misses"][t] += 1

    def _mt_invalidate(self, t, line):
        if self.mp.l2.track_miss_types:
            self.mt_invalidated[t].add(self._mt_bucket(line))

    def _mt_evict(self, t, line):
        if self.mp.l2.track_miss_types:
            self.mt_evicted[t].add(self._mt_bucket(line))

    def _mt_insert(self, t, line):
        # clearMissTypeTrackingSets: erase from exactly ONE set
        if not self.mp.l2.track_miss_types:
            return
        b = self._mt_bucket(line)
        if b in self.mt_evicted[t]:
            self.mt_evicted[t].discard(b)
        elif b in self.mt_invalidated[t]:
            self.mt_invalidated[t].discard(b)
        else:
            self.mt_fetched[t].discard(b)
        self.mt_fetched[t].add(b)

    # -- timing helpers ----------------------------------------------------

    def _cc(self, t: int, n, enabled: bool) -> int:
        # n may be per-tile (np array) under heterogeneous geometries
        if hasattr(n, "__len__"):
            n = int(n[t])
        return _cycles_to_ps(int(n), self.freq[t]) if enabled else 0

    def _dir_ps(self, n: int, enabled: bool) -> int:
        return _cycles_to_ps(n, self.mp.dir_freq_mhz) if enabled else 0

    def _net_ps(self, src: int, dst: int, bits: int, enabled: bool) -> int:
        mp = self.mp
        if mp.net_kind == "magic":
            return _cycles_to_ps(1, mp.net_freq_mhz) if enabled else 0
        w = mp.mesh_width
        hops = abs(src % w - dst % w) + abs(src // w - dst // w)
        cycles = hops * mp.hop_latency_cycles
        if src != dst:
            cycles += _ceil_div(bits, mp.flit_width_bits)
        return _cycles_to_ps(cycles, mp.net_freq_mhz) if enabled else 0

    def _net_arrive(self, src: int, dst: int, bits: int, t_send: int,
                    enabled: bool) -> int:
        """Arrival time of a unicast coherence message sent at t_send —
        per-hop serial routing under memory = emesh_hop_by_hop, else
        t_send + zero-load."""
        if self.net is not None:
            return self.net.route_bits(src, dst, bits, t_send, enabled)
        return t_send + self._net_ps(src, dst, bits, enabled)

    def _net_fanout(self, src: int, targets, bits: int, t0: int,
                    enabled: bool, n_copies=None, ranks=None,
                    copy_set=None) -> dict:
        """{target: arrival} for a home's multicast (engine contract —
        see _HbhNet.fanout).  Broadcast sweeps pass n_copies (total
        copies occupying the inject port), ranks (target -> rank among
        ALL copies), and copy_set (every copy destination — the ATAC
        mirror counts its ONet members exactly)."""
        if self.net is not None:
            return self.net.fanout(src, targets, bits, t0, enabled,
                                   n_copies, ranks, copy_set)
        return {s: t0 + self._net_ps(src, s, bits, enabled)
                for s in targets}

    def _dram_ps(self, enabled: bool) -> int:
        mp = self.mp
        return ((mp.dram_latency_ns + mp.dram_processing_ns) * 1000
                if enabled else 0)

    def _sync(self, t: int, a: int, b: int, enabled: bool) -> int:
        return self._cc(t, self.mp.sync_cycles(a, b), enabled)

    def _dsync(self, a: int, b: int, enabled: bool) -> int:
        return self._dir_ps(self.mp.sync_cycles(a, b), enabled)

    def _home_of(self, line: int) -> int:
        return self.mp.mc_tiles[line % len(self.mp.mc_tiles)]

    # -- eviction messages (`l2_cache_cntlr.cc:75-116 insertCacheLine` ->
    #    `processInvRepFromL2Cache`/`processFlushRep...` eviction branches)

    def _apply_eviction(self, src: int, line: int, is_flush: bool,
                        etime: int, enabled: bool) -> None:
        home = self._home_of(line)
        hm = self.homes[home]
        if enabled:
            self.counters["evictions"][home] += 1
            if is_flush:
                self.counters["dram_writes"][home] += 1
        self._emit("evict", src=src, home=home, line=line, dirty=is_flush)
        if is_flush:
            # park the flushed line in the home's one-entry data buffer
            # (`_cached_data_list`): a later request skips the DRAM read
            hm.cdata_line = line
            hm.cdata_valid = True
        e = self._dir_find(hm, line)
        if e is None:
            return
        e.sharers.discard(src)
        if is_flush:
            e.owner = -1
        if not e.sharers:
            e.dstate = DIR_UNCACHED
        elif is_flush:
            e.dstate = DIR_SHARED  # MOSI O departure leaves clean sharers

    def _dir_find(self, hm: _Home, line: int):
        row = hm.entries[line % self.mp.dir_sets]
        for e in row:
            if e.tag == line:
                return e
        return None

    # -- sharer-side FWD service (`l2_cache_cntlr.cc:295-503`) -------------

    def _serve_fwd(self, s: int, kind: str, line: int, ftime: int,
                   home: int, enabled: bool):
        """Serve one INV/FLUSH/WB request at sharer `s`; returns
        (ack_time, supplies_data)."""
        mp = self.mp
        hit, way, st = self.l2[s].lookup(line)
        assert hit, (
            f"golden: FWD {kind} to tile {s} for line {line:#x} not held "
            "(directory/cache divergence)")
        l2_cost = self._cc(
            s, mp.l2.tags_cycles if kind == "inv"
            else mp.l2.data_and_tags_cycles, enabled)
        done = (ftime + self._sync(s, MOD_L2, MOD_NET_MEM, enabled) + l2_cost
                + self._cc(s, mp.l1d.tags_cycles, enabled)
                + 2 * self._sync(s, MOD_L1D, MOD_L2, enabled))
        cloc = self.l2_cloc[s].get((line % self.l2[s].sets, way), 0)
        if kind in ("inv", "flush"):
            if cloc == MOD_L1I:
                self.l1i[s].invalidate(line)
            elif cloc == MOD_L1D:
                self.l1d[s].invalidate(line)
            self._util_depart(s, line, way, enabled)
            self.l2[s].set_state(line, way, INVALID)
            self._mt_invalidate(s, line)
            self.l2_cloc[s].pop((line % self.l2[s].sets, way), None)
            if enabled and kind == "inv":
                self.counters["invalidations"][s] += 1
        else:  # wb: downgrade, keep the line
            if mp.is_mosi:
                wb_state = OWNED if st == MODIFIED else st
            else:
                wb_state = SHARED
            l1 = (self.l1i[s] if cloc == MOD_L1I
                  else self.l1d[s] if cloc == MOD_L1D else None)
            if l1 is not None:
                l1_hit, l1_way, _ = l1.lookup(line)
                if l1_hit:
                    l1.set_state(line, l1_way, wb_state)
            self.l2[s].set_state(line, way, wb_state)
        ack_bits = mp.req_bits if kind == "inv" else mp.rep_bits
        supplies = kind in ("flush", "wb")
        self._emit("serve", tile=s, home=home, line=line, kind=kind,
                   supplies=supplies)
        return self._net_arrive(s, home, ack_bits, done, enabled), supplies

    # -- the directory transaction (`dram_directory_cntlr.cc:44-559`) ------

    def _home_txn(self, home: int, requester: int, line: int,
                  is_write: bool, arrival: int, enabled: bool,
                  _resumed: bool = False):
        """Run one EX/SH request at `home`; returns the reply arrival time
        at the requester."""
        mp = self.mp
        hm = self.homes[home]
        if _resumed:
            rtime = arrival  # saved request: message sync already charged
        else:
            rtime = arrival + (
                self._dsync(MOD_DIR, MOD_L2, enabled) if requester == home
                else self._dsync(MOD_DIR, MOD_NET_MEM, enabled))
        if line == hm.last_line:
            rtime = max(rtime, hm.last_done_ps)
        if enabled:
            self.counters["dir_accesses"][home] += 1

        # entry lookup / allocation (`processDirectoryEntryAllocationReq`)
        row = hm.entries[line % mp.dir_sets]
        entry = self._dir_find(hm, line)
        if entry is None:
            entry = next((e for e in row if e.tag == -1), None)
            if entry is None:
                # victim: min sharer count, first way on ties
                entry = min(row, key=lambda e: len(e.sharers))
                victim_live = entry.dstate != DIR_UNCACHED
                v_line, v_state = entry.tag, entry.dstate
                v_owner, v_sharers = entry.owner, set(entry.sharers)
                # install the new entry immediately (`replaceDirectoryEntry`)
                entry.tag, entry.dstate = line, DIR_UNCACHED
                entry.owner, entry.sharers = -1, set()
                if victim_live:
                    # NULLIFY the victim line, then resume the original
                    # request; the resumed request's time does NOT wait on
                    # the nullify (message-carried clocks; only the floor
                    # and dir state couple them)
                    self._run_protocol(
                        home, hm, requester, v_line, "nullify", rtime,
                        v_state, v_owner, v_sharers, None, enabled)
                    return self._home_txn(home, requester, line, is_write,
                                          rtime, enabled, _resumed=True)
            else:
                entry.tag, entry.dstate = line, DIR_UNCACHED
                entry.owner, entry.sharers = -1, set()
        return self._run_protocol(
            home, hm, requester, line, "ex" if is_write else "sh", rtime,
            entry.dstate, entry.owner, set(entry.sharers), entry, enabled)

    def _run_protocol(self, home, hm: _Home, requester, line, mtype, rtime,
                      dstate, owner, sharers, entry, enabled):
        """The per-state FSM for one EX/SH/NULLIFY transaction."""
        mp = self.mp
        self._emit("req", home=home, requester=requester, line=line,
                   mtype=mtype, dstate=dstate)
        eff_time = rtime + self._dir_ps(mp.dir_access_cycles, enabled)
        is_ex = mtype == "ex"
        is_sh = mtype == "sh"
        is_nullify = mtype == "nullify"
        uncached = dstate == DIR_UNCACHED
        shared = dstate == DIR_SHARED
        modified = dstate == DIR_MODIFIED
        owned = dstate == DIR_OWNED
        k = mp.max_hw_sharers
        already = requester in sharers

        sh_over = sh_over_m = False
        if mp.dir_type == "limited_no_broadcast":
            sh_over = (is_sh and (shared or owned) and len(sharers) >= k
                       and not already)
            sh_over_m = (is_sh and modified and len(sharers) >= k
                         and not already)
        if mp.dir_type == "limitless" and entry is not None and enabled:
            sw_mode = (len(sharers) > k) or (
                is_sh and not already and len(sharers) >= k
                and (shared or owned))
            if sw_mode:
                eff_time += self._dir_ps(mp.limitless_trap_cycles, True)

        # (a) immediate grants (UNCACHED; MSI also SHARED+SH from DRAM)
        if mp.is_mosi:
            imm = (is_ex and uncached) or (is_sh and uncached)
        else:
            imm = (is_ex and uncached) or (
                is_sh and (uncached or shared) and not sh_over)
        if imm:
            if is_ex:
                entry.dstate = DIR_MODIFIED
                entry.owner = requester
                entry.sharers = {requester}
            else:
                entry.dstate = DIR_SHARED
                entry.owner = -1
                if not shared:
                    entry.sharers = set()
                entry.sharers.add(requester)
            cdata_hit = hm.cdata_valid and hm.cdata_line == line
            rep_ready = eff_time + (0 if cdata_hit else self._dram_ps(enabled))
            if cdata_hit:
                hm.cdata_valid = False
            elif enabled:
                self.counters["dram_reads"][home] += 1
                self.counters["dram_total_lat_ps"][home] += \
                    self._dram_ps(True)
            hm.last_line, hm.last_done_ps = line, rep_ready
            self._emit("reply", home=home, requester=requester, line=line,
                       mtype=mtype,
                       source="cdata" if cdata_hit else "dram")
            return self._net_arrive(home, requester, mp.rep_bits,
                                    rep_ready, enabled)

        # (b) fan-out: build the (target -> message kind) map
        if mp.is_mosi:
            fan_inv = (is_ex or is_nullify) and (shared or owned)
            sh_fetch = is_sh and (shared or owned) and not sh_over
        else:
            fan_inv = (is_ex or is_nullify) and shared
            sh_fetch = False
        fan_owner = modified
        targets: dict[int, str] = {}
        if fan_inv:
            for s in sharers:
                targets[s] = "inv"
            if mp.is_mosi and (owned or (is_ex and shared)):
                # one sweep target supplies the data (`INV_FLUSH_COMBINED`)
                pick = owner if (owned and owner >= 0) else (
                    min(sharers) if sharers else -1)
                if pick >= 0:
                    targets[pick] = "flush"
        elif sh_fetch:
            src = owner if (owned and owner >= 0) else (
                min(sharers) if sharers else -1)
            if src >= 0:
                targets[src] = "wb"
        elif fan_owner:
            targets[owner] = "wb" if is_sh else "flush"

        if sh_over:
            # displacement: invalidate the lowest non-owner sharer (or
            # flush the owner when it is the only sharer) so the requester
            # fits in the hardware sharer list
            non_owner = sorted(s for s in sharers
                               if not (owned and s == owner))
            victim_is_owner = not non_owner
            victim = non_owner[0] if non_owner else owner
            entry.sharers.discard(victim)
            if victim_is_owner:
                entry.owner = -1
                entry.dstate = DIR_SHARED
            targets = {victim: "inv"}
            if mp.is_mosi and (shared or victim_is_owner):
                targets[victim] = "flush"
            if owned and not victim_is_owner and owner >= 0:
                targets[owner] = "wb"
        if sh_over_m:
            # M entry at capacity: FLUSH the owner, entry empties before
            # the SH finish installs {requester} alone
            targets = {owner: "flush"}
            entry.dstate = DIR_UNCACHED
            entry.owner = -1
            entry.sharers = set()
            modified = False

        broadcast = (mp.dir_type in ("ackwise", "limited_broadcast")
                     and fan_inv and len(sharers) > k)
        if broadcast and enabled:
            self.counters["dir_broadcasts"][home] += 1

        # serve each forwarded request; acks gate the finish.  An
        # overflowed-entry INV sweep broadcasts to EVERY tile (the
        # engine's `send | over_bc` row): the inject port then carries T
        # copies and each true holder's copy ranks by its tile id among
        # all T — non-holders drop theirs silently, but their copies
        # still occupy the port (n_copies/ranks mirror the engine's
        # cumsum over the full broadcast row)
        txn_time = eff_time
        got_data = False
        dir_acc = self._dir_ps(mp.dir_access_cycles, enabled)
        if broadcast:
            f_arrivals = self._net_fanout(
                home, list(targets), mp.req_bits, eff_time, enabled,
                n_copies=mp.n_tiles,
                ranks={s: s for s in targets},
                # the engine's broadcast row is `send | over_bc` — ALL
                # tiles, requester included (engine.py:1825; only the
                # shared-L2 engine excludes the requester)
                copy_set=list(range(mp.n_tiles)))
        else:
            f_arrivals = self._net_fanout(home, list(targets), mp.req_bits,
                                          eff_time, enabled)
        for s in sorted(targets):
            self._emit("fwd", home=home, target=s, line=line,
                       kind=targets[s], broadcast=broadcast)
        for s in sorted(targets):
            f_arrive = f_arrivals[s]
            ack_time, supplies = self._serve_fwd(
                s, targets[s], line, f_arrive, home, enabled)
            txn_time = max(txn_time, ack_time + dir_acc)
            got_data = got_data or supplies
            if targets[s] == "wb" and not mp.is_mosi and enabled:
                # MSI writes WB data through to DRAM (entry turns clean)
                self.counters["dram_writes"][home] += 1
            if targets[s] in ("inv", "flush") and entry is not None:
                entry.sharers.discard(s)
                if s == entry.owner:
                    entry.owner = -1

        # finish: directory end-state + reply
        if entry is not None and not is_nullify:
            if is_ex:
                entry.dstate = DIR_MODIFIED
                entry.owner = requester
                entry.sharers = {requester}
            else:
                from_dirty = mp.is_mosi and (modified or owned)
                entry.dstate = DIR_OWNED if from_dirty else DIR_SHARED
                if not from_dirty:
                    entry.owner = -1
                entry.sharers.add(requester)
        cdata_hit = hm.cdata_valid and hm.cdata_line == line
        need_dram = not (got_data or cdata_hit) and not is_nullify
        if cdata_hit:
            hm.cdata_valid = False
        rep_ready = txn_time + (self._dram_ps(enabled) if need_dram else 0)
        if need_dram and enabled:
            self.counters["dram_reads"][home] += 1
            self.counters["dram_total_lat_ps"][home] += self._dram_ps(True)
        hm.last_line, hm.last_done_ps = line, rep_ready
        if is_nullify:
            return None
        self._emit("reply", home=home, requester=requester, line=line,
                   mtype=mtype,
                   source=("c2c" if got_data
                           else "cdata" if cdata_hit else "dram"))
        return self._net_arrive(home, requester, mp.rep_bits, rep_ready,
                                enabled)

    # -- requester slot (`l1_cache_cntlr.cc:90-180` + reply fill) ----------

    def _slot(self, t: int, is_icache: bool, addr: int, write: bool,
              clock_ps: int, enabled: bool) -> int:
        mp = self.mp
        line = (addr & 0xFFFFFFFF) >> mp.line_bits
        comp = MOD_L1I if is_icache else MOD_L1D
        l1 = self.l1i[t] if is_icache else self.l1d[t]
        lp = mp.l1i if is_icache else mp.l1d
        c = self.counters

        # instruction-buffer fast path (`core.cc:205-220`)
        if is_icache:
            ibuf_hit = line == self.instr_buf[t]
            self.instr_buf[t] = line
            if ibuf_hit:
                if enabled:
                    c["l1i_hits"][t] += 1
                return self._cc(t, 1, enabled)

        sclock = clock_ps + self._sync(t, MOD_CORE, comp, enabled)
        l1_dat = self._cc(t, lp.data_and_tags_cycles, enabled)
        l1_tag = self._cc(t, lp.tags_cycles, enabled)

        hit, way, st = l1.lookup(line)
        if hit and (_writable(st) if write else _readable(st)):
            l1.touch(line, way)
            if enabled:
                if is_icache:
                    c["l1i_hits"][t] += 1
                elif write:
                    c["l1d_write_hits"][t] += 1
                else:
                    c["l1d_read_hits"][t] += 1
            self._emit("hit", tile=t, line=line, write=write, level="l1")
            return sclock + l1_dat - clock_ps

        # L1 miss: invalidate the stale L1 line, try L2
        l1.invalidate(line)
        if enabled:
            if is_icache:
                c["l1i_misses"][t] += 1
            elif write:
                c["l1d_write_misses"][t] += 1
            else:
                c["l1d_read_misses"][t] += 1

        l2 = self.l2[t]
        l2_hit, l2_way, l2_st = l2.lookup(line)
        if l2_hit and (_writable(l2_st) if write else _readable(l2_st)):
            if enabled:
                c["l2_hits"][t] += 1
            self._util_touch(t, line, l2_way, write, enabled)
            done = (sclock + l1_tag + self._sync(t, comp, MOD_L2, enabled)
                    + self._cc(t, mp.l2.data_and_tags_cycles, enabled)
                    + l1_dat)
            self._fill_l1(t, is_icache, line, l2_st, l2_way)
            l2.touch(line, l2_way)
            self._emit("hit", tile=t, line=line, write=write, level="l2")
            return done - clock_ps

        if enabled:
            c["l2_misses"][t] += 1
        req_send = sclock + l1_tag + self._cc(t, mp.l2.tags_cycles, enabled)
        home = self._home_of(line)

        # upgrade: write to a readable-but-unwritable L2 line — invalidate
        # + eviction to the home, then a full EX refetch
        # (`processExReqFromL1Cache`; documented engine simplification)
        # classification reads the sets BEFORE this access mutates them
        self._mt_classify(t, line, enabled)
        if l2_hit and write and l2_st in (SHARED, OWNED):
            dirty = l2_st == OWNED
            self._util_depart(t, line, l2_way, enabled)
            l2.set_state(line, l2_way, INVALID)
            self._mt_invalidate(t, line)
            self.l2_cloc[t].pop((line % self.l2[t].sets, l2_way), None)
            self._apply_eviction(
                t, line, dirty,
                self._net_arrive(t, home, mp.req_bits, req_send, enabled),
                enabled)

        arrival = self._net_arrive(t, home, mp.req_bits, req_send, enabled)
        rep_time = self._home_txn(home, t, line, write, arrival, enabled)

        # reply fill (`handleMsgFromDramDirectory` + insertCacheLine)
        new_state = MODIFIED if write else SHARED
        fill_l2 = (rep_time + self._sync(t, MOD_L2, MOD_NET_MEM, enabled)
                   + self._cc(t, mp.l2.data_and_tags_cycles, enabled))
        v_way, v_valid, v_line, v_state = l2.pick_victim(line)
        if v_valid:
            if enabled:
                c["evictions"][t] += 1
            self._mt_evict(t, v_line)
            v_dirty = v_state in (MODIFIED, OWNED)
            v_home = self._home_of(v_line)
            e_arr = self._net_arrive(
                t, v_home, mp.rep_bits if v_dirty else mp.req_bits,
                fill_l2, enabled)
            self.l2_cloc[t].pop((v_line % self.l2[t].sets, v_way), None)
            self._apply_eviction(t, v_line, v_dirty, e_arr, enabled)
            self._util_depart(t, v_line, v_way, enabled)
        self._mt_insert(t, line)
        l2.insert_at(line, v_way, new_state)
        self._util_init(t, line, v_way, write, enabled)
        self._fill_l1(t, is_icache, line, new_state, v_way)
        self._emit("fill", tile=t, line=line, write=write, state=new_state)
        done = fill_l2 + l1_dat
        return done - clock_ps

    def _fill_l1(self, t: int, is_icache: bool, line: int, st: int,
                 l2_way: int) -> None:
        """Insert into the right L1 (`insertCacheLineInL1`), tracking the
        L2 entry's cached-location byte and clearing the L1 victim's."""
        mp = self.mp
        l1 = self.l1i[t] if is_icache else self.l1d[t]
        way, v_valid, v_line, _ = l1.pick_victim(line)
        if v_valid:
            vh, vw, _ = self.l2[t].lookup(v_line)
            if vh:
                self.l2_cloc[t].pop((v_line % self.l2[t].sets, vw), None)
        l1.insert_at(line, way, st)
        self.l2_cloc[t][(line % self.l2[t].sets, l2_way)] = (
            MOD_L1I if is_icache else MOD_L1D)

    # -- public entry ------------------------------------------------------

    def access_record(self, t: int, op: int, flags: int, pc: int,
                      addr0: int, addr1: int, clock_ps: int,
                      enabled: bool) -> int:
        """Total memory latency (ps) of one record's slots; every slot's
        latency is measured from the record's base clock (the per-operand
        costs land on the clock together, `simple_core_model.cc:53-90`)."""
        mp = self.mp
        acc = 0
        is_instr = op < 15 or op == int(Op.BBLOCK)
        if mp.icache_modeling and enabled and is_instr:
            acc += self._slot(t, True, pc, False, clock_ps, enabled)
        if flags & FLAG_MEM0_VALID:
            acc += self._slot(t, False, addr0,
                              bool(flags & FLAG_MEM0_WRITE), clock_ps,
                              enabled)
        if flags & FLAG_MEM1_VALID:
            acc += self._slot(t, False, addr1,
                              bool(flags & FLAG_MEM1_WRITE), clock_ps,
                              enabled)
        return acc
