"""Event-driven sequential interpreter of the trace semantics (the oracle).

Independent second implementation for differential testing: a classic
discrete-event loop (always advance the runnable tile with the smallest
clock; blocked tiles park until their wake event exists).  Every
synchronization decision is ordered by (simulated time, tile id) — the
semantics the vectorized engine (`engine/step.py`) claims to implement
with masked iterations:

 - costs: static table cycles at the tile frequency (ceil ps conversion),
   one-bit branch predictor (predict last outcome, pc % size), BBLOCK runs
   aux1 cycles / aux0 instructions, dynamic records carry their cost;
 - SEND: zero-load arrival = clock + route latency (magic 1 cycle;
   hop-counter XY hops * (router+link) + receive serialization flits,
   self-sends skip serialization); RECV: clock = max(clock, arrival),
   charged as an instruction only when it waited;
 - BARRIER: release at the maximum arrival time (`SimBarrier`);
 - MUTEX: handoff at unlock time to the waiter with the earliest
   (clock, tile) key (`SimMutex`);
 - COND: wait releases the mutex; a signal at time S wakes the earliest
   eligible waiter (wait began at or before S) at time S, which then
   re-acquires the mutex; signals with no eligible waiter are lost;
   broadcast wakes every eligible waiter (`SimCond`);
 - THREAD_JOIN: clock pinned at max(clock, target stream's exit clock);
   Op.SPAWN (dynamic) sets clock = max(clock, value);
 - SYSCALL / DVFS_GET: the MCP / DVFS-manager round trip (2 cycles at
   1 GHz — both networks are magic);
 - ENABLE/DISABLE_MODELS: zero cost and no counters while disabled.

Scope: core timing + sync/messaging as above, plus — when shared memory
is enabled and the trace touches memory — the full private-L1/L2
dram-directory hierarchy via `golden.memory_model.GoldenMemory` (an
independent sequential implementation; see its docstring for the
ordering discipline and the exact-vs-envelope test contract).  DVFS
retuning remains out of scope — run with a fixed frequency.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from graphite_tpu.trace.schema import FLAG_BRANCH_TAKEN, Op, TraceBatch

ANY_SENDER = -1  # CAPI wildcard sender (`engine/step.py:57`)

HEADER_BYTES = 64  # NetPacket header (`network.h:27-53`)
FAR = 2**62


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def cycles_to_ps(cycles: int, freq_mhz: int) -> int:
    return _ceil_div(cycles * 10**6, freq_mhz)


@dataclasses.dataclass
class GoldenResult:
    clock_ps: np.ndarray
    instruction_count: np.ndarray
    recv_instructions: np.ndarray
    sync_instructions: np.ndarray
    bp_correct: np.ndarray
    bp_incorrect: np.ndarray
    # per-tile memory-hierarchy counters ({name: np.ndarray[T]}), None
    # when the run had no memory model
    mem_counters: dict | None = None
    # per-tile rejected DVFS_SET requests (engine: `dvfs.errors`)
    dvfs_errors: np.ndarray | None = None
    # per-tile final CORE-domain frequency after in-trace retunes
    core_freq_mhz: np.ndarray | None = None


class _Net:
    def __init__(self, kind, freq_mhz, mesh_width, hop_cycles, flit_bits):
        self.kind = kind
        self.freq_mhz = freq_mhz
        self.w = mesh_width
        self.hop_cycles = hop_cycles
        self.flit_bits = flit_bits

    def latency_ps(self, src, dst, payload_bytes, enabled):
        if self.kind == "magic":
            return cycles_to_ps(1, self.freq_mhz)
        hops = abs(src % self.w - dst % self.w) + abs(
            src // self.w - dst // self.w)
        cycles = hops * self.hop_cycles
        if src != dst and self.flit_bits > 0:
            cycles += _ceil_div((HEADER_BYTES + payload_bytes) * 8,
                                self.flit_bits)
        return cycles_to_ps(cycles, self.freq_mhz) if enabled else 0


class _HbhNet:
    """Serial per-hop emesh_hop_by_hop oracle: the reference's hop loop
    (`network_model_emesh_hop_by_hop.cc:146-265` + router contention)
    implemented one packet at a time over per-port queue dicts — the
    independent counterpart of the engine's dense-grid formulation (which
    must match it exactly for cross-call queueing; same-call packet
    batching follows the engine's documented approximation contract, so
    differential tests use serialized traffic)."""

    def __init__(self, p):
        self.p = p  # HopByHopParams (config-derived constants)
        self.q: dict[int, dict] = {}  # qid -> queue scalars

    def _queue(self, qid):
        return self.q.setdefault(qid, dict(
            qt=0, ws=0, sum_st=0, sum_st2=0, n=0, newest=0))

    def _delay(self, qid, t, proc):
        s = self._queue(qid)
        qp = self.p.queue
        if qp.kind in ("history_list", "history_tree"):
            if qp.analytical_enabled and (t + proc) < s["ws"]:
                # M/G/1 fallback from running moments (mirrors
                # queue_models._mg1_wait)
                import math

                if s["n"] == 0:
                    return 0, True
                mean = s["sum_st"] / s["n"]
                var = s["sum_st2"] / s["n"] - mean * mean
                mu = 1.0 / max(mean, 1e-12)
                lam = min(s["n"] / max(s["newest"], 1e-12), 0.999 * mu)
                w = 0.5 * mu * lam * (1.0 / (mu * mu) + var) / (mu - lam)
                return int(math.ceil(w)), True
            return max(s["qt"] - t, 0), False
        return max(s["qt"] - t, 0), False

    def _commit(self, qid, t, delay, proc):
        s = self._queue(qid)
        qp = self.p.queue
        in_window = True
        if qp.kind in ("history_list", "history_tree"):
            in_window = not (qp.analytical_enabled
                             and (t + proc) < s["ws"])
        if in_window:
            s["qt"] = max(s["qt"], t) + proc
            s["ws"] = max(s["ws"], s["qt"] - qp.history_span)
        s["sum_st"] += proc
        s["sum_st2"] += proc * proc
        s["n"] += 1
        s["newest"] = max(s["newest"], t + delay + proc)

    def route(self, src, dst, payload_bytes, t_send_ps, enabled):
        """Returns the arrival time in ps (absolute)."""
        return self.route_bits(
            src, dst, (HEADER_BYTES + payload_bytes) * 8, t_send_ps,
            enabled)

    def route_bits(self, src, dst, bits, t_send_ps, enabled):
        """Route a packet of `bits` modeled length (no NetPacket header —
        the MEMORY net's ShmemMsg lengths are carried raw)."""
        from graphite_tpu.models.network_hop_by_hop import (
            NUM_PORTS, PORT_DOWN, PORT_INJECT, PORT_LEFT, PORT_RIGHT,
            PORT_SELF, PORT_UP,
        )

        p = self.p
        if not enabled:
            return t_send_ps
        flits = max(_ceil_div(bits, p.flit_width_bits), 1)
        # Time::toCycles is ceil (`time_types.h:104-109`)
        t = _ceil_div(t_send_ps * p.freq_mhz, 10**6)

        def hop_delay(qid, t):
            if not p.contention_enabled:
                return 0
            d, _ = self._delay(qid, t, flits)
            self._commit(qid, t, d, flits)
            return d

        # injection
        t = t + p.router_delay + hop_delay(
            src * NUM_PORTS + PORT_INJECT, t)
        # XY route, scalar arithmetic (independent of the engine's helper)
        w = p.mesh_width
        cx, cy = src % w, src // w
        tx, ty = dst % w, dst // w
        while True:
            if cx < tx:
                port, cx = PORT_RIGHT, cx + 1
            elif cx > tx:
                port, cx = PORT_LEFT, cx - 1
            elif cy < ty:
                port, cy = PORT_UP, cy + 1
            elif cy > ty:
                port, cy = PORT_DOWN, cy - 1
            else:
                port = PORT_SELF
            # the queue consulted is the port at the tile BEFORE moving
            at = ((cy if port in (PORT_SELF, PORT_RIGHT, PORT_LEFT)
                   else cy - (1 if port == PORT_UP else -1)) * w
                  + (cx if port in (PORT_SELF, PORT_UP, PORT_DOWN)
                     else cx - (1 if port == PORT_RIGHT else -1)))
            t = t + p.router_delay + p.link_delay + hop_delay(
                at * NUM_PORTS + port, t)
            if port == PORT_SELF:
                break
        if src != dst:
            t += flits
        return cycles_to_ps(int(t), p.freq_mhz)

    def fanout(self, src, targets, bits, t0_ps, enabled, n_copies=None,
               ranks=None, copy_set=None):
        """A home's multicast, mirroring the ENGINE's shared fan-out
        approximation (`memory/engine.py mem_net_fanout`): ONE inject-port
        charge of n_copies*flits, rank-of-target serialization (by tile
        id), then each copy's zero-load path — intermediate-hop queues are
        neither read nor committed for fan-out copies.  This is the one
        piece of the memory NoC the oracle shares with the engine by
        construction instead of independently (documented there); all
        unicast flows remain independently per-hop modeled.  Returns
        {target: arrival_ps}."""
        from graphite_tpu.models.network_hop_by_hop import (
            NUM_PORTS, PORT_INJECT,
        )

        p = self.p
        targets = sorted(targets)
        if not enabled or not targets:
            return {s: t0_ps for s in targets}
        flits = max(_ceil_div(bits, p.flit_width_bits), 1)
        k = n_copies if n_copies is not None else len(targets)
        t0 = _ceil_div(t0_ps * p.freq_mhz, 10**6)
        inj = 0
        if p.contention_enabled:
            qid = src * NUM_PORTS + PORT_INJECT
            inj, _ = self._delay(qid, t0, k * flits)
            self._commit(qid, t0, inj, k * flits)
        w = p.mesh_width
        step = p.router_delay + p.link_delay
        out = {}
        for i, s in enumerate(targets):
            rank = ranks[s] if ranks is not None else i
            hops = abs(src % w - s % w) + abs(src // w - s // w)
            zl = p.router_delay + (hops + 1) * step + (
                0 if s == src else flits)
            out[s] = t0_ps + cycles_to_ps(
                int(zl + inj + rank * flits), p.freq_mhz)
        return out


class _AtacNet(_HbhNet):
    """Serial ATAC optical-NoC oracle (`network_model_atac.cc:337-368`):
    one packet at a time over per-hub queue dicts — the independent
    counterpart of `models/network_atac.route_atac`.  Intra-cluster (or
    short-distance under distance_based routing) unicasts ride the ENet
    at hop cost; everything else pays ENet-to-hub, send-hub queue +
    router, the optical link (waveguide + E-O/O-E), receive-hub queue +
    router, and the receive net, plus receiver serialization."""

    # (route() is inherited: _HbhNet already wraps route_bits with the
    # NetPacket header)

    def _cluster(self, t):
        p = self.p
        x, y = t % p.mesh_width, t // p.mesh_width
        cpr = p.mesh_width // p.cluster_width
        return (y // p.cluster_height) * cpr + (x // p.cluster_width)

    def _hub(self, c):
        p = self.p
        cpr = p.mesh_width // p.cluster_width
        return ((c // cpr) * p.cluster_height * p.mesh_width
                + (c % cpr) * p.cluster_width)

    def _hops(self, a, b):
        w = self.p.mesh_width
        return abs(a % w - b % w) + abs(a // w - b // w)

    def _use_onet(self, src, dst):
        p = self.p
        same = self._cluster(src) == self._cluster(dst)
        if p.global_routing_strategy == "distance_based":
            return not (same
                        or self._hops(src, dst)
                        <= p.unicast_distance_threshold)
        return not same

    def route_bits(self, src, dst, bits, t_send_ps, enabled):
        """Route a packet of `bits` modeled length (raw ShmemMsg lengths
        on the MEMORY net, NetPacket-headered on the USER net)."""
        p = self.p  # AtacParams
        if not enabled:
            return t_send_ps
        flits = max(_ceil_div(bits, p.flit_width_bits), 1)

        def cyc_ps(n):
            return _ceil_div(int(n) * 10**6, p.freq_mhz)

        ser_ps = 0 if src == dst else cyc_ps(flits)
        csrc, cdst = self._cluster(src), self._cluster(dst)
        if not self._use_onet(src, dst):
            return (t_send_ps
                    + cyc_ps(self._hops(src, dst) * p.enet_hop_cycles)
                    + ser_ps)

        sendhub_arrive = t_send_ps + cyc_ps(
            self._hops(src, self._hub(csrc)) * p.enet_hop_cycles)
        if p.contention_enabled:
            t_cyc = _ceil_div(sendhub_arrive * p.freq_mhz, 10**6)
            d, _ = self._delay(csrc, t_cyc, flits)
            self._commit(csrc, t_cyc, d, flits)
        else:
            d = 0
        sendhub_done = sendhub_arrive + cyc_ps(d + p.send_hub_cycles)
        recvhub_arrive = sendhub_done + p.optical_link_ps
        if p.contention_enabled:
            t_cyc = _ceil_div(recvhub_arrive * p.freq_mhz, 10**6)
            d2, _ = self._delay(p.n_clusters + cdst, t_cyc, flits)
            self._commit(p.n_clusters + cdst, t_cyc, d2, flits)
        else:
            d2 = 0
        recvhub_done = recvhub_arrive + cyc_ps(d2 + p.receive_hub_cycles)
        return (recvhub_done
                + cyc_ps(p.receive_net_levels * p.receive_net_cycles)
                + ser_ps)

    def _zeroload_ps(self, src, dst, bits):
        """Contention-free path cost (engine's atac_zeroload_ps mirror)."""
        p = self.p
        flits = max(_ceil_div(bits, p.flit_width_bits), 1)

        def cyc_ps(n):
            return _ceil_div(int(n) * 10**6, p.freq_mhz)

        ser = 0 if src == dst else cyc_ps(flits)
        if not self._use_onet(src, dst):
            return (cyc_ps(self._hops(src, dst) * p.enet_hop_cycles)
                    + ser, False)
        onet = (cyc_ps(self._hops(src, self._hub(self._cluster(src)))
                       * p.enet_hop_cycles)
                + cyc_ps(p.send_hub_cycles) + p.optical_link_ps
                + cyc_ps(p.receive_hub_cycles)
                + cyc_ps(p.receive_net_levels * p.receive_net_cycles))
        return onet + ser, True

    def fanout(self, src, targets, bits, t0_ps, enabled, n_copies=None,
               ranks=None, copy_set=None):
        """A home's multicast, mirroring the ENGINE's ATAC fan-out
        (`memory/engine.py mem_net_fanout` atac leg): ONE send-hub charge
        of k_onet*flits (delay applied to ONet copies), rank-of-target
        serialization (by tile id) for every copy, then each copy's
        zero-load path.  Returns {target: arrival_ps}."""
        p = self.p
        targets = sorted(targets)
        if not enabled or not targets:
            return {s: t0_ps for s in targets}
        flits = max(_ceil_div(bits, p.flit_width_bits), 1)
        zl = {s: self._zeroload_ps(src, s, bits) for s in targets}
        # the hub charge counts every ONet COPY — broadcast sweeps pass
        # the full copy set (engine: (send_hs & onet_pair).sum())
        copies = copy_set if copy_set is not None else targets
        k_onet = sum(1 for s in copies if self._use_onet(src, s))
        inj = 0
        if p.contention_enabled and k_onet > 0:
            t_cyc = _ceil_div(t0_ps * p.freq_mhz, 10**6)
            inj, _ = self._delay(self._cluster(src), t_cyc, k_onet * flits)
            self._commit(self._cluster(src), t_cyc, inj, k_onet * flits)

        def cyc_ps(n):
            return _ceil_div(int(n) * 10**6, p.freq_mhz)

        out = {}
        for i, s in enumerate(targets):
            rank = ranks[s] if ranks is not None else i
            lat, onet = zl[s]
            # ONE cycles->ps conversion for the combined extra cycles —
            # the engine converts the sum (rank*flits + hub delay) once,
            # and split ceil conversions diverge at frequencies that do
            # not divide 10^6
            out[s] = t0_ps + lat + cyc_ps(
                rank * flits + (inj if onet else 0))
        return out


class _Tile:
    __slots__ = ("tid", "clock", "idx", "done", "blocked", "counts")

    def __init__(self, tid):
        self.tid = tid
        self.clock = 0
        self.idx = 0
        self.done = False
        self.blocked = None  # None | ("recv", src) | ("barrier", b)
        #                       | ("mutex", m) | ("join", t) | ("cond", c, m)
        self.counts = dict(instr=0, recv=0, sync=0, bp_ok=0, bp_bad=0)


def run_golden(sim_config, batch: TraceBatch,
               syscall_rt_ps: int = 2000) -> GoldenResult:
    cfg = sim_config.cfg
    T = batch.n_tiles
    # per-tile core frequency comes from the CORE DVFS domain, exactly as
    # the simulator initializes it (`simulator.py` core_freq)
    from graphite_tpu.models.dvfs import DvfsParams, module_freq_mhz

    freq_mhz = int(module_freq_mhz(cfg, "CORE"))
    # per-tile V/f state for in-trace DVFS_SET (mirrors the engine's
    # legacy per-tile table: AUTO picks the minimum voltage for the
    # frequency, HOLD keeps the current voltage and fails above its
    # maximum, invalid requests count and leave state unchanged; the
    # retune itself is zero-cost).  Core instruction costs read the
    # issuing tile's CORE-domain frequency.
    dvp = DvfsParams.from_config(cfg)
    dvfs_freq = [[int(f) for f in dvp.domain_freq_mhz] for _ in range(T)]
    dvfs_volt = [[int(dvp.min_voltage_mv(int(f)))
                  for f in dvp.domain_freq_mhz] for _ in range(T)]
    dvfs_errors = [0] * T
    core_freq = [freq_mhz] * T

    # static cost table
    from graphite_tpu.trace.schema import STATIC_COST_KEYS

    costs = [cfg.get_int(f"core/static_instruction_costs/{k}", 0)
             for k in STATIC_COST_KEYS]

    net_kind = cfg.get_string("network/user", "magic")
    if net_kind == "magic":
        net = _Net("magic", 1000, 0, 0, -1)
    elif net_kind == "emesh_hop_by_hop":
        from graphite_tpu.models.network_hop_by_hop import HopByHopParams

        net = _HbhNet(HopByHopParams.from_config(sim_config, "user"))
    elif net_kind == "atac":
        from graphite_tpu.models.network_atac import AtacParams

        net = _AtacNet(AtacParams.from_config(sim_config, "user"))
    else:
        from graphite_tpu.models.network_user import mesh_dims

        w, _ = mesh_dims(T)
        router = cfg.get_int(f"network/{net_kind}/router/delay", 1)
        link = cfg.get_int(f"network/{net_kind}/link/delay", 1)
        flit = cfg.get_int(f"network/{net_kind}/flit_width", 64)
        net = _Net("emesh", 1000, w, router + link, flit)

    bp_size = cfg.get_int("branch_predictor/size", 1024)
    bp_penalty = cfg.get_int("branch_predictor/mispredict_penalty", 14)
    bp_bits = np.zeros((T, bp_size), np.uint8)

    # memory hierarchy (same gating as the engine, `simulator.py`):
    # enable_shared_mem AND the trace actually touches memory
    from graphite_tpu.trace.schema import FLAG_MEM0_VALID, FLAG_MEM1_VALID

    has_mem = bool(
        np.any(batch.flags & (FLAG_MEM0_VALID | FLAG_MEM1_VALID))
    ) or cfg.get_bool("general/enable_icache_modeling", False)
    # scope guard: the golden core model is the simple 1-IPC in-order
    # pipeline; iocoom tiles overlap memory latencies in the scoreboard
    # (`iocoom_core_model.cc:120-280`) which this oracle does not model
    for tt in range(T):
        ct = sim_config.tile_spec(tt).core_type
        if ct not in ("simple", "magic"):
            raise NotImplementedError(
                f"golden oracle models the simple core only; tile {tt} "
                f"is {ct!r}")
    mem = None
    if sim_config.enable_shared_mem and has_mem:
        from graphite_tpu.memory.params import MemParams

        mp = MemParams.from_config(sim_config)
        if mp.protocol.startswith("pr_l1_sh_l2"):
            from graphite_tpu.golden.memory_model_shl2 import GoldenShL2

            mem = GoldenShL2(mp, module_freq_mhz(cfg, "CORE"))
        else:
            from graphite_tpu.golden.memory_model import GoldenMemory

            mem = GoldenMemory(mp, module_freq_mhz(cfg, "CORE"))

    tiles = [_Tile(t) for t in range(T)]
    enabled = [True]  # models toggle is GLOBAL (PerformanceCounterManager)
    # messages: (src,dst) -> FIFO of (arrival_ps,)
    channels: dict[tuple, list] = {}
    barriers: dict[int, dict] = {}   # id -> {count, arrived:[(clock,tile)]}
    mutexes: dict[int, dict] = {}    # id -> {locked, handoff, waiters}
    conds: dict[int, list] = {}      # id -> [(arrival, tile, mutex_id)]
    exit_clock: dict[int, int] = {}
    # split-form rendezvous state (BARRIER_ARRIVE/SYNC, COND_JOIN),
    # generation-exact (the engine keeps a GEN_RING-deep ring; identical
    # while rendezvous lag <= GEN_RING, the documented bound)
    bar_gen: dict[int, int] = {}      # id -> releases so far
    bar_release: dict[tuple, int] = {}  # (id, gen) -> release time
    sig_seq: dict[int, int] = {}      # cond id -> published signals so far
    sig_time: dict[tuple, int] = {}   # (cond id, seq) -> publish time

    def runnable(t: _Tile) -> bool:
        if t.done or t.blocked is not None:
            return False
        return t.idx < batch.length

    def rec(t, field):
        return int(getattr(batch, field)[t.tid, t.idx])

    def grant_mutex(m: int):
        """Hand the mutex to the waiter with the smallest (eff_clock, tile)
        key, at the unlock handoff time (`SimMutex`)."""
        mx = mutexes.setdefault(m, dict(locked=False, handoff=0, waiters=[]))
        if mx["locked"] or not mx["waiters"]:
            return
        mx["waiters"].sort()
        eff_clock, wtid, wake = mx["waiters"].pop(0)
        mx["locked"] = True
        t = tiles[wtid]
        new_clock = max(eff_clock, mx["handoff"], wake)
        if new_clock > t.clock and enabled[0]:
            t.counts["sync"] += 1
        t.clock = new_clock
        t.blocked = None

    def try_unblock(t: _Tile):
        """Re-check a parked tile's wake condition."""
        kind = t.blocked[0]
        if kind == "recv":
            src = t.blocked[1]
            if src == ANY_SENDER:
                cand = [(q[0], s) for (s, d), q in channels.items()
                        if d == t.tid and q]
                if not cand:
                    return
                arrival, src = min(cand)
            else:
                q = channels.get((src, t.tid))
                if not q:
                    return
                arrival = q[0]
            channels[(src, t.tid)].pop(0)
            if arrival > t.clock:
                if enabled[0]:
                    t.counts["recv"] += 1
                t.clock = arrival
            t.blocked = None
            t.idx += 1
        elif kind == "join":
            target = t.blocked[1]
            if target in exit_clock:
                t.clock = max(t.clock, exit_clock[target])
                t.blocked = None
                t.idx += 1
        elif kind == "bsync":
            b, gen = t.blocked[1], t.blocked[2]
            if bar_gen.get(b, 0) >= gen:
                rel = bar_release.get((b, gen), 0)
                if rel > t.clock and enabled[0]:
                    t.counts["sync"] += 1
                t.clock = max(t.clock, rel)
                t.blocked = None
                t.idx += 1
        elif kind == "cjoin":
            c, k = t.blocked[1], t.blocked[2]
            if sig_seq.get(c, 0) >= k:
                st = sig_time.get((c, k), 0)
                if st > t.clock and enabled[0]:
                    t.counts["sync"] += 1
                t.clock = max(t.clock, st)
                t.blocked = None
                t.idx += 1

    def step(t: _Tile):
        op = rec(t, "op")
        aux0, aux1 = rec(t, "aux0"), rec(t, "aux1")
        advance = True
        if op == Op.THREAD_EXIT or op == Op.NOP:
            t.done = True
            exit_clock[t.tid] = t.clock
            for other in tiles:
                if other.blocked and other.blocked[0] == "join" \
                        and other.blocked[1] == t.tid:
                    try_unblock(other)
            return
        def mem_acc():
            """Memory latency of this record's slots (0 without a model);
            data slots mutate cache/directory state even when models are
            disabled (the icache slot exists only while enabled)."""
            if mem is None:
                return 0
            return mem.access_record(
                t.tid, op, rec(t, "flags"), rec(t, "pc"),
                rec(t, "addr0"), rec(t, "addr1"), t.clock, enabled[0])

        if op < Op.DYNAMIC_MISC and op != Op.BRANCH:   # static instr
            acc = mem_acc()
            if enabled[0]:
                t.clock += cycles_to_ps(costs[op], core_freq[t.tid]) + acc
                t.counts["instr"] += 1
        elif op == Op.BRANCH:
            pc = rec(t, "pc") % bp_size
            taken = 1 if (rec(t, "flags") & FLAG_BRANCH_TAKEN) else 0
            ok = bp_bits[t.tid, pc] == taken
            bp_bits[t.tid, pc] = taken
            cycles = 1 if ok else bp_penalty
            acc = mem_acc()
            if enabled[0]:
                t.clock += cycles_to_ps(cycles, core_freq[t.tid]) + acc
                t.counts["instr"] += 1
                t.counts["bp_ok" if ok else "bp_bad"] += 1
        elif op < 20:                                   # dynamic
            dyn = int(batch.dyn_ps[t.tid, t.idx])
            if op == Op.SPAWN:
                t.clock = max(t.clock, dyn)
            else:
                if enabled[0]:
                    t.clock += dyn
                    t.counts["instr"] += 1
        elif op == Op.BBLOCK:
            acc = mem_acc()
            if enabled[0]:
                t.clock += cycles_to_ps(aux1, core_freq[t.tid]) + acc
                t.counts["instr"] += aux0
        elif op == Op.SEND:
            if isinstance(net, _HbhNet):
                arrival = net.route(t.tid, aux0, aux1, t.clock, enabled[0])
            else:
                arrival = t.clock + net.latency_ps(
                    t.tid, aux0, aux1, enabled[0])
            channels.setdefault((t.tid, aux0), []).append(arrival)
            for other in tiles:
                if other.blocked and other.blocked[0] == "recv":
                    try_unblock(other)
        elif op == Op.NET_RECV:
            t.blocked = ("recv", aux0)
            try_unblock(t)
            return  # try_unblock advances idx on success
        elif op == Op.BARRIER_INIT:
            b = barriers.setdefault(aux0, dict(count=0, arrived=[]))
            b["count"] = aux1  # re-arm the count; arrivals stay
        elif op in (Op.BARRIER_WAIT, Op.BARRIER_ARRIVE):
            blocking = op == Op.BARRIER_WAIT
            b = barriers[aux0]
            # arrival time captured NOW (ARRIVE lanes keep running)
            b["arrived"].append((t.clock, t.tid, blocking))
            if blocking:
                t.blocked = ("barrier", aux0)
            t.idx += 1  # the record commits at release time
            if len(b["arrived"]) >= b["count"]:
                release = max(c for c, _, _ in b["arrived"])
                for (c, x, was_blocking) in b["arrived"]:
                    if not was_blocking:
                        continue
                    tx = tiles[x]
                    if release > tx.clock and enabled[0]:
                        tx.counts["sync"] += 1
                    tx.clock = max(tx.clock, release)
                    tx.blocked = None
                b["arrived"] = []
                g = bar_gen.get(aux0, 0) + 1
                bar_gen[aux0] = g
                bar_release[(aux0, g)] = release
            return
        elif op == Op.BARRIER_SYNC:
            t.blocked = ("bsync", aux0, aux1)
            try_unblock(t)
            return
        elif op == Op.COND_JOIN:
            t.blocked = ("cjoin", aux0, aux1)
            try_unblock(t)
            return
        elif op == Op.MUTEX_INIT:
            mutexes[aux0] = dict(locked=False, handoff=0, waiters=[])
        elif op == Op.MUTEX_LOCK:
            mutexes.setdefault(
                aux0, dict(locked=False, handoff=0, waiters=[]))
            mutexes[aux0]["waiters"].append((t.clock, t.tid, 0))
            t.blocked = ("mutex", aux0)
            t.idx += 1
            grant_mutex(aux0)
            return
        elif op == Op.MUTEX_UNLOCK:
            mx = mutexes[aux0]
            mx["locked"] = False
            mx["handoff"] = t.clock
            grant_mutex(aux0)
        elif op == Op.COND_INIT:
            conds[aux0] = []
        elif op == Op.COND_WAIT:
            # release the mutex, park on the cond
            mx = mutexes[aux1]
            mx["locked"] = False
            mx["handoff"] = t.clock
            conds.setdefault(aux0, []).append((t.clock, t.tid, aux1))
            t.blocked = ("cond", aux0, aux1)
            t.idx += 1
            grant_mutex(aux1)
            return
        elif op in (Op.COND_SIGNAL, Op.COND_BROADCAST) and aux1 > 0:
            # published form (live frontend): bump the sequence + stamp
            k = sig_seq.get(aux0, 0) + 1
            sig_seq[aux0] = k
            sig_time[(aux0, k)] = t.clock
        elif op in (Op.COND_SIGNAL, Op.COND_BROADCAST):
            S = t.clock
            waiters = conds.setdefault(aux0, [])
            elig = sorted(w for w in waiters if w[0] <= S)
            wake = elig if op == Op.COND_BROADCAST else elig[:1]
            for (arr, wtid, m) in wake:
                waiters.remove((arr, wtid, m))
                # woken waiter re-acquires its mutex; its grant key is its
                # effective clock max(clock, wake time S)
                mutexes[m]["waiters"].append(
                    (max(tiles[wtid].clock, S), wtid, S))
                tiles[wtid].blocked = ("mutex", m)
                grant_mutex(m)
            # no eligible waiter: the signal is lost
        elif op == Op.THREAD_SPAWN:
            pass  # functionally nothing: streams are pre-laid-out
        elif op == Op.THREAD_JOIN:
            t.blocked = ("join", aux0)
            try_unblock(t)
            return
        elif op == Op.ENABLE_MODELS:
            enabled[0] = True
        elif op == Op.DISABLE_MODELS:
            enabled[0] = False
        elif op in (Op.SYSCALL, Op.DVFS_GET):
            if enabled[0]:
                t.clock += syscall_rt_ps
        elif op == Op.DVFS_SET:
            # zero-cost retune; mirrors the engine's `_dvfs_block`
            # validation exactly (legacy per-tile table).  aux1 < 0 is
            # the HOLD encoding: keep the current voltage, the request
            # must fit under its max frequency.  AUTO picks the minimum
            # voltage for the frequency.  An invalid domain or an
            # unachievable frequency counts one error, state untouched.
            req = abs(aux1)
            dom = min(max(aux0, 0), dvp.n_domains - 1)
            valid_dom = 0 <= aux0 < dvp.n_domains
            auto_mv = dvp.min_voltage_mv(req) if req > 0 else -1
            if aux1 < 0:  # HOLD: current voltage caps the frequency
                cap = dvp.max_freq_at_mv(dvfs_volt[t.tid][dom])
                ok = valid_dom and auto_mv >= 0 and req <= cap
                new_mv = dvfs_volt[t.tid][dom]
            else:
                ok = valid_dom and auto_mv >= 0
                new_mv = auto_mv
            if ok:
                dvfs_freq[t.tid][dom] = req
                dvfs_volt[t.tid][dom] = new_mv
                if dom == dvp.core_domain:
                    core_freq[t.tid] = req
            else:
                dvfs_errors[t.tid] += 1
        else:
            raise NotImplementedError(f"golden: op {op}")
        if advance:
            t.idx += 1

    # main loop: smallest-clock runnable tile first
    while True:
        # state-conditioned rendezvous kinds wake lazily here
        for t in tiles:
            if t.blocked and t.blocked[0] in ("bsync", "cjoin"):
                try_unblock(t)
        run = [t for t in tiles if runnable(t)]
        if not run:
            # every tile done, or deadlock (mirrors the engine's detector)
            if all(t.done or t.idx >= batch.length for t in tiles):
                break
            stuck = [t.tid for t in tiles if not t.done]
            raise RuntimeError(f"golden: deadlock, blocked tiles {stuck}")
        t = min(run, key=lambda x: (x.clock, x.tid))
        step(t)

    return GoldenResult(
        clock_ps=np.asarray([t.clock for t in tiles], np.int64),
        instruction_count=np.asarray(
            [t.counts["instr"] for t in tiles], np.int64),
        recv_instructions=np.asarray(
            [t.counts["recv"] for t in tiles], np.int64),
        sync_instructions=np.asarray(
            [t.counts["sync"] for t in tiles], np.int64),
        bp_correct=np.asarray([t.counts["bp_ok"] for t in tiles], np.int64),
        bp_incorrect=np.asarray(
            [t.counts["bp_bad"] for t in tiles], np.int64),
        mem_counters=(
            {k: np.asarray(v, np.int64) for k, v in mem.counters.items()}
            if mem is not None else None),
        dvfs_errors=np.asarray(dvfs_errors, np.int64),
        core_freq_mhz=np.asarray(core_freq, np.int64),
    )
