"""Sequential golden model of the shared-L2 protocols (pr_l1_sh_l2_*).

Independent second implementation of `memory/engine_shl2.py` for
differential testing — one access at a time over plain Python data
structures, deliberately sharing no logic with the vectorized engine
(only `MemParams`, the config-derived constants, and the reusable serial
cache/net fixtures from the sibling oracles).

Semantics modeled (reference: `pr_l1_sh_l2_{msi,mesi}/`):
 - private L1s over a DISTRIBUTED shared L2: the slice at a line's home
   tile (line % T, `l2_cache_hash_fn.cc`) holds data + an embedded
   directory entry over the L1 copies (`l2_cache_cntlr.h:27-67`);
 - L1 miss -> EX/SH_REQ to the home (`l1_cache_cntlr.cc:81-160`); the
   home serves it from the slice, running the directory FSM over the L1
   sharers (`l2_cache_cntlr.cc:443-700`), or allocates DATA_INVALID and
   fetches from DRAM (`:541-560,900-915`);
 - MESI grants EXCLUSIVE on a read of a line with no other L1 copies
   (`pr_l1_sh_l2_mesi/l2_cache_cntlr.cc:660-680`) and promotes E->M
   silently on a write hit;
 - slice-victim replacement: a victim with live L1 copies runs NULLIFY
   (INV/FLUSH sweep) before the original request resumes; a clean
   UNCACHED victim dies silently (dirty -> DRAM write);
 - engine-mirrored simplifications (documented there): upgrade replies
   modeled as EX_REP, one transaction per home, the DRAM fetch is a
   timing round trip to the line's DRAM home with zero-load net legs.

Ordering discipline matches the private-L2 oracle: accesses are
processed synchronously in core-clock order; differential tests assert
bit-exactness on serialized/disjoint workloads and envelopes on racy
ones (BASELINE.md carve-outs).
"""

from __future__ import annotations

from graphite_tpu.golden.memory_model import (
    EXCLUSIVE, INVALID, MODIFIED, SHARED,
    _Cache, _ceil_div, _cycles_to_ps, _readable, _writable,
)
from graphite_tpu.memory.params import MemParams
from graphite_tpu.memory.state import (
    MOD_CORE, MOD_L1D, MOD_L1I, MOD_L2, MOD_NET_MEM,
)
from graphite_tpu.trace.schema import (
    FLAG_MEM0_VALID, FLAG_MEM0_WRITE, FLAG_MEM1_VALID, FLAG_MEM1_WRITE, Op,
)

DIR_UNCACHED, DIR_SHARED, DIR_MODIFIED = 0, 1, 2
DIR_EXCLUSIVE = 4
DATA_INVALID = 5  # slice data still in flight from DRAM


class _SliceEntry:
    """Embedded directory entry of one L2-slice line."""

    __slots__ = ("dstate", "owner", "sharers")

    def __init__(self):
        self.dstate = DIR_UNCACHED
        self.owner = -1
        self.sharers: set[int] = set()


class GoldenShL2:
    """Drop-in for GoldenMemory (same access_record interface) modeling
    the shared-L2 protocols."""

    def __init__(self, mp: MemParams, freq_mhz):
        self.mp = mp
        self.mesi = mp.protocol.endswith("mesi")
        T = mp.n_tiles
        self.freq = [int(f) for f in freq_mhz] if hasattr(
            freq_mhz, "__len__") else [int(freq_mhz)] * T

        def geom(lp, t):
            s = lp.tile_sets[t] if lp.tile_sets is not None else lp.num_sets
            w = lp.tile_ways[t] if lp.tile_ways is not None else lp.num_ways
            return s, w

        self.l1i = [_Cache(*geom(mp.l1i, t), mp.l1i.replacement)
                    for t in range(T)]
        self.l1d = [_Cache(*geom(mp.l1d, t), mp.l1d.replacement)
                    for t in range(T)]
        self.l2 = [_Cache(*geom(mp.l2, t), mp.l2.replacement)
                   for t in range(T)]
        # embedded directory per slice: (set, way) -> _SliceEntry
        self.dir: list[dict] = [dict() for _ in range(T)]
        self.last_line = [-1] * T      # per-home same-line floor
        self.last_done = [0] * T
        self.instr_buf = [-1] * T
        if mp.net_hbh is not None:
            from graphite_tpu.golden.interpreter import _HbhNet

            self.net = _HbhNet(mp.net_hbh)
        elif mp.net_atac is not None:
            # coherence messages over the ATAC optical NoC
            from graphite_tpu.golden.interpreter import _AtacNet

            self.net = _AtacNet(mp.net_atac)
        else:
            self.net = None
        self.counters = {
            k: [0] * T
            for k in ("l1i_hits", "l1i_misses", "l1d_read_hits",
                      "l1d_read_misses", "l1d_write_hits",
                      "l1d_write_misses", "l2_hits", "l2_misses",
                      "evictions", "invalidations", "dir_accesses",
                      "dir_broadcasts", "dram_reads", "dram_writes",
                      "dram_total_lat_ps", "l2_cold_misses",
                      "l2_capacity_misses", "l2_sharing_misses")
        }
        # optional protocol-event observer (analysis/protocol.py model
        # checker); None in normal runs — zero semantic effect
        self.event_cb = None

    def _emit(self, etype: str, **kw) -> None:
        if self.event_cb is not None:
            self.event_cb(etype, kw)

    # -- timing helpers ----------------------------------------------------

    def _cc(self, t, n, enabled):
        if hasattr(n, "__len__"):
            n = int(n[t])
        return _cycles_to_ps(int(n), self.freq[t]) if enabled else 0

    def _sync(self, t, a, b, enabled):
        return self._cc(t, self.mp.sync_cycles(a, b), enabled)

    def _net_zero_ps(self, src, dst, bits, enabled):
        mp = self.mp
        if not enabled:
            return 0
        if mp.net_atac is not None:
            # ATAC zero-load path cost (the engine's mem_net_latency_ps
            # atac branch — used by its _dram_lat_ps round trip)
            return self.net._zeroload_ps(src, dst, bits)[0]
        if mp.net_kind == "magic":
            return _cycles_to_ps(1, mp.net_freq_mhz)
        w = mp.mesh_width
        hops = abs(src % w - dst % w) + abs(src // w - dst // w)
        cycles = hops * mp.hop_latency_cycles
        if src != dst:
            cycles += _ceil_div(bits, mp.flit_width_bits)
        return _cycles_to_ps(cycles, mp.net_freq_mhz)

    def _net_arrive(self, src, dst, bits, t_send, enabled):
        if self.net is not None:
            return self.net.route_bits(src, dst, bits, t_send, enabled)
        return t_send + self._net_zero_ps(src, dst, bits, enabled)

    def _net_fanout(self, src, targets, bits, t0, enabled,
                    n_copies=None, ranks=None, copy_set=None):
        if self.net is not None:
            return self.net.fanout(src, targets, bits, t0, enabled,
                                   n_copies, ranks, copy_set)
        return {s: t0 + self._net_zero_ps(src, s, bits, enabled)
                for s in targets}

    def _dram_rt(self, home, enabled):
        """DRAM fetch round trip (engine `_dram_lat_ps`: zero-load net
        legs + access, even under hop_by_hop — documented)."""
        mp = self.mp
        dram_home = mp.mc_tiles[home % len(mp.mc_tiles)]
        net = self._net_zero_ps(home, dram_home, mp.rep_bits, enabled)
        acc = ((mp.dram_latency_ns + mp.dram_processing_ns) * 1000
               if enabled else 0)
        return 2 * net + acc

    def _home_of(self, line):
        return line % self.mp.n_tiles

    def _entry(self, home, line):
        l2 = self.l2[home]
        hit, way, _ = l2.lookup(line)
        if not hit:
            return None, -1
        key = (line % l2.sets, way)
        return self.dir[home].setdefault(key, _SliceEntry()), way

    # -- sharer-side FWD service (`l1_cache_cntlr.cc` handlers) ------------

    def _serve_fwd(self, s, kind, line, ftime, home, enabled):
        """(ack_time, dirty_data_travels)."""
        mp = self.mp
        l1i, l1d = self.l1i[s], self.l1d[s]
        hi, wi, sti = l1i.lookup(line)
        hd, wd, std = l1d.lookup(line)
        assert hi or hd, (
            f"golden shl2: FWD {kind} to tile {s} line {line:#x} not held")
        was_dirty = (hd and std == MODIFIED) or (hi and sti == MODIFIED)
        done = (ftime + self._sync(s, MOD_L1D, MOD_NET_MEM, enabled)
                + self._cc(s, mp.l1d.data_and_tags_cycles, enabled))
        if kind == "wb":
            if hi:
                l1i.set_state(line, wi, SHARED)
            if hd:
                l1d.set_state(line, wd, SHARED)
            ack_dirty = was_dirty
            ack_is_inv = False
        else:  # inv / flush
            if hi:
                l1i.invalidate(line)
            if hd:
                l1d.invalidate(line)
            if kind == "inv" and enabled:
                self.counters["invalidations"][s] += 1
            ack_dirty = kind == "flush" and was_dirty
            # a FLUSH of a clean line carries no data: INV_REP
            ack_is_inv = kind == "inv" or (kind == "flush" and not was_dirty)
        bits = mp.req_bits if ack_is_inv else mp.rep_bits
        self._emit("serve", tile=s, home=home, line=line, kind=kind,
                   supplies=ack_dirty)
        return self._net_arrive(s, home, bits, done, enabled), ack_dirty

    # -- L1 eviction notices at the home -----------------------------------

    def _apply_eviction(self, src, line, is_flush, etime, enabled):
        home = self._home_of(line)
        if enabled:
            self.counters["evictions"][home] += 1
        self._emit("evict", src=src, home=home, line=line, dirty=is_flush)
        entry, way = self._entry(home, line)
        if entry is None:
            return
        entry.sharers.discard(src)
        if src == entry.owner:
            entry.owner = -1
        entry.dstate = DIR_UNCACHED if not entry.sharers else DIR_SHARED
        if is_flush:
            self.l2[home].set_state(line, way, MODIFIED)

    # -- one home transaction ----------------------------------------------

    def _home_txn(self, home, requester, line, is_write, arrival, enabled,
                  _resumed=False):
        """Serve one EX/SH request at the home slice; returns the reply
        arrival time at the requester."""
        mp = self.mp
        l2 = self.l2[home]
        c = self.counters
        l2_acc = self._cc(home, mp.l2.data_and_tags_cycles, enabled)

        rtime = arrival
        if not _resumed:
            rtime += self._sync(home, MOD_L2, MOD_NET_MEM, enabled)
        if line == self.last_line[home]:
            rtime = max(rtime, self.last_done[home])
        if enabled:
            c["dir_accesses"][home] += 1
        self._emit("req", home=home, requester=requester, line=line,
                   mtype="ex" if is_write else "sh")

        hit, way, l2_state = l2.lookup(line)
        if not hit:
            # allocate: victim with live L1 copies runs NULLIFY first
            v_way, v_valid, v_line, v_state = l2.pick_victim(line)
            v_entry = (self.dir[home].get((v_line % l2.sets, v_way))
                       if v_valid else None)
            if v_valid and v_entry is not None and \
                    v_entry.dstate != DIR_UNCACHED:
                self._run_nullify(home, v_line, v_way, v_entry,
                                  rtime, enabled, requester)
                # resume the original request (saved + re-run)
                return self._home_txn(home, requester, line, is_write,
                                      rtime, enabled, _resumed=True)
            if v_valid:
                # clean UNCACHED victim: silent kill (dirty -> DRAM)
                if v_state == MODIFIED and enabled:
                    c["dram_writes"][home] += 1
                self._emit("slice_kill", home=home, line=v_line,
                           dirty=v_state == MODIFIED)
                self.dir[home].pop((v_line % l2.sets, v_way), None)
                l2.invalidate(v_line)
            eff_time = rtime + l2_acc
            l2.insert_at(line, v_way, DATA_INVALID)
            self.dir[home][(line % l2.sets, v_way)] = _SliceEntry()
            if enabled:
                c["l2_misses"][home] += 1
                c["dram_reads"][home] += 1
                c["dram_total_lat_ps"][home] += (
                    (mp.dram_latency_ns + mp.dram_processing_ns) * 1000)
            txn_time = max(eff_time,
                           eff_time + self._dram_rt(home, enabled))
            self._emit("slice_fill", home=home, line=line, source="dram")
            l2.set_state(line, v_way, SHARED)
            entry = self.dir[home][(line % l2.sets, v_way)]
            way, l2_state = v_way, SHARED
            got_flush = False
        else:
            eff_time = rtime + l2_acc
            entry, _ = self._entry(home, line)
            if enabled:
                c["l2_hits"][home] += 1
            txn_time = eff_time
            got_flush = False

            # fan-out per directory state
            targets = {}
            shared = entry.dstate == DIR_SHARED
            owned_like = entry.dstate in (DIR_MODIFIED, DIR_EXCLUSIVE)
            if is_write and shared:
                for s in entry.sharers:
                    if s != requester:  # upgrade keeps the requester copy
                        targets[s] = "inv"
            elif owned_like:
                targets[entry.owner] = "wb" if not is_write else "flush"

            broadcast = False
            k = mp.max_hw_sharers
            if mp.dir_type == "limited_no_broadcast" and not is_write:
                already = requester in entry.sharers
                if shared and len(entry.sharers) >= k and not already:
                    victims = sorted(entry.sharers)
                    victim = victims[0]
                    entry.sharers.discard(victim)
                    targets = {victim: "inv"}
                elif owned_like and len(entry.sharers) >= k \
                        and not already:
                    targets = {entry.owner: "flush"}
                    entry.dstate = DIR_UNCACHED
                    entry.owner = -1
                    entry.sharers = set()
                    owned_like = False
            if mp.dir_type in ("ackwise", "limited_broadcast") \
                    and is_write and shared \
                    and len(entry.sharers) > k:
                broadcast = True
                if enabled:
                    c["dir_broadcasts"][home] += 1
            if mp.dir_type == "limitless":
                already = requester in entry.sharers
                sw = (len(entry.sharers) > k
                      or (not is_write and not already
                          and len(entry.sharers) >= k
                          and (shared or owned_like)))
                if sw:
                    eff_time += (_cycles_to_ps(mp.limitless_trap_cycles,
                                               mp.dir_freq_mhz)
                                 if enabled else 0)
                    txn_time = eff_time

            if targets:
                if broadcast:
                    # the shl2 engine's upgrade sweep row: all tiles
                    # except the requester (its bit was cleared from
                    # pending); ranks ARE positions in that row
                    row = sorted(set(range(mp.n_tiles)) - {requester})
                    order = {s: i for i, s in enumerate(row)}
                    f_arrivals = self._net_fanout(
                        home, list(targets), mp.req_bits, eff_time,
                        enabled, n_copies=len(row),
                        ranks={s: order[s] for s in targets},
                        copy_set=row)
                else:
                    f_arrivals = self._net_fanout(
                        home, list(targets), mp.req_bits, eff_time,
                        enabled)
                for s in sorted(targets):
                    self._emit("fwd", home=home, target=s, line=line,
                               kind=targets[s], broadcast=broadcast)
                for s in sorted(targets):
                    ack_time, dirty = self._serve_fwd(
                        s, targets[s], line, f_arrivals[s], home, enabled)
                    txn_time = max(txn_time, ack_time + l2_acc)
                    got_flush = got_flush or dirty
                    if targets[s] in ("inv", "flush"):
                        entry.sharers.discard(s)
                        if s == entry.owner:
                            entry.owner = -1
                if got_flush:
                    l2.set_state(line, way, MODIFIED)
                if targets and any(v == "wb" for v in targets.values()):
                    entry.dstate = DIR_SHARED

        # finish: directory end state + reply
        if is_write:
            entry.dstate = DIR_MODIFIED
            entry.owner = requester
            entry.sharers = {requester}
            rep = "ex"
        else:
            alone = len(entry.sharers - {requester}) == 0
            if alone and self.mesi:
                entry.dstate = DIR_EXCLUSIVE
                entry.owner = requester
                rep = "excl"
            else:
                entry.dstate = DIR_SHARED
                entry.owner = -1
                rep = "sh"
            entry.sharers.add(requester)
        rep_ready = txn_time + self._sync(home, MOD_L2, MOD_NET_MEM,
                                          enabled)
        self.last_line[home] = line
        self.last_done[home] = rep_ready
        self._emit("reply", home=home, requester=requester, line=line,
                   mtype=rep, source="slice")
        return (self._net_arrive(home, requester, mp.rep_bits, rep_ready,
                                 enabled), rep)

    def _run_nullify(self, home, v_line, v_way, entry, rtime, enabled,
                     requester):
        """Evict a slice victim with live L1 copies: INV the sharers (or
        FLUSH the owner), then the entry dies; dirty data -> DRAM.

        An ackwise/limited_broadcast victim whose sharer count overflows
        the hardware list sweeps as a BROADCAST, exactly like the engine
        (`engine_shl2.py` over_bc includes nullify_live & shared): every
        tile except the saved requester gets a copy — PLUS the requester
        itself when it holds the victim line (it sits in `targets`)."""
        mp = self.mp
        l2 = self.l2[home]
        c = self.counters
        l2_acc = self._cc(home, mp.l2.data_and_tags_cycles, enabled)
        # dir_accesses counts request pops + resumes only (the engine's
        # `starting` — the nullify runs inside the pop's iteration)
        eff_time = rtime + l2_acc
        self._emit("req", home=home, requester=requester, line=v_line,
                   mtype="nullify")
        if entry.dstate in (DIR_MODIFIED, DIR_EXCLUSIVE):
            targets = {entry.owner: "flush"}
        else:
            targets = {s: "inv" for s in entry.sharers}
        txn_time = eff_time
        got_flush = False
        broadcast = (mp.dir_type in ("ackwise", "limited_broadcast")
                     and entry.dstate not in (DIR_MODIFIED, DIR_EXCLUSIVE)
                     and len(entry.sharers) > mp.max_hw_sharers)
        if broadcast:
            if enabled:
                c["dir_broadcasts"][home] += 1
            copy_set = sorted((set(range(mp.n_tiles)) - {requester})
                              | set(targets))
            # copy_set IS the engine's send row — ranks are positions
            order = {s: i for i, s in enumerate(copy_set)}
            f_arrivals = self._net_fanout(
                home, list(targets), mp.req_bits, eff_time, enabled,
                n_copies=len(copy_set),
                ranks={s: order[s] for s in targets}, copy_set=copy_set)
        else:
            f_arrivals = self._net_fanout(home, list(targets), mp.req_bits,
                                          eff_time, enabled)
        for s in sorted(targets):
            self._emit("fwd", home=home, target=s, line=v_line,
                       kind=targets[s], broadcast=broadcast)
        for s in sorted(targets):
            ack_time, dirty = self._serve_fwd(
                s, targets[s], line=v_line, ftime=f_arrivals[s],
                home=home, enabled=enabled)
            txn_time = max(txn_time, ack_time + l2_acc)
            got_flush = got_flush or dirty
        _, _, v_state = l2.lookup(v_line)
        if (v_state == MODIFIED or got_flush) and enabled:
            c["dram_writes"][home] += 1
        self._emit("slice_kill", home=home, line=v_line,
                   dirty=v_state == MODIFIED or got_flush)
        l2.invalidate(v_line)
        self.dir[home].pop((v_line % l2.sets, v_way), None)
        rep_ready = txn_time + self._sync(home, MOD_L2, MOD_NET_MEM,
                                          enabled)
        self.last_line[home] = v_line
        self.last_done[home] = rep_ready

    # -- requester slot ----------------------------------------------------

    def _slot(self, t, is_icache, addr, write, clock_ps, enabled):
        mp = self.mp
        line = addr >> mp.line_bits
        l1 = self.l1i[t] if is_icache else self.l1d[t]
        lp = mp.l1i if is_icache else mp.l1d
        c = self.counters

        if is_icache:
            ibuf_hit = line == self.instr_buf[t]
            self.instr_buf[t] = line
            if ibuf_hit:
                if enabled:
                    c["l1i_hits"][t] += 1
                return self._cc(t, 1, enabled)

        # engine uses sync(CORE, L1D) for both L1s (sync_core_l1)
        sclock = clock_ps + self._sync(t, MOD_CORE, MOD_L1D, enabled)
        l1_dat = self._cc(t, lp.data_and_tags_cycles, enabled)
        l1_tag = self._cc(t, lp.tags_cycles, enabled)

        hit, way, st = l1.lookup(line)
        if hit and (_writable(st) if write else _readable(st)):
            # MESI silent E->M promotion on a write hit
            if write and st == EXCLUSIVE:
                l1.set_state(line, way, MODIFIED)
            l1.touch(line, way)
            if enabled:
                if is_icache:
                    c["l1i_hits"][t] += 1
                elif write:
                    c["l1d_write_hits"][t] += 1
                else:
                    c["l1d_read_hits"][t] += 1
            self._emit("hit", tile=t, line=line, write=write, level="l1",
                       promoted=write and st == EXCLUSIVE)
            return sclock + l1_dat - clock_ps
        if enabled:
            if is_icache:
                c["l1i_misses"][t] += 1
            elif write:
                c["l1d_write_misses"][t] += 1
            else:
                c["l1d_read_misses"][t] += 1

        home = self._home_of(line)
        req_send = sclock + l1_tag + self._sync(t, MOD_L1D, MOD_NET_MEM,
                                                enabled)
        arrival = self._net_arrive(t, home, mp.req_bits, req_send, enabled)
        rep_time, rep = self._home_txn(home, t, line, write, arrival,
                                       enabled)

        # fill: upgrades land in the existing way, misses pick a victim
        new_state = (MODIFIED if rep == "ex"
                     else EXCLUSIVE if rep == "excl" else SHARED)
        fill_ps = (rep_time + self._sync(t, MOD_L1D, MOD_NET_MEM, enabled)
                   + self._cc(t, mp.l1d.data_and_tags_cycles, enabled))
        hit2, way2, _ = l1.lookup(line)
        if hit2:
            l1.insert_at(line, way2, new_state)
        else:
            v_way, v_valid, v_line, v_state = l1.pick_victim(line)
            if v_valid:
                if enabled:
                    c["evictions"][t] += 1
                v_home = self._home_of(v_line)
                e_bits = (mp.rep_bits if v_state == MODIFIED
                          else mp.req_bits)
                e_arr = self._net_arrive(t, v_home, e_bits, fill_ps,
                                         enabled)
                self._apply_eviction(t, v_line, v_state == MODIFIED,
                                     e_arr, enabled)
            l1.insert_at(line, v_way, new_state)
        self._emit("fill", tile=t, line=line, write=write, state=new_state)
        return fill_ps - clock_ps

    # -- record entry (same interface as GoldenMemory) ---------------------

    def access_record(self, t, op, flags, pc, addr0, addr1, clock_ps,
                      enabled):
        mp = self.mp
        acc = 0
        is_instr = op < 15 or op == int(Op.BBLOCK)
        if mp.icache_modeling and enabled and is_instr:
            acc += self._slot(t, True, pc, False, clock_ps, enabled)
        if flags & FLAG_MEM0_VALID:
            acc += self._slot(t, False, addr0,
                              bool(flags & FLAG_MEM0_WRITE), clock_ps,
                              enabled)
        if flags & FLAG_MEM1_VALID:
            acc += self._slot(t, False, addr1,
                              bool(flags & FLAG_MEM1_WRITE), clock_ps,
                              enabled)
        return acc
